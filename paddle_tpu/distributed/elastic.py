"""Elastic membership: TCPStore-lease heartbeats + peer-set watch.

Reference parity: ``ElasticManager``
(python/paddle/distributed/fleet/elastic/manager.py:125) — each node keeps
an etcd lease alive from a heartbeat thread, a watcher maintains the live
host set, and a membership mismatch (node lost / joined) triggers a
coordinated restart; workers resume from their own checkpoints. The
reference downgrades to ``ElasticLevel.FAULT_TOLERANCE`` (fixed world size)
when min_np == max_np — the mode implemented here, the one that matters on
TPU pods where the slice size is fixed.

TPU-native: the lease server is the native TCPStore daemon
(core/csrc/tcp_store.cpp) instead of etcd. Each worker refreshes
``{prefix}/node/{rank}`` with a monotonic-clock timestamp every ttl/3; a
peer is ALIVE while its newest stamp is younger than ttl. Two watchers
cooperate:

- **worker-side** (``monitor()``): a daemon thread that watches the peer
  set and hard-exits this process with ``ELASTIC_EXIT_CODE`` when a peer's
  lease lapses — the survivor's collectives would otherwise block forever
  on the dead rank, so a thread-level ``os._exit`` is the only reliable
  unblocking mechanism (the reference kills trainers from the manager for
  the same reason).
- **launcher-side** (``stale_ranks()``): the launch controller polls
  leases from its own client and restarts the incarnation when a worker
  stops heartbeating WITHOUT exiting (a hung process has no exit code —
  membership, not process state, is the signal).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Set

from .log_utils import get_logger

ELASTIC_EXIT_CODE = 101  # restart-requested (manager.py ELASTIC_EXIT_CODE analog)


class ElasticManager:
    """Lease registry + peer-set watch over a TCPStore endpoint."""

    def __init__(self, store=None, *, endpoint: Optional[str] = None,
                 rank: Optional[int] = None, world_size: Optional[int] = None,
                 ttl: float = 10.0, job_id: str = "default"):
        if store is None:
            from .store import TCPStore

            endpoint = endpoint or os.environ.get("PADDLE_ELASTIC_STORE")
            if endpoint is None:
                raise ValueError(
                    "ElasticManager needs a TCPStore or an endpoint "
                    "(PADDLE_ELASTIC_STORE)")
            host, port = endpoint.rsplit(":", 1)
            store = TCPStore(host, int(port), is_master=False,
                             world_size=world_size or 1)
        self._store = store
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = world_size or int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.ttl = float(ttl)
        self._prefix = f"pd_elastic/{job_id}"
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        self._hb_paused_until = 0.0  # monotonic; heartbeat skips beats

    # ---- lease --------------------------------------------------------------
    def _key(self, rank: int) -> str:
        return f"{self._prefix}/node/{rank}"

    def _beat(self):
        # a RESTARTED rank re-registers with a fresh stamp, so "alive" is
        # lease freshness, not existence. CLOCK_MONOTONIC: comparable across
        # processes on one host (launcher + its workers — the supported
        # topology) and immune to NTP steps/suspend, which under wall time
        # would falsely lapse every lease at once
        self._store.set(self._key(self.rank), repr(time.monotonic()))

    def register(self):
        """Start the lease heartbeat (manager.py:251-289 lease_heartbeat)."""
        if self._hb_thread is not None:
            return self
        self._beat()

        def heartbeat():
            while not self._stop.wait(self.ttl / 3.0):
                if time.monotonic() < self._hb_paused_until:
                    continue  # paused (chaos stall): process alive,
                              # lease deliberately lapsing
                try:
                    self._beat()
                except Exception as e:
                    # transient store hiccup; next beat retries — but a
                    # run of these is a lease about to lapse, so say so
                    get_logger().warning(
                        "elastic heartbeat for rank %s failed (%s: %s); "
                        "retrying next beat", self.rank,
                        type(e).__name__, e)

        self._hb_thread = threading.Thread(
            name="elastic-heartbeat", target=heartbeat, daemon=True)
        self._hb_thread.start()
        return self

    def stop_heartbeat(self):
        """Stop refreshing the lease (the test hook for a simulated hang —
        process alive, membership lapsed)."""
        self._stop.set()

    def pause_heartbeat(self, duration_s: float):
        """Skip lease beats for ``duration_s`` then resume — the
        RECOVERABLE stall (chaos heartbeat-stall injection): the lease
        lapses, peers reap this rank, and the fresh post-pause stamp is
        what lets it rejoin (the pool only readmits on a heartbeat newer
        than the observed death)."""
        self._hb_paused_until = time.monotonic() + float(duration_s)

    def mark_done(self):
        """Deregister on CLEAN exit: peers must not confuse a finished
        rank's silent lease with a hang (manager.py exit(completed=True))."""
        try:
            self._store.set(f"{self._prefix}/done/{self.rank}", b"1")
        except Exception as e:
            # failed deregistration makes this clean exit look like a
            # hang to every peer watcher — the one elastic fault that
            # must never be silent
            get_logger().warning(
                "elastic mark_done for rank %s failed (%s: %s); peers "
                "may treat this exit as a lapsed lease", self.rank,
                type(e).__name__, e)
        self._stop.set()

    def _is_done(self, rank: int) -> bool:
        try:
            self._store.get(f"{self._prefix}/done/{rank}", timeout=0.2)
            return True
        except TimeoutError:
            return False  # no done-marker within the probe window
        except Exception as e:
            # store unreachable is indistinguishable from "not done" for
            # the caller, but not for the operator debugging a restart
            # loop — log at debug (polled every watch interval)
            get_logger().debug("elastic done-probe for rank %s failed "
                               "(%s: %s)", rank, type(e).__name__, e)
            return False

    # ---- membership metadata -------------------------------------------------
    # a lease says a rank is ALIVE; metadata says WHAT it is. The serving
    # pool (serving_cluster) publishes each worker's address/role/handoff
    # channel here so the router discovers workers the same way trainers
    # discover peers — through the store, no side channel.

    def register_metadata(self, info: dict):
        """Publish this rank's JSON metadata next to its lease."""
        import json

        self._store.set(f"{self._prefix}/meta/{self.rank}",
                        json.dumps(info))

    def peer_metadata(self, rank: int) -> Optional[dict]:
        """A peer's published metadata, or None when it never published
        (or published garbage — treated as absent, like a garbled lease
        stamp)."""
        import json

        try:
            raw = self._store.get(f"{self._prefix}/meta/{rank}",
                                  timeout=0.2)
            return json.loads(raw)
        except (TimeoutError, ValueError):
            return None
        except Exception as e:
            get_logger().debug("elastic metadata probe for rank %s failed "
                               "(%s: %s)", rank, type(e).__name__, e)
            return None

    def lease_age(self, rank: Optional[int] = None) -> Optional[float]:
        """Seconds since ``rank``'s (default: this rank's) newest
        heartbeat stamp; None when it never registered. An age past
        ``ttl`` is a lapsed lease — the /health surface exposes this so
        a load balancer sees staleness before the pool reacts."""
        st = self._stamp(self.rank if rank is None else rank)
        if st is None:
            return None
        return max(0.0, time.monotonic() - st)

    # ---- peer view ----------------------------------------------------------
    def _stamp(self, rank: int) -> Optional[float]:
        try:
            return float(self._store.get(self._key(rank), timeout=0.2))
        except (TimeoutError, ValueError):
            return None  # never registered / garbled stamp: not alive
        except Exception as e:
            get_logger().debug("elastic lease probe for rank %s failed "
                               "(%s: %s)", rank, type(e).__name__, e)
            return None

    def alive_ranks(self) -> Set[int]:
        now = time.monotonic()
        out = set()
        for r in range(self.world_size):
            st = self._stamp(r)
            if st is not None and (now - st) <= self.ttl:
                out.add(r)
        return out

    def stale_ranks(self, registered_only: bool = True) -> List[int]:
        """Ranks whose lease EXPIRED (registered once, then lapsed). Ranks
        that never registered are reported only with registered_only=False
        (startup grace: a slow-to-boot worker is not a membership loss)."""
        now = time.monotonic()
        out = []
        for r in range(self.world_size):
            st = self._stamp(r)
            if st is None:
                if not registered_only and not self._is_done(r):
                    out.append(r)
            elif (now - st) > self.ttl and not self._is_done(r):
                # done-marker consulted only on an actual lapse: it costs a
                # blocking store round-trip, and the common all-alive poll
                # must stay cheap (launcher iterates this every 0.2s)
                out.append(r)
        return out

    # ---- worker-side watch --------------------------------------------------
    def monitor(self, on_change: Optional[Callable[[Set[int]], None]] = None,
                interval: Optional[float] = None):
        """Watch the peer set from a daemon thread; when a PEER that was
        alive lapses, either call ``on_change(lost)`` or (default) log and
        ``os._exit(ELASTIC_EXIT_CODE)`` so the launcher relaunches the
        incarnation and every worker resumes from checkpoint."""
        if self._watch_thread is not None:
            return self
        interval = interval if interval is not None else self.ttl / 3.0

        def watch():
            seen: Set[int] = set()
            while not self._stop.wait(interval):
                try:
                    alive = self.alive_ranks()
                except Exception as e:
                    # a watcher that cannot see the store cannot detect
                    # lost peers — the exact blindness worth a line
                    get_logger().warning(
                        "elastic watch cannot read the peer set "
                        "(%s: %s); retrying in %.1fs",
                        type(e).__name__, e, interval)
                    continue
                seen |= alive
                lost = {r for r in seen - alive
                        if r != self.rank and not self._is_done(r)}
                if lost:
                    if on_change is not None:
                        on_change(lost)
                        seen = set(alive)
                        continue
                    print(f"elastic: rank {self.rank} detected lost peers "
                          f"{sorted(lost)}; exiting for coordinated restart",
                          flush=True)
                    os._exit(ELASTIC_EXIT_CODE)

        self._watch_thread = threading.Thread(
            name="elastic-watch", target=watch, daemon=True)
        self._watch_thread.start()
        return self

    def close(self):
        self._stop.set()


class RestartGuard:
    """SIGTERM → checkpoint-then-exit, with torn-state protection.

    The launcher tears an incarnation down with SIGTERM (launch/main.py
    watch loop; ref ``ElasticManager`` stops trainers the same way before a
    membership-driven restart). A rank that was healthy at teardown time
    may be AHEAD of its last periodic checkpoint — ``save_fn`` runs once
    here so the next incarnation resumes from the newest step instead of
    replaying work (the reference's "save on signal" contract). The handler
    then exits with ``exit_code`` (never returns): resuming training after
    teardown began would race the relaunch.

    A Python signal handler runs at an arbitrary bytecode boundary — in
    the middle of ``optimizer.step()`` the parameters are half-updated and
    a save there would checkpoint torn state. Wrap each mutation span in
    ``shield()``: a signal landing inside it defers the save to the
    ``with`` exit, when the model/step-counter pair is consistent again.
    Between spans (data loading, collective waits — where workers spend
    teardown in practice) the save runs immediately.
    """

    def __init__(self, save_fn: Callable[[], None],
                 exit_code: int = ELASTIC_EXIT_CODE):
        self._save_fn = save_fn
        self._exit_code = exit_code
        self._fired = False
        self._shielded = 0
        self._pending = False

    def _save_and_exit(self):
        try:
            self._save_fn()
        finally:
            os._exit(self._exit_code)

    def _handler(self, signum, frame):
        if self._fired:
            os._exit(self._exit_code)
        self._fired = True
        if self._shielded:
            self._pending = True  # defer to the shield() exit
            return
        self._save_and_exit()

    def shield(self):
        """Context manager marking a model/step-counter mutation span as
        atomic with respect to the save-on-signal handler."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            self._shielded += 1
            try:
                yield
            finally:
                self._shielded -= 1
                if self._pending and not self._shielded:
                    self._save_and_exit()

        return cm()


def on_restart_signal(save_fn: Callable[[], None],
                      exit_code: int = ELASTIC_EXIT_CODE) -> RestartGuard:
    """Install the save-on-signal SIGTERM handler; returns the guard whose
    ``shield()`` protects mutation spans from torn-state saves."""
    import signal

    guard = RestartGuard(save_fn, exit_code)
    signal.signal(signal.SIGTERM, guard._handler)
    return guard


def start_elastic(job_id: Optional[str] = None, ttl: Optional[float] = None):
    """Worker one-liner: register this rank's lease and monitor peers
    (endpoint/rank/world/job from the launcher's env). No-op when the job
    was not launched with --elastic_ttl. Deregisters automatically on a
    clean interpreter exit so peers do not mistake completion for a hang."""
    import atexit

    if "PADDLE_ELASTIC_STORE" not in os.environ:
        return None
    job_id = job_id or os.environ.get("PADDLE_ELASTIC_JOB_ID", "default")
    ttl = ttl if ttl is not None else float(
        os.environ.get("PADDLE_ELASTIC_TTL", "10"))
    mgr = ElasticManager(endpoint=os.environ["PADDLE_ELASTIC_STORE"],
                         ttl=ttl, job_id=job_id)
    atexit.register(mgr.mark_done)
    return mgr.register().monitor()
