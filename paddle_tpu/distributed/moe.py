"""Mixture-of-Experts with expert parallelism (EP).

Reference parity (SURVEY.md §2.7 "EP"):
- ``MoELayer``: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
- gates: python/paddle/incubate/distributed/models/moe/gate/
  {naive_gate,gshard_gate,switch_gate}.py
- count/capacity ops: python/paddle/incubate/distributed/models/moe/utils.py
  (count_by_gate, limit_by_capacity, prune_gate_by_capacity)
- global_scatter/global_gather: python/paddle/distributed/utils/moe_utils.py:20,153
- SPMD rule: paddle/phi/infermeta/spmd_rules/moe_gate_dispatch.cc
- fused grouped-GEMM path: paddle/phi/kernels/fusion/cutlass/fused_moe_kernel.cu

TPU-native design (SURVEY.md §7 step 8). The reference routes tokens with a
sort + variable-length NCCL alltoall (``global_scatter``). That shape-dynamic
pattern defeats XLA, so dispatch here is the dense GShard formulation:
a capacity-``C`` one-hot dispatch tensor ``[S, E, C]`` and combine tensor of
the same shape, applied with einsums — static shapes, MXU-friendly grouped
matmuls, and when the expert dim is sharded over mesh axes (``moe_group``)
GSPMD materialises exactly the expert-parallel all_to_all the reference
issues by hand. Experts are authored in the GLOBAL view (all ``E`` experts
constructed once, sharded by annotation) rather than per-rank construction.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer import Layer
from ..nn.initializer_core import XavierUniform, Constant
from ..tensor_class import wrap, unwrap
from .collective import Group
from .topology import get_hybrid_communicate_group


# --------------------------------------------------------------------------
# capacity / counting primitives (parity: moe/utils.py ops, as pure jnp fns)
# --------------------------------------------------------------------------

def expert_count(gate_idx, n_expert: int):
    """Tokens assigned per expert. Parity: number_count op
    (moe/utils.py count_by_gate)."""
    gate_idx = unwrap(gate_idx)
    return jnp.sum(jax.nn.one_hot(gate_idx.reshape(-1), n_expert, dtype=jnp.int32), axis=0)


def limit_by_capacity(expert_counts, capacity: int):
    """Clamp per-expert counts to capacity (moe/utils.py limit_by_capacity)."""
    return jnp.minimum(unwrap(expert_counts), capacity)


def prune_gate_by_capacity(gate_idx, n_expert: int, capacity: int):
    """Replace over-capacity assignments with -1
    (moe/utils.py prune_gate_by_capacity)."""
    gate_idx = unwrap(gate_idx)
    flat = gate_idx.reshape(-1)
    onehot = jax.nn.one_hot(flat, n_expert, dtype=jnp.int32)
    pos = jnp.cumsum(onehot, axis=0) - 1  # position of each token within its expert
    mypos = jnp.take_along_axis(pos, flat[:, None], axis=1)[:, 0]
    pruned = jnp.where(mypos < capacity, flat, -1)
    return pruned.reshape(gate_idx.shape)


def compute_capacity(num_tokens: int, num_experts: int, top_k: int,
                     capacity_factor: float) -> int:
    cap = int(math.ceil(num_tokens * top_k * capacity_factor / num_experts))
    return max(1, min(cap, num_tokens))


def one_hot_dispatch(probs, topk_idx, capacity: int):
    """Dense GShard dispatch from top-k routing.

    probs: [S, E] softmax router probabilities.
    topk_idx: [S, K] chosen experts per token (priority = batch order,
      matching the reference's cumsum-position semantics in
      prune_gate_by_capacity).
    Returns (combine [S, E, C] float, dispatch [S, E, C] bool).
    """
    S, E = probs.shape
    K = topk_idx.shape[1]
    # vectorized over K (VERDICT r2 item 9): routes ordered k-major —
    # all k=0 routes take expert slots before any k=1 route, matching the
    # loop-with-base-offset (and the reference's cumsum-position semantics)
    mask = jax.nn.one_hot(topk_idx.T, E, dtype=jnp.int32)  # [K, S, E]
    flat = mask.reshape(K * S, E)
    pos = jnp.cumsum(flat, axis=0) - 1                      # [K*S, E]
    keep = flat * (pos < capacity)
    pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                            dtype=probs.dtype)              # [K*S, E, C]
    weights = (keep.astype(probs.dtype).reshape(K, S, E)
               * probs[None])                               # [K, S, E]
    combine = jnp.einsum("kse,ksec->sec", weights,
                         pos_oh.reshape(K, S, E, capacity))
    dispatch = combine > 0
    return combine, dispatch


def load_balance_loss(probs, topk_idx):
    """Switch/GShard auxiliary loss: E * sum_e(mean_prob_e * frac_tokens_e),
    using the top-1 assignment fraction. =1 at perfect balance."""
    E = probs.shape[-1]
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(topk_idx[:, 0], E, dtype=probs.dtype), axis=0)
    return E * jnp.sum(me * ce)


# --------------------------------------------------------------------------
# gates
# --------------------------------------------------------------------------

class BaseGate(Layer):
    """Router base (gate/base_gate.py). ``num_expert`` is the per-rank count
    in the reference; total experts = num_expert * world_size. Here experts
    are global, so tot_expert is the routing width."""

    def __init__(self, num_expert: int, world_size: int = 1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = num_expert * world_size
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear: bool = True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def dispatch(self, x_flat):  # pragma: no cover - abstract
        raise NotImplementedError


class NaiveGate(BaseGate):
    """Plain top-k softmax router, no capacity drop (gate/naive_gate.py).

    Routing runs through :func:`~paddle_tpu.ops.registry.apply` as one pure
    stage so the eager tape differentiates through the combine weights."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1, topk: int = 2,
                 capacity_factor: Optional[float] = 2.0):
        super().__init__(num_expert, world_size)
        self.d_model = d_model
        self.top_k = topk
        # Default 2.0 bounds the dispatch tensors at O(S*K*factor*M)
        # (VERDICT r2 item 9: C = S by default is quadratic in tokens).
        # Pass capacity_factor=None to opt IN to the reference's strict
        # no-drop semantics (C = S) for small-S correctness work.
        self.capacity_factor = capacity_factor
        self.gate_weight = self.create_parameter(
            [d_model, self.tot_expert], default_initializer=XavierUniform())
        self.gate_bias = self.create_parameter(
            [self.tot_expert], default_initializer=Constant(0.0), is_bias=True)

    # -- pure routing stage (x, w, b, key are raw arrays) ------------------
    def _route(self, x, w, b, key, training):
        probs = jax.nn.softmax((x @ w + b).astype(jnp.float32), axis=-1)
        _, topk_idx = jax.lax.top_k(probs, self.top_k)
        if self.capacity_factor is None:
            cap = x.shape[0]  # no drop
        else:
            cap = compute_capacity(x.shape[0], self.tot_expert, self.top_k,
                                   self.capacity_factor)
        combine, disp = one_hot_dispatch(probs, topk_idx, cap)
        aux = jnp.zeros((), jnp.float32)
        return (combine.astype(x.dtype),
                jax.lax.stop_gradient(disp.astype(x.dtype)), aux)

    def dispatch(self, x_flat):
        """x_flat: Tensor [S, M] → (combine [S,E,C], dispatch_f [S,E,C])."""
        from ..ops.registry import apply

        key = self._routing_key()
        combine, disp, aux = apply(
            "moe_gate", self._route, x_flat, self.gate_weight, self.gate_bias,
            key, training=self.training)
        self.set_loss(aux)
        return combine, disp

    def _routing_key(self):
        return None


class SwitchGate(NaiveGate):
    """Top-1 router with capacity + training jitter (gate/switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size: int = 1, topk: int = 1,
                 switch_eps: float = 0.1, capacity: Sequence[float] = (1.2, 2.4)):
        assert topk == 1, "switch gate is top-1"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity = capacity  # (train_factor, eval_factor)

    def _routing_key(self):
        if self.training and self.switch_eps > 0:
            from ..framework.random import next_key

            return next_key()
        return None

    def _route(self, x, w, b, key, training):
        logits = (x @ w + b).astype(jnp.float32)
        if key is not None:
            noise = jax.random.uniform(
                key, logits.shape,
                minval=1.0 - self.switch_eps, maxval=1.0 + self.switch_eps)
            logits = logits + jnp.log(noise)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_idx = jnp.argmax(probs, axis=-1)[:, None]
        factor = self.capacity[0] if training else self.capacity[1]
        cap = compute_capacity(x.shape[0], self.tot_expert, 1, factor)
        combine, disp = one_hot_dispatch(probs, topk_idx, cap)
        aux = load_balance_loss(probs, topk_idx)
        return (combine.astype(x.dtype),
                jax.lax.stop_gradient(disp.astype(x.dtype)), aux)


class GShardGate(NaiveGate):
    """Top-2 router with capacity + balance loss (gate/gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size: int = 1, topk: int = 2,
                 capacity: Sequence[float] = (1.2, 2.4), random_routing: bool = True):
        assert topk == 2, "gshard gate is top-2"
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity = capacity
        self.random_routing = random_routing

    def _routing_key(self):
        if self.random_routing and self.training:
            from ..framework.random import next_key

            return next_key()
        return None

    def _route(self, x, w, b, key, training):
        probs = jax.nn.softmax((x @ w + b).astype(jnp.float32), axis=-1)
        topk_val, topk_idx = jax.lax.top_k(probs, 2)
        if key is not None:
            # keep 2nd expert with prob 2*gate2 (gshard_gate.py random routing);
            # -1 is the drop sentinel: one_hot(-1) is all-zero, so the route
            # simply vanishes (matches the reference's _random_routing)
            r = jax.random.uniform(key, topk_val[:, 1].shape)
            drop = r >= 2.0 * jax.lax.stop_gradient(topk_val[:, 1])
            topk_idx = topk_idx.at[:, 1].set(
                jnp.where(drop, -1, topk_idx[:, 1]))
        factor = self.capacity[0] if training else self.capacity[1]
        cap = compute_capacity(x.shape[0], self.tot_expert, 2, factor)
        combine, disp = one_hot_dispatch(probs, topk_idx, cap)
        aux = load_balance_loss(probs, topk_idx)
        return (combine.astype(x.dtype),
                jax.lax.stop_gradient(disp.astype(x.dtype)), aux)


# --------------------------------------------------------------------------
# experts
# --------------------------------------------------------------------------

def _act_fn(activation: str):
    if activation == "gelu":  # exact erf gelu (paddle F.gelu default)
        return lambda v: jax.nn.gelu(v, approximate=False)
    return getattr(jax.nn, activation)


def _expert_act(z, activation: str):
    """Hidden activation of the expert FFN. ``"swiglu"`` reads z as the
    FUSED gate‖up projection output ([..., 2*hid] — the LLM-expert form:
    DeepSeekMoE/Qwen2-MoE/ERNIE experts are silu(x@Wg) * (x@Wu) @ Wd);
    the one definition serves the padded ([E, C, M]) and ragged paths."""
    if activation == "swiglu":
        g, u = jnp.split(z, 2, axis=-1)
        return jax.nn.silu(g) * u
    return _act_fn(activation)(z)


def _grouped_ffn(xe, w1, b1, w2, b2, activation: str):
    """[E, C, M] grouped FFN on raw arrays — shared by the Layer forward
    and the tape-recorded apply() path."""
    h = _expert_act(jnp.einsum("ecm,emh->ech", xe, w1) + b1, activation)
    return jnp.einsum("ech,ehm->ecm", h, w2) + b2


class GroupedMLP(Layer):
    """All E experts' FFN weights stacked on a leading expert dim — the
    grouped-GEMM formulation (parity: fused_moe cutlass grouped GEMM,
    paddle/phi/kernels/fusion/cutlass/cutlass_kernels/moe_gemm/). One einsum
    per projection keeps the MXU busy across experts and lets the expert dim
    be sharded for EP."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: str = "gelu"):
        super().__init__()
        self.num_experts = num_experts
        self.d_model, self.d_hidden = d_model, d_hidden
        self.activation = activation
        # swiglu experts fuse gate‖up into one [E, M, 2*hid] projection
        # (one grouped GEMM instead of two)
        fan1 = d_hidden * (2 if activation == "swiglu" else 1)
        # per-expert fans: the stacked [E, in, out] layout would otherwise be
        # read as conv-style (E*out receptive) by Initializer._fan
        self.w1 = self.create_parameter(
            [num_experts, d_model, fan1],
            default_initializer=XavierUniform(fan_in=d_model, fan_out=d_hidden))
        self.b1 = self.create_parameter(
            [num_experts, 1, fan1], default_initializer=Constant(0.0), is_bias=True)
        self.w2 = self.create_parameter(
            [num_experts, d_hidden, d_model],
            default_initializer=XavierUniform(fan_in=d_hidden, fan_out=d_model))
        self.b2 = self.create_parameter(
            [num_experts, 1, d_model], default_initializer=Constant(0.0), is_bias=True)

    def forward_expert_batch(self, xe):
        """xe: [E, C, M] → [E, C, M]."""
        return _grouped_ffn(xe, unwrap(self.w1), unwrap(self.b1),
                            unwrap(self.w2), unwrap(self.b2), self.activation)

    def forward_ragged(self, x, group_sizes):
        """Ragged grouped GEMM: x [T, M] tokens SORTED by expert,
        group_sizes [E] (sum = T). Uses jax.lax.ragged_dot, which lowers to
        the TPU grouped-matmul kernel (the role of the reference's cutlass
        moe_gemm, fusion/cutlass/cutlass_kernels/moe_gemm/) — no padding to
        a uniform capacity, so imbalanced expert loads waste no FLOPs."""
        xs = unwrap(x)
        gs = unwrap(group_sizes).astype(jnp.int32)
        T = xs.shape[0]
        try:  # loud failure beats silently-garbage trailing rows
            total = int(gs.sum())
            if total != T:
                raise ValueError(
                    f"forward_ragged: group_sizes sums to {total} but x has "
                    f"{T} tokens")
        except jax.errors.TracerIntegerConversionError:
            pass  # traced sizes: shape agreement is the caller's contract
        w1, b1 = unwrap(self.w1), unwrap(self.b1)
        w2, b2 = unwrap(self.w2), unwrap(self.b2)
        b1_tok = jnp.repeat(b1[:, 0], gs, axis=0, total_repeat_length=T)
        b2_tok = jnp.repeat(b2[:, 0], gs, axis=0, total_repeat_length=T)
        h = _expert_act(jax.lax.ragged_dot(xs, w1, gs) + b1_tok,
                        self.activation)
        out = jax.lax.ragged_dot(h, w2, gs) + b2_tok
        return wrap(out)

    def forward(self, x):
        return wrap(self.forward_expert_batch(unwrap(x)))


def default_ep_axes(num_experts: int):
    """The hybrid topology's data axes (dp, sharding) whose joint degree
    divides ``num_experts`` — the default expert-parallel placement (the
    reference's moe group defaults to the data-parallel communicator)."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return ()
    axes = tuple(a for a in ("dp", "sharding")
                 if hcg.mesh.get_dim_size(a) > 1)
    if axes and num_experts % np.prod(
            [hcg.mesh.get_dim_size(a) for a in axes]) == 0:
        return axes
    return ()


def ep_constrain(arr, axes, expert_sharded: bool = True):
    """Sharding constraint on a dispatched [E, C, M]-style block so GSPMD
    inserts the EP all_to_all at the dispatch/combine boundary. No-op in
    eager mode (the constraint only means something under tracing) or when
    no hybrid mesh / axes are active."""
    hcg = get_hybrid_communicate_group()
    axes = tuple(axes or ())
    if hcg is None or not axes or not isinstance(arr, jax.core.Tracer):
        return arr
    spec = [None] * arr.ndim
    if expert_sharded:
        spec[0] = axes if len(axes) > 1 else axes[0]
    return jax.lax.with_sharding_constraint(
        arr, NamedSharding(hcg.mesh.jax_mesh(), PartitionSpec(*spec)))


def shard_grouped_experts(experts: "GroupedMLP", axes) -> tuple:
    """EP placement: shard a GroupedMLP's expert dim over mesh ``axes``
    (a multi-axis Shard when several axes fold together). Returns the axes
    applied (() when no hybrid mesh / empty axes)."""
    hcg = get_hybrid_communicate_group()
    axes = tuple(axes or ())
    if hcg is None or not axes:
        return ()
    mesh = hcg.mesh
    for name in ("w1", "b1", "w2", "b2"):
        p = getattr(experts, name)
        spec = [None] * len(p.shape)
        spec[0] = axes if len(axes) > 1 else axes[0]
        p._array = jax.device_put(
            unwrap(p), NamedSharding(mesh.jax_mesh(), PartitionSpec(*spec)))
    return axes


class MoELayer(Layer):
    """Mixture-of-experts layer (moe_layer.py:263).

    Args mirror the reference: ``experts`` is either a :class:`GroupedMLP`
    (preferred — grouped GEMM + EP sharding) or a list of per-expert Layers
    (looped; kept for API parity with arbitrary expert modules).
    ``moe_group`` names the mesh axes the expert dim is sharded over (the
    reference's NCCL moe group); default: the hybrid topology's data axes.
    """

    def __init__(self, d_model: int, experts, gate=None,
                 moe_group: Optional[Group] = None, mp_group=None,
                 recompute_interval: int = 0, top_k: int = 2):
        super().__init__()
        self.d_model = d_model
        if isinstance(experts, GroupedMLP):
            self.experts = experts
            num_experts = experts.num_experts
        else:
            from ..nn.container import LayerList

            if not isinstance(experts, Layer):
                experts = LayerList(list(experts))  # materialize iterables once
            self.experts = experts
            num_experts = len(list(experts))
        self.num_experts = num_experts
        if gate is None:
            gate = NaiveGate(d_model, num_experts, topk=top_k)
        elif isinstance(gate, dict):
            kind = gate.get("type", "naive")
            cls = {"naive": NaiveGate, "gshard": GShardGate, "switch": SwitchGate}[kind]
            kwargs = {k: v for k, v in gate.items() if k != "type"}
            kwargs.setdefault("topk", 1 if kind == "switch" else 2)
            gate = cls(d_model, num_experts, **kwargs)
        self.gate = gate
        self.recompute_interval = recompute_interval
        self.activation_name = (experts.activation
                                if isinstance(experts, GroupedMLP) else "gelu")
        self._ep_axes = self._resolve_ep_axes(moe_group)
        if self._ep_axes and isinstance(self.experts, GroupedMLP):
            self._shard_experts()

    # -- EP sharding -------------------------------------------------------
    def _resolve_ep_axes(self, moe_group):
        if isinstance(moe_group, (Group, tuple, list)):
            if isinstance(moe_group, Group):
                axes, mesh = tuple(moe_group.axis_names), moe_group.mesh
            else:
                axes = tuple(moe_group)
                hcg = get_hybrid_communicate_group()
                mesh = hcg.mesh if hcg is not None else None
            if mesh is not None and axes:
                ep = int(np.prod([mesh.get_dim_size(a) for a in axes]))
                if self.num_experts % ep != 0:
                    raise ValueError(
                        f"num_experts={self.num_experts} must be divisible by "
                        f"EP degree {ep} (moe_group axes {axes})")
            return axes
        if moe_group is None:
            axes = default_ep_axes(self.num_experts)
            if axes:
                return axes
        return ()

    def _shard_experts(self):
        shard_grouped_experts(self.experts, self._ep_axes)

    def _constrain(self, arr, expert_sharded: bool):
        return ep_constrain(arr, self._ep_axes, expert_sharded)

    # -- forward -----------------------------------------------------------
    def _dispatch_fn(self, x_flat, dispatch):
        # [S,M] x [S,E,C] -> [E,C,M]  (the reference's MoEScatter+global_scatter)
        xe = jnp.einsum("sm,sec->ecm", x_flat, dispatch.astype(x_flat.dtype))
        return self._constrain(xe, expert_sharded=True)

    def _expert_ffn_fn(self, xe, w1, b1, w2, b2):
        ffn = lambda v: _grouped_ffn(v, w1, b1, w2, b2, self.activation_name)
        if self.recompute_interval > 0:
            ffn = jax.checkpoint(ffn)
        return self._constrain(ffn(xe), expert_sharded=True)

    def _combine_fn(self, ye, combine):
        # [E,C,M] x [S,E,C] -> [S,M]  (MoEGather+global_gather)
        return jnp.einsum("ecm,sec->sm", ye, combine.astype(ye.dtype))

    def forward(self, x):
        from ..ops.registry import apply

        orig_shape = tuple(x.shape)
        x_flat = apply("reshape", lambda a: a.reshape(-1, self.d_model), x)
        combine, dispatch = self.gate.dispatch(x_flat)
        xe = apply("moe_dispatch", self._dispatch_fn, x_flat, dispatch)
        if isinstance(self.experts, GroupedMLP):
            g = self.experts
            ye = apply("moe_expert_ffn", self._expert_ffn_fn, xe,
                       g.w1, g.b1, g.w2, g.b2)
        else:
            outs = [expert(xe[e]) for e, expert in enumerate(self.experts)]
            ye = apply("stack", lambda *a: jnp.stack(a, axis=0), *outs)
        y = apply("moe_combine", self._combine_fn, ye, combine)
        return apply("reshape", lambda a: a.reshape(orig_shape), y)


# --------------------------------------------------------------------------
# eager global_scatter / global_gather (moe_utils.py:20,153)
# --------------------------------------------------------------------------

def _counts_to_np(c):
    return np.asarray(unwrap(c)).astype(np.int64)


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Reference-semantics expert exchange (moe_utils.py:20) in the global
    view. ``x``: [world, local_batch, M] stacked per-rank token buffers, each
    rank's tokens ordered by destination index i = dest_rank * n_expert +
    expert; ``local_count``: [world, world * n_expert]; ``global_count``:
    [world, world * n_expert] (i = src_rank * n_expert + expert). Output:
    [world, out_batch, M] where each rank's buffer is ordered expert-major
    then source-rank (the layout the reference's recv loop produces),
    zero-padded to the max recv count.

    This is an EAGER data-movement utility for API parity/testing; the
    jit/production path is MoELayer's dense dispatch (see module docstring).
    """
    xg = np.asarray(unwrap(x))
    lc, gc = _counts_to_np(local_count), _counts_to_np(global_count)
    world, _, M = xg.shape
    n_expert = lc.shape[1] // world
    # start offset of segment i in each source rank's buffer
    starts = np.concatenate([np.zeros((world, 1), np.int64), np.cumsum(lc, axis=1)], axis=1)
    out_batch = int(gc.sum(axis=1).max()) if gc.size else 0
    out = np.zeros((world, out_batch, M), xg.dtype)
    for dst in range(world):
        off = 0
        for e in range(n_expert):
            for src in range(world):
                cnt = int(lc[src, dst * n_expert + e])
                s = int(starts[src, dst * n_expert + e])
                out[dst, off:off + cnt] = xg[src, s:s + cnt]
                off += cnt
    return wrap(jnp.asarray(out))


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of :func:`global_scatter` (moe_utils.py:153): routes expert
    outputs back to the token owners, restoring each rank's original
    local-buffer order."""
    xg = np.asarray(unwrap(x))
    lc, gc = _counts_to_np(local_count), _counts_to_np(global_count)
    world, _, M = xg.shape
    n_expert = lc.shape[1] // world
    starts = np.concatenate([np.zeros((world, 1), np.int64), np.cumsum(lc, axis=1)], axis=1)
    out_batch = int(lc.sum(axis=1).max()) if lc.size else 0
    out = np.zeros((world, out_batch, M), xg.dtype)
    # walk the scattered layout in the same order global_scatter wrote it
    for dst in range(world):
        off = 0
        for e in range(n_expert):
            for src in range(world):
                cnt = int(lc[src, dst * n_expert + e])
                s = int(starts[src, dst * n_expert + e])
                out[src, s:s + cnt] = xg[dst, off:off + cnt]
                off += cnt
    return wrap(jnp.asarray(out))
