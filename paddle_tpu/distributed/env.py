"""Distributed environment: ranks, world size, multi-host bootstrap.

Reference parity: paddle.distributed.init_parallel_env + PADDLE_TRAINER_* env
protocol (python/paddle/distributed/parallel.py:978,1134 — TCPStore
rendezvous). TPU-native: ``jax.distributed.initialize`` is the coordinator
(the TCPStore analog); after it, ``jax.devices()`` is the global device list
and all collectives compile over ICI/DCN. Single-process multi-device (one
host, N chips) needs no bootstrap at all.
"""
from __future__ import annotations

import os

import jax

_initialized = [False]


def init_parallel_env(coordinator_address=None, num_processes=None, process_id=None,
                      local_device_ids=None):
    """Bootstrap multi-host; no-op for single-process jobs.

    Env protocol (launcher parity): PADDLE_MASTER / PADDLE_TRAINERS_NUM /
    PADDLE_TRAINER_ID, falling back to the JAX coordination vars.
    """
    if _initialized[0]:
        return
    coordinator_address = coordinator_address or os.environ.get("PADDLE_MASTER") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _int_env("PADDLE_TRAINERS_NUM") or _int_env("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None else (
        _int_env("PADDLE_TRAINER_ID") if "PADDLE_TRAINER_ID" in os.environ else _int_env("JAX_PROCESS_ID"))
    if coordinator_address and num_processes and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    _initialized[0] = True


def _int_env(name):
    v = os.environ.get(name)
    return int(v) if v is not None else None


def get_rank(group=None) -> int:
    """Process index (reference: paddle.distributed.get_rank)."""
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except RuntimeError:  # pragma: no cover — backend not initialized
        return 0


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except RuntimeError:  # pragma: no cover — backend not initialized
        return 1


def is_initialized() -> bool:
    return _initialized[0]


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()
