"""Placement types: Shard / Replicate / Partial.

Reference parity: paddle/phi/core/distributed/auto_parallel/placement_types.h
and python/paddle/distributed (dist.Shard/dist.Replicate/dist.Partial).
Mapping to jax.sharding: a placements list (one entry per MESH dim) compiles
to a PartitionSpec (one entry per TENSOR dim); Partial has no direct
PartitionSpec form — it is tracked as a pending-reduce annotation and
materialised by reshard() via psum (the same role the reference's
p_to_{r,s} reshard functions play).
"""
from __future__ import annotations

from typing import List, Optional, Sequence


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return True if dim is None else self.dim == dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


def placements_to_partition_spec(placements: Sequence[Placement], mesh_dim_names: Sequence[str],
                                 tensor_ndim: int):
    """Build the jax PartitionSpec equivalent of a placements list.

    Partial entries contribute nothing to the spec (the value is locally
    unreduced but replicated in layout terms).
    """
    from jax.sharding import PartitionSpec

    per_tensor_dim: List[list] = [[] for _ in range(tensor_ndim)]
    for mesh_dim, placement in enumerate(placements):
        if isinstance(placement, Shard):
            if placement.dim >= tensor_ndim:
                raise ValueError(
                    f"Shard(dim={placement.dim}) invalid for tensor of rank {tensor_ndim}")
            per_tensor_dim[placement.dim].append(mesh_dim_names[mesh_dim])
    entries = []
    for axes in per_tensor_dim:
        if not axes:
            entries.append(None)
        elif len(axes) == 1:
            entries.append(axes[0])
        else:
            entries.append(tuple(axes))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def partition_spec_to_placements(spec, mesh_dim_names: Sequence[str]) -> List[Placement]:
    placements: List[Placement] = [Replicate() for _ in mesh_dim_names]
    name_to_mesh_dim = {n: i for i, n in enumerate(mesh_dim_names)}
    for tensor_dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[name_to_mesh_dim[ax]] = Shard(tensor_dim)
    return placements
