"""Auto-tuner: search over hybrid-parallel configurations.

Reference parity: python/paddle/distributed/auto_tuner/
(``AutoTuner.search_once`` tuner.py:21,62, pruning rules prune.py, cost
models cost_model.py / memory_cost_model.py). Same shape here: grid search
over (dp, mp, pp, sharding-stage, micro-batch, recompute) candidates,
divisibility/memory pruning before any run, history-based pruning after
measured runs, and an analytic memory cost model tuned for TPU HBM.

TPU-native notes baked into the cost model: mp (tensor parallel) shards
both weights and activations over ICI; sharding stages 1/2/3 divide
optimizer state / grads / params; recompute trades step time for
activation memory (jax.checkpoint).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class TunerConfig:
    """User settings (tuner_cfg parity; only TPU-meaningful knobs)."""

    num_devices: int = 8
    global_batch_size: int = 8
    # model shape for the memory model
    hidden_size: int = 2048
    num_layers: int = 8
    seq_len: int = 2048
    vocab_size: int = 32000
    intermediate_size: Optional[int] = None
    dtype_bytes: int = 2          # bf16 params
    hbm_bytes: int = 16 * 2 ** 30  # v5e default; v5p: 95GB
    # search space (None = derive from num_devices divisors)
    mp_candidates: Optional[List[int]] = None
    pp_candidates: Optional[List[int]] = None
    sharding_stage_candidates: Optional[List[int]] = None
    micro_batch_candidates: Optional[List[int]] = None
    recompute_candidates: Optional[List[bool]] = None
    task_limit: int = 100


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class MemoryCostModel:
    """Analytic per-device HBM estimate (memory_cost_model.py role)."""

    def __init__(self, cfg: TunerConfig):
        self.cfg = cfg

    def params_bytes(self) -> int:
        c = self.cfg
        inter = c.intermediate_size or 4 * c.hidden_size
        per_layer = 4 * c.hidden_size * c.hidden_size + 3 * c.hidden_size * inter
        emb = c.vocab_size * c.hidden_size
        return (c.num_layers * per_layer + 2 * emb) * c.dtype_bytes

    def estimate(self, trial: Dict[str, Any]) -> int:
        c = self.cfg
        mp = trial["mp_degree"]
        pp = trial["pp_degree"]
        stage = trial["sharding_stage"]
        dp_shard = c.num_devices // (mp * pp)
        p = self.params_bytes() // (mp * pp)
        # optimizer: fp32 master + 2 adam moments = 6x param bytes (bf16->f32)
        opt = 6 * p
        grads = p
        if stage >= 1:
            opt //= max(dp_shard, 1)
        if stage >= 2:
            grads //= max(dp_shard, 1)
        if stage >= 3:
            p //= max(dp_shard, 1)
        micro = trial["micro_batch_size"]
        act_per_token = c.hidden_size * c.num_layers // pp * c.dtype_bytes
        acts = micro * c.seq_len * act_per_token * (4 if not trial["recompute"] else 1)
        acts //= mp
        return p + opt + grads + acts


class AutoTuner:
    """Grid search + pruning (tuner.py:21 parity).

    ``search_once()`` returns the next un-pruned candidate dict (or None
    when exhausted); ``add_cfg(cfg)`` records a measured result
    (``cfg["time"]`` seconds or ``cfg["error"]``) enabling history pruning
    (a config whose strictly-weaker sibling OOMed is skipped).
    """

    def __init__(self, tuner_cfg):
        self.cfg = (tuner_cfg if isinstance(tuner_cfg, TunerConfig)
                    else TunerConfig(**tuner_cfg))
        self.mem_model = MemoryCostModel(self.cfg)
        self.history_cfgs: List[Dict[str, Any]] = []
        self.cur_task_id = 1
        self.task_limit = self.cfg.task_limit
        self._candidates = self._build_candidates()
        self._cursor = 0

    # ---- candidate generation (search.py GridSearch role) -------------------
    def _build_candidates(self) -> List[Dict[str, Any]]:
        c = self.cfg
        mps = c.mp_candidates or _divisors(c.num_devices)
        pps = c.pp_candidates or _divisors(c.num_devices)
        stages = c.sharding_stage_candidates or [0, 1, 2, 3]
        micros = c.micro_batch_candidates or _divisors(c.global_batch_size)
        recs = c.recompute_candidates or [False, True]
        out = []
        for mp, pp, st, mb, rc in itertools.product(mps, pps, stages, micros, recs):
            trial = {"mp_degree": mp, "pp_degree": pp, "sharding_stage": st,
                     "micro_batch_size": mb, "recompute": rc}
            est = self._prune_static(trial)
            if est is None:
                continue
            trial["dp_degree"] = c.num_devices // (mp * pp)
            trial["estimated_memory"] = est
            out.append(trial)
        # cheapest memory first: likeliest to run, fastest signal (the
        # reference sorts candidates by its cost model too)
        out.sort(key=lambda t: t["estimated_memory"])
        return out

    # ---- pruning rules (prune.py role) ---------------------------------------
    def _prune_static(self, t):
        """Returns the memory estimate for a surviving trial, None when
        pruned (the estimate is reused, not recomputed)."""
        c = self.cfg
        mp, pp = t["mp_degree"], t["pp_degree"]
        if mp * pp > c.num_devices or c.num_devices % (mp * pp) != 0:
            return None  # prune_by_num_gpus
        if c.hidden_size % mp != 0:
            return None  # prune_by_mp: heads/hidden must divide
        if c.num_layers % pp != 0:
            return None  # prune_by_pp
        dp = c.num_devices // (mp * pp)
        if c.global_batch_size % (dp * t["micro_batch_size"]) != 0:
            return None  # prune_by_mbs: accumulate_steps must be integral
        if t["sharding_stage"] > 0 and dp == 1:
            return None  # sharding needs a data axis
        mem = self.mem_model.estimate({**t, "dp_degree": dp})
        if mem > self.cfg.hbm_bytes:
            return None  # memory model prune
        return mem

    def _prune_by_history(self, t) -> bool:
        for h in self.history_cfgs:
            if h.get("error") == "oom":
                # anything needing >= the OOMed config's memory is dead
                if t["estimated_memory"] >= h["estimated_memory"]:
                    return True
        return False

    # ---- the public surface (tuner.py:62) ------------------------------------
    def search_once(self) -> Optional[Dict[str, Any]]:
        """Return the next task config, or None when exhausted."""
        if self.cur_task_id > self.task_limit:
            return None
        while self._cursor < len(self._candidates):
            trial = self._candidates[self._cursor]
            self._cursor += 1
            if self._prune_by_history(trial):
                continue
            self.cur_task_id += 1
            return dict(trial)
        return None

    def add_cfg(self, cfg: Dict[str, Any]):
        """Record a measured result (time/error fields)."""
        self.history_cfgs.append(cfg)

    def best_cfg(self) -> Optional[Dict[str, Any]]:
        ran = [h for h in self.history_cfgs
               if "time" in h and h.get("error") is None]
        return min(ran, key=lambda h: h["time"]) if ran else None
