"""Reference-surface tail of paddle.distributed: async p2p handles, legacy
spellings, auto-parallel entry objects.

Reference parity, by name:
- ``isend/irecv/wait`` (communication/{send,recv}.py async forms +
  communication/wait): under the single-controller XLA runtime every
  dispatched collective is already asynchronous — the returned task's
  ``wait()`` is ``block_until_ready`` on the result.
- ``alltoall/alltoall_single`` (communication/all_to_all.py): the older
  spellings of all_to_all.
- ``get_backend/is_available/destroy_process_group`` (parallel.py): the
  backend is XLA's collective stack, not nccl/gloo.
- ``ReduceType`` (auto_parallel placement reduce kinds) and ``Strategy``
  (auto_parallel/strategy.py — the same knobs DistributedStrategy
  carries here).
- ``ParallelEnv``/``ParallelMode`` (legacy parallel env probes).
- ``dtensor_from_fn`` / ``shard_dataloader`` / ``shard_scaler``
  (auto_parallel/api.py): dist-tensor construction + input pipeline
  sharding; under GSPMD the scaler already operates on global arrays, so
  ``shard_scaler`` is the identity contract.
- ``DistModel`` / ``to_static`` (auto_parallel/api.py:2798): the
  mode-switched wrapper over the compiled hybrid-parallel step.
"""
from __future__ import annotations

import jax

from ..tensor_class import Tensor, unwrap, wrap
from .collective import all_to_all, recv, send


class P2POp:  # minimal task handle
    pass


class _Task:
    """Completed-dispatch handle (ProcessGroup::Task analog): XLA queues
    the transfer at dispatch; wait() syncs the payload."""

    def __init__(self, tensor):
        self._t = tensor

    def wait(self):
        arr = unwrap(self._t) if isinstance(self._t, Tensor) else self._t
        if hasattr(arr, "block_until_ready"):
            arr.block_until_ready()
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None, sync_op=False):
    """Same SPMD contract as send(): the single-controller facade has no
    eager P2P (it raises with guidance); where send works (pipeline
    runtime paths), the returned task's wait() syncs the transfer."""
    send(tensor, dst=dst, group=group, sync_op=False)
    return _Task(tensor)


def irecv(tensor, src=0, group=None, sync_op=False):
    out = recv(tensor, src=src, group=group, sync_op=False)
    return _Task(out)


def wait(tensor, group=None, use_calc_stream=True):
    """communication/wait parity: sync the tensor's pending work."""
    arr = unwrap(tensor) if isinstance(tensor, Tensor) else tensor
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return tensor


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Older spelling of all_to_all (same list-in/list-out contract)."""
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """communication/all_to_all.py alltoall_single parity on the GLOBAL
    array view: the leading dim shards over the group axis (rank r owns
    chunk r), each rank's chunk splits into nranks sub-chunks, and the
    exchange transposes sub-chunk ownership (lax.all_to_all in-graph —
    the collective that actually rides ICI). Needs the leading dim
    divisible by nranks^2 (global chunking x per-rank split). Uneven
    split sizes are not represented."""
    if in_split_sizes is not None or out_split_sizes is not None:
        raise NotImplementedError(
            "alltoall_single with explicit split sizes is not supported; "
            "the XLA all_to_all splits the leading dim evenly")
    import jax.numpy as jnp
    from jax import lax

    from .collective import _axis

    mesh, axes = _axis(group)
    arr = unwrap(in_tensor) if isinstance(in_tensor, Tensor) \
        else jnp.asarray(in_tensor)
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    if arr.shape[0] % (n * n):
        raise ValueError(
            f"alltoall_single: leading dim {arr.shape[0]} must be "
            f"divisible by nranks^2 ({n * n}) — global chunk per rank, "
            "then one sub-chunk per destination")
    from jax.sharding import NamedSharding, PartitionSpec
    from .collective import shard_map

    spec = PartitionSpec(axes[0], *([None] * (arr.ndim - 1)))
    fn = jax.jit(shard_map(
        lambda x: lax.all_to_all(x, axes[0], split_axis=0, concat_axis=0,
                                 tiled=True),
        mesh=mesh, in_specs=(spec,), out_specs=spec))
    out = fn(jax.device_put(arr, NamedSharding(mesh, spec)))
    joined = wrap(out)
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._array = joined._array
        return out_tensor
    return joined


def get_backend(group=None) -> str:
    return "XLA"


def is_available() -> bool:
    return True


def destroy_process_group(group=None):
    """Tear down the default group's cached mesh view. Sub-groups are
    stateless mesh views — destroying one is a no-op."""
    from . import collective

    if group is None or group is collective._default_group[0]:
        collective._default_group[0] = None


class ReduceType:
    """auto_parallel reduce kinds (placement Partial's reduce_type)."""

    kRedSum = "sum"
    kRedMax = "max"
    kRedMin = "min"
    kRedProd = "prod"
    kRedAvg = "avg"
    kRedAny = "any"
    kRedAll = "all"


class ParallelMode:
    """fleet.base.topology ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ParallelEnv:
    """Legacy env probe (parallel.py ParallelEnv): rank/world/device."""

    @property
    def rank(self) -> int:
        from .env import get_rank

        return get_rank()

    @property
    def world_size(self) -> int:
        from .env import get_world_size

        return get_world_size()

    @property
    def device_id(self) -> int:
        try:
            return jax.local_devices()[0].id
        except Exception:  # pdlint: disable=silent-exception -- no initialised backend has no device id: 0 is the documented placeholder and this accessor must never raise during env setup
            return 0

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def local_rank(self) -> int:
        return self.rank


class Strategy:
    """auto_parallel/strategy.py Strategy parity: the sub-config OBJECT
    spelling (``s.sharding.stage = 3``, ``s.pipeline.schedule_mode =
    "VPP"``) over the SAME live config records DistributedStrategy
    exposes as ``*_configs`` — one knob store, two reference spellings.
    Pass to fleet.init/to_static wherever a DistributedStrategy goes."""

    def __init__(self):
        from .strategy import DistributedStrategy

        # composition, not inheritance: DistributedStrategy's `amp` /
        # `recompute` properties return ENABLE BOOLS (the fleet spelling),
        # while this surface must return the config objects
        object.__setattr__(self, "_ds", DistributedStrategy())

    @property
    def sharding(self):
        return self._ds._sharding

    @property
    def pipeline(self):
        return self._ds._pipeline

    @property
    def amp(self):
        return self._ds._amp

    @property
    def recompute(self):
        return self._ds._recompute

    @property
    def gradient_merge(self):
        return self._ds._gradient_merge

    @property
    def hybrid_configs(self):
        return self._ds.hybrid_configs

    @hybrid_configs.setter
    def hybrid_configs(self, cfg):
        self._ds.hybrid_configs = cfg

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_ds"), name)

    def unwrap(self):
        """The underlying DistributedStrategy (what fleet.init consumes)."""
        return self._ds


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """auto_parallel/api.py dtensor_from_fn parity: build with ``fn`` then
    place as a dist tensor."""
    from .api import shard_tensor

    return shard_tensor(fn(*args, **kwargs), mesh, placements)


class ShardDataloader:
    """auto_parallel shard_dataloader result: iterates the wrapped loader,
    placing array fields as dist tensors on ``mesh`` (batch dim 0 sharded
    over the chosen MESH dim, everything else replicated). Dict batches
    place every value — or only ``input_keys`` when given (other keys
    pass through untouched)."""

    def __init__(self, dataloader, mesh, placements, input_keys=None):
        self._loader = dataloader
        self._mesh = mesh
        self._placements = placements
        self._keys = set(input_keys) if input_keys is not None else None

    def __len__(self):
        return len(self._loader)

    def _place(self, x):
        from .api import shard_tensor

        return shard_tensor(x, self._mesh, self._placements)

    def __iter__(self):
        for batch in self._loader:
            if isinstance(batch, dict):
                yield {k: (self._place(v)
                           if self._keys is None or k in self._keys else v)
                       for k, v in batch.items()}
            elif isinstance(batch, (list, tuple)):
                yield type(batch)(self._place(b) for b in batch)
            else:
                yield self._place(batch)


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    """auto_parallel/api.py shard_dataloader parity: ``shard_dims`` names
    the MESH dimension (str name or int index) the BATCH dim shards over;
    other mesh dims replicate. Default: the 'dp' dim when the mesh has
    one, else mesh dim 0."""
    from .placements import Replicate, Shard

    if is_dataset_splitted:
        raise NotImplementedError(
            "is_dataset_splitted=True (pre-split per-rank datasets) is not "
            "supported; the single-controller loader sees the global batch")
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
    names = list(getattr(mesh, "dim_names", ()) or ())
    rank = len(names) or getattr(getattr(mesh, "mesh", None), "ndim", 1)
    if shard_dims is None:
        mesh_dim = names.index("dp") if "dp" in names else 0
    elif isinstance(shard_dims, str):
        if shard_dims not in names:
            raise ValueError(
                f"shard_dims {shard_dims!r} is not a mesh dim of {names}")
        mesh_dim = names.index(shard_dims)
    else:
        mesh_dim = int(shard_dims)
    placements = [Replicate() for _ in range(rank)]
    placements[mesh_dim] = Shard(0)
    return ShardDataloader(dataloader, mesh, placements,
                           input_keys=input_keys)


def shard_scaler(scaler):
    """auto_parallel shard_scaler parity: under GSPMD the GradScaler's
    found-inf reduction already runs over global arrays — the scaler is
    returned unchanged (the reference rewires its per-rank all-reduce)."""
    return scaler


class DistModel:
    """auto_parallel/api.py DistModel: the mode-switched callable over the
    compiled hybrid-parallel step. ``train()``/``eval()`` pick the mode;
    calling with (inputs, labels) returns the loss in train/eval and the
    model outputs in predict mode."""

    def __init__(self, model, loss_fn=None, optimizer=None, strategy=None):
        from .engine import parallelize

        self._model = model
        self._loss_fn = loss_fn
        self._optimizer = optimizer
        self._mode = "train"
        self._step = (parallelize(model, loss_fn, optimizer,
                                  strategy=strategy)
                      if loss_fn is not None and optimizer is not None
                      else None)

    def train(self):
        self._mode = "train"
        return self

    def eval(self):
        self._mode = "eval"
        return self

    def predict(self):
        self._mode = "predict"
        return self

    @property
    def mode(self):
        return self._mode

    def state_dict(self, *a, **k):
        return self._model.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._model.set_state_dict(*a, **k)

    def __call__(self, *args):
        if self._mode == "train":
            if self._step is None:
                raise ValueError(
                    "DistModel train mode needs loss_fn and optimizer "
                    "(dist.to_static(model, loss_fn, optimizer))")
            return self._step(*args)
        if self._mode == "eval":
            if self._loss_fn is None:
                raise ValueError("DistModel eval mode needs a loss_fn")
            from ..autograd import tape as _tape

            with _tape.no_grad():
                return self._loss_fn(self._model, *args)
        from ..autograd import tape as _tape

        with _tape.no_grad():
            return self._model(*args)


def to_static(model, loader=None, loss_fn=None, optimizer=None,
              strategy=None):
    """auto_parallel/api.py:2798 to_static parity: returns the DistModel
    (the reference's single return). A ``loader`` is accepted for
    signature parity — shard the input pipeline separately with
    ``shard_dataloader`` (the loader itself is not rewrapped here)."""
    return DistModel(model, loss_fn, optimizer, strategy)
