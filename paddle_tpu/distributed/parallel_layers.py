"""Megatron-style tensor/sequence-parallel layers.

Reference parity: fleet/layers/mpu/mp_layers.py (VocabParallelEmbedding :49,
ColumnParallelLinear :336, RowParallelLinear :543, ParallelCrossEntropy :744)
and fleet/utils/sequence_parallel_utils.py (Column/RowSequenceParallelLinear
:429,564).

TPU-native: instead of manual collectives, each layer (a) creates its weight
pre-sharded on the mp axis of the hybrid mesh and (b) constrains its
activations' shardings. GSPMD then inserts exactly the Megatron
communication pattern: column-parallel = no comm fwd / allreduce bwd,
row-parallel = allreduce fwd, sequence-parallel boundaries = allgather /
reduce_scatter — this is the whole point of the architecture mapping
(SURVEY.md §7: "HybridCommunicateGroup → one Mesh with named axes").
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer import Layer
from ..nn.initializer_core import XavierNormal, Constant
from ..ops.registry import apply
from ..tensor_class import Tensor, unwrap, wrap
from .process_mesh import ProcessMesh
from .placements import Shard, Replicate
from .api import shard_tensor
from .topology import get_hybrid_communicate_group


def _mp_mesh():
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return None
    return hcg.mesh


def _constraint(arr, mesh: ProcessMesh, spec: PartitionSpec):
    """Sharding constraint that is a no-op outside traces."""
    try:
        if not jax.core.trace_state_clean():
            return jax.lax.with_sharding_constraint(arr, NamedSharding(mesh.jax_mesh(), spec))
    except Exception:  # pragma: no cover  # pdlint: disable=silent-exception -- trace-state probe: outside a trace the constraint is a deliberate no-op, and this sits on the per-layer forward path
        pass
    return arr


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierNormal())
        mesh = _mp_mesh()
        if mesh is not None and num_embeddings % mesh.get_dim_size("mp") == 0:
            placements = [Replicate()] * mesh.ndim
            placements[mesh.dim_names.index("mp")] = Shard(0)
            self.weight = shard_tensor(self.weight, mesh, placements)

    def forward(self, x):
        from ..nn.functional.common import embedding

        return embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """Linear with the OUT dim sharded over mp (mp_layers.py:336). Weight
    layout [in, out] (paddle convention); gather_output re-replicates."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierNormal())
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        self._mesh = _mp_mesh()
        if self._mesh is not None:
            mp_dim = self._mesh.dim_names.index("mp")
            wp = [Replicate()] * self._mesh.ndim
            wp[mp_dim] = Shard(1)
            self.weight = shard_tensor(self.weight, self._mesh, wp)
            if self.bias is not None:
                bp = [Replicate()] * self._mesh.ndim
                bp[mp_dim] = Shard(0)
                self.bias = shard_tensor(self.bias, self._mesh, bp)

    def forward(self, x):
        mesh = self._mesh

        def fn(a, w, *b):
            out = a @ w
            if b:
                out = out + b[0]
            if mesh is not None:
                spec = PartitionSpec(*([None] * (out.ndim - 1)), "mp")
                out = _constraint(out, mesh, spec)
                if self.gather_output:
                    out = _constraint(out, mesh, PartitionSpec(*([None] * out.ndim)))
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply("column_parallel_linear", fn, *args)


class RowParallelLinear(Layer):
    """Linear with the IN dim sharded over mp (mp_layers.py:543): local matmul
    over the input shard, then (GSPMD-inserted) allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr,
                                            default_initializer=XavierNormal())
        self.bias = self.create_parameter([out_features], is_bias=True) if has_bias else None
        self._mesh = _mp_mesh()
        if self._mesh is not None:
            mp_dim = self._mesh.dim_names.index("mp")
            wp = [Replicate()] * self._mesh.ndim
            wp[mp_dim] = Shard(0)
            self.weight = shard_tensor(self.weight, self._mesh, wp)

    def forward(self, x):
        mesh = self._mesh

        def fn(a, w, *b):
            if mesh is not None:
                in_spec = PartitionSpec(*([None] * (a.ndim - 1)), "mp")
                a = _constraint(a, mesh, in_spec)
            out = a @ w
            if mesh is not None:
                out = _constraint(out, mesh, PartitionSpec(*([None] * out.ndim)))
            if b:
                out = out + b[0]
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply("row_parallel_linear", fn, *args)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Megatron-SP column linear (sequence_parallel_utils.py:429): input
    arrives sequence-sharded on mp; an allgather precedes the matmul.
    Expressed as sharding constraints: in [B, S/mp, H] → gather → matmul →
    out [B, S, H/mp]."""

    def forward(self, x):
        mesh = self._mesh

        def fn(a, w, *b):
            if mesh is not None:
                # sequence-sharded input → gather to full sequence
                seq_spec = PartitionSpec(None, "mp", *([None] * (a.ndim - 2)))
                a = _constraint(a, mesh, seq_spec)
                a = _constraint(a, mesh, PartitionSpec(*([None] * a.ndim)))
            out = a @ w
            if b:
                out = out + b[0]
            if mesh is not None:
                out = _constraint(out, mesh, PartitionSpec(*([None] * (out.ndim - 1)), "mp"))
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply("column_seq_parallel_linear", fn, *args)


class RowSequenceParallelLinear(RowParallelLinear):
    """Megatron-SP row linear (sequence_parallel_utils.py:564): output leaves
    sequence-sharded (reduce_scatter instead of allreduce)."""

    def forward(self, x):
        mesh = self._mesh

        def fn(a, w, *b):
            if mesh is not None:
                a = _constraint(a, mesh, PartitionSpec(*([None] * (a.ndim - 1)), "mp"))
            out = a @ w
            if mesh is not None:
                # reduce_scatter onto the sequence dim
                out = _constraint(out, mesh, PartitionSpec(None, "mp", *([None] * (out.ndim - 2))))
            if b:
                out = out + b[0]
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply("row_seq_parallel_linear", fn, *args)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (mp_layers.py:744 wrapping
    c_softmax_with_cross_entropy): logits arrive vocab-sharded; under GSPMD
    the standard CE graph compiles to the same partial-softmax + allreduce
    pattern, so the implementation is the plain loss with a constraint."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self._mesh = _mp_mesh()

    def forward(self, input, label):
        from ..nn.functional.loss import cross_entropy

        mesh = self._mesh
        if mesh is not None:
            def fn(a):
                return _constraint(a, mesh, PartitionSpec(*([None] * (a.ndim - 1)), "mp"))

            input = apply("vocab_shard_constraint", fn, input)
        return cross_entropy(input, label, reduction="none", ignore_index=self.ignore_index)


# eager helpers kept for API parity with sequence_parallel_utils.py

def mark_as_sequence_parallel_parameter(parameter):
    parameter._sequence_parallel = True  # consumed by grad-sync hooks


class GatherOp:
    """PyLayer-parity namespace: functional gather over the sep/mp axis."""

    @staticmethod
    def apply(x, axis=1):
        attr = getattr(x, "_dist_attr", None)
        if attr is None:
            return x
        from .api import reshard

        new_p = [Replicate() if isinstance(p, Shard) and p.dim == axis else p
                 for p in attr.placements]
        return reshard(x, attr.mesh, new_p)


class ScatterOp:
    @staticmethod
    def apply(x, axis=1):
        hcg = get_hybrid_communicate_group()
        if hcg is None:
            return x
        mesh = hcg.mesh
        placements = [Replicate()] * mesh.ndim
        placements[mesh.dim_names.index("mp")] = Shard(axis)
        from .api import reshard

        return reshard(x, mesh, placements)


AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp
