"""TCPStore: rank rendezvous over the native C++ daemon.

Reference parity: paddle/phi/core/distributed/store/tcp_store.h:121
(TCPStore(host, port, is_master, world_size, timeout) with
set/get/add/wait) — the daemon and client are C++ (core/csrc/tcp_store.cpp)
bound via ctypes; this class is the Python surface, used by
init_parallel_env/launch for bootstrap barriers.
"""
from __future__ import annotations

import ctypes
import os
import time
from typing import List, Optional

from ..core import load_native


class TCPStore:
    def __init__(self, host: str = "127.0.0.1", port: int = 6170,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 900.0):
        self._lib = load_native()
        self._server = None
        self.host, self.is_master, self.world_size = host, is_master, world_size
        self.timeout = timeout
        if is_master:
            self._server = self._lib.pd_store_server_start(port)
            if not self._server:
                raise RuntimeError(f"TCPStore master failed to bind port {port}")
            port = self._lib.pd_store_server_port(self._server)
        self.port = port
        self._client = self._lib.pd_store_client_connect(
            host.encode(), port, timeout)
        if not self._client:
            if self._server:
                self._lib.pd_store_server_stop(self._server)
            raise RuntimeError(f"TCPStore could not reach {host}:{port} "
                               f"within {timeout}s")

    # -- kv ops ---------------------------------------------------------------
    def set(self, key: str, value) -> None:
        v = value if isinstance(value, (bytes, bytearray)) else str(value).encode()
        k = key.encode()
        rc = self._lib.pd_store_client_set(self._client, k, len(k), bytes(v),
                                           len(v))
        if rc != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        k = key.encode()
        total = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + total
        # the wait is sliced into short native calls so Python-level signal
        # handlers (save-on-signal checkpointing, Ctrl-C) run between ctypes
        # calls — one blocking native get would pin the interpreter for the
        # full timeout, and a SIGTERMed worker would be SIGKILLed unsaved
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
            out = ctypes.POINTER(ctypes.c_char)()
            out_len = ctypes.c_uint32()
            rc = self._lib.pd_store_client_get(
                self._client, k, len(k), ctypes.byref(out),
                ctypes.byref(out_len), min(0.5, remain))
            if rc == 1:
                continue  # slice elapsed without the key; re-check deadline
            if rc != 0:
                raise RuntimeError(f"TCPStore.get({key!r}) failed")
            data = ctypes.string_at(out, out_len.value)
            self._lib.pd_store_free(out)
            return data

    def add(self, key: str, amount: int = 1) -> int:
        k = key.encode()
        v = self._lib.pd_store_client_add(self._client, k, len(k), amount)
        if v == -(2 ** 63):
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return int(v)

    def wait(self, keys: List[str], timeout: Optional[float] = None) -> None:
        for key in ([keys] if isinstance(keys, str) else keys):
            self.get(key, timeout)

    def delete_key(self, key: str) -> None:
        k = key.encode()
        self._lib.pd_store_client_del(self._client, k, len(k))

    # -- rendezvous helper ----------------------------------------------------
    def barrier(self, name: str = "barrier", timeout: Optional[float] = None):
        """All world_size participants block until everyone arrived."""
        n = self.add(f"__{name}__count", 1)
        gen = (n - 1) // self.world_size  # reusable barrier generations
        if n % self.world_size == 0:
            self.set(f"__{name}__release_{gen}", b"1")
        self.get(f"__{name}__release_{gen}", timeout)

    def close(self):
        """Idempotent shutdown of the client connection and (if master) the
        daemon — callers that outlive many stores (elastic restart loop)
        must not rely on GC timing to release the port."""
        try:
            if getattr(self, "_client", None):
                self._lib.pd_store_client_close(self._client)
                self._client = None
            if getattr(self, "_server", None):
                self._lib.pd_store_server_stop(self._server)
                self._server = None
        except Exception as e:
            # a close that didn't close leaks the port — the elastic
            # restart loop then fails to rebind with a confusing EADDRINUSE
            # far from the cause; one warning line points back here
            from .log_utils import get_logger

            get_logger().warning("TCPStore.close failed (%s: %s); the "
                                 "daemon port may stay bound",
                                 type(e).__name__, e)

    def __del__(self):
        try:
            self.close()
        except Exception:  # pdlint: disable=silent-exception -- interpreter teardown: logging/ctypes may already be gone
            pass
