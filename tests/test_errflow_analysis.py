"""errflow: the interprocedural exception-flow analysis
(paddle_tpu/analysis/errflow) behind ``pdlint --errors``.

1. **Lattice fixtures** — control/fault/fatal/generic classification,
   project-hierarchy catch semantics, broad-handler detection.
2. **Engine fixtures** — handler subtraction, narrow-then-re-raise
   transparency (bare ``raise`` and ``raise e``), ``finally``
   raise-copy keeping both the pending and the masking type, SCC
   (mutual recursion) convergence.
3. **Rule fixtures** — both sides of every rule: a thread root that can
   die vs one guarded at the root; control-swallow (fires even when
   logged) vs fault-swallow-with-triage (clean); a retry loop that
   re-dispatches after a non-retryable error vs one that answers and
   returns; taxonomy drift in every direction over the pure
   ``compare_taxonomy`` core.
4. **Pinned repo summaries** — the escape sets of known serving
   functions, so a refactor that changes what can escape
   ``RouterServer._post_json`` shows up here, not in production.
5. **The tier-1 gate** — ``scripts/pdlint.py --json --errors`` exits 0
   with an EMPTY baseline, and ``unused-disable`` treats the
   ``error-*`` family per-family (a staged pragma is exempt on default
   runs, flagged once ``--errors`` actually runs the rule).
"""
import importlib.util
import json
import os

from paddle_tpu import analysis
from paddle_tpu.analysis.errflow import taxonomy as tax
from paddle_tpu.analysis.errflow.lattice import (ErrorLattice,
                                                 GENERIC_TOKEN,
                                                 handler_spec)
from paddle_tpu.analysis.errflow.rules import (http_contract_findings,
                                               retry_unsafe_findings,
                                               scope_roots,
                                               swallow_findings,
                                               thread_escape_findings)
from paddle_tpu.analysis.errflow.summaries import ErrorFlow, get_flow
from paddle_tpu.analysis.threads.model import ProjectModel, get_model

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = "fix.py"          # outside paddle_tpu/ -> always in scope


def _model(src, path=_FIX):
    return ProjectModel({path: src})


def _flow(m):
    flow = ErrorFlow(m)
    flow.analyze(sorted(m.functions))
    return flow


def _load_script(name):
    path = os.path.join(_REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location("pdlint_err", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# the lattice
# ---------------------------------------------------------------------------

_HIER_SRC = (
    "class _Hop(Exception):\n"
    "    pass\n"
    "class Corrupt(RuntimeError):\n"
    "    pass\n"
)


def test_lattice_classification():
    lat = ErrorLattice(_model(_HIER_SRC))
    assert lat.classify("_Hop") == "control"
    assert lat.classify("Corrupt") == "fault"
    assert lat.classify("KeyboardInterrupt") == "fatal"
    assert lat.classify("MemoryError") == "fatal"
    # builtins are generic: no project contract attaches to ValueError
    assert lat.classify("ValueError") == "generic"
    assert lat.classify(GENERIC_TOKEN) == "generic"


def test_lattice_catch_semantics():
    lat = ErrorLattice(_model(_HIER_SRC))
    # project class caught through its base chain into the builtin tree
    assert lat.caught_by("Corrupt", ["RuntimeError"])
    assert lat.caught_by("Corrupt", ["Exception"])
    assert not lat.caught_by("Corrupt", ["ValueError"])
    # builtin hierarchy: except OSError stops ConnectionResetError
    assert lat.caught_by("ConnectionResetError", ["OSError"])
    # the unknown-external token is stopped ONLY by broad handlers
    assert not lat.caught_by(GENERIC_TOKEN, ["ValueError"])
    assert lat.caught_by(GENERIC_TOKEN, [], broad=True)


def test_handler_spec_broad_detection():
    import ast

    def spec(src):
        handler = ast.parse(src).body[0].handlers[0]
        return handler_spec(handler.type, None)

    assert spec("try:\n a\nexcept Exception:\n b\n") == (["Exception"],
                                                         True)
    assert spec("try:\n a\nexcept:\n b\n") == ([], True)
    assert spec("try:\n a\nexcept OSError as e:\n b\n") == (["OSError"],
                                                            False)
    names, broad = spec("try:\n a\nexcept (ValueError, Exception):\n b\n")
    assert broad and "ValueError" in names


# ---------------------------------------------------------------------------
# the summaries engine
# ---------------------------------------------------------------------------

def test_handler_subtraction_interprocedural():
    """A callee's typed raise is subtracted by a caller's matching
    handler (through the base chain) and escapes a non-matching one."""
    m = _model(_HIER_SRC + (
        "def boom():\n"
        "    raise Corrupt('bad')\n"
        "def stopped():\n"
        "    try:\n"
        "        return boom()\n"
        "    except RuntimeError:\n"
        "        return None\n"
        "def missed():\n"
        "    try:\n"
        "        return boom()\n"
        "    except ValueError:\n"
        "        return None\n"
    ))
    flow = _flow(m)
    assert "Corrupt" in flow.escapes_of((_FIX, "boom"))
    assert "Corrupt" not in flow.escapes_of((_FIX, "stopped"))
    esc = flow.escapes_of((_FIX, "missed"))
    assert esc["Corrupt"] == (_FIX, 6)       # provenance: the raise site


def test_narrow_reraise_is_transparent():
    """``except _Hop: ... raise`` and ``except _Hop as e: raise e`` both
    re-emit the arrival set — the handler is observability, not a
    swallow, and the type keeps flowing to the real catcher."""
    m = _model(_HIER_SRC + (
        "def src():\n"
        "    raise _Hop()\n"
        "def relay_bare():\n"
        "    try:\n"
        "        return src()\n"
        "    except _Hop:\n"
        "        raise\n"
        "def relay_bound():\n"
        "    try:\n"
        "        return src()\n"
        "    except _Hop as e:\n"
        "        raise e\n"
    ))
    flow = _flow(m)
    assert "_Hop" in flow.escapes_of((_FIX, "relay_bare"))
    assert "_Hop" in flow.escapes_of((_FIX, "relay_bound"))


def test_finally_keeps_pending_and_masking_types():
    """A raising ``finally`` masks the in-flight exception at runtime;
    the engine deliberately over-approximates and keeps BOTH — losing
    the pending type would hide the original contract."""
    m = _model(_HIER_SRC + (
        "def masked():\n"
        "    try:\n"
        "        raise Corrupt()\n"
        "    finally:\n"
        "        raise _Hop()\n"
    ))
    esc = _flow(m).escapes_of((_FIX, "masked"))
    assert "Corrupt" in esc and "_Hop" in esc


def test_scc_mutual_recursion_converges():
    m = _model(_HIER_SRC + (
        "def a(n):\n"
        "    if n:\n"
        "        return b(n - 1)\n"
        "    raise Corrupt()\n"
        "def b(n):\n"
        "    return a(n)\n"
    ))
    flow = _flow(m)
    assert "Corrupt" in flow.escapes_of((_FIX, "a"))
    assert "Corrupt" in flow.escapes_of((_FIX, "b"))


# ---------------------------------------------------------------------------
# error-thread-escape
# ---------------------------------------------------------------------------

_SPAWN = (
    "import threading\n"
    "class Corrupt(RuntimeError):\n"
    "    pass\n"
    "class Daemon:\n"
    "    def start(self):\n"
    "        self._stop = threading.Event()\n"
    "        self._t = threading.Thread(target=self._loop,\n"
    "                                   name='d-loop', daemon=True)\n"
    "        self._t.start()\n"
    "    def _work(self):\n"
    "        raise Corrupt('bad frame')\n"
)


def test_thread_escape_fires_on_typed_escape():
    m = _model(_SPAWN + (
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            self._work()\n"
    ))
    (f,) = thread_escape_findings(m, _flow(m))
    assert f.rule == "error-thread-escape"
    assert "Corrupt" in f.message and "d-loop" in f.message
    assert "Corrupt" in f.data["escapes"]


def test_thread_escape_guarded_root_is_clean():
    m = _model(_SPAWN + (
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            try:\n"
        "                self._work()\n"
        "            except Exception as e:\n"
        "                self._last = e\n"
    ))
    assert thread_escape_findings(m, _flow(m)) == []


def test_thread_escape_generic_only_still_fires():
    """No typed escape, but an unresolvable external call means at
    least one path has no guard at all — the root can still die."""
    m = _model(_SPAWN + (
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            self.sock.recv(4096)\n"
    ))
    (f,) = thread_escape_findings(m, _flow(m))
    assert "any uncaught exception" in f.message


def test_thread_escape_fatal_exempt_and_pragma():
    fatal = _model(_SPAWN + (
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            raise SystemExit(0)\n"
    ))
    assert thread_escape_findings(fatal, _flow(fatal)) == []
    pragma = _model(_SPAWN.replace(
        "target=self._loop,",
        "target=self._loop,  # pdlint: disable=error-thread-escape"
    ) + (
        "    def _loop(self):\n"
        "        while not self._stop.is_set():\n"
        "            self._work()\n"
    ))
    assert thread_escape_findings(pragma, _flow(pragma)) == []


# ---------------------------------------------------------------------------
# error-swallow
# ---------------------------------------------------------------------------

_SWALLOW_HDR = _HIER_SRC + (
    "def hop():\n"
    "    raise _Hop()\n"
    "def fault():\n"
    "    raise Corrupt()\n"
)


def test_swallow_control_fires_even_when_logged():
    m = _model(_SWALLOW_HDR + (
        "def caller():\n"
        "    try:\n"
        "        return hop()\n"
        "    except Exception as e:\n"
        "        print(e)\n"
    ))
    (f,) = swallow_findings(m, _flow(m))
    assert "control-flow" in f.message and "_Hop" in f.message
    assert "_Hop" in f.data["swallowed"]


def test_swallow_silent_fault_fires_triaged_fault_clean():
    silent = _model(_SWALLOW_HDR + (
        "def caller():\n"
        "    try:\n"
        "        return fault()\n"
        "    except Exception:\n"
        "        return None\n"
    ))
    (f,) = swallow_findings(silent, _flow(silent))
    assert "Corrupt" in f.message
    triaged = _model(_SWALLOW_HDR + (
        "def caller():\n"
        "    try:\n"
        "        return fault()\n"
        "    except Exception as e:\n"
        "        return {'error': str(e)}\n"
    ))
    assert swallow_findings(triaged, _flow(triaged)) == []


def test_swallow_reraise_and_narrow_exempt():
    reraise = _model(_SWALLOW_HDR + (
        "def caller():\n"
        "    try:\n"
        "        return hop()\n"
        "    except Exception:\n"
        "        raise\n"
    ))
    assert swallow_findings(reraise, _flow(reraise)) == []
    narrow = _model(_SWALLOW_HDR + (
        "def caller():\n"
        "    try:\n"
        "        return hop()\n"
        "    except ValueError:\n"
        "        return None\n"
    ))
    assert swallow_findings(narrow, _flow(narrow)) == []


# ---------------------------------------------------------------------------
# error-retry-unsafe
# ---------------------------------------------------------------------------

_RETRY_HDR = (
    "class _DeadlineExpired(Exception):\n"
    "    pass\n"
    "class _UpstreamError(Exception):\n"
    "    pass\n"
    "def dispatch(w):\n"
    "    raise _DeadlineExpired()\n"
)


def test_retry_unsafe_fires_on_nonretryable_rejoin():
    m = _model(_RETRY_HDR + (
        "def failover(workers):\n"
        "    for w in workers:\n"
        "        try:\n"
        "            return dispatch(w)\n"
        "        except _DeadlineExpired:\n"
        "            continue\n"
    ))
    (f,) = retry_unsafe_findings(m, _flow(m))
    assert f.rule == "error-retry-unsafe"
    assert "_DeadlineExpired" in f.message
    assert "_DeadlineExpired" in f.data["non_retryable"]


def test_retry_unsafe_broad_handler_caught_by_arrival():
    """Even an untyped ``except Exception: continue`` is unsafe when
    the ARRIVAL set (per the summaries) carries a non-retryable type."""
    m = _model(_RETRY_HDR + (
        "def failover(workers):\n"
        "    for w in workers:\n"
        "        try:\n"
        "            return dispatch(w)\n"
        "        except Exception:\n"
        "            continue\n"
    ))
    (f,) = retry_unsafe_findings(m, _flow(m))
    assert "_DeadlineExpired" in f.data["non_retryable"]


def test_retry_honoring_catalog_is_clean():
    """Answering the client on the non-retryable type (return) while
    failing over only on the retryable one is the documented shape."""
    m = _model(_RETRY_HDR + (
        "def failover(workers):\n"
        "    for w in workers:\n"
        "        try:\n"
        "            return dispatch(w)\n"
        "        except _DeadlineExpired:\n"
        "            return None\n"
        "        except _UpstreamError:\n"
        "            continue\n"
    ))
    assert retry_unsafe_findings(m, _flow(m)) == []


# ---------------------------------------------------------------------------
# error-http-contract: the pure comparison core
# ---------------------------------------------------------------------------

def _perfect_world():
    docs = {e.cls: (e.status_doc, e.code, e.retryable)
            for e in tax.TAXONOMY}
    known = {e.cls for e in tax.TAXONOMY if not e.is_pseudo}
    codes = {e.code for e in tax.TAXONOMY if e.code}
    statuses = {e.status for e in tax.TAXONOMY if e.status is not None}
    return docs, known, codes, statuses


def test_taxonomy_in_agreement_is_clean():
    docs, known, codes, statuses = _perfect_world()
    assert tax.compare_taxonomy(docs, tax.TAXONOMY, known, codes,
                                statuses) == []


def test_taxonomy_drift_fires_in_every_direction():
    docs, known, codes, statuses = _perfect_world()
    # a taxonomy entry with no docs row
    short = dict(docs)
    del short["QueueFull"]
    msgs = tax.compare_taxonomy(short, tax.TAXONOMY, known, codes,
                                statuses)
    assert any("QueueFull" in m and "no row" in m for m in msgs)
    # a docs row with no taxonomy entry
    extra = dict(docs, GhostError=("500", "", True))
    msgs = tax.compare_taxonomy(extra, tax.TAXONOMY, known, codes,
                                statuses)
    assert any("GhostError" in m and "not in the taxonomy" in m
               for m in msgs)
    # per-cell drift: the docs call a terminal error retryable
    flipped = dict(docs, _DeadlineExpired=("504", "deadline_exceeded",
                                           True))
    msgs = tax.compare_taxonomy(flipped, tax.TAXONOMY, known, codes,
                                statuses)
    assert any("contract drift for _DeadlineExpired" in m for m in msgs)
    # a taxonomy class that does not exist in the project
    msgs = tax.compare_taxonomy(docs, tax.TAXONOMY,
                                known - {"XlaOom"}, codes, statuses)
    assert any("XlaOom" in m and "no such class" in m for m in msgs)
    # a documented code= the serving tier never emits
    msgs = tax.compare_taxonomy(docs, tax.TAXONOMY, known,
                                codes - {"request_quarantined"},
                                statuses)
    assert any("request_quarantined" in m and "never emitted" in m
               for m in msgs)
    # an emitted code= the taxonomy does not document
    msgs = tax.compare_taxonomy(docs, tax.TAXONOMY, known,
                                codes | {"mystery_mode"}, statuses)
    assert any("mystery_mode" in m and "no entry" in m for m in msgs)


def test_documented_taxonomy_roundtrips_the_repo_docs():
    """docs/SERVING.md 'Error taxonomy' parses back to exactly the
    registry — the live half of the two-direction lint."""
    docs = tax.documented_taxonomy(
        os.path.join(_REPO, "docs", "SERVING.md"))
    assert docs == {e.cls: (e.status_doc, e.code, e.retryable)
                    for e in tax.TAXONOMY}


# ---------------------------------------------------------------------------
# pinned repo summaries
# ---------------------------------------------------------------------------

def test_pinned_serving_escape_summaries():
    """What can escape the load-bearing serving functions, pinned. A
    refactor that adds or removes an escaping type must update this
    test AND the docs taxonomy it implements."""
    m = get_model(_REPO)
    flow = get_flow(m)
    flow.analyze(scope_roots(m))

    def typed(file, qual):
        return set(flow.typed(flow.escapes_of((file, qual))))

    assert typed("paddle_tpu/serving.py",
                 "ContinuousBatchEngine._check_queue_bound") == {
        "QueueFull"}
    assert typed("paddle_tpu/serving.py", "verify_bundle") == {
        "HandoffCorrupt"}
    assert typed("paddle_tpu/serving_cluster/router.py",
                 "RouterServer._post_json") == {
        "_ClientError", "_DeadlineExpired", "_UpstreamError",
        "_WorkerBusy"}
    # the relay adds the mid-stream control hops
    proxy = typed("paddle_tpu/serving_cluster/router.py",
                  "RouterServer._proxy_stream")
    assert {"_Migrated", "_ClientGone"} <= proxy
    # the real lattice classifies the real types
    assert flow.lattice.classify("_Migrated") == "control"
    assert flow.lattice.classify("QueueFull") == "fault"
    # and the repo itself is clean under the typed rules
    assert thread_escape_findings(m, flow) == []
    assert swallow_findings(m, flow) == []
    assert retry_unsafe_findings(m, flow) == []
    assert http_contract_findings(m, _REPO) == []


# ---------------------------------------------------------------------------
# the tier-1 gate + per-family pragma hygiene
# ---------------------------------------------------------------------------

def test_pdlint_errors_gate_empty_baseline(capsys):
    """``--errors`` exits 0 against an EMPTY baseline: every real
    finding this analysis ever produced was FIXED, not baselined."""
    mod = _load_script("pdlint.py")
    rc = mod.main(["--json", "--errors"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0, f"pdlint --errors found new findings:\n{out}"
    assert doc["total"] == 0
    assert doc["baselined"] == 0
    for rid in ("error-thread-escape", "error-http-contract",
                "error-swallow", "error-retry-unsafe"):
        assert rid in doc["rules"]


def test_unused_disable_is_per_family(tmp_path):
    """A staged ``disable=error-swallow`` pragma is exempt on a default
    run (the family did not run) and flagged as unused-disable the
    moment ``--errors`` runs the rule and it suppresses nothing."""
    f = tmp_path / "fix.py"
    f.write_text(
        "def handle(req):\n"
        "    try:\n"
        "        return req.parse()\n"
        "    except Exception:  # pdlint: disable=error-swallow -- staged\n"
        "        return None\n")

    def unused(findings):
        return [fd for fd in findings if fd.rule == "unused-disable"
                and "error-swallow" in fd.message]

    plain = analysis.run(paths=[str(f)], root=str(tmp_path),
                         with_project_rules=False)
    assert unused(plain) == []
    full = analysis.run(paths=[str(f)], root=str(tmp_path),
                        selected=["unused-disable", "error-swallow"])
    assert len(unused(full)) == 1


# ---------------------------------------------------------------------------
# fused-coverage (satellite): the structural sweep itself
# ---------------------------------------------------------------------------

def test_fused_coverage_structural_split():
    """llama's decoder layer passes the structural fused-decode gate;
    qwen2 (qkv bias) correctly does not — the two sides the floor
    pins."""
    from paddle_tpu.analysis.rules.fused_coverage import (
        FUSED_FLOOR, structural_coverage)
    cov = structural_coverage()
    assert cov["llama"] is True and cov["qwen2"] is False
    assert "llama" in FUSED_FLOOR and "qwen2" not in FUSED_FLOOR
