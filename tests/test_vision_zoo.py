"""Round-3 vision breadth: model zoo part 2 + the detection op suite
(numeric identities: deform_conv≡conv at zero offsets, box_coder
round-trip, NMS suppression behavior, prior_box coverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision.models as M
import paddle_tpu.vision.ops as V


def test_zoo2_forward_shapes():
    paddle.seed(0)
    x = paddle.randn([1, 3, 64, 64])
    for fn in (M.mobilenet_v3_small, M.squeezenet1_1,
               M.shufflenet_v2_x0_25, M.densenet121):
        m = fn(num_classes=7)
        m.eval()
        assert m(x).shape == [1, 7], fn.__name__
    g = M.googlenet(num_classes=5)
    g.eval()
    main, aux1, aux2 = g(paddle.randn([1, 3, 96, 96]))
    assert main.shape == [1, 5] and aux1.shape == [1, 5]


def test_zoo2_state_dict_roundtrip():
    m = M.mobilenet_v3_small(num_classes=4)
    sd = m.state_dict()
    m2 = M.mobilenet_v3_small(num_classes=4)
    m2.set_state_dict(sd)
    m.eval(); m2.eval()
    x = paddle.randn([1, 3, 32, 32])
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-5)


def test_box_coder_roundtrip():
    priors = paddle.to_tensor(
        np.array([[0., 0., 10., 10.], [5., 5., 15., 15.]], "float32"))
    pvar = paddle.to_tensor(np.full((2, 4), 1.0, "float32"))
    targets = paddle.to_tensor(
        np.array([[1., 1., 9., 9.], [4., 6., 16., 14.]], "float32"))
    enc = V.box_coder(priors, pvar, targets, "encode_center_size")
    deltas = enc.numpy()[np.arange(2), np.arange(2)][None]
    dec = V.box_coder(priors, pvar, paddle.to_tensor(deltas),
                      "decode_center_size", axis=0)
    np.testing.assert_allclose(dec.numpy()[0], targets.numpy(), atol=1e-4)


def test_deform_conv_zero_offset_equals_conv():
    paddle.seed(1)
    x = paddle.randn([1, 4, 8, 8])
    w = paddle.randn([6, 4, 3, 3])
    off = paddle.zeros([1, 18, 8, 8])
    got = V.deform_conv2d(x, off, w, padding=1)
    import paddle_tpu.nn.functional as F

    want = F.conv2d(x, w, padding=1)
    np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-3)
    # nonzero offsets change the answer
    off2 = paddle.full([1, 18, 8, 8], 0.7)
    assert not np.allclose(V.deform_conv2d(x, off2, w, padding=1).numpy(),
                           want.numpy(), atol=1e-3)


def test_matrix_nms_suppresses_overlaps():
    bx = paddle.to_tensor(np.array(
        [[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5], [50, 50, 60, 60]]],
        "float32"))
    sc = paddle.to_tensor(np.array([[[0.9, 0.8, 0.7]]], "float32"))
    out, num = V.matrix_nms(bx, sc, score_threshold=0.1, post_threshold=0.5,
                            background_label=-1)
    kept = out.numpy()
    # the near-duplicate gets decayed below post_threshold; 2 boxes survive
    assert kept.shape[0] == 2
    assert {round(float(s), 1) for s in kept[:, 1]} == {0.9, 0.7}


def test_yolo_box_and_loss():
    paddle.seed(0)
    x = paddle.randn([1, 3 * 7, 4, 4])
    boxes, scores = V.yolo_box(x, paddle.to_tensor(np.array([[128, 128]])),
                               [10, 13, 16, 30, 33, 23], 2)
    assert boxes.shape == [1, 48, 4] and scores.shape == [1, 48, 2]
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 127).all()  # clipped to image
    gt = paddle.to_tensor(np.array([[[0.5, 0.5, 0.3, 0.3]]], "float32"))
    gl = paddle.to_tensor(np.array([[1]]))
    loss = V.yolo_loss(x, gt, gl, [10, 13, 16, 30, 33, 23], [0, 1, 2], 2,
                       0.7, 32)
    assert loss.shape == [1] and float(loss.numpy()[0]) > 0


def test_prior_box_and_fpn_distribute():
    pb, var = V.prior_box(paddle.randn([1, 8, 2, 2]),
                          paddle.randn([1, 3, 16, 16]),
                          min_sizes=[4.0], aspect_ratios=[1.0, 2.0],
                          flip=True, clip=True)
    assert pb.shape == [2, 2, 3, 4] and var.shape == [2, 2, 3, 4]
    arr = pb.numpy()
    assert (arr >= 0).all() and (arr <= 1).all()
    rois = paddle.to_tensor(
        np.array([[0, 0, 10, 10], [0, 0, 200, 200]], "float32"))
    outs, restore, nums = V.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    assert len(outs) == 4
    sizes = [o.shape[0] for o in outs]
    assert sum(sizes) == 2 and sizes[0] == 1  # small roi → lowest level
    assert sorted(restore.numpy().tolist()) == [0, 1]


def test_generate_proposals_and_psroi():
    np.random.seed(0)
    anchors = np.zeros((2, 2, 3, 4), "float32")
    for i in range(2):
        for j in range(2):
            anchors[i, j] = [[j * 16, i * 16, j * 16 + 32, i * 16 + 32]] * 3
    rois, rscores = V.generate_proposals(
        paddle.to_tensor(np.random.rand(1, 3, 2, 2).astype("float32")),
        paddle.to_tensor(np.random.randn(1, 12, 2, 2).astype("float32") * 0.1),
        paddle.to_tensor(np.array([[64.0, 64.0]], "float32")),
        paddle.to_tensor(anchors.reshape(-1, 4)),
        paddle.to_tensor(np.full((12, 4), 1.0, "float32")),
        nms_thresh=0.9)
    assert rois.shape[1] == 4 and rois.shape[0] == rscores.shape[0] > 0
    ps = V.psroi_pool(
        paddle.randn([1, 8, 16, 16]),
        paddle.to_tensor(np.array([[0., 0., 8., 8.]], "float32")),
        paddle.to_tensor(np.array([1])), 2)
    assert ps.shape == [1, 2, 2, 2]


def test_image_io_roundtrip(tmp_path):
    from PIL import Image

    # smooth gradient — JPEG preserves low-frequency content
    gy, gx = np.mgrid[0:10, 0:12]
    arr = np.stack([gy * 20, gx * 20, gy * 10 + gx * 10], -1).astype("uint8")
    p = tmp_path / "t.jpg"
    Image.fromarray(arr).save(p, quality=95)
    raw = V.read_file(str(p))
    assert raw.dtype == paddle.uint8
    img = V.decode_jpeg(raw)
    assert img.shape == [3, 10, 12]
    # lossy but close
    assert np.abs(img.numpy().transpose(1, 2, 0).astype(int)
                  - arr.astype(int)).mean() < 20
