"""Round-3 vision breadth: model zoo part 2 + the detection op suite
(numeric identities: deform_conv≡conv at zero offsets, box_coder
round-trip, NMS suppression behavior, prior_box coverage)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision.models as M
import paddle_tpu.vision.ops as V


def test_zoo2_forward_shapes():
    paddle.seed(0)
    x = paddle.randn([1, 3, 64, 64])
    for fn in (M.mobilenet_v3_small, M.squeezenet1_1,
               M.shufflenet_v2_x0_25, M.densenet121):
        m = fn(num_classes=7)
        m.eval()
        assert m(x).shape == [1, 7], fn.__name__
    g = M.googlenet(num_classes=5)
    g.eval()
    main, aux1, aux2 = g(paddle.randn([1, 3, 96, 96]))
    assert main.shape == [1, 5] and aux1.shape == [1, 5]


def test_zoo2_state_dict_roundtrip():
    m = M.mobilenet_v3_small(num_classes=4)
    sd = m.state_dict()
    m2 = M.mobilenet_v3_small(num_classes=4)
    m2.set_state_dict(sd)
    m.eval(); m2.eval()
    x = paddle.randn([1, 3, 32, 32])
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-5)


def test_box_coder_roundtrip():
    priors = paddle.to_tensor(
        np.array([[0., 0., 10., 10.], [5., 5., 15., 15.]], "float32"))
    pvar = paddle.to_tensor(np.full((2, 4), 1.0, "float32"))
    targets = paddle.to_tensor(
        np.array([[1., 1., 9., 9.], [4., 6., 16., 14.]], "float32"))
    enc = V.box_coder(priors, pvar, targets, "encode_center_size")
    deltas = enc.numpy()[np.arange(2), np.arange(2)][None]
    dec = V.box_coder(priors, pvar, paddle.to_tensor(deltas),
                      "decode_center_size", axis=0)
    np.testing.assert_allclose(dec.numpy()[0], targets.numpy(), atol=1e-4)


def test_deform_conv_zero_offset_equals_conv():
    paddle.seed(1)
    x = paddle.randn([1, 4, 8, 8])
    w = paddle.randn([6, 4, 3, 3])
    off = paddle.zeros([1, 18, 8, 8])
    got = V.deform_conv2d(x, off, w, padding=1)
    import paddle_tpu.nn.functional as F

    want = F.conv2d(x, w, padding=1)
    np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-3)
    # nonzero offsets change the answer
    off2 = paddle.full([1, 18, 8, 8], 0.7)
    assert not np.allclose(V.deform_conv2d(x, off2, w, padding=1).numpy(),
                           want.numpy(), atol=1e-3)


def test_matrix_nms_suppresses_overlaps():
    bx = paddle.to_tensor(np.array(
        [[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5], [50, 50, 60, 60]]],
        "float32"))
    sc = paddle.to_tensor(np.array([[[0.9, 0.8, 0.7]]], "float32"))
    out, num = V.matrix_nms(bx, sc, score_threshold=0.1, post_threshold=0.5,
                            background_label=-1)
    kept = out.numpy()
    # the near-duplicate gets decayed below post_threshold; 2 boxes survive
    assert kept.shape[0] == 2
    assert {round(float(s), 1) for s in kept[:, 1]} == {0.9, 0.7}


def test_yolo_box_and_loss():
    paddle.seed(0)
    x = paddle.randn([1, 3 * 7, 4, 4])
    boxes, scores = V.yolo_box(x, paddle.to_tensor(np.array([[128, 128]])),
                               [10, 13, 16, 30, 33, 23], 2)
    assert boxes.shape == [1, 48, 4] and scores.shape == [1, 48, 2]
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 127).all()  # clipped to image
    gt = paddle.to_tensor(np.array([[[0.5, 0.5, 0.3, 0.3]]], "float32"))
    gl = paddle.to_tensor(np.array([[1]]))
    loss = V.yolo_loss(x, gt, gl, [10, 13, 16, 30, 33, 23], [0, 1, 2], 2,
                       0.7, 32)
    assert loss.shape == [1] and float(loss.numpy()[0]) > 0


def test_prior_box_and_fpn_distribute():
    pb, var = V.prior_box(paddle.randn([1, 8, 2, 2]),
                          paddle.randn([1, 3, 16, 16]),
                          min_sizes=[4.0], aspect_ratios=[1.0, 2.0],
                          flip=True, clip=True)
    assert pb.shape == [2, 2, 3, 4] and var.shape == [2, 2, 3, 4]
    arr = pb.numpy()
    assert (arr >= 0).all() and (arr <= 1).all()
    rois = paddle.to_tensor(
        np.array([[0, 0, 10, 10], [0, 0, 200, 200]], "float32"))
    outs, restore, nums = V.distribute_fpn_proposals(rois, 2, 5, 4, 224)
    assert len(outs) == 4
    sizes = [o.shape[0] for o in outs]
    assert sum(sizes) == 2 and sizes[0] == 1  # small roi → lowest level
    assert sorted(restore.numpy().tolist()) == [0, 1]


def test_generate_proposals_and_psroi():
    np.random.seed(0)
    anchors = np.zeros((2, 2, 3, 4), "float32")
    for i in range(2):
        for j in range(2):
            anchors[i, j] = [[j * 16, i * 16, j * 16 + 32, i * 16 + 32]] * 3
    rois, rscores = V.generate_proposals(
        paddle.to_tensor(np.random.rand(1, 3, 2, 2).astype("float32")),
        paddle.to_tensor(np.random.randn(1, 12, 2, 2).astype("float32") * 0.1),
        paddle.to_tensor(np.array([[64.0, 64.0]], "float32")),
        paddle.to_tensor(anchors.reshape(-1, 4)),
        paddle.to_tensor(np.full((12, 4), 1.0, "float32")),
        nms_thresh=0.9)
    assert rois.shape[1] == 4 and rois.shape[0] == rscores.shape[0] > 0
    ps = V.psroi_pool(
        paddle.randn([1, 8, 16, 16]),
        paddle.to_tensor(np.array([[0., 0., 8., 8.]], "float32")),
        paddle.to_tensor(np.array([1])), 2)
    assert ps.shape == [1, 2, 2, 2]


def test_image_io_roundtrip(tmp_path):
    from PIL import Image

    # smooth gradient — JPEG preserves low-frequency content
    gy, gx = np.mgrid[0:10, 0:12]
    arr = np.stack([gy * 20, gx * 20, gy * 10 + gx * 10], -1).astype("uint8")
    p = tmp_path / "t.jpg"
    Image.fromarray(arr).save(p, quality=95)
    raw = V.read_file(str(p))
    assert raw.dtype == paddle.uint8
    img = V.decode_jpeg(raw)
    assert img.shape == [3, 10, 12]
    # lossy but close
    assert np.abs(img.numpy().transpose(1, 2, 0).astype(int)
                  - arr.astype(int)).mean() < 20


def _targz_of(tmp_path, name, files, mode="w:gz"):
    import tarfile

    p = tmp_path / name
    with tarfile.open(p, mode) as tf:
        for fname, data in files.items():
            full = tmp_path / "stage" / fname
            full.parent.mkdir(parents=True, exist_ok=True)
            if isinstance(data, bytes):
                full.write_bytes(data)
            else:
                full.write_text(data)
            tf.add(full, arcname=fname)
    return str(p)


def test_flowers_dataset_synthetic(tmp_path):
    """Flowers parses the reference triple (tgz + .mat labels/setid)."""
    import io

    import scipy.io as sio
    from PIL import Image

    import paddle_tpu.vision.datasets as D

    imgs = {}
    for i in (1, 2, 3):
        buf = io.BytesIO()
        Image.fromarray((np.ones((6, 6, 3)) * i * 40).astype("uint8")).save(
            buf, format="JPEG")
        imgs[f"jpg/image_{i:05d}.jpg"] = buf.getvalue()
    tgz = _targz_of(tmp_path, "102flowers.tgz", imgs)
    lbl = tmp_path / "imagelabels.mat"
    sio.savemat(lbl, {"labels": np.array([[5, 7, 9]])})
    sid = tmp_path / "setid.mat"
    sio.savemat(sid, {"trnid": np.array([[1, 3]]), "valid": np.array([[2]]),
                      "tstid": np.array([[2]])})
    ds = D.Flowers(data_file=tgz, label_file=str(lbl), setid_file=str(sid),
                   mode="train")
    assert len(ds) == 2
    img, label = ds[0]
    assert img.shape == (6, 6, 3) and int(label[0]) == 5
    img2, label2 = ds[1]
    assert int(label2[0]) == 9


def test_voc2012_dataset_synthetic(tmp_path):
    import io

    from PIL import Image

    import paddle_tpu.vision.datasets as D

    files = {}
    buf = io.BytesIO()
    Image.fromarray(np.zeros((5, 5, 3), "uint8")).save(buf, format="JPEG")
    files["VOCdevkit/VOC2012/JPEGImages/2007_000001.jpg"] = buf.getvalue()
    buf2 = io.BytesIO()
    Image.fromarray(np.ones((5, 5), "uint8")).save(buf2, format="PNG")
    files["VOCdevkit/VOC2012/SegmentationClass/2007_000001.png"] = buf2.getvalue()
    files["VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt"] = "2007_000001\n"
    tar = _targz_of(tmp_path, "voc.tar", files, mode="w")
    ds = D.VOC2012(data_file=tar, mode="train")
    assert len(ds) == 1
    img, lbl = ds[0]
    assert img.shape == (5, 5, 3) and lbl.shape == (5, 5)


def test_text_datasets_synthetic(tmp_path):
    import gzip
    import zipfile

    import paddle_tpu.text as T

    # Imikolov: PTB-style text
    txt = "the cat sat on the mat\nthe dog sat on the rug\n" * 30
    tgz = _targz_of(tmp_path, "simple-examples.tgz",
                    {"simple-examples/data/ptb.train.txt": txt,
                     "simple-examples/data/ptb.valid.txt": txt[:60]})
    ds = T.Imikolov(data_file=tgz, window_size=3, mode="train",
                    min_word_freq=5)
    assert len(ds) > 0 and ds[0].shape == (3,)
    assert "the" in ds.word_idx

    # Movielens
    mlzip = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(mlzip, "w") as zf:
        zf.writestr("ml-1m/users.dat", "1::M::25::4::12345\n2::F::35::7::6789\n")
        zf.writestr("ml-1m/movies.dat", "10::Movie A::Comedy|Drama\n")
        zf.writestr("ml-1m/ratings.dat",
                    "1::10::5::964982703\n2::10::3::964982704\n")
    ds2 = T.Movielens(data_file=str(mlzip), mode="train", test_ratio=0.0)
    assert len(ds2) == 2
    uid, gender, age, job, mid, rating = ds2[0]
    assert rating.dtype == np.float32

    # WMT16: parallel pairs
    pairs = "ein hund\ta dog\nzwei katzen\ttwo cats\n"
    wtar = _targz_of(tmp_path, "wmt16.tar.gz", {"wmt16/train": pairs,
                                                "wmt16/val": pairs})
    ds3 = T.WMT16(data_file=wtar, mode="train")
    assert len(ds3) == 2
    src, trg_in, trg_out = ds3[0]
    assert trg_in[0] == 0 and trg_out[-1] == 1  # <s> ... <e>

    # WMT14 same format under train/
    wtar2 = _targz_of(tmp_path, "wmt14.tgz", {"wmt14/train/part0": pairs})
    ds4 = T.WMT14(data_file=wtar2, mode="train")
    assert len(ds4) == 2

    # Conll05st: words + props column files, gzipped inside the tar
    words = "The\ncat\nsat\n\n"
    props = "-\t(A0*)\n-\t*\nsat\t(V*)\n\n".replace("\t", " ")
    ctar = _targz_of(tmp_path, "conll05st-tests.tar.gz", {
        "conll05st-release/test.wsj/words/test.wsj.words.gz":
            gzip.compress(words.encode()),
        "conll05st-release/test.wsj/props/test.wsj.props.gz":
            gzip.compress(props.encode()),
    })
    ds5 = T.Conll05st(data_file=ctar)
    assert len(ds5) == 1
    ids, verb, labels = ds5[0]
    assert verb == "sat" and len(labels) == 3
    assert labels[0].startswith("B-") and labels[2] == "B-V"
