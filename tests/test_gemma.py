"""Gemma family: the three signature knobs (GeGLU, (1+w) norms, scaled
embeddings), training, HF conversion + logits/greedy parity against
transformers (7B-style GQA and 2B-style MQA tiny shapes)."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gemma import (GemmaConfig, GemmaForCausalLM,
                                     gemma_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_construction_and_knobs():
    paddle.seed(0)
    cfg = GemmaConfig.tiny()
    m = GemmaForCausalLM(cfg)
    # tied head, zeros-init norm weights (identity through the (1+w) form)
    assert m.lm_head is None
    norm = m.llama.layers[0].input_layernorm
    assert norm.offset == 1.0
    np.testing.assert_array_equal(norm.weight.numpy(),
                                  np.zeros(cfg.hidden_size, np.float32))
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    loss, _ = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))
    with pytest.raises(ValueError, match="gelu_pytorch_tanh"):
        GemmaForCausalLM(dataclasses.replace(cfg, hidden_act="silu"))
    with pytest.raises(ValueError, match="rms_norm_offset"):
        GemmaForCausalLM(dataclasses.replace(cfg, rms_norm_offset=False))
    with pytest.raises(ValueError, match="sqrt"):
        GemmaForCausalLM(dataclasses.replace(cfg, scale_embeddings=False))
    with pytest.raises(NotImplementedError, match="hidden_act"):
        dataclasses.replace(cfg, hidden_act="relu")


def test_scale_embeddings_matters():
    """The sqrt(hidden) input scaling must actually change the logits."""
    paddle.seed(1)
    m = GemmaForCausalLM(GemmaConfig.tiny())
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (1, 8)))
    a = m(ids).numpy()
    m.config = dataclasses.replace(m.config, scale_embeddings=False)
    m.llama.config = m.config
    b = m(ids).numpy()
    assert not np.allclose(a, b)


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(2)
    m = GemmaForCausalLM(GemmaConfig.tiny())

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def _tiny_hf(mqa=False):
    from transformers import GemmaConfig as HFConfig
    from transformers import GemmaForCausalLM as HFGemma

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=1 if mqa else 2, head_dim=32,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=10000.0, hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True, attn_implementation="eager")
    return HFGemma(hf_cfg).eval()


def test_llama_from_hf_refuses_gemma_checkpoints():
    """A Gemma checkpoint has exactly Llama's key layout — the plain
    mapper must refuse it instead of silently building a silu/no-offset
    model that computes garbage."""
    from paddle_tpu.models.llama import llama_from_hf

    hf = _tiny_hf()
    with pytest.raises(NotImplementedError, match="gemma_from_hf"):
        llama_from_hf(hf, dtype="float32")


def test_moe_trunk_honors_norm_offset():
    """The MoE decoder's fused add_rms_norm must consume effective_weight()
    — with rms_norm_offset=True its zeros-init weight means (1+0)=identity,
    not a near-zero norm that collapses post-attention activations."""
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    paddle.seed(7)
    cfg = MixtralConfig.tiny(num_hidden_layers=1, rms_norm_offset=True)
    m = MixtralForCausalLM(cfg)
    norm = m.llama.layers[0].post_attention_layernorm
    np.testing.assert_array_equal(norm.weight.numpy(),
                                  np.zeros(cfg.hidden_size, np.float32))
    ids = paddle.to_tensor(np.random.RandomState(8).randint(0, 512, (1, 8)))
    logits = m(ids).numpy()
    # identity norms at init: the logits must be in a healthy range, not
    # collapsed toward the near-zero scale a raw-w read would produce
    assert np.isfinite(logits).all()
    assert np.abs(logits).max() > 1e-2


@pytest.mark.parametrize("mqa", [False, True], ids=["gqa", "mqa"])
def test_logits_and_generate_match_transformers(mqa):
    hf = _tiny_hf(mqa=mqa)
    ours = gemma_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.rms_norm_offset and ours.config.scale_embeddings
    assert ours.config.hidden_act == "gelu_pytorch_tanh"
    assert ours.config.head_dim == 32
    ids = np.random.RandomState(0).randint(0, 128, (2, 9))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 9:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)
