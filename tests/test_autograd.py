"""Eager autograd (tape) tests: backward, grad, hooks, PyLayer, no_grad.

Mirrors the reference test strategy for the eager engine
(test/legacy_test + test/autograd): analytic grads checked against
hand-derived and numeric values.
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_backward_simple():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_backward_chain():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x  # 4
    z = y * x  # x^3 = 8
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 12.0)  # 3x^2


def test_backward_branching():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    a = x * 2
    b = x * 5
    out = a + b
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), 7.0)


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = paddle.to_tensor(3.0)  # stop_gradient=True
    out = x * y
    out.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)
    assert y.grad is None


def test_detach_blocks():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = (x * x).detach()
    z = y * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 4.0)  # only the last mult


def test_no_grad_context():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    with paddle.no_grad():
        y = x * x
    assert y.stop_gradient
    z = x * 3
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)


def test_matmul_grad():
    A = paddle.randn([3, 4])
    A.stop_gradient = False
    B = paddle.randn([4, 5])
    B.stop_gradient = False
    out = (A @ B).sum()
    out.backward()
    np.testing.assert_allclose(A.grad.numpy(), np.ones((3, 5)) @ B.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(B.grad.numpy(), A.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_paddle_grad_api():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad(y, [x])
    np.testing.assert_allclose(gx.numpy(), 12.0)
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_backward_nonscalar_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


def test_backward_nonscalar_raises():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    with pytest.raises(RuntimeError):
        y.backward()


def test_multi_output_op_grad():
    x = paddle.to_tensor([[3.0, 1.0, 2.0]], stop_gradient=False)
    values, indices = paddle.topk(x, k=2)
    values.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0, 1]])


def test_getitem_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x[1] * 5
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 5, 0])


def test_reduction_grads():
    x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
    x.mean().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 0.25))


def test_numeric_gradient_check():
    """Finite-difference check, the OpTest check_grad analog
    (test/legacy_test/op_test.py:3081)."""
    rng = np.random.RandomState(0)
    x0 = rng.randn(4, 3).astype(np.float32)

    def f_np(x):
        return np.tanh(x).sum() + (x * x).sum()

    x = paddle.to_tensor(x0, stop_gradient=False)
    out = paddle.tanh(x).sum() + (x * x).sum()
    out.backward()
    analytic = x.grad.numpy()

    eps = 1e-3
    numeric = np.zeros_like(x0)
    for i in range(x0.shape[0]):
        for j in range(x0.shape[1]):
            xp = x0.copy()
            xp[i, j] += eps
            xm = x0.copy()
            xm[i, j] -= eps
            numeric[i, j] = (f_np(xp) - f_np(xm)) / (2 * eps)
    np.testing.assert_allclose(analytic, numeric, rtol=1e-2, atol=1e-3)


def test_register_hook():
    seen = []
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * 3
    y.register_hook(lambda g: seen.append(np.asarray(g)))
    y.backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], 1.0)


def test_pylayer():
    class Double(paddle.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor
            return grad * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [2, 4])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_pylayer_composes_with_ops():
    class Square(paddle.PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, grad):
            (x,) = ctx.saved_tensor
            return grad * 2 * x

    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = Square.apply(x * 2)  # (2x)^2 = 4x^2 → d/dx = 8x = 24
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 24.0)


def test_create_graph_double_backward():
    """d2/dx2 x^3 = 6x through paddle.grad(create_graph=True) twice.

    Parity: paddle/fluid/eager/backward.cc:450 Grad with create_graph; the
    TPU build records the whole vjp composite as one differentiable node."""
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g.numpy(), 12.0)  # 3x^2
    (g2,) = paddle.grad(g, [x])
    np.testing.assert_allclose(g2.numpy(), 12.0)  # 6x


def test_create_graph_grad_in_loss():
    """Gradient-penalty pattern: grads used inside a further loss."""
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x * x).sum()
    (g,) = paddle.grad(y, [x], create_graph=True)
    z = (g * g).sum()  # sum (3x^2)^2 = 9x^4 → dz/dx = 36x^3
    (h,) = paddle.grad(z, [x])
    np.testing.assert_allclose(h.numpy(), [288.0, 972.0])


def test_create_graph_backward_into_leaf_grad():
    """create_graph grads feed .backward() accumulation as well."""
    x = paddle.to_tensor(2.0, stop_gradient=False)
    (g,) = paddle.grad(x * x, [x], create_graph=True)  # 2x
    (g * g).backward()  # 4x^2 → d/dx = 8x = 16
    np.testing.assert_allclose(x.grad.numpy(), 16.0)


def test_create_graph_unused_input():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    u = paddle.to_tensor(5.0, stop_gradient=False)
    y = x * x
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, u], create_graph=True)
    gx, gu = paddle.grad(y, [x, u], create_graph=True, allow_unused=True)
    np.testing.assert_allclose(gx.numpy(), 4.0)
    assert gu is None


def test_inplace_grad_flows():
    """In-place op on a non-leaf keeps the chain (review regression)."""
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2          # non-leaf
    y[0] = 10.0        # in-place setitem on non-leaf
    y.sum().backward()
    # d(sum)/dx: position 0 overwritten -> 0, others flow through *2
    np.testing.assert_allclose(x.grad.numpy(), [0, 2, 2])


def test_inplace_on_leaf_raises():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    with pytest.raises(RuntimeError):
        x.add_(paddle.to_tensor([1.0, 1.0]))


def test_bool_mask_grad_flows():
    """Boolean-mask indexing is differentiable (review regression)."""
    x = paddle.to_tensor([1.0, -2.0, 3.0], stop_gradient=False)
    y = x[paddle.to_tensor([True, False, True])]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 0, 1])


def test_masked_select_grad_flows():
    x = paddle.to_tensor([[1.0, -2.0], [3.0, -4.0]], stop_gradient=False)
    out = paddle.masked_select(x, x > 0)
    np.testing.assert_allclose(out.numpy(), [1, 3])
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0], [1, 0]])


def test_grad_api_nonleaf_input():
    """paddle.grad with a non-leaf input (review regression)."""
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * 2
    z = (y * 3).sum()
    (gy,) = paddle.grad(z, [y])
    np.testing.assert_allclose(gy.numpy(), 3.0)


def test_grad_api_no_leaf_pollution():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    w = paddle.to_tensor(5.0, stop_gradient=False)
    z = x * w
    (gx,) = paddle.grad(z, [x])
    assert w.grad is None and x.grad is None


def test_independent_graphs_survive_backward():
    """backward() must not destroy other live graphs (review regression)."""
    x = paddle.to_tensor(1.0, stop_gradient=False)
    y1 = x * 2
    y2 = x * 3
    y1.backward()
    y2.backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_mode_returns_most_frequent():
    v, i = paddle.mode(paddle.to_tensor([2.0, 2.0, 7.0, 8.0, 9.0]))
    assert v.item() == 2.0


def test_to_device_and_dtype():
    t = paddle.to_tensor([1.0, 2.0])
    out = t.to("cpu", dtype="float16")
    assert out.dtype == paddle.float16
    assert "cpu" in str(out.place).lower() or "Cpu" in str(out.place)
