"""Gemma2 family: sandwich norms, q-premul softmax scale, tanh soft caps,
alternating sliding/full layers; HF conversion + logits/greedy parity
against transformers; loud refusals on the unsupported kernel paths."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gemma2 import (Gemma2Config, Gemma2ForCausalLM,
                                      gemma2_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_construction_and_schedule():
    paddle.seed(0)
    cfg = Gemma2Config.tiny()
    assert cfg.layer_types == ("sliding_attention", "full_attention")
    m = Gemma2ForCausalLM(cfg)
    layers = m.llama.layers
    assert layers[0].self_attn.window == cfg.sliding_window
    assert layers[1].self_attn.window is None
    # q premul folds query_pre_attn_scalar: head_dim 32, scalar 64
    assert layers[0].self_attn.q_premul == pytest.approx(
        np.sqrt(32 / 64.0))
    for norm in ("input_layernorm", "post_attention_layernorm",
                 "pre_feedforward_layernorm", "post_feedforward_layernorm"):
        assert getattr(layers[0], norm).offset == 1.0
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    loss, _ = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))


def test_layer_types_validation():
    with pytest.raises(ValueError, match="entries for"):
        Gemma2Config.tiny(layer_types=("full_attention",))
    with pytest.raises(ValueError, match="unknown layer_types"):
        Gemma2Config.tiny(layer_types=("full_attention", "banded"))
    with pytest.raises(ValueError, match="sliding_window is not set"):
        Gemma2Config.tiny(sliding_window=None,
                          layer_types=("sliding_attention",
                                       "full_attention"))
    with pytest.raises(NotImplementedError, match="fuse_linear"):
        Gemma2Config.tiny(fuse_linear_cross_entropy=True)


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(1)
    m = Gemma2ForCausalLM(Gemma2Config.tiny())

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_paged_softcap_matches_cached():
    """Softcapped decode rides the exact paged gather reference (the
    fused kernel computes uncapped scores) — paged == dense cached."""
    paddle.seed(2)
    m = Gemma2ForCausalLM(Gemma2Config.tiny())
    ids = paddle.to_tensor(np.random.RandomState(3).randint(1, 512, (1, 8)))
    a = m.generate(ids, max_new_tokens=5).numpy()
    b = m.generate(ids, max_new_tokens=5, paged=True, page_size=4).numpy()
    np.testing.assert_array_equal(a, b)


def test_engine_serves_gemma2():
    """The continuous-batching engine serves a softcapped, alternating-
    window model token-identically to solo generate."""
    from paddle_tpu.serving import ContinuousBatchEngine

    paddle.seed(3)
    m = Gemma2ForCausalLM(Gemma2Config.tiny())
    prompt = np.random.RandomState(4).randint(1, 512, (9,))
    solo = m.generate(paddle.to_tensor(prompt[None]),
                      max_new_tokens=6).numpy()[0]
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=32, page_size=8)
    rid = eng.add_request(prompt.tolist(), max_new_tokens=6)
    out = eng.run_until_done()[rid]
    np.testing.assert_array_equal(np.asarray(out), solo)


def _tiny_hf(seq_window=8):
    from transformers import Gemma2Config as HFConfig
    from transformers import Gemma2ForCausalLM as HFGemma2

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, query_pre_attn_scalar=64.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=seq_window, max_position_embeddings=128,
        rms_norm_eps=1e-6, rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh", tie_word_embeddings=True,
        attn_implementation="eager")
    return HFGemma2(hf_cfg).eval()


def test_logits_and_generate_match_transformers():
    """Prompt longer than the sliding window so the alternation genuinely
    bites on layer 0 while layer 1 attends fully."""
    hf = _tiny_hf(seq_window=8)
    ours = gemma2_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.attn_logit_softcapping == 50.0
    assert ours.config.final_logit_softcapping == 30.0
    assert ours.config.layer_types == ("sliding_attention",
                                       "full_attention")
    ids = np.random.RandomState(0).randint(0, 128, (2, 12))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 12:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_pipe_refuses_gemma2_knobs():
    """The pipeline assembly cannot honor layer_types (index-free
    LayerDescs) or the final soft cap (raw-weight head stages) — it must
    refuse, not silently diverge."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe

    cfg = LlamaConfig.tiny(num_hidden_layers=2, sliding_window=8,
                           layer_types=("sliding_attention",
                                        "full_attention"))
    with pytest.raises(NotImplementedError, match="layer_types"):
        LlamaForCausalLMPipe(cfg, num_stages=1)
    cfg2 = LlamaConfig.tiny(num_hidden_layers=2,
                            final_logit_softcapping=30.0)
    with pytest.raises(NotImplementedError, match="final_logit"):
        LlamaForCausalLMPipe(cfg2, num_stages=1)


def test_moe_trunk_honors_layer_schedule():
    """layer_types flows into MoE trunks' per-layer attention windows."""
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    paddle.seed(9)
    cfg = MixtralConfig.tiny(num_hidden_layers=2, sliding_window=8,
                             layer_types=("sliding_attention",
                                          "full_attention"))
    m = MixtralForCausalLM(cfg)
    assert m.llama.layers[0].self_attn.window == 8
    assert m.llama.layers[1].self_attn.window is None


def test_final_softcap_changes_logits():
    paddle.seed(4)
    m = Gemma2ForCausalLM(Gemma2Config.tiny())
    ids = paddle.to_tensor(np.random.RandomState(5).randint(0, 512, (1, 6)))
    capped = m(ids).numpy()
    m.config = dataclasses.replace(m.config, final_logit_softcapping=None)
    uncapped = m(ids).numpy()
    assert np.abs(capped).max() <= 30.0 + 1e-5
    assert not np.allclose(capped, uncapped)


def test_lora_on_gemma2():
    """peft targets named trunk Linears, so the sandwich trunk fine-tunes
    with adapters only; merge restores a plain model with moved logits."""
    from paddle_tpu import optimizer as opt
    from paddle_tpu.peft import LoRAConfig, get_peft_model, merge_lora

    paddle.seed(6)
    m = Gemma2ForCausalLM(Gemma2Config.tiny(num_hidden_layers=1))
    ids = paddle.to_tensor(np.random.RandomState(7).randint(1, 512, (2, 10)))
    base_logits = m(ids).numpy()
    m, n_adapters = get_peft_model(m, LoRAConfig(r=4, lora_alpha=8))
    assert n_adapters > 0
    trainable = [p for p in m.parameters() if not p.stop_gradient]
    assert trainable and all("lora" in n for n, p in m.named_parameters()
                             if not p.stop_gradient)
    np.testing.assert_allclose(m(ids).numpy(), base_logits,
                               atol=1e-5, rtol=1e-5)  # identity at init

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(5e-2, parameters=trainable))
    y = paddle.to_tensor(np.random.RandomState(8).randint(1, 512, (2, 10)))
    for _ in range(3):
        step(ids, y)
    tuned = m(ids).numpy()
    assert not np.allclose(tuned, base_logits)
    merged, n_merged = merge_lora(m)
    assert n_merged == n_adapters
    np.testing.assert_allclose(merged(ids).numpy(), tuned,
                               atol=1e-4, rtol=1e-4)
