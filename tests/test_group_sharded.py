"""group_sharded_parallel (ZeRO levels) veneer.

Parity: test/collective/fleet dygraph_group_sharded_* tests — train-loss
parity between sharded and unsharded runs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer as opt


def _data():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16), np.float32)
    y = rng.standard_normal((8, 4), np.float32)
    return x, y


def _train(level):
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)
    try:
        paddle.seed(3)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        optimizer = opt.AdamW(1e-2, parameters=model.parameters())
        if level is not None:
            model, optimizer, _ = dist.sharding.group_sharded_parallel(
                model, optimizer, level)
        x, y = _data()
        losses = []
        for _ in range(5):
            pred = model(paddle.to_tensor(x))
            loss = ((pred - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        return losses
    finally:
        dist.set_hybrid_communicate_group(None)


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_group_sharded_loss_parity(level):
    ref = _train(None)
    got = _train(level)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)
    assert got[-1] < got[0]  # actually trained


def test_group_sharded_bad_level_and_offload():
    s = dist.DistributedStrategy()
    dist.fleet.init(is_collective=True, strategy=s)
    try:
        model = nn.Linear(4, 4)
        optimizer = opt.AdamW(1e-2, parameters=model.parameters())
        with pytest.raises(ValueError, match="level"):
            dist.sharding.group_sharded_parallel(model, optimizer, "zz")
        with pytest.raises(NotImplementedError):
            dist.sharding.group_sharded_parallel(model, optimizer, "os",
                                                 offload=True)
    finally:
        dist.set_hybrid_communicate_group(None)


def test_save_group_sharded_model(tmp_path):
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 4}
    dist.fleet.init(is_collective=True, strategy=s)
    try:
        paddle.seed(3)
        model = nn.Linear(8, 8)
        optimizer = opt.AdamW(1e-2, parameters=model.parameters())
        model, optimizer, _ = dist.sharding.group_sharded_parallel(
            model, optimizer, "p_g_os")
        dist.sharding.save_group_sharded_model(model, str(tmp_path), optimizer)
        sd = paddle.load(str(tmp_path / "model.pdparams"))
        assert set(sd) == set(model.state_dict())
    finally:
        dist.set_hybrid_communicate_group(None)
