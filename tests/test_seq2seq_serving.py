"""Seq2SeqBatchEngine: continuous batching for encoder-decoder families —
Whisper (ASR) and BART served in-flight, token-identical to solo
generate; staggered admission; T5 refusal."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.serving import Seq2SeqBatchEngine


def _mel(frames=32, bins=8, seed=0):
    return np.random.RandomState(seed).randn(bins, frames).astype(np.float32)


@pytest.fixture(scope="module")
def whisper_model():
    from paddle_tpu.models.whisper import (WhisperConfig,
                                           WhisperForConditionalGeneration)

    paddle.seed(0)
    return WhisperForConditionalGeneration(WhisperConfig.tiny())


def _solo(m, feats, n, seed_ids=None):
    out = m.generate(paddle.to_tensor(feats[None]), max_new_tokens=n,
                     decoder_input_ids=(None if seed_ids is None
                                        else np.asarray(seed_ids)[None]),
                     eos_token_id=None).numpy()[0]
    eos = m.config.eos_token_id
    if eos in out:
        out = out[: list(out).index(eos) + 1]
    return out.tolist()


def test_whisper_engine_matches_solo(whisper_model):
    m = whisper_model
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16)
    feats = [_mel(seed=i) for i in range(3)]
    solos = [_solo(m, f, 8) for f in feats]
    r0 = eng.add_request(feats[0], max_new_tokens=8)
    eng.step()                                  # r0 in flight...
    r1 = eng.add_request(feats[1], max_new_tokens=8)
    r2 = eng.add_request(feats[2], max_new_tokens=8)   # queued (2 slots)
    done = eng.run_until_done()
    for rid, solo in zip((r0, r1, r2), solos):
        assert done[rid].tolist() == solo, rid


def test_whisper_seed_prompt(whisper_model):
    m = whisper_model
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16)
    feats = _mel(seed=7)
    seed = [1, 9, 4]
    solo = _solo(m, feats, 6, seed_ids=seed)
    rid = eng.add_request(feats, max_new_tokens=6, seed_ids=seed)
    done = eng.run_until_done()
    assert done[rid].tolist() == solo


def test_bart_engine_matches_solo():
    from paddle_tpu.models.bart import (BartConfig,
                                        BartForConditionalGeneration)

    paddle.seed(1)
    m = BartForConditionalGeneration(BartConfig.tiny())
    rng = np.random.RandomState(3)
    enc_ids = [rng.randint(3, 256, (n,)) for n in (9, 6)]
    solos = []
    for ids in enc_ids:
        out = m.generate(paddle.to_tensor(ids[None]), max_new_tokens=7,
                         eos_token_id=-1).numpy()[0]
        solos.append(out.tolist())
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16, eos_token_id=-1)
    r0 = eng.add_request(enc_ids[0], max_new_tokens=7)
    eng.step()
    r1 = eng.add_request(enc_ids[1], max_new_tokens=7)
    done = eng.run_until_done()
    assert done[r0].tolist() == solos[0]
    assert done[r1].tolist() == solos[1]


def test_t5_engine_matches_solo():
    """T5 serves through the engine too: the per-row relative-position
    bias (T5Stack._bias_rows) makes ragged rows exact — staggered
    admission token-identical to solo generate."""
    from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration

    paddle.seed(2)
    m = T5ForConditionalGeneration(T5Config.tiny())
    rng = np.random.RandomState(4)
    enc_ids = [rng.randint(2, 256, (n,)) for n in (10, 7)]
    solos = [m.generate(paddle.to_tensor(ids[None]), max_new_tokens=7,
                        eos_token_id=-1).numpy()[0].tolist()
             for ids in enc_ids]
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16, eos_token_id=-1)
    r0 = eng.add_request(enc_ids[0], max_new_tokens=7)
    eng.step()
    r1 = eng.add_request(enc_ids[1], max_new_tokens=7)
    done = eng.run_until_done()
    assert done[r0].tolist() == solos[0]
    assert done[r1].tolist() == solos[1]


def test_budget_and_encoder_overflow(whisper_model):
    m = whisper_model
    eng = Seq2SeqBatchEngine(m, max_batch=1, max_decode_len=8,
                             max_encoder_len=8)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.add_request(_mel(), max_new_tokens=9)
    with pytest.raises(ValueError, match="max_encoder_len"):
        eng.add_request(_mel(frames=32), max_new_tokens=4)
        eng.run_until_done()


def test_seed_counts_against_decode_budget(whisper_model):
    """Review r5 repro: seed + max_new_tokens overran the self-cache rows
    and silently diverged — now rejects at add_request."""
    m = whisper_model
    eng = Seq2SeqBatchEngine(m, max_batch=1, max_decode_len=8,
                             max_encoder_len=16)
    with pytest.raises(ValueError, match="seed"):
        eng.add_request(_mel(), max_new_tokens=8, seed_ids=[1, 2, 3, 4, 5])
    # the same request sized correctly serves exactly
    solo = _solo(m, _mel(seed=11), 3, seed_ids=[1, 2, 3, 4, 5])
    rid = eng.add_request(_mel(seed=11), max_new_tokens=3,
                          seed_ids=[1, 2, 3, 4, 5])
    assert eng.run_until_done()[rid].tolist() == solo


def test_decode_table_validated(whisper_model):
    with pytest.raises(ValueError, match="position table"):
        Seq2SeqBatchEngine(whisper_model, max_batch=1,
                           max_decode_len=10 ** 4, max_encoder_len=16)


def test_cancel_and_stats(whisper_model):
    m = whisper_model
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16)
    keep_f = _mel(seed=20)
    solo = _solo(m, keep_f, 6)
    keep = eng.add_request(keep_f, max_new_tokens=6)
    dead = eng.add_request(_mel(seed=21), max_new_tokens=6)
    eng.step()
    assert eng.cancel(dead) is True
    assert eng.finish_reason(dead) == "cancelled"
    done = eng.run_until_done()
    assert dead not in done
    assert done[keep].tolist() == solo
    assert eng.finish_reason(keep) in ("stop", "length")
    s = eng.stats()
    assert s["requests_admitted"] == 2 and s["requests_finished"] == 1
    assert s["tokens_generated"] >= len(solo)
