"""nn.Layer system + layers + functional tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_forward_backward():
    l = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = l(x)
    assert y.shape == [2, 3]
    y.sum().backward()
    assert l.weight.grad is not None and l.weight.grad.shape == [4, 3]
    assert l.bias.grad is not None


def test_sequential_and_state_dict():
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = m.state_dict()
    assert "0.weight" in sd and "2.bias" in sd
    m2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    missing, unexpected = m2.set_state_dict(sd)
    assert not missing and not unexpected
    x = paddle.randn([2, 4])
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)


def test_layerlist_and_dict():
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3 and len(ll.parameters()) == 6
    ld = nn.LayerDict({"a": nn.Linear(2, 2)})
    assert "a" in ld


def test_train_eval_mode():
    d = nn.Dropout(0.5)
    x = paddle.ones([100])
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), np.ones(100))
    d.train()
    out = d(x).numpy()
    assert (out == 0).any() and (out > 1).any()  # upscale_in_train


def test_embedding_padding_idx():
    e = nn.Embedding(10, 4, padding_idx=0)
    out = e(paddle.to_tensor([[0, 1], [2, 0]]))
    np.testing.assert_allclose(out.numpy()[0, 0], np.zeros(4))
    assert np.abs(out.numpy()[0, 1]).sum() > 0


def test_layernorm_stats():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8]) * 5 + 3
    y = ln(x).numpy()
    np.testing.assert_allclose(y.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(y.std(-1), np.ones(4), atol=1e-2)


def test_rmsnorm_matches_reference():
    rms = nn.RMSNorm(16)
    x = paddle.randn([2, 3, 16])
    y = rms(x).numpy()
    xn = x.numpy()
    ref = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y, ref, rtol=1e-4)


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm1D(4, momentum=0.9)
    bn.train()
    x = paddle.randn([32, 4]) * 2 + 1
    bn(x)
    assert np.abs(bn._mean.numpy()).sum() > 0  # moved from zeros
    bn.eval()
    y = bn(x)
    assert y.shape == [32, 4]


def test_conv2d_shape_and_grad():
    conv = nn.Conv2D(3, 8, 3, padding=1)
    x = paddle.randn([2, 3, 8, 8])
    y = conv(x)
    assert y.shape == [2, 8, 8, 8]
    y.mean().backward()
    assert conv.weight.grad.shape == [8, 3, 3, 3]


def test_conv2d_matches_numpy():
    conv = nn.Conv2D(1, 1, 2, bias_attr=False)
    x = paddle.ones([1, 1, 3, 3])
    w = conv.weight.numpy()
    y = conv(x).numpy()
    assert y.shape == (1, 1, 2, 2)
    np.testing.assert_allclose(y[0, 0, 0, 0], w.sum(), rtol=1e-5)


def test_conv_transpose_shape():
    ct = nn.Conv2DTranspose(4, 2, 4, stride=2, padding=1)
    y = ct(paddle.randn([1, 4, 8, 8]))
    assert y.shape == [1, 2, 16, 16]


def test_grouped_conv():
    conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
    assert conv(paddle.randn([1, 4, 5, 5])).shape == [1, 8, 5, 5]


def test_pools():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
    np.testing.assert_allclose(
        nn.AdaptiveAvgPool2D(1)(x).numpy()[0, 0, 0, 0], x.numpy()[0, 0].mean(), rtol=1e-5)


def test_activations():
    x = paddle.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(F.relu(x).numpy(), [0, 0, 0, 0.5, 2])
    np.testing.assert_allclose(F.hardtanh(x).numpy(), [-1, -0.5, 0, 0.5, 1])
    assert F.gelu(x).shape == [5]
    assert F.softmax(x).numpy().sum() == pytest.approx(1.0, rel=1e-5)
    np.testing.assert_allclose(F.glu(paddle.to_tensor([1.0, 0.0])).numpy(), [0.5], rtol=1e-5)


def test_cross_entropy_matches_manual():
    logits = paddle.randn([3, 5])
    labels = paddle.to_tensor([0, 2, 4])
    loss = F.cross_entropy(logits, labels).numpy()
    l = logits.numpy()
    logp = l - np.log(np.exp(l).sum(-1, keepdims=True))
    manual = -logp[np.arange(3), [0, 2, 4]].mean()
    np.testing.assert_allclose(loss, manual, rtol=1e-5)


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor([0, -100, 2, -100])
    loss = F.cross_entropy(logits, labels, ignore_index=-100).numpy()
    l = logits.numpy()
    logp = l - np.log(np.exp(l).sum(-1, keepdims=True))
    manual = -(logp[0, 0] + logp[2, 2]) / 2
    np.testing.assert_allclose(loss, manual, rtol=1e-5)


def test_bce_with_logits_stable():
    z = paddle.to_tensor([100.0, -100.0])
    l = paddle.to_tensor([1.0, 0.0])
    loss = F.binary_cross_entropy_with_logits(z, l)
    assert np.isfinite(loss.numpy()) and loss.numpy() < 1e-3


def test_mha_shapes_and_grad():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    y = mha(x)
    assert y.shape == [2, 6, 16]
    y.sum().backward()
    assert mha.q_proj.weight.grad is not None


def test_sdpa_matches_reference():
    q = paddle.randn([1, 4, 2, 8])
    k = paddle.randn([1, 4, 2, 8])
    v = paddle.randn([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
    qn, kn, vn = (t.numpy().transpose(0, 2, 1, 3) for t in (q, k, v))  # BHSD
    scores = qn @ kn.transpose(0, 1, 3, 2) / np.sqrt(8)
    mask = np.tril(np.ones((4, 4), bool))
    scores = np.where(mask, scores, -np.inf)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ref = (p @ vn).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=2, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, 2)
    out = enc(paddle.randn([2, 5, 16]))
    assert out.shape == [2, 5, 16]


def test_lstm_and_gru():
    lstm = nn.LSTM(8, 16, num_layers=2, direction="bidirectional")
    out, (h, c) = lstm(paddle.randn([3, 5, 8]))
    assert out.shape == [3, 5, 32]
    assert h.shape == [4, 3, 16]
    gru = nn.GRU(8, 16)
    out, h = gru(paddle.randn([3, 5, 8]))
    assert out.shape == [3, 5, 16]
    out.sum().backward()


def test_rnn_grad_flows():
    rnn = nn.SimpleRNN(4, 8)
    out, h = rnn(paddle.randn([2, 3, 4]))
    out.sum().backward()
    assert rnn.weight_ih_l0.grad is not None


def test_initializers():
    from paddle_tpu.nn.initializer import Constant, XavierNormal, KaimingNormal, Orthogonal

    l = nn.Linear(10, 10, weight_attr=nn.ParamAttr(initializer=Constant(2.0)))
    np.testing.assert_allclose(l.weight.numpy(), np.full((10, 10), 2.0))
    w = Orthogonal()((8, 8), np.float32)
    np.testing.assert_allclose(np.asarray(w) @ np.asarray(w).T, np.eye(8), atol=1e-5)


def test_parameter_freeze():
    l = nn.Linear(4, 4)
    l.weight.stop_gradient = True
    y = l(paddle.randn([2, 4]))
    y.sum().backward()
    assert l.weight.grad is None and l.bias.grad is not None


def test_hooks():
    l = nn.Linear(4, 4)
    calls = []
    h = l.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    l(paddle.randn([1, 4]))
    assert calls == [1]
    h.remove()
    l(paddle.randn([1, 4]))
    assert calls == [1]


def test_to_dtype():
    l = nn.Linear(4, 4)
    l.bfloat16()
    assert l.weight.dtype == paddle.bfloat16
    out = l(paddle.randn([2, 4]).astype("bfloat16"))
    assert out.dtype == paddle.bfloat16


def test_weight_norm():
    from paddle_tpu.nn.utils import weight_norm, remove_weight_norm

    l = nn.Linear(4, 3)
    w0 = l.weight.numpy() if hasattr(l, "weight") else None
    weight_norm(l, "weight")
    assert "weight_g" in dict(l.named_parameters())
    y = l(paddle.randn([2, 4]))
    assert y.shape == [2, 3]
    remove_weight_norm(l)
    assert "weight" in dict(l.named_parameters())


def test_pixel_shuffle_roundtrip():
    x = paddle.randn([1, 8, 4, 4])
    up = F.pixel_shuffle(x, 2)
    assert up.shape == [1, 2, 8, 8]
    down = F.pixel_unshuffle(up, 2)
    np.testing.assert_allclose(down.numpy(), x.numpy(), rtol=1e-6)


def test_interpolate():
    x = paddle.randn([1, 2, 4, 4])
    y = F.interpolate(x, scale_factor=2, mode="nearest")
    assert y.shape == [1, 2, 8, 8]
    y = F.interpolate(x, size=[6, 6], mode="bilinear")
    assert y.shape == [1, 2, 6, 6]


def test_batchnorm_grad_includes_stats_terms():
    """BN input grad must include d(mean)/dx and d(var)/dx (review regression):
    for affine-less BN over a batch, sum of input grads of sum(output) ~ 0."""
    bn = nn.BatchNorm1D(3, weight_attr=False, bias_attr=False)
    bn.train()
    x = paddle.randn([8, 3])
    x.stop_gradient = False
    bn(x).sum().backward()
    np.testing.assert_allclose(x.grad.numpy().sum(axis=0), np.zeros(3), atol=1e-4)


def test_nll_loss_log_prob_input():
    logits = paddle.randn([4, 5])
    logits.stop_gradient = False
    logp = F.log_softmax(logits)
    labels = paddle.to_tensor([1, 0, 3, 2])
    loss = F.nll_loss(logp, labels)
    ce = F.cross_entropy(logits.detach(), labels)
    np.testing.assert_allclose(loss.numpy(), ce.numpy(), rtol=1e-5)
    loss.backward()
    assert np.abs(logits.grad.numpy()).sum() > 0


def test_lstm_initial_state_used():
    lstm = nn.LSTM(4, 8)
    x = paddle.randn([2, 3, 4])
    h0 = paddle.ones([1, 2, 8])
    c0 = paddle.ones([1, 2, 8])
    out_zero, _ = lstm(x)
    out_init, (h, c) = lstm(x, (h0, c0))
    assert not np.allclose(out_zero.numpy(), out_init.numpy())
    # chunked == full sequence when states carried over
    out_full, (hf, cf) = lstm(x)
    o1, (h1, c1) = lstm(x[:, :2])
    o2, (h2, c2) = lstm(x[:, 2:], (h1, c1))
    np.testing.assert_allclose(np.concatenate([o1.numpy(), o2.numpy()], axis=1),
                               out_full.numpy(), rtol=1e-4, atol=1e-5)


def test_weight_norm_grads_flow():
    from paddle_tpu.nn.utils import weight_norm

    l = weight_norm(nn.Linear(4, 3))
    y = l(paddle.randn([2, 4]))
    y.sum().backward()
    assert l._parameters["weight_g"].grad is not None
    assert l._parameters["weight_v"].grad is not None


def test_rrelu_and_gumbel_softmax():
    """Randomized activations: bounds/simplex properties + eval-mode
    determinism (these can't be value-matched against a fixed reference)."""
    paddle.seed(4)
    x = paddle.to_tensor(np.random.RandomState(0).randn(64).astype("float32"))
    # eval mode: fixed mean slope, deterministic
    out = F.rrelu(x, lower=0.1, upper=0.3, training=False).numpy()
    xn = x.numpy()
    np.testing.assert_allclose(out[xn >= 0], xn[xn >= 0])
    np.testing.assert_allclose(out[xn < 0], xn[xn < 0] * 0.2, rtol=1e-5)
    # training mode: slopes inside [lower, upper]
    out_t = F.rrelu(x, lower=0.1, upper=0.3, training=True).numpy()
    neg = xn < 0
    slopes = out_t[neg] / xn[neg]
    assert (slopes >= 0.1 - 1e-6).all() and (slopes <= 0.3 + 1e-6).all()
    np.testing.assert_allclose(out_t[~neg], xn[~neg])

    # gumbel softmax: simplex rows; hard=True gives one-hot straight-through
    logits = paddle.to_tensor(np.random.RandomState(1).randn(8, 5).astype("float32"),
                              stop_gradient=False)
    soft = F.gumbel_softmax(logits, temperature=0.5)
    sn = soft.numpy()
    np.testing.assert_allclose(sn.sum(-1), 1.0, rtol=1e-5)
    assert (sn >= 0).all()
    hard = F.gumbel_softmax(logits, temperature=0.5, hard=True)
    hn = hard.numpy()
    assert ((hn == 0) | (np.isclose(hn, 1))).all()
    np.testing.assert_array_equal(hn.sum(-1), 1.0)
    hard.sum().backward()  # straight-through grads reach the logits
    assert logits.grad is not None


def test_ctc_loss_matches_manual():
    """CTC on a tiny case vs a hand-computed forward algorithm."""
    # T=2, B=1, C=3 (blank=0); label "1"
    logp = np.log(np.array([
        [[0.6, 0.3, 0.1]],   # t=0
        [[0.5, 0.4, 0.1]],   # t=1
    ], dtype="float32"))
    labels = np.array([[1]], dtype="int32")
    # paths emitting "1" over 2 frames: (1,1), (1,-), (-,1)
    p = 0.3 * 0.4 + 0.3 * 0.5 + 0.6 * 0.4
    ref = -np.log(p)
    loss = F.ctc_loss(paddle.to_tensor(logp), paddle.to_tensor(labels),
                      paddle.to_tensor(np.array([2])),
                      paddle.to_tensor(np.array([1])), reduction="none")
    np.testing.assert_allclose(np.ravel(loss.numpy())[0], ref, rtol=1e-4)
