"""Qwen2-MoE family: construction, shared-expert sigmoid gate, training,
HF conversion + logits/greedy parity against transformers, EP sharding."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig, Qwen2MoeForCausalLM,
                                         qwen2_moe_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_construction_and_shared_gate():
    paddle.seed(0)
    cfg = Qwen2MoeConfig.tiny()
    m = Qwen2MoeForCausalLM(cfg)
    mlp = m.llama.layers[0].mlp
    assert mlp.shared_gate_weight is not None
    assert mlp.shared_gate_weight.shape == [cfg.hidden_size, 1]
    # swiglu experts: fused gate||up
    assert mlp.experts.w1.shape == [cfg.n_routed_experts, cfg.hidden_size,
                                    2 * cfg.moe_intermediate_size]
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    loss, _ = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))
    with pytest.raises(ValueError, match="attention_bias"):
        Qwen2MoeForCausalLM(dataclasses.replace(cfg, attention_bias=False))


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(1)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny())

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_logits_and_generate_match_transformers():
    """Full-precision parity with HF modeling_qwen2_moe on a tiny shape.
    moe_capacity_factor is raised so the capacity-based dispatch drops no
    token (HF routing is dropless)."""
    from transformers import Qwen2MoeConfig as HFConfig
    from transformers import Qwen2MoeForCausalLM as HFMoe

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6, rope_theta=1e6,
        num_experts=4, num_experts_per_tok=2, moe_intermediate_size=32,
        shared_expert_intermediate_size=64, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        output_router_logits=False, tie_word_embeddings=False,
        attn_implementation="eager")
    hf = HFMoe(hf_cfg).eval()
    ours = qwen2_moe_from_hf(hf, dtype="float32", use_flash_attention=False,
                             moe_capacity_factor=8.0)
    assert ours.config.n_shared_experts == 2          # 64 = 2 x 32
    assert ours.config.norm_topk_prob is False
    ids = np.random.RandomState(0).randint(0, 128, (2, 9))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 9:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_norm_topk_renormalization_matters():
    """norm_topk_prob=False (Qwen2-MoE) vs True must give different
    combines whenever top-k probs do not already sum to 1."""
    paddle.seed(2)
    cfg = Qwen2MoeConfig.tiny()
    m1 = Qwen2MoeForCausalLM(cfg)
    paddle.seed(2)
    m2 = Qwen2MoeForCausalLM(dataclasses.replace(cfg, norm_topk_prob=True))
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 512, (1, 8)))
    a = m1(ids).numpy()
    b = m2(ids).numpy()
    assert not np.allclose(a, b)


def test_ep_sharding_under_hybrid_mesh():
    import paddle_tpu.distributed as dist

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(4)
        m = Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny())
        mlp = m.llama.layers[0].mlp
        assert mlp._ep_axes == ("dp",)  # E=4 over dp4
    finally:
        dist.set_hybrid_communicate_group(None)


def test_shared_gate_gets_eager_gradients():
    """Review regression: the sigmoid shared-expert gate must be recorded
    on the eager tape — shared_gate_weight.grad flows without jit."""
    paddle.seed(5)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny(num_hidden_layers=1))
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 8)))
    loss, _ = m(ids, labels=ids)
    loss.backward()
    g = m.llama.layers[0].mlp.shared_gate_weight.grad
    assert g is not None
    assert float(np.abs(g.numpy()).sum()) > 0
