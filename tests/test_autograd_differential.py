"""Differential fuzz: the eager tape's gradients vs jax.grad on the SAME
randomly composed op chains. The tape is this framework's own machinery
(jax.vjp per recorded node + graph accumulation); jax.grad of the identical
composition is an independent oracle — any divergence is a tape bug, not a
kernel bug."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle

# (name, paddle_fn, jnp_fn, needs_positive)
_UNARY = [
    ("tanh", paddle.tanh, jnp.tanh, False),
    ("sigmoid", paddle.sigmoid, jax.nn.sigmoid, False),
    ("exp", paddle.exp, jnp.exp, False),
    ("log", paddle.log, jnp.log, True),
    ("sqrt", paddle.sqrt, jnp.sqrt, True),
    ("square", paddle.square, jnp.square, False),
    ("sin", paddle.sin, jnp.sin, False),
    ("erf", paddle.erf, jax.scipy.special.erf, False),
]
_BINARY = [
    ("add", paddle.add, jnp.add),
    ("subtract", paddle.subtract, jnp.subtract),
    ("multiply", paddle.multiply, jnp.multiply),
    ("maximum", paddle.maximum, jnp.maximum),
]


def _random_chain(rng, depth):
    """A program: list of ('u', i, op) / ('b', i, j, op) steps over a
    growing value list seeded with two inputs."""
    steps = []
    n_vals = 2
    for _ in range(depth):
        if rng.rand() < 0.5:
            steps.append(("u", rng.randint(n_vals),
                          rng.randint(len(_UNARY))))
        else:
            steps.append(("b", rng.randint(n_vals), rng.randint(n_vals),
                          rng.randint(len(_BINARY))))
        n_vals += 1
    return steps


def _run(steps, x0, x1, lib):
    vals = [x0, x1]
    for s in steps:
        if s[0] == "u":
            _, i, k = s
            fn = _UNARY[k][1] if lib == "paddle" else _UNARY[k][2]
            v = vals[i]
            if _UNARY[k][3]:  # domain guard for log/sqrt
                v = (paddle.abs(v) + 0.5) if lib == "paddle" \
                    else (jnp.abs(v) + 0.5)
            vals.append(fn(v))
        else:
            _, i, j, k = s
            fn = _BINARY[k][1] if lib == "paddle" else _BINARY[k][2]
            vals.append(fn(vals[i], vals[j]))
    out = vals[-1]
    return out.sum() if lib == "paddle" else jnp.sum(out)


@pytest.mark.parametrize("seed", range(12))
def test_tape_matches_jax_grad_on_random_chains(seed):
    rng = np.random.RandomState(seed)
    depth = rng.randint(3, 9)
    steps = _random_chain(rng, depth)
    a = rng.uniform(-1.5, 1.5, (3, 4)).astype("float32")
    b = rng.uniform(-1.5, 1.5, (3, 4)).astype("float32")

    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    loss = _run(steps, ta, tb, "paddle")
    loss.backward()

    ref_fn = lambda xa, xb: _run(steps, xa, xb, "jax")
    ga, gb = jax.grad(ref_fn, argnums=(0, 1))(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(float(loss.numpy()),
                               float(ref_fn(jnp.asarray(a), jnp.asarray(b))),
                               rtol=2e-5, atol=1e-5)
    for t, want in ((ta, ga), (tb, gb)):
        if t.grad is None:
            # unused leaf: paddle leaves .grad None; the oracle gives zeros
            np.testing.assert_allclose(np.asarray(want), 0, atol=1e-7,
                                       err_msg=f"steps={steps}")
        else:
            np.testing.assert_allclose(t.grad.numpy(), np.asarray(want),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"steps={steps}")


@pytest.mark.parametrize("seed", range(6))
def test_paddle_grad_api_matches_jax(seed):
    """Same chains through paddle.grad (no .grad mutation) + reuse of one
    tensor in several ops (fan-out accumulation)."""
    rng = np.random.RandomState(100 + seed)
    steps = _random_chain(rng, rng.randint(4, 8))
    a = rng.uniform(-1.0, 1.0, (2, 5)).astype("float32")
    b = rng.uniform(-1.0, 1.0, (2, 5)).astype("float32")
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    loss = _run(steps, ta, tb, "paddle")
    ga, gb = paddle.grad([loss], [ta, tb], allow_unused=True)
    ref = jax.grad(lambda xa, xb: _run(steps, xa, xb, "jax"),
                   argnums=(0, 1))(jnp.asarray(a), jnp.asarray(b))
    for got, want in zip((ga, gb), ref):
        if got is None:
            np.testing.assert_allclose(np.asarray(want), 0, atol=1e-7)
        else:
            np.testing.assert_allclose(got.numpy(), np.asarray(want),
                                       rtol=2e-4, atol=2e-5)


def test_tape_matmul_and_reduction_mix():
    """Matmul + reductions + broadcasting through both systems."""
    rng = np.random.RandomState(7)
    a = rng.randn(4, 6).astype("float32")
    w = rng.randn(6, 3).astype("float32")
    bias = rng.randn(3).astype("float32")

    ta = paddle.to_tensor(a, stop_gradient=False)
    tw = paddle.to_tensor(w, stop_gradient=False)
    tbias = paddle.to_tensor(bias, stop_gradient=False)
    out = paddle.matmul(ta, tw) + tbias
    loss = (paddle.tanh(out) ** 2).mean() + paddle.abs(out).sum() * 0.1
    loss.backward()

    def ref(xa, xw, xb):
        o = xa @ xw + xb
        return jnp.mean(jnp.tanh(o) ** 2) + jnp.sum(jnp.abs(o)) * 0.1

    g = jax.grad(ref, argnums=(0, 1, 2))(
        jnp.asarray(a), jnp.asarray(w), jnp.asarray(bias))
    for got, want in zip((ta, tw, tbias), g):
        np.testing.assert_allclose(got.grad.numpy(), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
