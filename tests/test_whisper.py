"""Whisper family: conv frontend + sinusoid positions, pre-LN stacks, HF
conversion with logits parity, cached greedy vs manual HF greedy (the HF
generate() task-token forcing is tokenizer-layer policy, so parity is
against the raw model loop), training, cache==no-cache."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.whisper import (WhisperConfig,
                                       WhisperForConditionalGeneration,
                                       sinusoids, whisper_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _tiny_hf():
    from transformers import WhisperConfig as HFConfig
    from transformers import WhisperForConditionalGeneration as HFWhisper

    torch.manual_seed(0)
    cfg = HFConfig(
        vocab_size=256, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128, num_mel_bins=8,
        max_source_positions=16, max_target_positions=64,
        decoder_start_token_id=1, eos_token_id=2, pad_token_id=2,
        bos_token_id=3, suppress_tokens=[], begin_suppress_tokens=[],
        attn_implementation="eager")
    return HFWhisper(cfg).eval()


def _mel(batch=2, frames=32, bins=8, seed=0):
    # frames -> frames//2 encoder positions after the stride-2 conv
    return np.random.RandomState(seed).randn(
        batch, bins, frames).astype(np.float32)


def test_sinusoids_match_transformers():
    from transformers.models.whisper.modeling_whisper import (
        sinusoids as hf_sinusoids)

    ours = sinusoids(16, 64)
    ref = hf_sinusoids(16, 64).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_logits_match_transformers():
    hf = _tiny_hf()
    ours = whisper_from_hf(hf)
    feats = _mel()
    dec = np.random.RandomState(1).randint(4, 256, (2, 7))
    with torch.no_grad():
        ref = hf(input_features=torch.from_numpy(feats),
                 decoder_input_ids=torch.from_numpy(dec)).logits.numpy()
    got = ours(paddle.to_tensor(feats), paddle.to_tensor(dec)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)


def test_cached_greedy_matches_manual_hf_greedy():
    hf = _tiny_hf()
    ours = whisper_from_hf(hf)
    feats = _mel(seed=2)
    seed_ids = np.full((2, 1), 1, np.int64)   # decoder_start
    # manual HF greedy loop — no task-token forcing, pure model argmax
    toks = torch.from_numpy(seed_ids)
    with torch.no_grad():
        for _ in range(6):
            logits = hf(input_features=torch.from_numpy(feats),
                        decoder_input_ids=toks).logits
            nxt = logits[:, -1, :].argmax(-1, keepdim=True)
            toks = torch.cat([toks, nxt], dim=1)
    ref = toks.numpy()[:, 1:]
    got = ours.generate(paddle.to_tensor(feats), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(got, ref)


def test_cached_equals_no_cache():
    paddle.seed(0)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    feats = paddle.to_tensor(_mel(seed=3))
    cached = m.generate(feats, max_new_tokens=5, eos_token_id=None).numpy()
    # no-cache reference: rerun the full decode each step
    ids = np.full((2, 1), m.config.decoder_start_token_id, np.int64)
    for _ in range(5):
        logits = m(feats, paddle.to_tensor(ids)).numpy()
        ids = np.concatenate([ids, logits[:, -1, :].argmax(-1)[:, None]],
                             axis=1)
    np.testing.assert_array_equal(cached, ids[:, 1:])


def test_decoder_prompt_seed():
    """A multi-token decoder seed (task/language prompt) prefills the
    self-cache in one shot."""
    paddle.seed(1)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    feats = paddle.to_tensor(_mel(seed=4))
    seed = np.array([[1, 7, 9], [1, 7, 9]], np.int64)
    out = m.generate(feats, decoder_input_ids=seed,
                     max_new_tokens=4, eos_token_id=None).numpy()
    ids = seed.copy()
    for _ in range(4):
        logits = m(feats, paddle.to_tensor(ids)).numpy()
        ids = np.concatenate([ids, logits[:, -1, :].argmax(-1)[:, None]],
                             axis=1)
    np.testing.assert_array_equal(out, ids[:, 3:])


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(2)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    feats = paddle.to_tensor(_mel(seed=5))
    dec = paddle.to_tensor(np.random.RandomState(6).randint(4, 256, (2, 8)))
    labels = paddle.to_tensor(
        np.random.RandomState(7).randint(4, 256, (2, 8)))
    optimizer = opt.AdamW(1e-2, parameters=m.parameters())
    losses = []
    for _ in range(4):
        loss, _ = m(feats, dec, labels=labels)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]
    # the fixed sinusoid table must stay fixed
    np.testing.assert_allclose(m.model.encoder_pos.weight.numpy(),
                               sinusoids(16, 64), atol=1e-6)


def test_eos_semantics():
    """eos_token_id=None DISABLES eos (decoder-only semantics); omitting
    it uses the config default — review r5: the two spellings used to
    collapse, silently stopping 'disabled' runs at the config eos."""
    paddle.seed(4)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    feats = paddle.to_tensor(_mel(seed=9))
    disabled = m.generate(feats, max_new_tokens=6,
                          eos_token_id=None).numpy()
    assert disabled.shape == (2, 6)   # never stops early, never pads
    forced = m.generate(feats, max_new_tokens=6,
                        eos_token_id=int(disabled[0, 0])).numpy()
    # row 0's first token is its eos: the row freezes to eos immediately
    assert (forced[0] == disabled[0, 0]).all()
    with pytest.raises(NotImplementedError, match="gelu"):
        WhisperConfig.tiny(activation_function="relu")


def test_frame_overflow_raises():
    paddle.seed(3)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    with pytest.raises(ValueError, match="max_source_positions"):
        m.model.encode(paddle.to_tensor(_mel(frames=64, seed=8)))


def test_beam_search_matches_transformers():
    """num_beams>1 on the Whisper enc-dec path: token-identical to HF beam
    generate (the tiny config carries no task-token forcing)."""
    hf = _tiny_hf()
    ours = whisper_from_hf(hf)
    feats = _mel(seed=12)
    seed_ids = np.full((2, 1), 1, np.int64)
    with torch.no_grad():
        # HF whisper counts max_new_tokens as TOTAL decoder length and
        # echoes the seed: [2, 6] including the start token
        ref = hf.generate(input_features=torch.from_numpy(feats),
                          decoder_input_ids=torch.from_numpy(seed_ids),
                          max_new_tokens=6, num_beams=2, do_sample=False,
                          length_penalty=1.0,
                          early_stopping=False).numpy()[:, 1:]
    got = ours.generate(paddle.to_tensor(feats), max_new_tokens=5,
                        num_beams=2).numpy()
    np.testing.assert_array_equal(got, ref)


def test_beam_k1_equals_greedy():
    paddle.seed(5)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    feats = paddle.to_tensor(_mel(seed=13))
    a = m.generate(feats, max_new_tokens=5, eos_token_id=None).numpy()
    b = m.generate(feats, max_new_tokens=5, eos_token_id=None,
                   num_beams=1).numpy()
    np.testing.assert_array_equal(a, b)
