"""Distributed checkpoint: sharded save, dedup, reshard-on-load.

Parity model: test/auto_parallel checkpoint tests
(semi_auto_parallel_checkpoint_dedup_tensor.py etc.) — saved-shard dedup
and load under a *different* placement than save.
"""
import os
import pickle

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    Metadata, get_checkpoint_metadata, load_state_dict, save_state_dict,
    wait_async_save)
from paddle_tpu.tensor_class import wrap


def _mesh(n, name="x"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _sharded(value, mesh, spec):
    return jax.device_put(jnp.asarray(value), NamedSharding(mesh, spec))


def test_roundtrip_plain_numpy(tmp_path):
    sd = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
          "step": np.int64(7)}
    save_state_dict(sd, str(tmp_path))
    target = {"w": paddle.to_tensor(np.zeros((3, 4), np.float32)),
              "step": paddle.to_tensor(np.int64(0))}
    load_state_dict(target, str(tmp_path))
    np.testing.assert_array_equal(target["w"].numpy(), sd["w"])
    assert int(target["step"].numpy()) == 7


def test_sharded_save_dedups_replicas(tmp_path):
    mesh = _mesh(4)
    w = _sharded(np.arange(8, dtype=np.float32), mesh, P())  # replicated x4
    save_state_dict({"w": wrap(w)}, str(tmp_path))
    md = get_checkpoint_metadata(str(tmp_path))
    assert len(md.state_dict_metadata["w"]) == 1  # one canonical shard
    shard_files = [p for p in tmp_path.iterdir() if p.suffix == ".npy"]
    assert len(shard_files) == 1


def test_reshard_on_load(tmp_path):
    """Save sharded over 4 devices, load sharded over 2 on a different dim."""
    mesh4 = _mesh(4)
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    w4 = _sharded(data, mesh4, P("x", None))  # row-sharded over 4
    save_state_dict({"w": wrap(w4)}, str(tmp_path))
    md = get_checkpoint_metadata(str(tmp_path))
    assert len(md.state_dict_metadata["w"]) == 4

    mesh2 = _mesh(2, "y")
    target = wrap(_sharded(np.zeros_like(data), mesh2, P(None, "y")))  # col-sharded
    sd = {"w": target}
    load_state_dict(sd, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(sd["w"]._array), data)
    # target sharding preserved
    assert sd["w"]._array.sharding.spec == P(None, "y")


def test_load_onto_bigger_degree(tmp_path):
    """2-way saved → 8-way loaded (degree change, the elastic-resume case)."""
    mesh2 = _mesh(2)
    data = np.arange(32, dtype=np.float32).reshape(16, 2)
    save_state_dict({"w": wrap(_sharded(data, mesh2, P("x", None)))},
                    str(tmp_path))
    mesh8 = _mesh(8)
    tgt = {"w": wrap(_sharded(np.zeros_like(data), mesh8, P("x", None)))}
    load_state_dict(tgt, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(tgt["w"]._array), data)


def test_dtype_cast_and_missing_key(tmp_path):
    save_state_dict({"w": np.ones((2, 2), np.float32)}, str(tmp_path))
    tgt = {"w": paddle.to_tensor(np.zeros((2, 2), np.float16))}
    load_state_dict(tgt, str(tmp_path))
    assert tgt["w"].numpy().dtype == np.float16
    with pytest.raises(KeyError):
        load_state_dict({"nope": paddle.to_tensor(np.zeros(1))}, str(tmp_path))


def test_async_save(tmp_path):
    sd = {"w": np.arange(4, dtype=np.float32)}
    save_state_dict(sd, str(tmp_path), async_save=True)
    wait_async_save()
    tgt = {"w": paddle.to_tensor(np.zeros(4, np.float32))}
    load_state_dict(tgt, str(tmp_path))
    np.testing.assert_array_equal(tgt["w"].numpy(), sd["w"])


def test_model_optimizer_roundtrip_hybrid(tmp_path):
    """End-to-end: FSDP-sharded Llama + AdamW states through save/load with
    a changed sharding degree."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    def build(sharding_degree):
        s = dist.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 8 // sharding_degree,
                            "sharding_degree": sharding_degree, "mp_degree": 1}
        s.sharding_configs = {"stage": 3}
        dist.fleet.init(is_collective=True, strategy=s)
        paddle.seed(11)
        model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
        model = dist.fleet.distributed_model(model)
        return model

    try:
        m1 = build(4)
        sd1 = m1.state_dict()
        ref = {k: v.numpy().copy() for k, v in sd1.items()}
        save_state_dict(sd1, str(tmp_path))

        dist.set_hybrid_communicate_group(None)
        m2 = build(2)  # different degree; params start from a different seed state
        paddle.seed(99)
        sd2 = m2.state_dict()
        load_state_dict(sd2, str(tmp_path))
        for k, v in sd2.items():
            np.testing.assert_array_equal(v.numpy(), ref[k], err_msg=k)
    finally:
        dist.set_hybrid_communicate_group(None)


def test_pipeline_checkpoint_across_pp_degree(tmp_path):
    """A pipeline model trained at pp=2 (hybrid mesh, mp2 x sharding2)
    checkpoints through the TOPOLOGY-STABLE item_state_dict and restores
    into a pp=1 rebuild of the same model — the train-at-pp-N /
    serve-at-pp-M workflow (ref: structured param names survive topology
    changes)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLMPipe,
                                         causal_lm_loss)

    cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False,
                           tie_word_embeddings=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 17))
    try:
        s = dist.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                            "sharding_degree": 2, "sep_degree": 1}
        dist.fleet.init(is_collective=True, strategy=s)
        paddle.seed(11)
        pipe = LlamaForCausalLMPipe(cfg)
        pp = dist.fleet.distributed_model(pipe)
        o = opt.SGD(0.05, parameters=pipe.parameters())
        loss_trained = float(np.asarray(pp.train_batch(
            [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])], o)))
        sd = pipe.item_state_dict()
        assert all(k.startswith("item_") for k in sd)
        save_state_dict(sd, str(tmp_path))  # sharded save off stage submeshes
    finally:
        dist.set_hybrid_communicate_group(None)

    # restore into a single-device pp=1 build (different partitioning AND
    # different weights)
    paddle.seed(99)
    pipe1 = LlamaForCausalLMPipe(cfg, num_stages=1)
    sd1 = pipe1.item_state_dict()
    assert set(sd1) == set(sd)  # stable keys across pp degrees
    load_state_dict(sd1, str(tmp_path))
    # DETACHED numpy copies so load_item_state_dict's assignment (raw-array
    # branch, dtype cast, sharding preservation) actually executes
    detached = {k: np.asarray(v._array) for k, v in sd1.items()}
    paddle.seed(7)
    pipe1 = LlamaForCausalLMPipe(cfg, num_stages=1)  # fresh, different init
    pipe1.load_item_state_dict(detached)
    # shape mismatches are rejected, not silently installed
    bad = dict(detached)
    first = next(iter(bad))
    bad[first] = np.zeros((3, 3), np.float32)
    with pytest.raises(ValueError, match="shape mismatch"):
        pipe1.load_item_state_dict(bad)
    pipe1.load_item_state_dict(detached)
    # the restored pp=1 model computes a finite loss on the train batch
    out = pipe1(paddle.to_tensor(ids[:, :-1]))
    loss_restored = float(causal_lm_loss(
        out, paddle.to_tensor(ids[:, 1:])).numpy())
    assert np.isfinite(loss_restored) and loss_restored < loss_trained + 1.0
    # byte-level check: every restored tensor equals the trained one
    trained = {k: np.asarray(v._array) for k, v in sd.items()}
    for k, v in pipe1.item_state_dict().items():
        np.testing.assert_array_equal(np.asarray(v._array), trained[k],
                                      err_msg=k)
