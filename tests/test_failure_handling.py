"""Failure handling: progress watchdog + launch restart-from-checkpoint.

Parity model: the reference's comm-task watchdog (comm_task.h:127,
comm_task_manager.h:37 — timeout detection + desync dump + abort) and the
elastic restart loop (fleet/elastic/manager.py:125, launch controllers).
"""
import io
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle


def test_watchdog_detects_stall():
    from paddle_tpu.distributed.watchdog import Watchdog

    fired = []
    buf = io.StringIO()
    wd = Watchdog(timeout=0.3, poll_interval=0.05,
                  on_timeout=lambda w: fired.append(w), stream=buf)
    wd.start()
    time.sleep(1.0)  # no stamps → stall
    wd.stop()
    assert wd.fired and fired
    out = buf.getvalue()
    assert "NO PROGRESS" in out
    assert "watchdog start" in out          # stamp history dumped
    assert "Thread" in out or "thread" in out  # faulthandler stacks


def test_watchdog_quiet_under_progress():
    from paddle_tpu.distributed.watchdog import Watchdog

    buf = io.StringIO()
    wd = Watchdog(timeout=0.5, poll_interval=0.05, stream=buf)
    wd.start()
    for i in range(10):
        time.sleep(0.1)
        wd.stamp(f"step {i}")
    wd.stop()
    assert not wd.fired
    assert buf.getvalue() == ""


def test_watchdog_global_api():
    import paddle_tpu.distributed as dist

    wd = dist.enable_watchdog(timeout=30, abort=False)
    dist.watchdog_stamp("step 0")
    assert wd._history[-1][1] == "step 0"
    dist.disable_watchdog()


_WORKER = r'''
import os, pickle, sys, time
import numpy as np

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
incarnation = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
ckpt_dir = sys.argv[1]
crash_at = int(sys.argv[2])
total_steps = int(sys.argv[3])

from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.watchdog import Watchdog

host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
store = TCPStore(host, int(port), is_master=(rank == 0), world_size=world,
                 timeout=30)
store.barrier(f"boot{incarnation}")

wd = Watchdog(timeout=60, name=f"rank{rank}").start()

# deterministic "training": w += step value each step; checkpoint each step
ck = os.path.join(ckpt_dir, f"rank{rank}.pkl")
if os.path.exists(ck):
    with open(ck, "rb") as f:
        state = pickle.load(f)
else:
    state = {"step": 0, "w": 0.0}

# resume-step agreement: a crashed rank may hold an older checkpoint than a
# rank that was SIGTERMed later — everyone rolls back to the MIN step (the
# role of the dist-checkpoint global metadata)
store.set(f"resume_{incarnation}_{rank}", str(state["step"]).encode())
store.barrier(f"resume{incarnation}")
steps = [int(store.get(f"resume_{incarnation}_{r}", timeout=15))
         for r in range(world)]
agreed = min(steps)
if agreed != state["step"]:
    state = {"step": agreed, "w": float(sum(range(1, agreed + 1)))}
if incarnation > 0:
    print(f"rank {rank} RESUMED from step {agreed} "
          f"(incarnation {incarnation})", flush=True)

for step in range(state["step"], total_steps):
    state["w"] += float(step + 1)
    state["step"] = step + 1
    # crash-safe checkpoint: tmp + rename
    with open(ck + ".tmp", "wb") as f:
        pickle.dump(state, f)
    os.replace(ck + ".tmp", ck)
    wd.stamp(f"step {step}")
    store.barrier(f"step{incarnation}_{step}")
    if incarnation == 0 and rank == 1 and step + 1 == crash_at:
        print(f"rank {rank} CRASHING at step {step + 1}", flush=True)
        os._exit(17)

wd.stop()
print(f"rank {rank} DONE w={state['w']} step={state['step']}", flush=True)
'''


@pytest.mark.slow
def test_launch_restart_resumes_from_checkpoint(tmp_path):
    """Kill one rank mid-run; the launcher detects the death, tears the
    job down, relaunches, and workers resume from their checkpoints
    (VERDICT r2 item 6 done-criterion)."""
    import socket

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    logd = tmp_path / "logs"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    total_steps, crash_at = 5, 2
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--max_restarts", "1", "--log_dir", str(logd),
         str(worker), str(ckpt), str(crash_at), str(total_steps)],
        cwd="/root/repo", capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restart 1/1" in r.stdout

    # both ranks finished all steps with the exact uninterrupted sum
    import pickle

    expect_w = float(sum(range(1, total_steps + 1)))
    for rank in range(2):
        with open(ckpt / f"rank{rank}.pkl", "rb") as f:
            state = pickle.load(f)
        assert state["step"] == total_steps
        assert state["w"] == expect_w, (rank, state)
    # the resumed incarnation logged its recovery
    logs = "".join(p.read_text() for p in logd.iterdir())
    assert "RESUMED from step" in logs
    assert "CRASHING at step 2" in logs


# ---------------------------------------------------------------------------
# elastic membership (fleet/elastic/manager.py:125 parity — VERDICT r3 #6)
# ---------------------------------------------------------------------------

def test_elastic_lease_and_peer_watch():
    """Leases: fresh heartbeats keep a rank alive; stopping the heartbeat
    lapses its lease; a peer's monitor observes the loss via on_change."""
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.distributed.elastic import ElasticManager

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    m0 = ElasticManager(master, rank=0, world_size=2, ttl=1.2,
                        job_id="t").register()
    client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=2)
    m1 = ElasticManager(client, rank=1, world_size=2, ttl=1.2,
                        job_id="t").register()
    time.sleep(0.3)
    assert m0.alive_ranks() == {0, 1}
    assert m0.stale_ranks() == []

    lost_events = []
    m0.monitor(on_change=lambda lost: lost_events.append(lost),
               interval=0.2)
    m1.stop_heartbeat()           # rank 1 "hangs": alive but not beating
    deadline = time.time() + 6.0
    while not lost_events and time.time() < deadline:
        time.sleep(0.1)
    assert lost_events and lost_events[0] == {1}
    assert m0.stale_ranks() == [1]        # launcher-side view agrees
    assert 1 not in m0.alive_ranks()
    # never-registered ranks are NOT stale (startup grace)
    m_big = ElasticManager(master, rank=0, world_size=4, ttl=1.2, job_id="t")
    assert 3 not in m_big.stale_ranks()
    assert 3 in m_big.stale_ranks(registered_only=False)
    m0.close(); m1.close()


_ELASTIC_WORKER = r'''
import os, sys, time
out_dir = sys.argv[1]
rank = int(os.environ["PADDLE_TRAINER_ID"])
incarnation = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))

import jax
jax.config.update("jax_platforms", "cpu")
from paddle_tpu.distributed.elastic import start_elastic

mgr = start_elastic(job_id="ejob")
assert mgr is not None, "PADDLE_ELASTIC_STORE must be set by the launcher"

ckpt = os.path.join(out_dir, f"ckpt_{rank}.txt")
start = int(open(ckpt).read()) + 1 if os.path.exists(ckpt) else 0
for step in range(start, 6):
    if rank == 1 and incarnation == 0 and step == 2:
        # simulated HANG: stop heartbeating but stay alive — only the
        # membership watch (lease lapse), not an exit code, can catch this
        mgr.stop_heartbeat()
        time.sleep(3600)
    with open(ckpt, "w") as f:
        f.write(str(step))
    time.sleep(0.05)
with open(os.path.join(out_dir, f"done_{rank}_{incarnation}.txt"), "w") as f:
    f.write(f"resumed_at={start}")
print(f"rank {rank} incarnation {incarnation} done (resumed at {start})",
      flush=True)
'''


@pytest.mark.slow
def test_elastic_hang_detected_and_restart_resumes(tmp_path):
    """E2E membership: one of two launched workers hangs (stops
    heartbeating without exiting). The launcher's elastic watch detects the
    lapsed lease, fails the incarnation, relaunches BOTH workers, and they
    resume from their checkpoints and finish."""
    worker = tmp_path / "worker.py"
    worker.write_text(_ELASTIC_WORKER)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--max_restarts", "1",
         "--elastic_ttl", "2.0", "--job_id", "ejob",
         "--log_dir", str(tmp_path / "logs"), str(worker), str(tmp_path)],
        cwd="/root/repo", capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "lease(s) [1] lapsed" in r.stdout, r.stdout
    # both ranks completed in incarnation 1
    for rank in range(2):
        done = tmp_path / f"done_{rank}_1.txt"
        assert done.exists(), r.stdout
    # rank 1 resumed from its checkpoint (step > 0), not from scratch
    resumed = (tmp_path / "done_1_1.txt").read_text()
    assert resumed == "resumed_at=2", resumed


def test_elastic_clean_exit_is_not_membership_loss():
    """A rank that finishes and deregisters (mark_done) must not trigger
    peers' loss detection or the launcher's stale view — completion is not
    a hang (review: staggered finish times must not burn restarts)."""
    from paddle_tpu.distributed.store import TCPStore
    from paddle_tpu.distributed.elastic import ElasticManager

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=2)
    m0 = ElasticManager(master, rank=0, world_size=2, ttl=0.9,
                        job_id="c").register()
    client = TCPStore("127.0.0.1", master.port, is_master=False, world_size=2)
    m1 = ElasticManager(client, rank=1, world_size=2, ttl=0.9,
                        job_id="c").register()
    lost_events = []
    m0.monitor(on_change=lambda lost: lost_events.append(lost), interval=0.15)
    time.sleep(0.4)
    m1.mark_done()               # clean exit: lease will lapse, done marker set
    time.sleep(2.5)              # > ttl: lease definitely lapsed by now
    assert lost_events == []     # not reported lost
    assert m0.stale_ranks() == []  # launcher view agrees
    m0.close(); m1.close()
    master.close(); client.close()


# ---------------------------------------------------------------------------
# elastic close-the-loop (VERDICT r4 item 5): real model, save-on-signal,
# membership-driven scale-in with reshard-on-load, loss continuity
# ---------------------------------------------------------------------------

_ELASTIC_TRAIN_WORKER = r'''
import glob, os, pickle, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import SGD
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.elastic import on_restart_signal

out, crash_at, total = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
ckpt_every = int(sys.argv[4])
rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
inc = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
store = TCPStore(host, int(port), is_master=(rank == 0), world_size=world,
                 timeout=60)
store.barrier(f"boot{inc}")

paddle.seed(0)  # every incarnation builds the SAME init before any load
model = nn.Linear(4, 1)
opt = SGD(learning_rate=0.05, parameters=model.parameters())

# resume from the NEWEST checkpoint across ALL former ranks: weights are
# replicated, so any newest copy is valid at any world size, and the new
# (possibly smaller) world re-partitions the data below — reshard-on-load
step0, best = 0, None
for f in sorted(glob.glob(os.path.join(out, "ck_*.pkl"))):
    with open(f, "rb") as fh:
        st = pickle.load(fh)
    if best is None or st["step"] > best["step"]:
        best = st
if best is not None:
    own = model.state_dict()
    for k, v in best["w"].items():
        own[k].set_value(paddle.to_tensor(v))
    step0 = best["step"]
    print(f"rank {rank} resumed from step {step0} at world {world}", flush=True)

cur = {"step": step0}
my_ck = os.path.join(out, f"ck_{rank}.pkl")

def save():
    cur["w"] = {k: np.asarray(v._array) for k, v in model.state_dict().items()}
    with open(my_ck + ".tmp", "wb") as f:
        pickle.dump(cur, f)
    os.replace(my_ck + ".tmp", my_ck)
    print(f"rank {rank} saved step {cur['step']}", flush=True)

# launcher SIGTERM => checkpoint newest step, exit; shield() below keeps
# the optimizer-update + step-counter span atomic wrt that save
guard = on_restart_signal(save)

rng = np.random.RandomState(42)
X = rng.randn(64, 4).astype("float32")
W_TRUE = np.array([[3.0], [-1.0], [2.0], [0.5]], np.float32)
Y = X @ W_TRUE - 2.0

for step in range(step0, total):
    if rank == 1 and inc == 0 and step == crash_at:
        print(f"rank {rank} CRASHING at step {step}", flush=True)
        os._exit(7)
    shard = np.array_split(np.arange(64), world)[rank]
    x, y = paddle.to_tensor(X[shard]), paddle.to_tensor(Y[shard])
    diff = model(x) - y
    loss = (diff * diff).mean()
    loss.backward()
    # grad allreduce over the TCPStore (eager dp on the CPU test rig)
    grads = {k: p.grad.numpy() for k, p in
             zip(("w", "b"), model.parameters())}
    store.set(f"g{inc}_{step}_{rank}", pickle.dumps(grads))
    acc = None
    for r in range(world):
        g = pickle.loads(store.get(f"g{inc}_{step}_{r}", timeout=60))
        acc = g if acc is None else {k: acc[k] + g[k] for k in acc}
    with guard.shield():
        for (k, p) in zip(("w", "b"), model.parameters()):
            p.grad.set_value(paddle.to_tensor(acc[k] / world))
        opt.step()
        opt.clear_grad()
        cur["step"] = step + 1
    print(f"rank {rank} inc {inc} step {step + 1} loss "
          f"{float(loss.numpy()):.6f}", flush=True)
    if (step + 1) % ckpt_every == 0:
        save()

print(f"rank {rank} DONE at step {cur['step']}", flush=True)
'''


@pytest.mark.slow
def test_elastic_scale_in_resumes_model_training(tmp_path):
    """Kill one worker of a 2-process REAL-MODEL run: the launcher detects
    the death, scales the world in (--np_range 1:2), and the survivor
    resumes from the save-on-signal checkpoint with the loss continuing
    where it left off (VERDICT r4 item 5 done-criterion)."""
    import re
    import socket

    worker = tmp_path / "worker.py"
    worker.write_text(_ELASTIC_TRAIN_WORKER)
    logd = tmp_path / "logs"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    crash_at, total, ckpt_every = 3, 20, 100  # periodic saves never fire:
    # the resume step can only come from the SIGTERM save-on-signal path
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--max_restarts", "1", "--np_range", "1:2",
         "--log_dir", str(logd), "--job_id", "scalein",
         str(worker), str(tmp_path), str(crash_at), str(total),
         str(ckpt_every)],
        cwd="/root/repo", capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    assert "elastic scale-in 2 -> 1" in r.stdout, r.stdout

    logs = {p.name: p.read_text() for p in logd.iterdir()}
    all_logs = "".join(logs.values())
    # rank 0 was SIGTERMed mid-step and saved the exact completed-step count
    assert f"rank 0 saved step {crash_at}" in all_logs
    # the survivor resumed at the signal-saved step as a world of ONE
    assert f"resumed from step {crash_at} at world 1" in all_logs
    assert f"DONE at step {total}" in all_logs

    # loss continuity: the first post-restart loss continues the descent —
    # below the first incarnation's initial loss, and no worse than the
    # last pre-crash loss (allowing the world-2 -> world-1 batch change)
    losses0 = [float(m) for m in re.findall(
        r"rank 0 inc 0 step \d+ loss ([0-9.]+)", all_logs)]
    losses1 = [float(m) for m in re.findall(
        r"rank 0 inc 1 step \d+ loss ([0-9.]+)", all_logs)]
    assert len(losses0) == crash_at and losses1, (losses0, losses1)
    assert losses1[0] < losses0[0] * 0.9
    assert losses1[0] < losses0[-1] * 1.5
    assert losses1[-1] < losses0[0] * 0.2  # kept converging after resume


def test_restart_guard_shield_defers_save(monkeypatch):
    """A SIGTERM landing inside a shield() span must defer the checkpoint
    to the span exit (consistent state), not save mid-update; outside a
    span it saves immediately."""
    from paddle_tpu.distributed import elastic

    events = []
    monkeypatch.setattr(elastic.os, "_exit",
                        lambda code: events.append(("exit", code)))

    g = elastic.RestartGuard(lambda: events.append(("save",)), exit_code=5)
    with g.shield():
        g._handler(15, None)          # landed mid-update: deferred
        assert events == []           # nothing saved inside the span
    assert events == [("save",), ("exit", 5)]

    events.clear()
    g2 = elastic.RestartGuard(lambda: events.append(("save",)), exit_code=5)
    g2._handler(15, None)             # between spans: immediate
    assert events == [("save",), ("exit", 5)]
