"""Failure handling: progress watchdog + launch restart-from-checkpoint.

Parity model: the reference's comm-task watchdog (comm_task.h:127,
comm_task_manager.h:37 — timeout detection + desync dump + abort) and the
elastic restart loop (fleet/elastic/manager.py:125, launch controllers).
"""
import io
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle


def test_watchdog_detects_stall():
    from paddle_tpu.distributed.watchdog import Watchdog

    fired = []
    buf = io.StringIO()
    wd = Watchdog(timeout=0.3, poll_interval=0.05,
                  on_timeout=lambda w: fired.append(w), stream=buf)
    wd.start()
    time.sleep(1.0)  # no stamps → stall
    wd.stop()
    assert wd.fired and fired
    out = buf.getvalue()
    assert "NO PROGRESS" in out
    assert "watchdog start" in out          # stamp history dumped
    assert "Thread" in out or "thread" in out  # faulthandler stacks


def test_watchdog_quiet_under_progress():
    from paddle_tpu.distributed.watchdog import Watchdog

    buf = io.StringIO()
    wd = Watchdog(timeout=0.5, poll_interval=0.05, stream=buf)
    wd.start()
    for i in range(10):
        time.sleep(0.1)
        wd.stamp(f"step {i}")
    wd.stop()
    assert not wd.fired
    assert buf.getvalue() == ""


def test_watchdog_global_api():
    import paddle_tpu.distributed as dist

    wd = dist.enable_watchdog(timeout=30, abort=False)
    dist.watchdog_stamp("step 0")
    assert wd._history[-1][1] == "step 0"
    dist.disable_watchdog()


_WORKER = r'''
import os, pickle, sys, time
import numpy as np

rank = int(os.environ["PADDLE_TRAINER_ID"])
world = int(os.environ["PADDLE_TRAINERS_NUM"])
incarnation = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
ckpt_dir = sys.argv[1]
crash_at = int(sys.argv[2])
total_steps = int(sys.argv[3])

from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.watchdog import Watchdog

host, port = os.environ["PADDLE_MASTER"].rsplit(":", 1)
store = TCPStore(host, int(port), is_master=(rank == 0), world_size=world,
                 timeout=30)
store.barrier(f"boot{incarnation}")

wd = Watchdog(timeout=60, name=f"rank{rank}").start()

# deterministic "training": w += step value each step; checkpoint each step
ck = os.path.join(ckpt_dir, f"rank{rank}.pkl")
if os.path.exists(ck):
    with open(ck, "rb") as f:
        state = pickle.load(f)
else:
    state = {"step": 0, "w": 0.0}

# resume-step agreement: a crashed rank may hold an older checkpoint than a
# rank that was SIGTERMed later — everyone rolls back to the MIN step (the
# role of the dist-checkpoint global metadata)
store.set(f"resume_{incarnation}_{rank}", str(state["step"]).encode())
store.barrier(f"resume{incarnation}")
steps = [int(store.get(f"resume_{incarnation}_{r}", timeout=15))
         for r in range(world)]
agreed = min(steps)
if agreed != state["step"]:
    state = {"step": agreed, "w": float(sum(range(1, agreed + 1)))}
if incarnation > 0:
    print(f"rank {rank} RESUMED from step {agreed} "
          f"(incarnation {incarnation})", flush=True)

for step in range(state["step"], total_steps):
    state["w"] += float(step + 1)
    state["step"] = step + 1
    # crash-safe checkpoint: tmp + rename
    with open(ck + ".tmp", "wb") as f:
        pickle.dump(state, f)
    os.replace(ck + ".tmp", ck)
    wd.stamp(f"step {step}")
    store.barrier(f"step{incarnation}_{step}")
    if incarnation == 0 and rank == 1 and step + 1 == crash_at:
        print(f"rank {rank} CRASHING at step {step + 1}", flush=True)
        os._exit(17)

wd.stop()
print(f"rank {rank} DONE w={state['w']} step={state['step']}", flush=True)
'''


@pytest.mark.slow
def test_launch_restart_resumes_from_checkpoint(tmp_path):
    """Kill one rank mid-run; the launcher detects the death, tears the
    job down, relaunches, and workers resume from their checkpoints
    (VERDICT r2 item 6 done-criterion)."""
    import socket

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    logd = tmp_path / "logs"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    total_steps, crash_at = 5, 2
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--max_restarts", "1", "--log_dir", str(logd),
         str(worker), str(ckpt), str(crash_at), str(total_steps)],
        cwd="/root/repo", capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo"})
    assert r.returncode == 0, r.stdout + r.stderr
    assert "restart 1/1" in r.stdout

    # both ranks finished all steps with the exact uninterrupted sum
    import pickle

    expect_w = float(sum(range(1, total_steps + 1)))
    for rank in range(2):
        with open(ckpt / f"rank{rank}.pkl", "rb") as f:
            state = pickle.load(f)
        assert state["step"] == total_steps
        assert state["w"] == expect_w, (rank, state)
    # the resumed incarnation logged its recovery
    logs = "".join(p.read_text() for p in logd.iterdir())
    assert "RESUMED from step" in logs
    assert "CRASHING at step 2" in logs
