"""Optimizer / LR scheduler / AMP / GradScaler tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt


def _train_quadratic(optimizer_fn, steps=120):
    """Minimise (w - 3)^2; return final w."""
    w = paddle.to_tensor([0.0], stop_gradient=False)
    o = optimizer_fn([w])
    for _ in range(steps):
        loss = ((w - 3.0) ** 2).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    return float(w.numpy()[0])


def test_sgd_converges():
    assert _train_quadratic(lambda p: opt.SGD(0.1, parameters=p)) == pytest.approx(3.0, abs=1e-3)


def test_momentum_converges():
    assert _train_quadratic(lambda p: opt.Momentum(0.05, 0.9, parameters=p)) == pytest.approx(3.0, abs=1e-2)


def test_adam_converges():
    assert _train_quadratic(lambda p: opt.Adam(0.2, parameters=p)) == pytest.approx(3.0, abs=1e-2)


def test_adamw_converges():
    assert _train_quadratic(lambda p: opt.AdamW(0.2, parameters=p, weight_decay=0.0)) == pytest.approx(3.0, abs=1e-2)


def test_rmsprop_lamb_lion_run():
    for name, f in (("rmsprop", lambda p: opt.RMSProp(0.05, parameters=p)),
                    ("lamb", lambda p: opt.Lamb(0.1, parameters=p)),
                    ("lion", lambda p: opt.Lion(0.1, parameters=p)),
                    ("adagrad", lambda p: opt.Adagrad(0.5, parameters=p)),
                    ("adamax", lambda p: opt.Adamax(0.3, parameters=p))):
        w = _train_quadratic(f, steps=150)
        assert abs(w - 3.0) < 1.5, f"{name}: {w}"


def test_adadelta_makes_progress():
    # adadelta's accumulator design makes early steps tiny — check monotone
    # progress rather than convergence (matches its known behavior)
    w = _train_quadratic(lambda p: opt.Adadelta(1.0, parameters=p), steps=150)
    assert 0.2 < w < 3.5


def test_adamw_decoupled_decay():
    # pure decay, zero grad → w shrinks by lr*wd each step
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = opt.AdamW(0.1, parameters=[w], weight_decay=0.5)
    loss = (w * 0.0).sum()
    loss.backward()
    o.step()
    assert float(w.numpy()[0]) == pytest.approx(1.0 * (1 - 0.1 * 0.5), rel=1e-5)


def test_master_weights_bf16():
    w = paddle.to_tensor(np.full(4, 0.0, np.float32), stop_gradient=False).astype("bfloat16")
    w = paddle.Parameter.from_tensor(w)
    o = opt.Adam(learning_rate=0.01, parameters=[w])
    for _ in range(5):
        ((w.astype("float32") - 1.0) ** 2).sum().backward()
        o.step()
        o.clear_grad()
    # master copy exists and is f32
    st = o._eager_state["param_states"]
    key = next(iter(st))
    assert "master" in st[key]
    assert str(st[key]["master"].dtype) == "float32"


def test_grad_clip_global_norm():
    w = paddle.to_tensor([3.0, 4.0], stop_gradient=False)
    o = opt.SGD(1.0, parameters=[w], grad_clip=opt.ClipGradByGlobalNorm(1.0))
    (w * paddle.to_tensor([3.0, 4.0])).sum().backward()  # grad = [3, 4], norm 5
    o.step()
    # clipped grad = [0.6, 0.8]
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.6, 4.0 - 0.8], rtol=1e-4)


def test_lr_schedulers():
    s = opt.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(round(s.get_lr(), 6))
        s.step()
    assert lrs == [0.1, 0.1, 0.05, 0.05, 0.025]

    c = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
    assert c.lr_at(0) == pytest.approx(1.0)
    assert c.lr_at(10) == pytest.approx(0.0, abs=1e-6)

    w = opt.lr.LinearWarmup(0.5, warmup_steps=10, start_lr=0.0, end_lr=0.5)
    assert w.lr_at(5) == pytest.approx(0.25)

    n = opt.lr.CosineAnnealingWithWarmupDecay(1e-3, 1e-5, 10, 100)
    assert n.lr_at(0) == 0.0
    assert n.lr_at(10) == pytest.approx(1e-3)
    assert n.lr_at(100) == pytest.approx(1e-5)


def test_optimizer_with_scheduler():
    sched = opt.lr.StepDecay(0.1, step_size=1, gamma=0.1)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = opt.SGD(sched, parameters=[w])
    assert o.get_lr() == pytest.approx(0.1)
    sched.step()
    assert o.get_lr() == pytest.approx(0.01)


def test_auto_cast_o1():
    with paddle.amp.auto_cast(level="O1"):
        a = paddle.randn([4, 4])
        b = paddle.randn([4, 4])
        c = a @ b  # white list op → bf16
        assert c.dtype == paddle.bfloat16
        s = paddle.nn.functional.softmax(c)  # black list → f32
        assert s.dtype == paddle.float32
    c2 = a @ b
    assert c2.dtype == paddle.float32


def test_auto_cast_custom_lists():
    with paddle.amp.auto_cast(custom_black_list=["matmul"]):
        a = paddle.randn([2, 2])
        assert (a @ a).dtype == paddle.float32


def test_amp_decorate_o2():
    model = nn.Sequential(nn.Linear(4, 4), nn.LayerNorm(4))
    paddle.amp.decorate(model, level="O2", dtype="bfloat16")
    assert model[0].weight.dtype == paddle.bfloat16
    assert model[1].weight.dtype == paddle.float32  # excluded layer


def test_grad_scaler_flow():
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = opt.SGD(0.1, parameters=[w])
    loss = (w * 2).sum()
    scaled = scaler.scale(loss)
    assert float(scaled.numpy()) == pytest.approx(4.0)
    scaled.backward()
    scaler.step(o)  # unscales then steps
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 2.0], rtol=1e-5)


def test_grad_scaler_skips_on_inf():
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = opt.SGD(0.1, parameters=[w])
    (w * float("inf")).sum().backward()
    scaler.step(o)
    np.testing.assert_allclose(w.numpy(), [1.0])  # step skipped
    assert scaler._scale < 2.0  # scale decreased


def test_gradscaler_no_double_unscale():
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
    w = paddle.to_tensor([1.0], stop_gradient=False)
    o = opt.SGD(0.1, parameters=[w])
    scaler.scale((w * 2).sum()).backward()
    scaler.unscale_(o)   # manual unscale (clip workflow)
    g1 = float(w.grad.numpy()[0])
    scaler.step(o)       # must NOT unscale again
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * g1], rtol=1e-5)
    assert g1 == pytest.approx(2.0)


def test_adamw_decay_param_fun():
    wa = paddle.to_tensor([1.0], stop_gradient=False); wa.name = "linear.weight"
    wb = paddle.to_tensor([1.0], stop_gradient=False); wb.name = "norm.bias"
    o = opt.AdamW(0.1, parameters=[wa, wb], weight_decay=0.5,
                  apply_decay_param_fun=lambda n: "bias" not in n)
    ((wa * 0.0) + (wb * 0.0)).sum().backward()
    o.step()
    assert float(wa.numpy()[0]) < 1.0     # decayed
    assert float(wb.numpy()[0]) == 1.0    # excluded from decay


def test_round3_optimizers_converge():
    """ASGD/RAdam/NAdam/Rprop each minimize a quadratic (eager path)."""
    import paddle_tpu.optimizer as opt

    for cls, kwargs in [(opt.ASGD, {"batch_num": 4}),
                        (opt.RAdam, {}), (opt.NAdam, {}),
                        (opt.Rprop, {})]:
        paddle.seed(0)
        w = paddle.to_tensor(np.array([5.0, -3.0], dtype="float32"),
                             stop_gradient=False)
        w_param = paddle.Parameter(w._array)
        o = cls(learning_rate=0.1, parameters=[w_param], **kwargs)
        for _ in range(150):
            loss = ((w_param - paddle.to_tensor(
                np.array([1.0, 2.0], dtype="float32"))) ** 2).sum()
            loss.backward()
            o.step()
            o.clear_grad()
        err = np.abs(w_param.numpy() - np.array([1.0, 2.0])).max()
        assert err < 0.3, f"{cls.__name__}: err {err}"


def test_round3_optimizers_jit_path():
    """The same optimizers work through the functional TrainStep path."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    for cls in [opt.ASGD, opt.RAdam, opt.NAdam]:
        paddle.seed(0)
        net = nn.Linear(4, 1)
        o = cls(learning_rate=0.05, parameters=net.parameters())
        step = paddle.jit.train_step(
            net, lambda m, x, y: ((m(x) - y) ** 2).mean(), o)
        x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
        y = paddle.to_tensor(np.random.rand(8, 1).astype("float32"))
        losses = [float(step(x, y).numpy()) for _ in range(30)]
        assert losses[-1] < losses[0], f"{cls.__name__} did not descend"


def test_lbfgs_quadratic():
    """LBFGS drives a quadratic to optimum in a few closure steps."""
    import paddle_tpu.optimizer as opt

    w = paddle.Parameter(np.array([4.0, -2.0], dtype="float32"))
    o = opt.LBFGS(learning_rate=1.0, max_iter=10, parameters=[w])
    target = paddle.to_tensor(np.array([1.0, 2.0], dtype="float32"))

    def closure():
        o.clear_grad()
        loss = ((w - target) ** 2).sum()
        loss.backward()
        return loss

    for _ in range(3):
        o.step(closure)
    assert np.abs(w.numpy() - np.array([1.0, 2.0])).max() < 1e-3


def test_linear_lr_schedule():
    import paddle_tpu.optimizer as opt

    s = opt.lr.LinearLR(0.2, total_steps=4, start_factor=0.5, end_factor=1.0)
    seen = [round(s.get_lr(), 4)]
    for _ in range(4):
        s.step()
        seen.append(round(s.get_lr(), 4))
    assert seen[0] == 0.1 and seen[-1] == 0.2
    assert all(b >= a for a, b in zip(seen, seen[1:]))
