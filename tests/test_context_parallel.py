"""Context-parallel attention: ring + Ulysses vs full-attention reference.

Parity model: the reference has no CP (SURVEY.md §2.7); these tests follow
its distributed-test philosophy — loss/output parity between single-device
and parallel execution (test/legacy_test/test_dist_base.py semantics) — on
the virtual 8-device CPU mesh.
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from paddle_tpu.distributed.context_parallel import (
    ring_attention, ulysses_attention, sep_attention)


def _ref_attention(q, k, v, causal):
    qf, kf, vf = (x.astype(np.float64) for x in (q, k, v))
    if kf.shape[2] != qf.shape[2]:
        rep = qf.shape[2] // kf.shape[2]
        kf = np.repeat(kf, rep, axis=2)
        vf = np.repeat(vf, rep, axis=2)
    s = np.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if causal:
        s_q, s_k = s.shape[-2:]
        mask = np.arange(s_q)[:, None] >= np.arange(s_k)[None, :]
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, vf)


def _mesh(n, name="sep"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _sharded_fn(inner, mesh, axis, **kw):
    spec = P(None, axis, None, None)
    return shard_map(
        functools.partial(inner, axis_name=axis, **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_matches_reference(inner, causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 4, 16
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=causal)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_gqa(inner):
    rng = np.random.default_rng(1)
    b, s, h, hkv, d = 1, 32, 8, 4, 8
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, hkv, d), np.float32)
    v = rng.standard_normal((b, s, hkv, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=True)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_grads_match_reference(inner):
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 4, 8
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=True)

    def loss_cp(q, k, v):
        return (jnp.sin(fn(q, k, v)) ** 2).sum()

    def loss_ref(q, k, v):
        from paddle_tpu.distributed.context_parallel import _sdpa_core
        return (jnp.sin(_sdpa_core(q, k, v, True, 1.0 / d ** 0.5)) ** 2).sum()

    g_cp = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_cp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-5)


def test_ring_uneven_ring_size_eight():
    # full 8-way ring, seq not a multiple of 128 — exercises block masking
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 8 * 5, 2, 4
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(8)
    fn = _sharded_fn(ring_attention, mesh, "sep", causal=True)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_degree():
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 16, 2, 4  # h=2 not divisible by sep=4
    q = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(ulysses_attention, mesh, "sep")
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(fn)(q, q, q)


def test_sep_attention_via_fleet():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        rng = np.random.default_rng(5)
        b, s, h, d = 2, 32, 4, 8
        q = rng.standard_normal((b, s, h, d), np.float32)
        k = rng.standard_normal((b, s, h, d), np.float32)
        v = rng.standard_normal((b, s, h, d), np.float32)
        for mode in ("ring", "ulysses"):
            out = sep_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                paddle.to_tensor(v), causal=True, mode=mode)
            np.testing.assert_allclose(out.numpy(),
                                       _ref_attention(q, k, v, True),
                                       rtol=2e-4, atol=2e-5)
    finally:
        dist.set_hybrid_communicate_group(None)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_llama_train_step_with_cp(mode):
    """End-to-end: hybrid dp×sep train step with context-parallel attention
    produces the same loss as the single-device model (dist-test philosophy
    of test/legacy_test/test_dist_base.py: single vs parallel loss parity)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed.engine import parallelize
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    def build(sep_mode):
        paddle.seed(7)
        cfg = LlamaConfig.tiny(use_flash_attention=False, sep_mode=sep_mode)
        return LlamaForCausalLM(cfg), cfg

    rng = np.random.default_rng(6)
    ids = rng.integers(0, 512, (4, 33))
    x_np, y_np = ids[:, :-1], ids[:, 1:]

    # single-device reference loss
    model_ref, _ = build("allgather")
    loss_ref, _ = model_ref(paddle.to_tensor(x_np), labels=paddle.to_tensor(y_np))
    ref = float(loss_ref.numpy())

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        model, cfg = build(mode)
        model = dist.fleet.distributed_model(model)
        optimizer = opt.AdamW(1e-3, parameters=model.parameters())

        def loss_fn(m, x, y):
            loss, _ = m(x, labels=y)
            return loss

        step = parallelize(model, loss_fn, optimizer)
        loss = step(paddle.to_tensor(x_np), paddle.to_tensor(y_np))
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=2e-4)
    finally:
        dist.set_hybrid_communicate_group(None)
