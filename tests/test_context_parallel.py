"""Context-parallel attention: ring + Ulysses vs full-attention reference.

Parity model: the reference has no CP (SURVEY.md §2.7); these tests follow
its distributed-test philosophy — loss/output parity between single-device
and parallel execution (test/legacy_test/test_dist_base.py semantics) — on
the virtual 8-device CPU mesh.
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from paddle_tpu.distributed.context_parallel import (
    ring_attention, ulysses_attention, sep_attention)


def _ref_attention(q, k, v, causal, window=None):
    qf, kf, vf = (x.astype(np.float64) for x in (q, k, v))
    if kf.shape[2] != qf.shape[2]:
        rep = qf.shape[2] // kf.shape[2]
        kf = np.repeat(kf, rep, axis=2)
        vf = np.repeat(vf, rep, axis=2)
    s = np.einsum("bqhd,bkhd->bhqk", qf, kf) / np.sqrt(q.shape[-1])
    if causal:
        s_q, s_k = s.shape[-2:]
        diff = np.arange(s_q)[:, None] - np.arange(s_k)[None, :]
        mask = diff >= 0
        if window is not None:
            mask &= diff < window
        s = np.where(mask, s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, vf)


def _mesh(n, name="sep"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _sharded_fn(inner, mesh, axis, **kw):
    spec = P(None, axis, None, None)
    # check_vma=False: the splash ring runs pallas_call inside shard_map,
    # which jax only permits with the vma checker off
    return shard_map(
        functools.partial(inner, axis_name=axis, **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_matches_reference(inner, causal):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 64, 4, 16
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=causal)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_gqa(inner):
    rng = np.random.default_rng(1)
    b, s, h, hkv, d = 1, 32, 8, 4, 8
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, hkv, d), np.float32)
    v = rng.standard_normal((b, s, hkv, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=True)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_grads_match_reference(inner):
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 32, 4, 8
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=True)

    def loss_cp(q, k, v):
        return (jnp.sin(fn(q, k, v)) ** 2).sum()

    def loss_ref(q, k, v):
        from paddle_tpu.distributed.context_parallel import _sdpa_core
        return (jnp.sin(_sdpa_core(q, k, v, True, 1.0 / d ** 0.5)) ** 2).sum()

    g_cp = jax.jit(jax.grad(loss_cp, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b_ in zip(g_cp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("window", [5, 16, 40])
@pytest.mark.parametrize("inner", [ring_attention, ulysses_attention])
def test_cp_sliding_window(inner, window):
    """Mistral-style sliding window under CP (VERDICT r4 weak #3): band
    masking uses GLOBAL positions; windows smaller than a block, equal to
    a block, and spanning blocks all match the full-attention reference."""
    rng = np.random.default_rng(7)
    b, s, h, d = 1, 64, 4, 8
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(inner, mesh, "sep", causal=True, window=window)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), _ref_attention(q, k, v, True, window=window),
        rtol=2e-4, atol=2e-5)


def test_window_requires_causal():
    from paddle_tpu.distributed.context_parallel import _live_hops

    rng = np.random.default_rng(8)
    q = rng.standard_normal((1, 16, 2, 4), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(ring_attention, mesh, "sep", causal=False, window=8)
    with pytest.raises(ValueError, match="causal"):
        jax.jit(fn)(q, q, q)
    # static hop-skip accounting: with block len 128, a 128-window needs
    # 2 hops (diagonal + one back), a 256-window 3, a full-seq window all n
    assert _live_hops(8, 128, True, 128) == 2
    # w=129 reaches back exactly 128 = one block: still 2 hops; 130 is the
    # first window that can cross into a second block back
    assert _live_hops(8, 128, True, 129) == 2
    assert _live_hops(8, 128, True, 130) == 3
    assert _live_hops(8, 128, True, 256) == 3
    assert _live_hops(8, 128, True, None) == 8
    assert _live_hops(4, 128, True, 10_000) == 4
    assert _live_hops(8, 128, True, 1) == 1  # self-attention only


class TestRingSplash:
    """Ring attention with the Pallas splash kernel per hop (VERDICT r4
    item 3 / SURVEY §7 step 9 "Pallas flash + ppermute"), CPU-interpret
    parity vs the einsum path and the full-attention reference. Shapes
    honor splash tiling: local seq and head_dim multiples of 128."""

    @staticmethod
    def _qkv(rng, b, s, h, hkv, d):
        q = rng.standard_normal((b, s, h, d), np.float32)
        k = rng.standard_normal((b, s, hkv, d), np.float32)
        v = rng.standard_normal((b, s, hkv, d), np.float32)
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_splash_matches_reference(self, causal):
        rng = np.random.default_rng(9)
        q, k, v = self._qkv(rng, 1, 512, 2, 2, 128)
        mesh = _mesh(4)
        fn = _sharded_fn(ring_attention, mesh, "sep", causal=causal,
                         impl="splash", interpret=True)
        out = jax.jit(fn)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), _ref_attention(q, k, v, causal),
            rtol=2e-3, atol=2e-4)

    def test_splash_gqa_window(self):
        """GQA (kv stays unexpanded through the ring) + sliding window
        (LocalMask per hop, out-of-band hops skipped statically)."""
        rng = np.random.default_rng(10)
        q, k, v = self._qkv(rng, 1, 512, 4, 2, 128)
        mesh = _mesh(4)
        for window in (96, 128, 200):
            fn = _sharded_fn(ring_attention, mesh, "sep", causal=True,
                             window=window, impl="splash", interpret=True)
            out = jax.jit(fn)(q, k, v)
            np.testing.assert_allclose(
                np.asarray(out), _ref_attention(q, k, v, True, window=window),
                rtol=2e-3, atol=2e-4)

    def test_splash_grads_match_einsum(self, tmp_path):
        """The custom VJP recomputes through the einsum ring; grads must
        match differentiating the einsum path directly (and hence the
        reference — test_cp_grads_match_reference covers that leg).

        Runs in a FRESH subprocess: XLA's CPU collective runtime carries
        in-process rendezvous state (rendezvous.h "id < num_threads"
        CHECK) that makes the splash-VJP collective-permute flaky when
        earlier tests in the same process used collectives on other mesh
        shapes — a CPU-runtime quirk, not a kernel bug (TPU unaffected;
        the fwd splash legs and the einsum grad in-process both pass)."""
        import os
        import subprocess
        import sys

        script = tmp_path / "grad_parity.py"
        script.write_text(r'''
import jax
jax.config.update("jax_platforms", "cpu")
import functools
import numpy as np
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P
from paddle_tpu.distributed.context_parallel import ring_attention

rng = np.random.default_rng(11)
q = rng.standard_normal((1, 1024, 2, 128), np.float32)
k = rng.standard_normal((1, 1024, 1, 128), np.float32)
v = rng.standard_normal((1, 1024, 1, 128), np.float32)
mesh = Mesh(np.array(jax.devices()[:8]), ("sep",))
spec = P(None, "sep", None, None)

def sharded(impl, interpret):
    return shard_map(
        functools.partial(ring_attention, axis_name="sep", causal=True,
                          window=160, impl=impl, interpret=interpret),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)

def loss(fn):
    return lambda q, k, v: (jnp.sin(fn(q, k, v)) ** 2).sum()

g_s = jax.jit(jax.grad(loss(sharded("splash", True)), argnums=(0, 1, 2)))(q, k, v)
g_e = jax.jit(jax.grad(loss(sharded("einsum", False)), argnums=(0, 1, 2)))(q, k, v)
for a, b in zip(g_s, g_e):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-4, atol=1e-5)
print("GRAD PARITY OK")
''')
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, str(script)], capture_output=True, text=True,
            timeout=600, cwd=repo,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": repo,
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
        assert r.returncode == 0 and "GRAD PARITY OK" in r.stdout, (
            r.stdout + "\n" + r.stderr[-2000:])

    def test_splash_impl_rejects_bad_shapes(self):
        rng = np.random.default_rng(12)
        q, k, v = self._qkv(rng, 1, 64, 2, 2, 16)  # 16-dim: not tileable
        mesh = _mesh(4)
        fn = _sharded_fn(ring_attention, mesh, "sep", causal=True,
                         impl="splash", interpret=True)
        with pytest.raises(ValueError, match="splash"):
            jax.jit(fn)(q, k, v)


def test_mistral_trains_under_sep():
    """Mistral (sliding_window set) trains under sequence parallelism —
    the exact combination VERDICT r4 weak #3 flagged as unsupported: loss
    parity vs the single-device model, finite grads after a step."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM

    def build(sep_mode):
        paddle.seed(17)
        cfg = MistralConfig.tiny(use_flash_attention=False, sep_mode=sep_mode)
        assert cfg.sliding_window is not None
        return MistralForCausalLM(cfg)

    rng = np.random.default_rng(13)
    ids = rng.integers(0, 512, (4, 65))
    x_np, y_np = ids[:, :-1], ids[:, 1:]

    model_ref = build("allgather")
    loss_ref, _ = model_ref(paddle.to_tensor(x_np),
                            labels=paddle.to_tensor(y_np))
    ref = float(loss_ref.numpy())

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        model = build("ring")
        model = dist.fleet.distributed_model(model)
        loss, _ = model(paddle.to_tensor(x_np), labels=paddle.to_tensor(y_np))
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=2e-4)
        optimizer = opt.AdamW(1e-3, parameters=model.parameters())
        loss.backward()
        optimizer.step()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._array)))
    finally:
        dist.set_hybrid_communicate_group(None)


def _mla_ref(q, c_kv, k_pe, w3, dn):
    """Expanded MLA attention in f64: kv = c_kv·w3, k = [k_nope ‖ k_pe]."""
    qf = q.astype(np.float64)
    kv = np.einsum("bsr,rhd->bshd", c_kv.astype(np.float64),
                   w3.astype(np.float64))
    B, S, H, _ = kv.shape
    dr = q.shape[-1] - dn
    k = np.concatenate(
        [kv[..., :dn],
         np.broadcast_to(k_pe.astype(np.float64)[:, :, None, :],
                         (B, S, H, dr))], -1)
    v = kv[..., dn:]
    s = np.einsum("bqhd,bkhd->bhqk", qf, k) / np.sqrt(q.shape[-1])
    mask = np.arange(S)[:, None] >= np.arange(S)[None, :]
    s = np.where(mask[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


def _mla_args(seed=23, B=2, S=32, H=4, dn=16, dr=8, dv=16, r=24):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, S, H, dn + dr), np.float32) * 0.3
    c_kv = rng.standard_normal((B, S, r), np.float32) * 0.3
    k_pe = rng.standard_normal((B, S, dr), np.float32) * 0.3
    w3 = rng.standard_normal((r, H * (dn + dv)), np.float32) * 0.1
    return q, c_kv, k_pe, w3, dn, dv


def test_mla_ring_matches_reference():
    """The latent ring (mla_ring_attention: ppermute moves c_kv/k_pe,
    each hop re-expands K/V locally) must equal expanded full attention."""
    from paddle_tpu.distributed.context_parallel import mla_ring_attention

    q, c_kv, k_pe, w3, dn, dv = _mla_args()
    mesh = _mesh(4)
    spec4, spec3, spec2 = (P(None, "sep", None, None), P(None, "sep", None),
                           P(None, None))
    fn = shard_map(
        functools.partial(mla_ring_attention, axis_name="sep",
                          nope_dim=dn, v_dim=dv),
        mesh=mesh, in_specs=(spec4, spec3, spec3, spec2), out_specs=spec4,
        check_vma=False)
    with mesh:
        got = np.asarray(jax.jit(fn)(q, c_kv, k_pe, w3))
    ref = _mla_ref(q, c_kv, k_pe, w3.reshape(24, 4, -1), dn)
    np.testing.assert_allclose(got, ref, atol=2e-5, rtol=2e-5)


def test_mla_ring_grads_match_reference():
    from paddle_tpu.distributed.context_parallel import mla_ring_attention

    q, c_kv, k_pe, w3, dn, dv = _mla_args(S=16)
    mesh = _mesh(4)
    spec4, spec3, spec2 = (P(None, "sep", None, None), P(None, "sep", None),
                           P(None, None))
    ring = shard_map(
        functools.partial(mla_ring_attention, axis_name="sep",
                          nope_dim=dn, v_dim=dv),
        mesh=mesh, in_specs=(spec4, spec3, spec3, spec2), out_specs=spec4,
        check_vma=False)

    def ref_fn(q, c_kv, k_pe, w3):
        kv = jnp.einsum("bsr,rhd->bshd", c_kv, w3.reshape(24, 4, -1))
        B, S, H, _ = kv.shape
        dr = q.shape[-1] - dn
        k = jnp.concatenate(
            [kv[..., :dn],
             jnp.broadcast_to(k_pe[:, :, None, :], (B, S, H, dr))], -1)
        v = kv[..., dn:]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    with mesh:
        g_ring = jax.grad(lambda *a: jnp.sum(ring(*a) ** 2),
                          argnums=(0, 1, 2, 3))(q, c_kv, k_pe, w3)
    g_ref = jax.grad(lambda *a: jnp.sum(ref_fn(*a) ** 2),
                     argnums=(0, 1, 2, 3))(q, c_kv, k_pe, w3)
    for gr, gf in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   atol=2e-4, rtol=2e-4)


def test_deepseek_trains_under_sep():
    """DeepSeek-V2 (MLA + MoE) trains under sequence parallelism through
    the latent ring: loss parity vs the single-device model, finite grads
    after an optimizer step."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                            DeepseekV2ForCausalLM)

    def build(sep_mode):
        paddle.seed(19)
        return DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(
            num_hidden_layers=2, sep_mode=sep_mode))

    rng = np.random.default_rng(29)
    ids = rng.integers(0, 512, (4, 65))
    x_np, y_np = ids[:, :-1], ids[:, 1:]

    model_ref = build("allgather")
    loss_ref, _ = model_ref(paddle.to_tensor(x_np),
                            labels=paddle.to_tensor(y_np))
    ref = float(loss_ref.numpy())

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4,
                               "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        model = build("ring")
        model = dist.fleet.distributed_model(model)
        loss, _ = model(paddle.to_tensor(x_np), labels=paddle.to_tensor(y_np))
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=2e-4)
        optimizer = opt.AdamW(1e-3, parameters=model.parameters())
        loss.backward()
        optimizer.step()
        for p in model.parameters():
            assert np.all(np.isfinite(np.asarray(p._array)))
    finally:
        dist.set_hybrid_communicate_group(None)


def test_ring_uneven_ring_size_eight():
    # full 8-way ring, seq not a multiple of 128 — exercises block masking
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 8 * 5, 2, 4
    q = rng.standard_normal((b, s, h, d), np.float32)
    k = rng.standard_normal((b, s, h, d), np.float32)
    v = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(8)
    fn = _sharded_fn(ring_attention, mesh, "sep", causal=True)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), _ref_attention(q, k, v, True),
                               rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_bad_degree():
    rng = np.random.default_rng(4)
    b, s, h, d = 1, 16, 2, 4  # h=2 not divisible by sep=4
    q = rng.standard_normal((b, s, h, d), np.float32)
    mesh = _mesh(4)
    fn = _sharded_fn(ulysses_attention, mesh, "sep")
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(fn)(q, q, q)


def test_sep_attention_via_fleet():
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        rng = np.random.default_rng(5)
        b, s, h, d = 2, 32, 4, 8
        q = rng.standard_normal((b, s, h, d), np.float32)
        k = rng.standard_normal((b, s, h, d), np.float32)
        v = rng.standard_normal((b, s, h, d), np.float32)
        for mode in ("ring", "ulysses"):
            out = sep_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                                paddle.to_tensor(v), causal=True, mode=mode)
            np.testing.assert_allclose(out.numpy(),
                                       _ref_attention(q, k, v, True),
                                       rtol=2e-4, atol=2e-5)
    finally:
        dist.set_hybrid_communicate_group(None)


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_llama_train_step_with_cp(mode):
    """End-to-end: hybrid dp×sep train step with context-parallel attention
    produces the same loss as the single-device model (dist-test philosophy
    of test/legacy_test/test_dist_base.py: single vs parallel loss parity)."""
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.distributed.engine import parallelize
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    def build(sep_mode):
        paddle.seed(7)
        cfg = LlamaConfig.tiny(use_flash_attention=False, sep_mode=sep_mode)
        return LlamaForCausalLM(cfg), cfg

    rng = np.random.default_rng(6)
    ids = rng.integers(0, 512, (4, 33))
    x_np, y_np = ids[:, :-1], ids[:, 1:]

    # single-device reference loss
    model_ref, _ = build("allgather")
    loss_ref, _ = model_ref(paddle.to_tensor(x_np), labels=paddle.to_tensor(y_np))
    ref = float(loss_ref.numpy())

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "sep_degree": 4, "mp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        model, cfg = build(mode)
        model = dist.fleet.distributed_model(model)
        optimizer = opt.AdamW(1e-3, parameters=model.parameters())

        def loss_fn(m, x, y):
            loss, _ = m(x, labels=y)
            return loss

        step = parallelize(model, loss_fn, optimizer)
        loss = step(paddle.to_tensor(x_np), paddle.to_tensor(y_np))
        np.testing.assert_allclose(float(loss.numpy()), ref, rtol=2e-4)
    finally:
        dist.set_hybrid_communicate_group(None)
