"""Flags, profiler, NaN/Inf checking, memory stats.

Parity model: paddle.set_flags/get_flags (paddle/common/flags.h registry),
paddle.profiler.Profiler scheduler + chrome export (profiler.py:358,:227),
FLAGS_check_nan_inf (eager_gen.py:440, nan_inf_utils.cc).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 export_chrome_tracing, make_scheduler)


# ---- flags -------------------------------------------------------------------

def test_flags_get_set_roundtrip():
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert paddle.get_flags(["check_nan_inf"])["check_nan_inf"] is True
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_unknown_raises():
    with pytest.raises(ValueError):
        paddle.set_flags({"FLAGS_no_such_flag": 1})
    with pytest.raises(ValueError):
        paddle.get_flags("no_such_flag")


def test_flags_string_bool_parse():
    paddle.set_flags({"FLAGS_benchmark": "true"})
    try:
        assert paddle.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
    finally:
        paddle.set_flags({"FLAGS_benchmark": "false"})


# ---- NaN/Inf checking --------------------------------------------------------

def test_check_nan_inf_forward_and_backward():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match=r"operator \[divide\]|divide"):
            _ = paddle.to_tensor(np.array([1.0, 1.0], np.float32)) / x

        # backward: log'(0) = inf
        y = paddle.to_tensor(np.array([1.0, 0.0], np.float32),
                             stop_gradient=False)
        out = (y * y).sum()  # fine forward
        out.backward()  # fine backward
        z = paddle.to_tensor(np.array([0.5, 0.0], np.float32),
                             stop_gradient=False)
        with pytest.raises(FloatingPointError):
            paddle.log(z).sum().backward()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
        from paddle_tpu.autograd import tape

        tape.reset_tape()


def test_check_nan_inf_off_is_silent():
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    out = paddle.to_tensor(np.array([1.0, 1.0], np.float32)) / x
    assert np.isinf(out.numpy()[1])


# ---- profiler ----------------------------------------------------------------

def test_make_scheduler_state_machine():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=2, skip_first=1)
    states = [sched(i) for i in range(10)]
    assert states[0] == ProfilerState.CLOSED            # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED            # cycle 2
    assert states[9] == ProfilerState.CLOSED            # repeat exhausted


def test_profiler_records_spans_and_exports(tmp_path):
    traces = []

    def on_ready(prof):
        path = tmp_path / "trace.json"
        prof._export_chrome(str(path))
        traces.append(path)

    p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=2, repeat=1),
                 on_trace_ready=on_ready)
    p.start()
    for step in range(2):
        with RecordEvent("train_step"):
            with RecordEvent("forward"):
                pass
        p.step(num_samples=32)
    p.stop()
    assert traces, "trace not exported"
    data = json.load(open(traces[0]))
    names = {e["name"] for e in data["traceEvents"]}
    assert "train_step" in names and "forward" in names
    info = p.step_info()
    assert "ips" in info and "batch_cost" in info


def test_profiler_timer_only_ips():
    p = Profiler(timer_only=True)
    p.start()
    for _ in range(3):
        p.step(num_samples=16)
    p.stop()
    assert "ips" in p.step_info()


def test_record_event_outside_profiler_is_noop():
    with RecordEvent("orphan"):
        pass  # must not raise or leak


def test_profiler_summary_prints(capsys):
    p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=1, repeat=1),
                 on_trace_ready=lambda prof: None)
    p.start()
    with RecordEvent("op_a"):
        pass
    p.stop()
    p.summary()
    out = capsys.readouterr().out
    assert "op_a" in out and "Calls" in out


# ---- memory stats ------------------------------------------------------------

def test_memory_stats_api():
    from paddle_tpu.framework import device as dev

    x = paddle.to_tensor(np.zeros((256, 256), np.float32))
    assert dev.memory_allocated() >= 0
    assert dev.max_memory_allocated() >= dev.memory_allocated() or \
        dev.max_memory_allocated() == 0  # cpu backend may not track
    dev.empty_cache()


# ---- utils -------------------------------------------------------------------

def test_unique_name_and_run_check(capsys):
    from paddle_tpu.utils import unique_name

    a = unique_name.generate("fc")
    b = unique_name.generate("fc")
    assert a != b
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
    assert unique_name.generate("fc") == "fc_2"

    import paddle_tpu.utils as utils

    utils.run_check()
    assert "works" in capsys.readouterr().out


# ---- unified metrics subsystem (paddle_tpu.observability) --------------------

def _parse_prom(text):
    """Tiny exposition parser: {(name, (sorted label items))} -> float."""
    import re

    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        head, val = line.rsplit(" ", 1)
        if "{" in head:
            name, rest = head.split("{", 1)
            labels = tuple(sorted(
                (k, v) for k, v in re.findall(r'(\w+)="([^"]*)"', rest)))
        else:
            name, labels = head, ()
        out[(name, labels)] = float(val)
    return out


def test_registry_labels_and_idempotent_register():
    from paddle_tpu.observability import MetricsRegistry

    r = MetricsRegistry()
    c = r.counter("reqs_total", "requests", labels=("engine", "event"))
    c.inc(engine="a", event="ok")
    c.inc(2, engine="a", event="ok")
    c.inc(engine="b", event="err")
    assert c.value(engine="a", event="ok") == 3
    assert c.value(engine="b", event="err") == 1
    # re-registering the same schema returns the SAME family
    assert r.counter("reqs_total", labels=("engine", "event")) is c
    # schema drift raises instead of silently forking
    with pytest.raises(ValueError):
        r.gauge("reqs_total")
    with pytest.raises(ValueError):
        r.counter("reqs_total", labels=("engine",))
    # wrong label names raise
    with pytest.raises(ValueError):
        c.inc(engine="a", evnt="typo")
    with pytest.raises(ValueError):
        c.inc(1.0)  # missing labels entirely
    g = r.gauge("depth")
    g.set(7)
    assert g.value() == 7
    with pytest.raises(ValueError):
        c.inc(-1, engine="a", event="ok")  # counters only go up


def test_histogram_bucket_edges_le_semantics():
    from paddle_tpu.observability import MetricsRegistry

    r = MetricsRegistry()
    h = r.histogram("lat", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 5.0, 100.0):
        h.observe(v)
    # le semantics: a value exactly AT the edge lands in that bucket
    assert h.bucket_counts() == [2, 2, 1, 1]
    assert h.count() == 6 and abs(h.sum() - 106.65) < 1e-9
    parsed = _parse_prom(r.render_prometheus())
    assert parsed[("lat_bucket", (("le", "0.1"),))] == 2
    assert parsed[("lat_bucket", (("le", "1"),))] == 4     # cumulative
    assert parsed[("lat_bucket", (("le", "10"),))] == 5
    assert parsed[("lat_bucket", (("le", "+Inf"),))] == 6
    assert parsed[("lat_count", ())] == 6


def test_concurrent_increments_from_threads():
    """HTTP handler threads and the engine thread record concurrently —
    every mutation holds the registry lock, so totals are exact."""
    import threading

    from paddle_tpu.observability import MetricsRegistry

    r = MetricsRegistry()
    c = r.counter("hits_total", labels=("who",))
    h = r.histogram("obs_seconds", buckets=(0.5,))
    n_threads, per = 8, 500

    def worker(i):
        # both call styles under contention: family-level labeled inc and
        # the pre-bound child the engines use on the hot path
        child = c.labels(who=str(i % 2))
        for k in range(per):
            if k % 2:
                c.inc(who=str(i % 2))
            else:
                child.inc()
            h.observe(k * 1e-3)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = c.value(who="0") + c.value(who="1")
    assert total == n_threads * per
    assert h.count() == n_threads * per
    # render under load-free conditions parses cleanly
    assert ("hits_total", (("who", "0"),)) in _parse_prom(
        r.render_prometheus())


def test_exposition_escaping_and_roundtrip():
    from paddle_tpu.observability import MetricsRegistry

    r = MetricsRegistry()
    c = r.counter("odd_total", 'help with "quotes"\nand newline',
                  labels=("tag",))
    c.inc(tag='va"l\nue')
    text = r.render_prometheus()
    assert '# HELP odd_total help with "quotes"\\nand newline' in text
    assert r'tag="va\"l\nue"' in text
    assert text.endswith("\n")


def test_stats_payload_unified_across_engines():
    """Satellite: ONE stats() implementation for both engines — identical
    key sets (the old hand-copied seq2seq dict had already dropped
    prefix_pages_reused)."""
    from paddle_tpu.serving import (ContinuousBatchEngine,
                                    Seq2SeqBatchEngine)

    a = object.__new__(ContinuousBatchEngine)
    b = object.__new__(Seq2SeqBatchEngine)
    for eng, label in ((a, "decoder"), (b, "seq2seq")):
        eng._slots = [None] * 4
        eng.max_batch = 4
        eng._init_bookkeeping(label)
    sa, sb = a.stats(), b.stats()
    assert set(sa) == set(sb)
    assert sb["prefix_pages_reused"] == 0
    assert ContinuousBatchEngine.stats is Seq2SeqBatchEngine.stats


def test_engine_metrics_and_http_exposition():
    """Acceptance: a short ContinuousBatchEngine serve, then GET /metrics
    returns valid Prometheus text whose TTFT / inter-token / queue-wait
    histogram counts match the served requests and tokens — with
    engine-vs-solo token parity unchanged."""
    import http.client
    import json as _json

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability import get_registry
    from paddle_tpu.serving import ContinuousBatchEngine
    from paddle_tpu.serving_http import CompletionServer

    def decoder_series(parsed, name, **extra):
        labels = tuple(sorted({"engine": "decoder", **extra}.items()))
        return parsed.get((name, labels), 0.0)

    before = _parse_prom(get_registry().render_prometheus())
    paddle.seed(0)
    model = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    eng = ContinuousBatchEngine(model, max_batch=2, max_len=64, page_size=8)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        budgets = (5, 4)
        solos = []
        for i, budget in enumerate(budgets):
            prompt = np.random.RandomState(20 + i).randint(
                1, 512, (6 + i,)).tolist()
            solo = model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                                  max_new_tokens=budget).numpy()[0].tolist()
            conn = http.client.HTTPConnection(host, port, timeout=120)
            conn.request("POST", "/v1/completions",
                         _json.dumps({"prompt_token_ids": prompt,
                                      "max_tokens": budget}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = _json.loads(resp.read())
            conn.close()
            assert resp.status == 200
            # parity: the engine serves the solo-generate tokens
            assert out["choices"][0]["token_ids"] == solo
            solos.append(solo)
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        ctype = resp.getheader("Content-Type")
        text = resp.read().decode()
        conn.close()
    assert resp.status == 200 and "text/plain" in ctype
    assert "# TYPE serving_time_to_first_token_seconds histogram" in text
    after = _parse_prom(text)

    n_req = len(budgets)
    n_tok = sum(budgets)

    def delta(name, **extra):
        return (decoder_series(after, name, **extra)
                - decoder_series(before, name, **extra))

    assert delta("serving_time_to_first_token_seconds_count") == n_req
    assert delta("serving_queue_wait_seconds_count") == n_req
    assert delta("serving_inter_token_latency_seconds_count") == n_tok - n_req
    assert delta("serving_tokens_generated_total") == n_tok
    assert delta("serving_requests_total", event="admitted") == n_req
    assert delta("serving_requests_total", event="finished") == n_req
    assert delta("serving_prefill_seconds_count") == n_req
    assert delta("serving_decode_step_seconds_count") >= max(budgets)
    assert delta("serving_time_to_first_token_seconds_sum") > 0
    # histograms are monotone: cumulative bucket counts never decrease
    # with increasing le
    import re as _re

    for hist in ("serving_time_to_first_token_seconds",
                 "serving_inter_token_latency_seconds",
                 "serving_queue_wait_seconds"):
        rows = [(float(m.group(1).replace("+Inf", "inf")),
                 float(line.rsplit(" ", 1)[1]))
                for line in text.splitlines()
                for m in [_re.search(
                    hist + r'_bucket\{engine="decoder",le="([^"]+)"\}',
                    line)] if m]
        edges = [e for e, _ in rows]
        counts = [c for _, c in rows]
        assert edges == sorted(edges) and counts == sorted(counts)
    # /metrics sits NEXT TO /health: same engine snapshot both ways
    assert decoder_series(after, "serving_active_slots") == 0
    assert after[("serving_http_requests_total",
                  (("code", "200"), ("path", "/metrics")))] >= 1


def test_snapshot_writer_rank_aware(tmp_path, monkeypatch):
    import json

    from paddle_tpu.observability import SnapshotWriter

    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    w = SnapshotWriter(str(tmp_path))
    path = w.write(step=1)
    w.write(step=2, extra={"phase": "train"})
    assert path.endswith("metrics.rank3.jsonl")
    lines = open(path).read().splitlines()
    assert len(lines) == 2
    rec = json.loads(lines[0])
    assert rec["rank"] == 3 and rec["step"] == 1 and "metrics" in rec
    assert json.loads(lines[1])["phase"] == "train"
    # unranked process: no suffix (single-file single-writer)
    monkeypatch.delenv("PADDLE_TRAINER_ID")
    monkeypatch.delenv("RANK", raising=False)
    assert SnapshotWriter(str(tmp_path)).path.endswith("/metrics.jsonl")


def test_step_timer_publishes_and_memory_flag(monkeypatch):
    """Satellite: FLAGS_log_memory_stats (previously defined but dead)
    now gates per-step memory logging through the rank-aware logger."""
    import io
    import logging

    import paddle_tpu as paddle
    from paddle_tpu.observability import StepTimer, catalog as cat

    lg = logging.getLogger("test_step_timer_obs")
    lg.handlers = []
    lg.propagate = False
    buf = io.StringIO()
    lg.addHandler(logging.StreamHandler(buf))
    lg.setLevel(logging.INFO)

    steps_before = cat.TRAIN_STEP_SECONDS.count()
    timer = StepTimer(logger=lg)
    with timer.step(n_samples=4, n_tokens=128):
        pass
    assert cat.TRAIN_STEP_SECONDS.count() == steps_before + 1
    assert cat.TRAIN_TOKENS_PER_SEC.value() > 0
    assert cat.TRAIN_SAMPLES_PER_SEC.value() > 0
    assert buf.getvalue() == ""          # flag off: silent

    paddle.set_flags({"FLAGS_log_memory_stats": True})
    try:
        with timer.step():
            pass
        assert "device mem" in buf.getvalue()
    finally:
        paddle.set_flags({"FLAGS_log_memory_stats": False})
    # end() without begin() must not record garbage
    assert StepTimer().end() is None


def test_hapi_step_timer_callback(tmp_path):
    import json

    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi.callbacks import StepTimer
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.observability import catalog as cat

    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(opt.SGD(0.1, parameters=net.parameters()), nn.MSELoss())
    x = np.random.randn(8, 4).astype("float32")
    y = np.random.randn(8, 2).astype("float32")
    before = cat.TRAIN_STEP_SECONDS.count()
    cb = StepTimer(tokens_per_sample=4, snapshot_dir=str(tmp_path),
                   snapshot_freq=3)
    m.fit(list(zip(x, y)), batch_size=4, epochs=1, verbose=0,
          callbacks=[cb])
    assert cat.TRAIN_STEP_SECONDS.count() > before
    files = [f for f in __import__("os").listdir(str(tmp_path))
             if f.endswith(".jsonl")]
    assert files, "snapshot not written"
    line = open(tmp_path / files[0]).readline()
    assert "train_step_seconds" in json.loads(line)["metrics"]


def test_metrics_catalog_lint():
    """Satellite: the docs/SERVING.md catalog and the registry agree
    (both directions) — the standalone script doubles as a tier-1 test."""
    import importlib.util
    import os

    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "check_metrics_catalog.py")
    spec = importlib.util.spec_from_file_location("_metrics_lint", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


def test_rank_aware_logger(capsys, monkeypatch):
    """log_utils parity: records carry the [rank N/M] tag and log_on_rank
    filters by rank."""
    import importlib
    import logging

    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    from paddle_tpu.distributed import log_utils
    importlib.reload(log_utils)
    lg = log_utils.get_logger(logging.INFO, name="test_rank_logger")
    import io
    buf = io.StringIO()
    lg.handlers[0].stream = buf
    lg.info("hello")
    assert "[rank 2/4]" in buf.getvalue() and "hello" in buf.getvalue()
    # log_on_rank: silent on non-matching rank
    buf2 = io.StringIO()
    lg.handlers[0].stream = buf2
    log_utils.log_on_rank("only-zero", rank=0, logger=lg)
    assert "only-zero" not in buf2.getvalue()
    log_utils.log_on_rank("mine", rank=2, logger=lg)
    assert "mine" in buf2.getvalue()
