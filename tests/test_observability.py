"""Flags, profiler, NaN/Inf checking, memory stats.

Parity model: paddle.set_flags/get_flags (paddle/common/flags.h registry),
paddle.profiler.Profiler scheduler + chrome export (profiler.py:358,:227),
FLAGS_check_nan_inf (eager_gen.py:440, nan_inf_utils.cc).
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.profiler as profiler
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 export_chrome_tracing, make_scheduler)


# ---- flags -------------------------------------------------------------------

def test_flags_get_set_roundtrip():
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert paddle.get_flags(["check_nan_inf"])["check_nan_inf"] is True
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_unknown_raises():
    with pytest.raises(ValueError):
        paddle.set_flags({"FLAGS_no_such_flag": 1})
    with pytest.raises(ValueError):
        paddle.get_flags("no_such_flag")


def test_flags_string_bool_parse():
    paddle.set_flags({"FLAGS_benchmark": "true"})
    try:
        assert paddle.get_flags("FLAGS_benchmark")["FLAGS_benchmark"] is True
    finally:
        paddle.set_flags({"FLAGS_benchmark": "false"})


# ---- NaN/Inf checking --------------------------------------------------------

def test_check_nan_inf_forward_and_backward():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
        with pytest.raises(FloatingPointError, match=r"operator \[divide\]|divide"):
            _ = paddle.to_tensor(np.array([1.0, 1.0], np.float32)) / x

        # backward: log'(0) = inf
        y = paddle.to_tensor(np.array([1.0, 0.0], np.float32),
                             stop_gradient=False)
        out = (y * y).sum()  # fine forward
        out.backward()  # fine backward
        z = paddle.to_tensor(np.array([0.5, 0.0], np.float32),
                             stop_gradient=False)
        with pytest.raises(FloatingPointError):
            paddle.log(z).sum().backward()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
        from paddle_tpu.autograd import tape

        tape.reset_tape()


def test_check_nan_inf_off_is_silent():
    x = paddle.to_tensor(np.array([1.0, 0.0], np.float32))
    out = paddle.to_tensor(np.array([1.0, 1.0], np.float32)) / x
    assert np.isinf(out.numpy()[1])


# ---- profiler ----------------------------------------------------------------

def test_make_scheduler_state_machine():
    sched = make_scheduler(closed=1, ready=1, record=2, repeat=2, skip_first=1)
    states = [sched(i) for i in range(10)]
    assert states[0] == ProfilerState.CLOSED            # skip_first
    assert states[1] == ProfilerState.CLOSED
    assert states[2] == ProfilerState.READY
    assert states[3] == ProfilerState.RECORD
    assert states[4] == ProfilerState.RECORD_AND_RETURN
    assert states[5] == ProfilerState.CLOSED            # cycle 2
    assert states[9] == ProfilerState.CLOSED            # repeat exhausted


def test_profiler_records_spans_and_exports(tmp_path):
    traces = []

    def on_ready(prof):
        path = tmp_path / "trace.json"
        prof._export_chrome(str(path))
        traces.append(path)

    p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=2, repeat=1),
                 on_trace_ready=on_ready)
    p.start()
    for step in range(2):
        with RecordEvent("train_step"):
            with RecordEvent("forward"):
                pass
        p.step(num_samples=32)
    p.stop()
    assert traces, "trace not exported"
    data = json.load(open(traces[0]))
    names = {e["name"] for e in data["traceEvents"]}
    assert "train_step" in names and "forward" in names
    info = p.step_info()
    assert "ips" in info and "batch_cost" in info


def test_profiler_timer_only_ips():
    p = Profiler(timer_only=True)
    p.start()
    for _ in range(3):
        p.step(num_samples=16)
    p.stop()
    assert "ips" in p.step_info()


def test_record_event_outside_profiler_is_noop():
    with RecordEvent("orphan"):
        pass  # must not raise or leak


def test_profiler_summary_prints(capsys):
    p = Profiler(scheduler=make_scheduler(closed=0, ready=0, record=1, repeat=1),
                 on_trace_ready=lambda prof: None)
    p.start()
    with RecordEvent("op_a"):
        pass
    p.stop()
    p.summary()
    out = capsys.readouterr().out
    assert "op_a" in out and "Calls" in out


# ---- memory stats ------------------------------------------------------------

def test_memory_stats_api():
    from paddle_tpu.framework import device as dev

    x = paddle.to_tensor(np.zeros((256, 256), np.float32))
    assert dev.memory_allocated() >= 0
    assert dev.max_memory_allocated() >= dev.memory_allocated() or \
        dev.max_memory_allocated() == 0  # cpu backend may not track
    dev.empty_cache()


# ---- utils -------------------------------------------------------------------

def test_unique_name_and_run_check(capsys):
    from paddle_tpu.utils import unique_name

    a = unique_name.generate("fc")
    b = unique_name.generate("fc")
    assert a != b
    with unique_name.guard():
        assert unique_name.generate("fc") == "fc_0"
    assert unique_name.generate("fc") == "fc_2"

    import paddle_tpu.utils as utils

    utils.run_check()
    assert "works" in capsys.readouterr().out


def test_rank_aware_logger(capsys, monkeypatch):
    """log_utils parity: records carry the [rank N/M] tag and log_on_rank
    filters by rank."""
    import importlib
    import logging

    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    from paddle_tpu.distributed import log_utils
    importlib.reload(log_utils)
    lg = log_utils.get_logger(logging.INFO, name="test_rank_logger")
    import io
    buf = io.StringIO()
    lg.handlers[0].stream = buf
    lg.info("hello")
    assert "[rank 2/4]" in buf.getvalue() and "hello" in buf.getvalue()
    # log_on_rank: silent on non-matching rank
    buf2 = io.StringIO()
    lg.handlers[0].stream = buf2
    log_utils.log_on_rank("only-zero", rank=0, logger=lg)
    assert "only-zero" not in buf2.getvalue()
    log_utils.log_on_rank("mine", rank=2, logger=lg)
    assert "mine" in buf2.getvalue()
