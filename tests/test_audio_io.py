"""Audio IO backend + datasets (ref python/paddle/audio/backends/,
datasets/): PCM16 WAV roundtrip, metadata, slicing, registry, and the
TESS/ESC50 local-file datasets."""
import csv
import os
import wave

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.audio as audio


def _sine(sr=16000, seconds=0.1, freq=440.0, channels=1):
    t = np.arange(int(sr * seconds)) / sr
    w = 0.4 * np.sin(2 * np.pi * freq * t).astype(np.float32)
    return np.tile(w, (channels, 1))  # [C, T]


class TestWaveBackend:
    def test_save_load_roundtrip(self, tmp_path):
        sr = 16000
        w = _sine(sr, channels=2)
        path = str(tmp_path / "x.wav")
        audio.save(path, paddle.to_tensor(w), sr)
        back, sr2 = audio.load(path)
        assert sr2 == sr
        assert back.shape == list(w.shape)
        np.testing.assert_allclose(back.numpy(), w, atol=1.0 / 32000)

    def test_info(self, tmp_path):
        path = str(tmp_path / "i.wav")
        audio.save(path, paddle.to_tensor(_sine(8000)), 8000)
        meta = audio.info(path)
        assert meta.sample_rate == 8000
        assert meta.num_channels == 1
        assert meta.num_samples == 800
        assert meta.bits_per_sample == 16

    def test_frame_slicing_and_channels_last(self, tmp_path):
        sr = 8000
        w = _sine(sr)
        path = str(tmp_path / "s.wav")
        audio.save(path, paddle.to_tensor(w), sr)
        part, _ = audio.load(path, frame_offset=100, num_frames=50)
        assert part.shape == [1, 50]
        np.testing.assert_allclose(part.numpy()[0], w[0, 100:150],
                                   atol=1.0 / 32000)
        tc, _ = audio.load(path, channels_first=False)
        assert tc.shape == [w.shape[1], 1]

    def test_unnormalized_is_int16_scale(self, tmp_path):
        path = str(tmp_path / "u.wav")
        audio.save(path, paddle.to_tensor(_sine(8000)), 8000)
        raw, _ = audio.load(path, normalize=False)
        assert np.abs(raw.numpy()).max() > 1000  # int16 magnitude

    def test_non_wav_raises(self, tmp_path):
        bad = tmp_path / "not.wav"
        bad.write_bytes(b"definitely not RIFF data")
        with pytest.raises(NotImplementedError):
            audio.load(str(bad))

    def test_backend_registry(self):
        assert audio.backends.list_available_backends() == ["wave_backend"]
        assert audio.backends.get_current_audio_backend() == "wave_backend"
        audio.backends.set_backend("wave")  # both spellings accepted
        with pytest.raises(NotImplementedError):
            audio.backends.set_backend("soundfile")


class TestAudioDatasets:
    def _make_tess(self, root):
        emotions = ["angry", "happy", "sad", "neutral"]
        for i, emo in enumerate(emotions * 3):
            path = os.path.join(root, f"OAF_word{i}_{emo}.wav")
            audio.save(path, paddle.to_tensor(_sine(8000, 0.02)), 8000)
        return emotions

    def test_tess_split_and_labels(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS

        self._make_tess(str(tmp_path))
        train = TESS(str(tmp_path), mode="train", n_folds=4, split=1)
        dev = TESS(str(tmp_path), mode="dev", n_folds=4, split=1)
        assert len(train) + len(dev) == 12
        assert len(dev) == 3
        w, label = train[0]
        assert w.shape[0] == 1 and 0 <= label < len(TESS.labels_list)

    def test_tess_feature_mode(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS

        self._make_tess(str(tmp_path))
        ds = TESS(str(tmp_path), mode="train", feat_type="melspectrogram",
                  sample_rate=8000, n_fft=128, n_mels=8)
        feat, _ = ds[0]
        assert feat.shape[-2] == 8  # mel bins

    def test_tess_missing_root_raises(self, tmp_path):
        from paddle_tpu.audio.datasets import TESS

        with pytest.raises(RuntimeError, match="no TESS"):
            TESS(str(tmp_path / "empty"))

    def test_esc50_meta_layout(self, tmp_path):
        from paddle_tpu.audio.datasets import ESC50

        os.makedirs(tmp_path / "audio")
        os.makedirs(tmp_path / "meta")
        rows = []
        for i in range(10):
            name = f"clip{i}.wav"
            audio.save(str(tmp_path / "audio" / name),
                       paddle.to_tensor(_sine(8000, 0.02)), 8000)
            rows.append({"filename": name, "fold": i % 5 + 1,
                         "target": i % 3})
        with open(tmp_path / "meta" / "esc50.csv", "w", newline="") as f:
            wr = csv.DictWriter(f, fieldnames=["filename", "fold", "target"])
            wr.writeheader()
            wr.writerows(rows)
        train = ESC50(str(tmp_path), mode="train", split=1)
        dev = ESC50(str(tmp_path), mode="dev", split=1)
        assert len(train) == 8 and len(dev) == 2
        w, label = dev[0]
        assert w.shape[0] == 1 and label in (0, 1, 2)

    def test_esc50_missing_meta_raises(self, tmp_path):
        from paddle_tpu.audio.datasets import ESC50

        with pytest.raises(RuntimeError, match="metadata"):
            ESC50(str(tmp_path))


def test_mel_and_fft_frequencies():
    """functional.py:126,166 parity: endpoint + monotonicity + the rfft
    bin grid."""
    import paddle_tpu.audio.functional as AF

    freqs = AF.mel_frequencies(n_mels=16, f_min=100.0, f_max=4000.0)
    f = freqs.numpy()
    assert f.shape == (16,)
    np.testing.assert_allclose(f[0], 100.0, rtol=1e-5)
    np.testing.assert_allclose(f[-1], 4000.0, rtol=1e-5)
    assert np.all(np.diff(f) > 0)
    grid = AF.fft_frequencies(sr=16000, n_fft=512).numpy()
    assert grid.shape == (257,)
    np.testing.assert_allclose(grid[-1], 8000.0)
    np.testing.assert_allclose(grid[1], 16000 / 512)


def test_save_integer_scales_and_validation(tmp_path):
    """Review regressions: int32/uint8 PCM rescale instead of wrapping;
    bad integer dtypes and bad dataset modes/splits fail loudly."""
    sr = 8000
    w = _sine(sr)
    p16 = str(tmp_path / "a.wav")
    audio.save(p16, paddle.to_tensor(w), sr)
    raw16, _ = audio.load(p16, normalize=False)       # int16-scale values
    p2 = str(tmp_path / "b.wav")
    audio.save(p2, np.asarray(raw16.numpy(), np.int32) << 16, sr)  # 32-bit scale
    back, _ = audio.load(p2)
    np.testing.assert_allclose(back.numpy(), w, atol=1.0 / 32000)
    with pytest.raises(TypeError):
        audio.save(str(tmp_path / "c.wav"),
                   np.zeros((1, 10), np.int64), sr)
    bad = tmp_path / "not-riff.wav"
    bad.write_bytes(b"not a wav header")
    with pytest.raises(NotImplementedError):
        audio.info(str(bad))  # same exception type as load()


    from paddle_tpu.audio.datasets import ESC50, TESS
    with pytest.raises(ValueError, match="mode"):
        TESS(str(tmp_path), mode="test")
    with pytest.raises(ValueError, match="split"):
        ESC50(str(tmp_path), split=6)
