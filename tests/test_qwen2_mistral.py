"""Qwen2 (q/k/v bias) and Mistral (sliding window) decoder families:
construction, sliding-window attention parity (kernel vs dense band mask),
training, decode, and numeric parity against the canonical transformers
implementations."""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
from paddle_tpu.models.qwen2 import Qwen2Config, Qwen2ForCausalLM

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


class TestSlidingWindowKernel:
    def test_splash_window_matches_dense_band(self):
        from paddle_tpu.nn.functional.attention import _sdpa_ref
        from paddle_tpu.ops.pallas import flash_attention as pf

        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 256, 2, 128).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 256, 1, 128).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 256, 1, 128).astype(np.float32))
        win = 64
        out = pf.flash_attention_bshd(q, k, v, causal=True, window=win,
                                      interpret=True)
        rows = jnp.arange(256)[:, None]
        cols = jnp.arange(256)[None, :]
        band = ((cols <= rows) & (cols > rows - win))[None, None]
        ke = jnp.repeat(k, 2, axis=2)
        ve = jnp.repeat(v, 2, axis=2)
        ref = _sdpa_ref(q, ke, ve, mask=band)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_window_requires_causal(self):
        from paddle_tpu.ops.pallas import flash_attention as pf

        q = jnp.zeros((1, 128, 2, 128), jnp.float32)
        with pytest.raises(ValueError, match="causal"):
            pf.flash_attention_bshd(q, q[:, :, :1], q[:, :, :1],
                                    causal=False, window=8, interpret=True)


class TestMistral:
    def test_short_seq_matches_full_attention(self):
        """Below the window the band mask is the causal mask: a Mistral
        model must produce the same logits as the window-free twin."""
        cfg = MistralConfig.tiny(sliding_window=64, use_flash_attention=False)
        paddle.seed(0)
        m1 = MistralForCausalLM(cfg)
        paddle.seed(0)
        m2 = MistralForCausalLM(dataclasses.replace(cfg, sliding_window=None))
        ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
        np.testing.assert_allclose(m1(ids).numpy(), m2(ids).numpy(),
                                   atol=1e-5)

    def test_long_seq_window_changes_logits(self):
        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        paddle.seed(0)
        m1 = MistralForCausalLM(cfg)
        paddle.seed(0)
        m2 = MistralForCausalLM(dataclasses.replace(cfg, sliding_window=None))
        ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (1, 32)))
        assert not np.allclose(m1(ids).numpy(), m2(ids).numpy(), atol=1e-3)

    def test_trains(self):
        from paddle_tpu import optimizer as opt

        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        paddle.seed(0)
        m = MistralForCausalLM(cfg)

        def loss_fn(mm, x, y):
            loss, _ = mm(x, labels=y)
            return loss

        step = paddle.jit.train_step(m, loss_fn, opt.AdamW(1e-2, parameters=m.parameters()))
        x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 32)))
        y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 32)))
        losses = [float(step(x, y).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_logits_and_generate_match_transformers(self):
        """seq > window so the sliding band actually bites; transformers'
        eager Mistral attention is the external reference."""
        from transformers import MistralConfig as HFConfig
        from transformers import MistralForCausalLM as HFMistral
        from paddle_tpu.models.mistral import mistral_from_hf

        torch.manual_seed(0)
        hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=128,
                          rms_norm_eps=1e-5, rope_theta=10000.0,
                          sliding_window=8, tie_word_embeddings=False,
                          attn_implementation="eager")
        hf = HFMistral(hf_cfg).eval()
        ours = mistral_from_hf(hf, dtype="float32", use_flash_attention=False)
        assert ours.config.sliding_window == 8
        ids = np.random.RandomState(0).randint(0, 128, (2, 24))
        with torch.no_grad():
            ref = hf(torch.from_numpy(ids)).logits.numpy()
        got = ours(paddle.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)
        with torch.no_grad():
            gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                               do_sample=False).numpy()[:, 24:]
        ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        np.testing.assert_array_equal(ggot, gref)

    def test_ragged_batch_decode_matches_solo(self):
        """Right-padded batch decode under a sliding window must equal each
        row's solo run: the window has to count TRUE token positions, not
        shared-buffer slots (a short row's prompt lives at slots 0..len-1
        while decode writes at the batch-wide offset)."""
        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        paddle.seed(0)
        m = MistralForCausalLM(cfg)
        rng = np.random.RandomState(0)
        long_ids = rng.randint(1, 512, (1, 20))
        short_ids = rng.randint(1, 512, (1, 5))
        solo_long = m.generate(paddle.to_tensor(long_ids), max_new_tokens=10).numpy()
        solo_short = m.generate(paddle.to_tensor(short_ids), max_new_tokens=10).numpy()
        batch_ids = np.zeros((2, 20), np.int64)
        batch_ids[0] = long_ids[0]
        batch_ids[1, :5] = short_ids[0]
        am = np.zeros((2, 20), np.int64)
        am[0, :] = 1
        am[1, :5] = 1
        got = m.generate(paddle.to_tensor(batch_ids), max_new_tokens=10,
                         attention_mask=paddle.to_tensor(am)).numpy()
        np.testing.assert_array_equal(got[0], solo_long[0])
        np.testing.assert_array_equal(got[1], solo_short[0])

    def test_paged_decode_supports_window(self):
        """r5: paged decode applies the band lower bound (was a raise);
        short-prompt smoke — the beyond-window leg lives in
        TestWindowedPagedServing."""
        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        paddle.seed(0)
        m = MistralForCausalLM(cfg)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(
            0, 512, (1, 4)))
        dense = m.generate(ids, max_new_tokens=2).numpy()
        paged = m.generate(ids, max_new_tokens=2, paged=True,
                           page_size=4).numpy()
        np.testing.assert_array_equal(dense, paged)


class TestQwen2:
    def test_bias_params_exist_and_trains(self):
        from paddle_tpu import optimizer as opt

        cfg = Qwen2Config.tiny()
        paddle.seed(0)
        m = Qwen2ForCausalLM(cfg)
        names = dict(m.named_parameters())
        assert "llama.layers.0.self_attn.q_proj.bias" in names
        assert "llama.layers.0.self_attn.o_proj.bias" not in names

        def loss_fn(mm, x, y):
            loss, _ = mm(x, labels=y)
            return loss

        step = paddle.jit.train_step(m, loss_fn, opt.AdamW(1e-2, parameters=m.parameters()))
        x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
        y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
        losses = [float(step(x, y).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_logits_and_generate_match_transformers(self):
        from transformers import Qwen2Config as HFConfig
        from transformers import Qwen2ForCausalLM as HFQwen2
        from paddle_tpu.models.qwen2 import qwen2_from_hf

        torch.manual_seed(0)
        hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=128,
                          rms_norm_eps=1e-6, rope_theta=1e6,
                          tie_word_embeddings=False,
                          attn_implementation="eager")
        hf = HFQwen2(hf_cfg).eval()
        ours = qwen2_from_hf(hf, dtype="float32", use_flash_attention=False)
        assert ours.config.attention_bias
        assert ours.config.sliding_window is None  # use_sliding_window=False
        ids = np.random.RandomState(0).randint(0, 128, (2, 9))
        with torch.no_grad():
            ref = hf(torch.from_numpy(ids)).logits.numpy()
        got = ours(paddle.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)
        with torch.no_grad():
            gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                               do_sample=False).numpy()[:, 9:]
        ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
        np.testing.assert_array_equal(ggot, gref)


class TestHybridMesh:
    """The family deviations (qkv bias, sliding window) must survive the
    hybrid tensor-parallel path: mp2-sharded forward == single-device."""

    def _mp2(self):
        import paddle_tpu.distributed as dist

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 1,
                                   "sep_degree": 1}
        dist.fleet.init(is_collective=True, strategy=strategy)
        return dist

    def test_qwen2_bias_mp2_parity(self):
        dist = self._mp2()
        try:
            paddle.seed(0)
            m = Qwen2ForCausalLM(Qwen2Config.tiny())
            from paddle_tpu.distributed import ColumnParallelLinear

            attn = m.llama.layers[0].self_attn
            assert isinstance(attn.q_proj, ColumnParallelLinear)
            assert attn.q_proj.bias is not None
            state = {k: np.array(v.numpy()) for k, v in m.state_dict().items()}
            ids = paddle.to_tensor(
                np.random.RandomState(0).randint(0, 512, (2, 12)))
            sharded = m(ids).numpy()
        finally:
            dist.set_hybrid_communicate_group(None)
        paddle.seed(1)
        solo = Qwen2ForCausalLM(Qwen2Config.tiny())
        solo.set_state_dict(state)
        np.testing.assert_allclose(solo(ids).numpy(), sharded,
                                   atol=2e-4, rtol=2e-4)

    def test_mistral_window_mp2_parity(self):
        dist = self._mp2()
        try:
            paddle.seed(0)
            cfg = MistralConfig.tiny(sliding_window=8)
            m = MistralForCausalLM(cfg)
            state = {k: np.array(v.numpy()) for k, v in m.state_dict().items()}
            ids = paddle.to_tensor(
                np.random.RandomState(0).randint(0, 512, (1, 24)))
            sharded = m(ids).numpy()
        finally:
            dist.set_hybrid_communicate_group(None)
        paddle.seed(1)
        solo = MistralForCausalLM(MistralConfig.tiny(sliding_window=8))
        solo.set_state_dict(state)
        np.testing.assert_allclose(solo(ids).numpy(), sharded,
                                   atol=2e-4, rtol=2e-4)


def test_mistral_beam_matches_transformers():
    """Beam search composes with the sliding window: token parity against
    transformers' beam generate on an eager Mistral (seq > window)."""
    from transformers import MistralConfig as HFConfig
    from transformers import MistralForCausalLM as HFMistral
    from paddle_tpu.models.mistral import mistral_from_hf

    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128,
                      sliding_window=8, tie_word_embeddings=False,
                      attn_implementation="eager")
    hf = HFMistral(hf_cfg).eval()
    ours = mistral_from_hf(hf, dtype="float32", use_flash_attention=False)
    ids = np.random.RandomState(8).randint(0, 128, (2, 16))
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                          do_sample=False, num_beams=3,
                          pad_token_id=0).numpy()[:, 16:]
    got = ours.generate(paddle.to_tensor(ids), max_new_tokens=6,
                        num_beams=3).numpy()
    np.testing.assert_array_equal(got[:, :ref.shape[1]], ref)


class TestWindowedPagedServing:
    """Sliding window on the PAGED decode path (r5: was a raise): the
    gather fallback applies the band lower bound, so Mistral serves
    through the continuous-batching engine token-identically."""

    def test_paged_generate_matches_dense_beyond_window(self):
        paddle.seed(0)
        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        m = MistralForCausalLM(cfg)
        ids = np.random.RandomState(0).randint(0, 512, (2, 24))
        t = paddle.to_tensor(ids)
        dense = m.generate(t, max_new_tokens=6).numpy()
        paged = m.generate(t, max_new_tokens=6, paged=True).numpy()
        np.testing.assert_array_equal(dense, paged)

    def test_engine_serves_windowed_model(self):
        from paddle_tpu.serving import ContinuousBatchEngine

        paddle.seed(0)
        cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
        m = MistralForCausalLM(cfg)
        ids = np.random.RandomState(0).randint(0, 512, (2, 24))
        eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
        r0 = eng.add_request(ids[0], 6)
        r1 = eng.add_request(ids[1][:20], 6)  # ragged: different lengths
        done = eng.run_until_done()
        for rid, prompt in ((r0, ids[0]), (r1, ids[1][:20])):
            solo = m.generate(paddle.to_tensor(prompt[None]),
                              max_new_tokens=6).numpy()[0]
            assert done[rid].tolist() == solo.tolist()

    def test_paged_ref_window_band(self):
        """_paged_attention_ref with a window must equal dense attention
        over only the newest `window` positions."""
        import jax.numpy as jnp
        from paddle_tpu.generation import _paged_attention_ref

        rng = np.random.RandomState(3)
        B, H, hk, D, ps, npages = 2, 4, 2, 8, 4, 3
        q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
        k_pages = jnp.asarray(rng.randn(hk, npages * B, ps, D), jnp.float32)
        v_pages = jnp.asarray(rng.randn(hk, npages * B, ps, D), jnp.float32)
        page_indices = jnp.arange(B * npages).reshape(B, npages)
        lengths = jnp.asarray([10, 7], jnp.int32)
        win = 4
        out = _paged_attention_ref(q, k_pages, v_pages, lengths,
                                   page_indices, window=win)
        # dense reference over the gathered kv with the same band
        k = jnp.moveaxis(k_pages[:, page_indices], 0, 1).reshape(B, hk, -1, D)
        v = jnp.moveaxis(v_pages[:, page_indices], 0, 1).reshape(B, hk, -1, D)
        T = k.shape[2]
        g = H // hk
        qg = q.reshape(B, hk, g, D)
        s = jnp.einsum("bkgd,bktd->bkgt", qg, k) / np.sqrt(D)
        idx = jnp.arange(T)[None, :]
        band = (idx < lengths[:, None]) & (idx >= lengths[:, None] - win)
        s = jnp.where(band[:, None, None], s, -jnp.inf)
        ref = jnp.einsum("bkgt,bktd->bkgd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref.reshape(B, H, D)),
                                   rtol=1e-5, atol=1e-5)


def test_paged_window_attention_matches_full_gather():
    """The O(window) page-gather path == the full-cache banded reference,
    across ragged lengths incl. rows shorter than the window and bands
    crossing page boundaries."""
    import jax.numpy as jnp

    from paddle_tpu.generation import (_paged_attention_ref,
                                       _paged_window_attention)

    rng = np.random.RandomState(7)
    B, H, hk, D, ps, npages = 3, 4, 2, 8, 4, 6   # 24 cache positions/row
    q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
    k_pages = jnp.asarray(rng.randn(hk, npages * B, ps, D), jnp.float32)
    v_pages = jnp.asarray(rng.randn(hk, npages * B, ps, D), jnp.float32)
    page_indices = jnp.arange(B * npages).reshape(B, npages)
    lengths = jnp.asarray([23, 2, 13], jnp.int32)  # long / short / mid
    for win in (3, 4, 7, 16):
        fast = _paged_window_attention(q, k_pages, v_pages, lengths,
                                       page_indices, win)
        ref = _paged_attention_ref(q, k_pages, v_pages, lengths,
                                   page_indices, window=win)
        np.testing.assert_allclose(np.asarray(fast), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
