"""Reverse HF export (llama_to_hf / export_hf_llama): weights trained
here load into transformers with exact logits parity — the deploy-
anywhere direction of the interop story."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                     llama_from_hf, llama_to_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _load_into_hf(hf_model, sd):
    hf_model.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()},
                             strict=False)
    return hf_model.eval()


def test_llama_roundtrip_logits():
    """Train a few steps HERE, export, load into transformers: logits
    match to float tolerance."""
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFLlama
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 12)))
    for _ in range(3):
        step(x, y)

    sd = llama_to_hf(m)
    assert "lm_head.weight" in sd                  # untied: exported
    hf = _load_into_hf(HFLlama(HFConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=500000.0, attn_implementation="eager")), sd)
    ids = np.random.RandomState(2).randint(0, 512, (2, 10))
    ours = m(paddle.to_tensor(ids)).numpy()
    with torch.no_grad():
        theirs = hf(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=5e-4, rtol=5e-4)


def test_gemma2_roundtrip_through_from_hf():
    """from_hf(to_hf(m)) reproduces the model exactly (sandwich norms and
    (1+w) deltas included)."""
    from paddle_tpu.models.gemma2 import (Gemma2Config, Gemma2ForCausalLM,
                                          gemma2_from_hf)
    from paddle_tpu.models.llama import llama_to_hf

    paddle.seed(1)
    m = Gemma2ForCausalLM(Gemma2Config.tiny())
    sd = llama_to_hf(m)
    assert "lm_head.weight" not in sd              # tied: dropped
    assert any("pre_feedforward_layernorm" in k for k in sd)
    cfg = dict(
        model_type="gemma2", vocab_size=512, hidden_size=64,
        intermediate_size=128, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=32, query_pre_attn_scalar=64.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=16, layer_types=["sliding_attention",
                                        "full_attention"],
        max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=10000.0, hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True)
    m2 = gemma2_from_hf(sd, cfg, dtype="float32")
    ids = paddle.to_tensor(np.random.RandomState(3).randint(0, 512, (2, 9)))
    np.testing.assert_allclose(m(ids).numpy(), m2(ids).numpy(),
                               atol=1e-5, rtol=1e-5)


def test_olmo2_roundtrip_through_transformers():
    """OLMo2 (post-only norms, full-width qk norms) exports through its
    own layer_norms plan and reloads in transformers greedily."""
    from transformers import Olmo2Config as HFConfig
    from transformers import Olmo2ForCausalLM as HFOlmo2
    from paddle_tpu.models.olmo2 import Olmo2Config, Olmo2ForCausalLM

    paddle.seed(4)
    m = Olmo2ForCausalLM(Olmo2Config.tiny(num_hidden_layers=2))
    sd = llama_to_hf(m)
    assert any("post_feedforward_layernorm" in k for k in sd)
    assert not any("input_layernorm" in k for k in sd)
    hf = _load_into_hf(HFOlmo2(HFConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=500000.0, tie_word_embeddings=False, pad_token_id=0,
        attn_implementation="eager")), sd)
    ids = np.random.RandomState(5).randint(0, 512, (1, 8))
    ours = m.generate(paddle.to_tensor(ids), max_new_tokens=5).numpy()
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(ids), max_new_tokens=5,
                             do_sample=False).numpy()[:, 8:]
    np.testing.assert_array_equal(ours, theirs)


def test_transformed_families_refuse_export():
    """GLM/Phi-3 checkpoints are TRANSFORMED at load; exporting raw
    runtime weights would be silently wrong — must refuse."""
    from paddle_tpu.models.glm import Glm4Config, Glm4ForCausalLM
    from paddle_tpu.models.phi3 import Phi3Config, Phi3ForCausalLM

    paddle.seed(3)
    for m in (Glm4ForCausalLM(Glm4Config.tiny(num_hidden_layers=1)),
              Phi3ForCausalLM(Phi3Config.tiny(num_hidden_layers=1))):
        with pytest.raises(NotImplementedError, match="TRANSFORMED"):
            llama_to_hf(m)


def test_qwen3_roundtrip_through_transformers():
    """Qwen3 (qk norms, decoupled head_dim) exports and reloads through
    the real transformers model."""
    from transformers import Qwen3Config as HFConfig
    from transformers import Qwen3ForCausalLM as HFQwen3
    from paddle_tpu.models.qwen3 import Qwen3Config, Qwen3ForCausalLM
    from paddle_tpu.models.llama import llama_to_hf

    paddle.seed(2)
    m = Qwen3ForCausalLM(Qwen3Config.tiny(num_hidden_layers=2))
    sd = llama_to_hf(m)
    hf = _load_into_hf(HFQwen3(HFConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=256, rms_norm_eps=1e-6,
        rope_theta=1e6, tie_word_embeddings=False,
        attn_implementation="eager")), sd)
    ids = np.random.RandomState(4).randint(0, 512, (1, 8))
    ours = m.generate(paddle.to_tensor(ids), max_new_tokens=5).numpy()
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(ids), max_new_tokens=5,
                             do_sample=False).numpy()[:, 8:]
    np.testing.assert_array_equal(ours, theirs)


def test_qwen2_mistral_roundtrip_through_transformers():
    """Bias (Qwen2) and windowed (Mistral) variants export and reload
    through real transformers models with greedy parity."""
    from transformers import MistralConfig as HFMistralC
    from transformers import MistralForCausalLM as HFMistral
    from transformers import Qwen2Config as HFQwen2C
    from transformers import Qwen2ForCausalLM as HFQwen2
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    from paddle_tpu.models.qwen2 import Qwen2Config, Qwen2ForCausalLM

    paddle.seed(6)
    q = Qwen2ForCausalLM(Qwen2Config.tiny(num_hidden_layers=2))
    sd = llama_to_hf(q)
    assert any(k.endswith("q_proj.bias") for k in sd)
    hfq = _load_into_hf(HFQwen2(HFQwen2C(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5, rope_theta=1e6,
        tie_word_embeddings=False, attn_implementation="eager")), sd)
    ids = np.random.RandomState(7).randint(0, 512, (1, 8))
    ours = q.generate(paddle.to_tensor(ids), max_new_tokens=5).numpy()
    with torch.no_grad():
        theirs = hfq.generate(torch.from_numpy(ids), max_new_tokens=5,
                              do_sample=False).numpy()[:, 8:]
    np.testing.assert_array_equal(ours, theirs)

    paddle.seed(7)
    m = MistralForCausalLM(MistralConfig.tiny(num_hidden_layers=2,
                                              sliding_window=6))
    sd = llama_to_hf(m)
    hfm = _load_into_hf(HFMistral(HFMistralC(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, sliding_window=6, tie_word_embeddings=False,
        attn_implementation="eager")), sd)
    ids = np.random.RandomState(8).randint(0, 512, (1, 12))
    ours = m.generate(paddle.to_tensor(ids), max_new_tokens=5).numpy()
    with torch.no_grad():
        theirs = hfm.generate(torch.from_numpy(ids), max_new_tokens=5,
                              do_sample=False).numpy()[:, 12:]
    np.testing.assert_array_equal(ours, theirs)
