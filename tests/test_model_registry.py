"""Every entry in the models lazy-import registry resolves — a missing
module or symbol in the map would otherwise only fail on first attribute
access in user code."""
import importlib

import paddle_tpu.models as M


def test_every_registry_entry_resolves():
    lazy = getattr(M, "_LAZY", None) or getattr(M, "_lazy", None)
    if lazy is None:
        # find the mapping attr generically
        for name in dir(M):
            v = getattr(M, name)
            if (isinstance(v, dict) and v
                    and all(isinstance(k, str) for k in v)
                    and all(isinstance(t, tuple) and len(t) == 2
                            for t in v.values())):
                lazy = v
                break
    assert lazy, "models lazy-import map not found"
    for public, (module, symbol) in sorted(lazy.items()):
        mod = importlib.import_module(f"paddle_tpu.models.{module}")
        if symbol is not None:
            assert hasattr(mod, symbol), (public, module, symbol)
        # and the public attribute itself resolves through the lazy hook
        assert getattr(M, public) is not None, public
