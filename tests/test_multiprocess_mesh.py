"""Two processes, ONE global mesh (VERDICT r3 item 4).

Parity model: test/collective/test_communication_api_base.py:28,58-70 —
launch real processes that rendezvous on one master. TPU-native twist: the
processes call jax.distributed.initialize (via dist.init_parallel_env) and
form a SINGLE 8-device jax mesh (4 virtual CPU devices each), then run the
full hybrid DistTrainStep (dp2 x mp2 x sharding2) plus a collective over
it; loss must match the single-process 8-device run bit-for-bit.
"""
import os
import pickle
import socket
import subprocess
import sys

import numpy as np
import pytest


_WORKER = r'''
import os, pickle, sys
import numpy as np

out_dir = sys.argv[1]
rank = int(os.environ["PADDLE_TRAINER_ID"])

import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.distributed.engine import parallelize

strategy = dist.DistributedStrategy()
strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "sep_degree": 1,
                           "sharding_degree": 2, "pp_degree": 1}
strategy.sharding_configs = {"stage": 3}
dist.fleet.init(is_collective=True, strategy=strategy)  # init_parallel_env
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()      # ONE global mesh
assert jax.local_device_count() == 4

# a collective over the global mesh
t = paddle.to_tensor(np.full((4,), float(rank + 1), dtype="float32"))
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), 3.0)

paddle.seed(0)
cfg = LlamaConfig.tiny(num_hidden_layers=1, use_flash_attention=False,
                       num_attention_heads=4, num_key_value_heads=2)
model = LlamaForCausalLM(cfg)
model = dist.fleet.distributed_model(model)
optimizer = opt.AdamW(1e-3, parameters=model.parameters())

def loss_fn(m, x, y):
    loss, _ = m(x, labels=y)
    return loss

step = parallelize(model, loss_fn, optimizer)
ids = np.random.RandomState(5).randint(0, cfg.vocab_size, (8, 33))
losses = [float(np.asarray(step(paddle.to_tensor(ids[:, :-1]),
                                paddle.to_tensor(ids[:, 1:])).numpy()))
          for _ in range(2)]
with open(os.path.join(out_dir, f"rank{rank}.pkl"), "wb") as f:
    pickle.dump({"rank": rank, "losses": losses}, f)
print(f"rank {rank} OK", flush=True)
'''


@pytest.mark.slow
def test_two_process_one_mesh_dist_train_step(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "logs"), str(worker), str(tmp_path)],
        cwd="/root/repo", capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert r.returncode == 0, r.stdout + "\n" + r.stderr

    results = []
    for rank in range(2):
        with open(tmp_path / f"rank{rank}.pkl", "rb") as f:
            results.append(pickle.load(f))
    # both ranks observed the SAME global losses (one mesh, one computation)
    assert results[0]["losses"] == results[1]["losses"]

    # single-process reference over the same 8 devices (this process's
    # virtual mesh), same seeds/degrees/data
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.distributed.engine import parallelize

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "sep_degree": 1, "sharding_degree": 2,
                               "pp_degree": 1}
    strategy.sharding_configs = {"stage": 3}
    try:
        dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=1, use_flash_attention=False,
                               num_attention_heads=4, num_key_value_heads=2)
        model = LlamaForCausalLM(cfg)
        model = dist.fleet.distributed_model(model)
        optimizer = opt.AdamW(1e-3, parameters=model.parameters())

        def loss_fn(m, x, y):
            loss, _ = m(x, labels=y)
            return loss

        step = parallelize(model, loss_fn, optimizer)
        ids = np.random.RandomState(5).randint(0, cfg.vocab_size, (8, 33))
        ref = [float(np.asarray(step(paddle.to_tensor(ids[:, :-1]),
                                     paddle.to_tensor(ids[:, 1:])).numpy()))
               for _ in range(2)]
    finally:
        dist.set_hybrid_communicate_group(None)
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=1e-6)


_PP_WORKER = r'''
import os, pickle, sys
import numpy as np

out_dir = sys.argv[1]
rank = int(os.environ["PADDLE_TRAINER_ID"])
xport = int(sys.argv[2 + rank])  # per-rank pre-reserved socket port

import jax
jax.config.update("jax_platforms", "cpu")
# CPU backend needs jax's DCN socket transfers for the stage->stage hops;
# TPU PjRt supports cross-host transfers natively
jax.config.update("jax_cross_host_transfer_socket_address",
                  f"127.0.0.1:{xport}")

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe
from paddle_tpu.optimizer import SGD

s = dist.DistributedStrategy()
s.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                    "sharding_degree": 2, "sep_degree": 1}
s.sharding_configs = {"stage": 3}
dist.fleet.init(is_collective=True, strategy=s)
assert jax.device_count() == 8 and jax.local_device_count() == 4
paddle.seed(0)
cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False)
pipe = LlamaForCausalLMPipe(cfg)
pp = dist.fleet.distributed_model(pipe)
assert pp._hybrid and pp._multiproc
# each pipeline stage's submesh is one process's devices
owners = [sorted({d.process_index for d in pm.jax_mesh().devices.flat})
          for pm in pp._stage_meshes]
assert owners == [[0], [1]], owners
opt = SGD(0.05, parameters=pipe.parameters())
rng = np.random.RandomState(0)
ids = rng.randint(0, cfg.vocab_size, (4, 17))
losses = [float(np.asarray(pp.train_batch(
    [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])], opt)))
    for _ in range(2)]

# interleaved VPP leg CROSS-HOST: S=2 stages (one per process) x V=2
# chunks, Megatron-interleaved order over the same socket transfers
paddle.seed(4)
import dataclasses
cfg4 = dataclasses.replace(cfg, num_hidden_layers=4)  # 4 parts for S2xV2
s.pipeline_configs = {"schedule_mode": "VPP", "accumulate_steps": 4}
vpipe = LlamaForCausalLMPipe(cfg4, num_virtual_pipeline_stages=2)
vpp = dist.fleet.distributed_model(vpipe)
assert vpp._schedule == "VPP"
assert vpp._hybrid and vpp._multiproc
vopt = SGD(0.05, parameters=vpipe.parameters())
vloss = float(np.asarray(vpp.train_batch(
    [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])], vopt)))
from paddle_tpu.distributed.pipeline import interleaved_order
expect = interleaved_order(2, 2, vpp._accumulate_steps)
for s_ in range(2):
    got = [e for e in vpp.op_log if e[1] % 2 == s_]
    assert got == expect[s_], f"stage {s_} not interleaved"
losses.append(vloss)
with open(os.path.join(out_dir, f"pp_rank{rank}.pkl"), "wb") as f:
    pickle.dump(losses, f)
print(f"rank {rank} OK", flush=True)
'''


@pytest.mark.slow
def test_cross_host_pipeline_parallel(tmp_path):
    """CROSS-HOST pipeline parallelism: 2 launched processes form one
    8-device mesh; stage 0's submesh lives entirely on process 0, stage 1's
    on process 1 (the TPU pod pp-across-hosts topology). The same SPMD
    scheduler runs everywhere — stage jits no-op off-owner, activations hop
    between hosts via _cross_put — and the loss trajectory matches the
    single-process hybrid run exactly."""
    worker = tmp_path / "ppworker.py"
    worker.write_text(_PP_WORKER)
    socks = [socket.socket() for _ in range(3)]
    for sk in socks:
        sk.bind(("127.0.0.1", 0))
    port = socks[0].getsockname()[1]
    xport = socks[1].getsockname()[1]
    # worker rank r binds xport + r: reserve both, release just before use
    xport2 = socks[2].getsockname()[1]
    for sk in socks:
        sk.close()

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "logs"), str(worker), str(tmp_path),
         str(xport), str(xport2)],
        cwd="/root/repo", capture_output=True, text=True, timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=4"})
    assert r.returncode == 0, r.stdout + "\n" + r.stderr
    results = []
    for rank in range(2):
        with open(tmp_path / f"pp_rank{rank}.pkl", "rb") as f:
            results.append(pickle.load(f))
    assert results[0] == results[1]          # both hosts agree
    assert results[0][1] < results[0][0]     # learns
    assert np.isfinite(results[0][2])        # cross-host VPP leg ran

    # single-process reference: identical seeds/config on this process's
    # 8 virtual devices
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe
    from paddle_tpu.optimizer import SGD

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sep_degree": 1}
    strategy.sharding_configs = {"stage": 3}
    try:
        dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2, use_flash_attention=False)
        pipe = LlamaForCausalLMPipe(cfg)
        pp = dist.fleet.distributed_model(pipe)
        opt = SGD(0.05, parameters=pipe.parameters())
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 17))
        ref = [float(np.asarray(pp.train_batch(
            [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])],
            opt))) for _ in range(2)]
    finally:
        dist.set_hybrid_communicate_group(None)
    np.testing.assert_allclose(results[0][:2], ref, rtol=1e-6)
