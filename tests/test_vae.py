"""AutoencoderKL tests (the VAE half of the DiT/SD3 latent pipeline)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.vision.models.vae import (AutoencoderKL, DiagonalGaussian,
                                          VAEConfig)


def test_vae_roundtrip_shapes():
    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny())
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, 16, 16).astype("float32"))
    post = vae.encode(x)
    z = post.sample()
    # 2 mults -> one downsample: 16 -> 8 spatial, latent_channels=4
    assert tuple(z.shape) == (2, 4, 8, 8)
    recon = vae.decode(z)
    assert tuple(recon.shape) == (2, 3, 16, 16)
    assert np.isfinite(recon.numpy()).all()


def test_vae_posterior_stats_and_kl():
    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny())
    x = paddle.to_tensor(
        np.random.RandomState(1).rand(2, 3, 16, 16).astype("float32"))
    post = vae.encode(x)
    kl = post.kl().numpy()
    assert kl.shape == (2,) and (kl >= 0).all()
    # mode is deterministic; samples differ draw to draw
    m1 = post.mode().numpy()
    m2 = post.mode().numpy()
    np.testing.assert_array_equal(m1, m2)
    s1 = post.sample().numpy()
    s2 = post.sample().numpy()
    assert np.abs(s1 - s2).max() > 0


def test_vae_trains_under_train_step():
    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny())
    o = opt.AdamW(1e-3, parameters=vae.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(2).rand(2, 3, 16, 16).astype("float32"))
    step = paddle.jit.train_step(vae, lambda m, a: m.loss(a), o)
    l0 = float(step(x).numpy())
    for _ in range(5):
        l1 = float(step(x).numpy())
    assert np.isfinite(l1) and l1 < l0


def test_sd3_vae_pairing():
    """The SD3 preset must pair with MMDiTConfig defaults (16 latent
    channels), and the shift+scale roundtrip must invert exactly."""
    from paddle_tpu.models.sd3 import MMDiTConfig

    assert VAEConfig.sd3().latent_channels == MMDiTConfig().in_channels
    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny(latent_channels=16,
                                       scaling_factor=1.5305,
                                       shift_factor=0.0609))
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(1, 3, 16, 16).astype("float32"))
    z = vae.encode(x).mode()
    rt = vae.unscale_latents(vae.scale_latents(z))
    np.testing.assert_allclose(rt.numpy(), z.numpy(), rtol=1e-5, atol=1e-6)


def test_vae_latents_feed_dit():
    """End-to-end latent pipeline: VAE-encode -> scale -> DiT eps loss."""
    from paddle_tpu.models.sd3 import ddpm_eps_loss
    from paddle_tpu.vision.models.dit import DiT, DiTConfig

    paddle.seed(0)
    vae = AutoencoderKL(VAEConfig.tiny())
    d = DiT(DiTConfig.tiny())  # input_size=8 matches the tiny VAE latent
    x = paddle.to_tensor(
        np.random.RandomState(3).rand(2, 3, 16, 16).astype("float32"))
    z = vae.scale_latents(vae.encode(x).sample())
    y = paddle.to_tensor(np.array([1, 2], dtype="int64"))
    loss = ddpm_eps_loss(d, z, y)
    assert np.isfinite(float(loss.numpy()))
