"""Pipeline-parallel tests: segmentation, schedules, and loss/param parity
between pipelined and sequential training (the reference's loss-parity test
style, SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.pipeline import (
    LayerDesc, SharedLayerDesc, SegmentLayers, PipelineLayer, PipelineParallel,
    fthenb_order, one_f_one_b_order,
)
from paddle_tpu.optimizer import SGD


def _mse(out, label):
    diff = out - label
    return (diff * diff).mean()


def _make_descs(width=16, n_blocks=8):
    return [LayerDesc(nn.Linear, width, width) for _ in range(n_blocks)]


def _snapshot(layer):
    return {k: np.asarray(v._array) for k, v in layer.state_dict().items()}


def _load(layer, snap):
    import jax.numpy as jnp

    own = layer.state_dict()
    for k, v in snap.items():
        own[k]._array = jnp.asarray(v)


class TestSegmentLayers:
    def test_uniform_even(self):
        seg = SegmentLayers(_make_descs(n_blocks=8), 4, "uniform")
        assert seg.do_segment() == [0, 2, 4, 6, 8]

    def test_uniform_remainder(self):
        seg = SegmentLayers(_make_descs(n_blocks=10), 4, "uniform")
        parts = seg.do_segment()
        assert parts[0] == 0 and parts[-1] == 10
        sizes = [parts[i + 1] - parts[i] for i in range(4)]
        assert sorted(sizes) == [2, 2, 3, 3]
        # remainder goes to the earliest stages (reference behavior)
        assert sizes == [3, 3, 2, 2]

    def test_layer_name_method(self):
        descs = []
        for _ in range(4):
            descs.append(LayerDesc(nn.Linear, 8, 8))
            descs.append(LayerDesc(nn.GELU))
        seg = SegmentLayers(descs, 4, "layer:Linear")
        parts = seg.do_segment()
        assert parts == [0, 2, 4, 6, 8]

    def test_too_few_layers_raises(self):
        with pytest.raises(ValueError):
            SegmentLayers(_make_descs(n_blocks=2), 4, "uniform")


class TestSchedules:
    def test_1f1b_local_orders(self):
        order = one_f_one_b_order(num_stages=4, num_micro=8)
        # last stage strictly alternates F,B from the start
        assert order[3][:6] == [("fwd", 0), ("bwd", 0), ("fwd", 1), ("bwd", 1), ("fwd", 2), ("bwd", 2)]
        # first stage warms up with (S-1)=3 forwards
        assert order[0][:3] == [("fwd", 0), ("fwd", 1), ("fwd", 2)]
        assert order[0][3] == ("fwd", 3)
        assert order[0][4] == ("bwd", 0)
        for s in range(4):
            assert len(order[s]) == 16
            assert order[s].count(("fwd", 7)) == 1 and order[s].count(("bwd", 7)) == 1

    def test_fthenb_local_orders(self):
        order = fthenb_order(2, 4)
        assert order[0] == [("fwd", m) for m in range(4)] + [("bwd", m) for m in range(4)]


class TestPipelineForward:
    def test_forward_matches_sequential(self):
        paddle.seed(7)
        pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        x = paddle.to_tensor(np.random.randn(4, 16).astype("float32"))
        out = pipe(x)
        # sequential application of the same built layers
        y = x
        for part in range(4):
            y = pipe.get_stage_layer(part)(y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(y), rtol=1e-6)


class TestTrainParity:
    @pytest.mark.parametrize("schedule", ["1F1B", "FThenB", "ZBH1"])
    def test_param_parity_vs_sequential(self, schedule):
        paddle.seed(11)
        pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        snap = _snapshot(pipe)

        paddle.seed(99)  # different init, will be overwritten by snapshot
        ref = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        _load(ref, snap)

        pp = PipelineParallel(pipe, accumulate_steps=4, schedule=schedule)
        opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters())
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters())

        rng = np.random.RandomState(0)
        for step in range(3):
            x = rng.randn(8, 16).astype("float32")
            lbl = rng.randn(8, 16).astype("float32")
            loss_p = pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)

            xt = paddle.to_tensor(x)
            out = ref(xt)
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()

            np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)

        for (k, p), (k2, p2) in zip(sorted(pipe.state_dict().items()),
                                    sorted(ref.state_dict().items())):
            assert k == k2
            np.testing.assert_allclose(np.asarray(p._array), np.asarray(p2._array),
                                       rtol=2e-5, atol=2e-6)

    def test_op_log_is_valid_1f1b(self):
        paddle.seed(3)
        pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        pp = PipelineParallel(pipe, accumulate_steps=8, schedule="1F1B")
        opt = SGD(learning_rate=0.01, parameters=pipe.parameters())
        x = np.random.randn(8, 16).astype("float32")
        pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)

        log = pp.op_log
        assert len(log) == 2 * 4 * 8  # fwd+bwd per stage per micro
        done = set()
        for op, s, mb in log:
            if op == "fwd":
                assert s == 0 or ("fwd", s - 1, mb) in done
            else:
                assert ("fwd", s, mb) in done
                assert s == 3 or ("bwd", s + 1, mb) in done
            done.add((op, s, mb))
        # per-stage projection equals the canonical local 1F1B order
        expect = one_f_one_b_order(4, 8)
        for s in range(4):
            local = [(op, mb) for op, st, mb in log if st == s]
            assert local == expect[s]


class TestZeroBubble:
    """ZB-H1 schedule (ref passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62):
    backward split into B (activation grads) and W (weight grads)."""

    def test_zbh1_local_orders(self):
        from paddle_tpu.distributed.pipeline import zero_bubble_order

        order = zero_bubble_order(num_stages=4, num_micro=8)
        for s in range(4):
            ops = order[s]
            assert len(ops) == 3 * 8  # F, B, W per micro
            # W(mb) strictly after B(mb)
            for mb in range(8):
                assert ops.index(("bwd_w", mb)) > ops.index(("bwd_b", mb))
            # deferral bound: at any prefix, #B - #W <= S-1-s ... +1 slack
            max_def = 0
            b = w = 0
            for op, _mb in ops:
                b += op == "bwd_b"
                w += op == "bwd_w"
                max_def = max(max_def, b - w)
            assert max_def <= max(4 - 1 - s, 1)
        # last stage (deferral bound 0) runs F, B, W triplets from the start
        assert order[3][:6] == [("fwd", 0), ("bwd_b", 0), ("bwd_w", 0),
                                ("fwd", 1), ("bwd_b", 1), ("bwd_w", 1)]
        # zero-bubble property: the first stage's cooldown interleaves W
        # between the trailing B's instead of the 1F1B bubble
        tail = order[0][-8:]
        assert ("bwd_w", 7) == tail[-1]
        assert any(op == "bwd_w" for op, _ in order[0][:-(8 - 4)][-6:])

    def test_zbh1_op_log_dependencies(self):
        paddle.seed(3)
        pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        pp = PipelineParallel(pipe, accumulate_steps=8, schedule="ZBH1")
        opt = SGD(learning_rate=0.01, parameters=pipe.parameters())
        x = np.random.randn(8, 16).astype("float32")
        pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)

        log = pp.op_log
        assert len(log) == 3 * 4 * 8
        done = set()
        for op, s, mb in log:
            if op == "fwd":
                assert s == 0 or ("fwd", s - 1, mb) in done
            elif op == "bwd_b":
                assert ("fwd", s, mb) in done
                assert s == 3 or ("bwd_b", s + 1, mb) in done
            else:
                assert op == "bwd_w"
                assert ("bwd_b", s, mb) in done
            done.add((op, s, mb))
        # per-stage projection equals the canonical ZBH1 local order
        from paddle_tpu.distributed.pipeline import zero_bubble_order

        expect = zero_bubble_order(4, 8)
        for s in range(4):
            local = [(op, mb) for op, st, mb in log if st == s]
            assert local == expect[s]

    def test_zbh1_from_strategy(self):
        import paddle_tpu.distributed as dist

        strategy = dist.DistributedStrategy()
        strategy.pipeline_configs = {"schedule_mode": "ZBH1",
                                     "accumulate_steps": 4}
        paddle.seed(3)
        pipe = PipelineLayer(_make_descs(), num_stages=2, loss_fn=_mse)
        pp = PipelineParallel(pipe, strategy=strategy)
        assert pp._schedule == "ZBH1"
        assert pp._accumulate_steps == 4


class TestSharedLayers:
    def test_shared_desc_ties_weights(self):
        paddle.seed(5)
        V, H = 32, 16

        # first stage embeds via gather, last stage projects with the SAME weight
        class TiedEmbed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.weight = self.create_parameter([V, H])

            def forward(self, x):
                import paddle_tpu.nn.functional as F

                return F.embedding(x, self.weight)

        def head_fwd(layer, h):
            return paddle.matmul(h, layer.weight, transpose_y=True)

        descs = [
            SharedLayerDesc("emb", TiedEmbed),
            LayerDesc(nn.Linear, H, H),
            LayerDesc(nn.Linear, H, H),
            SharedLayerDesc("emb", TiedEmbed, forward_func=head_fwd),
        ]
        pipe = PipelineLayer(descs, num_stages=4,
                             loss_fn=lambda out, lbl: paddle.nn.functional.cross_entropy(
                                 out.reshape([-1, V]), lbl.reshape([-1])).mean())
        # one shared weight object across both stages
        names = [k for k, _ in pipe.named_parameters() if k.endswith("weight")]
        embeds = [pipe.get_stage_layer(0)._items[0], pipe.get_stage_layer(3)._items[0]]
        assert embeds[0] is embeds[1]
        n_emb_params = sum(1 for k in names if "stage_0" in k or "stage_3" in k)
        assert n_emb_params == 1  # deduped in named_parameters

        pp = PipelineParallel(pipe, accumulate_steps=2, schedule="1F1B")
        opt = SGD(learning_rate=0.05, parameters=pipe.parameters())
        ids = np.random.randint(0, V, (4, 6)).astype("int32")
        before = np.asarray(embeds[0].weight._array).copy()
        loss = pp.train_batch([paddle.to_tensor(ids), paddle.to_tensor(ids.astype("int64"))], opt)
        after = np.asarray(embeds[0].weight._array)
        assert np.isfinite(float(loss))
        assert not np.allclose(before, after)  # tied weight received grads


def _clock_sim(seqs, nparts):
    """Clocked execution of per-physical-stage op sequences: each tick every
    stage retires at most ONE ready op (ops cost one tick each — the
    standard bubble accounting). Returns (total_ticks, bubble_ticks) where
    a bubble tick is a stage idling while it still has work queued."""
    heads = {s: 0 for s in seqs}
    done = set()
    ticks = bubbles = 0

    def ready(op, part, mb):
        if op == "fwd":
            return part == 0 or ("fwd", part - 1, mb) in done
        return ("fwd", part, mb) in done and (
            part == nparts - 1 or ("bwd", part + 1, mb) in done)

    while any(heads[s] < len(seqs[s]) for s in seqs):
        fired = [(s, seqs[s][heads[s]]) for s in seqs
                 if heads[s] < len(seqs[s]) and ready(*seqs[s][heads[s]])]
        assert fired, "clock simulation deadlocked"
        waiting = sum(1 for s in seqs if heads[s] < len(seqs[s]))
        bubbles += waiting - len(fired)
        for s, e in fired:
            heads[s] += 1
            done.add(e)
        ticks += 1
    return ticks, bubbles


class TestInterleaved:
    def test_interleaved_local_order_properties(self):
        """Megatron-style interleave at S=2/V=2/m=4: stage s holds parts
        {s, S+s}; forwards walk chunk 0 micros 0..S-1 then chunk 1 micros
        0..S-1; backwards walk chunks in reverse."""
        from paddle_tpu.distributed.pipeline import interleaved_order

        order = interleaved_order(num_stages=2, num_virtual=2, num_micro=4)
        S, V, m = 2, 2, 4
        for s in (0, 1):
            seq = order[s]
            assert len(seq) == 2 * V * m
            # every (part, micro) appears exactly once per op kind
            for c in range(V):
                part = c * S + s
                for mb in range(m):
                    assert seq.count(("fwd", part, mb)) == 1
                    assert seq.count(("bwd", part, mb)) == 1
        # stage 0 warmup = (S-1-0)*2 + (V-1)*S = 4 forwards:
        # chunk0 micros 0,1 then chunk1 micros 0,1
        assert order[0][:4] == [("fwd", 0, 0), ("fwd", 0, 1),
                                ("fwd", 2, 0), ("fwd", 2, 1)]
        # first backward on stage 0 is the LAST chunk (part 2), micro 0
        first_bwd = next(e for e in order[0] if e[0] == "bwd")
        assert first_bwd == ("bwd", 2, 0)
        # stage 1 warmup = (S-1-1)*2 + (V-1)*S = 2 forwards
        assert order[1][:2] == [("fwd", 1, 0), ("fwd", 1, 1)]
        assert order[1][2] == ("fwd", 3, 0)
        assert order[1][3] == ("bwd", 3, 0)

    def test_interleaved_preconditions(self):
        from paddle_tpu.distributed.pipeline import interleaved_order

        with pytest.raises(ValueError):  # V must exceed 1
            interleaved_order(num_stages=2, num_virtual=1, num_micro=4)
        with pytest.raises(ValueError):  # m must divide by S
            interleaved_order(num_stages=2, num_virtual=2, num_micro=3)

    def test_interleaved_fewer_bubbles_than_1f1b(self):
        """The VPP claim (ref pipeline_parallel.py:1174): interleaving the
        V chunks lets early backwards start (V-1)*S slots sooner, so the
        clocked schedule at S=2/V=2/m=8 drains in strictly fewer ticks —
        and with strictly fewer bubble slots — than depth-first 1F1B over
        the same 4-part chain executed on the same 2 physical stages."""
        from paddle_tpu.distributed.pipeline import interleaved_order

        S, V, m = 2, 2, 8
        vpp = interleaved_order(S, V, m)

        # baseline: the actual op_log of a 1F1B run over nparts=S*V,
        # projected onto physical stages (stage = part % S)
        paddle.seed(5)
        pipe = PipelineLayer(_make_descs(), num_stages=S, loss_fn=_mse,
                             num_virtual_pipeline_stages=V)
        pp = PipelineParallel(pipe, accumulate_steps=m, schedule="1F1B")
        opt = SGD(learning_rate=0.01, parameters=pipe.parameters())
        x = np.random.randn(8, 16).astype("float32")
        pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)
        base = {s: [(op, part, mb) for op, part, mb in pp.op_log
                    if part % S == s] for s in range(S)}

        ticks_v, bub_v = _clock_sim(vpp, S * V)
        ticks_b, bub_b = _clock_sim(base, S * V)
        assert ticks_v < ticks_b, (ticks_v, ticks_b)
        assert bub_v < bub_b, (bub_v, bub_b)

    def test_interleaved_param_parity(self):
        """schedule="VPP" end-to-end: S=2 x V=2 over 8 blocks, m=4 —
        loss and updated params match sequential training."""
        paddle.seed(23)
        pipe = PipelineLayer(_make_descs(), num_stages=2, loss_fn=_mse,
                             num_virtual_pipeline_stages=2)
        snap = _snapshot(pipe)
        ref = PipelineLayer(_make_descs(), num_stages=2, loss_fn=_mse,
                            num_virtual_pipeline_stages=2)
        _load(ref, snap)

        pp = PipelineParallel(pipe, accumulate_steps=4, schedule="VPP")
        opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters())
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters())
        rng = np.random.RandomState(2)
        for _ in range(2):
            x = rng.randn(8, 16).astype("float32")
            lbl = rng.randn(8, 16).astype("float32")
            loss_p = pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)
            out = ref(paddle.to_tensor(x))
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)
        for (k, p), (k2, p2) in zip(sorted(pipe.state_dict().items()),
                                    sorted(ref.state_dict().items())):
            assert k == k2
            np.testing.assert_allclose(np.asarray(p._array), np.asarray(p2._array),
                                       rtol=2e-5, atol=2e-6)
        # the op_log per physical stage matches the canonical interleaved order
        from paddle_tpu.distributed.pipeline import interleaved_order
        expect = interleaved_order(2, 2, 4)
        for s in range(2):
            local = [e for e in pp.op_log if e[1] % 2 == s]
            assert local == expect[s]

    def test_unknown_schedule_raises(self):
        paddle.seed(4)
        pipe = PipelineLayer(_make_descs(n_blocks=4), num_stages=2, loss_fn=_mse)
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            PipelineParallel(pipe, accumulate_steps=2, schedule="bogus")
        # a post-construction override (test/tooling path) fails at run time
        pp = PipelineParallel(pipe, accumulate_steps=2, schedule="1F1B")
        pp._schedule = "not-a-schedule"
        opt = SGD(learning_rate=0.01, parameters=pipe.parameters())
        x = np.random.randn(4, 16).astype("float32")
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)

    def test_vpp_param_parity(self):
        """Virtual pipeline stages (VPP): S=2 stages x V=2 chunks over 8
        blocks; parity vs sequential training."""
        paddle.seed(21)
        pipe = PipelineLayer(_make_descs(), num_stages=2, loss_fn=_mse,
                             num_virtual_pipeline_stages=2)
        assert len(pipe._stages) == 4
        snap = _snapshot(pipe)
        ref = PipelineLayer(_make_descs(), num_stages=2, loss_fn=_mse,
                            num_virtual_pipeline_stages=2)
        _load(ref, snap)

        pp = PipelineParallel(pipe, accumulate_steps=4, schedule="1F1B")
        opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters())
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters())
        rng = np.random.RandomState(1)
        for _ in range(2):
            x = rng.randn(8, 16).astype("float32")
            lbl = rng.randn(8, 16).astype("float32")
            loss_p = pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)
            out = ref(paddle.to_tensor(x))
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)


class TestFleetIntegration:
    def test_distributed_model_wraps_pipeline(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed import fleet

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 2}
        strategy.pipeline_configs = {"accumulate_steps": 2, "micro_batch_size": 2}
        fleet.init(is_collective=True, strategy=strategy)
        pipe = PipelineLayer(_make_descs(n_blocks=4), num_stages=2, loss_fn=_mse)
        model = fleet.distributed_model(pipe)
        assert isinstance(model, PipelineParallel)
        assert model._accumulate_steps == 2
        opt = SGD(learning_rate=0.05, parameters=pipe.parameters())
        x = np.random.randn(4, 16).astype("float32")
        loss = model.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)
        assert np.isfinite(float(loss))


class TestReviewRegressions:
    def test_segment_by_params_monotonic(self):
        """Boundaries must be strictly monotonic with no empty/duplicated
        segments, even with one dominant prebuilt layer."""
        big = nn.Linear(100, 100)
        descs = [LayerDesc(nn.Linear, 4, 4), LayerDesc(nn.Linear, 4, 4),
                 LayerDesc(nn.Linear, 4, 4), big]
        parts = SegmentLayers(descs, 3, "parameter").do_segment()
        assert parts[0] == 0 and parts[-1] == 4
        assert all(parts[i] < parts[i + 1] for i in range(3))

    def test_batchnorm_running_stats_update(self):
        """BN running stats mutated inside a stage forward must survive the
        functional stage boundary (threaded out as new_buffers)."""
        paddle.seed(17)
        descs = [LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.BatchNorm1D, 8),
                 LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.BatchNorm1D, 8)]
        pipe = PipelineLayer(descs, num_stages=2, loss_fn=_mse)
        pipe.train()
        pp = PipelineParallel(pipe, accumulate_steps=2, schedule="1F1B")
        opt = SGD(learning_rate=0.01, parameters=pipe.parameters())
        bn = pipe.get_stage_layer(0)._items[1]
        before = np.asarray(bn._mean._array).copy()
        x = np.random.randn(8, 8).astype("float32") * 3 + 1
        pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(x)], opt)
        after = np.asarray(bn._mean._array)
        assert not np.allclose(before, after)

    def test_global_norm_clip_parity(self):
        """ClipGradByGlobalNorm must clip against the ALL-parameter norm even
        when stages live on different devices."""
        from paddle_tpu.optimizer.clip import ClipGradByGlobalNorm

        paddle.seed(23)
        pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        snap = _snapshot(pipe)
        ref = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
        _load(ref, snap)

        pp = PipelineParallel(pipe, accumulate_steps=4)
        clip_val = 0.05  # small enough that clipping definitely activates
        opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters(),
                    grad_clip=ClipGradByGlobalNorm(clip_val))
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters(),
                    grad_clip=ClipGradByGlobalNorm(clip_val))
        rng = np.random.RandomState(4)
        for _ in range(2):
            x = rng.randn(8, 16).astype("float32")
            lbl = rng.randn(8, 16).astype("float32") * 5
            pp.train_batch([paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)
            out = ref(paddle.to_tensor(x))
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
        for (k, p), (k2, p2) in zip(sorted(pipe.state_dict().items()),
                                    sorted(ref.state_dict().items())):
            np.testing.assert_allclose(np.asarray(p._array), np.asarray(p2._array),
                                       rtol=3e-5, atol=3e-6)


class TestHybridMeshPP:
    """PP fused with the other parallel axes on ONE 5-axis mesh (VERDICT r3
    item 2; ref topology.py:189 + pipeline_parallel.py:820): each stage owns
    the (dp, sharding, sep, mp) submesh at its pp coordinate, in-stage
    TP/FSDP collectives ride GSPMD, activations hop between submeshes."""

    @staticmethod
    def _tp_descs(width, n_blocks):
        import paddle_tpu.nn as pnn
        from paddle_tpu.distributed.parallel_layers import (
            ColumnParallelLinear, RowParallelLinear)

        class Block(pnn.Layer):
            def __init__(self, w):
                super().__init__()
                self.col = ColumnParallelLinear(w, 2 * w, gather_output=False)
                self.row = RowParallelLinear(2 * w, w, input_is_parallel=True)

            def forward(self, x):
                return self.row(self.col(x)) + x

        return [LayerDesc(Block, width) for _ in range(n_blocks)]

    def _run_parity(self, hybrid_configs, schedule, sharding_stage=3,
                    steps=2, width=16):
        import paddle_tpu.distributed as dist

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = hybrid_configs
        strategy.sharding_configs = {"stage": sharding_stage}
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            pipe = PipelineLayer(self._tp_descs(width, 4),
                                 num_stages=hybrid_configs["pp_degree"],
                                 loss_fn=_mse)
            snap = _snapshot(pipe)
            pp = dist.fleet.distributed_model(pipe)
            assert pp._hybrid, "hcg with pp>1 must enter hybrid-mesh mode"
            # stages must own DISJOINT submeshes covering the whole mesh
            stage_devsets = [frozenset(d.id for d in pm.jax_mesh().devices.flat)
                             for pm in pp._stage_meshes]
            assert len(set(stage_devsets)) == hybrid_configs["pp_degree"]
            assert not frozenset.intersection(*stage_devsets)
            pp._schedule = schedule
            opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters())
        finally:
            dist.set_hybrid_communicate_group(None)

        paddle.seed(0)
        ref = PipelineLayer(self._tp_descs(width, 4),
                            num_stages=hybrid_configs["pp_degree"],
                            loss_fn=_mse)
        _load(ref, snap)
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters())
        rng = np.random.RandomState(0)
        for _ in range(steps):
            x = rng.randn(8, width).astype("float32")
            lbl = rng.randn(8, width).astype("float32")
            # no ambient hcg needed: stage calls install their stage-local
            # hcg themselves (_ambient_stage_hcg)
            loss_p = pp.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)
            out = ref(paddle.to_tensor(x))
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(loss_p), float(loss_r), rtol=1e-5)
        for (k, p), (k2, p2) in zip(sorted(pipe.state_dict().items()),
                                    sorted(ref.state_dict().items())):
            assert k == k2
            np.testing.assert_allclose(np.asarray(p._array),
                                       np.asarray(p2._array),
                                       rtol=2e-5, atol=2e-6)

    def test_pp_mp_sharding_parity_1f1b(self):
        self._run_parity({"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                          "sharding_degree": 2, "sep_degree": 1}, "1F1B")

    def test_pp_mp_sharding_parity_zbh1(self):
        self._run_parity({"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                          "sharding_degree": 2, "sep_degree": 1}, "ZBH1")

    def test_pp_dp_mp_parity(self):
        """dp>1 under PP: batch dim sharded over dp inside each stage."""
        self._run_parity({"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                          "sharding_degree": 1, "sep_degree": 1}, "1F1B",
                         sharding_stage=0)

    def test_pp_degree_mismatch_raises(self):
        import paddle_tpu.distributed as dist

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                                   "pp_degree": 2, "sharding_degree": 1,
                                   "sep_degree": 1}
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            pipe = PipelineLayer(_make_descs(), num_stages=4, loss_fn=_mse)
            with pytest.raises(ValueError, match="pp degree"):
                PipelineParallel(pipe, hcg=dist.get_hybrid_communicate_group())
        finally:
            dist.set_hybrid_communicate_group(None)


class TestHybridSharedLayers:
    def test_shared_tied_weights_hybrid_parity(self):
        """SharedLayerDesc under the hybrid mesh: the tied weight's canonical
        copy lives on the FIRST stage's submesh; the last stage computes on a
        transferred replica (train via _stage_state, inference via forward).
        Loss parity vs single-device, and the tied weight trains."""
        import paddle_tpu.distributed as dist
        import paddle_tpu.nn.functional as F

        V, H = 32, 16

        class TiedEmbed(nn.Layer):
            def __init__(self):
                super().__init__()
                self.weight = self.create_parameter([V, H])

            def forward(self, x):
                return F.embedding(x, self.weight)

        def head_fwd(layer, h):
            return paddle.matmul(h, layer.weight, transpose_y=True)

        def make_pipe():
            return PipelineLayer(
                [SharedLayerDesc("emb", TiedEmbed),
                 LayerDesc(nn.Linear, H, H),
                 LayerDesc(nn.Linear, H, H),
                 SharedLayerDesc("emb", TiedEmbed, forward_func=head_fwd)],
                num_stages=2,
                loss_fn=lambda out, lbl: F.cross_entropy(
                    out.reshape([-1, V]), lbl.reshape([-1])).mean())

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                                   "pp_degree": 2, "sharding_degree": 2,
                                   "sep_degree": 1}
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(5)
            pipe = make_pipe()
            snap = _snapshot(pipe)
            pp = dist.fleet.distributed_model(pipe)
            assert pp._hybrid
            opt_p = SGD(learning_rate=0.05, parameters=pipe.parameters())
        finally:
            dist.set_hybrid_communicate_group(None)

        paddle.seed(5)
        ref = make_pipe()
        _load(ref, snap)
        opt_r = SGD(learning_rate=0.05, parameters=ref.parameters())

        rng = np.random.RandomState(3)
        ids = rng.randint(0, V, (4, 6)).astype("int32")
        lbl = ids.astype("int64")
        # hybrid inference forward crosses submeshes with the shared replica
        out_h = pp(paddle.to_tensor(ids))
        out_r = ref(paddle.to_tensor(ids))
        np.testing.assert_allclose(np.asarray(out_h.numpy()),
                                   np.asarray(out_r.numpy()),
                                   rtol=1e-5, atol=1e-6)
        for _ in range(2):
            loss_p = pp.train_batch(
                [paddle.to_tensor(ids), paddle.to_tensor(lbl)], opt_p)
            loss_r = ref._loss_fn(ref(paddle.to_tensor(ids)),
                                  paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(loss_p), float(loss_r),
                                       rtol=1e-5)
        for (k, p), (k2, p2) in zip(sorted(pipe.state_dict().items()),
                                    sorted(ref.state_dict().items())):
            assert k == k2
            np.testing.assert_allclose(np.asarray(p._array),
                                       np.asarray(p2._array),
                                       rtol=2e-5, atol=2e-6)


def _copy_pipe_weights(pipe, ref):
    """Map untied pipe stage params onto the monolithic model's params
    (shared by the Llama and DeepSeek pipe parity tests)."""
    import jax.numpy as jnp

    src = {}
    L = ref.config.num_hidden_layers
    items = []
    for part in range(len(pipe._stages)):
        items.extend(pipe.get_stage_layer(part)._items)
    emb, layers, head = items[0], items[1:1 + L], items[1 + L]
    src["llama.embed_tokens.weight"] = emb.embed_tokens.weight
    for i, lp in enumerate(layers):
        for name, p in lp.layer.named_parameters():
            src[f"llama.layers.{i}.{name}"] = p
    src["llama.norm.weight"] = head.norm.weight
    src["lm_head.weight"] = head.lm_head.weight
    own = dict(ref.named_parameters())
    assert set(own) == set(src), (set(own) ^ set(src))
    for k, p in src.items():
        own[k]._array = jnp.asarray(np.asarray(p._array))


class TestLlamaPipe:
    """LlamaForCausalLMPipe (PaddleNLP pipeline-llama pattern) under the
    hybrid mesh: pp2 x mp2 x sharding2 training parity vs LlamaForCausalLM
    with identical weights on one device."""

    def test_llama_pipe_hybrid_parity(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                             LlamaForCausalLMPipe)
        from paddle_tpu.models.llama import causal_lm_loss

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                                   "pp_degree": 2, "sharding_degree": 2,
                                   "sep_degree": 1}
        strategy.sharding_configs = {"stage": 3}
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            cfg = LlamaConfig.tiny(num_hidden_layers=2,
                                   use_flash_attention=False)
            pipe = LlamaForCausalLMPipe(cfg)
            assert pipe.num_stages == 2
            pp = dist.fleet.distributed_model(pipe)
            assert pp._hybrid
            opt_p = SGD(learning_rate=0.05, parameters=pipe.parameters())
        finally:
            dist.set_hybrid_communicate_group(None)

        paddle.seed(1)  # different init; weights copied from the pipe below
        ref = LlamaForCausalLM(cfg)
        _copy_pipe_weights(pipe, ref)
        opt_r = SGD(learning_rate=0.05, parameters=ref.parameters())

        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 17))
        x, y = ids[:, :-1], ids[:, 1:]
        for _ in range(2):
            loss_p = pp.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(y)], opt_p,
            )
            loss_r, _ = ref(paddle.to_tensor(x), labels=paddle.to_tensor(y))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(np.asarray(loss_p)),
                                       float(loss_r.numpy()), rtol=2e-5)


class TestDeepseekPipe:
    """DeepseekForCausalLMPipe: MLA + MoE (aux-free V3 routing) under
    pp2 x mp2 x sharding2 — training parity vs the monolithic
    DeepseekV2ForCausalLM with identical weights on one device."""

    def test_deepseek_pipe_hybrid_parity(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.models.deepseek import (DeepseekForCausalLMPipe,
                                                DeepseekV2Config,
                                                DeepseekV2ForCausalLM)

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                                   "pp_degree": 2, "sharding_degree": 2,
                                   "sep_degree": 1}
        strategy.sharding_configs = {"stage": 3}
        cfg = DeepseekV2Config.tiny_v3(num_hidden_layers=2,
                                       use_flash_attention=False)
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(0)
            pipe = DeepseekForCausalLMPipe(cfg)
            assert pipe.num_stages == 2
            pp = dist.fleet.distributed_model(pipe)
            assert pp._hybrid
            opt_p = SGD(learning_rate=0.05, parameters=pipe.parameters())
        finally:
            dist.set_hybrid_communicate_group(None)

        paddle.seed(1)  # different init; weights copied from the pipe below
        ref = DeepseekV2ForCausalLM(cfg)
        _copy_pipe_weights(pipe, ref)
        opt_r = SGD(learning_rate=0.05, parameters=ref.parameters())

        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 17))
        x, y = ids[:, :-1], ids[:, 1:]
        for _ in range(2):
            loss_p = pp.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(y)], opt_p)
            loss_r, _ = ref(paddle.to_tensor(x), labels=paddle.to_tensor(y))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(np.asarray(loss_p)),
                                       float(loss_r.numpy()), rtol=2e-5)

    def test_nonzero_aux_coef_rejected(self):
        from paddle_tpu.models.deepseek import (DeepseekForCausalLMPipe,
                                                DeepseekV2Config)

        cfg = DeepseekV2Config.tiny_mla()  # default aux coef 0.001
        with pytest.raises(NotImplementedError, match="aux"):
            DeepseekForCausalLMPipe(cfg, num_stages=1)


class TestHybridVPP:
    def test_vpp_under_hybrid_mesh_parity(self):
        """Interleaved VPP (S=2 stages x V=2 chunks) composed with mp2 on
        the hybrid mesh: chunks of a stage colocate on the stage's submesh
        (part % S mapping), loss parity vs single-device."""
        import paddle_tpu.distributed as dist

        strategy = dist.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                                   "pp_degree": 2, "sharding_degree": 2,
                                   "sep_degree": 1}
        descs = TestHybridMeshPP._tp_descs(16, 8)
        try:
            dist.fleet.init(is_collective=True, strategy=strategy)
            paddle.seed(13)
            pipe = PipelineLayer(descs, num_stages=2, loss_fn=_mse,
                                 num_virtual_pipeline_stages=2)
            snap = _snapshot(pipe)
            pp = PipelineParallel(pipe, hcg=dist.get_hybrid_communicate_group(),
                                  accumulate_steps=4, schedule="VPP")
            assert pp._hybrid and len(pipe._stages) == 4
            # chunk c of stage s colocates with stage s (part = c*S + s)
            assert pp._stage_meshes[0] is pp._stage_meshes[2]
            assert pp._stage_meshes[1] is pp._stage_meshes[3]
            opt_p = SGD(learning_rate=0.1, parameters=pipe.parameters())
        finally:
            dist.set_hybrid_communicate_group(None)

        paddle.seed(13)
        ref = PipelineLayer(TestHybridMeshPP._tp_descs(16, 8), num_stages=2,
                            loss_fn=_mse, num_virtual_pipeline_stages=2)
        _load(ref, snap)
        opt_r = SGD(learning_rate=0.1, parameters=ref.parameters())
        rng = np.random.RandomState(1)
        for _ in range(2):
            x = rng.randn(8, 16).astype("float32")
            lbl = rng.randn(8, 16).astype("float32")
            loss_p = pp.train_batch(
                [paddle.to_tensor(x), paddle.to_tensor(lbl)], opt_p)
            out = ref(paddle.to_tensor(x))
            loss_r = _mse(out, paddle.to_tensor(lbl))
            loss_r.backward()
            opt_r.step()
            opt_r.clear_grad()
            np.testing.assert_allclose(float(loss_p), float(loss_r),
                                       rtol=1e-5)


def test_llama_pipe_tied_embeddings_hybrid():
    """tie_word_embeddings in the pipe model: ONE shared weight serves the
    first-stage embedding and the last-stage head (SharedLayerDesc), and it
    receives gradients from both ends under the hybrid mesh."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLMPipe
    from paddle_tpu.optimizer import SGD as _SGD

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sep_degree": 1}
    try:
        dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2,
                               use_flash_attention=False,
                               tie_word_embeddings=True)
        pipe = LlamaForCausalLMPipe(cfg)
        embeds = [pipe.get_stage_layer(0)._items[0],
                  pipe.get_stage_layer(1)._items[-1]]
        assert embeds[0] is embeds[1]  # one shared layer object
        # no separate lm_head parameter exists
        names = [k for k, _ in pipe.named_parameters()]
        assert not any("lm_head" in k for k in names)
        pp = dist.fleet.distributed_model(pipe)
        opt = _SGD(learning_rate=0.05, parameters=pipe.parameters())
    finally:
        dist.set_hybrid_communicate_group(None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4, 17))
    before = np.asarray(embeds[0].embed_tokens.weight._array).copy()
    losses = [float(np.asarray(pp.train_batch(
        [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])], opt)))
        for _ in range(4)]
    after = np.asarray(embeds[0].embed_tokens.weight._array)
    assert losses[-1] < losses[0]          # learns
    assert not np.allclose(before, after)  # tied weight got grads


def test_llama_pipe_sep_ring_attention_hybrid():
    """pp2 x sep2 x sharding2: context parallelism (ring attention over the
    sep axis) runs INSIDE each pipeline stage's submesh — the last
    composition of the 5-axis topology. Instrumented to prove the ring path
    traced; loss parity vs single device (ring reorders the softmax
    reduction, so approximate)."""
    import paddle_tpu.distributed as dist
    import paddle_tpu.distributed.context_parallel as cp
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLMPipe,
                                         causal_lm_loss)
    import jax.numpy as jnp

    calls = []
    orig_ring = cp.ring_attention

    def counting_ring(*a, **k):
        calls.append(1)
        return orig_ring(*a, **k)

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sep_degree": 2}
    cp.ring_attention = counting_ring
    try:
        dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2,
                               use_flash_attention=False, sep_mode="ring")
        pipe = LlamaForCausalLMPipe(cfg)
        snap = _snapshot(pipe)
        pp = dist.fleet.distributed_model(pipe)
        opt = SGD(0.05, parameters=pipe.parameters())
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 33))  # seq 32 % sep2 == 0
        loss_p = float(np.asarray(pp.train_batch(
            [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])],
            opt)))
        assert calls, "ring attention must trace inside the stage jit"
    finally:
        cp.ring_attention = orig_ring
        dist.set_hybrid_communicate_group(None)

    paddle.seed(9)
    ref = LlamaForCausalLMPipe(cfg, num_stages=2)
    _load(ref, snap)
    out = ref(paddle.to_tensor(ids[:, :-1]))
    loss_r = float(causal_lm_loss(out, paddle.to_tensor(ids[:, 1:])).numpy())
    np.testing.assert_allclose(loss_p, loss_r, rtol=1e-5)


def test_tied_weights_global_norm_clip_hybrid():
    """Tied embeddings + ClipGradByGlobalNorm under the hybrid mesh: the
    shared param's grad accumulator lives on the LAST stage's submesh (its
    bwd runs first), so the lifted global-norm reduction must align grads
    to their params' placements before fusing (found by the pipeline
    example; parity vs single-device clip)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLMPipe,
                                         causal_lm_loss)
    from paddle_tpu.optimizer import AdamW, ClipGradByGlobalNorm

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2,
                               "pp_degree": 2, "sharding_degree": 2,
                               "sep_degree": 1}
    try:
        dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2,
                               use_flash_attention=False,
                               tie_word_embeddings=True)
        pipe = LlamaForCausalLMPipe(cfg)
        snap = _snapshot(pipe)
        pp = dist.fleet.distributed_model(pipe)
        opt_p = AdamW(5e-3, parameters=pipe.parameters(),
                      grad_clip=ClipGradByGlobalNorm(0.5))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 17))
        losses = [float(np.asarray(pp.train_batch(
            [paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])],
            opt_p))) for _ in range(2)]
    finally:
        dist.set_hybrid_communicate_group(None)

    # single-device reference: same tied pipe + same clipped AdamW
    paddle.seed(9)
    ref = LlamaForCausalLMPipe(cfg, num_stages=2)
    _load(ref, snap)
    opt_r = AdamW(5e-3, parameters=ref.parameters(),
                  grad_clip=ClipGradByGlobalNorm(0.5))
    ref_losses = []
    for _ in range(2):
        loss = causal_lm_loss(ref(paddle.to_tensor(ids[:, :-1])),
                              paddle.to_tensor(ids[:, 1:]))
        loss.backward()
        opt_r.step()
        opt_r.clear_grad()
        ref_losses.append(float(loss.numpy()))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
