"""Chaos injection + bundle integrity: the robustness proof layer.

Unit tier: FaultPlan round-trip/validation, deterministic nth-arrival
injection with scopes, checksummed bundle seal/verify (bit-flip and
version-skew regressions raising the typed HandoffCorrupt), jittered
backoff bounds. Gate tier: THE chaos dryrun — the real multi-process
cluster under the fixed-seed default plan (worker kill + handoff drop +
handoff corruption + heartbeat stall + router 5xx in one run), asserting
token-identical completions, zero client-visible 5xx, corrupt bundles
refused-and-retried, and stall-reap-rejoin."""
import random

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import chaos
from paddle_tpu.chaos.inject import ChaosInjector
from paddle_tpu.chaos.plan import Fault, FaultPlan
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (ContinuousBatchEngine, HandoffCorrupt,
                                HANDOFF_SCHEMA_VERSION, seal_bundle,
                                verify_bundle)


def _ref_model(layers=2):
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=layers))


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    return ContinuousBatchEngine(model, **kw)


# ---- plan model --------------------------------------------------------------

def test_fault_plan_roundtrip_and_validation():
    plan = FaultPlan(seed=7, faults=[
        Fault("kv_handoff.send", "drop", nth=2, scope="worker:0"),
        Fault("worker.request", "stall_heartbeat", nth=3,
              scope="worker:1", duration_s=4.0),
        Fault("router.upstream", "http_500", nth=5),
    ])
    again = FaultPlan.loads(plan.dumps())
    assert again.seed == 7
    assert [f.as_dict() for f in again.faults] == \
        [f.as_dict() for f in plan.faults]
    assert again.points() == {"kv_handoff.send", "worker.request",
                              "router.upstream"}
    with pytest.raises(ValueError, match="unknown injection point"):
        Fault("nope.nope", "drop")
    with pytest.raises(ValueError, match="not legal"):
        Fault("kv_handoff.send", "kill")
    with pytest.raises(ValueError, match="1-based"):
        Fault("pool.probe", "probe_fail", nth=0)


def test_injector_incarnation_scoping_and_crash_on_rid():
    """Incarnation-scoped faults target ONE life of a supervised worker
    (default 0 = the original process, so a planned kill never re-fires
    in the respawn it caused; None = any), and crash_on_rid matches on
    the poison rid entering the dispatch instead of the arrival count."""
    plan = FaultPlan(seed=0, faults=[
        Fault("worker.step", "kill", nth=1, scope="w"),            # inc 0
        Fault("worker.step", "kill", nth=2, scope="w",
              incarnation=1),
        Fault("engine.dispatch", "crash_on_rid", detail="poison",
              incarnation=None),
    ])
    # original process: only the incarnation-0 kill arms
    inj0 = ChaosInjector(plan, scope="w", incarnation=0)
    assert inj0.fire("worker.step").action == "kill"
    assert inj0.fire("worker.step") is None       # inc-1 fault invisible
    # the respawn: its own kill at ITS 2nd step, not the spent one
    inj1 = ChaosInjector(plan, scope="w", incarnation=1)
    assert inj1.fire("worker.step") is None
    assert inj1.fire("worker.step").action == "kill"
    # crash_on_rid: fires in ANY incarnation, only when the rid rides
    inj2 = ChaosInjector(plan, scope="w", incarnation=7)
    assert inj2.fire("engine.dispatch", rids=("a", "b")) is None
    hit = inj2.fire("engine.dispatch", rids=("a", "poison"))
    assert hit is not None and hit.action == "crash_on_rid"
    assert inj2.fire("engine.dispatch", rids=("poison",)) is None  # spent
    # round-trip preserves the new fields
    again = FaultPlan.loads(plan.dumps())
    assert again.faults[1].incarnation == 1
    assert again.faults[2].incarnation is None
    assert again.faults[2].detail == "poison"
    with pytest.raises(ValueError, match="crash_on_rid needs detail"):
        Fault("engine.dispatch", "crash_on_rid")
    # env-driven incarnation selection (what the supervisor exports)
    import os as _os

    from paddle_tpu.chaos import inject as _inj

    _os.environ[_inj.ENV_PLAN] = plan.dumps()
    _os.environ[_inj.ENV_INCARNATION] = "1"
    try:
        inj = chaos.install_from_env(scope="w")
        assert inj.incarnation == 1
    finally:
        _os.environ.pop(_inj.ENV_PLAN, None)
        _os.environ.pop(_inj.ENV_INCARNATION, None)
        chaos.uninstall()


def test_injector_fires_on_nth_arrival_once_scoped():
    plan = FaultPlan(seed=0, faults=[
        Fault("kv_handoff.send", "drop", nth=3, scope="worker:0"),
        Fault("kv_handoff.send", "corrupt", nth=2, scope="worker:1"),
    ])
    inj = ChaosInjector(plan, scope="worker:0")
    hits = [inj.fire("kv_handoff.send") for _ in range(5)]
    # only the scope-matching fault, only on its nth arrival, only once
    assert [h.action if h else None for h in hits] == \
        [None, None, "drop", None, None]
    assert inj.counts() == {"kv_handoff.send": 5}
    assert inj.fired() == [{"point": "kv_handoff.send", "action": "drop",
                            "nth": 3, "scope": "worker:0"}]
    # the same plan in the other scope fires the other fault — and the
    # two runs are reproducible (pure arrival counting, no clock)
    inj2 = ChaosInjector(plan, scope="worker:1")
    hits2 = [inj2.fire("kv_handoff.send") for _ in range(5)]
    assert [h.action if h else None for h in hits2] == \
        [None, "corrupt", None, None, None]


def test_install_on_fast_path_and_env(monkeypatch):
    chaos.uninstall()
    assert chaos.on("pool.probe") is None  # no plan: free no-op
    plan = FaultPlan(seed=1, faults=[Fault("pool.probe", "probe_fail")])
    monkeypatch.setenv("PDTPU_CHAOS_PLAN", plan.dumps())
    inj = chaos.install_from_env(scope="worker:9")
    try:
        assert inj is chaos.active()
        f = chaos.on("pool.probe")
        assert f is not None and f.action == "probe_fail"
        assert chaos.on("pool.probe") is None  # spent
    finally:
        chaos.uninstall()


# ---- bundle integrity (satellite: checksum + schema version) ----------------

def test_bit_flipped_bundle_raises_handoff_corrupt():
    """The regression the checksum exists for: one flipped byte in a KV
    leaf must raise the typed HandoffCorrupt at admission — never
    scatter garbage into the page pool."""
    model = _ref_model()
    pre, dec = _engine(model), _engine(model)
    prompt = np.random.RandomState(0).randint(1, 512, (9,)).tolist()
    bundle = pre.export_prefill(prompt, max_new_tokens=4)
    assert bundle["version"] == HANDOFF_SCHEMA_VERSION
    bad = chaos.corrupt_bundle(bundle, rng=random.Random(0))
    with pytest.raises(HandoffCorrupt, match="checksum mismatch"):
        dec.admit_prefilled(bad, max_new_tokens=4)
    # the pristine bundle still admits (corrupt_bundle copied)
    rid = dec.admit_prefilled(bundle, max_new_tokens=4)
    assert rid >= 0
    # migration bundles are guarded the same way
    src = _engine(model)
    r = src.add_request(prompt, max_new_tokens=6)
    src.step()
    mig = src.export_slot(r)
    bad_mig = chaos.corrupt_bundle(mig, rng=random.Random(1))
    dst = _engine(model)
    with pytest.raises(HandoffCorrupt, match="checksum mismatch"):
        dst.admit_migrated(bad_mig)


def test_version_skew_and_missing_checksum_rejected():
    model = _ref_model()
    pre, dec = _engine(model), _engine(model)
    bundle = pre.export_prefill([1, 2, 3], max_new_tokens=4)
    skew = dict(bundle)
    skew["version"] = HANDOFF_SCHEMA_VERSION + 1
    with pytest.raises(HandoffCorrupt, match="version skew"):
        dec.admit_prefilled(skew, max_new_tokens=4)
    naked = {k: v for k, v in bundle.items() if k != "checksum"}
    with pytest.raises(HandoffCorrupt, match="version skew|no checksum"):
        dec.admit_prefilled(dict(naked, version=None), max_new_tokens=4)
    # kind mismatch: a prefill bundle is not a migration bundle
    with pytest.raises(HandoffCorrupt, match="kind"):
        dec.admit_migrated(bundle)


def test_seal_verify_roundtrip_over_transport_shapes():
    """verify_bundle must be invariant to list/tuple container changes
    (the shm transport rebuilds containers) but sensitive to any leaf
    change."""
    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = seal_bundle({"kind": "prefill", "layers": [(arr, arr * 2)],
                     "prompt_tokens": 3, "bucket": 8})
    verify_bundle(b, kind="prefill")
    as_lists = dict(b, layers=[[arr, arr * 2]])
    verify_bundle(as_lists, kind="prefill")     # container type is noise
    with pytest.raises(HandoffCorrupt):
        verify_bundle(dict(b, prompt_tokens=4))  # scalar drift is not
    with pytest.raises(HandoffCorrupt):
        verify_bundle(dict(b, layers=[(arr, arr * 3)]))
    with pytest.raises(HandoffCorrupt):
        verify_bundle("not a dict")


# ---- jittered backoff (satellite) -------------------------------------------

def test_jitter_bounds_pinned():
    from paddle_tpu.serving_cluster.pool import jittered

    rng = random.Random(0)
    vals = [jittered(0.5, rng=rng) for _ in range(2000)]
    assert min(vals) >= 0.25 - 1e-9 and max(vals) <= 0.75 + 1e-9
    # actually spreads (a constant would defeat the point)
    assert max(vals) - min(vals) > 0.3
    # frac clamps at zero for aggressive settings
    assert all(jittered(1.0, frac=2.0, rng=rng) >= 0.0
               for _ in range(100))


def test_mark_busy_backoff_is_jittered():
    import time as _time

    from paddle_tpu.serving_cluster.pool import WorkerInfo, WorkerPool

    class _Store:          # never touched: refresh() is not called
        pass

    pool = WorkerPool(store=_Store(), world_size=1)
    w = WorkerInfo(0, {"host": "127.0.0.1", "port": 1})
    pool._workers[0] = w
    spans = []
    for _ in range(200):
        before = _time.monotonic()
        pool.mark_busy(0, backoff_s=0.5)
        spans.append(w.busy_until - before)
    assert min(spans) >= 0.25 - 0.01 and max(spans) <= 0.75 + 0.01
    assert max(spans) - min(spans) > 0.1  # not the old fixed constant


# ---- THE chaos gate ---------------------------------------------------------

def test_chaos_dryrun_gate():
    """Tier-1 robustness gate: the real multi-process SUPERVISED cluster
    under the fixed-seed default plan, WITH generated open-loop load
    flowing while the faults fire (not idle hand-built streams). Worker
    kill + handoff drop + handoff corruption + heartbeat stall +
    injected router 5xx, then the self-healing story — restart, a
    double-kill, a poison request, a post-heal capacity replay — in ONE
    run:

    - every gate stream completes token-identical with a clean [DONE];
    - zero client-visible 5xx (every injected fault was absorbable) —
      for the gate streams AND the generated load;
    - every generated-load rejection is typed (429 / deadline-504),
      none stalls silently, and the shed accounting balances
      (requests_shed == deadline_misses: no bounded queue here, so
      every shed is a deadline miss);
    - the corrupt bundle was DETECTED (HandoffCorrupt checksum message
      in the retry reason) and retried — never admitted;
    - the dropped bundle was absorbed: its own 504 timeout re-placed it,
      or (when the waiting decode worker was the one the plan killed
      inside the wait window) the failover re-place path took over —
      either way the stream stayed token-identical;
    - the heartbeat-stalled worker was reaped and rejoined on a fresh
      lease (its PROCESS never died — the supervisor must not restart a
      stall); the killed worker exited with the planned code;
    - SELF-HEALING: the supervisor restarted the killed worker (same
      replica id, fresh lease/port) and pool capacity returned to all 3
      workers; the plan's incarnation-1 DOUBLE-KILL fired in the
      restarted worker and healed again, with every stream driven
      through that window absorbed token-identical;
    - POISON CONTAINMENT: the crash_on_rid request killed at most 2
      workers before the quarantine refused it with exactly one typed
      422 code=request_quarantined; NO innocent rid was quarantined
      (deathnote blame precision at cluster level);
    - POST-HEAL CAPACITY: a seeded open-loop burst at the same offered
      rate against the healed tier completed with typed-only outcomes
      and zero 5xx — capacity recovered, not merely survived;
    - WATCHTOWER: the router's cluster AlertManager (second-scale
      windows via alert_time_scale) judged the kills end to end — the
      worker_restart_rate objective FIRED while the supervisor was
      restarting workers and RESOLVED once the scaled window drained
      after the heal, deterministically (the clean-run zero-alert
      control lives in the serving-cluster federation gate)."""
    from paddle_tpu.chaos.dryrun import (POISON_RID, default_plan,
                                         run_dryrun)

    report = run_dryrun(default_plan(seed=0), load_qps=6.0,
                        load_duration_s=4.0)
    assert report["streams"], "no streams ran"
    for s in report["streams"]:
        assert s["status"] == 200, report
        assert s["clean"], report
        assert s["token_identical"], report
    assert report["client_5xx"] == 0, report
    assert report["corrupt_detected_and_retried"], report
    assert report["drop_fired"] and report["drop_absorbed"], report
    assert report["stalled_worker_rejoined"], report
    assert report["worker_lost"], report
    assert report["killed_worker_exit"] == 137, report
    # the injected faults are visible as chaos.inject events in the
    # processes that injected them (the killed worker's ring died with
    # it — its evidence is the exit code above)
    fired = report["faults_fired"]
    router_actions = {f["action"] for f in fired.get("router", ())}
    assert "http_500" in router_actions, fired
    w0 = {(f["point"], f["action"]) for f in fired.get("worker:0", ())}
    assert ("kv_handoff.send", "drop") in w0, fired
    assert ("kv_handoff.send", "corrupt") in w0, fired
    assert ("worker.request", "stall_heartbeat") in w0, fired

    # self-healing: restart -> heal -> double-kill -> heal
    assert report["healed_after_kill"], report
    assert report["double_kill_restarts"] >= 2, report
    assert report["double_kill_streams_ok"], report
    assert report["healed_after_double_kill"], report
    sup = report["supervisor"]
    assert sup["restarts_total"] >= 2, sup
    assert sup["breakers_open"] == 0, sup   # planned chaos != crash loop
    # the stall leg proves restart is death-triggered: worker:0 stalled
    # its HEARTBEAT but never died, so it was reaped+rejoined, NOT
    # restarted
    assert sup["workers"]["0"]["incarnation"] == 0, sup

    # poison containment: <= 2 worker deaths, exactly one typed 422,
    # only the poison rid in the quarantine ledger
    poison = report["poison"]
    assert poison is not None, report
    assert poison["status"] == 422, poison
    assert poison["code"] == "request_quarantined", poison
    assert poison["deaths"] <= 2, poison
    assert poison["quarantined"] == [POISON_RID], poison
    assert report["healed_after_poison"], report

    # post-heal capacity at the offered rate: typed-only, zero 5xx
    post = report["post_heal_load"]
    assert post is not None and post["completed"] > 0, post
    assert post["http_5xx"] == 0 and post["untyped"] == 0, post
    assert post["timed_out"] == 0, post

    # the watchtower judged the kills: fire while restarting, resolve
    # after heal — proven over the real federated store, not unit math
    alerts = report["alerts"]
    assert alerts is not None and alerts["enabled"], report
    assert alerts["restart_fired"], alerts
    assert alerts["restart_resolved"], alerts
    assert "worker_restart_rate" not in alerts["firing_final"], alerts

    assert report["ok"], report

    # the generated-load leg: traffic flowed WHILE the faults fired,
    # and the overload contract held — typed outcomes only, zero 5xx,
    # zero silent stalls, shed accounting balanced
    load = report["load"]
    assert load is not None and load["n"] > 0, load
    assert load["http_5xx"] == 0, load
    assert load["untyped"] == 0, load
    assert load["timed_out"] == 0, load
    stack = load["stack"]
    # no bounded queue in the dryrun engines: every shed is a deadline
    # miss, and the counters (summed over the same engines) must agree
    assert stack["requests_shed"] == stack["deadline_misses"], stack
    if load["shed_504"]:
        assert stack["deadline_misses"] > 0, (load, stack)
    # the harness now records the healing counters off the router's
    # supervisor section: the window saw restarts, zero quarantines
    # (the poison leg runs after the load window)
    after = load.get("stack")
    assert "worker_restarts" in after and "requests_quarantined" in after
