"""BART encoder-decoder family: post-LN blocks, learned positions with the
+2 offset, final_logits_bias — numeric parity against transformers and
training/masking behavior."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.bart import (BartConfig, BartForConditionalGeneration,
                                    bart_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_pair():
    from transformers import BartConfig as HFConfig
    from transformers import BartForConditionalGeneration as HFBart

    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=256, d_model=64, encoder_layers=2,
                      decoder_layers=2, encoder_attention_heads=4,
                      decoder_attention_heads=4, encoder_ffn_dim=128,
                      decoder_ffn_dim=128, max_position_embeddings=128,
                      attn_implementation="eager",
                      activation_function="gelu",
                      decoder_start_token_id=2, eos_token_id=2,
                      pad_token_id=1, bos_token_id=0,
                      forced_eos_token_id=None)
    hf = HFBart(hf_cfg).eval()
    return hf, bart_from_hf(hf)


def test_logits_match_transformers(hf_pair):
    hf, ours = hf_pair
    enc = np.random.RandomState(0).randint(3, 256, (2, 11))
    dec = np.random.RandomState(1).randint(3, 256, (2, 7))
    with torch.no_grad():
        ref = hf(input_ids=torch.from_numpy(enc),
                 decoder_input_ids=torch.from_numpy(dec)).logits.numpy()
    got = ours(paddle.to_tensor(enc), paddle.to_tensor(dec)).numpy()
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_encoder_pad_mask_matches_transformers(hf_pair):
    hf, ours = hf_pair
    enc = np.random.RandomState(2).randint(3, 256, (2, 10))
    am = np.ones((2, 10), np.int64)
    am[1, 6:] = 0
    dec = np.random.RandomState(3).randint(3, 256, (2, 5))
    with torch.no_grad():
        ref = hf(input_ids=torch.from_numpy(enc),
                 attention_mask=torch.from_numpy(am),
                 decoder_input_ids=torch.from_numpy(dec)).logits.numpy()
    got = ours(paddle.to_tensor(enc), paddle.to_tensor(dec),
               attention_mask=paddle.to_tensor(am.astype(bool))).numpy()
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_cached_generate_matches_transformers(hf_pair):
    """Greedy with eos disabled on both sides: the cached decoder (learned
    positions at the cache offset + static cross K/V) must be
    token-identical to HF's uncached reference loop."""
    hf, ours = hf_pair
    enc = np.random.RandomState(4).randint(3, 256, (2, 11))
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(enc), max_new_tokens=8,
                          do_sample=False, num_beams=1, eos_token_id=None,
                          pad_token_id=1).numpy()[:, 1:]
    got = ours.generate(paddle.to_tensor(enc), max_new_tokens=8,
                        eos_token_id=-1).numpy()
    np.testing.assert_array_equal(got, ref)


def test_padded_generate_matches_unpadded():
    paddle.seed(0)
    m = BartForConditionalGeneration(BartConfig.tiny())
    rng = np.random.RandomState(5)
    short = rng.randint(3, 256, (1, 6))
    solo = m.generate(paddle.to_tensor(short), max_new_tokens=6,
                      eos_token_id=-1).numpy()
    padded = np.ones((1, 10), np.int64)
    padded[0, :6] = short[0]
    am = np.zeros((1, 10), np.int64)
    am[0, :6] = 1
    got = m.generate(paddle.to_tensor(padded), max_new_tokens=6,
                     eos_token_id=-1,
                     attention_mask=paddle.to_tensor(am)).numpy()
    np.testing.assert_array_equal(got, solo)


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    m = BartForConditionalGeneration(BartConfig.tiny())

    def loss_fn(mm, x, dec_x, y):
        loss, _ = mm(x, dec_x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(3, 256, (2, 12)))
    tgt = rng.randint(3, 256, (2, 8))
    dec_in = np.concatenate([np.full((2, 1), 2, np.int64), tgt[:, :-1]], 1)
    losses = [float(step(x, paddle.to_tensor(dec_in),
                         paddle.to_tensor(tgt)).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_activation_and_length_guards():
    import dataclasses

    with pytest.raises(NotImplementedError, match="activation_function"):
        BartConfig.tiny(activation_function="swish")
    m = BartForConditionalGeneration(
        BartConfig.tiny(max_position_embeddings=16))
    long_ids = paddle.to_tensor(np.ones((1, 20), np.int64))
    with pytest.raises(ValueError, match="max_position_embeddings"):
        m(long_ids, paddle.to_tensor(np.ones((1, 4), np.int64)))


def test_bart_beam_search_matches_transformers():
    """num_beams>1 on the BART enc-dec path: token-identical to HF."""
    import torch
    from transformers import BartConfig as HFConfig
    from transformers import BartForConditionalGeneration as HFBart
    from paddle_tpu.models.bart import bart_from_hf

    torch.manual_seed(0)
    # eos points at an UNLIKELY token (95) so the untrained net cannot
    # retire every beam at step 1 (decoder_start==2 would otherwise be
    # the eos too and both sides emit a width-1 "parity" trivially)
    hf = HFBart(HFConfig(vocab_size=96, d_model=64, encoder_layers=2,
                         decoder_layers=2, encoder_attention_heads=4,
                         decoder_attention_heads=4, encoder_ffn_dim=128,
                         decoder_ffn_dim=128, max_position_embeddings=64,
                         forced_eos_token_id=None, forced_bos_token_id=None,
                         bos_token_id=0, eos_token_id=95, pad_token_id=1,
                         decoder_start_token_id=2)).eval()
    ours = bart_from_hf(hf, dtype="float32")
    ids = np.random.RandomState(1).randint(3, 95, (2, 8))
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(ids), max_new_tokens=7,
                          num_beams=2, do_sample=False,
                          early_stopping=False).numpy()[:, 1:]
    got = ours.generate(paddle.to_tensor(ids), max_new_tokens=7,
                        num_beams=2, eos_token_id=95).numpy()
    assert got.shape[1] >= 5, got  # no silent truncation
    w = min(got.shape[1], ref.shape[1])
    np.testing.assert_array_equal(got[:, :w], ref[:, :w])
