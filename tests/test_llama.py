"""Flagship Llama model tests: correctness + hybrid-parallel loss parity."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def tiny_batch(vocab=512, b=4, s=32):
    np.random.seed(0)
    ids = np.random.randint(0, vocab, (b, s + 1))
    return paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch()
    logits = model(x)
    assert logits.shape == [4, 32, cfg.vocab_size]
    loss, logits = model(x, labels=y)
    assert loss.ndim == 0 and np.isfinite(float(loss.numpy()))
    # random init → loss ≈ ln(vocab)
    assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 1.0


def test_llama_gqa_kv_heads():
    cfg = LlamaConfig.tiny(num_attention_heads=4, num_key_value_heads=1)
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch()
    loss, _ = model(x, labels=y)
    assert np.isfinite(float(loss.numpy()))


def test_llama_trains_eager():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    o = opt.AdamW(1e-3, parameters=model.parameters())
    x, y = tiny_batch(b=2, s=16)
    losses = []
    for _ in range(8):
        loss, _ = model(x, labels=y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_llama_train_step_compiled():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    o = opt.AdamW(1e-3, parameters=model.parameters())

    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    step = paddle.jit.train_step(model, loss_fn, o)
    x, y = tiny_batch(b=2, s=16)
    losses = [float(step(x, y).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_llama_ignore_index_in_loss():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch(b=2, s=16)
    y_masked = paddle.to_tensor(np.where(np.arange(16) < 8, y.numpy(), -100))
    loss, _ = model(x, labels=y_masked)
    assert np.isfinite(float(loss.numpy()))


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_llama_hybrid_parallel_loss_parity():
    """dp2 × mp2 × sep2 sharded compiled step == serial step (loss parity,
    the reference's hybrid_strategy test pattern)."""
    cfg_kw = dict(num_hidden_layers=2, use_flash_attention=False)

    def build(parallel):
        paddle.seed(11)
        if parallel:
            strategy = dist.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "sep_degree": 2}
            dist.fleet.init(is_collective=True, strategy=strategy)
        else:
            dist.set_hybrid_communicate_group(None)
        model = LlamaForCausalLM(LlamaConfig.tiny(**cfg_kw))
        o = opt.AdamW(1e-3, parameters=model.parameters())
        return model, o

    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    x, y = tiny_batch(b=4, s=32)

    model_s, opt_s = build(parallel=False)
    step_s = paddle.jit.train_step(model_s, loss_fn, opt_s)
    serial = [float(step_s(x, y).numpy()) for _ in range(3)]

    model_p, opt_p = build(parallel=True)
    from paddle_tpu.distributed.engine import parallelize

    step_p = parallelize(model_p, loss_fn, opt_p)
    parallel = [float(step_p(x, y).numpy()) for _ in range(3)]
    dist.set_hybrid_communicate_group(None)

    np.testing.assert_allclose(serial, parallel, rtol=2e-3)

    # weights really sharded over mp
    qw = model_p.llama.layers[0].self_attn.q_proj.weight
    assert len(qw._array.sharding.device_set) == 8


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_llama_fsdp_parity():
    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    x, y = tiny_batch(b=4, s=16)
    mesh = dist.ProcessMesh(np.arange(8), ["sharding"])

    paddle.seed(5)
    m1 = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    o1 = opt.AdamW(1e-3, parameters=m1.parameters())
    s1 = paddle.jit.train_step(m1, loss_fn, o1)
    serial = [float(s1(x, y).numpy()) for _ in range(3)]

    paddle.seed(5)
    m2 = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    dist.ShardingStage3(axis_name="sharding", mesh=mesh).apply(m2)
    o2 = opt.AdamW(1e-3, parameters=m2.parameters())
    s2 = paddle.jit.train_step(m2, loss_fn, o2)
    fsdp = [float(s2(x, y).numpy()) for _ in range(3)]

    np.testing.assert_allclose(serial, fsdp, rtol=2e-3)
