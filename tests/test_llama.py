"""Flagship Llama model tests: correctness + hybrid-parallel loss parity."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def tiny_batch(vocab=512, b=4, s=32):
    np.random.seed(0)
    ids = np.random.randint(0, vocab, (b, s + 1))
    return paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])


def test_llama_forward_shapes():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch()
    logits = model(x)
    assert logits.shape == [4, 32, cfg.vocab_size]
    loss, logits = model(x, labels=y)
    assert loss.ndim == 0 and np.isfinite(float(loss.numpy()))
    # random init → loss ≈ ln(vocab)
    assert abs(float(loss.numpy()) - np.log(cfg.vocab_size)) < 1.0


def test_llama_gqa_kv_heads():
    cfg = LlamaConfig.tiny(num_attention_heads=4, num_key_value_heads=1)
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch()
    loss, _ = model(x, labels=y)
    assert np.isfinite(float(loss.numpy()))


def test_llama_trains_eager():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    o = opt.AdamW(1e-3, parameters=model.parameters())
    x, y = tiny_batch(b=2, s=16)
    losses = []
    for _ in range(4):
        loss, _ = model(x, labels=y)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_llama_train_step_compiled():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    o = opt.AdamW(1e-3, parameters=model.parameters())

    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    step = paddle.jit.train_step(model, loss_fn, o)
    x, y = tiny_batch(b=2, s=16)
    losses = [float(step(x, y).numpy()) for _ in range(8)]
    assert losses[-1] < losses[0]


def test_llama_ignore_index_in_loss():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    model = LlamaForCausalLM(cfg)
    x, y = tiny_batch(b=2, s=16)
    y_masked = paddle.to_tensor(np.where(np.arange(16) < 8, y.numpy(), -100))
    loss, _ = model(x, labels=y_masked)
    assert np.isfinite(float(loss.numpy()))


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_llama_hybrid_parallel_loss_parity():
    """dp2 × mp2 × sep2 sharded compiled step == serial step (loss parity,
    the reference's hybrid_strategy test pattern)."""
    cfg_kw = dict(num_hidden_layers=2, use_flash_attention=False)

    def build(parallel):
        paddle.seed(11)
        if parallel:
            strategy = dist.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "sep_degree": 2}
            dist.fleet.init(is_collective=True, strategy=strategy)
        else:
            dist.set_hybrid_communicate_group(None)
        model = LlamaForCausalLM(LlamaConfig.tiny(**cfg_kw))
        o = opt.AdamW(1e-3, parameters=model.parameters())
        return model, o

    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    x, y = tiny_batch(b=4, s=32)

    model_s, opt_s = build(parallel=False)
    step_s = paddle.jit.train_step(model_s, loss_fn, opt_s)
    serial = [float(step_s(x, y).numpy()) for _ in range(3)]

    model_p, opt_p = build(parallel=True)
    from paddle_tpu.distributed.engine import parallelize

    step_p = parallelize(model_p, loss_fn, opt_p)
    parallel = [float(step_p(x, y).numpy()) for _ in range(3)]
    dist.set_hybrid_communicate_group(None)

    np.testing.assert_allclose(serial, parallel, rtol=2e-3)

    # weights really sharded over mp
    qw = model_p.llama.layers[0].self_attn.q_proj.weight
    assert len(qw._array.sharding.device_set) == 8


@pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")
def test_llama_fsdp_parity():
    def loss_fn(m, x, y):
        loss, _ = m(x, labels=y)
        return loss

    x, y = tiny_batch(b=4, s=16)
    mesh = dist.ProcessMesh(np.arange(8), ["sharding"])

    paddle.seed(5)
    m1 = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    o1 = opt.AdamW(1e-3, parameters=m1.parameters())
    s1 = paddle.jit.train_step(m1, loss_fn, o1)
    serial = [float(s1(x, y).numpy()) for _ in range(3)]

    paddle.seed(5)
    m2 = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
    dist.ShardingStage3(axis_name="sharding", mesh=mesh).apply(m2)
    o2 = opt.AdamW(1e-3, parameters=m2.parameters())
    s2 = paddle.jit.train_step(m2, loss_fn, o2)
    fsdp = [float(s2(x, y).numpy()) for _ in range(3)]

    np.testing.assert_allclose(serial, fsdp, rtol=2e-3)


def test_splash_flash_attention_gqa_parity():
    """GQA-native splash kernel vs the XLA SDPA reference (interpret mode).

    VERDICT r2 item 2: the flash path must accept num_kv_heads < num_heads
    without expanding KV. Parity ref: flash_attn_kernel.cu handles GQA
    natively in the reference."""
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _sdpa_ref
    from paddle_tpu.ops.pallas import flash_attention as pf
    from paddle_tpu.distributed.context_parallel import _expand_gqa

    b, s, hq, hkv, d = 1, 256, 4, 2, 128
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)

    assert pf.supported(q, k, v, interpret=True)
    out = pf.flash_attention_bshd(q, k, v, causal=True, interpret=True)
    ke, ve = _expand_gqa(k, v, hq)
    ref = _sdpa_ref(q, ke, ve, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_splash_flash_attention_grad_parity():
    """The splash custom-VJP backward matches the SDPA reference grads."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _sdpa_ref
    from paddle_tpu.ops.pallas import flash_attention as pf
    from paddle_tpu.distributed.context_parallel import _expand_gqa

    b, s, hq, hkv, d = 1, 256, 2, 1, 128
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, s, hq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, hkv, d), jnp.float32)

    def loss_splash(q, k, v):
        return (pf.flash_attention_bshd(q, k, v, causal=True, interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        ke, ve = _expand_gqa(k, v, hq)
        return (_sdpa_ref(q, ke, ve, causal=True) ** 2).sum()

    gs = jax.grad(loss_splash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gs, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=5e-2, atol=5e-2)


def test_splash_rectangular_causal_parity():
    """Chunked-prefill shape (s_q < s_kv): the causal triangle must be
    bottom-aligned like _sdpa_ref's tril(k=s_kv-s_q) (review regression)."""
    import jax.numpy as jnp

    from paddle_tpu.nn.functional.attention import _sdpa_ref
    from paddle_tpu.ops.pallas import flash_attention as pf

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 128, 2, 128), jnp.float32)
    k = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
    v = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
    out = pf.flash_attention_bshd(q, k, v, causal=True, interpret=True)
    ref = _sdpa_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_splash_block_sizes_divide_seq():
    """seq=640 passes supported() (128-multiple) but 512 does not divide it;
    the kernel must pick a dividing block, not crash (review regression)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as pf

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 640, 2, 128), jnp.float32)
    k = jnp.asarray(rng.randn(1, 640, 1, 128), jnp.float32)
    v = jnp.asarray(rng.randn(1, 640, 1, 128), jnp.float32)
    assert pf.supported(q, k, v, interpret=True)
    out = pf.flash_attention_bshd(q, k, v, causal=True, interpret=True)
    assert out.shape == q.shape


def test_functional_flash_attention_gqa_fallback():
    """GQA inputs through the public wrapper on the XLA fallback path must
    expand KV, not crash in the einsum (review regression)."""
    from paddle_tpu.nn.functional.attention import flash_attention

    rng = np.random.RandomState(2)
    q = paddle.to_tensor(rng.randn(1, 64, 4, 32).astype("float32"))
    k = paddle.to_tensor(rng.randn(1, 64, 2, 32).astype("float32"))
    v = paddle.to_tensor(rng.randn(1, 64, 2, 32).astype("float32"))
    out, _ = flash_attention(q, k, v, causal=True)
    assert tuple(out.shape) == (1, 64, 4, 32)


def test_fused_norm_blocks_scale_with_hidden():
    """VMEM regression (8b bench OOM): the row block shrinks as hidden
    grows (block*d <= 512K elements) and the d=4096 path stays numerically
    exact vs the reference formulation."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import fused_norm as fnorm

    assert fnorm._pick_block_rows(2048, 2048) == 256
    assert fnorm._pick_block_rows(256, 4096) == 128
    assert fnorm._pick_block_rows(256, 8192) == 64
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(256, 4096).astype("float32"))
    w = jnp.asarray(rng.randn(4096).astype("float32"))
    np.testing.assert_allclose(
        np.asarray(fnorm.rms_norm(x, w)),
        np.asarray(fnorm._rmsnorm_ref(x, w, 1e-6)), atol=1e-5)
    r = jnp.asarray(rng.randn(256, 4096).astype("float32"))
    o, h = fnorm.add_rms_norm(x, r, w)
    ro, rh = fnorm._add_rms_ref(x, r, w, 1e-6)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ro), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), np.asarray(rh), atol=1e-5)


def test_flash_attention_module_surface_tail():
    """nn.functional.flash_attention module parity tail (r5):
    get_triangle_upper_mask and calc_reduced_attention_scores (the lse-
    reusing reduced-scores op) — numeric vs a full-softmax reference."""
    import jax.numpy as jnp

    from paddle_tpu.nn.functional import flash_attention as FA

    rng = np.random.RandomState(0)
    q = paddle.to_tensor(rng.randn(2, 4, 2, 8).astype(np.float32))
    k = paddle.to_tensor(rng.randn(2, 6, 2, 8).astype(np.float32))
    s = np.einsum("bqhd,bkhd->bhqk", q.numpy(), k.numpy()) / np.sqrt(8)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = p.sum(-2, keepdims=True)
    lse = paddle.to_tensor(
        np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) + s.max(-1))
    out = FA.calc_reduced_attention_scores(q, k, lse)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)

    m = FA.get_triangle_upper_mask(
        paddle.to_tensor(np.zeros((1, 2, 4, 4), np.float32)))
    assert m.stop_gradient
    assert m.numpy()[0, 0, 0, 1] == -1e4 and m.numpy()[0, 0, 1, 1] == 0
