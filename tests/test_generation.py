"""Serving slice tests: static-KV generate, paged decode, sampling,
predictor round-trip.

Parity model: the reference's serving stack (block_multi_head_attention
paged decode, top_p_sampling) + PaddleNLP GenerationMixin semantics.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu import generation


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    return LlamaForCausalLM(cfg)


def _prompt(cfg, b=2, s=5, seed=0):
    ids = np.random.RandomState(seed).randint(0, cfg.vocab_size, (b, s))
    return paddle.to_tensor(ids)


def test_greedy_cache_matches_no_cache(tiny_model):
    """Static-KV decode must produce exactly the no-cache argmax loop."""
    x = _prompt(tiny_model.config)
    out_c = tiny_model.generate(x, max_new_tokens=4, use_cache=True)
    out_n = tiny_model.generate(x, max_new_tokens=4, use_cache=False)
    np.testing.assert_array_equal(out_c.numpy(), out_n.numpy())
    assert out_c.shape[0] == 2  # batched decode


def test_paged_decode_matches_dense(tiny_model):
    """Paged KV decode (block-table layout) == dense static cache."""
    x = _prompt(tiny_model.config)
    dense = tiny_model.generate(x, max_new_tokens=6)
    paged = generation.generate_paged(tiny_model, x, max_new_tokens=6,
                                      page_size=4)
    np.testing.assert_array_equal(dense.numpy(), paged.numpy())


def test_eos_early_stop_and_padding(tiny_model):
    x = _prompt(tiny_model.config)
    greedy = tiny_model.generate(x, max_new_tokens=4).numpy()
    eos = int(greedy[0, 1])  # token row 0 will emit at step 1
    out = tiny_model.generate(x, max_new_tokens=4, eos_token_id=eos).numpy()
    # after a row hits eos it keeps emitting eos (padding semantics)
    hit = np.where(out[0] == eos)[0]
    assert len(hit) > 0
    assert (out[0, hit[0]:] == eos).all()


def test_sampling_seeded_and_filtered(tiny_model):
    x = _prompt(tiny_model.config)
    paddle.seed(42)
    a = tiny_model.generate(x, max_new_tokens=5, do_sample=True,
                            top_k=8, temperature=0.7).numpy()
    paddle.seed(42)
    b = tiny_model.generate(x, max_new_tokens=5, do_sample=True,
                            top_k=8, temperature=0.7).numpy()
    np.testing.assert_array_equal(a, b)  # seeded determinism
    assert (a < tiny_model.config.vocab_size).all()


def test_top_k_top_p_filters():
    import jax.numpy as jnp

    from paddle_tpu.generation import _top_k_filter, _top_p_filter

    logits = jnp.asarray(np.log([[0.5, 0.3, 0.15, 0.05]]))
    k2 = _top_k_filter(logits, 2)
    assert np.isfinite(np.asarray(k2)[0, :2]).all()
    assert np.isinf(np.asarray(k2)[0, 2:]).all()
    p = _top_p_filter(logits, 0.7)
    kept = np.isfinite(np.asarray(p))[0]
    np.testing.assert_array_equal(kept, [True, True, False, False])


def test_top_p_sampling_op(tiny_model):
    """paddle.tensor.top_p_sampling parity surface: (scores, ids)."""
    probs = paddle.to_tensor(np.array([[0.7, 0.2, 0.05, 0.05],
                                       [0.05, 0.05, 0.2, 0.7]], "float32"))
    ps = paddle.to_tensor(np.array([0.5, 0.5], "float32"))
    scores, ids = generation.top_p_sampling(probs, ps, seed=3)
    assert int(ids.numpy()[0]) == 0 and int(ids.numpy()[1]) == 3
    np.testing.assert_allclose(scores.numpy(), [0.7, 0.7], rtol=1e-6)


def test_paged_attention_ref_masks_lengths():
    """Positions beyond each row's length must not contribute."""
    import jax.numpy as jnp

    B, H, hk, D, ps = 2, 4, 2, 8, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, D), jnp.float32)
    k_pages = jnp.asarray(rng.randn(hk, 4, ps, D), jnp.float32)
    v_pages = jnp.asarray(rng.randn(hk, 4, ps, D), jnp.float32)
    page_indices = jnp.asarray([[0, 1], [2, 3]], jnp.int32)
    out_a = generation._paged_attention_ref(
        q, k_pages, v_pages, jnp.asarray([3, 5]), page_indices)
    # corrupting masked-out positions changes nothing
    k2 = k_pages.at[:, :, 3:].add(100.0)  # row0 length 3 → slot 3 masked
    out_b = generation._paged_attention_ref(
        q, k2, v_pages, jnp.asarray([3, 5]), page_indices)
    np.testing.assert_allclose(np.asarray(out_a[0]), np.asarray(out_b[0]),
                               rtol=1e-5)


def test_generation_predictor_roundtrip(tiny_model, tmp_path):
    """jit.save weights -> GenerationPredictor loads + decodes (paged and
    dense) with identical tokens to the source model."""
    from paddle_tpu.inference import GenerationPredictor

    x = _prompt(tiny_model.config)
    ref = tiny_model.generate(x, max_new_tokens=5).numpy()
    path = os.path.join(tmp_path, "llama")
    paddle.jit.save(tiny_model, path)

    paddle.seed(123)  # fresh (different) weights to prove loading matters
    fresh = LlamaForCausalLM(tiny_model.config)
    pred = GenerationPredictor(path, fresh)
    np.testing.assert_array_equal(
        pred.generate(x, max_new_tokens=5).numpy(), ref)
    np.testing.assert_array_equal(
        pred.generate(x, max_new_tokens=5, paged=True, page_size=4).numpy(),
        ref)


def test_generate_rejects_overflow(tiny_model):
    x = _prompt(tiny_model.config, s=5)
    too_many = tiny_model.config.max_position_embeddings
    with pytest.raises(ValueError):
        tiny_model.generate(x, max_new_tokens=too_many)


def test_attention_mask_ragged_batch(tiny_model):
    """Right-padded ragged prompts: pad columns never attended, per-row
    RoPE positions, first token from each row's last REAL logit. Row 0 of
    a padded batch must decode exactly like its unpadded solo run."""
    cfg = tiny_model.config
    rng = np.random.RandomState(3)
    a = rng.randint(0, cfg.vocab_size, (1, 3))
    b = rng.randint(0, cfg.vocab_size, (1, 5))
    solo_a = tiny_model.generate(paddle.to_tensor(a), max_new_tokens=3).numpy()
    solo_b = tiny_model.generate(paddle.to_tensor(b), max_new_tokens=3).numpy()

    # batch [a padded to 5, b], mask marks real tokens
    pad = np.zeros((1, 2), a.dtype)
    batch = np.concatenate([np.concatenate([a, pad], 1), b], 0)
    mask = np.array([[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], "int32")
    out = tiny_model.generate(paddle.to_tensor(batch), max_new_tokens=3,
                              attention_mask=paddle.to_tensor(mask)).numpy()
    np.testing.assert_array_equal(out[0], solo_a[0])
    np.testing.assert_array_equal(out[1], solo_b[0])


def test_generate_zero_tokens(tiny_model):
    x = _prompt(tiny_model.config)
    out = tiny_model.generate(x, max_new_tokens=0)
    assert tuple(out.shape) == (2, 0)


def test_flash_prefill_matches_dense_prefill():
    """cached_attention(use_flash=True) — the serving prefill fast path
    (flash kernel over the prompt, never touching the Smax buffer) — must
    match the dense masked-einsum prefill exactly in fp32 (interpret mode
    runs the Pallas splash kernel on CPU)."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    B, S, H, hk, D, Smax = 2, 128, 4, 2, 128, 256
    q = jnp.asarray(rng.randn(B, S, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, hk, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, hk, D), jnp.float32)
    cos = jnp.asarray(rng.randn(Smax, D), jnp.float32)
    sin = jnp.asarray(rng.randn(Smax, D), jnp.float32)
    kb = jnp.zeros((B, Smax, hk, D), jnp.float32)
    vb = jnp.zeros((B, Smax, hk, D), jnp.float32)
    pos = jnp.zeros((), jnp.int32)
    out_d, kd, vd = generation.cached_attention(
        q, k, v, cos, sin, kb, vb, pos, use_flash=False)
    out_f, kf, vf = generation.cached_attention(
        q, k, v, cos, sin, kb, vb, pos, use_flash=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(kd), np.asarray(kf))
    np.testing.assert_array_equal(np.asarray(vd), np.asarray(vf))
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               atol=2e-5, rtol=2e-5)


def test_flash_prefill_guards_stay_dense():
    """The flash prefill branch must NOT trigger for padded batches,
    non-zero offsets, or decode steps — those stay on the dense path."""
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    B, S, H, hk, D, Smax = 1, 128, 2, 1, 128, 256
    mk = lambda *s: jnp.asarray(rng.randn(*s), jnp.float32)
    q, k, v = mk(B, S, H, D), mk(B, S, hk, D), mk(B, S, hk, D)
    cos, sin = mk(Smax, D), mk(Smax, D)
    kb = vb = jnp.zeros((B, Smax, hk, D), jnp.float32)
    # pos != 0: splash flash (which ignores the buffer) must be bypassed —
    # the fast path here is the append kernel, which DOES attend the
    # buffer, so outputs match the dense call (streaming-softmax float
    # noise only)
    base = generation.cached_attention(q, k, v, cos, sin, kb, vb, 128)
    fl = generation.cached_attention(q, k, v, cos, sin, kb, vb, 128,
                                     use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(base[0]), np.asarray(fl[0]),
                               rtol=1e-4, atol=1e-5)
    # padded batch (allowed mask) bypasses splash flash: mask a REAL column
    # inside the prompt so a path that ignored `allowed` would diverge
    allowed = jnp.ones((B, Smax), bool).at[:, 3].set(False)
    base = generation.cached_attention(q, k, v, cos, sin, kb, vb, 0,
                                       allowed=allowed)
    fl = generation.cached_attention(q, k, v, cos, sin, kb, vb, 0,
                                     allowed=allowed, use_flash=True,
                                     interpret=True)
    np.testing.assert_allclose(np.asarray(base[0]), np.asarray(fl[0]),
                               rtol=1e-4, atol=1e-5)


def test_ragged_long_generation_matches_solo(tiny_model):
    """Ragged batch rows must match the SOLO run of the same prompt for a
    LONG generation: per-row RoPE positions advance each decoded token
    (review r4 — frozen row_pos diverged from token 5 on)."""
    cfg = tiny_model.config
    rng = np.random.RandomState(2)
    a = rng.randint(0, cfg.vocab_size, (1, 3))
    b = rng.randint(0, cfg.vocab_size, (1, 9))
    pad = np.zeros((1, 9), dtype=a.dtype)
    pad[0, :3] = a[0]
    batch = np.concatenate([pad, b], axis=0)
    mask = np.zeros((2, 9), dtype="int64")
    mask[0, :3] = 1
    mask[1, :] = 1
    out = tiny_model.generate(paddle.to_tensor(batch), max_new_tokens=10,
                              attention_mask=paddle.to_tensor(mask))
    solo_a = tiny_model.generate(paddle.to_tensor(a), max_new_tokens=10)
    solo_b = tiny_model.generate(paddle.to_tensor(b), max_new_tokens=10)
    np.testing.assert_array_equal(out.numpy()[0], solo_a.numpy()[0])
    np.testing.assert_array_equal(out.numpy()[1], solo_b.numpy()[0])


def test_ragged_paged_decode_matches_dense(tiny_model):
    """Ragged batches over the PAGED cache: per-row write positions +
    per-row RoPE make padded prompts first-class in the paged layout
    (block_multi_head_attention write pattern). Must equal the dense-cache
    ragged run AND each row's solo run."""
    cfg = tiny_model.config
    rng = np.random.RandomState(5)
    a = rng.randint(0, cfg.vocab_size, (1, 3))
    b = rng.randint(0, cfg.vocab_size, (1, 7))
    pad = np.zeros((1, 4), a.dtype)
    batch = np.concatenate([np.concatenate([a, pad], 1), b], 0)
    mask = np.array([[1, 1, 1, 0, 0, 0, 0], [1] * 7], "int64")
    dense = tiny_model.generate(paddle.to_tensor(batch), max_new_tokens=8,
                                attention_mask=paddle.to_tensor(mask))
    paged = tiny_model.generate(paddle.to_tensor(batch), max_new_tokens=8,
                                attention_mask=paddle.to_tensor(mask),
                                paged=True, page_size=4)
    np.testing.assert_array_equal(dense.numpy(), paged.numpy())
    solo_a = tiny_model.generate(paddle.to_tensor(a), max_new_tokens=8)
    np.testing.assert_array_equal(paged.numpy()[0], solo_a.numpy()[0])


def test_left_padded_mask_rejected(tiny_model):
    """Non-contiguous masks (interior holes) must fail loudly; left
    padding is supported since r5 (rolled to the internal right-padded
    layout — test_left_padded_prompts_match_right_padded)."""
    cfg = tiny_model.config
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 5))
    for bad in ([[1, 0, 1, 1, 0], [1, 1, 1, 1, 1]],):   # interior hole
        with pytest.raises(ValueError, match="interior holes"):
            tiny_model.generate(
                paddle.to_tensor(ids), max_new_tokens=3,
                attention_mask=paddle.to_tensor(np.array(bad, "int64")))
    # an all-zero row passes the prefix check but has no real token to
    # decode from — rejected explicitly, not gathered from garbage
    empty = np.array([[0, 0, 0, 0, 0], [1, 1, 1, 1, 1]], "int64")
    with pytest.raises(ValueError, match="at least one"):
        tiny_model.generate(paddle.to_tensor(ids), max_new_tokens=3,
                            attention_mask=paddle.to_tensor(empty))


class TestChunkedPrefill:
    """prefill_chunk_size must not change ANY output: the chunked scan
    writes the same cache the one-shot prefill does."""

    def _model(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m, cfg

    def test_matches_one_shot_single_prompt(self):
        m, cfg = self._model()
        prompt = np.random.RandomState(0).randint(0, cfg.vocab_size, (1, 13))
        ref = m.generate(paddle.to_tensor(prompt), max_new_tokens=8).numpy()
        for chunk in (4, 5, 13, 16):
            out = m.generate(paddle.to_tensor(prompt), max_new_tokens=8,
                             prefill_chunk_size=chunk).numpy()
            np.testing.assert_array_equal(out, ref,
                                          err_msg=f"chunk={chunk}")

    def test_matches_one_shot_ragged_batch(self):
        m, cfg = self._model()
        rng = np.random.RandomState(1)
        S0 = 11
        prompt = rng.randint(0, cfg.vocab_size, (3, S0))
        am = np.zeros((3, S0), np.int64)
        for b, n in enumerate((11, 7, 4)):
            am[b, :n] = 1
            prompt[b, n:] = 0
        ref = m.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                         attention_mask=paddle.to_tensor(am)).numpy()
        out = m.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                         attention_mask=paddle.to_tensor(am),
                         prefill_chunk_size=4).numpy()
        np.testing.assert_array_equal(out, ref)

    def test_matches_with_eos_and_sampling_paths(self):
        m, cfg = self._model()
        prompt = np.random.RandomState(2).randint(0, cfg.vocab_size, (2, 9))
        ref = m.generate(paddle.to_tensor(prompt), max_new_tokens=7).numpy()
        eos = int(ref[0, 2])
        ref_eos = m.generate(paddle.to_tensor(prompt), max_new_tokens=7,
                             eos_token_id=eos).numpy()
        out_eos = m.generate(paddle.to_tensor(prompt), max_new_tokens=7,
                             eos_token_id=eos, prefill_chunk_size=4).numpy()
        np.testing.assert_array_equal(out_eos, ref_eos)
        # sampling path: identical key stream => identical tokens
        paddle.seed(7)
        ref_s = m.generate(paddle.to_tensor(prompt), max_new_tokens=7,
                           do_sample=True, temperature=0.8, top_k=5).numpy()
        paddle.seed(7)
        out_s = m.generate(paddle.to_tensor(prompt), max_new_tokens=7,
                           do_sample=True, temperature=0.8, top_k=5,
                           prefill_chunk_size=4).numpy()
        np.testing.assert_array_equal(out_s, ref_s)

    def test_paged_decode_composes(self):
        m, cfg = self._model()
        prompt = np.random.RandomState(3).randint(0, cfg.vocab_size, (2, 10))
        ref = m.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                         paged=True, page_size=8).numpy()
        out = m.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                         paged=True, page_size=8,
                         prefill_chunk_size=4).numpy()
        np.testing.assert_array_equal(out, ref)

    def test_compile_buckets_by_chunk_count(self):
        """Two prompts in the same chunk-count bucket reuse ONE compiled
        prefill (the whole point of chunking)."""
        m, cfg = self._model()
        for s in (9, 11):  # both -> 3 chunks of 4
            p = np.random.RandomState(s).randint(0, cfg.vocab_size, (1, s))
            m.generate(paddle.to_tensor(p), max_new_tokens=4,
                       prefill_chunk_size=4)
        steps = m.__dict__.get("_chunked_prefill_steps")
        assert steps is not None and len(steps) == 1, steps and len(steps)


class TestPenalties:
    """repetition_penalty / min_new_tokens: HF-semantics parity against
    transformers' logits processors on an identical converted model."""

    @pytest.fixture(scope="class")
    def hf_pair(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM as HFLlama
        from paddle_tpu.models.llama import llama_from_hf

        torch.manual_seed(0)
        hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=128,
                          attention_bias=False, tie_word_embeddings=False)
        hf = HFLlama(hf_cfg).eval()
        ours = llama_from_hf(hf, dtype="float32", use_flash_attention=False)
        return hf, ours

    def test_repetition_penalty_matches_transformers(self, hf_pair):
        import torch

        hf, ours = hf_pair
        ids = np.random.RandomState(0).randint(0, 128, (2, 10))
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(ids), max_new_tokens=8,
                              do_sample=False,
                              repetition_penalty=1.7).numpy()[:, 10:]
        got = ours.generate(paddle.to_tensor(ids), max_new_tokens=8,
                            repetition_penalty=1.7).numpy()
        np.testing.assert_array_equal(got, ref)

    def test_min_new_tokens_blocks_eos(self, hf_pair):
        import torch

        hf, ours = hf_pair
        ids = np.random.RandomState(1).randint(0, 128, (1, 8))
        # pick the model's own first greedy token as a fake eos so the
        # unconstrained run would stop immediately
        first = int(ours.generate(paddle.to_tensor(ids),
                                  max_new_tokens=1).numpy()[0, 0])
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                              do_sample=False, eos_token_id=first,
                              min_new_tokens=4,
                              pad_token_id=first).numpy()[:, 8:]
        got = ours.generate(paddle.to_tensor(ids), max_new_tokens=6,
                            eos_token_id=first, min_new_tokens=4).numpy()
        assert got.shape[1] >= 4
        if got.shape[1] < ref.shape[1]:  # both pad with the eos id
            got = np.pad(got, ((0, 0), (0, ref.shape[1] - got.shape[1])),
                         constant_values=first)
        np.testing.assert_array_equal(got[:, :ref.shape[1]], ref)

    def test_penalty_validation(self, hf_pair):
        _, ours = hf_pair
        ids = paddle.to_tensor(np.zeros((1, 4), np.int64))
        with pytest.raises(ValueError, match="positive"):
            ours.generate(ids, repetition_penalty=0.0)
        with pytest.raises(ValueError, match="eos"):
            ours.generate(ids, min_new_tokens=2)

    def test_no_cache_path_matches_cached(self, hf_pair):
        _, ours = hf_pair
        ids = np.random.RandomState(2).randint(0, 128, (2, 9))
        a = ours.generate(paddle.to_tensor(ids), max_new_tokens=6,
                          repetition_penalty=1.4).numpy()
        b = ours.generate(paddle.to_tensor(ids), max_new_tokens=6,
                          repetition_penalty=1.4, use_cache=False).numpy()
        np.testing.assert_array_equal(a, b)


class TestBeamSearch:
    """num_beams>1: HF-semantics beam search (2K candidates, eos retiring,
    length-penalty-normalized hypothesis pool) — token parity against
    transformers' implementation on a converted model."""

    @pytest.fixture(scope="class")
    def hf_pair(self):
        torch = pytest.importorskip("torch")
        pytest.importorskip("transformers")
        from transformers import LlamaConfig as HFConfig
        from transformers import LlamaForCausalLM as HFLlama
        from paddle_tpu.models.llama import llama_from_hf

        torch.manual_seed(0)
        hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=128,
                          attention_bias=False, tie_word_embeddings=False)
        hf = HFLlama(hf_cfg).eval()
        ours = llama_from_hf(hf, dtype="float32", use_flash_attention=False)
        return hf, ours

    @pytest.mark.parametrize("beams,eos,lp,es", [
        (3, None, 1.0, False),
        (3, 5, 1.0, False),
        (4, 5, 2.0, False),
        (3, 5, 0.5, True),
    ])
    def test_matches_transformers(self, hf_pair, beams, eos, lp, es):
        import torch

        hf, ours = hf_pair
        ids = np.random.RandomState(0).randint(0, 128, (2, 10))
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(ids), max_new_tokens=8,
                              do_sample=False, num_beams=beams,
                              eos_token_id=eos, length_penalty=lp,
                              early_stopping=es,
                              pad_token_id=eos if eos is not None else 0
                              ).numpy()[:, 10:]
        got = ours.generate(paddle.to_tensor(ids), max_new_tokens=8,
                            num_beams=beams, eos_token_id=eos,
                            length_penalty=lp, early_stopping=es).numpy()
        # compare at FULL reference width: both sides pad with eos, so a
        # termination-length divergence cannot hide behind a prefix slice
        fill = eos if eos is not None else 0
        # a LONGER best hypothesis than HF's is itself a divergence — it
        # must not hide behind the width slice below
        assert got.shape[1] <= ref.shape[1], (got, ref)
        if got.shape[1] < ref.shape[1]:
            got = np.pad(got, ((0, 0), (0, ref.shape[1] - got.shape[1])),
                         constant_values=fill)
        np.testing.assert_array_equal(got[:, :ref.shape[1]], ref)

    def test_ragged_batch_matches_solo(self, hf_pair):
        """Beam search over a right-padded batch == each row's solo run."""
        _, ours = hf_pair
        rng = np.random.RandomState(3)
        long_ids = rng.randint(1, 128, (1, 12))
        short_ids = rng.randint(1, 128, (1, 6))
        kw = dict(max_new_tokens=6, num_beams=3, eos_token_id=5)
        solo_long = ours.generate(paddle.to_tensor(long_ids), **kw).numpy()
        solo_short = ours.generate(paddle.to_tensor(short_ids), **kw).numpy()
        batch = np.zeros((2, 12), np.int64)
        batch[0] = long_ids[0]
        batch[1, :6] = short_ids[0]
        am = np.zeros((2, 12), np.int64)
        am[0] = 1
        am[1, :6] = 1
        got = ours.generate(paddle.to_tensor(batch),
                            attention_mask=paddle.to_tensor(am), **kw).numpy()
        for row, solo in ((0, solo_long), (1, solo_short)):
            n = min(got.shape[1], solo.shape[1])
            np.testing.assert_array_equal(got[row, :n], solo[0, :n])

    def test_unsupported_combinations_raise(self, hf_pair):
        _, ours = hf_pair
        ids = paddle.to_tensor(np.ones((1, 4), np.int64))
        with pytest.raises(NotImplementedError, match="beam sampling"):
            ours.generate(ids, num_beams=2, do_sample=True)
        with pytest.raises(NotImplementedError, match="paged"):
            ours.generate(ids, num_beams=2, paged=True)

    @pytest.mark.parametrize("kw", [
        # every config pins eos explicitly: HF otherwise falls back to
        # its config default (2) while ours runs eos-free — divergent
        # stopping behavior a seed change could surface
        dict(repetition_penalty=1.4, eos_token_id=5),
        dict(no_repeat_ngram_size=2, eos_token_id=5),
        dict(eos_token_id=5, min_new_tokens=4),
        dict(repetition_penalty=1.3, no_repeat_ngram_size=3,
             eos_token_id=5, min_new_tokens=3),
    ])
    def test_beams_compose_with_penalties(self, hf_pair, kw):
        """r5: repetition_penalty / no_repeat_ngram_size / min_new_tokens
        under num_beams>1 — HF applies the processors to the per-beam
        log-softmax scores; token parity against transformers."""
        import torch

        hf, ours = hf_pair
        ids = np.random.RandomState(1).randint(0, 128, (2, 10))
        eos = kw.get("eos_token_id")
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(ids), max_new_tokens=8,
                              do_sample=False, num_beams=3,
                              pad_token_id=eos if eos is not None else 0,
                              **kw).numpy()[:, 10:]
        got = ours.generate(paddle.to_tensor(ids), max_new_tokens=8,
                            num_beams=3, **kw).numpy()
        fill = eos if eos is not None else 0
        # a LONGER best hypothesis than HF's is itself a divergence — it
        # must not hide behind the width slice below
        assert got.shape[1] <= ref.shape[1], (got, ref)
        if got.shape[1] < ref.shape[1]:
            got = np.pad(got, ((0, 0), (0, ref.shape[1] - got.shape[1])),
                         constant_values=fill)
        np.testing.assert_array_equal(got[:, :ref.shape[1]], ref)


def test_no_repeat_ngram_matches_transformers():
    """no_repeat_ngram_size bans completions of already-seen n-grams —
    token-identical to transformers' greedy with the same processor
    (greedy tiny models repeat heavily, so the ban actually bites)."""
    torch = pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFLlama
    from paddle_tpu.models.llama import llama_from_hf

    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128,
                      attention_bias=False, tie_word_embeddings=False)
    hf = HFLlama(hf_cfg).eval()
    ours = llama_from_hf(hf, dtype="float32", use_flash_attention=False)
    ids = np.random.RandomState(7).randint(0, 128, (2, 10))
    plain = ours.generate(paddle.to_tensor(ids), max_new_tokens=12).numpy()
    with torch.no_grad():
        ref = hf.generate(torch.from_numpy(ids), max_new_tokens=12,
                          do_sample=False, no_repeat_ngram_size=2,
                          pad_token_id=0).numpy()[:, 10:]
    got = ours.generate(paddle.to_tensor(ids), max_new_tokens=12,
                        no_repeat_ngram_size=2).numpy()
    np.testing.assert_array_equal(got, ref)
    assert not np.array_equal(got, plain)  # the ban actually changed output


def test_no_repeat_ngram_no_cache_matches_cached(tiny_model):
    x = _prompt(tiny_model.config, s=6, seed=9)
    a = tiny_model.generate(x, max_new_tokens=10, no_repeat_ngram_size=2).numpy()
    b = tiny_model.generate(x, max_new_tokens=10, no_repeat_ngram_size=2,
                            use_cache=False).numpy()
    np.testing.assert_array_equal(a, b)


class TestAdviceRegressions:
    """ADVICE r4 low-severity items, pinned."""

    def test_zero_temperature_rows_decode_greedily(self):
        """sample_logits_rows with temperature=0 + do_sample must take the
        argmax instead of overflowing the 1e6-scaled logits."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.generation import sample_logits_rows

        logits = jnp.asarray([[0.1, 5.0, 0.2], [3.0, 0.0, 0.1]], jnp.float32)
        out = sample_logits_rows(
            logits, jax.random.key(0),
            do_sample=jnp.asarray([True, True]),
            temperature=jnp.asarray([0.0, 1.0], jnp.float32),
            top_k=jnp.asarray([0, 0]), top_p=jnp.asarray([1.0, 1.0]))
        assert int(out[0]) == 1  # greedy despite do_sample
        assert np.all(np.isfinite(np.asarray(out)))

    def test_engine_rejects_negative_temperature(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving import ContinuousBatchEngine

        paddle.seed(0)
        eng = ContinuousBatchEngine(
            LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1)),
            max_batch=2, max_len=32)
        with pytest.raises(ValueError, match="temperature"):
            eng.add_request(np.array([1, 2, 3]), 4, do_sample=True,
                            temperature=-1.0)
        # temperature=0 with do_sample is legal: it decodes greedily
        eng.add_request(np.array([1, 2, 3]), 2, do_sample=True,
                        temperature=0.0)
        eng.run_until_done()

    def test_gpt2_cached_decode_overflow_raises(self):
        from paddle_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

        paddle.seed(0)
        cfg = GPT2Config.tiny(max_position_embeddings=16)
        m = GPT2LMHeadModel(cfg)
        ids = paddle.to_tensor(np.ones((1, 10), np.int64))
        with pytest.raises(ValueError, match="max_position_embeddings"):
            m.generate(ids, max_new_tokens=10)  # 10 + 10 > 16

    def test_t5_generate_accepts_default_kwargs(self):
        from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration

        paddle.seed(0)
        m = T5ForConditionalGeneration(T5Config.tiny())
        ids = paddle.to_tensor(np.ones((1, 6), np.int64))
        out = m.generate(ids, max_new_tokens=3, num_beams=1, use_cache=True,
                         repetition_penalty=1.0)  # explicit defaults: OK
        assert out.shape[0] == 1
        with pytest.raises(NotImplementedError, match="paged=True"):
            m.generate(ids, max_new_tokens=3, paged=True)

    def test_generate_defaults_dict_matches_signature(self):
        """GENERATE_DEFAULTS is the drift-guard copy of generate()'s
        defaults — keep them in lockstep."""
        import inspect
        from paddle_tpu.generation import GENERATE_DEFAULTS, generate

        sig = inspect.signature(generate)
        for k, v in GENERATE_DEFAULTS.items():
            assert sig.parameters[k].default == v, (k, v)

    def test_scalar_path_zero_temperature_greedy(self):
        """generate(do_sample=True, temperature=0) is deterministic greedy
        through the SCALAR sampling path too."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        paddle.seed(9)
        m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
        ids = paddle.to_tensor(np.arange(1, 7)[None, :])
        a = m.generate(ids, max_new_tokens=6, do_sample=True,
                       temperature=0.0)
        b = m.generate(ids, max_new_tokens=6, do_sample=True,
                       temperature=0.0)
        g = m.generate(ids, max_new_tokens=6, do_sample=False)
        np.testing.assert_array_equal(a.numpy(), b.numpy())
        np.testing.assert_array_equal(a.numpy(), g.numpy())

    def test_engine_level_negative_temperature_rejected(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.serving import ContinuousBatchEngine

        paddle.seed(0)
        with pytest.raises(ValueError, match="temperature"):
            ContinuousBatchEngine(
                LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1)),
                max_batch=2, max_len=32, do_sample=True, temperature=-0.5)

    def test_ngram_tracker_incremental_matches_oneshot(self):
        from paddle_tpu.generation import _NgramBan, _ngram_banned

        rng = np.random.RandomState(0)
        hist = [list(rng.randint(0, 7, 25)) for _ in range(3)]
        n, vocab = 3, 7
        tracker = _NgramBan([h[:5] for h in hist], n)
        for b, h in enumerate(hist):
            for t in h[5:]:
                tracker.append(b, t)
        np.testing.assert_array_equal(tracker.banned(vocab),
                                      _ngram_banned(hist, n, vocab))


def test_left_padded_prompts_match_right_padded(tiny_model):
    """HF-convention LEFT padding (r5: was a raise): internally rolled to
    the right-padded layout — rows decode exactly like their solo runs,
    greedy and beamed; interior holes still fail loudly."""
    cfg = tiny_model.config
    rng = np.random.RandomState(11)
    a = rng.randint(1, cfg.vocab_size, (1, 3))
    b = rng.randint(1, cfg.vocab_size, (1, 6))
    batch = np.zeros((2, 6), a.dtype)
    batch[0, 3:] = a[0]
    batch[1] = b[0]
    left = np.array([[0, 0, 0, 1, 1, 1], [1, 1, 1, 1, 1, 1]], "int64")

    for kw in (dict(), dict(num_beams=2, eos_token_id=5)):
        solo_a = tiny_model.generate(paddle.to_tensor(a),
                                     max_new_tokens=4, **kw).numpy()
        solo_b = tiny_model.generate(paddle.to_tensor(b),
                                     max_new_tokens=4, **kw).numpy()
        out = tiny_model.generate(
            paddle.to_tensor(batch), max_new_tokens=4,
            attention_mask=paddle.to_tensor(left), **kw).numpy()
        n = min(out.shape[1], solo_a.shape[1])
        np.testing.assert_array_equal(out[0, :n], solo_a[0, :n])
        n = min(out.shape[1], solo_b.shape[1])
        np.testing.assert_array_equal(out[1, :n], solo_b[0, :n])

    hole = np.array([[1, 0, 1, 1, 1, 1], [1, 1, 1, 1, 1, 1]], "int64")
    with pytest.raises(ValueError, match="interior holes"):
        tiny_model.generate(paddle.to_tensor(batch), max_new_tokens=2,
                            attention_mask=paddle.to_tensor(hole))

    # MIXED layouts: row 0 right-padded, row 1 left-padded — both valid
    mixed_batch = np.zeros((2, 6), a.dtype)
    mixed_batch[0, :3] = a[0]
    mixed_batch[1] = b[0]
    mixed = np.array([[1, 1, 1, 0, 0, 0], [1, 1, 1, 1, 1, 1]], "int64")
    out = tiny_model.generate(paddle.to_tensor(mixed_batch),
                              max_new_tokens=4,
                              attention_mask=paddle.to_tensor(mixed)).numpy()
    solo_a = tiny_model.generate(paddle.to_tensor(a), max_new_tokens=4).numpy()
    solo_b = tiny_model.generate(paddle.to_tensor(b), max_new_tokens=4).numpy()
    np.testing.assert_array_equal(out[0], solo_a[0])
    np.testing.assert_array_equal(out[1], solo_b[0])
