"""OLMo2 family: post-norm-only blocks, full-width q/k norms; HF
conversion with logits/greedy parity; decode-path agreement."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.olmo2 import (Olmo2Config, Olmo2ForCausalLM,
                                     olmo2_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _tiny_hf():
    from transformers import Olmo2Config as HFConfig
    from transformers import Olmo2ForCausalLM as HFOlmo2

    torch.manual_seed(0)
    return HFOlmo2(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        rope_theta=500000.0, tie_word_embeddings=False, pad_token_id=0,
        attn_implementation="eager")).eval()


def test_logits_and_generate_match_transformers():
    hf = _tiny_hf()
    ours = olmo2_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.qk_norm == "full"
    attn = ours.llama.layers[0].self_attn
    assert attn.q_norm.hidden_size == 64          # full projected width
    assert attn.k_norm.hidden_size == 32          # kv heads x head_dim
    ids = np.random.RandomState(0).randint(0, 128, (2, 11))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 11:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_decode_paths_agree():
    paddle.seed(0)
    m = Olmo2ForCausalLM(Olmo2Config.tiny())
    ids = paddle.to_tensor(np.random.RandomState(1).randint(1, 512, (1, 9)))
    a = m.generate(ids, max_new_tokens=5).numpy()
    b = m.generate(ids, max_new_tokens=5, use_cache=False).numpy()
    c = m.generate(ids, max_new_tokens=5, paged=True, page_size=4).numpy()
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)


def test_guards_and_training():
    from paddle_tpu import optimizer as opt

    with pytest.raises(ValueError, match="full"):
        Olmo2ForCausalLM(Olmo2Config.tiny(qk_norm=True))
    with pytest.raises(ValueError, match="qk_norm"):
        Olmo2Config.tiny(qk_norm="banded")
    paddle.seed(1)
    m = Olmo2ForCausalLM(Olmo2Config.tiny())

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]
