"""paddle.onnx.export: structural verification of the hand-written ONNX
protobuf (decoded with an independent minimal wire-format reader)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.onnx as onnx
from paddle_tpu.jit import InputSpec


def _read_varint(b, i):
    v = 0
    s = 0
    while True:
        x = b[i]
        i += 1
        v |= (x & 0x7F) << s
        if not x & 0x80:
            return v, i
        s += 7


def _parse(b):
    i = 0
    out = {}
    while i < len(b):
        key, i = _read_varint(b, i)
        f, w = key >> 3, key & 7
        if w == 0:
            v, i = _read_varint(b, i)
        elif w == 2:
            ln, i = _read_varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif w == 5:
            v = b[i:i + 4]
            i += 4
        else:
            raise ValueError(f"unexpected wire type {w}")
        out.setdefault(f, []).append(v)
    return out


def _graph_of(path):
    model = _parse(open(path, "rb").read())
    assert model[1][0] == 8                      # ir_version
    assert model[2][0] == b"paddle_tpu"          # producer
    opset = _parse(model[8][0])
    assert opset[2][0] == 13
    return _parse(model[7][0])


def test_mlp_export(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Dropout(0.5),
                        nn.Linear(16, 4), nn.Softmax())
    p = onnx.export(net, str(tmp_path / "mlp"),
                    input_spec=[InputSpec([2, 8], "float32")])
    g = _graph_of(p)
    ops = [_parse(n)[4][0].decode() for n in g[1]]
    # dropout elided in eval; linear = MatMul+Add
    assert ops == ["MatMul", "Add", "Relu", "MatMul", "Add", "Softmax"]
    inits = [_parse(t) for t in g.get(5, [])]
    assert [tuple(t.get(1, [])) for t in inits] == [
        (8, 16), (16,), (16, 4), (4,)]
    # initializer raw bytes hold the live weights
    w0 = np.frombuffer(inits[0][9][0], np.float32).reshape(8, 16)
    np.testing.assert_allclose(w0, net[0].weight.numpy(), rtol=1e-6)
    assert len(g.get(11, [])) == 1 and len(g.get(12, [])) == 1


def test_conv_export(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1, stride=2),
                        nn.BatchNorm2D(8), nn.ReLU(),
                        nn.AdaptiveAvgPool2D(1), nn.Flatten(),
                        nn.Linear(8, 4))
    p = onnx.export(net, str(tmp_path / "cnn"),
                    input_spec=[InputSpec([1, 3, 16, 16], "float32")])
    g = _graph_of(p)
    nodes = [_parse(n) for n in g[1]]
    ops = [n[4][0].decode() for n in nodes]
    assert ops == ["Conv", "BatchNormalization", "Relu",
                   "GlobalAveragePool", "Reshape", "MatMul", "Add"]
    conv_attrs = {_parse(a)[1][0].decode(): _parse(a)
                  for a in nodes[0].get(5, [])}
    assert conv_attrs["strides"][8] == [2, 2]
    assert conv_attrs["pads"][8] == [1, 1, 1, 1]
    assert conv_attrs["group"][3] == [1]
    # BatchNormalization carries exactly 5 inputs (x, scale, B, mean, var)
    assert len(nodes[1][1]) == 5


def test_unsupported_op_raises(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, 0)

    with pytest.raises(NotImplementedError, match="cumsum"):
        onnx.export(Weird(), str(tmp_path / "w"),
                    input_spec=[InputSpec([3], "float32")])
    with pytest.raises(ValueError):
        onnx.export(nn.Linear(2, 2), str(tmp_path / "n"))


def test_closure_attr_extraction(tmp_path):
    """Review regressions: attrs live in op closures, not recorded kwargs —
    reshape/transpose/matmul-transpose/softmax-axis/gelu-approx/custom-eps
    BN/downscale dropout/asymmetric conv padding must all round into the
    file correctly."""

    class M(nn.Layer):
        def forward(self, x):
            h = paddle.reshape(x, [2, 4])
            h = paddle.transpose(h, [1, 0])
            h = paddle.matmul(h, paddle.ones([2, 3]))
            return nn.functional.softmax(h, axis=0)

    p = onnx.export(M(), str(tmp_path / "m"),
                    input_spec=[InputSpec([8], "float32")])
    g = _graph_of(p)
    nodes = [_parse(n) for n in g[1]]
    assert [n[4][0].decode() for n in nodes] == [
        "Reshape", "Transpose", "MatMul", "Softmax"]
    assert _parse(nodes[-1][5][0])[3] == [0]          # softmax axis=0

    class M2(nn.Layer):
        def forward(self, x):
            return paddle.matmul(x, paddle.ones([4, 3]), transpose_y=True)

    p2 = onnx.export(M2(), str(tmp_path / "m2"),
                     input_spec=[InputSpec([2, 3], "float32")])
    ops2 = [_parse(n)[4][0].decode() for n in _graph_of(p2)[1]]
    assert ops2 == ["Transpose", "MatMul"]            # ty emitted

    class M3(nn.Layer):
        def forward(self, x):
            return nn.functional.gelu(x, approximate=True)

    p3 = onnx.export(M3(), str(tmp_path / "m3"),
                     input_spec=[InputSpec([4], "float32")])
    assert "Tanh" in [_parse(n)[4][0].decode() for n in _graph_of(p3)[1]]

    import struct

    bn = nn.BatchNorm2D(4, epsilon=1e-3, weight_attr=False, bias_attr=False)
    bn.eval()
    p4 = onnx.export(bn, str(tmp_path / "m4"),
                     input_spec=[InputSpec([1, 4, 5, 5], "float32")])
    g4 = _graph_of(p4)
    node = _parse(g4[1][0])
    assert len(node[1]) == 5                          # synthesized scale/bias
    eps = struct.unpack("<f", _parse(node[5][0])[2][0])[0]
    assert abs(eps - 1e-3) < 1e-9

    class M5(nn.Layer):
        def forward(self, x):
            return nn.functional.dropout(x, 0.5, training=self.training,
                                         mode="downscale_in_infer")

    m5 = M5()
    m5.eval()
    p5 = onnx.export(m5, str(tmp_path / "m5"),
                     input_spec=[InputSpec([4], "float32")])
    assert [_parse(n)[4][0].decode()
            for n in _graph_of(p5)[1]] == ["Mul"]     # (1-p) kept

    conv = nn.Conv2D(2, 2, 3, padding=[1, 2])
    p6 = onnx.export(conv, str(tmp_path / "m6"),
                     input_spec=[InputSpec([1, 2, 8, 8], "float32")])
    pads = {_parse(a)[1][0].decode(): _parse(a)
            for a in _parse(_graph_of(p6)[1][0]).get(5, [])}["pads"][8]
    assert pads == [1, 2, 1, 2]                       # begins + ends

    # dynamic batch dim becomes dim_param, not a frozen 1
    p7 = onnx.export(nn.Linear(8, 2), str(tmp_path / "m7"),
                     input_spec=[InputSpec([None, 8], "float32")])
    vi = _parse(_graph_of(p7)[11][0])
    dims = [_parse(d) for d in
            _parse(_parse(_parse(vi[2][0])[1][0])[2][0])[1]]
    assert dims[0][2][0].decode() == "dyn_0" and dims[1][1] == [8]

    # flatten lowers to Reshape with the rank-preserving target shape
    f8 = nn.Sequential(nn.Flatten(1), nn.Linear(12, 2))
    p8 = onnx.export(f8, str(tmp_path / "m8"),
                     input_spec=[InputSpec([2, 3, 4], "float32")])
    ops8 = [_parse(n)[4][0].decode() for n in _graph_of(p8)[1]]
    assert ops8 == ["Reshape", "MatMul", "Add"]


def test_tiny_lm_export_with_embedding_and_rmsnorm(tmp_path):
    """Embedding → Gather, rms_norm → Mul/ReduceMean/Add/Sqrt/Div chain —
    a minimal language-model head exports end-to-end."""

    class TinyLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.rms_w = paddle.create_parameter([16], "float32")
            self.head = nn.Linear(16, 50)

        def forward(self, ids):
            h = self.emb(ids)
            h = nn.functional.rms_norm(h, self.rms_w)
            return nn.functional.softmax(self.head(h), axis=-1)

    paddle.seed(0)
    m = TinyLM()
    m.eval()
    p = onnx.export(m, str(tmp_path / "lm"),
                    input_spec=[InputSpec([1, 6], "int64")])
    g = _graph_of(p)
    ops = [_parse(n)[4][0].decode() for n in g[1]]
    assert ops == ["Gather", "Mul", "ReduceMean", "Add", "Sqrt", "Div",
                   "Mul", "MatMul", "Add", "Softmax"]
    # embedding table rides as an initializer with the right shape
    inits = [_parse(t) for t in g.get(5, [])]
    shapes = [tuple(t.get(1, [])) for t in inits]
    assert (50, 16) in shapes


def test_flatten_dynamic_batch_reshape_wildcards(tmp_path):
    """flatten with a dynamic batch dim lowers to Reshape [0, -1] (ONNX
    wildcards), not the traced concrete shape — the exported graph must be
    valid at any batch size, not just the traced one (ADVICE r3)."""
    net = nn.Sequential(nn.Flatten(1), nn.Linear(12, 2))
    p = onnx.export(net, str(tmp_path / "mflat"),
                    input_spec=[InputSpec([None, 3, 4], "float32")])
    g = _graph_of(p)
    inits = {_parse(t)[8][0].decode(): _parse(t) for t in g.get(5, [])}
    shape_c = next(v for k, v in inits.items() if k.startswith("shape_const"))
    target = np.frombuffer(shape_c[9][0], np.int64)
    np.testing.assert_array_equal(target, [0, -1])
