"""Correctness sentinel (paddle_tpu.observability.sentinel): shadow
audits on the reference decode path, typed skip verdicts, the injected-
divergence drill (chaos -> sealed bundle -> alert -> offline replay with
flag bisection), canary probes, the federated stats contract, and the
< 1% enabled-overhead gate. See docs/SERVING.md "Correctness sentinel".
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.chaos import inject as chaos
from paddle_tpu.chaos.plan import Fault, FaultPlan
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import alerts as al
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.observability import sentinel
from paddle_tpu.observability import timeseries as ts
from paddle_tpu.serving import ContinuousBatchEngine, HandoffCorrupt


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    return ContinuousBatchEngine(model, **kw)


def _wait_counts(sn, want, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        fed = sn.federated()
        if (fed["audit_pass"] + fed["audit_diverged"]
                + fed["audit_skipped"]) >= want:
            return fed
        time.sleep(0.01)
    raise AssertionError(f"audits never drained: {sn.federated()}")


# ---- shadow audits ----------------------------------------------------------

def test_shadow_audit_clean_run_all_pass(tiny_model):
    """A clean greedy run at audit_rate=1.0: every finished request is
    replayed on the reference path and passes token-for-token; logprob
    drift is float-noise scale; zero divergence bundles are sealed."""
    eng = _engine(tiny_model)
    sn = eng.sentinel
    assert sn.auditable and not sn.enabled    # engine opts in, off by default
    sn.enable(audit_rate=1.0)
    sn.start()
    rec = frec.get_recorder()
    was = rec.enabled
    rec.enable()
    try:
        since = rec.stats()["recorded"]
        rng = np.random.RandomState(3)
        rids = [eng.add_request(rng.randint(1, 512, (5 + i,)),
                                max_new_tokens=6) for i in range(2)]
        eng.run_until_done()
        _wait_counts(sn, 2)       # shadow audits drain asynchronously
        verdicts = [sn.wait_verdict(r) for r in rids]
        assert all(v is not None for v in verdicts), verdicts
        assert [v["verdict"] for v in verdicts] == ["pass", "pass"]
        for v in verdicts:
            assert v["source"] == "shadow"
            assert v["first_divergence"] is None
            assert v["logprob_drift"] < 1e-4   # fused-vs-reference noise
        assert not sn.divergence_bundles()
        st = eng.stats()
        assert st["audit_pass"] == 2.0
        assert st["audit_diverged"] == 0.0
        kinds = [e["kind"] for e in rec.events(since=since, kind="audit")]
        assert kinds.count("audit.pass") == 2
    finally:
        sn.stop()
        if not was:
            rec.disable()


def test_forced_audit_of_sampled_request_skips_typed(tiny_model):
    """The on-demand contract for an ineligible request: a sampled
    request has no greedy reference stream, so audit=True records a
    waitable ``skipped`` verdict with reason ``sampling`` — typed,
    never silent."""
    eng = _engine(tiny_model)
    sn = eng.sentinel
    sn.enable(audit_rate=0.0)
    sn.start()
    try:
        rid = eng.add_request(np.arange(1, 7), max_new_tokens=4,
                              do_sample=True, temperature=0.9, audit=True)
        v = sn.wait_verdict(rid, timeout=30.0)   # skipped at ADMISSION
        assert v is not None
        assert v["verdict"] == "skipped"
        assert v["reason"] == "sampling"
        assert v["source"] == "ondemand"
        eng.run_until_done()                     # the request still runs
        assert sn.federated()["audit_skipped"] == 1.0
        assert sn.payload()["skip_reasons"] == {"sampling": 1}
    finally:
        sn.stop()


# ---- the injected-divergence drill ------------------------------------------

def test_divergence_drill_bundle_alert_and_replay_bisection(
        tiny_model, tmp_path):
    """THE acceptance drill: a chaos plan perturbs ONE emitted token;
    the audit catches it (first_divergence at the perturbed position),
    seals EXACTLY one checksummed divergence bundle, the
    ``audit_divergence`` objective fires off the metric increase, and
    the offline replay reproduces both streams and bisects blame back
    to the chaos plan."""
    store = ts.TimeSeriesStore(registry=None)
    store.enable()
    store.sample_once()
    plan = FaultPlan(seed=0, faults=[
        Fault("engine.logits", "perturb_logit", nth=2)])
    chaos.install(plan, scope="worker:0")
    eng = _engine(tiny_model)
    sn = eng.sentinel
    sn.enable(audit_rate=0.0, divergence_dir=str(tmp_path))
    sn.start()
    try:
        rid = eng.add_request(np.arange(1, 8), max_new_tokens=6,
                              audit=True)
        eng.run_until_done()
        v = sn.wait_verdict(rid, timeout=120.0)
        assert v is not None and v["verdict"] == "diverged", v
        assert v["first_divergence"] == 1      # nth=2 flips step 2's token
        assert v["source"] == "ondemand"
        assert v.get("bundle"), v
    finally:
        sn.stop()
        chaos.uninstall()

    # exactly ONE sealed bundle on disk; load re-verifies the checksum
    files = sorted(p for p in os.listdir(tmp_path)
                   if p.startswith("divergence-") and p.endswith(".json"))
    assert len(files) == 1, files
    path = os.path.join(tmp_path, files[0])
    bundle = sentinel.load_bundle(path)
    assert bundle["first_divergence"] == 1
    assert bundle["chaos"] is not None
    assert bundle["config"]["max_len"] == 64

    # the alert objective fires off the counter increase
    store.sample_once()
    objs = al.default_objectives()
    mgr = al.AlertManager(
        store, {"audit_divergence": objs["audit_divergence"]}, name="sn")
    mgr.evaluate()
    assert mgr.firing() == ["audit_divergence"]

    # a flipped byte is HandoffCorrupt, not a wrong-answer replay
    with open(path) as f:
        raw = json.load(f)
    raw["live_tokens"][0] = int(raw["live_tokens"][0]) + 1
    tampered = os.path.join(tmp_path, "tampered.json")
    with open(tampered, "w") as f:
        json.dump(raw, f)
    with pytest.raises(HandoffCorrupt):
        sentinel.load_bundle(tampered)

    # offline replay: both streams reproduce, bisection blames the plan
    report = sentinel.replay_bundle(bundle, tiny_model)
    assert report["ref_reproduced"] is True
    assert report["diverged_reproduced"] is True
    assert report["blame"] == ["chaos"]
    assert report["first_divergence_replayed"] == 1


# ---- canary probes ----------------------------------------------------------

def test_canary_probes_pin_baseline_and_pass(tiny_model):
    """Canaries pin expected outputs once per (config, flag-set)
    fingerprint and re-verify through the injected submitter; a clean
    engine passes every probe and the fingerprint is visible."""
    eng = _engine(tiny_model)
    sn = eng.sentinel
    sn.enable(n_canaries=2, canary_prompt_len=4, canary_max_new=4)
    sn.submitter = lambda ids, mnew: sentinel.reference_decode(
        eng.model, ids, mnew, eng.eos_token_id, None)
    results = sn.run_canaries()
    assert len(results) == 2
    assert all(r["verdict"] == "pass" for r in results)
    pay = sn.payload()
    assert pay["canary"]["runs"] == 1
    assert pay["canary"]["fingerprint"]
    fp = pay["canary"]["fingerprint"]
    # a canary-config change re-baselines: the fingerprint moves
    sn.enable(n_canaries=1, canary_max_new=5)
    sn.run_canaries()
    assert sn.payload()["canary"]["fingerprint"] != fp


# ---- surfaces: stats, federation, alerts, incident bundles ------------------

def test_federated_keys_alert_objectives_and_incident_section(
        tiny_model, tmp_path):
    """The contract the router/alerts/forensics surfaces pin: stats()
    always carries the audit scalars (even disabled), the objectives
    are registered on both sides, the federated series are declared,
    and incident bundles grow the additive ``audit`` section."""
    eng = _engine(tiny_model)
    st = eng.stats()
    for key in ("audit_pass", "audit_diverged", "audit_skipped",
                "audit_drift"):
        assert st[key] == 0.0
    assert "audit_divergence" in al.default_objectives()
    assert "cluster_audit_divergence" in al.cluster_objectives()
    assert {"cluster_audit_pass", "cluster_audit_diverged",
            "cluster_audit_skipped",
            "cluster_audit_drift"} <= set(al.FEDERATED_SERIES)
    # GET /audit document shape
    pay = sentinel.audit_payload()
    assert pay["schema_version"] == 1
    assert eng.sentinel.engine in pay["engines"]
    # incident bundles carry it (additive-optional: validate accepts
    # both presence and absence)
    rep = frec.IncidentReporter(str(tmp_path))
    b = rep.bundle("sentinel_test")
    frec.validate_bundle(b)
    assert b["audit"] is not None
    assert eng.sentinel.engine in b["audit"]["engines"]
    stripped = dict(b)
    del stripped["audit"]
    frec.validate_bundle(stripped)           # pre-audit bundles still load


# ---- the < 1% overhead gate -------------------------------------------------

def test_sentinel_overhead_under_one_percent_of_decode_step(tiny_model):
    """The enabled sentinel's cost on an UNAUDITED request — the
    admission-time sampling decision plus the finish-path guard — must
    stay under 1% of a real decode step."""
    eng = _engine(tiny_model)
    eng.profiler.enable()
    rng = np.random.RandomState(0)
    for i in range(4):
        eng.add_request(rng.randint(1, 512, (5 + i,)), 12)
    eng.run_until_done()
    step_p50_ms = eng.profiler.payload()["step_ms"]["p50"]
    assert step_p50_ms > 0

    sn = eng.sentinel
    sn.enable(audit_rate=0.0)
    # min over rounds: a scheduler preemption inflates a mean but not
    # the best round (the kvatlas/profiler gate convention)
    rounds, per = 10, 200
    over_ms = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(per):
            eng._mark_audit(None, None)       # admission decision
            sn.should_sample()                # the finish-path gate
        over_ms = min(over_ms, (time.perf_counter() - t0) * 1e3 / per)
    assert over_ms < 0.01 * step_p50_ms, (
        f"sentinel overhead {over_ms * 1e3:.2f}us is "
        f">= 1% of a {step_p50_ms:.3f}ms decode step")
