"""Engine-integrated speculative decoding (serving.py speculative_k):
multi-token steps in the continuous-batching engine.

THE correctness gate: engine speculative decode is TOKEN-IDENTICAL to
greedy non-speculative decode — against the solo dense-path generate
AND the one-token engine — across paged and prefix-hit layouts, with
chunked prefill and a preempt→restore cycle interleaved, and a slot
exported mid-speculation seals a consistent migration bundle. Plus the
drafter edge cases (empty history, k=1 degenerate rounds, eos inside an
accepted run) and the acceptance observability stack (stats keys,
serving_spec_accepted_tokens, sched.spec_* events, span attributes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.serving import ContinuousBatchEngine
from paddle_tpu.speculative import ngram_propose


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


@pytest.fixture()
def recorder():
    rec = frec.get_recorder()
    was = rec.enabled
    rec.enable()
    yield rec
    if not was:
        rec.disable()


def _solo(model, prompt, new):
    return model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                          max_new_tokens=new).numpy()[0]


#: a prompt the n-gram drafter can actually mine — repetitive enough
#: that the greedy stream's own cycles land in the history window
def _repetitive(n_reps=8):
    return np.tile(np.asarray([3, 5, 7, 9]), n_reps)


# ---- the drafter ------------------------------------------------------------

def test_ngram_propose_edge_cases():
    """Empty/short histories return empty proposals (the engine pads);
    matches prefer the LONGEST n-gram and its MOST RECENT occurrence,
    and the iterated lookup EXTENDS a periodic history past its end
    (each proposed token feeds the next lookup)."""
    assert ngram_propose([], 3).size == 0
    assert ngram_propose([5], 3).size == 0          # nothing before tail
    np.testing.assert_array_equal(ngram_propose([1, 2, 3, 1, 2], 3),
                                  [3, 1, 2])
    np.testing.assert_array_equal(
        ngram_propose([1, 2, 9, 4, 1, 2, 8, 4, 1, 2], 2), [8, 4])
    # a constant run extends autoregressively, not truncating at the end
    np.testing.assert_array_equal(ngram_propose([9, 9, 9], 3), [9, 9, 9])
    # a period-2 cycle keeps cycling
    np.testing.assert_array_equal(ngram_propose([4, 6, 4, 6], 4),
                                  [4, 6, 4, 6])
    assert ngram_propose([1, 2, 3], 0).size == 0    # k=0 degenerate
    assert ngram_propose([1, 2, 3, 4], 3).size == 0  # nothing repeats


# ---- token identity: THE gate ----------------------------------------------

@pytest.mark.parametrize("k", [1, 2, 4])
def test_spec_engine_token_identity_paged(tiny_model, k):
    """Engine speculative decode at several chunk widths (k=1 is the
    degenerate no-draft round) equals solo greedy generate (the dense
    reference path) for every staggered request — random prompts (empty
    drafter history / no n-gram hits) AND a repetitive prompt (real
    accepted runs)."""
    m = tiny_model
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, m.config.vocab_size, (n,))
               for n in (5, 11, 3)] + [_repetitive()]
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                speculative_k=k)
    rids = [eng.add_request(p, max_new_tokens=8) for p in prompts[:3]]
    for _ in range(3):
        eng.step()
    rids.append(eng.add_request(prompts[3], max_new_tokens=8))
    done = eng.run_until_done()
    for rid, p in zip(rids, prompts):
        np.testing.assert_array_equal(done[rid], _solo(m, p, 8),
                                      err_msg=f"req {rid} k={k}")


def test_spec_accepts_on_repetitive_prompt(tiny_model):
    """The n-gram drafter must actually EARN tokens on a repetitive
    workload: accepted_tokens_per_dispatch > 1.0, counters and the
    acceptance histogram move, output stays exactly greedy."""
    from paddle_tpu.observability import catalog as cat

    m = tiny_model
    p = _repetitive()
    n0 = cat.SERVING_SPEC_ACCEPTED.count(engine="decoder")
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=128, page_size=8,
                                speculative_k=4)
    rid = eng.add_request(p, max_new_tokens=16)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[rid], _solo(m, p, 16))
    st = eng.stats()
    assert st["spec_accepted_tokens"] > 0
    assert st["accepted_tokens_per_dispatch"] > 1.0
    assert st["spec_dispatches"] == st["decode_steps"]
    assert st["spec_emitted_tokens"] == 16
    # the histogram observed once per slot per dispatch
    assert cat.SERVING_SPEC_ACCEPTED.count(engine="decoder") > n0


def test_spec_with_prefix_cache_hit(tiny_model):
    """Speculation over a prefix-cached admission: the second request
    copies pages from the ACTIVE first slot, then both decode through
    multi-token steps token-identically to solo."""
    m = tiny_model
    rng = np.random.RandomState(5)
    shared = rng.randint(0, m.config.vocab_size, (17,))
    p1 = np.concatenate([shared, rng.randint(0, m.config.vocab_size, (4,))])
    p2 = np.concatenate([shared, rng.randint(0, m.config.vocab_size, (7,))])
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                enable_prefix_cache=True, speculative_k=4)
    r1 = eng.add_request(p1, max_new_tokens=6)
    r2 = eng.add_request(p2, max_new_tokens=6)
    assert eng.prefix_pages_reused == 2
    done = eng.run_until_done()
    for rid, p in ((r1, p1), (r2, p2)):
        np.testing.assert_array_equal(done[rid], _solo(m, p, 6))


def test_spec_with_chunked_prefill_interleaved(tiny_model):
    """A long prompt lands chunk by chunk while a live slot runs
    MULTI-token speculative dispatches in between: the reserved slot's
    k throwaway writes park at its chunk frontier and the next chunk's
    scatter overwrites them — both outputs exactly solo greedy."""
    m = tiny_model
    rng = np.random.RandomState(7)
    long_p = rng.randint(0, m.config.vocab_size, (40,))
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16, speculative_k=4)
    live = eng.add_request(_repetitive(4), max_new_tokens=12)
    for _ in range(2):
        eng.step()                       # live slot decoding speculatively
    r_long = eng.add_request(long_p, max_new_tokens=6)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[live], _solo(m, _repetitive(4), 12))
    np.testing.assert_array_equal(done[r_long], _solo(m, long_p, 6))


def test_spec_with_preempt_restore_cycle(tiny_model, recorder):
    """Preemption mid-speculation: the victim's bundle seals kv_len =
    prompt + delivered tokens (rejected-draft garbage beyond it is
    masked and overwritten after restore), and BOTH streams finish
    token-identical to uninterrupted greedy runs."""
    m = tiny_model
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    victim_p = _repetitive(6)            # speculation active when evicted
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                enable_preemption=True, speculative_k=3)
    since = recorder.stats()["recorded"]
    victim = eng.add_request(victim_p, max_new_tokens=12, priority=2)
    for _ in range(3):
        eng.step()
    n_gen = len(eng._slots[0].tokens)
    assert n_gen >= 3                    # spec steps emitted >= 1 each
    hi = eng.add_request(long_p, max_new_tokens=6, priority=0)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[hi], _solo(m, long_p, 6))
    np.testing.assert_array_equal(done[victim], _solo(m, victim_p, 12))
    evs = recorder.events(since=since)
    pre = [e for e in evs if e["kind"] == "sched.preempt"]
    res = [e for e in evs if e["kind"] == "sched.restore"]
    assert len(pre) == 1 and len(res) == 1
    assert pre[0]["kv_len"] == res[0]["kv_len"] == victim_p.size + n_gen


def test_spec_slot_migrates_mid_speculation(tiny_model):
    """export_slot() on a speculating slot seals a consistent bundle: a
    PEER engine admits it and the continued stream is token-identical —
    the delivered prefix plus the peer's continuation equals solo."""
    m = tiny_model
    p = _repetitive()
    src = ContinuousBatchEngine(m, max_batch=1, max_len=128, page_size=8,
                                speculative_k=4)
    rid = src.add_request(p, max_new_tokens=16)
    for _ in range(2):
        src.step()
    delivered = list(src._slots[0].tokens)
    assert delivered                       # mid-stream
    bundle = src.export_slot(rid)
    dst = ContinuousBatchEngine(m, max_batch=1, max_len=128, page_size=8,
                                speculative_k=4)
    rid2 = dst.admit_migrated(bundle)
    done = dst.run_until_done()
    np.testing.assert_array_equal(done[rid2], _solo(m, p, 16))
    assert done[rid2][:len(delivered)].tolist() == delivered


def test_spec_composes_with_sliding_window():
    """Speculative verify under a Mistral sliding window: the chunk's
    banded mask counts per-position true distances — token-identical to
    solo greedy, with real acceptance on the repetitive slot."""
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM

    paddle.seed(0)
    cfg = MistralConfig.tiny(sliding_window=8, use_flash_attention=False)
    m = MistralForCausalLM(cfg)
    p = np.random.RandomState(0).randint(0, 512, (20,))
    rep = _repetitive(6)
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                speculative_k=4)
    r1 = eng.add_request(p, max_new_tokens=8)
    r2 = eng.add_request(rep, max_new_tokens=8)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[r1], _solo(m, p, 8))
    np.testing.assert_array_equal(done[r2], _solo(m, rep, 8))


# ---- stop conditions inside an accepted run ---------------------------------

def test_eos_inside_accepted_run(tiny_model, monkeypatch):
    """eos landing at position >= 1 of an ACCEPTED run: tokens past it
    are never delivered and the slot retires with reason "stop". An
    oracle drafter (the true greedy continuation) makes the first
    dispatch accept a full varied-token chunk deterministically, so the
    eos is guaranteed to sit INSIDE the run, not at a dispatch
    boundary."""
    import paddle_tpu.speculative as spec_mod

    m = tiny_model
    rng = np.random.RandomState(3)
    p = rng.randint(0, m.config.vocab_size, (9,))
    ref = _solo(m, p, 16)

    def oracle(history, k, max_ngram=3):
        n_gen = np.asarray(history).reshape(-1).size - p.size
        return np.asarray(ref[n_gen: n_gen + k], np.int32)

    monkeypatch.setattr(spec_mod, "ngram_propose", oracle)
    # first dispatch (k=4) accepts ref[0:4]; an eos at chunk position 2
    # whose FIRST occurrence is there truncates mid-run
    j = next(jj for jj in range(1, 4) if ref[jj] not in ref[:jj])
    eos = int(ref[j])
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                eos_token_id=eos, speculative_k=4)
    rid = eng.add_request(p, max_new_tokens=16)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[rid], ref[: j + 1])
    assert eng.finish_reason(rid) == "stop"
    st = eng.stats()
    assert st["spec_dispatches"] == 1        # one multi-token dispatch
    assert st["spec_emitted_tokens"] == j + 1


def test_budget_truncates_inside_accepted_run(tiny_model):
    """max_new_tokens hit mid-run: the engine delivers exactly the
    budget and retires with reason "length" — extra accepted tokens are
    discarded, never streamed."""
    m = tiny_model
    p = _repetitive()
    ref = _solo(m, p, 5)
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                speculative_k=4)
    rid = eng.add_request(p, max_new_tokens=5)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[rid], ref)
    assert done[rid].size == 5
    assert eng.finish_reason(rid) == "length"


def test_stop_token_ids_inside_run(tiny_model, monkeypatch):
    """Per-request stop sets truncate accepted runs exactly like the
    engine eos (oracle drafter pins the stop inside the first run)."""
    import paddle_tpu.speculative as spec_mod

    m = tiny_model
    rng = np.random.RandomState(3)
    p = rng.randint(0, m.config.vocab_size, (9,))
    ref = _solo(m, p, 16)

    def oracle(history, k, max_ngram=3):
        n_gen = np.asarray(history).reshape(-1).size - p.size
        return np.asarray(ref[n_gen: n_gen + k], np.int32)

    monkeypatch.setattr(spec_mod, "ngram_propose", oracle)
    j = next(jj for jj in range(1, 4) if ref[jj] not in ref[:jj])
    stop = int(ref[j])
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                speculative_k=4)
    rid = eng.add_request(p, max_new_tokens=16, stop_token_ids=[stop])
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[rid], ref[: j + 1])
    assert eng.finish_reason(rid) == "stop"


# ---- sampling fallback, streaming, logprobs ---------------------------------

def test_sampling_slot_falls_back_to_one_token_step(tiny_model):
    """A dispatch with a sampling slot active runs the one-token step
    (speculation is greedy-exact only); the greedy request still equals
    its solo run, and speculation resumes once the sampler retires."""
    m = tiny_model
    rng = np.random.RandomState(11)
    pg = _repetitive()
    ps = rng.randint(0, 512, (9,))
    paddle.seed(123)
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=128, page_size=8,
                                speculative_k=4)
    r_greedy = eng.add_request(pg, max_new_tokens=16)
    r_sample = eng.add_request(ps, max_new_tokens=4, do_sample=True,
                               temperature=0.8, top_k=7)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[r_greedy], _solo(m, pg, 16))
    assert done[r_sample].shape == (4,)
    st = eng.stats()
    # the sampler's 4 dispatches ran one-token; spec resumed after
    assert 0 < st["spec_dispatches"] < st["decode_steps"]


def test_spec_streaming_and_logprobs(tiny_model):
    """on_token streams every token of an accepted run in order (last
    one flagged done) and chosen-token logprobs align 1:1 with the
    generated ids, exactly like the one-token engine."""
    m = tiny_model
    p = _repetitive()
    streamed = []

    def cb(rid, tok, done, lp):
        streamed.append((tok, done, lp))

    eng = ContinuousBatchEngine(m, max_batch=1, max_len=128, page_size=8,
                                speculative_k=4)
    rid = eng.add_request(p, max_new_tokens=12, on_token=cb, logprobs=True)
    done = eng.run_until_done()
    toks = [t for t, _, _ in streamed]
    np.testing.assert_array_equal(np.asarray(toks), done[rid])
    flags = [d for _, d, _ in streamed]
    assert flags == [False] * (len(flags) - 1) + [True]
    lps = eng.logprobs(rid)
    assert lps is not None and len(lps) == done[rid].size
    assert all(lp <= 0.0 for lp in lps)
    # reference: the one-token engine's logprobs for the same stream
    eng2 = ContinuousBatchEngine(m, max_batch=1, max_len=128, page_size=8)
    rid2 = eng2.add_request(p, max_new_tokens=12, logprobs=True)
    eng2.run_until_done()
    np.testing.assert_allclose(lps, eng2.logprobs(rid2), rtol=2e-5,
                               atol=2e-5)


# ---- observability ----------------------------------------------------------

def test_spec_events_and_span_attrs(tiny_model, recorder):
    """Every spec dispatch leaves sched.spec_propose/verify/accept in
    the flight recorder, and the request's root span carries the
    spec_rounds / spec_accepted_tokens attributes at retirement."""
    from paddle_tpu.observability import tracing

    tracer = tracing.get_tracer()
    was = tracer.enabled
    tracer.enable()
    try:
        since = recorder.stats()["recorded"]
        m = tiny_model
        eng = ContinuousBatchEngine(m, max_batch=1, max_len=128,
                                    page_size=8, speculative_k=4)
        rid = eng.add_request(_repetitive(), max_new_tokens=12)
        eng.run_until_done()
        evs = recorder.events(since=since)
        prop = [e for e in evs if e["kind"] == "sched.spec_propose"]
        ver = [e for e in evs if e["kind"] == "sched.spec_verify"]
        acc = [e for e in evs if e["kind"] == "sched.spec_accept"]
        n = eng.stats()["spec_dispatches"]
        assert len(prop) == len(ver) == len(acc) == n > 0
        assert all(e["k"] == 4 for e in ver)
        assert sum(e["emitted"] for e in acc) == 12
        # newest-first over finished spans: rids restart per engine and
        # earlier tests may have left same-rid (or still-live) request
        # spans in the global tracer — the spec attrs identify ours
        root = next(s for s in reversed(tracer.spans())
                    if s["name"] == "serving.request"
                    and s["attrs"].get("rid") == rid
                    and "spec_rounds" in s["attrs"])
        assert root["attrs"]["spec_rounds"] == n
        assert root["attrs"]["spec_accepted_tokens"] == \
            eng.stats()["spec_accepted_tokens"]
    finally:
        if not was:
            tracer.disable()


def test_spec_stats_keys_present_when_off(tiny_model):
    """Dashboards read stable keys: a spec-off engine reports the spec
    stats keys as zeros (and /health therefore always carries them)."""
    eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=32,
                                page_size=8)
    st = eng.stats()
    assert st["spec_dispatches"] == 0
    assert st["accepted_tokens_per_dispatch"] == 0.0


# ---- admission guard rails --------------------------------------------------

def test_spec_slack_enforced_at_admission(tiny_model):
    """prompt + max_new + (k-1) must fit max_len: without the slack the
    final dispatch's chunk scatter would clamp onto the slot's last
    valid page."""
    eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=16,
                                page_size=4, speculative_k=4)
    eng.add_request(np.arange(1, 6), max_new_tokens=8)   # 5+8+3 == 16 ok
    with pytest.raises(ValueError, match="speculation slack"):
        eng.add_request(np.arange(1, 7), max_new_tokens=8)


def test_spec_rejects_latent_mode():
    from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                            DeepseekV2ForCausalLM)

    paddle.seed(3)
    m = DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(
        num_hidden_layers=1))
    with pytest.raises(NotImplementedError, match="paged"):
        ContinuousBatchEngine(m, max_batch=1, max_len=32, page_size=8,
                              speculative_k=4)


def test_spec_auto_k_off_device_defaults(tiny_model):
    """speculative_k="auto" resolves through the autotune cost table;
    off-TPU (no measurements possible) it lands on the default without
    touching the device."""
    eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=64,
                                page_size=8, speculative_k="auto")
    assert eng.speculative_k == 4


def test_spec_auto_k_reranks_by_expected_tokens(tiny_model, monkeypatch):
    """The auto-k pick combines the measured per-dispatch cost table
    with the geometric acceptance expectation: a wider chunk whose
    dispatch is only marginally slower wins on expected retired tokens
    per dispatch, and failed geometries are skipped."""
    from paddle_tpu.ops.pallas import autotune
    from paddle_tpu.serving import _resolve_spec_k

    monkeypatch.setattr(autotune, "enabled", lambda: True)
    captured = {}

    def fake_search(kernel, sig, default, cands, runner, can, **kw):
        captured["kernel"] = kernel
        return default

    class FakeCache:
        def entry(self, kernel, key):
            return {"table": {"2": {"status": "ok", "ms": 1.0},
                              "4": {"status": "ok", "ms": 1.15},
                              "6": {"status": "ok", "ms": 1.3},
                              "8": {"status": "fail"}}}

    monkeypatch.setattr(autotune, "search", fake_search)
    monkeypatch.setattr(autotune, "get_cache", lambda: FakeCache())
    # ms/E[tokens] at p=0.7: k=2 -> .59, k=4 -> .45, k=6 -> .44 (best)
    assert _resolve_spec_k(tiny_model, 4, 64) == 6
    assert captured["kernel"] == "spec_verify"


def test_spec_invalid_k_rejected(tiny_model):
    with pytest.raises(ValueError, match="speculative_k"):
        ContinuousBatchEngine(tiny_model, max_batch=1, max_len=32,
                              page_size=8, speculative_k=0)


# ---- fused decode tail (megakernel) x speculation ---------------------------

def test_spec_fused_decode_tail_token_identity():
    """The S>1 verify chunk rides the fused decode-tail megakernels
    (flattened B*S rows, per-row rope positions) where the gate admits:
    token-identical to the discrete path, in interpret mode on CPU."""
    from paddle_tpu.utils.flags import get_flags, set_flags

    cfg = LlamaConfig(vocab_size=128, hidden_size=256,
                      intermediate_size=512, num_hidden_layers=2,
                      num_attention_heads=2, num_key_value_heads=1,
                      max_position_embeddings=256,
                      use_flash_attention=False, dtype="float32")
    paddle.seed(0)
    m = LlamaForCausalLM(cfg)
    p = _repetitive(5)
    ref = _solo(m, p, 10)
    prev = get_flags("FLAGS_use_fused_decode_tail")[
        "FLAGS_use_fused_decode_tail"]
    set_flags({"FLAGS_use_fused_decode_tail": True})
    try:
        eng = ContinuousBatchEngine(m, max_batch=2, max_len=64,
                                    page_size=8, speculative_k=4)
        rid = eng.add_request(p, max_new_tokens=10)
        done = eng.run_until_done()
        np.testing.assert_array_equal(done[rid], ref)
    finally:
        set_flags({"FLAGS_use_fused_decode_tail": prev})


# ---- solo-path stats contract ----------------------------------------------

def test_speculative_generate_return_stats(tiny_model):
    """speculative_generate(return_stats=True) matches
    mtp_speculative_generate's stats contract (rounds/hits/acceptance)
    and never changes the emitted tokens."""
    from paddle_tpu.speculative import speculative_generate

    m = tiny_model
    prompt = np.random.RandomState(0).randint(
        0, m.config.vocab_size, (1, 9))
    ref = m.generate(paddle.to_tensor(prompt), max_new_tokens=10).numpy()
    out, stats = speculative_generate(
        m, m, paddle.to_tensor(prompt), max_new_tokens=10, draft_k=3,
        return_stats=True)
    np.testing.assert_array_equal(out.numpy(), ref)
    assert set(stats) == {"rounds", "hits", "acceptance"}
    # perfect draft (target == draft): every proposal accepted
    assert stats["hits"] == stats["rounds"] * 3
    assert stats["acceptance"] == 1.0
