"""pdlint --lifecycle: the CFG-based resource-leak layer.

Four blocks, mirroring tests/test_static_analysis.py:

1. **CFG unit tests** — the builder's edge sets on the constructs that
   break naive walkers (try/finally with return, while-True/break,
   except chains, else clauses, nested with, may_raise whitelisting).
2. **Fixture corpus per lifecycle behavior** — a known-leaking snippet
   that FAILS and a known-good twin that stays clean, for every escape
   kind (except-edge, early return, loop re-acquire, discarded) and
   every non-leak (transfer via return/attr/container, finally-release,
   with-managed, None and -1 sentinel guards, helper summaries).
3. **Framework tests** — leak-path pragma suppression, the generalized
   unused-disable rule, SARIF output shape, --prune-baseline.
4. **The tier-1 gate** — ``scripts/pdlint.py --lifecycle --json`` over
   the whole package exits 0 with ZERO baselined leak-path entries,
   plus regression tests for the real leaks this pass found and fixed
   (router lease guards, Tracer.span end-before-pop).
"""
import ast
import importlib.util
import json
import os
import sys
import textwrap

import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, _REPO)

from paddle_tpu import analysis
from paddle_tpu.analysis import cfg
from paddle_tpu.analysis import baseline as bl
from paddle_tpu.analysis import report


def _rules():
    return analysis.ast_rules(["leak-path"])


def lint(src, filename="fix.py"):
    """leak-path findings for one dedented snippet."""
    found = analysis.analyze_source(textwrap.dedent(src), filename,
                                    _rules())
    return [f.message for f in found]


def _cfg_of(src, noraise=frozenset()):
    tree = ast.parse(textwrap.dedent(src))
    func = cfg.function_nodes(tree)[0][1]
    return cfg.build_cfg(func, noraise=noraise)


# ---------------------------------------------------------------------------
# 1. the CFG builder on its own
# ---------------------------------------------------------------------------

def test_cfg_if_else_edges():
    g = _cfg_of("""
    def f(c):
        if c:
            a = 1
        else:
            a = 2
        return a
    """)
    labels = g.edge_labels()
    assert ("branch@3", "true", "stmt@4") in labels
    assert ("branch@3", "false", "stmt@6") in labels
    assert ("stmt@7", "return", "exit") in labels
    # a bare-name test cannot raise: no raise edge off the branch
    assert not any(s == "branch@3" and k == "raise"
                   for (s, k, _d) in labels)


def test_cfg_try_finally_with_return_runs_finally():
    """The classic: ``return`` inside try must route THROUGH the
    finally body before reaching exit — the property the whole leak
    pass rests on."""
    g = _cfg_of("""
    def f(x):
        try:
            return x
        finally:
            done()
    """)
    labels = g.edge_labels()
    assert ("stmt@4", "return", "finally@6") in labels
    assert ("finally@6", "next", "stmt@6") in labels
    assert ("stmt@6", "return", "exit") in labels
    # no edge skips the finally: the return stmt never reaches exit
    # directly
    assert ("stmt@4", "return", "exit") not in labels


def test_cfg_while_true_has_no_false_exit():
    g = _cfg_of("""
    def f():
        while True:
            if ready():
                break
        return 1
    """)
    labels = g.edge_labels()
    # while True: the loop head's ONLY structured exit is the break
    assert not any(s == "loop@3" and k == "false"
                   for (s, k, _d) in labels)
    assert ("stmt@5", "break", "loopexit@3") in labels
    assert ("loopexit@3", "next", "stmt@6") in labels


def test_cfg_except_dispatch_and_narrow_handler_unwind():
    g = _cfg_of("""
    def f():
        try:
            risky()
        except ValueError:
            handle()
        return 1
    """)
    labels = g.edge_labels()
    assert ("stmt@4", "raise", "except@3") in labels
    assert ("handler@5", "caught", "stmt@6") in labels
    # a NARROW handler may not match: the unwind continues out
    assert ("handler@5", "raise", "raise") in labels


def test_cfg_else_clauses():
    g = _cfg_of("""
    def f():
        try:
            risky()
        except ValueError:
            handle()
        else:
            good()
        return 1
    """)
    labels = g.edge_labels()
    # try-body success flows into the else body, never the handler
    assert ("stmt@4", "next", "stmt@8") in labels
    assert ("stmt@8", "next", "stmt@9") in labels
    g2 = _cfg_of("""
    def f(xs):
        for x in xs:
            use(x)
        else:
            done()
    """)
    labels2 = g2.edge_labels()
    assert ("loop@3", "false", "stmt@6") in labels2
    assert ("stmt@4", "loop", "loop@3") in labels2


def test_cfg_nested_with_unwind():
    g = _cfg_of("""
    def f(p):
        with open(p) as a:
            with open(p) as b:
                use(a, b)
    """)
    labels = g.edge_labels()
    assert ("with@3", "with", "with@4") in labels
    assert ("with@4", "with", "stmt@5") in labels
    # each context expr can raise during acquisition
    assert ("with@3", "raise", "raise") in labels
    assert ("with@4", "raise", "raise") in labels


def test_may_raise_whitelist_and_scope_barriers():
    stmt = ast.parse("log.info('x')").body[0]
    assert cfg.may_raise(stmt)
    assert not cfg.may_raise(stmt, resolver=lambda n: "log.info",
                             noraise=frozenset({"info"}))
    assert not cfg.may_raise(ast.parse("x = y + 1").body[0])
    # a call inside a lambda body runs LATER, elsewhere
    assert not cfg.may_raise(ast.parse("cb = lambda: boom()").body[0])


# ---------------------------------------------------------------------------
# 2. fixture corpus: every escape kind and every non-leak
# ---------------------------------------------------------------------------

def test_leak_on_except_edge():
    msgs = lint("""
    def f(pool, risky):
        w = pool.select()
        risky(w)
        pool.release(w)
    """)
    assert len(msgs) == 1
    assert "pool-lease 'w'" in msgs[0]
    assert "leaks when `risky(w)` raises" in msgs[0]


def test_finally_release_is_clean():
    assert lint("""
    def f(pool, risky):
        w = pool.select()
        try:
            risky(w)
        finally:
            pool.release(w)
    """) == []


def test_leak_on_early_return_names_the_return():
    msgs = lint("""
    def f(pool, cond):
        w = pool.select()
        if cond:
            return 0
        pool.release(w)
        return 1
    """)
    assert len(msgs) == 1
    assert "leaks at `return 0` (line 5)" in msgs[0]


def test_transfer_via_return_is_clean():
    assert lint("""
    def f(pool):
        w = pool.select()
        return w
    """) == []


def test_none_and_index_sentinel_guards_are_clean():
    assert lint("""
    def f(pool):
        w = pool.select()
        if w is None:
            return None
        return w
    """) == []
    # the -1 convention: engine _alloc_slot answers -1 for "no slot"
    assert lint("""
    def f(self):
        s = self._alloc_slot()
        if s < 0:
            return None
        try:
            self.use(s)
        finally:
            self._release_slot(s)
    """) == []


def test_engine_slot_leak_without_release():
    msgs = lint("""
    def f(self, risky):
        s = self._alloc_slot()
        if s < 0:
            return None
        risky(s)
        self._release_slot(s)
    """)
    assert len(msgs) == 1
    assert "engine-slot 's'" in msgs[0]


def test_with_managed_acquire_is_clean():
    assert lint("""
    def f(path, risky):
        with open(path) as fh:
            risky(fh.read())
    """) == []


def test_loop_reacquire_leak_and_released_loop_clean():
    msgs = lint("""
    def f(pool, items, risky):
        for it in items:
            w = pool.select()
            if w is None:
                continue
            risky(it)
            pool.release(w)
    """)
    assert len(msgs) == 1
    assert "leaks when `risky(it)` raises" in msgs[0]
    assert lint("""
    def f(pool, items):
        for it in items:
            w = pool.select()
            if w is None:
                continue
            pool.release(w)
    """) == []


def test_discarded_acquire_is_flagged():
    msgs = lint("""
    import subprocess
    def f():
        subprocess.Popen(['ls'])
    """)
    assert len(msgs) == 1
    assert "process-handle" in msgs[0]
    assert "discarded immediately" in msgs[0]


def test_transfer_via_attribute_and_container_store():
    assert lint("""
    def f(self, pool):
        w = pool.select()
        self.w = w
    """) == []
    assert lint("""
    def f(pool, q):
        w = pool.select()
        q.append(w)
    """) == []


def test_one_level_helper_summary_releases():
    assert lint("""
    class R:
        def _teardown(self, w):
            self.pool.release(w)
        def go(self, risky):
            w = self.pool.select()
            try:
                risky()
            finally:
                self._teardown(w)
    """) == []


def test_kv_bundle_transfer_vs_drop():
    assert lint("""
    def f(engine, dst):
        b = engine.export_slot(3)
        dst.admit_migrated(b)
    """) == []
    msgs = lint("""
    def f(engine, dst, risky):
        b = engine.export_slot(3)
        risky()
        dst.admit_migrated(b)
    """)
    assert len(msgs) == 1
    assert "kv-bundle 'b'" in msgs[0]


def test_tracer_span_needs_end_on_every_path():
    msgs = lint("""
    def f(tracer, risky):
        sp = tracer.start_span('x')
        risky()
        sp.end()
    """)
    assert len(msgs) == 1
    assert "tracer-span 'sp'" in msgs[0]
    assert lint("""
    def f(tracer, risky):
        sp = tracer.start_span('x')
        try:
            risky()
        finally:
            sp.end()
    """) == []


def test_pool_claim_counts_as_acquire():
    msgs = lint("""
    def f(self, w, risky):
        self.pool.claim(w)
        risky()
        self.pool.release(w)
    """)
    assert len(msgs) == 1
    assert "pool-lease 'w'" in msgs[0]


def test_noraise_calls_are_not_escape_edges():
    # the logger between acquire and release is trusted not to throw
    assert lint("""
    def f(pool, log):
        w = pool.select()
        log.info('placing %s', w)
        pool.release(w)
    """) == []


# ---------------------------------------------------------------------------
# 3. framework: pragmas, unused-disable, SARIF, --prune-baseline
# ---------------------------------------------------------------------------

def test_leak_path_pragma_suppresses():
    assert lint("""
    def f(pool, risky):
        w = pool.select()  # pdlint: disable=leak-path -- deliberate
        risky(w)
        pool.release(w)
    """) == []


def test_unused_disable_flags_dead_pragma():
    src = ("def f():\n"
           "    return 1  # pdlint: disable=silent-exception\n")
    msgs = [f.message for f in analysis.analyze_source(src, "m.py")
            if f.rule == "unused-disable"]
    assert len(msgs) == 1
    assert "suppresses nothing" in msgs[0]


def test_used_disable_is_not_flagged():
    src = ("def f():\n"
           "    try:\n"
           "        risky()\n"
           "    except Exception:  # pdlint: disable=silent-exception\n"
           "        pass\n")
    found = analysis.analyze_source(src, "m.py")
    assert [f for f in found if f.rule == "unused-disable"] == []
    assert [f for f in found if f.rule == "silent-exception"] == []


def test_unknown_rule_id_in_pragma_is_flagged():
    src = "x = 1  # pdlint: disable=leek-path\n"
    msgs = [f.message for f in analysis.analyze_source(src, "m.py")
            if f.rule == "unused-disable"]
    assert len(msgs) == 1
    assert "unknown rule 'leek-path'" in msgs[0]


def test_disable_all_and_gated_rule_ids_never_flagged():
    # 'all' is a policy statement, not a rule id
    src = "x = 1  # pdlint: disable=all\n"
    found = analysis.analyze_source(src, "m.py")
    assert [f for f in found if f.rule == "unused-disable"] == []
    # a pragma for a GATED rule family (leak-path only runs under
    # --lifecycle) must not be called unused by a default run that
    # never executed the rule
    src2 = ("def f(pool, risky):\n"
            "    w = pool.select()  # pdlint: disable=leak-path\n"
            "    risky(w)\n"
            "    pool.release(w)\n")
    found2 = analysis.analyze_source(src2, "m.py")
    assert [f for f in found2 if f.rule == "unused-disable"] == []


def test_lifecycle_rules_are_gated_from_default_runs():
    leaky = ("def f(pool, risky):\n"
             "    w = pool.select()\n"
             "    risky(w)\n"
             "    pool.release(w)\n")
    default = analysis.analyze_source(leaky, "m.py")
    assert [f for f in default if f.rule == "leak-path"] == []
    gated = analysis.analyze_source(leaky, "m.py",
                                    analysis.ast_rules(lifecycle=True))
    assert [f for f in gated if f.rule == "leak-path"]


def test_sarif_output_shape():
    leaky = ("def f(pool, risky):\n"
             "    w = pool.select()\n"
             "    risky(w)\n"
             "    pool.release(w)\n")
    findings = analysis.analyze_source(leaky, "m.py", _rules())
    doc = json.loads(report.render_sarif(findings, rules=analysis.RULES))
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "pdlint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "leak-path" in rule_ids
    res = run["results"][0]
    assert res["ruleId"] == "leak-path"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "m.py"
    assert loc["region"]["startLine"] == 2
    # the fingerprint is the baseline key: stable across line drift
    assert res["partialFingerprints"]["pdlintKey/v1"] \
        == "|".join(findings[0].key())


def _load_script(name):
    path = os.path.join(_REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_prune_baseline_drops_stale_keeps_live(tmp_path, capsys):
    base = tmp_path / "bl.json"
    live = {"file": "paddle_tpu/serving.py", "rule": "silent-exception",
            "symbol": "ContinuousBatchEngine._admit", "message": "m"}
    stale = {"file": "paddle_tpu/serving.py", "rule": "silent-exception",
             "symbol": "ClassThatNeverExisted.method", "message": "m"}
    gone = {"file": "paddle_tpu/no_such_file.py", "rule": "host-sync",
            "symbol": "", "message": "m"}
    bl.save_entries(str(base), [live, stale, gone])
    mod = _load_script("pdlint.py")
    rc = mod.main(["--prune-baseline", "--baseline", str(base)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "kept 1 of 3" in out
    kept = bl.load_entries(str(base))
    assert kept == [live]


# ---------------------------------------------------------------------------
# 4. the tier-1 gate + regressions for the leaks this pass found
# ---------------------------------------------------------------------------

def test_lifecycle_gate_zero_findings(capsys):
    """THE gate: ``scripts/pdlint.py --lifecycle --json`` over the whole
    package exits 0 — with the checked-in baseline EMPTY, so zero
    baselined leak-path entries exist anywhere (the acceptance
    criterion: every real leak was fixed, never grandfathered)."""
    mod = _load_script("pdlint.py")
    rc = mod.main(["--lifecycle", "--json"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0, f"pdlint --lifecycle found leaks:\n{out}"
    assert doc["total"] == 0
    entries = bl.load_entries(os.path.join(_REPO,
                                           ".pdlint_baseline.json"))
    assert [e for e in entries if e["rule"] == "leak-path"] == []


def test_rule_catalog_lists_lifecycle_rules(capsys):
    mod = _load_script("pdlint.py")
    assert mod.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "leak-path" in out
    assert "unused-disable" in out


def test_router_plan_releases_lease_when_planning_raises():
    """Regression (found by leak-path): an exception between
    ``pool.select()`` and _plan's ownership-transferring return left
    the lease counted as phantom pending load forever."""
    from paddle_tpu.serving_cluster.pool import WorkerInfo
    from paddle_tpu.serving_cluster.router import RouterServer

    class Pool:
        def __init__(self):
            self.w = WorkerInfo(0, {"host": "h", "port": 1,
                                    "role": "unified"})
            self.released = []

        def select(self, roles=None, exclude=()):
            self.w.pending += 1
            return self.w

        def has_role(self, role):
            raise RuntimeError("pool backend lost")

        def release(self, w):
            w.pending -= 1
            self.released.append(w.replica_id)

    rts = RouterServer.__new__(RouterServer)
    rts.pool = Pool()
    # kv_channel truthy forces the has_role() probe on the plan path
    rts.pool.w.kv_channel = "chan"
    with pytest.raises(RuntimeError):
        rts._plan(())
    assert rts.pool.released == [0]
    assert rts.pool.w.pending == 0


def test_tracer_span_ends_even_when_pop_raises():
    """Regression (found by leak-path): the span context manager called
    ``_pop`` BEFORE ``end`` — a raising pop lost the span entirely, a
    hole in the trace exactly where the failure was."""
    from paddle_tpu.observability import tracing

    class PopBomb(tracing.Tracer):
        def _pop(self, span):
            super()._pop(span)
            raise RuntimeError("stack corrupted")

    tr = PopBomb(capacity=16)
    tr.enable()
    with pytest.raises(RuntimeError):
        with tr.span("work"):
            pass
    recs = [r for r in tr.spans() if r["name"] == "work"]
    assert len(recs) == 1          # ended BEFORE the pop raised


def test_tracer_span_error_status_on_body_raise():
    from paddle_tpu.observability import tracing

    tr = tracing.Tracer(capacity=16)
    tr.enable()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    rec = [r for r in tr.spans() if r["name"] == "boom"][0]
    assert rec["status"] == "error"
