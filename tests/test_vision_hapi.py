"""vision (transforms/models/ops), metric, hapi Model, text viterbi.

Parity model: test/legacy_test/test_vision_models.py (forward shape
checks), transforms unit tests, hapi model fit/evaluate/predict tests
(test/legacy_test/test_model.py semantics), metric tests vs sklearn-style
references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io.dataset import Dataset


# ---- transforms --------------------------------------------------------------

def test_transforms_pipeline():
    import paddle_tpu.vision.transforms as T

    img = np.random.randint(0, 255, (40, 60, 3), np.uint8)
    tf = T.Compose([
        T.Resize(32), T.CenterCrop(24), T.RandomHorizontalFlip(0.0),
        T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3)])
    out = tf(img)
    assert out.shape == [3, 24, 24]
    assert out.numpy().dtype == np.float32


def test_resize_semantics():
    from paddle_tpu.vision.transforms import functional as F

    img = np.arange(16, dtype=np.uint8).reshape(4, 4)
    up = F.resize(img, (8, 8), "nearest")
    assert up.shape == (8, 8)
    assert up[0, 0] == img[0, 0] and up[-1, -1] == img[-1, -1]
    # short-side int resize keeps aspect
    rect = np.zeros((10, 20), np.uint8)
    out = F.resize(rect, 5)
    assert out.shape == (5, 10)


def test_color_transforms_preserve_dtype():
    from paddle_tpu.vision.transforms import functional as F

    img = np.random.randint(0, 255, (8, 8, 3), np.uint8)
    for fn, arg in [(F.adjust_brightness, 1.2), (F.adjust_contrast, 0.8),
                    (F.adjust_saturation, 1.5), (F.adjust_hue, 0.1)]:
        out = fn(img, arg)
        assert out.dtype == np.uint8 and out.shape == img.shape
    # hue identity: factor 0 returns (almost) the same image
    np.testing.assert_allclose(F.adjust_hue(img, 0.0), img, atol=2)


def test_random_erasing_and_crop():
    import paddle_tpu.vision.transforms as T

    img = np.ones((16, 16, 3), np.uint8) * 255
    erased = T.RandomErasing(prob=1.0)(img)
    assert (erased == 0).any()
    cropped = T.RandomCrop(8)(img)
    assert cropped.shape == (8, 8, 3)


# ---- models ------------------------------------------------------------------

@pytest.mark.parametrize("factory,in_shape,n_cls", [
    ("lenet", (2, 1, 28, 28), 10),
    ("resnet18", (2, 3, 32, 32), 1000),
])
def test_model_forward_shapes(factory, in_shape, n_cls):
    import paddle_tpu.vision.models as M

    if factory == "lenet":
        net = M.LeNet()
    else:
        net = getattr(M, factory)()
    net.eval()
    x = paddle.to_tensor(np.random.randn(*in_shape).astype(np.float32))
    out = net(x)
    assert out.shape == [in_shape[0], n_cls]


def test_resnet50_and_friends_construct():
    import paddle_tpu.vision.models as M

    # two representative archs (resnet50 = the BASELINE.json smoke config);
    # constructing all five is pure init-compile time with no extra coverage
    for f in (M.resnet50, M.mobilenet_v2):
        net = f(num_classes=4)
        assert len(list(net.parameters())) > 0
    with pytest.raises(NotImplementedError):
        M.resnet18(pretrained=True)


def test_lenet_trains():
    import paddle_tpu.vision.models as M
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    net = M.LeNet()
    optim = opt.Adam(1e-3, parameters=net.parameters())
    x = paddle.to_tensor(np.random.randn(8, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 10, (8,)))
    loss_fn = nn.CrossEntropyLoss()
    losses = []
    for _ in range(5):
        loss = loss_fn(net(x), y)
        loss.backward()
        optim.step()
        optim.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


# ---- vision ops --------------------------------------------------------------

def test_nms_and_box_iou():
    from paddle_tpu.vision import ops as vops

    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = vops.nms(paddle.to_tensor(boxes), 0.5,
                    scores=paddle.to_tensor(scores))
    assert keep.numpy().tolist() == [0, 2]
    iou = vops.box_iou(paddle.to_tensor(boxes), paddle.to_tensor(boxes))
    np.testing.assert_allclose(np.diag(iou.numpy()), 1.0, atol=1e-6)


def test_roi_align_shapes_and_values():
    from paddle_tpu.vision import ops as vops

    # constant feature map → every roi bin equals the constant
    x = np.full((1, 2, 8, 8), 3.0, np.float32)
    boxes = np.array([[0, 0, 4, 4], [2, 2, 6, 6]], np.float32)
    out = vops.roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                         paddle.to_tensor(np.array([2], np.int32)), 2)
    assert out.shape == [2, 2, 2, 2]
    np.testing.assert_allclose(out.numpy(), 3.0, rtol=1e-5)


# ---- metric ------------------------------------------------------------------

def test_accuracy_metric():
    from paddle_tpu.metric import Accuracy

    m = Accuracy(topk=(1, 2))
    pred = np.array([[0.1, 0.9, 0], [0.8, 0.1, 0.1], [0.2, 0.3, 0.5]],
                    np.float32)
    label = np.array([[1], [2], [2]])
    m.update(m.compute(pred, label))
    top1, top2 = m.accumulate()
    assert abs(top1 - 2 / 3) < 1e-6
    assert abs(top2 - 2 / 3) < 1e-6 or top2 >= top1


def test_precision_recall_auc():
    from paddle_tpu.metric import Auc, Precision, Recall

    preds = np.array([0.9, 0.8, 0.2, 0.6], np.float32)
    labels = np.array([1, 0, 0, 1])
    p = Precision(); p.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    r = Recall(); r.update(preds, labels)
    assert abs(r.accumulate() - 1.0) < 1e-6
    a = Auc(); a.update(np.stack([1 - preds, preds], 1), labels)
    # one inverted pair (0.8 neg above 0.6 pos) out of 4 → AUC = 0.75
    assert abs(a.accumulate() - 0.75) < 1e-3


# ---- hapi Model --------------------------------------------------------------

class _RandomDataset(Dataset):
    def __init__(self, n=32):
        rng = np.random.default_rng(0)
        self.x = rng.standard_normal((n, 8)).astype(np.float32)
        w = rng.standard_normal((8,)).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def test_hapi_model_fit_evaluate_predict(tmp_path, capsys):
    from paddle_tpu import optimizer as opt
    from paddle_tpu.hapi import Model
    from paddle_tpu.metric import Accuracy

    paddle.seed(5)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net)
    model.prepare(opt.Adam(5e-3, parameters=net.parameters()),
                  nn.CrossEntropyLoss(), Accuracy())
    ds = _RandomDataset()
    hist = model.fit(ds, epochs=3, batch_size=8, verbose=0)
    assert len(hist) == 3
    assert hist[-1]["loss"] < hist[0]["loss"]
    ev = model.evaluate(ds, batch_size=8, verbose=0)
    assert "acc" in ev and ev["acc"] > 0.5
    preds = model.predict(ds, batch_size=8, stack_outputs=True)
    assert preds[0].shape == (32, 2)

    model.save(str(tmp_path / "ck"))
    net2 = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m2 = Model(net2)
    m2.prepare(opt.Adam(5e-3, parameters=net2.parameters()),
               nn.CrossEntropyLoss(), Accuracy())
    m2.load(str(tmp_path / "ck"))
    ev2 = m2.evaluate(ds, batch_size=8, verbose=0)
    np.testing.assert_allclose(ev2["loss"], ev["loss"], rtol=1e-5)


def test_hapi_early_stopping():
    from paddle_tpu import optimizer as opt
    from paddle_tpu.hapi import EarlyStopping, Model
    from paddle_tpu.metric import Accuracy

    paddle.seed(5)
    net = nn.Linear(8, 2)
    model = Model(net)
    model.prepare(opt.Adam(0.0, parameters=net.parameters()),  # lr=0: no progress
                  nn.CrossEntropyLoss(), Accuracy())
    ds = _RandomDataset()
    es = EarlyStopping(monitor="loss", patience=1, save_best_model=False)
    hist = model.fit(ds, eval_data=ds, epochs=10, batch_size=8, verbose=0,
                     callbacks=[es])
    assert len(hist) < 10  # stopped early


def test_model_summary_and_flops(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    info = paddle.summary(net, (1, 8))
    out = capsys.readouterr().out
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    assert "Linear" in out
    fl = paddle.flops(net, (1, 8))
    assert fl == 2 * (8 * 16 + 16 * 2)


# ---- text --------------------------------------------------------------------

def test_viterbi_decode_matches_bruteforce():
    import itertools

    from paddle_tpu.text import ViterbiDecoder

    rng = np.random.default_rng(0)
    b, l, t = 2, 5, 3
    pot = rng.standard_normal((b, l, t)).astype(np.float32)
    trans = rng.standard_normal((t, t)).astype(np.float32)
    lens = np.array([5, 3], np.int64)

    dec = ViterbiDecoder(paddle.to_tensor(trans), include_bos_eos_tag=False)
    scores, paths = dec(paddle.to_tensor(pot), paddle.to_tensor(lens))

    for i in range(b):
        best, best_path = -1e9, None
        for path in itertools.product(range(t), repeat=int(lens[i])):
            s = pot[i, 0, path[0]]
            for j in range(1, len(path)):
                s += trans[path[j - 1], path[j]] + pot[i, j, path[j]]
            if s > best:
                best, best_path = s, path
        np.testing.assert_allclose(float(scores.numpy()[i]), best, rtol=1e-5)
        assert paths.numpy()[i, :int(lens[i])].tolist() == list(best_path)


def test_reduce_lr_on_plateau_and_visualdl(tmp_path):
    """callbacks.py ReduceLROnPlateau (lr drops after a plateau) and
    VisualDL (JSONL scalar records under log_dir)."""
    import json

    from paddle_tpu.callbacks import ReduceLROnPlateau, VisualDL

    class FakeOpt:
        def __init__(self):
            self._lr = 0.1
        def get_lr(self):
            return self._lr
        def set_lr(self, v):
            self._lr = v

    class FakeModel:
        pass

    m = FakeModel()
    m._optimizer = FakeOpt()
    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    cb.set_model(m)
    for epoch, loss in enumerate([1.0, 0.5, 0.5, 0.5]):  # plateau from e1
        cb.on_epoch_end(epoch, {"loss": loss})
    assert abs(m._optimizer.get_lr() - 0.05) < 1e-9  # one halving

    # eval metrics take over once seen (no double counting of patience),
    # and cooldown SUPPRESSES counting
    m2 = FakeModel(); m2._optimizer = FakeOpt()
    cb2 = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                            cooldown=2, verbose=0)
    cb2.set_model(m2)
    for epoch in range(6):
        cb2.on_epoch_end(epoch, {"loss": 123.0})   # train logs: ignored...
        cb2.on_eval_end({"loss": 1.0})             # ...once eval fires
    # flat eval loss, patience 1, cooldown 2: reductions at e1, e4 only
    assert abs(m2._optimizer.get_lr() - 0.1 * 0.25) < 1e-9

    vdl = VisualDL(log_dir=str(tmp_path))
    vdl.on_train_batch_end(7, {"loss": 1.5})       # the MODEL's step number
    vdl.on_eval_end({"acc": [0.75]})
    recs = [json.loads(l) for l in
            open(tmp_path / "vdlrecords.jsonl").read().splitlines()]
    assert recs[0]["tag"] == "train" and recs[0]["loss"] == 1.5
    assert recs[0]["step"] == 7                     # not a private counter
    assert recs[1]["tag"] == "eval" and recs[1]["acc"] == 0.75

    from paddle_tpu.callbacks import WandbCallback
    with pytest.raises(ImportError, match="wandb"):
        WandbCallback()
