"""Weight-only int8 serving: WeightOnlyLinear + quantize_for_serving
(the llm.int8 / weight_only_int8 serving configuration) composed with
generate() and the continuous-batching engine."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn.quant import (WeightOnlyLinear, quantize_for_serving,
                                 weight_dequantize, weight_quantize)
from paddle_tpu.serving import ContinuousBatchEngine


@pytest.fixture()
def float_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


def test_quantize_for_serving_replaces_targets(float_model):
    m, n = quantize_for_serving(float_model)
    # 2 layers x (q,k,v,o,gate,up,down) + lm_head
    assert n == 15
    assert isinstance(m.lm_head, WeightOnlyLinear)
    assert isinstance(m.llama.layers[0].self_attn.q_proj, WeightOnlyLinear)
    sd = m.state_dict()
    assert str(sd["lm_head.quant_weight"].dtype) == "int8"


def test_quantized_logits_close_and_roundtrip(float_model):
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    ref = float_model(ids).numpy()
    m, _ = quantize_for_serving(float_model)
    got = m(ids).numpy()
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.05  # int8 weight rounding only

    # quantize/dequantize round trip bounded by the per-channel step size
    w = paddle.to_tensor(np.random.RandomState(1).randn(32, 16).astype("float32"))
    q, s = weight_quantize(w)
    back = weight_dequantize(q, s, out_dtype="float32")
    step = np.abs(w.numpy()).max(0) / 127.0
    assert (np.abs(back.numpy() - w.numpy()) <= step[None, :] * 0.5 + 1e-6).all()


def test_int4_pack_roundtrip_and_group_scales():
    """Nibble packing is exact over [-7, 7]; group-wise dequant bounded by
    the per-group step; odd in_features pads one zero row."""
    from paddle_tpu.nn.quant import _pack_int4, _unpack_int4
    import jax.numpy as jnp

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randint(-7, 8, (10, 6)), jnp.int8)
    np.testing.assert_array_equal(np.asarray(_unpack_int4(_pack_int4(q))),
                                  np.asarray(q))
    q_odd = jnp.asarray(rng.randint(-7, 8, (9, 6)), jnp.int8)
    back = np.asarray(_unpack_int4(_pack_int4(q_odd)))
    np.testing.assert_array_equal(back[:9], np.asarray(q_odd))
    assert (back[9] == 0).all()

    w = paddle.to_tensor(rng.randn(128, 16).astype("float32"))
    q4, s = weight_quantize(w, algo="weight_only_int4", group_size=64)
    assert q4.shape == [64, 16] and s.shape == [2, 16]
    back = weight_dequantize(q4, s, algo="weight_only_int4",
                             out_dtype="float32", group_size=64,
                             in_features=128).numpy()
    wn = w.numpy()
    step = np.abs(wn.reshape(2, 64, 16)).max(1) / 7.0     # [2, 16]
    err = np.abs(back - wn).reshape(2, 64, 16).max(1)
    assert (err <= step * 0.5 + 1e-6).all()


def test_int4_linear_matches_dequantized_reference():
    from paddle_tpu import nn

    rng = np.random.RandomState(4)
    lin = nn.Linear(48, 24)
    x = paddle.to_tensor(rng.randn(2, 48).astype("float32"))
    wol = WeightOnlyLinear.from_linear(lin, algo="weight_only_int4")
    ref_w = weight_dequantize(wol.quant_weight, wol.weight_scale,
                              algo="weight_only_int4",
                              out_dtype="float32",
                              in_features=48).numpy()
    want = x.numpy() @ ref_w + lin.bias.numpy()
    got = wol(x).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="group_size"):
        weight_quantize(paddle.to_tensor(rng.randn(128, 8).astype("float32")),
                        algo="weight_only_int4", group_size=32)
    with pytest.raises(ValueError, match="divisible"):
        weight_quantize(paddle.to_tensor(rng.randn(100, 8).astype("float32")),
                        algo="weight_only_int4", group_size=64)


@pytest.mark.parametrize("algo", ["weight_only_int8", "llm.int8",
                                  "weight_only_int4"])
def test_quantized_engine_matches_solo(float_model, algo):
    """The engine serving a quantized model is token-identical to the same
    quantized model's solo generate (the serving stack is quantization-
    transparent)."""
    m, _ = quantize_for_serving(float_model, algo=algo)
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, 512, (n,)) for n in (10, 7)]
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
    done = eng.run_until_done()
    for rid, p in zip(rids, prompts):
        solo = m.generate(paddle.to_tensor(p[None]), max_new_tokens=6).numpy()[0]
        np.testing.assert_array_equal(done[rid], solo)


def test_include_set_narrows_pass(float_model):
    m, n = quantize_for_serving(float_model, include=("lm_head",))
    assert n == 1
    from paddle_tpu.nn.layers_common import Linear

    assert isinstance(m.llama.layers[0].self_attn.q_proj, Linear)


def test_mp_linears_left_alone():
    """Sharded (ColumnParallel/RowParallel) projections must NOT be swapped
    — quantizing a local shard with shard-local scales would silently
    change the math under mp."""
    import paddle_tpu.distributed as dist

    dist.set_hybrid_communicate_group(
        dist.HybridCommunicateGroup(mp_degree=2))
    try:
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=1))
        assert isinstance(m.llama.layers[0].self_attn.q_proj,
                          dist.ColumnParallelLinear)
        m, n = quantize_for_serving(m)
        assert n == 0  # every projection is parallel under mp
        assert isinstance(m.llama.layers[0].self_attn.q_proj,
                          dist.ColumnParallelLinear)
    finally:
        dist.set_hybrid_communicate_group(None)


def test_fused_ce_falls_back_for_swapped_head():
    """config.fuse_linear_cross_entropy + a quantized lm head: the fused op
    needs the raw weight matrix, so the loss path must fall back to the
    head's own forward instead of crashing on .weight."""
    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=1, fuse_linear_cross_entropy=True)
    m = LlamaForCausalLM(cfg)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (1, 8)))
    ref_loss, _ = m(ids, labels=ids)
    m, _ = quantize_for_serving(m)
    loss, logits = m(ids, labels=ids)  # would AttributeError before the fallback
    assert logits is not None  # fell back to the logits path
    assert abs(float(loss.numpy()) - float(ref_loss.numpy())) < 0.2


def test_int8_serving_composes_with_sliding_window():
    """Weight-only int8 + windowed banded decode through the engine ==
    the int8 model's solo generate."""
    from paddle_tpu.models.mistral import MistralConfig, MistralForCausalLM
    from paddle_tpu.nn.quant import quantize_for_serving
    from paddle_tpu.serving import ContinuousBatchEngine

    paddle.seed(2)
    m = MistralForCausalLM(MistralConfig.tiny(sliding_window=8,
                                              use_flash_attention=False))
    q8, _ = quantize_for_serving(m)
    ids = np.random.RandomState(1).randint(0, 512, (18,))
    eng = ContinuousBatchEngine(q8, max_batch=2, max_len=64, page_size=8)
    rid = eng.add_request(ids, 5)
    done = eng.run_until_done()
    solo = q8.generate(paddle.to_tensor(ids[None]),
                       max_new_tokens=5).numpy()[0]
    assert done[rid].tolist() == solo.tolist()


@pytest.mark.parametrize("family", ["gemma2", "olmo2", "glm4"])
def test_int4_serving_across_new_families(family):
    """quantize_for_serving targets named projections, so every
    llama-trunk family quantizes; the engine stays token-identical to
    the quantized model's own solo generate."""
    if family == "gemma2":
        from paddle_tpu.models.gemma2 import Gemma2Config as C
        from paddle_tpu.models.gemma2 import Gemma2ForCausalLM as M
    elif family == "olmo2":
        from paddle_tpu.models.olmo2 import Olmo2Config as C
        from paddle_tpu.models.olmo2 import Olmo2ForCausalLM as M
    else:
        from paddle_tpu.models.glm import Glm4Config as C
        from paddle_tpu.models.glm import Glm4ForCausalLM as M

    paddle.seed(10)
    m = M(C.tiny(num_hidden_layers=2))
    m, n = quantize_for_serving(m, algo="weight_only_int4")
    assert n >= 2 * 7  # per-layer projections swapped (head may be tied)
    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 512, (7,))
    solo = m.generate(paddle.to_tensor(prompt[None]),
                      max_new_tokens=6).numpy()[0]
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=32, page_size=8)
    rid = eng.add_request(prompt.tolist(), max_new_tokens=6)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[rid], solo)
