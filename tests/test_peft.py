"""LoRA fine-tuning (paddle_tpu.peft): adapters-only training through the
jit TrainStep, identity at init, merge-for-deployment parity."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.peft import (LoRAConfig, LoRALinear, get_peft_model,
                             lora_state_dict, merge_lora)


def _loss_fn(m, x, y):
    loss, _ = m(x, labels=y)
    return loss


@pytest.fixture()
def lora_model():
    paddle.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    return m


def test_wrap_is_identity_at_init(lora_model):
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    ref = lora_model(ids).numpy()
    m, n = get_peft_model(lora_model, LoRAConfig(r=4))
    assert n == 8  # 2 layers x (q,k,v,o)
    np.testing.assert_allclose(m(ids).numpy(), ref, atol=1e-6)


def test_only_adapters_train(lora_model):
    m, _ = get_peft_model(lora_model, LoRAConfig(r=4))
    trainable = [k for k, p in m.named_parameters() if not p.stop_gradient]
    assert trainable and all("lora_" in k for k in trainable)
    base_before = {k: np.array(v.numpy())
                   for k, v in m.state_dict().items() if "lora_" not in k}
    adapters_before = {k: np.array(v.numpy())
                       for k, v in lora_state_dict(m).items()}
    step = paddle.jit.train_step(
        m, _loss_fn, opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(5)]
    assert losses[-1] < losses[0]  # adapters alone reduce the loss
    after = m.state_dict()
    for k, v in base_before.items():
        np.testing.assert_array_equal(np.array(after[k].numpy()), v,
                                      err_msg=f"frozen {k} changed")
    changed = sum(not np.array_equal(np.array(after[k].numpy()), v)
                  for k, v in adapters_before.items())
    assert changed > 0


def test_merge_matches_adapter_forward(lora_model):
    m, _ = get_peft_model(lora_model, LoRAConfig(r=4))
    step = paddle.jit.train_step(
        m, _loss_fn, opt.AdamW(5e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(2).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(3).randint(0, 512, (2, 16)))
    for _ in range(3):
        step(x, y)
    ids = paddle.to_tensor(np.random.RandomState(4).randint(0, 512, (1, 10)))
    with_adapters = m(ids).numpy()
    m, n = merge_lora(m)
    assert n == 8
    assert not any("lora_" in k for k in m.state_dict())
    np.testing.assert_allclose(m(ids).numpy(), with_adapters,
                               atol=2e-5, rtol=2e-5)


def test_modules_to_save_and_generate(lora_model):
    m, _ = get_peft_model(
        lora_model, LoRAConfig(r=2, target_modules=("q_proj", "v_proj"),
                               modules_to_save=("norm",)))
    trainable = {k for k, p in m.named_parameters() if not p.stop_gradient}
    assert any("layernorm" in k or "norm" in k for k in trainable)
    out = m.generate(
        paddle.to_tensor(np.random.RandomState(5).randint(0, 512, (1, 8))),
        max_new_tokens=4)
    assert out.shape == [1, 4]


def test_no_target_match_raises(lora_model):
    with pytest.raises(ValueError, match="target_modules"):
        get_peft_model(lora_model, LoRAConfig(target_modules=("nope",)))


def test_merge_restores_user_freeze_state(lora_model):
    """A parameter the USER froze before get_peft_model must stay frozen
    after merge_lora (blanket unfreezing would silently resume training
    a deliberately frozen embedding)."""
    lora_model.llama.embed_tokens.weight.stop_gradient = True
    m, _ = get_peft_model(lora_model, LoRAConfig(r=2))
    m, _ = merge_lora(m)
    assert m.llama.embed_tokens.weight.stop_gradient is True
    assert m.lm_head.weight.stop_gradient is False  # others trainable again


def test_stacked_adapters_merge_keeps_model_trainable(lora_model):
    """Two get_peft_model calls (different targets) then merge: the model
    must come back trainable (the first pre-LoRA snapshot wins, not the
    all-frozen state between the calls)."""
    m, _ = get_peft_model(lora_model, LoRAConfig(r=2, target_modules=("q_proj",)))
    m, _ = get_peft_model(m, LoRAConfig(r=2, target_modules=("gate_proj",)))
    m, n = merge_lora(m)
    assert n == 4  # 2 layers x (q_proj + gate_proj)
    assert all(not p.stop_gradient for _, p in m.named_parameters())


def test_lora_on_moe_family():
    """LoRA wraps the MoE family's attention projections (routed experts
    stay frozen), trains adapters-only, merges back."""
    from paddle_tpu.models.qwen2_moe import Qwen2MoeConfig, Qwen2MoeForCausalLM

    paddle.seed(0)
    m = Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny())
    peft, n = get_peft_model(m, LoRAConfig(r=4,
                                           target_modules=["q_proj",
                                                           "v_proj"]))
    assert n == 4  # q+v per layer x 2 layers
    trainable = [p for p in peft.parameters() if not p.stop_gradient]
    assert len(trainable) == 8  # lora_A + lora_B per wrapped Linear
    # expert weights frozen
    assert m.llama.layers[0].mlp.experts.w1.stop_gradient

    step = paddle.jit.train_step(peft, _loss_fn,
                                 opt.AdamW(1e-2, parameters=trainable))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    losses = [float(step(x, x).numpy()) for _ in range(3)]
    assert losses[-1] < losses[0]
    def logits(mm):
        out = mm(x)
        return (out[0] if isinstance(out, tuple) else out).numpy()

    before = logits(peft)              # adapter-applied logits
    merge_lora(peft)
    # merge folds the adapters into the base weights: same function
    np.testing.assert_allclose(logits(m), before, rtol=1e-4, atol=1e-5)
    assert m.generate(x, max_new_tokens=4).shape == [2, 4]
