"""Property tests for matrix decompositions.

OpTest-style value comparison fails for decompositions whose outputs are
only unique up to sign/phase/ordering (qr, svd, eig, eigh, lu); these are
instead validated by reconstruction and structural properties, the way the
reference's test/legacy_test/test_qr_op.py etc. verify Q@R == A.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import linalg as L

R = np.random.RandomState(7)


def _mat(shape=(5, 4)):
    return paddle.to_tensor(R.uniform(-1, 1, shape).astype("float32"))


def _spd(n=4):
    a = R.uniform(-1, 1, (n, n))
    return paddle.to_tensor((a @ a.T + n * np.eye(n)).astype("float32"))


def test_qr_reconstruction():
    x = _mat((5, 4))
    q, r = L.qr(x)
    np.testing.assert_allclose(q.numpy() @ r.numpy(), x.numpy(), atol=1e-5)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4), atol=1e-5)
    assert np.allclose(np.tril(r.numpy(), -1), 0.0)
    r_only = L.qr(x, mode="r")
    np.testing.assert_allclose(np.abs(r_only.numpy()), np.abs(r.numpy()),
                               atol=1e-5)


def test_svd_reconstruction():
    x = _mat((5, 4))
    u, s, vh = L.svd(x)
    rec = (u.numpy() * s.numpy()[None, :]) @ vh.numpy()
    np.testing.assert_allclose(rec, x.numpy(), atol=1e-5)
    np.testing.assert_allclose(
        s.numpy(), np.linalg.svd(x.numpy(), compute_uv=False), atol=1e-5)
    np.testing.assert_allclose(L.svdvals(x).numpy(), s.numpy(), atol=1e-6)


def test_eigh_properties():
    x = _spd()
    w, v = L.eigh(x)
    np.testing.assert_allclose(
        x.numpy() @ v.numpy(), v.numpy() * w.numpy()[None, :], atol=1e-4)
    np.testing.assert_allclose(w.numpy(), np.linalg.eigvalsh(x.numpy()),
                               atol=1e-4)
    np.testing.assert_allclose(L.eigvalsh(x).numpy(), w.numpy(), atol=1e-5)


def test_eig_general():
    x = _mat((4, 4))
    w, v = L.eig(x)
    xw = x.numpy().astype("complex64") @ v.numpy()
    np.testing.assert_allclose(xw, v.numpy() * w.numpy()[None, :], atol=1e-4)
    got = np.sort_complex(L.eigvals(x).numpy())
    ref = np.sort_complex(np.linalg.eigvals(x.numpy()))
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_lu_and_unpack():
    x = _mat((4, 4))
    lu, piv = L.lu(x)
    assert piv.numpy().min() >= 1  # paddle pivots are 1-based
    p, l, u = L.lu_unpack(lu, piv)
    rec = p.numpy() @ l.numpy() @ u.numpy()
    np.testing.assert_allclose(rec, x.numpy(), atol=1e-5)
    assert np.allclose(np.triu(l.numpy(), 1), 0.0)
    assert np.allclose(np.diag(l.numpy()), 1.0)
    assert np.allclose(np.tril(u.numpy(), -1), 0.0)
    lu3 = L.lu(x, get_infos=True)
    assert len(lu3) == 3


def test_lstsq():
    a = _mat((6, 3))
    b = _mat((6, 2))
    sol = L.lstsq(a, b)[0]
    ref = np.linalg.lstsq(a.numpy(), b.numpy(), rcond=None)[0]
    np.testing.assert_allclose(sol.numpy(), ref, atol=1e-4)


def test_norms():
    x = _mat((3, 4))
    np.testing.assert_allclose(L.matrix_norm(x).numpy(),
                               np.linalg.norm(x.numpy(), "fro"), rtol=1e-5)
    np.testing.assert_allclose(L.vector_norm(x, p=2).numpy(),
                               np.linalg.norm(x.numpy().ravel()), rtol=1e-5)
    np.testing.assert_allclose(L.norm(x).numpy(),
                               np.linalg.norm(x.numpy()), rtol=1e-5)
    np.testing.assert_allclose(
        L.norm(x, p=np.inf, axis=1).numpy(),
        np.linalg.norm(x.numpy(), np.inf, axis=1), rtol=1e-5)


def test_slogdet():
    x = _spd()
    sign, logdet = L.slogdet(x)
    rs, rl = np.linalg.slogdet(x.numpy())
    np.testing.assert_allclose(sign.numpy(), rs, atol=1e-5)
    np.testing.assert_allclose(logdet.numpy(), rl, rtol=1e-4)
