"""Property tests for matrix decompositions.

OpTest-style value comparison fails for decompositions whose outputs are
only unique up to sign/phase/ordering (qr, svd, eig, eigh, lu); these are
instead validated by reconstruction and structural properties, the way the
reference's test/legacy_test/test_qr_op.py etc. verify Q@R == A.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import linalg as L

R = np.random.RandomState(7)


def _mat(shape=(5, 4)):
    return paddle.to_tensor(R.uniform(-1, 1, shape).astype("float32"))


def _spd(n=4):
    a = R.uniform(-1, 1, (n, n))
    return paddle.to_tensor((a @ a.T + n * np.eye(n)).astype("float32"))


def test_qr_reconstruction():
    x = _mat((5, 4))
    q, r = L.qr(x)
    np.testing.assert_allclose(q.numpy() @ r.numpy(), x.numpy(), atol=1e-5)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4), atol=1e-5)
    assert np.allclose(np.tril(r.numpy(), -1), 0.0)
    r_only = L.qr(x, mode="r")
    np.testing.assert_allclose(np.abs(r_only.numpy()), np.abs(r.numpy()),
                               atol=1e-5)


def test_svd_reconstruction():
    x = _mat((5, 4))
    u, s, vh = L.svd(x)
    rec = (u.numpy() * s.numpy()[None, :]) @ vh.numpy()
    np.testing.assert_allclose(rec, x.numpy(), atol=1e-5)
    np.testing.assert_allclose(
        s.numpy(), np.linalg.svd(x.numpy(), compute_uv=False), atol=1e-5)
    np.testing.assert_allclose(L.svdvals(x).numpy(), s.numpy(), atol=1e-6)


def test_eigh_properties():
    x = _spd()
    w, v = L.eigh(x)
    np.testing.assert_allclose(
        x.numpy() @ v.numpy(), v.numpy() * w.numpy()[None, :], atol=1e-4)
    np.testing.assert_allclose(w.numpy(), np.linalg.eigvalsh(x.numpy()),
                               atol=1e-4)
    np.testing.assert_allclose(L.eigvalsh(x).numpy(), w.numpy(), atol=1e-5)


def test_eig_general():
    x = _mat((4, 4))
    w, v = L.eig(x)
    xw = x.numpy().astype("complex64") @ v.numpy()
    np.testing.assert_allclose(xw, v.numpy() * w.numpy()[None, :], atol=1e-4)
    got = np.sort_complex(L.eigvals(x).numpy())
    ref = np.sort_complex(np.linalg.eigvals(x.numpy()))
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_lu_and_unpack():
    x = _mat((4, 4))
    lu, piv = L.lu(x)
    assert piv.numpy().min() >= 1  # paddle pivots are 1-based
    p, l, u = L.lu_unpack(lu, piv)
    rec = p.numpy() @ l.numpy() @ u.numpy()
    np.testing.assert_allclose(rec, x.numpy(), atol=1e-5)
    assert np.allclose(np.triu(l.numpy(), 1), 0.0)
    assert np.allclose(np.diag(l.numpy()), 1.0)
    assert np.allclose(np.tril(u.numpy(), -1), 0.0)
    lu3 = L.lu(x, get_infos=True)
    assert len(lu3) == 3


def test_lstsq():
    a = _mat((6, 3))
    b = _mat((6, 2))
    sol = L.lstsq(a, b)[0]
    ref = np.linalg.lstsq(a.numpy(), b.numpy(), rcond=None)[0]
    np.testing.assert_allclose(sol.numpy(), ref, atol=1e-4)


def test_norms():
    x = _mat((3, 4))
    np.testing.assert_allclose(L.matrix_norm(x).numpy(),
                               np.linalg.norm(x.numpy(), "fro"), rtol=1e-5)
    np.testing.assert_allclose(L.vector_norm(x, p=2).numpy(),
                               np.linalg.norm(x.numpy().ravel()), rtol=1e-5)
    np.testing.assert_allclose(L.norm(x).numpy(),
                               np.linalg.norm(x.numpy()), rtol=1e-5)
    np.testing.assert_allclose(
        L.norm(x, p=np.inf, axis=1).numpy(),
        np.linalg.norm(x.numpy(), np.inf, axis=1), rtol=1e-5)


def test_slogdet():
    x = _spd()
    sign, logdet = L.slogdet(x)
    rs, rl = np.linalg.slogdet(x.numpy())
    np.testing.assert_allclose(sign.numpy(), rs, atol=1e-5)
    np.testing.assert_allclose(logdet.numpy(), rl, rtol=1e-4)


def test_fp8_gemm_fused():
    """fp8_fp8_half_gemm_fused (tensor/linalg.py:357): values carry fp8
    quantization, scale/bias/act fuse, output lands in half/bf16."""
    import jax.numpy as jnp

    import paddle_tpu.linalg as L

    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(8, 16).astype("float32"))
    y = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
    b = paddle.to_tensor(rng.randn(4).astype("float32"))
    out = L.fp8_fp8_half_gemm_fused(x, y, bias=b, scale=0.5,
                                    output_dtype="bfloat16", act="relu")
    assert str(out.dtype) == "bfloat16" and out.shape == [8, 4]
    # reference computed through the same fp8 quantization
    xq = np.asarray(x.numpy(), np.float32).astype(jnp.float8_e4m3fn).astype(np.float32)
    yq = np.asarray(y.numpy(), np.float32).astype(jnp.float8_e4m3fn).astype(np.float32)
    ref = np.maximum(xq @ yq * 0.5 + b.numpy(), 0.0)
    np.testing.assert_allclose(out.numpy().astype(np.float32), ref,
                               rtol=0.1, atol=0.1)  # fp8+bf16 tolerance
    # transpose flags
    out2 = L.fp8_fp8_half_gemm_fused(
        paddle.to_tensor(x.numpy().T), y, transpose_x=True)
    np.testing.assert_allclose(
        out2.numpy().astype(np.float32),
        (xq @ yq).astype(np.float16).astype(np.float32), rtol=0.1, atol=0.2)
    with pytest.raises(ValueError, match="output_dtype"):
        L.fp8_fp8_half_gemm_fused(x, y, output_dtype="float32")
