"""SLO-aware scheduler (serving.py): chunked-prefill interleaving,
priority/deadline admission with aging, KV preemption to host, and the
bounded admission queue's 429 surface.

The load-bearing guarantees pinned here:
- chunked prefill is TOKEN-IDENTICAL to the monolithic path (paged,
  latent/MLA, and prefix-cache-hit admissions);
- a preempt -> restore round trip is token-identical to an
  uninterrupted run;
- while a long prefill is in flight, a live decode's worst inter-token
  stall is bounded by ~one chunk-step (a decode dispatch runs between
  every pair of chunks) and is strictly smaller than the monolithic
  prefill stall;
- every decision is a sched.* flight-recorder event + metric.
"""
import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.serving import (ContinuousBatchEngine, PRIORITY_DEFAULT,
                                QueueFull)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


def _solo(model, prompt, new):
    return model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                          max_new_tokens=new).numpy()[0]


@pytest.fixture()
def recorder():
    rec = frec.get_recorder()
    was = rec.enabled
    rec.enable()
    yield rec
    if not was:
        rec.disable()


# ---- chunked prefill: token identity ----------------------------------------

def test_chunked_prefill_token_identity_paged(tiny_model):
    """A long prompt admitted in 16-token chunks decodes token-identical
    to the monolithic bucketed prefill — with a live short decode
    interleaved between the chunks."""
    m = tiny_model
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    short_p = rng.randint(0, m.config.vocab_size, (5,))
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16)
    r_short = eng.add_request(short_p, max_new_tokens=12)
    eng.step()
    eng.step()
    r_long = eng.add_request(long_p, max_new_tokens=6)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[r_short], _solo(m, short_p, 12))
    np.testing.assert_array_equal(done[r_long], _solo(m, long_p, 6))


def test_chunked_prefill_token_identity_latent():
    """Latent (MLA) mode: chunk continuation goes through the latent
    suffix-prefill row copies — same token identity bar."""
    from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                            DeepseekV2ForCausalLM)

    paddle.seed(3)
    m = DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(num_hidden_layers=2))
    rng = np.random.RandomState(9)
    long_p = rng.randint(0, m.config.vocab_size, (37,))
    short_p = rng.randint(0, m.config.vocab_size, (5,))
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16)
    assert eng._latent_mode
    r_short = eng.add_request(short_p, max_new_tokens=10)
    eng.step()
    eng.step()
    r_long = eng.add_request(long_p, max_new_tokens=6)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[r_short], _solo(m, short_p, 10))
    np.testing.assert_array_equal(done[r_long], _solo(m, long_p, 6))


def test_chunked_prefill_with_prefix_cache_hit(tiny_model):
    """Prefix-cache hit + chunking compose: the first chunk copies the
    shared prefix pages from the active source slot and runs one chunk
    of the suffix; later chunks self-continue. Token-identical, and the
    reuse counter moves."""
    m = tiny_model
    rng = np.random.RandomState(11)
    base = rng.randint(0, m.config.vocab_size, (24,))
    p_a = np.concatenate([base, rng.randint(0, m.config.vocab_size, (9,))])
    p_b = np.concatenate([base, rng.randint(0, m.config.vocab_size, (17,))])
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16,
                                enable_prefix_cache=True)
    r_a = eng.add_request(p_a, max_new_tokens=8)
    for _ in range(4):
        eng.step()
    r_b = eng.add_request(p_b, max_new_tokens=8)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[r_a], _solo(m, p_a, 8))
    np.testing.assert_array_equal(done[r_b], _solo(m, p_b, 8))
    assert eng.prefix_pages_reused > 0


def test_short_prompts_skip_chunking(tiny_model, recorder):
    """A prompt no longer than one chunk admits monolithically — no
    sched.chunk events, no reserved-slot detour."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16)
    since = recorder.stats()["recorded"]
    rid = eng.add_request(np.arange(1, 9), max_new_tokens=4)
    done = eng.run_until_done()
    assert rid in done
    kinds = [e["kind"] for e in recorder.events(since=since)]
    assert "sched.chunk" not in kinds


# ---- the bounded-stall guarantee --------------------------------------------

def test_mixed_load_bounded_stalls(tiny_model, recorder):
    """THE acceptance bar: with chunking on, a decode dispatch runs
    between every pair of prefill chunks (structural bound: no decode
    step waits longer than one chunk-step), and the live request's
    worst wall-clock inter-token gap during the long prefill is
    strictly smaller than under the monolithic prefill."""
    m = tiny_model
    rng = np.random.RandomState(7)
    long_p = rng.randint(0, m.config.vocab_size, (48,))
    short_p = rng.randint(0, m.config.vocab_size, (5,))

    def run(chunk):
        eng = ContinuousBatchEngine(m, max_batch=2, max_len=64,
                                    page_size=8,
                                    prefill_chunk_tokens=chunk)
        times = []
        r_short = eng.add_request(
            short_p, max_new_tokens=24,
            on_token=lambda rid, t, done: times.append(
                time.perf_counter()))
        while len(times) < 2:      # live decode under way
            eng.step()
        n_before = len(times)
        eng.add_request(long_p, max_new_tokens=4)
        eng.run_until_done()
        gaps = np.diff(np.asarray(times[n_before - 1:]))
        return float(gaps.max())

    # warm both variants so no measured gap pays a compile
    run(16), run(None)
    since = recorder.stats()["recorded"]
    chunked_max = run(16)
    evs = recorder.events(since=since)
    # structural interleave: between consecutive chunks of one prefill
    # a decode dispatch fired for the live slot
    seq = [e["kind"] for e in evs
           if e["kind"] in ("sched.chunk", "engine.step")]
    chunk_idx = [i for i, k in enumerate(seq) if k == "sched.chunk"]
    assert len(chunk_idx) >= 2          # 48 tokens / 16 = 3 chunks
    for a, b in zip(chunk_idx, chunk_idx[1:]):
        assert "engine.step" in seq[a + 1:b], (
            f"no decode step between chunks {a} and {b}: {seq}")
    mono_max = run(None)
    assert chunked_max < mono_max, (
        f"chunked worst gap {chunked_max * 1e3:.2f}ms not better than "
        f"monolithic {mono_max * 1e3:.2f}ms")


# ---- priority / deadline / aging --------------------------------------------

def _admit_order(recorder, since, rids):
    order = [e["rid"] for e in recorder.events(since=since,
                                               kind="engine.admit")]
    return [r for r in order if r in rids]


def test_priority_admission_order(tiny_model, recorder):
    """With the slot pool full, queued requests admit by priority class
    (lower first), not FIFO."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                aging_s=0.0)
    busy = eng.add_request(np.arange(1, 6), max_new_tokens=6)
    since = recorder.stats()["recorded"]
    r_lo = eng.add_request(np.arange(1, 6), max_new_tokens=2, priority=5)
    r_mid = eng.add_request(np.arange(1, 6), max_new_tokens=2)
    r_hi = eng.add_request(np.arange(1, 6), max_new_tokens=2, priority=0)
    assert PRIORITY_DEFAULT == 1
    done = eng.run_until_done()
    assert set(done) >= {busy, r_lo, r_mid, r_hi}
    assert _admit_order(recorder, since, {r_lo, r_mid, r_hi}) == [
        r_hi, r_mid, r_lo]


def test_deadline_tiebreak_within_class(tiny_model, recorder):
    """Same class: the earlier SLO deadline admits first (EDF), ahead of
    an earlier-submitted request with a laxer deadline."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                aging_s=0.0)
    busy = eng.add_request(np.arange(1, 6), max_new_tokens=6)
    since = recorder.stats()["recorded"]
    r_lax = eng.add_request(np.arange(1, 6), max_new_tokens=2,
                            slo_ms=60000.0)
    r_tight = eng.add_request(np.arange(1, 6), max_new_tokens=2,
                              slo_ms=50.0)
    r_none = eng.add_request(np.arange(1, 6), max_new_tokens=2)
    done = eng.run_until_done()
    assert set(done) >= {busy, r_lax, r_tight, r_none}
    assert _admit_order(recorder, since, {r_lax, r_tight, r_none}) == [
        r_tight, r_lax, r_none]


def test_aging_bounds_starvation(tiny_model, recorder):
    """A low-priority request that has waited longer than aging_s beats
    fresh higher-priority arrivals — the starvation bound."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                aging_s=0.001)
    busy = eng.add_request(np.arange(1, 6), max_new_tokens=8)
    since = recorder.stats()["recorded"]
    r_old_lo = eng.add_request(np.arange(1, 6), max_new_tokens=2,
                               priority=5)
    time.sleep(0.05)   # >> aging_s: ~50 classes of credit
    r_fresh_hi = eng.add_request(np.arange(1, 6), max_new_tokens=2,
                                 priority=0)
    done = eng.run_until_done()
    assert set(done) >= {busy, r_old_lo, r_fresh_hi}
    assert _admit_order(recorder, since, {r_old_lo, r_fresh_hi}) == [
        r_old_lo, r_fresh_hi]


# ---- preemption -------------------------------------------------------------

def test_preempt_restore_token_identity(tiny_model, recorder):
    """A high-priority arrival preempts the low-priority slot (KV to
    host), runs to completion, then the victim restores and finishes —
    BOTH outputs token-identical to uninterrupted runs, with the
    sched.preempt/sched.restore audit trail and counters."""
    m = tiny_model
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    short_p = rng.randint(0, m.config.vocab_size, (5,))
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                enable_preemption=True)
    since = recorder.stats()["recorded"]
    victim = eng.add_request(short_p, max_new_tokens=12, priority=2)
    for _ in range(3):
        eng.step()                      # victim has generated tokens
    hi = eng.add_request(long_p, max_new_tokens=6, priority=0)
    done = eng.run_until_done()
    np.testing.assert_array_equal(done[hi], _solo(m, long_p, 6))
    np.testing.assert_array_equal(done[victim], _solo(m, short_p, 12))
    evs = recorder.events(since=since)
    pre = [e for e in evs if e["kind"] == "sched.preempt"]
    res = [e for e in evs if e["kind"] == "sched.restore"]
    assert len(pre) == 1 and len(res) == 1
    assert pre[0]["rid"] == victim and res[0]["rid"] == victim
    assert pre[0]["generated"] == 3 and pre[0]["bytes"] > 0
    assert pre[0]["kv_len"] == res[0]["kv_len"] == short_p.size + 3
    assert eng.stats()["requests_preempted"] == 1


def test_equal_priority_never_preempts(tiny_model, recorder):
    """Same-class arrivals wait; only a STRICTLY more important request
    evicts (raw classes — aging credit never triggers preemption)."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                enable_preemption=True, aging_s=0.001)
    first = eng.add_request(np.arange(1, 6), max_new_tokens=6)
    eng.step()
    time.sleep(0.05)   # aging credit accrues; must NOT enable preemption
    second = eng.add_request(np.arange(2, 7), max_new_tokens=2)
    done = eng.run_until_done()
    assert set(done) == {first, second}
    assert eng.stats()["requests_preempted"] == 0


def test_preemption_rejected_in_latent_mode():
    from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                            DeepseekV2ForCausalLM)

    paddle.seed(3)
    m = DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(num_hidden_layers=2))
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                              enable_preemption=True)


def test_preempted_request_streams_continuously(tiny_model):
    """on_token streaming across a preempt -> restore: no token is
    replayed and no token is lost."""
    m = tiny_model
    rng = np.random.RandomState(4)
    short_p = rng.randint(0, m.config.vocab_size, (5,))
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    streamed = []
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                enable_preemption=True)
    victim = eng.add_request(
        short_p, max_new_tokens=12, priority=2,
        on_token=lambda rid, t, done: streamed.append(int(t)))
    for _ in range(3):
        eng.step()
    eng.add_request(long_p, max_new_tokens=6, priority=0)
    done = eng.run_until_done()
    assert streamed == list(done[victim])


# ---- bounded admission queue ------------------------------------------------

def test_bounded_queue_rejects_typed(tiny_model):
    m = tiny_model
    from paddle_tpu.observability import catalog as cat

    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                max_queue=1)
    n0 = cat.SERVING_REQUESTS.value(engine="decoder", event="rejected")
    eng.add_request(np.arange(1, 6), max_new_tokens=6)   # takes the slot
    eng.add_request(np.arange(1, 6), max_new_tokens=2)   # queues (1/1)
    with pytest.raises(QueueFull) as ei:
        eng.add_request(np.arange(1, 6), max_new_tokens=2)
    assert ei.value.retry_after_s > 0
    assert eng.stats()["requests_rejected"] == 1
    assert cat.SERVING_REQUESTS.value(engine="decoder",
                                      event="rejected") == n0 + 1
    # drain: the bound never wedges the engine
    done = eng.run_until_done()
    assert len(done) == 2


def test_bound_ignores_free_slots(tiny_model):
    """max_queue=0 still admits when a slot is free — the bound is on
    WAITING, not on requests."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                max_queue=0)
    rid = eng.add_request(np.arange(1, 6), max_new_tokens=2)
    assert rid in eng.run_until_done()


def test_http_429_with_retry_after(tiny_model):
    """The HTTP surface: a full bounded queue answers 429 + Retry-After
    on both the batch and the streaming path (real status line — SSE
    headers are deferred to the first token)."""
    from paddle_tpu.serving_http import CompletionServer

    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                max_queue=0)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        holder = http.client.HTTPConnection(host, port, timeout=120)
        holder.request(
            "POST", "/v1/completions",
            json.dumps({"prompt_token_ids": [1, 2, 3, 4],
                        "max_tokens": 55, "stream": True}),
            {"Content-Type": "application/json"})
        resp = holder.getresponse()
        assert resp.status == 200
        resp.readline()            # first token: slot definitely held

        def post(body):
            c = http.client.HTTPConnection(host, port, timeout=120)
            c.request("POST", "/v1/completions", json.dumps(body),
                      {"Content-Type": "application/json"})
            r = c.getresponse()
            data = r.read()
            ra = r.getheader("Retry-After")
            c.close()
            return r.status, data, ra

        st, data, ra = post({"prompt_token_ids": [5, 6],
                             "max_tokens": 2})
        assert st == 429 and ra == "1" and b"queue is full" in data
        st, _, ra = post({"prompt_token_ids": [5, 6], "max_tokens": 2,
                          "stream": True})
        assert st == 429 and ra == "1"
        rest = resp.read()
        assert b"[DONE]" in rest   # the holder stream finished clean
        holder.close()


# ---- cancel / bookkeeping ---------------------------------------------------

def test_cancel_mid_chunk_frees_reserved_slot(tiny_model, recorder):
    m = tiny_model
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                prefill_chunk_tokens=16)
    since = recorder.stats()["recorded"]
    rid = eng.add_request(long_p, max_new_tokens=6)
    eng.step()                       # first chunk in, still prefilling
    assert eng.stats()["requests_prefilling"] == 1
    assert eng.cancel(rid) is True
    assert eng.stats()["requests_prefilling"] == 0
    assert eng.finish_reason(rid) == "cancelled"
    evs = recorder.events(since=since)
    cancels = [e for e in evs if e["kind"] == "engine.cancel"]
    assert cancels and cancels[-1]["where"] == "prefilling"
    # the freed slot serves the next request
    nxt = eng.add_request(np.arange(1, 6), max_new_tokens=2)
    assert nxt in eng.run_until_done()


def test_reason_retention_is_deque(tiny_model, monkeypatch):
    """The finish-reason window trims O(1) from the front (deque) and
    still evicts oldest-first."""
    import paddle_tpu.serving as serving

    m = tiny_model
    monkeypatch.setattr(serving, "_REASON_KEEP", 4)
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    rids = [eng.add_request(np.arange(1, 6), max_new_tokens=1)
            for _ in range(6)]
    eng.run_until_done()
    assert eng.finish_reason(rids[0]) is None     # evicted
    assert eng.finish_reason(rids[-1]) == "length"
    from collections import deque

    assert isinstance(eng._reason_order, deque)


def test_debug_state_carries_scheduler_fields(tiny_model):
    m = tiny_model
    rng = np.random.RandomState(4)
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                prefill_chunk_tokens=16)
    rid = eng.add_request(rng.randint(0, m.config.vocab_size, (41,)),
                          max_new_tokens=4, priority=3)
    eng.step()
    st = eng.debug_state()
    assert st["prefilling"] and list(st["prefilling"].values())[0][
        "rid"] == rid
    eng.run_until_done()
    st = eng.debug_state()
    assert st["prefilling"] == {}
    assert eng.stats()["requests_preempted"] == 0


def test_read_incident_prints_scheduler_decisions(tiny_model, tmp_path,
                                                  recorder, capsys):
    """scripts/read_incident.py surfaces the sched.* trail as its own
    section."""
    import importlib.util

    m = tiny_model
    rng = np.random.RandomState(4)
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                prefill_chunk_tokens=16,
                                enable_preemption=True)
    rep = frec.IncidentReporter(str(tmp_path))
    rep.register_engine("decoder", eng)
    victim = eng.add_request(np.arange(1, 6), max_new_tokens=8,
                             priority=2)
    for _ in range(3):
        eng.step()
    eng.add_request(rng.randint(0, m.config.vocab_size, (41,)),
                    max_new_tokens=4, priority=0)
    eng.run_until_done()
    path = rep.activate().dump("manual", context="sched-test")
    spec = importlib.util.spec_from_file_location(
        "_read_incident_sched",
        os.path.join(_REPO, "scripts", "read_incident.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([path]) == 0
    out = capsys.readouterr().out
    assert "SCHEDULER DECISIONS" in out
    assert "sched.chunk" in out and "sched.preempt" in out
    assert "sched.restore" in out
    assert f"rid={victim}" in out


def test_memoized_step_lru_keeps_hot_entries(tiny_model):
    """_memoized_step with maxsize is LRU: a hit refreshes the key, so
    cycling through a working set the size of the cache never evicts a
    hot program (the chunked-prefill suffix-program pattern)."""
    from paddle_tpu.generation import _memoized_step

    class Dummy:
        def functional_state(self):
            return {}

    model = Dummy()
    built = []

    def factory_for(key):
        def build():
            built.append(key)
            fn = lambda: key
            fn._state = None
            return fn
        return build

    for k in ("a", "b", "c"):
        _memoized_step(model, "_t", k, factory_for(k), maxsize=3)
    # touch "a" (hit -> moves to back), then insert "d": "b" (the LRU)
    # is evicted, "a" survives
    _memoized_step(model, "_t", "a", factory_for("a"), maxsize=3)
    _memoized_step(model, "_t", "d", factory_for("d"), maxsize=3)
    _memoized_step(model, "_t", "a", factory_for("a"), maxsize=3)
    assert built.count("a") == 1          # never rebuilt
    _memoized_step(model, "_t", "b", factory_for("b"), maxsize=3)
    assert built.count("b") == 2          # "b" was the eviction victim


# ---- deadline enforcement & load shedding (overload resilience) -------------

def test_expired_deadline_sheds_before_admission(tiny_model, recorder):
    """The hard invariant behind the saturation gate: a queued request
    whose deadline passed is SHED at the admission gate — typed event,
    deadline-miss counters, on_shed notification — and is never
    admitted (no engine.admit, no tokens, no prefill burned)."""
    from paddle_tpu.observability import catalog as cat

    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8)
    hold = eng.add_request(np.arange(1, 6), max_new_tokens=24)
    eng.step()                                    # slot taken
    sheds = []
    n0 = cat.SERVING_DEADLINE_MISSES.value(engine="decoder")
    since = recorder.stats()["recorded"]
    rid = eng.add_request(np.arange(1, 8), max_new_tokens=4, priority=2,
                          slo_ms=30.0,
                          on_shed=lambda r, info: sheds.append((r, info)))
    time.sleep(0.06)                              # budget expires queued
    done = eng.run_until_done()
    assert hold in done and rid not in done
    assert eng.finish_reason(rid) == "shed"
    assert sheds and sheds[0][0] == rid
    assert sheds[0][1]["where"] == "expired"
    assert sheds[0][1]["miss_ms"] > 0
    st = eng.stats()
    assert st["requests_shed"] == 1 and st["deadline_misses"] == 1
    assert cat.SERVING_DEADLINE_MISSES.value(engine="decoder") == n0 + 1
    evs = recorder.events(since=since)
    shed_evs = [e for e in evs if e["kind"] == "sched.shed"]
    assert shed_evs and shed_evs[0]["rid"] == rid
    assert shed_evs[0]["where"] == "expired"
    # never admitted: the rid appears in no engine.admit event
    assert rid not in {e["rid"] for e in evs
                       if e["kind"] == "engine.admit"}


def test_unmeetable_budget_sheds(tiny_model, recorder):
    """A request whose REMAINING budget is below the engine's observed
    admission->first-token floor is provably unmeetable and sheds
    before burning a prefill (the floor arms only past 3 samples, so a
    single compile-contaminated observation never mis-sheds)."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8)
    # un-armed floor: a tight-but-future deadline is NOT shed
    eng._ttft_admit_floor, eng._ttft_admit_n = 10.0, 1
    hold = eng.add_request(np.arange(1, 6), max_new_tokens=6)
    eng.step()
    r_ok = eng.add_request(np.arange(1, 8), max_new_tokens=2,
                           slo_ms=5000.0)
    eng.step()
    assert eng.finish_reason(r_ok) != "shed"
    eng.cancel(r_ok)
    # armed floor above the remaining budget: provably unmeetable
    eng._ttft_admit_floor, eng._ttft_admit_n = 10.0, 3
    sheds = []
    rid = eng.add_request(np.arange(1, 8), max_new_tokens=2,
                          slo_ms=5000.0,
                          on_shed=lambda r, info: sheds.append(info))
    eng.step()
    assert eng.finish_reason(rid) == "shed"
    assert sheds and sheds[0]["where"] == "unmeetable"
    eng.run_until_done()


def test_capacity_shed_prefers_lowest_class(tiny_model, recorder):
    """At a full bounded queue, a strictly more important arrival
    displaces the least-important queued request (where=capacity, the
    429 path) instead of bouncing — and an arrival that is NOT more
    important still gets the typed QueueFull."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                max_queue=1)
    eng.add_request(np.arange(1, 6), max_new_tokens=30)
    eng.step()                                    # slot taken
    sheds = []
    victim = eng.add_request(np.arange(1, 6), max_new_tokens=2,
                             priority=2,
                             on_shed=lambda r, i: sheds.append((r, i)))
    vip = eng.add_request(np.arange(1, 6), max_new_tokens=2, priority=0)
    assert eng.finish_reason(victim) == "shed"
    assert sheds and sheds[0][0] == victim
    assert sheds[0][1]["where"] == "capacity"
    assert sheds[0][1]["retry_after"] >= 0.5
    st = eng.stats()
    assert st["requests_shed"] == 1
    assert st["deadline_misses"] == 0             # capacity != miss
    # an equal-or-lower-class arrival still bounces typed
    with pytest.raises(QueueFull):
        eng.add_request(np.arange(1, 6), max_new_tokens=2, priority=0)
    done = eng.run_until_done()
    assert vip in done


def test_deadline_exceeded_typed_at_submission(tiny_model):
    """A request submitted with its budget already spent raises the
    typed DeadlineExceeded (the front door's 504) and is counted as a
    deadline miss."""
    from paddle_tpu.serving import DeadlineExceeded

    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8)
    with pytest.raises(DeadlineExceeded):
        eng.add_request(np.arange(1, 6), max_new_tokens=2, slo_ms=-5.0)
    st = eng.stats()
    assert st["deadline_misses"] == 1 and st["requests_shed"] == 1


def test_retry_after_estimate_bounds(tiny_model):
    """The computed Retry-After (queue depth / drain rate) is pinned to
    [0.5s, 30s], falls back to 1s before any finish history exists, and
    rides QueueFull.retry_after_s."""
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                max_queue=0)
    assert eng._retry_after_estimate() == 1.0     # no history yet
    eng._finish_interval_ewma = 1000.0
    assert eng._retry_after_estimate() == 30.0    # clamped high
    eng._finish_interval_ewma = 1e-6
    assert eng._retry_after_estimate() == 0.5     # clamped low
    eng._finish_interval_ewma = 2.0
    assert eng._retry_after_estimate() == 2.0     # (depth 0 + 1) * 2s
    eng.add_request(np.arange(1, 6), max_new_tokens=20)
    eng.step()
    with pytest.raises(QueueFull) as ei:
        eng.add_request(np.arange(1, 6), max_new_tokens=2)
    assert 0.5 <= ei.value.retry_after_s <= 30.0
    assert ei.value.retry_after_s == 2.0
    eng.run_until_done()


def test_finish_interval_estimator_updates(tiny_model):
    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    for _ in range(3):
        eng.add_request(np.arange(1, 6), max_new_tokens=2)
    eng.run_until_done()
    assert eng._finish_interval_ewma is not None
    assert eng._finish_interval_ewma > 0
    assert eng._ttft_admit_floor is not None and eng._ttft_admit_n >= 3


def test_http_504_on_queued_deadline_expiry(tiny_model):
    """The HTTP surface of a deadline shed: a queued request whose
    budget runs out answers a REAL 504 with code=deadline_exceeded on
    both the batch and the streaming path (SSE headers are deferred, so
    the status line is real) — never a silent stall."""
    from paddle_tpu.serving_http import CompletionServer

    m = tiny_model
    # a LONG holder stream keeps the single slot busy for the whole
    # probe sequence (the tiny model decodes ~ms/token — a short hold
    # would free the slot between probes and race the sheds away)
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=256, page_size=8)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        holder = http.client.HTTPConnection(host, port, timeout=120)
        holder.request(
            "POST", "/v1/completions",
            json.dumps({"prompt_token_ids": [1, 2, 3, 4],
                        "max_tokens": 250, "stream": True}),
            {"Content-Type": "application/json"})
        resp = holder.getresponse()
        assert resp.status == 200
        resp.readline()            # slot definitely held

        def post(body, headers=None):
            c = http.client.HTTPConnection(host, port, timeout=120)
            h = {"Content-Type": "application/json"}
            h.update(headers or {})
            c.request("POST", "/v1/completions", json.dumps(body), h)
            r = c.getresponse()
            data = json.loads(r.read())
            c.close()
            return r.status, data

        st, data = post({"prompt_token_ids": [5, 6], "max_tokens": 2,
                         "slo_ms": 40.0})
        assert st == 504 and data["code"] == "deadline_exceeded", data
        st, data = post({"prompt_token_ids": [5, 6], "max_tokens": 2,
                         "slo_ms": 40.0, "stream": True})
        assert st == 504 and data["code"] == "deadline_exceeded", data
        # deadline header: already-spent budget answers 504 at the door
        st, data = post({"prompt_token_ids": [5, 6], "max_tokens": 2},
                        headers={"X-Request-Deadline": "-100"})
        assert st == 504 and data["code"] == "deadline_exceeded", data
        # malformed header is a 400, not a stall or a 500
        st, data = post({"prompt_token_ids": [5, 6], "max_tokens": 2},
                        headers={"X-Request-Deadline": "soon"})
        assert st == 400
        rest = resp.read()
        assert b"[DONE]" in rest   # the holder stream finished clean
        holder.close()


def test_deadline_header_wins_over_body_slo(tiny_model):
    """X-Request-Deadline carries the REMAINING budget from the router
    and must override the body's original slo_ms: a request whose body
    SLO would instantly shed completes when the header grants budget."""
    from paddle_tpu.serving_http import CompletionServer

    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        c = http.client.HTTPConnection(host, port, timeout=120)
        c.request("POST", "/v1/completions",
                  json.dumps({"prompt_token_ids": [1, 2, 3], 
                              "max_tokens": 2, "slo_ms": 0.001}),
                  {"Content-Type": "application/json",
                   "X-Request-Deadline": "30000"})
        r = c.getresponse()
        data = json.loads(r.read())
        c.close()
        assert r.status == 200, data


def test_read_incident_prints_admission_shed_section(
        tiny_model, tmp_path, recorder, capsys):
    """scripts/read_incident.py surfaces the shed trail as its own
    ADMISSION / SHED section."""
    import importlib.util

    m = tiny_model
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8)
    rep = frec.IncidentReporter(str(tmp_path))
    rep.register_engine("decoder", eng)
    eng.add_request(np.arange(1, 6), max_new_tokens=20)
    eng.step()
    rid = eng.add_request(np.arange(1, 8), max_new_tokens=2,
                          slo_ms=20.0)
    time.sleep(0.04)
    eng.run_until_done()
    assert eng.finish_reason(rid) == "shed"
    path = rep.activate().dump("manual", context="shed-test")
    spec = importlib.util.spec_from_file_location(
        "_read_incident_shed",
        os.path.join(_REPO, "scripts", "read_incident.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([path]) == 0
    out = capsys.readouterr().out
    assert "ADMISSION / SHED" in out
    # the module-shared ring may carry sheds from earlier tests; this
    # test's expired shed must be counted and its rid listed
    assert "expired=" in out
    assert f"rid={rid}" in out
