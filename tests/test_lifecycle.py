"""End-to-end model lifecycle: pretrain (fused step) → checkpoint →
LoRA fine-tune (adapters only) → merge → int8 quantize → continuous-
batching serve — the user journey docs/MIGRATE.md promises, as one test."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.nn.quant import quantize_for_serving
from paddle_tpu.peft import LoRAConfig, get_peft_model, lora_state_dict, merge_lora
from paddle_tpu.serving import ContinuousBatchEngine


def _loss_fn(m, x, y):
    loss, _ = m(x, labels=y)
    return loss


def test_full_lifecycle(tmp_path):
    rng = np.random.RandomState(0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)

    # 1. pretrain
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    step = paddle.jit.train_step(
        model, _loss_fn, opt.AdamW(1e-2, parameters=model.parameters()))
    ids = rng.randint(0, cfg.vocab_size, (4, 33))
    x, y = paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])
    pre_losses = [float(step(x, y).numpy()) for _ in range(5)]
    assert pre_losses[-1] < pre_losses[0]

    # 2. checkpoint round trip
    ckpt = str(tmp_path / "base.pdparams")
    paddle.save(model.state_dict(), ckpt)
    paddle.seed(123)
    model = LlamaForCausalLM(cfg)
    model.set_state_dict(paddle.load(ckpt))

    # 3. LoRA fine-tune on a different distribution; base stays frozen
    model, n_ad = get_peft_model(model, LoRAConfig(r=4))
    assert n_ad == 8
    ft_ids = rng.randint(0, cfg.vocab_size // 2, (4, 33))  # skewed data
    fx, fy = paddle.to_tensor(ft_ids[:, :-1]), paddle.to_tensor(ft_ids[:, 1:])
    ft_step = paddle.jit.train_step(
        model, _loss_fn, opt.AdamW(5e-2, parameters=model.parameters()))
    ft_losses = [float(ft_step(fx, fy).numpy()) for _ in range(5)]
    assert ft_losses[-1] < ft_losses[0]
    adapters = lora_state_dict(model)
    assert len(adapters) == 16  # A+B per wrapped projection

    # 4. merge; logits identical to the adapter model
    probe = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (1, 10)))
    with_adapters = model(probe).numpy()
    model, n_merged = merge_lora(model)
    assert n_merged == n_ad
    np.testing.assert_allclose(model(probe).numpy(), with_adapters,
                               atol=2e-5, rtol=2e-5)

    # 5. quantize + serve: engine output token-identical to solo generate
    model, n_q = quantize_for_serving(model)
    assert n_q == 15
    eng = ContinuousBatchEngine(model, max_batch=2, max_len=64, page_size=8)
    prompts = [rng.randint(0, cfg.vocab_size, (n,)) for n in (9, 6)]
    streamed = {}
    rids = [eng.add_request(p, max_new_tokens=6,
                            on_token=lambda rid, t, d: streamed.setdefault(
                                rid, []).append(t))
            for p in prompts]
    done = eng.run_until_done()
    for rid, p in zip(rids, prompts):
        solo = model.generate(paddle.to_tensor(p[None]),
                              max_new_tokens=6).numpy()[0]
        np.testing.assert_array_equal(done[rid], solo)
        np.testing.assert_array_equal(np.asarray(streamed[rid]), solo)
