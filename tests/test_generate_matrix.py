"""Cross-family generate() consistency matrix: every decoder family must
satisfy the same internal-path equalities (cached == no-cache == paged;
beams at K=1 == greedy; penalized cached == penalized no-cache). The
per-family parity-vs-transformers tests live in the family files; this is
the one gate asserting the DECODE PATHS agree with each other everywhere."""
import numpy as np
import pytest

import paddle_tpu as paddle

FAMILIES = ["llama", "qwen2", "qwen3", "mistral", "gpt2", "qwen2_moe",
            "deepseek", "mixtral", "gemma", "gemma2", "phi3", "glm4",
            "olmo2"]


def _build(name):
    paddle.seed(11)
    if name == "llama":
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))
    if name == "qwen2":
        from paddle_tpu.models.qwen2 import Qwen2Config, Qwen2ForCausalLM

        return Qwen2ForCausalLM(Qwen2Config.tiny(num_hidden_layers=2))
    if name == "qwen3":
        from paddle_tpu.models.qwen3 import Qwen3Config, Qwen3ForCausalLM

        # head_dim != hidden/heads: every decode path sees the decoupling
        return Qwen3ForCausalLM(Qwen3Config.tiny(num_hidden_layers=2))
    if name == "mistral":
        from paddle_tpu.models.mistral import (MistralConfig,
                                               MistralForCausalLM)

        # window < prompt so the band genuinely bites on every path
        return MistralForCausalLM(MistralConfig.tiny(
            num_hidden_layers=2, sliding_window=6))
    if name == "gpt2":
        from paddle_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

        return GPT2LMHeadModel(GPT2Config.tiny(num_hidden_layers=2))
    if name == "qwen2_moe":
        from paddle_tpu.models.qwen2_moe import (Qwen2MoeConfig,
                                                 Qwen2MoeForCausalLM)

        return Qwen2MoeForCausalLM(Qwen2MoeConfig.tiny(num_hidden_layers=2))
    if name == "deepseek":
        from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                                DeepseekV2ForCausalLM)

        return DeepseekV2ForCausalLM(
            DeepseekV2Config.tiny_mla(num_hidden_layers=2))
    if name == "mixtral":
        from paddle_tpu.models.mixtral import (MixtralConfig,
                                               MixtralForCausalLM)

        return MixtralForCausalLM(MixtralConfig.tiny(num_hidden_layers=2))
    if name == "gemma":
        from paddle_tpu.models.gemma import GemmaConfig, GemmaForCausalLM

        # GeGLU + (1+w) norms + scaled embeddings + tied head on every path
        return GemmaForCausalLM(GemmaConfig.tiny(num_hidden_layers=2))
    if name == "gemma2":
        from paddle_tpu.models.gemma2 import (Gemma2Config,
                                              Gemma2ForCausalLM)

        # sandwich norms + softcaps + alternating window on every path
        return Gemma2ForCausalLM(Gemma2Config.tiny(num_hidden_layers=2))
    if name == "glm4":
        from paddle_tpu.models.glm import Glm4Config, Glm4ForCausalLM

        # sandwich trunk + partial rotary + qkv bias on every path
        return Glm4ForCausalLM(Glm4Config.tiny(num_hidden_layers=2))
    if name == "olmo2":
        from paddle_tpu.models.olmo2 import Olmo2Config, Olmo2ForCausalLM

        # post-norm blocks + full-width qk norms on every path
        return Olmo2ForCausalLM(Olmo2Config.tiny(num_hidden_layers=2))
    if name == "phi3":
        from paddle_tpu.models.phi3 import Phi3Config, Phi3ForCausalLM

        # longrope tables (long regime at these lengths) on every path
        return Phi3ForCausalLM(Phi3Config.tiny(
            num_hidden_layers=2,
            rope_scaling={"rope_type": "longrope",
                          "short_factor": [1.0] * 8,
                          "long_factor": [2.0] * 8,
                          "original_max_position_embeddings": 8}))
    raise AssertionError(name)


@pytest.fixture(scope="module", params=FAMILIES)
def family_model(request):
    return request.param, _build(request.param)


def _prompt(model, b=2, s=12):
    v = model.config.vocab_size
    return paddle.to_tensor(np.random.RandomState(5).randint(1, v, (b, s)))


def test_cached_equals_no_cache(family_model):
    name, m = family_model
    x = _prompt(m)
    a = m.generate(x, max_new_tokens=5).numpy()
    b = m.generate(x, max_new_tokens=5, use_cache=False).numpy()
    np.testing.assert_array_equal(a, b, err_msg=name)


def test_cached_equals_paged(family_model):
    name, m = family_model
    x = _prompt(m)
    if name == "deepseek":
        # MLA's latent cache has no per-head pages by design; the paged
        # path must refuse loudly, not silently mis-decode
        with pytest.raises(NotImplementedError, match="paged"):
            m.generate(x, max_new_tokens=5, paged=True, page_size=4)
        return
    a = m.generate(x, max_new_tokens=5).numpy()
    b = m.generate(x, max_new_tokens=5, paged=True, page_size=4).numpy()
    np.testing.assert_array_equal(a, b, err_msg=name)


def test_beam_k1_equals_greedy(family_model):
    name, m = family_model
    x = _prompt(m)
    a = m.generate(x, max_new_tokens=5).numpy()
    b = m.generate(x, max_new_tokens=5, num_beams=1).numpy()
    np.testing.assert_array_equal(a, b, err_msg=name)


def test_penalized_paths_agree(family_model):
    name, m = family_model
    x = _prompt(m)
    kw = dict(max_new_tokens=5, repetition_penalty=1.4,
              no_repeat_ngram_size=2)
    a = m.generate(x, **kw).numpy()
    b = m.generate(x, use_cache=False, **kw).numpy()
    np.testing.assert_array_equal(a, b, err_msg=name)
