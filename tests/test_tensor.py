"""Tensor construction, properties, methods, dunders."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_dtype_conversion():
    t = paddle.to_tensor([1, 2, 3])
    assert "int" in str(t.dtype)
    f = t.astype("float32")
    assert f.dtype == paddle.float32
    b = f.astype(paddle.bfloat16)
    assert b.dtype == paddle.bfloat16


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a + 1).numpy(), [2, 3])
    np.testing.assert_allclose((2 * a).numpy(), [2, 4])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])


def test_comparison():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    np.testing.assert_array_equal((a >= b).numpy(), [False, True, True])


def test_matmul_dunder():
    a = paddle.randn([3, 4])
    b = paddle.randn([4, 5])
    c = a @ b
    assert c.shape == [3, 5]
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)


def test_indexing_basic():
    t = paddle.arange(12, dtype="float32").reshape([3, 4])
    np.testing.assert_allclose(t[0].numpy(), [0, 1, 2, 3])
    np.testing.assert_allclose(t[:, 1].numpy(), [1, 5, 9])
    np.testing.assert_allclose(t[1, 2].numpy(), 6)
    np.testing.assert_allclose(t[::2].numpy(), t.numpy()[::2])
    np.testing.assert_allclose(t[..., -1].numpy(), [3, 7, 11])


def test_indexing_bool_mask():
    t = paddle.to_tensor([1.0, -2.0, 3.0, -4.0])
    out = t[t > 0]
    np.testing.assert_allclose(out.numpy(), [1, 3])


def test_setitem():
    t = paddle.zeros([3, 3])
    t[1] = 5.0
    np.testing.assert_allclose(t.numpy()[1], [5, 5, 5])
    t[0, 0] = 7.0
    assert t.numpy()[0, 0] == 7


def test_item_and_scalars():
    t = paddle.to_tensor(3.5)
    assert t.item() == pytest.approx(3.5)
    assert float(t) == pytest.approx(3.5)
    assert int(paddle.to_tensor(7)) == 7


def test_inplace_ops():
    t = paddle.to_tensor([1.0, 2.0])
    t.add_(paddle.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(t.numpy(), [2, 3])
    t.clip_(min=0.0, max=2.5)
    np.testing.assert_allclose(t.numpy(), [2, 2.5])
    t.zero_()
    np.testing.assert_allclose(t.numpy(), [0, 0])


def test_clone_detach():
    t = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    c = t.clone()
    d = t.detach()
    assert not c.stop_gradient
    assert d.stop_gradient
    np.testing.assert_allclose(c.numpy(), t.numpy())


def test_shape_props():
    t = paddle.zeros([2, 3, 4])
    assert t.ndim == 3
    assert t.size == 24
    assert t.numel() == 24
    assert len(t) == 2
    assert t.T.shape == [4, 3, 2]


def test_pytree_registration():
    import jax

    t = paddle.to_tensor([1.0, 2.0])
    leaves = jax.tree_util.tree_leaves(t)
    assert len(leaves) == 1
    doubled = jax.jit(lambda x: x * 2)(t)
    np.testing.assert_allclose(np.asarray(jax.tree_util.tree_leaves(doubled)[0]), [2, 4])


def test_creation_ops():
    np.testing.assert_allclose(paddle.zeros([2, 2]).numpy(), np.zeros((2, 2)))
    np.testing.assert_allclose(paddle.ones([2]).numpy(), [1, 1])
    np.testing.assert_allclose(paddle.full([2], 3.0).numpy(), [3, 3])
    np.testing.assert_allclose(paddle.arange(5).numpy(), [0, 1, 2, 3, 4])
    np.testing.assert_allclose(paddle.linspace(0, 1, 3).numpy(), [0, 0.5, 1])
    np.testing.assert_allclose(paddle.eye(2).numpy(), np.eye(2))
    assert paddle.randn([4, 4]).shape == [4, 4]
    assert paddle.randint(0, 10, [5]).shape == [5]
    r = paddle.uniform([100], min=2.0, max=3.0)
    assert (r.numpy() >= 2).all() and (r.numpy() < 3).all()


def test_random_seed_reproducible():
    paddle.seed(42)
    a = paddle.randn([4])
    paddle.seed(42)
    b = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_tensor_array():
    """TensorArray surface parity (python/paddle/tensor/array.py;
    phi/core/tensor_array.h)."""
    arr = paddle.create_array()
    t0 = paddle.to_tensor([1.0, 2.0])
    t1 = paddle.to_tensor([3.0, 4.0])
    paddle.array_write(t0, 0, arr)
    paddle.array_write(t1, paddle.to_tensor(1), arr)
    assert paddle.array_length(arr) == 2
    got = paddle.array_read(arr, paddle.to_tensor(0))
    np.testing.assert_array_equal(got.numpy(), t0.numpy())
    # overwrite in place
    paddle.array_write(t1, 0, arr)
    np.testing.assert_array_equal(paddle.array_read(arr, 0).numpy(), t1.numpy())
    # init list + type checks
    arr2 = paddle.create_array(initialized_list=[t0, t1])
    assert paddle.array_length(arr2) == 2
    with pytest.raises(TypeError):
        paddle.create_array(initialized_list=[1.5])
    with pytest.raises(IndexError):
        paddle.array_read(arr2, 5)
    with pytest.raises(IndexError):
        paddle.array_write(t0, 7, arr2)
    # grads flow through reads
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a3 = paddle.create_array()
    paddle.array_write(x * 3, 0, a3)
    paddle.array_read(a3, 0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 3.0)


def test_tensor_method_table_parity():
    """Every name in the reference's tensor_method_func list
    (python/paddle/tensor/__init__.py) exists on our Tensor. This is the
    method-table completeness gate for the round-3 surface push."""
    import os
    import re

    ref = "/root/reference/python/paddle/tensor/__init__.py"
    if not os.path.exists(ref):
        import pytest

        pytest.skip("reference tree not mounted")
    src = open(ref).read()
    m = re.search(r"tensor_method_func\s*=\s*\[(.*?)\]", src, re.S)
    names = re.findall(r"'([A-Za-z0-9_]+)'", m.group(1))
    missing = [n for n in names if not hasattr(paddle.Tensor, n)]
    assert not missing, f"{len(missing)} tensor methods missing: {missing}"


def test_inplace_and_random_fill_methods():
    """Round-3 in-place variants: value semantics + payload swap, and the
    random fills (cauchy_/geometric_/exponential_/log_normal_/set_)."""
    x = paddle.to_tensor(np.array([1.0, 4.0, 9.0], dtype="float32"))
    out = x.sqrt_()
    assert out is x
    np.testing.assert_allclose(x.numpy(), [1.0, 2.0, 3.0])
    x.cumsum_()
    np.testing.assert_allclose(x.numpy(), [1.0, 3.0, 6.0])
    x.cast_("int32")
    assert x.dtype == paddle.int32
    # comparison in-place changes dtype to bool (reference type-promoting
    # inplace semantics)
    y = paddle.to_tensor(np.array([1.0, 2.0], dtype="float32"))
    y.less_than_(paddle.to_tensor(np.array([2.0, 1.0], dtype="float32")))
    assert y.dtype == paddle.bool
    np.testing.assert_array_equal(y.numpy(), [True, False])
    # random fills keep shape/dtype and mutate in place
    paddle.seed(7)
    z = paddle.zeros([64], "float32")
    z.exponential_()
    assert float(z.numpy().min()) >= 0.0
    z.cauchy_(); z.geometric_(0.4); z.log_normal_()
    assert z.shape == [64] and z.dtype == paddle.float32
    w = paddle.zeros([3])
    w.set_(paddle.to_tensor(np.arange(5, dtype="float32")))
    assert w.shape == [5]
    t = paddle.to_tensor(np.ones((2, 3), dtype="float32"))
    t.t_()
    assert t.shape == [3, 2]


def test_shape_op():
    s = paddle.shape(paddle.ones([2, 3]))
    assert s.dtype == paddle.int32
    np.testing.assert_array_equal(s.numpy(), [2, 3])


def test_random_samplers_round3():
    """binomial/standard_gamma/log_normal: shape/dtype/moment sanity."""
    paddle.seed(0)
    b = paddle.binomial(paddle.full([2000], 10, "int32"),
                        paddle.full([2000], 0.5))
    assert paddle.is_integer(b)  # int64 logical dtype (x64-off → int32)
    assert 4.0 < float(b.numpy().mean()) < 6.0
    g = paddle.standard_gamma(paddle.full([2000], 2.0))
    assert 1.7 < float(g.numpy().mean()) < 2.3
    ln = paddle.log_normal(mean=0.0, std=0.5, shape=[2000])
    # E[lognormal(0, .5)] = exp(.125) ~ 1.133
    assert 1.0 < float(ln.numpy().mean()) < 1.3
