"""Mixtral family: construction guards, training, HF conversion +
logits/greedy parity against transformers, sliding-window mapping."""
import dataclasses

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.mixtral import (MixtralConfig, MixtralForCausalLM,
                                       mixtral_from_hf)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_construction_guards():
    paddle.seed(0)
    cfg = MixtralConfig.tiny()
    m = MixtralForCausalLM(cfg)
    mlp = m.llama.layers[0].mlp
    assert mlp.shared_expert is None
    assert mlp.experts.w1.shape == [cfg.n_routed_experts, cfg.hidden_size,
                                    2 * cfg.moe_intermediate_size]
    ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 12)))
    loss, _ = m(ids, labels=ids)
    assert np.isfinite(float(loss.numpy()))
    with pytest.raises(ValueError, match="shared expert"):
        MixtralForCausalLM(dataclasses.replace(cfg, n_shared_experts=1))
    with pytest.raises(ValueError, match="norm_topk_prob"):
        MixtralForCausalLM(dataclasses.replace(cfg, norm_topk_prob=False))
    with pytest.raises(ValueError, match="sparse from layer 0"):
        MixtralForCausalLM(dataclasses.replace(cfg, first_k_dense_replace=1))


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(1)
    m = MixtralForCausalLM(MixtralConfig.tiny())

    def loss_fn(mm, x, y):
        loss, _ = mm(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def _tiny_hf(window=None):
    from transformers import MixtralConfig as HFConfig
    from transformers import MixtralForCausalLM as HFMixtral

    torch.manual_seed(0)
    hf_cfg = HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=1e6,
        num_local_experts=4, num_experts_per_tok=2,
        sliding_window=window, output_router_logits=False,
        tie_word_embeddings=False, attn_implementation="eager")
    return HFMixtral(hf_cfg).eval()


def test_logits_and_generate_match_transformers():
    """Full-precision parity with HF modeling_mixtral on a tiny shape.
    Capacity raised so the GShard dispatch drops no token (HF is
    dropless); the top-2-softmax combine must equal the trunk's
    renormalized top-k path."""
    hf = _tiny_hf()
    ours = mixtral_from_hf(hf, dtype="float32", use_flash_attention=False,
                           moe_capacity_factor=8.0)
    assert ours.config.n_shared_experts == 0
    assert ours.config.norm_topk_prob is True
    assert ours.config.moe_intermediate_size == 96
    ids = np.random.RandomState(0).randint(0, 128, (2, 9))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 9:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_sliding_window_maps_from_hf():
    hf = _tiny_hf(window=8)
    ours = mixtral_from_hf(hf, dtype="float32", use_flash_attention=False,
                           moe_capacity_factor=8.0)
    assert ours.config.sliding_window == 8
    ids = np.random.RandomState(1).randint(0, 128, (1, 16))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
