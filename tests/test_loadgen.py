"""Traffic replay & saturation harness (paddle_tpu.loadgen): seeded
synthesis determinism (replay is only a referee if two runs provably
saw the same traffic), the JSONL trace round-trip, and THE tier-1
saturation gate — a seconds-scale QPS burst at 2x the measured knee
against an in-process engine, pinning the overload contract:

- zero requests admitted after their deadline expired (shed rids never
  appear as engine.admit events);
- every rejection is typed — 429 with Retry-After or 504 with
  code=deadline_exceeded — zero 5xx, zero silent stalls;
- lowest-priority classes shed first, top-class p99 TTFT stays bounded;
- goodput-under-SLO is reported, and the client-visible outcome counts
  reconcile exactly with the engine's own shed/reject accounting.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.loadgen import (TraceRequest, WorkloadSpec, dumps_trace,
                                find_knee, loads_trace, run_schedule,
                                stack_stats, summarize, sweep, synthesize,
                                trace_digest)
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.serving import ContinuousBatchEngine
from paddle_tpu.serving_http import CompletionServer


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


# ---- determinism: the referee must be reproducible --------------------------

def test_synthesis_deterministic_and_seed_sensitive():
    spec = WorkloadSpec(qps=12, duration_s=3, process="poisson",
                        prompt_tokens=(4, 10), max_tokens=(4, 12),
                        classes=((0, 500.0, 0.2), (1, 1000.0, 0.5),
                                 (2, 250.0, 0.3)),
                        cancel_rate=0.1, seed=7)
    a, b = synthesize(spec), synthesize(spec)
    # same seed + same spec => byte-identical schedule
    assert dumps_trace(a) == dumps_trace(b)
    assert trace_digest(a) == trace_digest(b)
    # a different seed is different traffic
    c = synthesize(spec.replace(seed=8))
    assert trace_digest(c) != trace_digest(a)
    # the mix actually covers the spec'd classes and cancel markers
    prios = {tr.priority for tr in a}
    assert prios <= {0, 1, 2} and len(prios) >= 2
    assert any(tr.cancel_after_s is not None for tr in a)
    assert all(tr.t < spec.duration_s for tr in a)


def test_trace_roundtrip_byte_identical(tmp_path):
    spec = WorkloadSpec(qps=10, duration_s=2, seed=3,
                        classes=((1, 800.0, 1.0),))
    sched = synthesize(spec)
    raw = dumps_trace(sched)
    again = loads_trace(raw)
    assert dumps_trace(again) == raw          # loader loses nothing
    path = tmp_path / "trace.jsonl"
    path.write_text(raw)
    from paddle_tpu.loadgen import load_trace

    assert dumps_trace(load_trace(str(path))) == raw
    # null-field round trip: no slo, no cancel
    tr = TraceRequest(0.5, [1, 2, 3], 4)
    rt = loads_trace(dumps_trace([tr]))[0]
    assert rt.slo_ms is None and rt.cancel_after_s is None


def test_arrival_processes():
    base = dict(duration_s=4.0, prompt_tokens=(4, 4), max_tokens=(4, 4),
                seed=5)
    uni = synthesize(WorkloadSpec(qps=10, process="uniform", **base))
    gaps = np.diff([tr.t for tr in uni])
    assert np.allclose(gaps, 0.1)             # fixed 1/qps spacing
    poi = synthesize(WorkloadSpec(qps=10, process="poisson", **base))
    assert 10 <= len(poi) <= 80               # ~40 expected, seeded
    assert np.diff([tr.t for tr in poi]).std() > 0
    bur = synthesize(WorkloadSpec(qps=10, process="burst",
                                  burst_on_s=1.0, burst_off_s=1.0,
                                  burst_factor=2.0, **base))
    # every burst arrival sits inside an on-window of the 2s cycle
    assert all((tr.t % 2.0) < 1.0 for tr in bur)


def test_find_knee_picks_last_good_point():
    pts = [{"offered_qps": q, "goodput": {"ratio": r}}
           for q, r in ((4, 1.0), (8, 0.95), (16, 0.6), (32, 0.2))]
    assert find_knee(pts, threshold=0.85) == 8
    # all past saturation -> lowest rate, never a crash
    bad = [{"offered_qps": q, "goodput": {"ratio": 0.1}} for q in (4, 8)]
    assert find_knee(bad) == 4


# ---- live harness -----------------------------------------------------------

def test_summary_stable_across_runs(tiny_model):
    """Same seed + same trace => identical schedule digest and identical
    outcome counts across two runs against a live engine (timing stats
    move, the schedule and its accounting must not)."""
    eng = ContinuousBatchEngine(tiny_model, max_batch=4, max_len=64,
                                page_size=8)
    spec = WorkloadSpec(qps=6, duration_s=1.5, prompt_tokens=(4, 8),
                        max_tokens=(2, 4), seed=2,
                        vocab_size=tiny_model.config.vocab_size)
    sched = synthesize(spec)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        url = f"http://{host}:{port}"
        runs = []
        for _ in range(2):
            outs = run_schedule(url, sched, stream_timeout=60)
            runs.append(summarize(outs, spec.duration_s,
                                  offered_qps=spec.qps,
                                  digest=trace_digest(sched)))
    a, b = runs
    assert a["schedule_digest"] == b["schedule_digest"]
    assert a["n"] == b["n"]
    # unsaturated engine, no SLOs: both runs complete everything
    assert a["completed"] == b["completed"] == a["n"]
    assert a["http_5xx"] == b["http_5xx"] == 0
    assert set(a["by_priority"]) == set(b["by_priority"])


def test_saturation_gate(tiny_model):
    """THE gate: sweep to the knee, then a 2x-knee overload burst with a
    priority/SLO mix. Zero admitted-then-expired, all rejections typed,
    zero 5xx / stalls, low classes shed first, top-class p99 TTFT
    bounded, goodput reported and reconciled with engine accounting."""
    # ONE slot + a short bounded queue: capacity is ~1/(tokens*step)
    # rps, so the 2x-knee burst reliably builds the queue the 250ms
    # class expires in — the gate needs real sheds and 429s, not a
    # lucky fast engine
    eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=64,
                                page_size=8, max_queue=8, aging_s=2.0)
    rec = frec.get_recorder()
    was = rec.enabled
    rec.enable()
    try:
        with CompletionServer(eng) as srv:
            host, port = srv.address
            url = f"http://{host}:{port}"
            base = WorkloadSpec(
                qps=8, duration_s=2.0, process="poisson",
                prompt_tokens=(4, 10), max_tokens=(16, 24),
                classes=((0, 3000.0, 0.2), (1, 1500.0, 0.4),
                         (2, 250.0, 0.4)),
                vocab_size=tiny_model.config.vocab_size, seed=0)
            # deterministic warm-up: both prompt-length buckets (8 and
            # 16 at page_size=8) compile OUTSIDE the measured runs, and
            # enough first tokens land to arm the engine's service
            # floor — the sweep then measures serving, not compiles
            run_schedule(url, [
                TraceRequest(0.1 * i, [7] * plen, 16)
                for i, plen in enumerate((5, 10, 5, 10, 5))],
                stream_timeout=120)
            curve = sweep(url, base, (16, 32), stream_timeout=60)
            knee = curve["knee_qps"]
            assert knee > 0                    # the knee is reported

            over_spec = base.replace(qps=2.0 * knee, duration_s=2.0)
            sched = synthesize(over_spec)
            since = rec.stats()["recorded"]
            before = stack_stats(url)
            outs = run_schedule(url, sched, stream_timeout=60)
            after = stack_stats(url)
            summary = summarize(outs, over_spec.duration_s,
                                offered_qps=over_spec.qps,
                                stack_before=before, stack_after=after,
                                digest=trace_digest(sched))
    finally:
        if not was:
            rec.disable()

    # --- every outcome typed; no stalls, no 5xx -------------------------
    assert summary["untyped"] == 0, summary
    assert summary["http_5xx"] == 0, summary
    assert summary["timed_out"] == 0, summary
    for o in outs:
        assert o.status in (200, 429, 504), o.as_dict()
        if o.status == 429:
            assert o.retry_after is not None      # computed hint rides
            assert 1 <= int(o.retry_after) <= 30  # the pinned bounds
        if o.status == 504:
            assert o.code == "deadline_exceeded", o.as_dict()

    # --- accounting reconciles client <-> engine ------------------------
    stack = summary["stack"]
    assert stack["deadline_misses"] == summary["shed_504"], (summary,
                                                             stack)
    capacity_sheds = stack["requests_shed"] - stack["deadline_misses"]
    assert capacity_sheds >= 0
    assert summary["rejected_429"] == (stack["requests_rejected"]
                                       + capacity_sheds), (summary, stack)

    # --- zero admitted-then-expired: a shed rid never took a slot -------
    evs = rec.events(since=since)
    shed_rids = {e["rid"] for e in evs if e["kind"] == "sched.shed"}
    admitted_rids = {e["rid"] for e in evs if e["kind"] == "engine.admit"}
    assert shed_rids, "a 2x-knee burst with a 300ms class must shed"
    assert not (shed_rids & admitted_rids)

    # --- priority ordering: the top class degrades last -----------------
    byp = summary["by_priority"]
    p0, p2 = byp["0"], byp["2"]
    r0 = p0["completed"] / p0["n"] if p0["n"] else 1.0
    r2 = p2["completed"] / p2["n"] if p2["n"] else 1.0
    assert r0 >= r2, (p0, p2)
    if p0["completed"]:
        assert p0["ttft_ms"]["p99"] < 10_000.0    # bounded, not stalled

    # --- goodput-under-SLO is reported ----------------------------------
    assert summary["goodput"]["ratio"] is not None
    assert summary["goodput"]["tokens_per_s"] >= 0.0
    assert summary["schedule_digest"] == trace_digest(sched)


def test_spec_engine_under_saturation_gate(tiny_model):
    """Engine speculative decode under the loadgen saturation gate: on a
    repetitive-prompt workload (the n-gram drafter's target traffic) at
    a rate past one slot's one-token capacity, the spec engine keeps the
    overload contract (every outcome typed, zero 5xx/stalls) and its
    goodput does NOT regress vs the plain engine at the same offered
    rate — multi-token steps must never cost capacity on the traffic
    they exist to accelerate."""
    prompt = [3, 5, 7, 9] * 6

    def drive(spec_k):
        eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=64,
                                    page_size=8, max_queue=16,
                                    speculative_k=spec_k)
        sched = [TraceRequest(0.05 * i, prompt, 16, slo_ms=8000.0)
                 for i in range(24)]
        with CompletionServer(eng) as srv:
            host, port = srv.address
            url = f"http://{host}:{port}"
            # warm the prompt bucket + the decode/verify programs
            run_schedule(url, [TraceRequest(0.0, prompt, 16)],
                         stream_timeout=120)
            outs = run_schedule(url, sched, stream_timeout=60)
        summary = summarize(outs, 1.2, offered_qps=20.0)
        return summary, eng.stats()

    plain, _ = drive(None)
    spec, st = drive(4)
    for s in (plain, spec):
        assert s["untyped"] == 0, s
        assert s["http_5xx"] == 0, s
        assert s["timed_out"] == 0, s
    # the spec engine actually speculated, and earned accepted tokens on
    # this workload (the gate is about the MULTI-token path, not a
    # silently-degenerate one-token fallback)
    assert st["spec_dispatches"] > 0
    assert st["accepted_tokens_per_dispatch"] > 1.0
    # goodput-under-SLO at the same offered rate: no regression beyond
    # scheduling noise (completed counts, not wall-clock sensitive p99s)
    assert spec["goodput"]["requests"] >= 0.9 * plain["goodput"]["requests"], \
        (plain["goodput"], spec["goodput"])


def test_audit_on_overload_sheds_typed_and_costs_no_goodput(tiny_model):
    """Shadow auditing under the saturation gate: the same overload
    burst with audit_rate=1.0 vs audit off. The budget discipline must
    hold — a loaded engine sheds its sampled audits (``verdict=skipped``
    with typed reasons, never silent) BEFORE they can cost user goodput,
    so the audited leg shows zero extra error classes and no goodput
    regression beyond scheduling noise."""
    prompt = [3, 5, 7, 9] * 2

    def drive(audit_rate):
        eng = ContinuousBatchEngine(tiny_model, max_batch=1, max_len=64,
                                    page_size=8, max_queue=16)
        sched = [TraceRequest(0.05 * i, prompt, 16, slo_ms=8000.0)
                 for i in range(24)]
        with CompletionServer(eng, audit_rate=audit_rate) as srv:
            host, port = srv.address
            url = f"http://{host}:{port}"
            # warm the prompt bucket + decode program outside the burst
            run_schedule(url, [TraceRequest(0.0, prompt, 16)],
                         stream_timeout=120)
            outs = run_schedule(url, sched, stream_timeout=60)
        return (summarize(outs, 1.2, offered_qps=20.0),
                eng.sentinel.federated(),
                eng.sentinel.payload()["skip_reasons"])

    plain, _, _ = drive(0.0)
    audited, fed, reasons = drive(1.0)
    # the overload contract holds identically with auditing on
    for s in (plain, audited):
        assert s["untyped"] == 0, s
        assert s["http_5xx"] == 0, s
        assert s["timed_out"] == 0, s
    # the budget gates actually fired: sheds are counted, never silent
    assert fed["audit_skipped"] > 0, (fed, reasons)
    assert reasons, reasons
    assert set(reasons) <= {"queue_full", "load", "headroom", "reason"}, \
        reasons
    # every audited finish reached SOME verdict (coverage is auditable)
    assert (fed["audit_pass"] + fed["audit_diverged"]
            + fed["audit_skipped"]) > 0
    assert fed["audit_diverged"] == 0.0
    # no goodput regression beyond scheduling noise (completed counts,
    # not wall-clock-sensitive percentiles)
    assert audited["goodput"]["requests"] >= \
        0.9 * plain["goodput"]["requests"], \
        (plain["goodput"], audited["goodput"])


def test_stack_stats_single_process(tiny_model):
    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    with CompletionServer(eng) as srv:
        host, port = srv.address
        url = f"http://{host}:{port}"
        before = stack_stats(url)
        sched = [TraceRequest(0.0, [1, 2, 3, 4], 3)]
        outs = run_schedule(url, sched, stream_timeout=60)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            after = stack_stats(url)
            if after["requests_finished"] - before["requests_finished"]:
                break
            time.sleep(0.05)
    assert outs[0].status == 200 and outs[0].clean
    assert after["requests_admitted"] - before["requests_admitted"] == 1
    assert after["tokens_generated"] - before["tokens_generated"] == 3
