"""static Program/Executor, sparse, linalg/fft/signal, quantization,
geometric, audio, incubate.

Parity model: test/legacy_test static executor tests (feed/fetch), sparse
op tests, OpTest-style numpy references.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


# ---- static ------------------------------------------------------------------

def test_static_program_executor():
    import paddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [-1, 4])
        w = paddle.to_tensor(np.ones((4, 2), np.float32))
        y = paddle.matmul(x, w) + 1.0
    exe = static.Executor()
    feed = np.arange(8, dtype=np.float32).reshape(2, 4)
    out, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
    np.testing.assert_allclose(out, feed @ np.ones((4, 2), np.float32) + 1.0)
    # different batch size re-specializes
    feed3 = np.ones((3, 4), np.float32)
    out3, = exe.run(prog, feed={"x": feed3}, fetch_list=[y])
    assert out3.shape == (3, 2)


def test_static_layer_graph_and_enable_static():
    import paddle_tpu.static as static

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4])
            y = net(x)
        exe = static.Executor()
        feed = np.random.randn(5, 4).astype(np.float32)
        out, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
    finally:
        static.disable_static()
    net.eval()
    ref = net(paddle.to_tensor(feed)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5)


def test_static_errors():
    import paddle_tpu.static as static

    with pytest.raises(RuntimeError):
        static.data("x", [2, 2])  # outside static mode
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 2])
        y = x + 1.0
    exe = static.Executor()
    with pytest.raises(KeyError):
        exe.run(prog, feed={"bogus": np.zeros((2, 2), np.float32)},
                fetch_list=[y])


def test_static_fetch_by_name():
    """Fetching by variable name (a common paddle.static fetch_list form):
    feed names resolve through program.feeds; tensor .name attributes
    resolve through the recorded graph; unknown names raise."""
    import paddle_tpu.static as static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 3])
        y = x * 2.0
        y.name = "doubled"
    exe = static.Executor()
    feed = np.ones((2, 3), np.float32)
    out_feed, out_named = exe.run(prog, feed={"x": feed},
                                  fetch_list=["x", "doubled"])
    np.testing.assert_allclose(out_feed, feed)
    np.testing.assert_allclose(out_named, feed * 2.0)
    with pytest.raises(KeyError):
        exe.run(prog, feed={"x": feed}, fetch_list=["nope"])
    with pytest.raises(TypeError):
        exe.run(prog, feed={"x": feed}, fetch_list=[123])


def test_qat_rejects_tracing():
    """QAT fake-quant layers update python-side scale state and must refuse
    to run under jit tracing instead of silently freezing the scale."""
    import jax

    from paddle_tpu.quantization import FakeQuanterWithAbsMaxObserver

    q = FakeQuanterWithAbsMaxObserver()
    x = paddle.to_tensor(np.random.randn(4, 4).astype(np.float32))
    q(x)  # eager works

    with pytest.raises(RuntimeError, match="eager"):
        jax.jit(lambda a: q(paddle.to_tensor(a)).numpy())(
            np.random.randn(4, 4).astype(np.float32))


def test_save_load_inference_model(tmp_path):
    import paddle_tpu.static as static

    paddle.seed(1)
    net = nn.Linear(4, 3)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4])
        y = net(x)
    exe = static.Executor()
    prefix = str(tmp_path / "model")
    static.save_inference_model(prefix, [x], [y], exe, program=prog)

    pred, feed_names, n_fetch = static.load_inference_model(prefix)
    assert feed_names == ["x"] and n_fetch == 1
    feed = np.random.randn(2, 4).astype(np.float32)
    out, = pred.run([feed])
    net.eval()
    np.testing.assert_allclose(out, net(paddle.to_tensor(feed)).numpy(),
                               rtol=1e-5)


# ---- sparse ------------------------------------------------------------------

def test_sparse_coo_roundtrip_and_ops():
    import paddle_tpu.sparse as sp

    indices = [[0, 1, 2], [1, 2, 0]]
    values = [1.0, 2.0, 3.0]
    s = sp.sparse_coo_tensor(indices, values, shape=[3, 3])
    assert s.is_sparse_coo() and s.nnz() == 3
    dense = s.to_dense().numpy()
    assert dense[0, 1] == 1.0 and dense[2, 0] == 3.0

    r = sp.relu(sp.neg(s))
    assert float(r.values().numpy().max()) == 0.0  # all values were positive

    two = sp.add(s, s)
    np.testing.assert_allclose(two.to_dense().numpy(), dense * 2)


def test_sparse_matmul_and_csr():
    import paddle_tpu.sparse as sp

    s = sp.sparse_coo_tensor([[0, 1], [1, 0]], [2.0, 3.0], shape=[2, 2])
    d = paddle.to_tensor(np.eye(2, dtype=np.float32))
    out = sp.matmul(s, d)
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               [[0, 2], [3, 0]])

    csr = sp.sparse_csr_tensor([0, 1, 2], [1, 0], [2.0, 3.0], [2, 2])
    assert csr.is_sparse_csr()
    np.testing.assert_allclose(csr.to_dense().numpy(), [[0, 2], [3, 0]])


# ---- linalg / fft / signal ---------------------------------------------------

def test_linalg_namespace():
    import paddle_tpu.linalg as L

    a = np.random.randn(3, 3).astype(np.float32)
    a = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    x = paddle.to_tensor(a)
    inv = L.inv(x).numpy()
    np.testing.assert_allclose(inv @ a, np.eye(3), atol=1e-4)
    u, s, vh = (t.numpy() for t in L.svd(x))
    np.testing.assert_allclose((u * s[..., None, :]) @ vh, a, atol=1e-4)
    p, l_, u_ = (t.numpy() for t in L.lu_unpack(*L.lu(x)))
    np.testing.assert_allclose(p @ l_ @ u_, a, atol=1e-4)


def test_fft_roundtrip():
    import paddle_tpu.fft as fft

    x = np.random.randn(8).astype(np.float32)
    X = fft.fft(paddle.to_tensor(x))
    back = fft.ifft(X).numpy()
    np.testing.assert_allclose(back.real, x, atol=1e-5)
    f = fft.rfftfreq(8, d=0.5).numpy()
    np.testing.assert_allclose(f, np.fft.rfftfreq(8, 0.5))


def test_stft_istft_roundtrip():
    import paddle_tpu.signal as signal

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 512)).astype(np.float32)
    spec = signal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16)
    assert list(spec.shape) == [2, 33, 512 // 16 + 1]
    rec = signal.istft(spec, n_fft=64, hop_length=16, length=512).numpy()
    np.testing.assert_allclose(rec, x, atol=1e-4)


# ---- quantization ------------------------------------------------------------

def test_qat_and_ptq():
    from paddle_tpu.quantization import (
        AbsMaxObserver, FakeQuanterWithAbsMaxObserver, PTQ, QAT, QuantConfig,
        QuanterFactory)

    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    ref = net(x).numpy()

    qat_cfg = QuantConfig(
        activation=QuanterFactory(FakeQuanterWithAbsMaxObserver),
        weight=QuanterFactory(FakeQuanterWithAbsMaxObserver))
    qmodel = QAT(qat_cfg).quantize(net)
    qout = qmodel(x).numpy()
    assert qout.shape == ref.shape
    # int8 fake-quant error should be small but nonzero
    err = np.abs(qout - ref).max()
    assert 0 < err < 0.5

    # QAT model still trains (straight-through grads)
    from paddle_tpu import optimizer as opt

    optim = opt.Adam(1e-2, parameters=qmodel.parameters())
    y = paddle.to_tensor(np.random.randn(4, 2).astype(np.float32))
    l0 = None
    for i in range(5):
        loss = ((qmodel(x) - y) ** 2).mean()
        loss.backward()
        optim.step()
        optim.clear_grad()
        l0 = l0 or float(loss.numpy())
    assert float(loss.numpy()) < l0

    ptq_cfg = QuantConfig(activation=QuanterFactory(AbsMaxObserver),
                          weight=QuanterFactory(AbsMaxObserver))
    pmodel = PTQ(ptq_cfg).quantize(net)
    pmodel(x)  # calibrate
    converted = PTQ(ptq_cfg).convert(pmodel)
    cout = converted(x).numpy()
    np.testing.assert_allclose(cout, ref, atol=0.3)


# ---- geometric / audio / incubate -------------------------------------------

def test_geometric_send_u_recv():
    import paddle_tpu.geometric as G

    x = paddle.to_tensor(np.array([[1.0], [2.0], [3.0]], np.float32))
    src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
    dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
    out = G.send_u_recv(x, src, dst, reduce_op="sum")
    np.testing.assert_allclose(out.numpy(), [[1.0], [4.0], [2.0]])
    seg = G.segment_mean(paddle.to_tensor(np.array([1.0, 3.0, 5.0], np.float32)),
                         paddle.to_tensor(np.array([0, 0, 1], np.int32)))
    np.testing.assert_allclose(seg.numpy(), [2.0, 5.0])


def test_audio_features():
    from paddle_tpu.audio.features import MFCC, MelSpectrogram

    x = paddle.to_tensor(np.random.randn(1, 2048).astype(np.float32))
    mel = MelSpectrogram(sr=8000, n_fft=256, n_mels=32)(x)
    assert mel.shape[1] == 32
    mfcc = MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32)(x)
    assert mfcc.shape[1] == 13
    assert np.isfinite(mfcc.numpy()).all()


def test_incubate_fused_ops():
    import paddle_tpu.incubate.nn.functional as IF

    x = paddle.to_tensor(np.random.randn(2, 4, 16).astype(np.float32))
    w = paddle.to_tensor(np.ones(16, np.float32))
    out = IF.fused_rms_norm(x, w)
    ref = x.numpy() / np.sqrt((x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4)

    g = paddle.to_tensor(np.random.randn(2, 6).astype(np.float32))
    sw = IF.swiglu(g)
    gn = g.numpy()
    sil = gn[:, :3] / (1 + np.exp(-gn[:, :3]))
    np.testing.assert_allclose(sw.numpy(), sil * gn[:, 3:], rtol=1e-4)


def test_incubate_fused_attention_layer():
    from paddle_tpu.incubate.nn import FusedMultiHeadAttention

    paddle.seed(3)
    layer = FusedMultiHeadAttention(embed_dim=16, num_heads=2,
                                    dropout_rate=0.0, attn_dropout_rate=0.0)
    layer.eval()
    x = paddle.to_tensor(np.random.randn(2, 5, 16).astype(np.float32))
    out = layer(x)
    assert out.shape == [2, 5, 16]
    assert np.isfinite(out.numpy()).all()


def test_onnx_export_requires_spec():
    """onnx.export is a real exporter now (tests/test_onnx_export.py);
    calling without input_spec still fails loudly."""
    import paddle_tpu.onnx as onnx

    with pytest.raises(ValueError, match="input_spec"):
        onnx.export(nn.Linear(2, 2), "m.onnx")


def test_vector_norm_semantics():
    import paddle_tpu.linalg as L

    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    # multi-axis stays a VECTOR norm (not spectral)
    v = L.vector_norm(x, p=2.0, axis=[-2, -1])
    np.testing.assert_allclose(float(v.numpy()),
                               np.sqrt((np.arange(6) ** 2).sum()), rtol=1e-6)
    kd = L.vector_norm(x, keepdim=True)
    assert list(kd.shape) == [1, 1]
    inf = L.vector_norm(x, p=float("inf"))
    assert float(inf.numpy()) == 5.0


def test_lu_unpack_flags():
    import paddle_tpu.linalg as L

    a = np.random.RandomState(0).randn(4, 4).astype(np.float32)
    lu_, piv = L.lu(paddle.to_tensor(a))
    p, l_, u_ = L.lu_unpack(lu_, piv)
    np.testing.assert_allclose(p.numpy() @ l_.numpy() @ u_.numpy(), a,
                               atol=1e-4)
    p2, l2, u2 = L.lu_unpack(lu_, piv, unpack_ludata=False)
    assert l2 is None and u2 is None and p2 is not None
    p3, l3, u3 = L.lu_unpack(lu_, piv, unpack_pivots=False)
    assert p3 is None and l3 is not None


def test_segment_ops_reject_tracing():
    import jax

    import paddle_tpu.geometric as G

    def traced(d, s):
        return G.segment_mean(d, s)

    with pytest.raises(ValueError, match="out_size"):
        jax.jit(lambda d, s: G.segment_mean(
            paddle.to_tensor(d), paddle.to_tensor(s)).numpy())(
                np.ones((3, 1), np.float32), np.array([0, 0, 1], np.int32))


def test_fused_rope_defaults_and_position_ids():
    import paddle_tpu.incubate.nn.functional as IF

    rng = np.random.default_rng(0)
    q = paddle.to_tensor(rng.standard_normal((1, 4, 2, 8)).astype(np.float32))
    k = paddle.to_tensor(rng.standard_normal((1, 4, 2, 8)).astype(np.float32))
    qo, ko, vo = IF.fused_rotary_position_embedding(q, k)
    assert vo is None and qo.shape == q.shape
    # position 0 is identity rotation
    np.testing.assert_allclose(qo.numpy()[:, 0], q.numpy()[:, 0], atol=1e-6)

    # decode: single token at position 2 must equal full-seq row 2
    pid = paddle.to_tensor(np.array([[2]], np.int64))
    q1 = paddle.to_tensor(q.numpy()[:, 2:3])
    qd, _, _ = IF.fused_rotary_position_embedding(q1, position_ids=pid)
    np.testing.assert_allclose(qd.numpy()[:, 0], qo.numpy()[:, 2], atol=1e-5)


def test_string_tensor():
    """StringTensor kernel-set parity: empty/copy/lower/upper incl. the
    ascii-vs-utf8 split (paddle/phi/kernels/strings/)."""
    from paddle_tpu import strings

    st = strings.StringTensor([["Hello", "WORLD"], ["Grüße", ""]])
    assert st.shape == [2, 2] and st.numel() == 4
    low = strings.lower(st, use_utf8_encoding=True)
    assert low.tolist() == [["hello", "world"], ["grüße", ""]]
    up_ascii = strings.upper(st, use_utf8_encoding=False)
    assert up_ascii.tolist()[0] == ["HELLO", "WORLD"]
    # ascii path leaves the non-ascii ü/ß untouched
    assert up_ascii.tolist()[1][0] == "GRüßE"
    cp = strings.copy(st)
    assert (cp == st).all()
    e = strings.empty([3])
    assert e.tolist() == ["", "", ""]
    assert strings.empty_like(st).shape == [2, 2]


def test_sparse_op_tail():
    """Round-3 sparse breadth (sparse_ops.yaml parity): unary tail,
    softmax, structural remaps, coalesce/mask_as/addmm/mv/slice."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.sparse as sparse

    d = np.array([[1.0, 0, 2], [0, 3, 0]], dtype="float32")
    m = sparse.to_sparse_coo(paddle.to_tensor(d))
    # unary ops act on stored values only
    np.testing.assert_allclose(sparse.expm1(m).to_dense().numpy(),
                               np.where(d != 0, np.expm1(d), 0), rtol=1e-6)
    np.testing.assert_allclose(
        sparse.leaky_relu(sparse.neg(m), 0.1).values().numpy(),
        np.array([-0.1, -0.2, -0.3], dtype="float32"), rtol=1e-6)
    # pattern-aware softmax: absent entries = -inf
    sm = sparse.softmax(m).to_dense().numpy()
    row0 = np.exp([1.0, 2.0]) / np.exp([1.0, 2.0]).sum()
    np.testing.assert_allclose(sm[0], [row0[0], 0, row0[1]], rtol=1e-5)
    assert sm[1, 1] == 1.0
    # structural ops preserve values
    t = sparse.transpose(m, [1, 0])
    np.testing.assert_allclose(t.to_dense().numpy(), d.T)
    r = sparse.reshape(m, [3, 2])
    np.testing.assert_allclose(r.to_dense().numpy(), d.reshape(3, 2))
    s = sparse.slice(m, [1], [1], [3])
    np.testing.assert_allclose(s.to_dense().numpy(), d[:, 1:3])
    # coalesce merges duplicates
    dup = sparse.sparse_coo_tensor(
        paddle.to_tensor(np.array([[0, 0], [1, 1]])),
        paddle.to_tensor(np.array([1.0, 2.0], dtype="float32")), shape=[2, 2])
    co = sparse.coalesce(dup)
    assert co.nnz() == 1 and float(co.values().numpy()[0]) == 3.0
    # mask_as / addmm / mv
    ma = sparse.mask_as(paddle.to_tensor(np.ones((2, 3), "float32")), m)
    np.testing.assert_allclose(ma.to_dense().numpy(), (d != 0).astype("f"))
    A = sparse.to_sparse_coo(paddle.to_tensor(np.eye(3, dtype="float32")))
    out = sparse.addmm(paddle.to_tensor(np.ones((3, 3), "float32")), A,
                       paddle.to_tensor(np.eye(3, dtype="float32")),
                       beta=1.0, alpha=2.0)
    np.testing.assert_allclose(out.numpy()[0], [3.0, 1.0, 1.0])
    mv = sparse.mv(A, paddle.to_tensor(np.arange(3, dtype="float32")))
    np.testing.assert_allclose(mv.numpy(), [0.0, 1.0, 2.0])


def test_sparse_nn_layers():
    """sparse.nn conv3d/subm_conv3d/pool/BN: dense-compute, sparse-storage
    (docstring rationale in sparse/nn.py); subm preserves the pattern."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.sparse as sparse

    paddle.seed(0)
    dense = np.zeros((1, 4, 4, 4, 3), "float32")
    coords = [(0, 1, 1, 1), (0, 2, 3, 0), (0, 3, 2, 2)]
    rng = np.random.RandomState(0)
    for c in coords:
        dense[c] = rng.rand(3)
    st = sparse.to_sparse_coo(paddle.to_tensor(dense), sparse_dim=4)

    subm = sparse.nn.SubmConv3D(3, 5, 3)
    out = subm(st)
    assert out.nnz() == 3 and out.values().shape == [3, 5]
    # subm output coords == input coords
    np.testing.assert_array_equal(
        np.sort(np.asarray(out._array.indices), 0),
        np.sort(np.asarray(st._array.indices), 0))
    # numeric parity vs dense lax conv on the same weights
    import jax
    w = subm.weight.numpy()
    ref = jax.lax.conv_general_dilated(
        dense, w, (1, 1, 1), [(1, 1)] * 3,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
    ref = np.asarray(ref) + subm.bias.numpy()
    got = out.to_dense().numpy()
    for c in coords:
        np.testing.assert_allclose(got[c], ref[c], rtol=1e-4, atol=1e-5)

    conv = sparse.nn.Conv3D(3, 2, 2, stride=2)
    assert conv(st).to_dense().numpy().shape == (1, 2, 2, 2, 2)
    pool = sparse.nn.MaxPool3D(2, 2)
    np.testing.assert_allclose(
        pool(st).to_dense().numpy(),
        np.asarray(jax.lax.reduce_window(
            dense, -np.inf, jax.lax.max, (1, 2, 2, 2, 1), (1, 2, 2, 2, 1),
            "VALID")).clip(0, None), rtol=1e-6)
    bn = sparse.nn.BatchNorm(3)
    nb = bn(st)
    vals = nb.values().numpy()
    np.testing.assert_allclose(vals.mean(0), 0.0, atol=1e-5)
    sync = sparse.nn.SyncBatchNorm.convert_sync_batchnorm(sparse.nn.BatchNorm(3))
    assert isinstance(sync, sparse.nn.SyncBatchNorm)
    att = sparse.fused_attention(
        paddle.randn([1, 2, 4, 8]), paddle.randn([1, 2, 4, 8]),
        paddle.randn([1, 2, 4, 8]),
        sparse.to_sparse_coo(paddle.to_tensor(np.ones((1, 2, 4, 4), "float32"))))
    assert att.shape == [1, 2, 4, 8]


def test_round3_surface_tails():
    """fft hermitian family, audio grids, utils.deprecated, initializer
    globals, LinearLR, transforms affine/perspective/erase, geometric
    sampling, incubate tail."""
    import warnings

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.fft as pfft

    x = paddle.to_tensor(np.array([2 + 2j, 2 + 2j, 3 + 3j], "complex64"))
    np.testing.assert_allclose(pfft.hfftn(x).numpy(), [9, 3, 1, -5],
                               atol=1e-5)
    a = np.random.rand(4, 6).astype("float32")
    np.testing.assert_allclose(
        pfft.hfft2(pfft.ihfft2(paddle.to_tensor(a)), s=[4, 6]).numpy(), a,
        atol=1e-4)

    from paddle_tpu.audio import functional as AF

    f = AF.fft_frequencies(16000, 512)
    assert f.shape == [257] and float(f.numpy()[-1]) == 8000.0
    mel = AF.mel_frequencies(10, 0.0, 8000.0).numpy()
    assert mel.shape == (10,) and np.all(np.diff(mel) > 0)

    import paddle_tpu.utils as U

    @U.deprecated(update_to="paddle.new", since="2.0")
    def old_fn():
        return 7

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn() == 7
        assert any("deprecated" in str(x.message) for x in w)
    assert U.require_version("0.0.1") is True

    import paddle_tpu.nn as nn

    nn.initializer.set_global_initializer(nn.initializer.Constant(2.5))
    try:
        lin = nn.Linear(2, 3)
        assert float(lin.weight.numpy()[0, 0]) == 2.5
    finally:
        nn.initializer.set_global_initializer(None)
    w4 = nn.initializer.Bilinear()([1, 1, 4, 4])
    assert float(np.asarray(w4).max()) <= 1.0

    import paddle_tpu.vision.transforms as T

    img = (np.random.rand(6, 6, 3) * 255).astype("uint8")
    assert np.array_equal(T.affine(img, 0.0, (0, 0), 1.0, (0.0, 0.0)), img)
    pts = [(0, 0), (5, 0), (5, 5), (0, 5)]
    assert np.array_equal(T.perspective(img, pts, pts), img)
    er = T.erase(img, 1, 1, 2, 2, 0)
    assert er[1:3, 1:3].sum() == 0
    assert T.RandomAffine(10)(img).shape == img.shape
    assert T.RandomPerspective(prob=1.0)(img).shape == img.shape

    import paddle_tpu.geometric as G

    row = paddle.to_tensor(np.array([1, 2, 3, 0, 2]))
    colptr = paddle.to_tensor(np.array([0, 3, 5]))
    nb, cnt = G.sample_neighbors(row, colptr,
                                 paddle.to_tensor(np.array([0, 1])),
                                 sample_size=2)
    assert list(cnt.numpy()) == [2, 2] and nb.shape == [4]
    src, dst, nodes = G.reindex_graph(
        paddle.to_tensor(np.array([5, 9])),
        paddle.to_tensor(np.array([9, 3, 5, 7])),
        paddle.to_tensor(np.array([2, 2])))
    np.testing.assert_array_equal(nodes.numpy(), [5, 9, 3, 7])
    np.testing.assert_array_equal(src.numpy(), [1, 2, 0, 3])
    np.testing.assert_array_equal(dst.numpy(), [0, 0, 1, 1])

    import paddle_tpu.incubate as inc

    sm = inc.softmax_mask_fuse_upper_triangle(paddle.randn([1, 4, 4]))
    got = sm.numpy()[0]
    assert np.allclose(np.triu(got, 1), 0, atol=1e-6)
    np.testing.assert_allclose(got.sum(-1), 1.0, rtol=1e-5)
    assert float(inc.identity_loss(paddle.ones([4]), "mean").numpy()) == 1.0
    enc = inc.nn.FusedTransformerEncoderLayer(8, 2, 16, dropout_rate=0.0)
    assert enc(paddle.randn([2, 3, 8])).shape == [2, 3, 8]


def test_graph_sampling_reproducible():
    """Host-side graph sampling draws from the framework seed stream
    (review fix: paddle.seed controls sample_neighbors)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.geometric as G

    row = paddle.to_tensor(np.arange(10) % 5)
    colptr = paddle.to_tensor(np.array([0, 5, 10]))
    paddle.seed(42)
    a1, _ = G.sample_neighbors(row, colptr,
                               paddle.to_tensor(np.array([0, 1])),
                               sample_size=3)
    paddle.seed(42)
    a2, _ = G.sample_neighbors(row, colptr,
                               paddle.to_tensor(np.array([0, 1])),
                               sample_size=3)
    np.testing.assert_array_equal(a1.numpy(), a2.numpy())


def test_static_compat_tail():
    """static round-3 tail: scopes, append_backward/gradients, metrics,
    EMA, program state, BuildStrategy strictness."""
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as st

    w = paddle.create_parameter([2], "float32", name="ab_w")
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    loss = ((w * x) ** 2).sum()
    pairs = st.append_backward(loss)
    assert pairs and pairs[0][1].shape == [2]
    assert pairs[0][1].name.endswith("@GRAD")
    manual = st.gradients(loss, [w])[0].numpy()
    np.testing.assert_allclose(pairs[0][1].numpy(), manual, rtol=1e-6)

    sc = st.Scope()
    with st.scope_guard(sc):
        st.create_global_var([2], 1.5, "float32", name="scoped_v")
        assert st.global_scope().find_var("scoped_v") is not None
    assert st.global_scope().find_var("scoped_v") is None

    bs = st.BuildStrategy()
    bs.fuse_elewise_add_act_ops = True
    with pytest.raises(AttributeError):
        bs.not_a_knob = 1

    acc = st.accuracy(
        paddle.to_tensor(np.array([[0.1, 0.9], [0.8, 0.2]], "float32")),
        paddle.to_tensor(np.array([[1], [1]])))
    assert float(acc.numpy()) == 0.5
    # separable predictions → AUC 1; anti-separable → 0
    auc_v, _, _ = st.auc(
        paddle.to_tensor(np.array([[0.3, 0.7], [0.6, 0.4], [0.2, 0.8],
                                   [0.9, 0.1]], "float32")),
        paddle.to_tensor(np.array([1, 0, 1, 0])))
    assert abs(float(auc_v.numpy()) - 1.0) < 1e-3

    p1 = paddle.Parameter(np.array([1.0], dtype="float32"))
    ema = st.ExponentialMovingAverage(0.5)
    for v in [1.0, 3.0]:
        p1.set_value(np.array([v], "float32"))
        ema.update([p1])
    with ema.apply():
        # bias-corrected: (0.5*0.5*1 + 0.5*3)/(1-0.25) = 2.333...
        assert abs(float(p1.numpy()[0]) - 7.0 / 3.0) < 1e-3
    assert float(p1.numpy()[0]) == 3.0

    tmp = tempfile.mkdtemp()
    prog = st.default_main_program()
    w2 = st.create_parameter([3], "float32", name="w_saved_test")
    st.save(prog, tmp + "/model")
    old = w2.numpy().copy()
    w2.set_value(np.zeros(3, "float32"))
    st.load(prog, tmp + "/model")
    np.testing.assert_allclose(w2.numpy(), old)

    out = st.py_func(lambda a: a * 2, paddle.ones([3]))
    np.testing.assert_allclose(out.numpy(), 2.0)
    with pytest.raises(RuntimeError):
        st.IpuStrategy()


def test_round3_misc_modules():
    """hub/regularizer/callbacks/sysconfig/version/device-streams/
    autograd functional/nn.quant/amp.debugging."""
    import pathlib
    import tempfile

    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.autograd as AG

    # jacobian / hessian numerics
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"))
    J = AG.jacobian(lambda t: t ** 2, x)
    np.testing.assert_allclose(np.diag(J.numpy()), [2, 4, 6], rtol=1e-5)
    H = AG.hessian(lambda t: (t ** 2).sum(), x)
    np.testing.assert_allclose(H.numpy(), 2 * np.eye(3), atol=1e-5)

    # saved_tensors_hooks fire on pack and unpack
    events = []
    with AG.saved_tensors_hooks(
            lambda t: (events.append("pack"), t)[1],
            lambda t: (events.append("unpack"), t)[1]):
        a = paddle.to_tensor(np.array([2.0], "float32"),
                             stop_gradient=False)
        loss = (a * a).sum()
        loss.backward()
    assert "pack" in events and "unpack" in events
    np.testing.assert_allclose(a.grad.numpy(), [4.0])

    # nn.quant weight-only roundtrip + fused linear
    import paddle_tpu.nn.quant as Q

    paddle.seed(0)
    w = paddle.randn([8, 4])
    qw, scale = Q.weight_quantize(w)
    assert qw.dtype == paddle.int8
    deq = Q.weight_dequantize(qw, scale, out_dtype="float32")
    assert float(np.abs(deq.numpy() - w.numpy()).max()) < 0.05
    xq = paddle.randn([2, 8])
    np.testing.assert_allclose(
        Q.weight_only_linear(xq, qw, weight_scale=scale).numpy(),
        xq.numpy() @ w.numpy(), atol=0.1)

    # amp.debugging op stats count eager dispatches
    import paddle_tpu.amp.debugging as dbg

    dbg.enable_operator_stats_collection()
    _ = paddle.ones([2]) + paddle.ones([2])
    snap = dbg.operator_stats_snapshot()
    dbg.disable_operator_stats_collection()
    assert "add" in snap and "float32" in snap["add"]

    # regularizer / callbacks / sysconfig / version / hub
    assert paddle.regularizer.L2Decay(0.1).coeff == 0.1
    assert paddle.callbacks.EarlyStopping is not None
    assert paddle.sysconfig.get_include().endswith("csrc")
    assert paddle.version.full_version == paddle.__version__
    assert paddle.version.cuda() is False
    d = pathlib.Path(tempfile.mkdtemp())
    (d / "hubconf.py").write_text(
        "def tiny(n=3):\n    'Tiny.'\n    import paddle_tpu as P\n"
        "    return P.ones([n])\n")
    assert paddle.hub.list(str(d), source="local") == ["tiny"]
    assert paddle.hub.load(str(d), "tiny", source="local", n=2).shape == [2]
    assert "Tiny" in paddle.hub.help(str(d), "tiny", source="local")
    with pytest.raises(RuntimeError):
        paddle.hub.load("owner/repo", "m")  # github needs egress

    # device streams/events over the single-XLA-stream model
    s = paddle.device.Stream()
    e1 = s.record_event()
    _ = paddle.randn([32, 32]) @ paddle.randn([32, 32])
    e2 = paddle.device.Event()
    e2.record()
    assert e1.elapsed_time(e2) >= 0 and e2.query()
    with paddle.device.stream_guard(paddle.device.Stream()):
        assert paddle.device.current_stream() is not None


def test_saved_hooks_and_llm_int8_reviewfixes():
    """Review regressions: (a) per-node unpack capture — backward after the
    hooks context still restores packed residuals; (b) hooks that dispatch
    registry ops (cast) don't recurse; (c) llm_int8_linear runs a real
    int8 regular path and keeps outlier columns accurate vs the
    dequantized weight; (d) AMP op stats report the execution dtype."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.autograd as AG
    import paddle_tpu.nn.quant as Q

    events = []
    with AG.saved_tensors_hooks(
            lambda t: (events.append("pack"), t)[1],
            lambda t: (events.append("unpack"), t)[1]):
        a = paddle.to_tensor(np.array([2.0], "float32"),
                             stop_gradient=False)
        loss = (a * a).sum()
    loss.backward()  # outside the context: node-captured unpack fires
    assert "unpack" in events
    np.testing.assert_allclose(a.grad.numpy(), [4.0])

    b = paddle.to_tensor(np.array([3.0], "float32"), stop_gradient=False)
    with AG.saved_tensors_hooks(lambda t: t.cast("bfloat16"),
                                lambda t: t.cast("float32")):
        (b * b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [6.0], rtol=1e-2)

    paddle.seed(0)
    w = paddle.randn([8, 4])
    qw, scale = Q.weight_quantize(w)
    deq = Q.weight_dequantize(qw, scale, out_dtype="float32").numpy()
    xo = np.array(paddle.randn([2, 8]).numpy())
    xo[0, 0] = 50.0
    out = Q.llm_int8_linear(paddle.to_tensor(xo), qw, weight_scale=scale,
                            threshold=6.0)
    assert float(np.abs(out.numpy() - xo @ deq).max()) < 0.05

    import paddle_tpu.amp as amp
    import paddle_tpu.amp.debugging as dbg

    dbg.enable_operator_stats_collection()
    with amp.auto_cast():
        _ = paddle.randn([4, 4]) @ paddle.randn([4, 4])
    snap = dbg.operator_stats_snapshot()
    dbg.disable_operator_stats_collection()
    assert "bfloat16" in snap.get("matmul", {})


def test_asp_and_memory_efficient_attention():
    """incubate.asp 2:4 sparsity workflow + memory_efficient_attention."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.incubate as inc
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt

    paddle.seed(0)
    net = nn.Linear(8, 8)
    inc.asp.prune_model(net)
    assert abs(inc.asp.calculate_density(net.weight) - 0.5) < 1e-6
    # every group of 4 has exactly 2 nonzeros
    w = net.weight.numpy().reshape(-1, 4)
    np.testing.assert_array_equal((w != 0).sum(1), 2)
    o = inc.asp.decorate(opt.SGD(0.1, parameters=net.parameters()))
    for _ in range(3):
        loss = (net(paddle.randn([4, 8])) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
    assert abs(inc.asp.calculate_density(net.weight) - 0.5) < 1e-6

    q = paddle.randn([1, 6, 2, 8])
    out = inc.nn.functional.memory_efficient_attention(q, q, q)
    out_b = inc.nn.functional.memory_efficient_attention(
        q, q, q, attn_bias=paddle.zeros([1, 2, 6, 6]))
    np.testing.assert_allclose(out.numpy(), out_b.numpy(), atol=1e-5)

    from paddle_tpu.optimizer import Lamb

    assert isinstance(inc.DistributedFusedLamb(
        parameters=nn.Linear(4, 4).parameters()), Lamb)


def test_static_nn_module():
    """static.nn parity module (30 names): layer-as-function helpers,
    host control flow, padded sequence ops, review fixes (output_size-only
    conv transpose, BN1D attrs, prelu NHWC channel count)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.static as st

    paddle.seed(0)
    assert not [n for n in st.nn.__all__ if not hasattr(st.nn, n)]
    assert st.nn.fc(paddle.randn([2, 6]), 4, activation="relu").shape == [2, 4]
    assert st.nn.conv2d(paddle.randn([1, 3, 8, 8]), 6, 3,
                        padding=1).shape == [1, 6, 8, 8]
    assert st.nn.conv2d_transpose(paddle.randn([1, 3, 8, 8]), 4,
                                  output_size=[16, 16],
                                  stride=2).shape == [1, 4, 16, 16]
    assert st.nn.layer_norm(paddle.randn([2, 5])).shape == [2, 5]
    assert st.nn.batch_norm(paddle.randn([4, 6]),
                            bias_attr=False).shape == [4, 6]
    sn = st.nn.spectral_norm(paddle.randn([8, 6]))
    assert float(np.linalg.svd(sn.numpy(), compute_uv=False)[0]) < 1.3
    assert st.nn.row_conv(paddle.randn([2, 5, 4]), 2).shape == [2, 5, 4]
    assert st.nn.nce(paddle.randn([4, 8]),
                     paddle.to_tensor(np.array([[1], [2], [3], [0]])),
                     10).shape == [4, 1]
    # control flow on concrete values
    assert st.nn.cond(paddle.to_tensor(np.array(True)),
                      lambda: 1, lambda: 2) == 1
    assert st.nn.switch_case(paddle.to_tensor(np.array(1)),
                             {0: lambda: "a", 1: lambda: "b"}) == "b"
    out = st.nn.while_loop(lambda c: c.numpy() < 3, lambda c: [c + 1],
                           [paddle.to_tensor(np.array(0))])
    assert int(out[0].numpy()) == 3
    # padded sequence ops honor lengths
    lens = paddle.to_tensor(np.array([2, 4]))
    sm = st.nn.sequence_softmax(paddle.randn([2, 4, 3]), lengths=lens)
    np.testing.assert_allclose(sm.numpy()[0, :2].sum(0), 1.0, rtol=1e-5)
    np.testing.assert_allclose(sm.numpy()[0, 2:], 0, atol=1e-6)
    x = np.random.rand(2, 4, 3).astype("float32")
    last = st.nn.sequence_last_step(paddle.to_tensor(x), lengths=lens)
    np.testing.assert_allclose(last.numpy()[0], x[0, 1])
    np.testing.assert_allclose(last.numpy()[1], x[1, 3])
    assert st.nn.sequence_expand(paddle.randn([2, 3]),
                                 paddle.randn([2, 5, 3])).shape == [2, 5, 3]
    assert st.nn.sequence_conv(paddle.randn([2, 6, 4]), 5).shape == [2, 6, 5]
    # prelu channel count follows data_format
    assert st.nn.prelu(paddle.randn([1, 6, 6, 4]), mode="channel",
                       data_format="NHWC").shape == [1, 6, 6, 4]


def test_sparse_conv2d_and_new_packages():
    """Round-3 final parity batch: sparse 2-D convs (padding proven against
    dense conv — review fix: depth axis must not be padded),
    sparse.nn.functional module, device package imports, audio.backends
    WAV decode (8/16-bit), distributed.passes registry, tensorrt guidance,
    cpp_extension setup()."""
    import json
    import tempfile
    import wave

    import numpy as np

    import jax

    import paddle_tpu as paddle
    import paddle_tpu.sparse as sparse

    paddle.seed(0)
    d = np.zeros((1, 5, 5, 2), "float32")
    d[0, 2, 2] = [1.0, 2.0]
    st = sparse.to_sparse_coo(paddle.to_tensor(d), sparse_dim=3)
    c = sparse.nn.Conv2D(2, 3, 3, padding=1)
    out = c(st).to_dense().numpy()
    ref = np.asarray(jax.lax.conv_general_dilated(
        d, np.asarray(c.weight.numpy()), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))) + c.bias.numpy()
    np.testing.assert_allclose(out, ref, atol=1e-4)
    s = sparse.nn.SubmConv2D(2, 3, 3)
    assert s(st).nnz() == st.nnz()
    import paddle_tpu.sparse.nn.functional as SF

    assert SF.conv2d(st, c.weight, c.bias, padding=1).shape == [1, 5, 5, 3]

    # importable device package, both styles
    import paddle_tpu.device.cuda as C

    assert C.device_count() == 0  # cpu-only host
    assert paddle.device.get_device().startswith("cpu")

    # wave backend: 16-bit and centered 8-bit
    tmp = tempfile.mktemp(suffix=".wav")
    with wave.open(tmp, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(1)
        w.setframerate(8000)
        w.writeframes(bytes([128, 255, 0, 128]))
    sig, sr = paddle.audio.backends.load(tmp)
    np.testing.assert_allclose(sig.numpy().reshape(-1)[0], 0.0, atol=1e-6)
    np.testing.assert_allclose(sig.numpy().reshape(-1)[2], -1.0, atol=1e-6)
    assert sr == 8000
    assert paddle.audio.backends.get_current_backend() == "wave_backend"

    pm = paddle.distributed.passes.PassManager(
        [paddle.distributed.passes.new_pass("auto_parallel_recompute")])
    assert pm.apply() == ["recompute"]
    with pytest.raises(NotImplementedError):
        paddle.distributed.passes.new_pass("unknown_pass").apply()
    with pytest.raises(RuntimeError, match="StableHLO"):
        paddle.tensorrt.convert(None)

    # inference tail
    t = paddle.inference.Tensor("x")
    t.copy_from_cpu([[1.0, 2.0]])
    assert t.shape() == [1, 2]
    mf = tempfile.mktemp()
    open(mf, "w").write("x")
    paddle.inference.convert_to_mixed_precision(
        mf, None, mf + ".mixed", None, mixed_precision=2)
    assert json.load(open(mf + ".mixed.precision.json"))[
        "mixed_precision"] == 2

    # setup() builds real extensions with unique keys
    from paddle_tpu.utils import cpp_extension as ce

    s1 = tempfile.mktemp(suffix=".cc")
    open(s1, "w").write('extern "C" int f1() { return 21; }')
    mods = ce.setup(name="one_ext", ext_modules=[ce.CppExtension([s1])])
    assert mods["one_ext"].f1() == 21


def test_incubate_autograd_and_minimizers():
    """incubate.autograd vjp/jvp/Jacobian/forward_grad (forward-over-
    reverse) + functional BFGS/L-BFGS minimizers + fused functional tail."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.incubate as inc

    paddle.seed(0)
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    _, g = inc.autograd.vjp(lambda t: (t ** 2).sum(), x)
    np.testing.assert_allclose(g.numpy(), [2.0, 4.0], rtol=1e-6)
    _, t = inc.autograd.jvp(lambda t: (t ** 2).sum(), x,
                            v=paddle.to_tensor(
                                np.array([1.0, 0.0], "float32")))
    assert abs(float(t.numpy()) - 2.0) < 1e-6
    xt = paddle.to_tensor(np.array([1.0, 2.0], "float32"),
                          stop_gradient=False)
    fg = inc.autograd.forward_grad(xt ** 2, xt)
    np.testing.assert_allclose(fg.numpy(), [2.0, 4.0], rtol=1e-5)

    target = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    for minimize in (inc.optimizer.functional.minimize_lbfgs,
                     inc.optimizer.functional.minimize_bfgs):
        conv, iters, xs, fx, gx = minimize(
            lambda t: ((t - target) ** 2).sum(),
            paddle.to_tensor(np.array([5.0, -3.0], "float32")))
        assert bool(conv.numpy())
        np.testing.assert_allclose(xs.numpy(), [1.0, 2.0], atol=1e-3)

    F = inc.nn.functional
    a = paddle.randn([2, 4])
    w = paddle.randn([4, 3])
    b = paddle.randn([3])
    np.testing.assert_allclose(
        F.fused_matmul_bias(a, w, b).numpy(),
        a.numpy() @ w.numpy() + b.numpy(), rtol=1e-5, atol=1e-6)
    vm = F.variable_length_memory_efficient_attention(
        paddle.randn([2, 2, 5, 8]), paddle.randn([2, 2, 5, 8]),
        paddle.randn([2, 2, 5, 8]), paddle.to_tensor(np.array([5, 3])),
        paddle.to_tensor(np.array([5, 3])))
    assert vm.shape == [2, 2, 5, 8]


def test_identity_loss_reduction_codes():
    """identity_loss reference semantics (ADVICE r3): 0=sum, 1=mean,
    2=none, matching the string forms."""
    import paddle_tpu.incubate as inc

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    for red, want in [("sum", 6.0), (0, 6.0), ("mean", 2.0), (1, 2.0)]:
        assert float(inc.identity_loss(x, red).numpy()) == want
    for red in ("none", 2):
        np.testing.assert_array_equal(inc.identity_loss(x, red).numpy(),
                                      x.numpy())


def test_static_executor_reads_live_params():
    """Executor.run honors parameter values CURRENT at replay time
    (reference executor scope semantics, executor.py:1234) — weights
    updated after recording must flow into the next run, not the values
    baked when the program was recorded (VERDICT r3 #8)."""
    import paddle_tpu.static as static

    paddle.seed(0)
    net = nn.Linear(4, 2)
    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4])
            y = net(x)
        exe = static.Executor()
        feed = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        out1, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
        # update the weights AFTER recording
        net.weight.set_value(np.zeros((4, 2), np.float32))
        net.bias.set_value(np.full((2,), 7.0, np.float32))
        out2, = exe.run(prog, feed={"x": feed}, fetch_list=[y])
    finally:
        static.disable_static()
    assert not np.allclose(out1, out2)
    np.testing.assert_allclose(out2, np.full((3, 2), 7.0), rtol=1e-6)


def test_optimizer_step_raises_inside_recording():
    """optimizer.step() inside program_guard raises with TrainStep guidance
    instead of silently mutating params the recorded graph never sees."""
    import paddle_tpu.static as static
    import paddle_tpu.optimizer as opt

    net = nn.Linear(2, 2)
    o = opt.SGD(0.1, parameters=net.parameters())
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    loss = (net(x) ** 2).mean()
    loss.backward()
    prog = static.Program()
    with static.program_guard(prog):
        with pytest.raises(RuntimeError, match="TrainStep"):
            o.step()
    o.step()  # outside the region it works
    o.clear_grad()


def test_save_inference_model_bakes_current_weights(tmp_path):
    """save_inference_model exports the weights CURRENT at save time — the
    same values Executor.run was just validating — not the record-time
    captures (review: executor/export divergence)."""
    import paddle_tpu.static as static

    paddle.seed(0)
    net = nn.Linear(3, 2)
    static.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 3])
            y = net(x)
        exe = static.Executor()
        # weights change AFTER recording, BEFORE saving
        net.weight.set_value(np.zeros((3, 2), np.float32))
        net.bias.set_value(np.full((2,), 5.0, np.float32))
        static.save_inference_model(str(tmp_path / "m"), [x], [y], exe,
                                    program=prog)
        pred, feed_names, n_fetch = static.load_inference_model(
            str(tmp_path / "m"), exe)
    finally:
        static.disable_static()
    feed = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    out = pred.run([feed])[0]
    np.testing.assert_allclose(np.asarray(out), np.full((4, 2), 5.0),
                               rtol=1e-6)


def test_strings_tokenizer_surface():
    """strings.py beyond the reference's 4 kernels: the tokenizer-adjacent
    batch ops (strip/split/regex_replace/…) on host StringTensors."""
    import numpy as np
    from paddle_tpu import strings as S

    t = S.StringTensor([["  Hello World  ", "FOO bar"],
                        ["", "  a  b  c "]])
    stripped = S.strip(t)
    assert stripped[0][0] == "Hello World"
    assert S.lstrip(t)[0][0] == "Hello World  "
    assert S.rstrip(t)[0][0] == "  Hello World"
    np.testing.assert_array_equal(S.length(stripped),
                                  [[11, 7], [0, 7]])  # "a  b  c"
    toks = S.split(stripped)
    assert toks[0, 0] == ["Hello", "World"]
    assert toks[1, 0] == []
    assert S.join(S.StringTensor(["a", "b", "c"]), "-") == "a-b-c"
    cat = S.concat(S.StringTensor(["x", "y"]), S.StringTensor(["1", "2"]))
    assert cat.tolist() == ["x1", "y2"]
    assert S.concat(S.StringTensor(["x"]), "!").tolist() == ["x!"]
    rep = S.regex_replace(t, r"\s+", " ")
    assert rep[1][1] == " a b c "
    np.testing.assert_array_equal(
        S.startswith(S.StringTensor(["abc", "bcd"]), "ab"), [True, False])
    np.testing.assert_array_equal(
        S.endswith(S.StringTensor(["abc", "bcd"]), "cd"), [False, True])
    wt = S.whitespace_tokenize(t, lowercase=True)
    assert wt[0, 0] == ["hello", "world"]
    # shape-mismatch concat fails loudly
    import pytest
    with pytest.raises(ValueError):
        S.concat(S.StringTensor(["a"]), S.StringTensor(["a", "b"]))


def test_masked_multihead_attention_and_blha():
    """incubate serving entries (r5): masked_multihead_attention's core
    decode-step contract vs a dense reference; blha_get_max_len."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.incubate.nn import functional as IF

    rng = np.random.RandomState(0)
    B, H, T, D = 2, 2, 8, 4
    cache = np.zeros((2, B, H, T, D), np.float32)
    # preload 3 cached positions per row
    cache[:, :, :, :3] = rng.randn(2, B, H, 3, D)
    pos = np.array([[3], [3]], np.int64)
    x = rng.randn(B, 3 * H * D).astype(np.float32)
    out, new_cache = IF.masked_multihead_attention(
        paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
        sequence_lengths=paddle.to_tensor(pos))
    assert out.shape == [B, H * D]
    qkv = x.reshape(B, 3, H, D)
    kc = cache[0].copy()
    vc = cache[1].copy()
    kc[:, :, 3] = qkv[:, 1]
    vc[:, :, 3] = qkv[:, 2]
    np.testing.assert_allclose(np.asarray(new_cache.numpy()[0]), kc,
                               rtol=1e-6)
    # dense reference over the 4 live positions
    q = qkv[:, 0]
    s = np.einsum("bhd,bhtd->bht", q, kc[:, :, :4]) / np.sqrt(D)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bht,bhtd->bhd", p, vc[:, :, :4]).reshape(B, H * D)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)

    enc, dec = IF.blha_get_max_len(
        paddle.to_tensor(np.array([5, 9])),
        paddle.to_tensor(np.array([2, 1])), paddle.to_tensor(np.array([2])))
    assert int(enc.numpy()[0]) == 9 and int(dec.numpy()[0]) == 2

    with pytest.raises(NotImplementedError, match="ContinuousBatchEngine"):
        IF.block_multihead_attention()
    with pytest.raises(NotImplementedError, match="rotary"):
        IF.masked_multihead_attention(
            paddle.to_tensor(x), cache_kv=paddle.to_tensor(cache),
            rotary_tensor=paddle.to_tensor(np.zeros((1,), np.float32)))
