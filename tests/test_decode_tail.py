"""Fused decode-tail megakernels (ops/pallas/decode_tail): kernel-level
parity against the discrete reference ops, and end-to-end
TOKEN-IDENTITY of the fused S=1 decode path vs the discrete kernels —
the acceptance contract of the FLAGS_use_fused_decode_tail flag. All of
it runs in interpret mode on CPU (tier-1; no TPU needed)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                     fused_decode_supported)
from paddle_tpu.ops.pallas import decode_tail, fused_norm
from paddle_tpu.utils.flags import get_flags, set_flags


@pytest.fixture
def fused_flag():
    """Restore the flag and the once-per-shape announce dedupe set."""
    prev = get_flags("FLAGS_use_fused_decode_tail")[
        "FLAGS_use_fused_decode_tail"]
    seen = set(decode_tail._announced)
    yield
    set_flags({"FLAGS_use_fused_decode_tail": prev})
    decode_tail._announced.clear()
    decode_tail._announced.update(seen)


def _fusable_config(**kw):
    """Smallest shape that passes the structural gate: head_dim 128,
    hidden % 128 == 0."""
    base = dict(vocab_size=128, hidden_size=256, intermediate_size=512,
                num_hidden_layers=2, num_attention_heads=2,
                num_key_value_heads=1, max_position_embeddings=256,
                use_flash_attention=False, dtype="float32")
    base.update(kw)
    return LlamaConfig(**base)


# ---------------------------------------------------------------------------
# kernel-level parity
# ---------------------------------------------------------------------------

def _rope_ref_rows(x, cos, sin):
    """rope_ref specialized to per-row tables: x [B, n, D], cos/sin
    [B, D]."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rot = jnp.concatenate([-x2, x1], -1)
    return (x.astype(jnp.float32) * cos[:, None, :]
            + rot.astype(jnp.float32) * sin[:, None, :]).astype(x.dtype)


def test_fused_qkv_rope_matches_discrete():
    rng = np.random.RandomState(0)
    B, hidden, H, hk, D = 4, 256, 2, 1, 128
    eps = 1e-6
    x = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    wn = jnp.asarray(rng.randn(hidden), jnp.float32)
    wq = jnp.asarray(rng.randn(hidden, H * D) * 0.05, jnp.float32)
    wk = jnp.asarray(rng.randn(hidden, hk * D) * 0.05, jnp.float32)
    wv = jnp.asarray(rng.randn(hidden, hk * D) * 0.05, jnp.float32)
    cos = jnp.asarray(rng.randn(B, D), jnp.float32)
    sin = jnp.asarray(rng.randn(B, D), jnp.float32)

    q, k, v = decode_tail.fused_qkv_rope(x, wn, wq, wk, wv, cos, sin,
                                         eps, H, hk, D, interpret=True)

    normed = fused_norm._rmsnorm_ref(x, wn, eps)
    qr = _rope_ref_rows((normed @ wq).reshape(B, H, D), cos, sin)
    kr = _rope_ref_rows((normed @ wk).reshape(B, hk, D), cos, sin)
    vr = normed @ wv
    np.testing.assert_allclose(np.asarray(q), qr.reshape(B, H * D),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(k), kr.reshape(B, hk * D),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(vr),
                               rtol=1e-5, atol=1e-5)


def test_fused_epilogue_matches_discrete():
    rng = np.random.RandomState(1)
    B, width, hidden = 4, 256, 256
    eps = 1e-6
    attn = jnp.asarray(rng.randn(B, width), jnp.float32)
    wo = jnp.asarray(rng.randn(width, hidden) * 0.05, jnp.float32)
    res = jnp.asarray(rng.randn(B, hidden), jnp.float32)
    wn = jnp.asarray(rng.randn(hidden), jnp.float32)
    normed, new_res = decode_tail.fused_epilogue(attn, wo, res, wn, eps,
                                                 interpret=True)
    h_ref = attn @ wo + res
    np.testing.assert_allclose(np.asarray(new_res), np.asarray(h_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(normed),
        np.asarray(fused_norm._rmsnorm_ref(h_ref, wn, eps)),
        rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# end-to-end token identity (THE tier-1 parity gate)
# ---------------------------------------------------------------------------

def _gen(cfg, ids, **kw):
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    return np.asarray(model.generate(ids, **kw).numpy())


def test_generate_dense_token_identical(fused_flag):
    cfg = _fusable_config()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (2, 8)))
    set_flags({"FLAGS_use_fused_decode_tail": False})
    ref = _gen(cfg, ids, max_new_tokens=12)
    decode_tail._announced.clear()
    set_flags({"FLAGS_use_fused_decode_tail": True})
    fused = _gen(cfg, ids, max_new_tokens=12)
    # the fused path must have actually activated — a silently declined
    # gate would make this test vacuous
    assert any(s[0] == "dense" for s in decode_tail._announced)
    np.testing.assert_array_equal(ref, fused)


def test_generate_paged_token_identical(fused_flag):
    cfg = _fusable_config()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 8)))
    set_flags({"FLAGS_use_fused_decode_tail": False})
    ref = _gen(cfg, ids, max_new_tokens=10, paged=True, page_size=16)
    decode_tail._announced.clear()
    set_flags({"FLAGS_use_fused_decode_tail": True})
    fused = _gen(cfg, ids, max_new_tokens=10, paged=True, page_size=16)
    assert any(s[0] == "paged" for s in decode_tail._announced)
    np.testing.assert_array_equal(ref, fused)


def test_generate_ragged_token_identical(fused_flag):
    """attention_mask path: per-row RoPE positions (row_pos) must gather
    the same table rows the discrete per-row rope reads."""
    cfg = _fusable_config()
    rng = np.random.RandomState(2)
    ids = rng.randint(1, 128, (3, 10))
    am = np.ones((3, 10), np.int64)
    am[0, 6:] = 0          # right-padded row
    am[2, :3] = 0          # left-padded row
    kw = dict(max_new_tokens=9, attention_mask=paddle.to_tensor(am),
              eos_token_id=5)
    set_flags({"FLAGS_use_fused_decode_tail": False})
    ref = _gen(cfg, paddle.to_tensor(ids), **kw)
    decode_tail._announced.clear()
    set_flags({"FLAGS_use_fused_decode_tail": True})
    fused = _gen(cfg, paddle.to_tensor(ids), **kw)
    assert decode_tail._announced
    np.testing.assert_array_equal(ref, fused)


def test_engine_token_identical(fused_flag):
    """The ContinuousBatchEngine decode step — the path the serving
    tier multiplies across workers — is token-identical under the
    flag."""
    from paddle_tpu.serving import ContinuousBatchEngine

    cfg = _fusable_config()

    def run():
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        eng = ContinuousBatchEngine(model, max_batch=4, max_len=64,
                                    page_size=16)
        rng = np.random.RandomState(1)
        for i in range(6):
            eng.add_request(rng.randint(0, 128, (4 + i,)), 8)
        return {rid: toks.tolist()
                for rid, toks in sorted(eng.run_until_done().items())}

    set_flags({"FLAGS_use_fused_decode_tail": False})
    ref = run()
    decode_tail._announced.clear()
    set_flags({"FLAGS_use_fused_decode_tail": True})
    fused = run()
    assert decode_tail._announced
    assert ref == fused


# ---------------------------------------------------------------------------
# gate behavior
# ---------------------------------------------------------------------------

def _decode_layer_and_cache(cfg, b=2):
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    layer = model.llama.layers[0]
    d = layer.self_attn.head_dim
    hk = cfg.num_key_value_heads
    cache = {"k": jnp.zeros((b, 16, hk, d), jnp.float32),
             "v": jnp.zeros((b, 16, hk, d), jnp.float32), "pos": 4}
    hidden = paddle.to_tensor(
        np.zeros((b, 1, cfg.hidden_size), np.float32))
    cos, sin = model.llama._rope(16)
    return layer, hidden, cache, cos


def test_gate_accepts_fusable_shape(fused_flag):
    set_flags({"FLAGS_use_fused_decode_tail": True})
    layer, hidden, cache, cos = _decode_layer_and_cache(_fusable_config())
    assert fused_decode_supported(layer, hidden, cache, cos)


def test_gate_declines_flag_off(fused_flag):
    set_flags({"FLAGS_use_fused_decode_tail": False})
    layer, hidden, cache, cos = _decode_layer_and_cache(_fusable_config())
    assert not fused_decode_supported(layer, hidden, cache, cos)


@pytest.mark.parametrize("kw", [
    dict(num_attention_heads=4, num_key_value_heads=2),  # head_dim 64
    dict(qk_norm=True),                                  # Qwen3-style
    dict(attention_bias=True),                           # Qwen2-style
    dict(partial_rotary_factor=0.5),                     # partial rope
])
def test_gate_declines_unsupported_structure(fused_flag, kw):
    set_flags({"FLAGS_use_fused_decode_tail": True})
    layer, hidden, cache, cos = _decode_layer_and_cache(
        _fusable_config(**kw))
    assert not fused_decode_supported(layer, hidden, cache, cos)


def test_unsupported_model_still_generates(fused_flag):
    """Flag on + a declining structure = the discrete path, silently
    and correctly (exact-parity fallback)."""
    cfg = _fusable_config(attention_bias=True)
    ids = paddle.to_tensor(
        np.random.RandomState(3).randint(0, 128, (2, 6)))
    set_flags({"FLAGS_use_fused_decode_tail": False})
    ref = _gen(cfg, ids, max_new_tokens=8)
    decode_tail._announced.clear()
    set_flags({"FLAGS_use_fused_decode_tail": True})
    out = _gen(cfg, ids, max_new_tokens=8)
    assert not decode_tail._announced
    np.testing.assert_array_equal(ref, out)


def test_prefill_never_fused(fused_flag):
    set_flags({"FLAGS_use_fused_decode_tail": True})
    layer, _, cache, cos = _decode_layer_and_cache(_fusable_config())
    prompt = paddle.to_tensor(np.zeros((2, 4, 256), np.float32))  # S=4
    assert not fused_decode_supported(layer, prompt, cache, cos)


# ---------------------------------------------------------------------------
# audit surface
# ---------------------------------------------------------------------------

def test_fused_step_event_recorded(fused_flag):
    from paddle_tpu.observability import flightrecorder as frec

    rec = frec.get_recorder()
    rec.clear()
    rec.enabled = True  # not enable(): skip the compile-events hook
    try:
        set_flags({"FLAGS_use_fused_decode_tail": True})
        decode_tail._announced.clear()
        cfg = _fusable_config()
        ids = paddle.to_tensor(
            np.random.RandomState(4).randint(0, 128, (2, 6)))
        _gen(cfg, ids, max_new_tokens=4)
        evs = rec.events(kind="kernel.fused_step")
        assert evs and evs[0]["head_dim"] == 128
        assert evs[0]["layout"] == "dense"
        # announce dedupes per shape: one event, not one per layer/step
        assert len(evs) == 1
    finally:
        rec.enabled = False
        rec.clear()
