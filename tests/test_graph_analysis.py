"""Graph-rule (jaxpr-level) analysis tests — the second pdlint layer.

Three layers, mirroring tests/test_static_analysis.py:

1. **Known-bad fixtures** — each graph rule has a tiny program carrying
   exactly the hazard it exists for (indivisible spec, bf16→f32 upcast,
   data-dependent shape, baked const, dtype-lying OpDecl) and must
   produce exactly the expected finding; known-good twins produce zero.
2. **Preflight** — ``Engine.preflight()`` rejects an indivisible
   sharding / over-budget model with a structured ``PreflightReport``
   instead of a compile-time crash, and admits the clean build.
3. **The tier-1 gate** — ``scripts/pdlint.py --json --baseline
   .pdlint_baseline.json --graph`` exits 0 over the fast zoo set; the
   zoo-wide sweep (``PDLINT_GRAPH_SCOPE=full``) is ``slow``-marked.
"""
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import analysis
from paddle_tpu.analysis.graph import (
    PreflightError, cost, dtype_flow, op_dtypes, preflight_model, retrace,
    shard_spec, solver, trace_fn, trace_layer, spec, zoo,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# tracer harness
# ---------------------------------------------------------------------------

def test_trace_fn_captures_jaxpr():
    t = trace_fn(lambda x: x * 2.0, spec((4,), jnp.float32))
    assert t.ok and t.error is None
    assert t.n_data_inputs == 1
    assert len(t.closed_jaxpr.jaxpr.eqns) >= 1


def test_trace_fn_captures_error_instead_of_raising():
    t = trace_fn(lambda x: jnp.nonzero(x)[0], spec((8,), jnp.float32))
    assert not t.ok
    assert t.error is not None


def test_trace_layer_params_are_invars_not_consts():
    """The functional state must ride as invars (so shard specs map onto
    them) — a Layer whose weights trace as baked consts would defeat
    both the shard-spec rule and the retrace const check."""
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    model = LlamaForCausalLM(LlamaConfig.tiny(dtype="bfloat16"))
    t = trace_layer(model, spec((1, 8), jnp.int32))
    assert t.ok
    assert t.param_names == sorted(t.param_avals)
    n_invars = len(t.closed_jaxpr.jaxpr.invars)
    # params + rng key + input_ids
    assert n_invars == len(t.param_names) + 1 + 1
    assert t.param_bytes() > 0
    # bf16 build: the bulk of the state is 2-byte
    emb = t.param_avals["llama.embed_tokens.weight"]
    assert str(emb.dtype) == "bfloat16"


# ---------------------------------------------------------------------------
# shard-spec: annotation validity
# ---------------------------------------------------------------------------

def test_shard_spec_indivisible_dim_one_finding():
    msgs = shard_spec.check_partition_spec(
        ("mp", None), {"dp": 2, "mp": 4}, (6, 8), what="param w")
    assert len(msgs) == 1
    assert "not divisible" in msgs[0]


def test_shard_spec_unknown_axis():
    msgs = shard_spec.check_partition_spec(
        ("tp", None), {"dp": 2}, (8, 8))
    assert len(msgs) == 1 and "unknown mesh axis" in msgs[0]


def test_shard_spec_double_sharded_axis():
    msgs = shard_spec.check_partition_spec(
        ("mp", "mp"), {"mp": 2}, (8, 8))
    assert len(msgs) == 1 and "assigned to both" in msgs[0]


def test_shard_spec_valid_spec_zero_findings():
    assert shard_spec.check_partition_spec(
        ("dp", ("mp",)), {"dp": 2, "mp": 4}, (8, 16)) == []


def test_shard_spec_over_rank():
    msgs = shard_spec.check_partition_spec(
        ("dp", "mp", None), {"dp": 2, "mp": 2}, (8,))
    assert len(msgs) == 1 and "rank" in msgs[0]


def test_check_placements_against_process_mesh():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.placements import Replicate, Shard

    mesh = dist.ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
    # dim 1 of size 6 over mp=2: divisible -> clean
    assert shard_spec.check_placements(
        [Replicate(), Shard(1)], mesh, (4, 6)) == []
    # dim 1 of size 5: indivisible -> exactly one finding
    msgs = shard_spec.check_placements([Replicate(), Shard(1)], mesh, (4, 5))
    assert len(msgs) == 1 and "not divisible" in msgs[0]
    # Shard dim out of range
    msgs = shard_spec.check_placements([Shard(3)], mesh, (4, 5))
    assert len(msgs) == 1 and "invalid for rank" in msgs[0]


# ---------------------------------------------------------------------------
# shard-spec: GSPMD-lite propagation
# ---------------------------------------------------------------------------

def _propagated(fn, in_specs, axis_sizes, *arg_specs):
    t = trace_fn(fn, *arg_specs)
    assert t.ok
    return shard_spec.propagate(t, in_specs, axis_sizes)


def test_propagate_reshape_split_minor_flags_reshard():
    """Merging a sharded minor dim away forces an all-to-all: the
    known-bad propagation fixture."""
    finds = _propagated(lambda x: x.reshape(128), {0: (None, "mp")},
                        {"mp": 2}, spec((8, 16), jnp.float32))
    assert len(finds) == 1
    path, prim, msg = finds[0]
    assert prim == "reshape" and "reshard" in msg or "all-to-all" in msg


def test_propagate_reshape_major_survives():
    finds = _propagated(lambda x: x.reshape(2, 4, 16), {0: ("mp", None)},
                        {"mp": 2}, spec((8, 16), jnp.float32))
    assert finds == []


def test_propagate_elementwise_conflict():
    finds = _propagated(lambda x, y: x + y,
                        {0: ("mp", None), 1: ("dp", None)},
                        {"mp": 2, "dp": 2},
                        spec((8, 8), jnp.float32), spec((8, 8), jnp.float32))
    assert len(finds) == 1
    assert "reshard" in finds[0][2]


def test_propagate_elementwise_axis_reuse_conflict():
    """One mesh axis landing on two dims of the merged operand layout is
    equally impossible — GSPMD strips it from one dim."""
    finds = _propagated(lambda x, y: x + y,
                        {0: ("mp", None), 1: (None, "mp")}, {"mp": 2},
                        spec((8, 8), jnp.float32), spec((8, 8), jnp.float32))
    assert len(finds) == 1


def test_propagate_matched_elementwise_clean():
    finds = _propagated(lambda x, y: x * y,
                        {0: ("mp", None), 1: ("mp", None)}, {"mp": 2},
                        spec((8, 8), jnp.float32), spec((8, 8), jnp.float32))
    assert finds == []


def test_propagate_dot_contracting_mismatch():
    def f(x, y):
        return x @ y

    finds = _propagated(f, {0: (None, "mp"), 1: ("dp", None)},
                        {"mp": 2, "dp": 2},
                        spec((4, 8), jnp.float32), spec((8, 16), jnp.float32))
    assert len(finds) == 1
    assert finds[0][1] == "dot_general"
    assert "contracting" in finds[0][2]


def test_propagate_dot_matched_contracting_clean():
    """Both contracting dims on the same axis: GSPMD all-reduces the
    partial output — expected Megatron row-parallel behavior, no
    finding."""
    finds = _propagated(lambda x, y: x @ y,
                        {0: (None, "mp"), 1: ("mp", None)}, {"mp": 2},
                        spec((4, 8), jnp.float32), spec((8, 16), jnp.float32))
    assert finds == []


def test_propagate_dot_batch_dim_mismatch_flags():
    """Batch dims sharded over different axes: one operand re-tiles
    before the batched matmul — the case that used to fall through."""
    def bmm(a, b):
        return jax.lax.dot_general(a, b, (((2,), (1,)), ((0,), (0,))))

    finds = _propagated(bmm, {0: ("dp", None, None), 1: ("mp", None, None)},
                        {"dp": 2, "mp": 2},
                        spec((4, 8, 16), jnp.float32),
                        spec((4, 16, 8), jnp.float32))
    assert len(finds) == 1
    assert finds[0][1] == "dot_general" and "batch dims" in finds[0][2]


def test_propagate_dot_batch_dims_matched_clean():
    def bmm(a, b):
        return jax.lax.dot_general(a, b, (((2,), (1,)), ((0,), (0,))))

    finds = _propagated(bmm, {0: ("dp", None, None), 1: ("dp", None, None)},
                        {"dp": 2},
                        spec((4, 8, 16), jnp.float32),
                        spec((4, 16, 8), jnp.float32))
    assert finds == []


def _kv_scatter(pages, idx, new):
    # the paged-KV write shape: pages [heads, n_pages, d], update rows
    # landing at traced page indices
    return pages.at[:, idx].set(new)


def test_propagate_scatter_paged_kv_pattern_clean():
    """Scatter into unsharded page slots of a head-sharded pool — the
    engine's KV write path — keeps the operand layout, zero findings."""
    t = trace_fn(_kv_scatter, spec((4, 16, 8), jnp.float32),
                 spec((3,), jnp.int32), spec((4, 3, 8), jnp.float32))
    assert any(e.primitive.name.startswith("scatter")
               for e in t.closed_jaxpr.jaxpr.eqns)
    assert shard_spec.propagate(t, {0: ("mp", None, None)}, {"mp": 2}) == []


def test_propagate_scatter_into_sharded_dim_flags():
    def f(pages, idx, new):
        return pages.at[idx].set(new)

    t = trace_fn(f, spec((16, 8), jnp.float32), spec((3,), jnp.int32),
                 spec((3, 8), jnp.float32))
    finds = shard_spec.propagate(t, {0: ("mp", None)}, {"mp": 2})
    assert len(finds) == 1
    assert finds[0][1].startswith("scatter")
    assert "all-to-all" in finds[0][2]


def test_propagate_gather_vocab_parallel_is_expected_collective():
    """An embedding lookup into a vocab-sharded table is the PLANNED
    Megatron collective: an expected event with a byte charge for the
    solver — never a lint finding."""
    t = trace_fn(lambda w, ids: w[ids], spec((64, 16), jnp.float32),
                 spec((2, 8), jnp.int32))
    events = shard_spec.propagate_events(t, {0: ("mp", None)}, {"mp": 2})
    assert len(events) == 1
    e = events[0]
    assert e.expected and e.primitive == "gather" and e.bytes > 0
    assert shard_spec.propagate(t, {0: ("mp", None)}, {"mp": 2}) == []
    # hidden-sharded table: the lookup is local, nothing to charge
    assert shard_spec.propagate_events(
        t, {0: (None, "mp")}, {"mp": 2}) == []


def test_propagate_one_sided_contraction_charged_not_flagged():
    """x @ W with only W's contracting dim sharded: GSPMD slices the
    replicated side locally and all-reduces the partial output — an
    expected charge (the cost of 'row' plans), not a finding."""
    t = trace_fn(lambda x, w: x @ w, spec((4, 8), jnp.float32),
                 spec((8, 16), jnp.float32))
    events = shard_spec.propagate_events(t, {1: ("mp", None)}, {"mp": 2})
    assert len(events) == 1
    assert events[0].expected and "all-reduce" in events[0].message
    assert shard_spec.propagate(t, {1: ("mp", None)}, {"mp": 2}) == []


def test_zoo_sharded_llama_layout_clean():
    """The Megatron layout the zoo declares for llama must validate and
    propagate clean — this pins the mesh-divisibility choice (mp=2 over
    2 kv heads) the zoo comment documents."""
    e = zoo.entry("llama-sharded")
    t = zoo.traced("llama-sharded")
    assert t.ok
    in_specs = {}
    for name in t.param_names:
        aval = t.param_avals[name]
        sp = e.shard.spec_for(name, len(aval.shape))
        if sp is None:
            continue
        assert shard_spec.check_partition_spec(
            sp, e.shard.axis_sizes, aval.shape, what=name) == []
        in_specs[t.invar_index_of_param(name)] = \
            shard_spec.normalize_spec(sp, len(aval.shape))
    assert in_specs, "the layout matched no parameters"
    assert shard_spec.propagate(t, in_specs, e.shard.axis_sizes) == []


def test_zoo_sharded_llama_mp4_flags_attention_reshard():
    """Widening the same layout to mp=4 must flag: the per-param specs
    stay divisible (64 % 4 == 0) but splitting 2 kv heads over 4 shards
    makes the attention head reshape force an all-to-all — the hazard
    only the PROPAGATION walk can see, exactly the zoo comment's case."""
    e = zoo.entry("llama-sharded")
    t = zoo.traced("llama-sharded")
    axis_sizes = {"dp": 2, "mp": 4}
    in_specs = {}
    for name in t.param_names:
        aval = t.param_avals[name]
        sp = e.shard.spec_for(name, len(aval.shape))
        if sp is None:
            continue
        assert shard_spec.check_partition_spec(
            sp, axis_sizes, aval.shape, what=name) == []
        in_specs[t.invar_index_of_param(name)] = \
            shard_spec.normalize_spec(sp, len(aval.shape))
    finds = shard_spec.propagate(t, in_specs, axis_sizes)
    assert any(prim == "reshape" for _p, prim, _m in finds), finds


def test_check_spmd_notes_flags_lying_decl():
    class Lying:
        name = "fake_reduceish"
        spmd = "elementwise"

        @staticmethod
        def impl(x):
            return jnp.sum(x)

    class Honest:
        name = "fake_relu"
        spmd = "elementwise"

        @staticmethod
        def impl(x):
            return jnp.maximum(x, 0)

    problems = shard_spec.check_spmd_notes([Lying, Honest])
    assert len(problems) == 1
    assert problems[0][0] == "fake_reduceish"


# ---------------------------------------------------------------------------
# dtype-promotion
# ---------------------------------------------------------------------------

_F32_TABLE = jnp.ones((4,), jnp.float32)


def test_dtype_mix_with_independent_f32_table_one_finding():
    """THE bf16→f32 fixture: promotion (not the author) chooses f32
    where a bf16-derived value meets an f32 buffer."""
    def f(x):
        return x.astype(jnp.float32) * _F32_TABLE

    ups = dtype_flow.find_upcasts(trace_fn(f, spec((4,), jnp.bfloat16)))
    assert len(ups) == 1
    assert ups[0].kind == "mix" and ups[0].primitive == "mul"
    assert "promotion chose float32" in ups[0].message()


def test_dtype_direct_upcast_one_finding():
    def f(x, w):
        return jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    ups = dtype_flow.find_upcasts(trace_fn(
        f, spec((4, 8), jnp.bfloat16), spec((8, 4), jnp.bfloat16)))
    assert len(ups) == 1
    assert ups[0].kind == "direct" and ups[0].primitive == "dot_general"


def test_dtype_deliberate_island_zero_findings():
    """astype up → compute among derived values and weak scalars →
    astype down: the authored-island pattern (norms, softmax) must not
    flag."""
    def f(x):
        xf = x.astype(jnp.float32)
        v = jnp.mean(xf * xf) + 1e-6
        return (xf * jax.lax.rsqrt(v)).astype(jnp.bfloat16)

    assert dtype_flow.find_upcasts(
        trace_fn(f, spec((8,), jnp.bfloat16))) == []


def test_dtype_scalar_independent_never_flags():
    """A non-weak f32 *scalar* (np.float32 scale, -inf fill) joining a
    derived island carries no bytes and is not the reason the island is
    f32."""
    def f(x):
        return jnp.maximum(x.astype(jnp.float32) * np.float32(0.125),
                           np.float32(-np.inf))

    assert dtype_flow.find_upcasts(
        trace_fn(f, spec((8,), jnp.bfloat16))) == []


def test_dtype_bool_mask_convert_is_island_neutral():
    """int/bool→f32 converts (masks, one_hot) picked f32 to FOLLOW the
    island — not independent f32 bytes."""
    def f(x, m):
        s = x.astype(jnp.float32)
        return s + m.astype(jnp.float32)

    assert dtype_flow.find_upcasts(trace_fn(
        f, spec((8,), jnp.bfloat16), spec((8,), jnp.bool_))) == []


def test_dtype_allowlist_suppresses_primitive():
    def f(x):
        return x.astype(jnp.float32) * _F32_TABLE

    t = trace_fn(f, spec((4,), jnp.bfloat16))
    assert len(dtype_flow.find_upcasts(t)) == 1
    assert dtype_flow.find_upcasts(t, allow=("mul",)) == []


def test_dtype_mix_found_inside_pjit_sub_jaxpr():
    @jax.jit
    def inner(x):
        return x.astype(jnp.float32) * _F32_TABLE

    def f(x):
        return inner(x)

    ups = dtype_flow.find_upcasts(trace_fn(f, spec((4,), jnp.bfloat16)))
    assert len(ups) == 1
    assert "pjit" in ups[0].eqn_path


def test_zoo_fast_models_dtype_clean():
    """Known-good zoo builds produce zero dtype findings under their
    declared allowlists (rope's f32 tables are the documented island)."""
    for e in zoo.entries():
        if e.shard is not None:
            continue
        t = zoo.traced(e.name)
        assert t.ok, f"{e.name} does not trace: {t.error}"
        ups = dtype_flow.find_upcasts(t, allow=e.allow_upcast)
        assert ups == [], (
            f"{e.name}: {[u.message() for u in ups]}")


def test_whisper_encoder_pos_follows_model_dtype():
    """Regression for the finding this PR fixed: the sinusoidal encoder
    position table stayed float32 in a bf16 build and upcast every
    encoder activation at the stem."""
    from paddle_tpu.models.whisper import (WhisperConfig,
                                           WhisperForConditionalGeneration)

    m = WhisperForConditionalGeneration(WhisperConfig.tiny(dtype="bfloat16"))
    w = m.model.encoder_pos.weight
    assert str(w.dtype) in ("bfloat16", "paddle.bfloat16"), str(w.dtype)


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

def test_retrace_data_dependent_shape_one_finding():
    t = trace_fn(lambda x: jnp.nonzero(x)[0], spec((8,), jnp.float32))
    hazards = retrace.find_hazards(t)
    assert len(hazards) == 1
    key, msg = hazards[0]
    assert key == "trace-error"
    assert "data-dependent" in msg


def test_retrace_weak_scalar_const_flagged():
    c = jnp.asarray(2.0)  # weak f32 scalar — a closed-over Python number

    def f(x):
        return x * c

    hazards = retrace.find_hazards(trace_fn(f, spec((4,), jnp.float32)))
    assert len(hazards) == 1
    assert "weak-typed scalar" in hazards[0][1]


def test_retrace_large_const_flagged():
    big = jnp.zeros((1 << 19,), jnp.float32)  # 2 MiB baked table

    def f(x):
        return x + big[:4]

    hazards = retrace.find_hazards(trace_fn(f, spec((4,), jnp.float32)))
    assert len(hazards) == 1
    assert "baked into every specialization" in hazards[0][1]


def test_retrace_clean_fn_zero_findings():
    assert retrace.find_hazards(
        trace_fn(lambda x: x * 2.0, spec((4,), jnp.float32))) == []


def test_specialization_stats_hook():
    """The jit wiring: StaticFunction counts compiled specializations
    and live_specialization_findings turns a blow-up into a finding."""
    from paddle_tpu import jit as pjit

    @pjit.to_static
    def poly(x):
        return x * 2.0

    import paddle_tpu

    for n in (4, 8, 16):  # three shape buckets -> three specializations
        poly(paddle_tpu.ones([n]))
    stats = pjit.specialization_stats()
    name = [k for k in stats if "poly" in k]
    assert name and stats[name[0]] >= 3
    found = retrace.live_specialization_findings(threshold=3)
    assert any("poly" in n for n, _c in found)
    assert retrace.live_specialization_findings(threshold=10 ** 6) == []


# ---------------------------------------------------------------------------
# preflight-cost
# ---------------------------------------------------------------------------

def test_cost_dot_flops_exact():
    def f(x, w):
        return x @ w

    rep = cost.estimate(trace_fn(f, spec((4, 8), jnp.float32),
                                 spec((8, 16), jnp.float32)))
    assert rep.flops == 2 * 4 * 16 * 8
    assert rep.output_bytes == 4 * 16 * 4
    assert rep.eqns >= 1
    assert rep.peak_activation_bytes >= rep.output_bytes


def test_cost_llama_estimates_positive():
    t = zoo.traced("llama")
    rep = cost.estimate(t)
    assert rep.param_bytes == t.param_bytes() > 0
    assert rep.flops > 0 and rep.peak_activation_bytes > 0
    assert rep.total_resident_bytes() > rep.param_bytes


def test_kv_cache_bytes_formula():
    from paddle_tpu.models.llama import LlamaConfig, head_dim_of

    cfg = LlamaConfig.tiny(dtype="bfloat16")
    got = cost.kv_cache_bytes(cfg, max_batch=4, max_len=64)
    expect = (cfg.num_hidden_layers * 2 * cfg.num_key_value_heads * 4 * 64
              * head_dim_of(cfg) * 2)
    assert got == expect > 0


def test_kv_cache_bytes_non_causal_config_is_zero():
    class NoFields:
        pass

    assert cost.kv_cache_bytes(NoFields(), 4, 64) == 0


# ---------------------------------------------------------------------------
# op-dtypes honesty
# ---------------------------------------------------------------------------

def test_op_dtypes_flags_upcasting_and_rejecting_decls():
    class Upcaster:
        name = "fake_upcaster"
        dtypes = ("float32", "bfloat16")

        @staticmethod
        def impl(x):
            return x.astype(jnp.float32) * 2

    class Rejecter:
        name = "fake_rejecter"
        dtypes = ("float32", "float16")

        @staticmethod
        def impl(x):
            if x.dtype == jnp.float16:
                raise TypeError("no f16")
            return x

    class Honest:
        name = "fake_honest"
        dtypes = ("float32", "bfloat16")

        @staticmethod
        def impl(x):
            return x * 2

    problems = dict(op_dtypes.check_decl_dtypes([Upcaster, Rejecter, Honest]))
    assert "upcasts to float32" in problems["fake_upcaster"]
    assert "rejects it" in problems["fake_rejecter"]
    assert "fake_honest" not in problems


def test_op_dtypes_registry_is_honest():
    """The satellite: every probe-able OpDecl's claimed dtype list
    survives eval_shape of its impl — the registry advertises only what
    the kernels keep."""
    from paddle_tpu.ops import schema

    assert op_dtypes.check_decl_dtypes(schema.DECLS) == []


# ---------------------------------------------------------------------------
# preflight: the serving admission gate
# ---------------------------------------------------------------------------

def _tiny_llama(dtype="bfloat16"):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    return LlamaForCausalLM(LlamaConfig.tiny(dtype=dtype))


def test_preflight_clean_model_ok():
    report = preflight_model(_tiny_llama(), allow_upcast=("mul",))
    assert report.ok
    assert report.cost["param_bytes"] > 0
    assert report.cost["resident_bytes"] >= report.cost["param_bytes"]


def test_engine_preflight_rejects_indivisible_sharding():
    """THE acceptance case: an indivisible sharding config raises
    PreflightError with a structured findings report — not a compile
    crash."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.serving import ContinuousBatchEngine

    model = _tiny_llama()
    mesh = dist.ProcessMesh(
        [[0, 1, 2], [3, 4, 5]], dim_names=["dp", "mp"])  # mp=3
    with pytest.raises(PreflightError) as ei:
        ContinuousBatchEngine.preflight(
            model, max_batch=2, max_len=64, mesh=mesh,
            param_specs={"q_proj.weight": (None, "mp")})
    report = ei.value.report
    assert not report.ok
    assert any(f.rule == "graph-shard-spec" for f in report.fatal)
    doc = report.as_dict()
    assert doc["ok"] is False
    assert any(f["fatal"] and "not divisible" in f["message"]
               for f in doc["findings"])


def test_engine_preflight_rejects_over_budget_model():
    from paddle_tpu.serving import ContinuousBatchEngine

    with pytest.raises(PreflightError) as ei:
        ContinuousBatchEngine.preflight(
            _tiny_llama(), max_batch=2, max_len=64, budget_bytes=1024)
    assert any(f.rule == "graph-preflight-cost"
               for f in ei.value.report.fatal)
    assert "refuse before compile" in str(ei.value)


def test_engine_preflight_raise_on_fatal_false_returns_report():
    from paddle_tpu.serving import ContinuousBatchEngine

    report = ContinuousBatchEngine.preflight(
        _tiny_llama(), max_batch=2, max_len=64, budget_bytes=1024,
        raise_on_fatal=False)
    assert not report.ok and report.fatal


def test_engine_constructor_preflight_gate_admits_clean_model():
    from paddle_tpu.serving import ContinuousBatchEngine

    eng = ContinuousBatchEngine(_tiny_llama(), max_batch=2, max_len=64,
                                preflight=True)
    assert eng is not None


def test_preflight_untraceable_model_reports_retrace_hazard():
    # an untraceable "model": a Layer whose forward branches on a
    # concrete bool of its input (data-dependent control flow)
    import paddle_tpu.nn as nn

    class DataDep(nn.Layer):
        def forward(self, x):
            if bool(x.sum() > 0):
                return x
            return -x

    report = preflight_model(DataDep(), batch=1, seq_len=4)
    assert not report.ok
    assert any(f.rule == "graph-retrace-hazard" for f in report.findings)


# ---------------------------------------------------------------------------
# the auto-sharding solver
# ---------------------------------------------------------------------------

_MESH = {"dp": 2, "mp": 4}   # the acceptance mesh: 8 devices, dp2 x mp4


def _hand_specs(traced):
    """The Megatron-pattern hand layout applied to any family (the
    zoo's _LLAMA_SHARD rules, matched by substring) — what a human
    would write before the solver existed."""
    layout = zoo.entry("llama-sharded").shard
    return zoo.ShardLayout(axis_sizes=_MESH,
                           rules=layout.rules).specs_for(traced)


def test_solver_classifies_weight_classes():
    t = zoo.traced("llama")
    classes = solver.classify_params(t)
    assert classes["llama.embed_tokens.weight"] == "embed_in"
    assert classes["lm_head.weight"] == "lm_head"
    assert classes["llama.layers.0.self_attn.q_proj.weight"] == "attn_qkv"
    assert classes["llama.layers.0.self_attn.o_proj.weight"] == "attn_o"
    assert classes["llama.layers.0.mlp.up_proj.weight"] == "mlp_up"
    assert classes["llama.layers.0.mlp.down_proj.weight"] == "mlp_down"
    # norms and any other sub-2D state stay replicated
    assert classes["llama.norm.weight"] == "norm_scalar"


def test_solver_deterministic():
    """Two fresh solves return byte-identical plans (specs, costs,
    ledger ordering) — the search has no ambient state."""
    t = zoo.traced("llama")
    a = solver.solve(t, _MESH, budget_bytes=1 << 30)
    b = solver.solve(t, _MESH, budget_bytes=1 << 30)
    assert a.as_dict() == b.as_dict()


def test_solver_fast_zoo_feasible_and_beats_hand():
    """THE acceptance sweep: on the dp=2,mp=4 mesh every fast-zoo
    family gets a plan that (a) fits a budget tighter than the
    replicated footprint, (b) validates with zero fatal shard-spec
    problems and zero implicit reshards, and (c) matches or beats the
    hand-written Megatron pattern on the cost metric."""
    seen = set()
    for e in zoo.entries():
        t = zoo.traced(e.name)
        if t.name in seen:
            continue   # the sharded twin traces the same program
        seen.add(t.name)
        assert t.ok, f"{e.name} does not trace: {t.error}"
        replicated = cost.estimate(t).total_resident_bytes()
        plan = solver.solve(t, _MESH, budget_bytes=replicated)
        assert plan.feasible, f"{e.name}: no feasible plan"
        assert plan.specs, f"{e.name}: solver left everything replicated"
        assert plan.resident_bytes() <= replicated
        assert plan.per_device_param_bytes < t.param_bytes()
        assert plan.n_reshard_events == 0, (
            f"{e.name}: chosen plan carries implicit reshards")
        score = solver.score_specs(t, plan.specs, _MESH)
        assert score["problems"] == [], f"{e.name}: {score['problems']}"
        hand = _hand_specs(t)
        if hand:
            hand_score = solver.score_specs(t, hand, _MESH)
            if not hand_score["problems"]:   # a hand layout this mesh
                assert plan.cost <= hand_score["cost"], (
                    f"{e.name}: solver {plan.cost} worse than hand "
                    f"{hand_score['cost']}")


def test_solver_budget_infeasible_reported():
    t = zoo.traced("llama")
    plan = solver.solve(t, _MESH, budget_bytes=1024)
    assert not plan.feasible
    assert plan.budget_bytes == 1024
    assert plan.cost > 0   # the cheapest plan's numbers still ride along


def test_solver_ledger_accounts_for_the_search():
    t = zoo.traced("llama")
    plan = solver.solve(t, _MESH)
    # 6 classes x 4 candidates for llama
    assert plan.plans_considered == 4 ** 6
    statuses = {e["status"] for e in plan.ledger}
    assert "costlier" in statuses or "pruned" in statuses
    for entry in plan.ledger:
        assert entry["assignment"] and entry["reason"] is not None
    d = plan.as_dict()
    assert d["resident_bytes"] == plan.resident_bytes()
    assert json.loads(json.dumps(d)) == d   # JSON-able end to end


def test_score_specs_flags_invalid_layout():
    t = zoo.traced("llama")
    score = solver.score_specs(
        t, {"llama.embed_tokens.weight": ("nope", None)}, _MESH)
    assert any("unknown mesh axis" in p for p in score["problems"])


def test_engine_preflight_auto_returns_plan_and_event():
    """preflight(param_specs='auto'): the report carries the plan, and
    the decision is a preflight.autoshard flight-recorder event."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.observability import flightrecorder as frec
    from paddle_tpu.serving import ContinuousBatchEngine

    rec = frec.get_recorder()
    rec.enable()
    try:
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                                dim_names=["dp", "mp"])
        report = ContinuousBatchEngine.preflight(
            _tiny_llama(), max_batch=2, max_len=64, mesh=mesh,
            param_specs="auto", budget_bytes=1 << 30)
        events = [e for e in rec.drain()
                  if e["kind"] == "preflight.autoshard"]
    finally:
        rec.disable()
    assert report.ok
    assert report.plan is not None and report.plan["feasible"]
    assert report.plan["specs"] and report.plan["assignment"]
    assert report.as_dict()["plan"]["cost"] == report.plan["cost"]
    assert len(events) == 1
    ev = events[0]
    assert ev["feasible"] is True and ev["cost"] == report.plan["cost"]
    assert ev["assignment"] == report.plan["assignment"]


def test_engine_preflight_auto_rejects_over_budget():
    from paddle_tpu.serving import ContinuousBatchEngine
    import paddle_tpu.distributed as dist

    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                            dim_names=["dp", "mp"])
    with pytest.raises(PreflightError) as ei:
        ContinuousBatchEngine.preflight(
            _tiny_llama(), max_batch=2, max_len=64, mesh=mesh,
            param_specs="auto", budget_bytes=1024)
    report = ei.value.report
    assert report.plan is not None and not report.plan["feasible"]
    assert any("no sharding plan fits" in f.message for f in report.fatal)


def test_preflight_auto_without_mesh_is_fatal():
    report = preflight_model(_tiny_llama(), param_specs="auto",
                             allow_upcast=("mul",))
    assert not report.ok
    assert any("needs a mesh" in f.message for f in report.fatal)


def test_solver_plan_token_identical_engine():
    """THE acceptance leg: decode under the solver-chosen dp2xmp4
    layout (params laid out with apply_plan over the real 8-device CPU
    mesh) is token-identical to the unsharded engine."""
    import paddle_tpu
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ContinuousBatchEngine

    cfg = LlamaConfig.tiny(dtype="float32")
    prompt = np.array([3, 5, 7, 11, 13], dtype=np.int32)

    paddle_tpu.seed(7)
    model = LlamaForCausalLM(cfg)
    eng = ContinuousBatchEngine(model, max_batch=2, max_len=64)
    rid = eng.add_request(prompt, max_new_tokens=8)
    ref = eng.run_until_done()[rid]

    mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4).tolist(),
                            dim_names=["dp", "mp"])
    report = ContinuousBatchEngine.preflight(
        model, max_batch=2, max_len=64, mesh=mesh, param_specs="auto")
    paddle_tpu.seed(7)
    sharded = LlamaForCausalLM(cfg)
    n = solver.apply_plan(sharded, report.plan["specs"], mesh)
    assert n == len(report.plan["specs"]) > 0
    eng2 = ContinuousBatchEngine(sharded, max_batch=2, max_len=64)
    rid2 = eng2.add_request(prompt, max_new_tokens=8)
    out = eng2.run_until_done()[rid2]
    np.testing.assert_array_equal(ref, out)


def test_shard_solver_rule_audits_bad_hand_layout(monkeypatch):
    """graph-shard-solver: a zoo layout the planner beats by >=20% is
    flagged, with the plan + rejected ledger attached as finding data;
    the shipped llama-sharded layout survives the audit."""
    from paddle_tpu.analysis.graph.rules import ShardSolverRule

    findings = list(ShardSolverRule().check_project(_REPO))
    assert findings == [], [f.message for f in findings]

    # a deliberately terrible hand layout: shard ONE mlp weight, leave
    # the rest replicated — the planner beats it easily
    bad = zoo.ZooEntry(
        "llama-sharded", zoo.entry("llama-sharded").build,
        zoo._ids_inputs,
        shard=zoo.ShardLayout(
            axis_sizes={"dp": 2, "mp": 2},
            rules=(("layers.0.mlp.up_proj.weight", (None, "mp")),)))
    monkeypatch.setattr(zoo, "entries", lambda full=False: [bad])
    findings = list(ShardSolverRule().check_project(_REPO))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "graph-shard-solver" and "cheaper" in f.message
    assert f.data["plan"]["cost"] < f.data["hand"]["cost"]
    assert isinstance(f.data["ledger"], list) and f.data["ledger"]


def test_pdlint_solve_cli(capsys):
    mod = _load_script("pdlint.py")
    rc = mod.main(["--solve", "llama", "--mesh", "dp=2,mp=4", "--json"])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0
    assert doc["tool"] == "pdlint-solve" and doc["mesh"] == _MESH
    plan = doc["plans"]["llama"]
    assert plan["feasible"] and plan["specs"] and plan["ledger"]
    # an impossible budget exits non-zero
    assert mod.main(["--solve", "llama", "--mesh", "dp=2,mp=4",
                     "--budget-bytes", "1024", "--json"]) == 1
    capsys.readouterr()


@pytest.mark.slow
def test_solver_full_zoo_sweep():
    """Every family the zoo enumerates solves to a feasible,
    implicit-reshard-free plan on the acceptance mesh."""
    seen = set()
    for e in zoo.entries(full=True):
        t = zoo.traced(e.name, full=True)
        if t.name in seen or not t.ok:
            continue
        seen.add(t.name)
        replicated = cost.estimate(t).total_resident_bytes()
        plan = solver.solve(t, _MESH, budget_bytes=replicated)
        assert plan.feasible, f"{e.name}: no feasible plan"
        assert plan.n_reshard_events == 0, f"{e.name}"
        assert solver.score_specs(t, plan.specs, _MESH)["problems"] == []


# ---------------------------------------------------------------------------
# registry + CLI integration
# ---------------------------------------------------------------------------

def test_cost_table_rule_flags_drifted_entry(tmp_path, monkeypatch):
    """graph-cost-table: a persisted entry whose recorded bytes/FLOPs
    disagree with the live analytical model is flagged; agreeing and
    pre-search-era (no-est) entries pass."""
    import json as _json

    from paddle_tpu.analysis.graph.rules import AutotuneCostTableRule
    from paddle_tpu.ops.pallas import autotune

    params = {"rows": 128, "d": 256, "dtype": "float32"}
    good = autotune.analytical_cost("rms_norm", params, (8,))
    assert good is not None  # fused_norm registers its model at import
    data = {"rms_norm": {
        "good @dev": {"choice": [8], "ms": 1.0, "params": params,
                      "est": {"bytes": good["bytes"],
                              "flops": good["flops"]}},
        "drifted @dev": {"choice": [8], "ms": 1.0, "params": params,
                         "est": {"bytes": good["bytes"] * 7,
                                 "flops": good["flops"]}},
        "legacy @dev": {"choice": [8], "ms": 1.0},
    }}
    path = tmp_path / "cache.json"
    path.write_text(_json.dumps(data))
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(path))
    findings = list(AutotuneCostTableRule().check_project(_REPO))
    assert [f.symbol for f in findings] == ["rms_norm:drifted @dev"]
    assert "bytes" in findings[0].message


def test_cost_table_rule_absent_cache_is_silent(tmp_path, monkeypatch):
    from paddle_tpu.analysis.graph.rules import AutotuneCostTableRule

    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(tmp_path / "none.json"))
    assert list(AutotuneCostTableRule().check_project(_REPO)) == []


def test_cost_table_rule_orphaned_model_flagged(tmp_path, monkeypatch):
    """Estimates recorded for a kernel whose cost model is gone = stale
    evidence, flagged rather than skipped."""
    import json as _json

    from paddle_tpu.analysis.graph.rules import AutotuneCostTableRule

    data = {"gone_kernel": {"sig @dev": {
        "choice": [8], "ms": 1.0, "params": {"rows": 1},
        "est": {"bytes": 10, "flops": 10}}}}
    path = tmp_path / "cache.json"
    path.write_text(_json.dumps(data))
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(path))
    findings = list(AutotuneCostTableRule().check_project(_REPO))
    assert len(findings) == 1
    assert "no cost model" in findings[0].message


def test_graph_rules_registered_but_excluded_by_default():
    analysis.ast_rules()  # force registration
    graph_ids = {"graph-shard-spec", "graph-shard-solver",
                 "graph-dtype-promotion", "graph-retrace-hazard",
                 "graph-preflight-cost", "graph-op-dtypes"}
    assert graph_ids <= set(analysis.RULES)
    for rid in graph_ids:
        assert analysis.RULES[rid].rationale
    default_ids = {r.id for r in analysis.core.project_rules()}
    assert not (graph_ids & default_ids), "graph rules must be opt-in"
    with_graph = {r.id for r in analysis.core.project_rules(graph=True)}
    assert graph_ids <= with_graph
    # explicit selection overrides the opt-in gate
    sel = {r.id for r in analysis.core.project_rules(
        selected=["graph-op-dtypes"])}
    assert sel == {"graph-op-dtypes"}


def _load_script(name):
    path = os.path.join(_REPO, "scripts", name)
    sp = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(sp)
    sp.loader.exec_module(mod)
    return mod


def test_pdlint_graph_gate_zero_new_findings(capsys):
    """THE tier-1 graph gate: ``scripts/pdlint.py --json --baseline
    .pdlint_baseline.json --graph`` exits 0 — the fast zoo set traces
    clean against the checked-in baseline."""
    mod = _load_script("pdlint.py")
    rc = mod.main(["--json", "--graph", "--baseline",
                   os.path.join(_REPO, ".pdlint_baseline.json")])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0, f"pdlint --graph found new findings:\n{out}"
    assert doc["total"] == 0


@pytest.mark.slow
def test_pdlint_graph_full_zoo_sweep(capsys, monkeypatch):
    """The zoo-wide sweep (every family the zoo enumerates): slow-marked
    so the fast gate stays under budget."""
    monkeypatch.setenv("PDLINT_GRAPH_SCOPE", "full")
    mod = _load_script("pdlint.py")
    rc = mod.main(["--json", "--graph", "--baseline",
                   os.path.join(_REPO, ".pdlint_baseline.json")])
    out = capsys.readouterr().out
    assert rc == 0, f"full-zoo graph sweep found new findings:\n{out}"
