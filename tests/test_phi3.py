"""Phi-3 family: fused-projection split, LongRoPE (short and long
regimes), sliding window; HF conversion + logits/greedy parity against
transformers."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.phi3 import (Phi3Config, Phi3ForCausalLM,
                                    phi3_from_hf, split_phi3_fused)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _tiny_hf(rope_scaling=None, max_position=64, original_max=None,
             window=None):
    from transformers import Phi3Config as HFConfig
    from transformers import Phi3ForCausalLM as HFPhi3

    torch.manual_seed(0)
    kw = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=max_position, rms_norm_eps=1e-5,
        rope_theta=10000.0, sliding_window=window,
        tie_word_embeddings=False, pad_token_id=0,
        attn_implementation="eager")
    if rope_scaling is not None:
        kw["rope_scaling"] = rope_scaling
    if original_max is not None:
        kw["original_max_position_embeddings"] = original_max
    return HFPhi3(HFConfig(**kw)).eval()


def _parity(hf, ours, seq, seed=0):
    ids = np.random.RandomState(seed).randint(0, 128, (2, seq))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, seq:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_fused_split_and_plain_parity():
    hf = _tiny_hf()
    ours = phi3_from_hf(hf, dtype="float32", use_flash_attention=False)
    # the fused checkpoint split into the trunk's separate projections
    assert ours.llama.layers[0].self_attn.q_proj.weight.shape == [64, 4 * 16]
    assert ours.llama.layers[0].self_attn.k_proj.weight.shape == [64, 2 * 16]
    _parity(hf, ours, seq=12)


def test_sliding_window_maps():
    hf = _tiny_hf(window=6)
    ours = phi3_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.sliding_window == 6
    _parity(hf, ours, seq=14, seed=1)


def _longrope(short, long):
    # HF Phi3Config validates the legacy "type" key spelling
    return {"type": "longrope", "short_factor": short,
            "long_factor": long}


def test_longrope_short_regime_parity():
    """Table length <= original_max: the short factors apply throughout."""
    short = list(np.linspace(1.0, 1.5, 8))
    long = list(np.linspace(2.0, 4.0, 8))
    hf = _tiny_hf(rope_scaling=_longrope(short, long), max_position=96,
                  original_max=96)
    ours = phi3_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.rope_scaling["type"] == "longrope"
    _parity(hf, ours, seq=12, seed=2)


def test_longrope_long_regime_parity():
    """Prompt beyond original_max: transformers flips to the long factors
    and the sqrt(1 + ln(f)/ln(orig)) magnitude — tables must match."""
    short = list(np.linspace(1.0, 1.5, 8))
    long = list(np.linspace(2.0, 4.0, 8))
    hf = _tiny_hf(rope_scaling=_longrope(short, long), max_position=64,
                  original_max=16)
    ours = phi3_from_hf(hf, dtype="float32", use_flash_attention=False,
                        # generate()'s cached tables are sized to
                        # prompt+max_new; keep the no-cache comparison in
                        # the same (long) regime
                        )
    ids = np.random.RandomState(3).randint(0, 128, (2, 24))  # 24 > 16
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=5e-4, rtol=5e-4)


def test_longrope_validation():
    from paddle_tpu.models.llama import validate_rope_scaling

    with pytest.raises(ValueError, match="equal length"):
        validate_rope_scaling({"rope_type": "longrope",
                               "short_factor": [1.0],
                               "long_factor": [1.0, 2.0]}, max_position=64)
    with pytest.raises(ValueError, match="original_max"):
        validate_rope_scaling({"rope_type": "longrope",
                               "short_factor": [1.0],
                               "long_factor": [2.0]})


def test_longrope_engine_matches_solo():
    """Regression: the serving engine's bucketed prefill used to build
    rope at the BUCKET length while decode provisioned max_len — with
    longrope the two picked different factor regimes and served garbage.
    Prefill now provisions rope at the engine's max_len."""
    from paddle_tpu.serving import ContinuousBatchEngine

    paddle.seed(6)
    m = Phi3ForCausalLM(Phi3Config.tiny(
        num_hidden_layers=2,
        rope_scaling={"rope_type": "longrope",
                      "short_factor": [1.0] * 8,
                      "long_factor": [2.0] * 8,
                      "original_max_position_embeddings": 8}))
    # prompt length == a bucket boundary == original_max: the bucket-sized
    # table sat exactly at the short/long boundary
    prompt = np.random.RandomState(7).randint(1, 512, (8,))
    solo = m.generate(paddle.to_tensor(prompt[None]),
                      max_new_tokens=6).numpy()[0]
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=32, page_size=8)
    rid = eng.add_request(prompt.tolist(), max_new_tokens=6)
    out = eng.run_until_done()[rid]
    np.testing.assert_array_equal(np.asarray(out), solo)


def test_split_rejects_bad_shapes():
    hf = _tiny_hf()
    sd = {k: v for k, v in hf.state_dict().items()}
    key = "model.layers.0.self_attn.qkv_proj.weight"
    sd[key] = torch.zeros(7, 64)
    with pytest.raises(ValueError, match="fused qkv rows"):
        split_phi3_fused(sd, hf.config)


def test_partial_rotary_parity():
    """partial_rotary_factor=0.5 (the Phi-3-small / GLM / StableLM class):
    only the leading half of each head rotates — logits and greedy must
    match transformers on every decode path."""
    from transformers import Phi3Config as HFConfig
    from transformers import Phi3ForCausalLM as HFPhi3

    torch.manual_seed(1)
    hf = HFPhi3(HFConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        partial_rotary_factor=0.5, tie_word_embeddings=False,
        pad_token_id=0, attn_implementation="eager")).eval()
    ours = phi3_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.partial_rotary_factor == 0.5
    from paddle_tpu.models.llama import rope_dim_of

    assert rope_dim_of(ours.config) == 8     # head_dim 16 -> 8 rotate
    _parity(hf, ours, seq=12, seed=5)
    # paged serving path sees the narrow tables too
    ids = np.random.RandomState(6).randint(0, 128, (1, 9))
    a = ours.generate(paddle.to_tensor(ids), max_new_tokens=5).numpy()
    b = ours.generate(paddle.to_tensor(ids), max_new_tokens=5,
                      paged=True, page_size=4).numpy()
    np.testing.assert_array_equal(a, b)


def test_partial_rotary_validation():
    with pytest.raises(ValueError, match="partial_rotary_factor"):
        Phi3Config.tiny(partial_rotary_factor=1.5)
