"""Qwen3 family: per-head q/k RMSNorm + head_dim decoupled from
hidden/heads, expressed as LlamaConfig knobs — transformers parity plus
the decode paths the tiny config (head_dim 32 vs quotient 16) exercises
everywhere."""
import numpy as np
import pytest
import torch

import paddle_tpu as paddle
from paddle_tpu.models.qwen3 import (Qwen3Config, Qwen3ForCausalLM,
                                     qwen3_from_hf)


def test_logits_and_generate_match_transformers():
    from transformers import Qwen3Config as HFConfig
    from transformers import Qwen3ForCausalLM as HFQwen3

    torch.manual_seed(0)
    # head_dim 32 != hidden/heads (64/4=16): the decoupled case
    hf_cfg = HFConfig(vocab_size=128, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, head_dim=32,
                      max_position_embeddings=128, rms_norm_eps=1e-6,
                      rope_theta=1e6, tie_word_embeddings=False,
                      attn_implementation="eager")
    hf = HFQwen3(hf_cfg).eval()
    ours = qwen3_from_hf(hf, dtype="float32", use_flash_attention=False)
    assert ours.config.qk_norm and ours.config.head_dim == 32
    ids = np.random.RandomState(0).randint(0, 128, (2, 9))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids)).logits.numpy()
    got = ours(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)
    with torch.no_grad():
        gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                           do_sample=False).numpy()[:, 9:]
    ggot = ours.generate(paddle.to_tensor(ids), max_new_tokens=6).numpy()
    np.testing.assert_array_equal(ggot, gref)


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    m = Qwen3ForCausalLM(Qwen3Config.tiny())

    def loss_fn(model, x, y):
        loss, _ = model(x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512, (2, 16)))
    y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512, (2, 16)))
    losses = [float(step(x, y).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_qk_norm_required():
    with pytest.raises(ValueError, match="qk_norm"):
        Qwen3ForCausalLM(Qwen3Config.tiny(qk_norm=False))


class TestQwen3Moe:
    def test_logits_and_generate_match_transformers(self):
        from transformers import Qwen3MoeConfig as HFConfig
        from transformers import Qwen3MoeForCausalLM as HFQwen3Moe

        from paddle_tpu.models.qwen3_moe import qwen3_moe_from_hf

        torch.manual_seed(0)
        hf_cfg = HFConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=32,
            max_position_embeddings=128, rms_norm_eps=1e-6,
            rope_theta=1e6, tie_word_embeddings=False,
            num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=32, norm_topk_prob=True,
            attn_implementation="eager")
        hf = HFQwen3Moe(hf_cfg).eval()
        ours = qwen3_moe_from_hf(hf, dtype="float32",
                                 use_flash_attention=False)
        assert ours.config.qk_norm and ours.config.head_dim == 32
        assert ours.config.n_shared_experts == 0
        ids = np.random.RandomState(0).randint(0, 128, (2, 9))
        with torch.no_grad():
            ref = hf(torch.from_numpy(ids)).logits.numpy()
        got = ours(paddle.to_tensor(ids)).numpy()
        np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)
        with torch.no_grad():
            gref = hf.generate(torch.from_numpy(ids), max_new_tokens=6,
                               do_sample=False).numpy()[:, 9:]
        ggot = ours.generate(paddle.to_tensor(ids),
                             max_new_tokens=6).numpy()
        np.testing.assert_array_equal(ggot, gref)

    def test_trains_with_aux_loss(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.models.qwen3_moe import (Qwen3MoeConfig,
                                                 Qwen3MoeForCausalLM)

        paddle.seed(2)
        m = Qwen3MoeForCausalLM(Qwen3MoeConfig.tiny())

        def loss_fn(model, x, y):
            loss, _ = model(x, labels=y)
            return loss

        step = paddle.jit.train_step(
            m, loss_fn, opt.AdamW(1e-2, parameters=m.parameters()))
        x = paddle.to_tensor(np.random.RandomState(0).randint(0, 512,
                                                              (2, 16)))
        y = paddle.to_tensor(np.random.RandomState(1).randint(0, 512,
                                                              (2, 16)))
        losses = [float(step(x, y).numpy()) for _ in range(4)]
        assert losses[-1] < losses[0]

    def test_shared_expert_rejected(self):
        from paddle_tpu.models.qwen3_moe import (Qwen3MoeConfig,
                                                 Qwen3MoeForCausalLM)

        with pytest.raises(ValueError, match="shared"):
            Qwen3MoeForCausalLM(Qwen3MoeConfig.tiny(n_shared_experts=1))
