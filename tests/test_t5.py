"""T5 encoder-decoder family: relative-position-bias attention,
cross-attention, cached enc-dec generation — numeric parity against
transformers for both FFN variants, ragged encoder masks, and training."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.t5 import T5Config, T5ForConditionalGeneration, t5_from_hf

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _hf_pair(**cfg_kw):
    from transformers import T5Config as HFConfig
    from transformers import T5ForConditionalGeneration as HFT5

    torch.manual_seed(0)
    base = dict(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                num_layers=2, num_heads=4, decoder_start_token_id=0,
                attn_implementation="eager")
    base.update(cfg_kw)
    hf = HFT5(HFConfig(**base)).eval()
    return hf, t5_from_hf(hf)


@pytest.mark.parametrize("ff", ["relu", "gated-gelu"])
def test_logits_match_transformers(ff):
    hf, ours = _hf_pair(feed_forward_proj=ff)
    assert ours.config.feed_forward_proj == ff
    enc = np.random.RandomState(0).randint(2, 256, (2, 11))
    dec = np.random.RandomState(1).randint(2, 256, (2, 7))
    with torch.no_grad():
        ref = hf(input_ids=torch.from_numpy(enc),
                 decoder_input_ids=torch.from_numpy(dec)).logits.numpy()
    got = ours(paddle.to_tensor(enc), paddle.to_tensor(dec)).numpy()
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_greedy_generate_matches_transformers():
    hf, ours = _hf_pair()
    enc = np.random.RandomState(2).randint(2, 256, (2, 9))
    with torch.no_grad():
        # HF output starts with decoder_start_token_id — drop it
        ref = hf.generate(torch.from_numpy(enc), max_new_tokens=8,
                          do_sample=False).numpy()[:, 1:]
    got = ours.generate(paddle.to_tensor(enc), max_new_tokens=8).numpy()
    n = min(got.shape[1], ref.shape[1])
    np.testing.assert_array_equal(got[:, :n], ref[:, :n])


def test_encoder_pad_mask_matches_transformers():
    """Ragged encoder inputs through attention_mask: cross + encoder
    self-attention must ignore pad columns exactly as HF does."""
    hf, ours = _hf_pair()
    enc = np.random.RandomState(3).randint(2, 256, (2, 10))
    am = np.ones((2, 10), np.int64)
    am[1, 6:] = 0
    dec = np.random.RandomState(4).randint(2, 256, (2, 5))
    with torch.no_grad():
        ref = hf(input_ids=torch.from_numpy(enc),
                 attention_mask=torch.from_numpy(am),
                 decoder_input_ids=torch.from_numpy(dec)).logits.numpy()
    got = ours(paddle.to_tensor(enc), paddle.to_tensor(dec),
               attention_mask=paddle.to_tensor(am.astype(bool))).numpy()
    np.testing.assert_allclose(got, ref, atol=3e-4, rtol=3e-4)


def test_trains():
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    m = T5ForConditionalGeneration(T5Config.tiny())

    def loss_fn(mm, x, dec_x, y):
        loss, _ = mm(x, dec_x, labels=y)
        return loss

    step = paddle.jit.train_step(m, loss_fn,
                                 opt.AdamW(1e-2, parameters=m.parameters()))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randint(2, 256, (2, 12)))
    tgt = rng.randint(2, 256, (2, 8))
    dec_in = np.concatenate([np.zeros((2, 1), np.int64), tgt[:, :-1]], 1)
    losses = [float(step(x, paddle.to_tensor(dec_in),
                         paddle.to_tensor(tgt)).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_eos_stops_early_and_unsupported_raise():
    paddle.seed(0)
    m = T5ForConditionalGeneration(T5Config.tiny())
    enc = paddle.to_tensor(np.random.RandomState(5).randint(2, 256, (1, 6)))
    out = m.generate(enc, max_new_tokens=50)
    assert out.shape[1] <= 50
    # num_beams is supported now (r5); beam SAMPLING and genuinely
    # unsupported kwargs still fail loudly
    with pytest.raises(NotImplementedError, match="do_sample"):
        m.generate(enc, num_beams=3, do_sample=True)
    with pytest.raises(NotImplementedError, match="paged"):
        m.generate(enc, paged=True)


def test_padded_generate_matches_unpadded():
    """Cached cross-attention must carry the encoder pad mask: a padded
    row's generation equals the same sequence generated unpadded."""
    paddle.seed(0)
    m = T5ForConditionalGeneration(T5Config.tiny())
    rng = np.random.RandomState(6)
    short = rng.randint(2, 256, (1, 6))
    solo = m.generate(paddle.to_tensor(short), max_new_tokens=8).numpy()
    padded = np.zeros((1, 10), np.int64)
    padded[0, :6] = short[0]
    am = np.zeros((1, 10), np.int64)
    am[0, :6] = 1
    got = m.generate(paddle.to_tensor(padded), max_new_tokens=8,
                     attention_mask=paddle.to_tensor(am)).numpy()
    n = min(got.shape[1], solo.shape[1])
    np.testing.assert_array_equal(got[0, :n], solo[0, :n])


def test_bf16_config_builds_bf16_params_and_generates():
    paddle.seed(0)
    m = T5ForConditionalGeneration(T5Config.tiny(dtype="bfloat16"))
    dts = {str(p.dtype) for _, p in m.named_parameters()}
    assert dts == {"bfloat16"}
    out = m.generate(paddle.to_tensor(
        np.random.RandomState(0).randint(2, 256, (1, 8))), max_new_tokens=5,
        eos_token_id=-1)  # eos disabled: fixed-length regardless of argmax
    assert out.shape == [1, 5]


def test_t5_beam_search_matches_transformers():
    """num_beams>1 on the enc-dec path: token-identical to HF T5 beam
    generate across beam widths and length penalties."""
    import torch
    from transformers import T5Config as HFConfig
    from transformers import T5ForConditionalGeneration as HFT5
    from paddle_tpu.models.t5 import t5_from_hf

    torch.manual_seed(0)
    hf = HFT5(HFConfig(vocab_size=96, d_model=64, d_kv=16, d_ff=128,
                       num_layers=2, num_heads=4, relative_attention_num_buckets=8,
                       relative_attention_max_distance=20,
                       decoder_start_token_id=0,
                       tie_word_embeddings=True)).eval()
    ours = t5_from_hf(hf, dtype="float32")
    ids = np.random.RandomState(0).randint(3, 96, (2, 8))
    for beams, lp in ((2, 1.0), (3, 0.5)):
        with torch.no_grad():
            ref = hf.generate(torch.from_numpy(ids), max_new_tokens=7,
                              num_beams=beams, length_penalty=lp,
                              do_sample=False).numpy()[:, 1:]
        got = ours.generate(paddle.to_tensor(ids), max_new_tokens=7,
                            num_beams=beams, length_penalty=lp).numpy()
        assert got.shape[1] >= 5, got  # no silent truncation
        w = min(got.shape[1], ref.shape[1])
        np.testing.assert_array_equal(got[:, :w], ref[:, :w])
