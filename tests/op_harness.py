"""OpTest-grade harness: numpy-reference forward + numeric-vs-analytic grad.

Parity model: /root/reference/test/legacy_test/op_test.py — OpTest (:418)
compares every op against a NumPy reference implementation, and check_grad
(:3081) compares analytic gradients against numeric finite differences
(get_numeric_gradient :148). This harness re-creates that design for the
TPU build's eager surface:

- ``check(spec)`` runs the public paddle_tpu function on Tensors and
  compares against ``spec.ref`` (an independent numpy/scipy reference)
  per dtype;
- when ``spec.grad`` names inputs, it then runs tape backward on a
  weighted-sum loss and compares each input's ``.grad`` against central
  finite differences of the *reference* in float64 — one check validating
  both the forward semantics and the registered VJP.

Specs live in test_op_suite.py; a completeness test there asserts every
op in ops.registry.OPS is either spec-covered or whitelisted with a reason.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence

import numpy as np

import paddle_tpu as paddle


@dataclasses.dataclass
class OpSpec:
    name: str                      # dotted path under paddle_tpu, e.g. "nn.functional.relu"
    inputs: Dict[str, np.ndarray]  # float64/int64 canonical inputs
    ref: Callable                  # numpy reference: ref(**inputs, **attrs)
    attrs: Dict = dataclasses.field(default_factory=dict)
    dtypes: Sequence[str] = ("float32",)
    grad: Sequence[str] = ()       # input names to grad-check
    rtol: float = 1e-5
    atol: float = 1e-6
    grad_rtol: float = 1e-2
    grad_atol: float = 1e-3
    eps: float = 1e-3              # finite-difference step (on float64 ref)
    # some ops return int/bool regardless of input dtype
    out_cast: bool = True          # cast ref to actual dtype before compare
    covers: Sequence[str] = ()     # extra registry names this spec covers


def resolve(name: str):
    obj = paddle
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def _cast_input(a, dtype: str):
    if isinstance(a, (list, tuple)):
        return type(a)(_cast_input(v, dtype) for v in a)
    a = np.asarray(a)
    if np.issubdtype(a.dtype, np.floating) or np.issubdtype(a.dtype, np.complexfloating):
        if np.issubdtype(a.dtype, np.complexfloating):
            return a.astype("complex64")
        return a.astype(dtype)
    return a  # ints/bools keep their dtype


def _wrap_input(v):
    if isinstance(v, (list, tuple)):
        return type(v)(_wrap_input(e) for e in v)
    return paddle.to_tensor(v)


def _f64(v):
    if isinstance(v, (list, tuple)):
        return type(v)(_f64(e) for e in v)
    v = np.asarray(v)
    return v.astype("float64") if np.issubdtype(v.dtype, np.floating) else v


def _to_np(out):
    import jax

    if isinstance(out, (list, tuple)):
        return type(out)(_to_np(o) for o in out)
    if hasattr(out, "numpy"):
        return np.asarray(jax.device_get(out.numpy()))
    return np.asarray(out)


def _flatten(out):
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_flatten(o))
        return res
    return [out]


def check_forward(spec: OpSpec, dtype: str) -> None:
    fn = resolve(spec.name)
    np_inputs = {k: _cast_input(v, dtype) for k, v in spec.inputs.items()}
    tensors = {k: _wrap_input(v) for k, v in np_inputs.items()}
    out = fn(**tensors, **spec.attrs)
    ref_out = spec.ref(**{k: _f64(v) for k, v in np_inputs.items()},
                       **spec.attrs)
    got_flat = _flatten(_to_np(out))
    ref_flat = _flatten(ref_out if isinstance(ref_out, (list, tuple))
                        else (ref_out,))
    assert len(got_flat) == len(ref_flat), (
        f"{spec.name}: {len(got_flat)} outputs vs {len(ref_flat)} reference")
    for i, (g, r) in enumerate(zip(got_flat, ref_flat)):
        r = np.asarray(r)
        if spec.out_cast and g.dtype != r.dtype:
            r = r.astype(g.dtype)
        assert g.shape == tuple(np.shape(r)), (
            f"{spec.name}[{i}]: shape {g.shape} vs ref {np.shape(r)}")
        np.testing.assert_allclose(
            g, r, rtol=spec.rtol, atol=spec.atol,
            err_msg=f"{spec.name}[{i}] dtype={dtype} forward mismatch")


def _numeric_grad(spec: OpSpec, wrt: str, weights, np_inputs) -> np.ndarray:
    """Central finite differences of sum(ref * w) wrt np_inputs[wrt], f64."""
    base = {k: _f64(v) for k, v in np_inputs.items()}

    def loss(x):
        inp = dict(base)
        inp[wrt] = x
        out = spec.ref(**inp, **spec.attrs)
        flat = _flatten(out if isinstance(out, (list, tuple)) else (out,))
        return sum(float(np.sum(np.asarray(o, "float64") * w))
                   for o, w in zip(flat, weights))

    x0 = base[wrt].copy()
    g = np.zeros_like(x0)
    it = np.nditer(x0, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        h = spec.eps * max(1.0, abs(x0[idx]))
        xp = x0.copy(); xp[idx] += h
        xm = x0.copy(); xm[idx] -= h
        g[idx] = (loss(xp) - loss(xm)) / (2 * h)
        it.iternext()
    return g


def check_grad(spec: OpSpec, dtype: str = "float32") -> None:
    fn = resolve(spec.name)
    np_inputs = {k: _cast_input(v, dtype) for k, v in spec.inputs.items()}
    tensors = {}
    for k, v in np_inputs.items():
        t = _wrap_input(v)
        if k in spec.grad:
            t.stop_gradient = False
        tensors[k] = t
    out = fn(**tensors, **spec.attrs)
    out_flat = [t for t in _flatten(out) if hasattr(t, "numpy")]
    rng = np.random.RandomState(42)
    weights = [rng.uniform(0.5, 1.5, np.asarray(t.numpy()).shape)
               for t in out_flat]
    loss = None
    for t, w in zip(out_flat, weights):
        term = (t * paddle.to_tensor(w.astype(t.numpy().dtype))).sum()
        loss = term if loss is None else loss + term
    loss.backward()
    for k in spec.grad:
        analytic = tensors[k].grad
        assert analytic is not None, f"{spec.name}: no grad for input {k!r}"
        numeric = _numeric_grad(spec, k, weights, np_inputs)
        np.testing.assert_allclose(
            np.asarray(analytic.numpy(), "float64"), numeric,
            rtol=spec.grad_rtol, atol=spec.grad_atol,
            err_msg=f"{spec.name} grad[{k}] analytic-vs-numeric mismatch")


def run_spec(spec: OpSpec) -> None:
    for dtype in spec.dtypes:
        check_forward(spec, dtype)
    if spec.grad:
        check_grad(spec)
