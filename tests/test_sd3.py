"""SD3 / MMDiT tests: two-stream forward, rectified-flow + DDPM objectives,
flow/DDIM samplers with CFG (BASELINE.json "DiT / Stable-Diffusion-3").
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.models.sd3 import (MMDiT, MMDiTConfig, cfg_label_dropout,
                                   ddpm_eps_loss, rectified_flow_loss,
                                   sample_ddim, sample_flow)


def _inputs(B=2):
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(B, 4, 8, 8).astype("float32"))
    t = paddle.to_tensor(r.rand(B).astype("float32"))
    ctx = paddle.to_tensor(r.randn(B, 6, 32).astype("float32"))
    pool = paddle.to_tensor(r.randn(B, 16).astype("float32"))
    return x, t, ctx, pool


def test_mmdit_forward_shape_and_identity_init():
    paddle.seed(0)
    m = MMDiT(MMDiTConfig.tiny())
    x, t, ctx, pool = _inputs()
    out = m(x, t, ctx, pool)
    assert tuple(out.shape) == (2, 4, 8, 8)
    # FinalLayer is zero-init (adaLN-Zero) => exact zeros before training
    assert abs(out.numpy()).max() == 0.0


def test_mmdit_text_conditioning_matters():
    """Different text context must change the prediction: joint attention
    mixes the streams even though each keeps its own weights."""
    import jax.numpy as jnp

    paddle.seed(1)
    m = MMDiT(MMDiTConfig.tiny())
    # adaLN-Zero gates make every block identity at init — un-zero block 0's
    # image-stream gates (so joint attention output flows) AND the final
    # projection (so the signal reaches the output)
    m.blocks[0].img.adaLN.weight._array = jnp.asarray(
        np.random.RandomState(1).randn(*m.blocks[0].img.adaLN.weight.shape)
        .astype("float32") * 0.1)
    m.final.linear.weight._array = jnp.asarray(
        np.random.RandomState(2).randn(*m.final.linear.weight.shape)
        .astype("float32") * 0.1)
    x, t, ctx, pool = _inputs()
    r = np.random.RandomState(9)
    ctx2 = paddle.to_tensor(r.randn(*ctx.shape).astype("float32"))
    a = m(x, t, ctx, pool).numpy()
    b = m(x, t, ctx2, pool).numpy()
    assert np.abs(a - b).max() > 1e-6


def test_rectified_flow_trains_under_train_step():
    """The SD3 objective through the compiled TrainStep path: loss drops,
    and the traced-RNG context gives DIFFERENT noise draws per step."""
    paddle.seed(0)
    m = MMDiT(MMDiTConfig.tiny())
    o = opt.AdamW(2e-3, parameters=m.parameters())
    x, _, ctx, pool = _inputs(B=4)

    step = paddle.jit.train_step(
        m, lambda mm, a, c, p: rectified_flow_loss(mm, a, c, p), o)
    losses = [float(step(x, ctx, pool).numpy()) for _ in range(8)]
    assert all(np.isfinite(losses))
    # fresh timestep/noise draws per step: consecutive losses must differ
    assert len({round(v, 8) for v in losses}) > 1
    assert min(losses[4:]) < max(losses[:2])


def test_ddpm_loss_with_dit_and_label_dropout():
    from paddle_tpu.vision.models.dit import DiT, DiTConfig

    paddle.seed(0)
    d = DiT(DiTConfig.tiny())
    r = np.random.RandomState(0)
    x = paddle.to_tensor(r.randn(2, 4, 8, 8).astype("float32"))
    y = paddle.to_tensor(np.array([1, 2], dtype="int64"))
    yd = cfg_label_dropout(y, d.config.num_classes, prob=1.0)
    assert (yd.numpy() == d.config.num_classes).all()  # all dropped to null
    y0 = cfg_label_dropout(y, d.config.num_classes, prob=0.0)
    assert (y0.numpy() == y.numpy()).all()
    loss = ddpm_eps_loss(d, x, y)
    v = float(loss.numpy())
    # adaLN-Zero init => model predicts exactly 0 => loss = E[eps^2] ~ 1
    assert np.isfinite(v) and 0.3 < v < 3.0


def test_sample_flow_runs_and_is_finite():
    paddle.seed(0)
    m = MMDiT(MMDiTConfig.tiny())
    _, _, ctx, pool = _inputs()
    out = sample_flow(m, (2, 4, 8, 8), ctx, pool, steps=3)
    a = out.numpy()
    assert a.shape == (2, 4, 8, 8) and np.isfinite(a).all()
    # zero-init model => zero velocity => the sample IS the initial noise
    assert np.abs(a).std() > 0.5


def test_sample_ddim_cfg_matches_uncond_for_zero_scale():
    """guidance_scale=0 must equal the plain conditional sample; the CFG
    combination with the null class must run and stay finite."""
    from paddle_tpu.vision.models.dit import DiT, DiTConfig

    paddle.seed(0)
    d = DiT(DiTConfig.tiny(learn_sigma=True))
    y = paddle.to_tensor(np.array([1, 2], dtype="int64"))
    null = paddle.to_tensor(np.array([10, 10], dtype="int64"))
    import jax

    k = jax.random.key(7)
    a = sample_ddim(d, (2, 4, 8, 8), y, steps=3, key=k).numpy()
    b = sample_ddim(d, (2, 4, 8, 8), y, steps=3, guidance_scale=0.0,
                    uncond=(null,), key=k).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-6)
    c = sample_ddim(d, (2, 4, 8, 8), y, steps=3, guidance_scale=4.0,
                    uncond=(null,), key=k).numpy()
    assert np.isfinite(c).all()


def test_mmdit_shards_under_parallelize():
    """The SD3 train step under the hybrid engine: dp2 x mp2 x sharding2
    on the 8-device mesh (GSPMD shards the joint-attention matmuls)."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.engine import parallelize

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "sep_degree": 1, "sharding_degree": 2,
                               "pp_degree": 1}
    strategy.sharding_configs = {"stage": 3}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(0)
        m = MMDiT(MMDiTConfig.tiny())
        m = dist.fleet.distributed_model(m)
        o = opt.AdamW(1e-3, parameters=m.parameters())
        o = dist.fleet.distributed_optimizer(o)
        step = parallelize(
            m, lambda mm, a, c, p: rectified_flow_loss(mm, a, c, p), o)
        r = np.random.RandomState(0)
        x = paddle.to_tensor(r.randn(4, 4, 8, 8).astype("float32"))
        ctx = paddle.to_tensor(r.randn(4, 6, 32).astype("float32"))
        pool = paddle.to_tensor(r.randn(4, 16).astype("float32"))
        loss = step(x, ctx, pool)
        assert np.isfinite(float(loss.numpy()))
    finally:
        dist.set_hybrid_communicate_group(None)
