"""Launch-driven multi-process collective integration test.

Parity model: test/collective/test_communication_api_base.py:28,63-70 —
a unittest driver launches REAL processes via `python -m
paddle.distributed.launch` that rendezvous on one master, run collectives,
and assert loss parity with the single-process run.
"""
import os
import pickle
import socket
import subprocess
import sys

import numpy as np
import pytest


_WORKER = r'''
import os, pickle, sys
import numpy as np

out_dir = sys.argv[1]
rank = int(os.environ["PADDLE_TRAINER_ID"])

import jax
jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

import paddle_tpu
paddle_tpu.set_flags({"FLAGS_collective_static_check": True})
dist.init_parallel_env()
assert dist.get_world_size() == 2, dist.get_world_size()
assert dist.get_rank() == rank

# ---- all_reduce over the 2-process global mesh ----
x = paddle.to_tensor(np.full((4,), float(rank + 1), dtype="float32"))
dist.all_reduce(x)
np.testing.assert_allclose(x.numpy(), 3.0)  # 1 + 2

# ---- data-parallel loss parity vs the single-process whole batch ----
# global batch split by rank; grads allreduced -> must equal whole-batch run
paddle.seed(0)
model = paddle.nn.Linear(8, 4)
data = np.random.RandomState(7).randn(4, 8).astype("float32")
label = np.random.RandomState(8).randn(4, 4).astype("float32")
shard = slice(rank * 2, rank * 2 + 2)
out = model(paddle.to_tensor(data[shard]))
loss = ((out - paddle.to_tensor(label[shard])) ** 2).mean()
loss.backward()
# dp grad sync: mean over ranks
for p in model.parameters():
    g = p.grad
    dist.all_reduce(g)
    p._grad = g / 2.0
loss_sync = loss.clone()
dist.all_reduce(loss_sync)
# ---- object collectives over the 2-process world ----
objs = []
dist.all_gather_object(objs, {"rank": rank, "tag": f"r{rank}" * (rank + 1)})
assert [o["rank"] for o in objs] == [0, 1], objs
assert objs[1]["tag"] == "r1r1"

blist = [{"cfg": 42, "note": "from rank0"}] if rank == 0 else [None]
dist.broadcast_object_list(blist, src=0)
assert blist[0]["cfg"] == 42, blist

mine = []
dist.scatter_object_list(mine, ["for-rank0", "for-rank1"], src=0)
assert mine == [f"for-rank{rank}"], mine

result = {
    "rank": rank,
    "mean_loss": float(loss_sync.numpy()) / 2.0,
    "grads": {n: np.asarray(p.grad.numpy())
              for n, p in model.named_parameters()},
}
with open(os.path.join(out_dir, f"rank{rank}.pkl"), "wb") as f:
    pickle.dump(result, f)
print(f"rank {rank} OK", flush=True)
'''


@pytest.mark.slow
def test_launch_two_process_allreduce_and_loss_parity(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", str(tmp_path / "logs"), str(worker), str(tmp_path)],
        cwd="/root/repo", capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    assert r.returncode == 0, r.stdout + "\n" + r.stderr

    results = []
    for rank in range(2):
        with open(tmp_path / f"rank{rank}.pkl", "rb") as f:
            results.append(pickle.load(f))

    # ---- single-process reference on the WHOLE batch ----
    import jax

    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle

    paddle.seed(0)
    model = paddle.nn.Linear(8, 4)
    data = np.random.RandomState(7).randn(4, 8).astype("float32")
    label = np.random.RandomState(8).randn(4, 4).astype("float32")
    out = model(paddle.to_tensor(data))
    loss = ((out - paddle.to_tensor(label)) ** 2).mean()
    loss.backward()

    for res in results:
        np.testing.assert_allclose(res["mean_loss"], float(loss.numpy()),
                                   rtol=1e-5)
        for n, p in model.named_parameters():
            np.testing.assert_allclose(res["grads"][n], p.grad.numpy(),
                                       rtol=1e-4, atol=1e-6)
    # both ranks computed identical synced grads
    for n in results[0]["grads"]:
        np.testing.assert_array_equal(results[0]["grads"][n],
                                      results[1]["grads"][n])
