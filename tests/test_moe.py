"""MoE/EP tests (reference: test/collective/ moe cases + moe op unit tests;
SURVEY §2.7 EP row)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import moe


pytestmark = pytest.mark.skipif(jax.device_count() < 8, reason="needs 8 virtual devices")


def test_one_hot_dispatch_capacity_semantics():
    # 4 tokens, 2 experts, capacity 1: later tokens to a full expert drop
    probs = jnp.asarray([[0.9, 0.1], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]], jnp.float32)
    idx = jnp.argmax(probs, axis=-1)[:, None]  # [0,0,1,0]
    combine, disp = moe.one_hot_dispatch(probs, idx, capacity=1)
    assert combine.shape == (4, 2, 1)
    np.testing.assert_allclose(combine[0, 0, 0], 0.9, rtol=1e-6)  # token0 → e0 slot0
    np.testing.assert_allclose(combine[2, 1, 0], 0.7, rtol=1e-6)  # token2 → e1 slot0
    assert float(combine[1].sum()) == 0.0  # token1 dropped (e0 full)
    assert float(combine[3].sum()) == 0.0  # token3 dropped
    assert bool(disp[0, 0, 0]) and not bool(disp[1].any())


def test_expert_count_and_prune():
    idx = paddle.to_tensor(np.array([0, 0, 1, 0, 2], np.int32))
    counts = moe.expert_count(idx, 4)
    np.testing.assert_array_equal(np.asarray(counts), [3, 1, 1, 0])
    np.testing.assert_array_equal(
        np.asarray(moe.limit_by_capacity(counts, 2)), [2, 1, 1, 0])
    pruned = moe.prune_gate_by_capacity(idx, 4, capacity=2)
    np.testing.assert_array_equal(np.asarray(pruned), [0, 0, 1, -1, 2])


def _np_moe_reference(x, layer):
    """Dense loop reference: top-k routing with capacity bookkeeping."""
    gate = layer.gate
    w = gate.gate_weight.numpy()
    b = gate.gate_bias.numpy()
    logits = x @ w + b
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    k = gate.top_k
    topk = np.argsort(-probs, axis=-1, kind="stable")[:, :k]
    S, E = probs.shape
    mlp = layer.experts
    w1, b1 = mlp.w1.numpy(), mlp.b1.numpy()
    w2, b2 = mlp.w2.numpy(), mlp.b2.numpy()

    import math

    def expert(eid, v):
        h = v @ w1[eid] + b1[eid][0]
        h = 0.5 * h * (1 + np.vectorize(math.erf)(h / np.sqrt(2)))
        return h @ w2[eid] + b2[eid][0]

    counts = np.zeros(E, np.int64)
    cap = S  # naive gate: no drop
    out = np.zeros_like(x)
    # column-by-column to match one_hot_dispatch's priority ordering
    for i in range(k):
        for s in range(S):
            eid = topk[s, i]
            if counts[eid] < cap:
                out[s] += probs[s, eid] * expert(eid, x[s])
                counts[eid] += 1
    return out


def test_moe_layer_naive_gate_parity():
    paddle.seed(7)
    d_model, E = 16, 4
    layer = moe.MoELayer(
        d_model, moe.GroupedMLP(E, d_model, 32, activation="gelu"),
        gate=moe.NaiveGate(d_model, E, topk=2))
    x = np.random.randn(10, d_model).astype(np.float32)
    out = layer(paddle.to_tensor(x))
    ref = _np_moe_reference(x, layer)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-3, atol=2e-4)


def test_moe_layer_list_experts_matches_grouped():
    """Per-expert Layer list path (reference API) agrees with GroupedMLP."""
    import paddle_tpu.nn as nn

    paddle.seed(3)
    d_model, E = 8, 4
    grouped = moe.GroupedMLP(E, d_model, 16)
    layer_g = moe.MoELayer(d_model, grouped, gate=moe.NaiveGate(d_model, E, topk=2))

    class Expert(nn.Layer):
        def __init__(self, eid):
            super().__init__()
            self.fc1 = nn.Linear(d_model, 16)
            self.fc2 = nn.Linear(16, d_model)
            w1, b1 = grouped.w1.numpy()[eid], grouped.b1.numpy()[eid][0]
            w2, b2 = grouped.w2.numpy()[eid], grouped.b2.numpy()[eid][0]
            self.fc1.weight.set_value(w1)
            self.fc1.bias.set_value(b1)
            self.fc2.weight.set_value(w2)
            self.fc2.bias.set_value(b2)

        def forward(self, x):
            import paddle_tpu.nn.functional as F

            return self.fc2(F.gelu(self.fc1(x)))

    experts = [Expert(e) for e in range(E)]
    layer_l = moe.MoELayer(d_model, experts, gate=layer_g.gate)

    x = paddle.to_tensor(np.random.randn(6, d_model).astype(np.float32))
    np.testing.assert_allclose(layer_g(x).numpy(), layer_l(x).numpy(),
                               rtol=1e-4, atol=1e-5)


def test_switch_gate_drops_over_capacity():
    paddle.seed(1)
    d_model, E = 8, 2
    gate = moe.SwitchGate(d_model, E, capacity=(0.5, 0.5))
    layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 16), gate=gate)
    layer.eval()
    x = paddle.to_tensor(np.random.randn(8, d_model).astype(np.float32))
    out = layer(x)
    # capacity = ceil(8*1*0.5/2) = 2 per expert → at most 4 tokens routed
    routed = (np.abs(out.numpy()).sum(-1) > 1e-7).sum()
    assert routed <= 4
    assert gate.get_loss() is not None


def test_moe_backward_flows_to_gate_and_experts():
    paddle.seed(5)
    d_model, E = 8, 4
    layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 16),
                         gate=moe.GShardGate(d_model, E, random_routing=False))
    layer.train()
    x = paddle.to_tensor(np.random.randn(16, d_model).astype(np.float32))
    x.stop_gradient = False
    out = layer(x)
    loss = (out * out).mean() + layer.gate.get_loss()
    loss.backward()
    assert layer.experts.w1.grad is not None
    assert float(np.abs(layer.experts.w1.grad.numpy()).sum()) > 0
    assert layer.gate.gate_weight.grad is not None
    assert float(np.abs(layer.gate.gate_weight.grad.numpy()).sum()) > 0
    assert x.grad is not None


def test_gshard_random_routing_drops_not_doubles():
    """Dropped 2nd routes vanish (-1 sentinel) rather than double-count e0."""
    paddle.seed(9)
    d_model, E = 8, 4
    gate = moe.GShardGate(d_model, E, random_routing=True, capacity=(10.0, 10.0))
    layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 16), gate=gate)
    layer.train()
    x = np.random.randn(32, d_model).astype(np.float32)
    out = layer(paddle.to_tensor(x))
    assert np.isfinite(out.numpy()).all()
    # per-token combine mass never exceeds p1+p2 (no double-counted expert):
    w = gate.gate_weight.numpy()
    b = gate.gate_bias.numpy()
    logits = x @ w + b
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    top2 = np.sort(probs, axis=-1)[:, -2:].sum(-1)
    combine, disp, _ = gate._route(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b),
        jax.random.PRNGKey(0), True)
    mass = np.asarray(combine).sum(axis=(1, 2))
    assert (mass <= top2 + 1e-5).all()


def test_moe_ep_sharded_matches_unsharded():
    """EP over the dp axis: same numbers as the unsharded run, expert dim
    really sharded (loss-parity strategy, SURVEY §4)."""
    paddle.seed(11)
    d_model, E = 16, 8
    ref_layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 32),
                             gate=moe.NaiveGate(d_model, E, topk=2))
    x = np.random.randn(12, d_model).astype(np.float32)
    ref = ref_layer(paddle.to_tensor(x)).numpy()

    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(11)
        layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 32),
                             gate=moe.NaiveGate(d_model, E, topk=2),
                             moe_group=("dp",))
        assert layer._ep_axes == ("dp",)
        # expert dim sharded 8/4=2 per dp rank
        assert {s.data.shape for s in layer.experts.w1._array.addressable_shards} \
            == {(2, d_model, 32)}
        out = layer(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    finally:
        dist.set_hybrid_communicate_group(None)


def test_moe_ep_under_jit_train_step():
    """MoE inside a jitted loss/grad step with EP sharding compiles and runs."""
    strategy = dist.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=strategy)
    try:
        paddle.seed(2)
        d_model, E = 8, 8
        layer = moe.MoELayer(d_model, moe.GroupedMLP(E, d_model, 16),
                             gate=moe.SwitchGate(d_model, E, switch_eps=0.0),
                             moe_group=("dp",))
        layer.train()
        state = layer.functional_state()
        import jax as _jax

        from paddle_tpu.tensor_class import wrap, unwrap

        def loss_fn(state, xs):
            layer.load_functional_state(state)
            out = layer(wrap(xs))
            return (unwrap(out) ** 2).mean()

        xs = jnp.asarray(np.random.randn(8, d_model), jnp.float32)
        val, grads = _jax.jit(_jax.value_and_grad(loss_fn))(state, xs)
        assert np.isfinite(float(val))
        leaves = _jax.tree_util.tree_leaves(grads)
        assert any(float(jnp.abs(l).sum()) > 0 for l in leaves)
    finally:
        dist.set_hybrid_communicate_group(None)


def test_global_scatter_gather_roundtrip():
    world, n_expert, M = 2, 2, 4
    rng = np.random.RandomState(0)
    # rank0 sends [2,0,2,0]; rank1 sends [1,1,0,2] (i = dst*n_expert + e)
    lc = np.array([[2, 0, 2, 0], [1, 1, 0, 2]], np.int64)
    # global_count[dst, i] with i = src*n_expert + e: receives from each src
    gc = np.zeros_like(lc)
    for dst in range(world):
        for src in range(world):
            for e in range(n_expert):
                gc[dst, src * n_expert + e] = lc[src, dst * n_expert + e]
    batch = int(lc.sum(1).max())
    x = np.zeros((world, batch, M), np.float32)
    for r in range(world):
        n = int(lc[r].sum())
        x[r, :n] = rng.randn(n, M)
    xs = moe.global_scatter(paddle.to_tensor(x), paddle.to_tensor(lc),
                            paddle.to_tensor(gc))
    # rank0 receives: e0 ← src0's 2 (seg i=0) + src1's 1 (seg i=0); e1 ← src1's 1
    np.testing.assert_allclose(xs.numpy()[0, :2], x[0, :2])   # src0 → e0
    np.testing.assert_allclose(xs.numpy()[0, 2:3], x[1, :1])  # src1 → e0
    np.testing.assert_allclose(xs.numpy()[0, 3:4], x[1, 1:2])  # src1 → e1
    back = moe.global_gather(xs, paddle.to_tensor(lc), paddle.to_tensor(gc))
    for r in range(world):
        n = int(lc[r].sum())
        np.testing.assert_allclose(back.numpy()[r, :n], x[r, :n])


def test_dispatch_vectorized_matches_loop_semantics():
    """The k-major vectorized dispatch must equal the reference loop
    (cumsum positions, k=0 routes take slots before k=1) incl. drops."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.moe import one_hot_dispatch

    rng = np.random.RandomState(0)
    S, E, K, C = 16, 4, 2, 5
    probs = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.randn(S, E)), -1))
    idx = jnp.asarray(rng.randint(0, E, (S, K)))

    def loop_ref(probs, topk_idx, capacity):
        base = jnp.zeros((E,), jnp.int32)
        combine = jnp.zeros((S, E, capacity), probs.dtype)
        for i in range(K):
            mask = jax.nn.one_hot(topk_idx[:, i], E, dtype=jnp.int32)
            pos = (jnp.cumsum(mask, axis=0) - 1) + base[None, :]
            base = base + jnp.sum(mask, axis=0)
            keep = mask * (pos < capacity)
            pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                                    dtype=probs.dtype)
            combine = combine + (keep.astype(probs.dtype) * probs)[:, :, None] * pos_oh
        return combine

    got, disp = one_hot_dispatch(probs, idx, C)
    ref = loop_ref(probs, idx, C)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(disp), np.asarray(ref) > 0)


def test_naive_gate_default_capacity_finite():
    from paddle_tpu.distributed.moe import NaiveGate, compute_capacity

    g = NaiveGate(8, 4, topk=2)
    assert g.capacity_factor == 2.0  # finite by default (VERDICT r2 item 9)
    # no-drop is an explicit opt-in
    g2 = NaiveGate(8, 4, topk=2, capacity_factor=None)
    assert g2.capacity_factor is None
    assert compute_capacity(128, 4, 2, 2.0) == 128


def test_grouped_mlp_ragged_matches_batch():
    """ragged_dot grouped GEMM == looped per-expert FFN on sorted tokens."""
    from paddle_tpu.distributed.moe import GroupedMLP

    paddle.seed(0)
    E, M, H = 3, 8, 16
    mlp = GroupedMLP(E, M, H, activation="gelu")
    rng = np.random.RandomState(1)
    sizes = np.array([4, 0, 6])  # includes an empty expert
    x = rng.randn(int(sizes.sum()), M).astype("float32")
    out = mlp.forward_ragged(paddle.to_tensor(x),
                             paddle.to_tensor(sizes.astype("int32"))).numpy()

    # reference: run each expert's slice through its own weights
    w1 = mlp.w1.numpy(); b1 = mlp.b1.numpy()
    w2 = mlp.w2.numpy(); b2 = mlp.b2.numpy()
    import jax

    start = 0
    for e, n in enumerate(sizes):
        if n == 0:
            continue
        seg = x[start:start + n]
        h = np.asarray(jax.nn.gelu(seg @ w1[e] + b1[e, 0], approximate=False))
        ref = h @ w2[e] + b2[e, 0]
        np.testing.assert_allclose(out[start:start + n], ref, rtol=2e-4,
                                   atol=2e-5)
        start += n


def test_llama_moe_ep_sharded_flagship():
    """The flagship MoE LM (DeepSeekMoE/Qwen2-MoE family) constructed under
    a hybrid topology gets its expert dims EP-sharded over the data axes,
    and the full hybrid train step (ep x mp) matches the unsharded loss."""
    from paddle_tpu.models.llama_moe import LlamaMoEConfig, LlamaMoEForCausalLM
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.engine import parallelize

    ids = np.random.RandomState(0).randint(0, 512, (4, 33))

    def build_and_step(hybrid):
        if hybrid:
            strategy = dist.DistributedStrategy()
            strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
            dist.fleet.init(is_collective=True, strategy=strategy)
        paddle.seed(7)
        cfg = LlamaMoEConfig.tiny_moe(num_hidden_layers=2)
        m = LlamaMoEForCausalLM(cfg)
        o = opt.AdamW(1e-3, parameters=m.parameters())

        def loss_fn(mm, x, y):
            loss, _ = mm(x, labels=y)
            return loss

        if hybrid:
            # every MoE layer's experts must really be EP-sharded: E=4 over
            # dp4 -> one expert slice per dp rank
            mlp = m.llama.layers[1].mlp
            assert mlp._ep_axes == ("dp",)
            shapes = {s.data.shape
                      for s in mlp.experts.w1._array.addressable_shards}
            # swiglu experts fuse gate||up: 2*moe_intermediate_size wide
            assert shapes == {(1, cfg.hidden_size,
                               2 * cfg.moe_intermediate_size)}
            step = parallelize(m, loss_fn, o)
        else:
            step = paddle.jit.train_step(m, loss_fn, o)
        loss = step(paddle.to_tensor(ids[:, :-1]),
                    paddle.to_tensor(ids[:, 1:]))
        return float(loss.numpy())

    try:
        ep_loss = build_and_step(True)
    finally:
        dist.set_hybrid_communicate_group(None)
    ref_loss = build_and_step(False)
    assert np.isfinite(ep_loss)
    np.testing.assert_allclose(ep_loss, ref_loss, rtol=2e-4)
