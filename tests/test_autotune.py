"""Kernel block-geometry autotune (ops/pallas/autotune.py — the analog of
paddle/phi/kernels/autotune/cache.h + switch_autotune.cc): flag-gated
measurement, persisted cross-process cache, heuristic fallback."""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (flag registry init)
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.utils.flags import get_flags, set_flags


@pytest.fixture
def tuned_cache(tmp_path, monkeypatch):
    """Point the autotune cache at a throwaway file and restore the flag."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", path)
    prev = get_flags("FLAGS_use_autotune")["FLAGS_use_autotune"]
    yield path
    set_flags({"FLAGS_use_autotune": prev})


def _runner_factory(timings, calls):
    """Candidate runner whose fake work duration comes from ``timings``."""
    import time

    def runner(cfg):
        def run():
            calls.append(cfg)
            time.sleep(timings[cfg])
            return np.zeros(())
        return run
    return runner


class TestPick:
    def test_flag_off_returns_default_and_never_measures(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": False})
        calls = []
        got = autotune.pick("k", "key", (256,), [(128,), (64,)],
                            _runner_factory({}, calls), can_measure=True)
        assert got == (256,)
        assert calls == []
        assert not os.path.exists(tuned_cache)

    def test_measures_picks_fastest_and_persists(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        calls = []
        timings = {(128,): 0.03, (64,): 0.001, (32,): 0.02}
        got = autotune.pick("k", "rows128 d256", (128,), list(timings),
                            _runner_factory(timings, calls),
                            can_measure=True, log=False)
        assert got == (64,)
        assert set(calls) == set(timings)
        # persisted: a FRESH cache object (new process analog) sees it
        data = json.load(open(tuned_cache))
        assert data["k"][autotune.full_key("rows128 d256")]["choice"] == [64]
        fresh = autotune.AutotuneCache(tuned_cache)
        assert fresh.get("k", autotune.full_key("rows128 d256")) == [64]

    def test_cache_hit_skips_measurement(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        autotune.get_cache().put("k", autotune.full_key("sig"), (32,), 1.0)
        calls = []
        got = autotune.pick("k", "sig", (128,), [(128,), (32,)],
                            _runner_factory({}, calls), can_measure=True)
        assert got == (32,) and calls == []

    def test_no_measure_context_returns_default(self, tuned_cache):
        """Traced / off-TPU callers pass can_measure=False: cache miss must
        fall back to the heuristic default, not try to time tracers."""
        set_flags({"FLAGS_use_autotune": True})
        got = autotune.pick("k", "other", (128,), [(64,)],
                            _runner_factory({}, []), can_measure=False)
        assert got == (128,)

    def test_failing_candidates_lose(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})

        def runner(cfg):
            def run():
                if cfg == (512,):
                    raise RuntimeError("VMEM OOM")  # oversized block
                return np.zeros(())
            return run

        got = autotune.pick("k", "oom", (128,), [(512,), (64,)], runner,
                            can_measure=True, log=False)
        assert got == (64,)


class TestKernelIntegration:
    def test_rms_norm_uses_cached_block_and_stays_correct(self, tuned_cache):
        """A cached (non-default) geometry is honored by the kernel wrapper
        and does not change numerics."""
        from paddle_tpu.ops.pallas import fused_norm as fn

        set_flags({"FLAGS_use_autotune": True})
        rows, d = 64, 256
        autotune.get_cache().put("rms_norm",
                                 autotune.full_key(f"rows{rows} d{d} float32"),
                                 (8,), 1.0)
        block = fn._tuned_block_rows("rms_norm", rows, d, jnp.float32, None)
        assert block == 8 and block != fn._pick_block_rows(rows, d)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 16, d), jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).randn(d), jnp.float32)
        np.testing.assert_allclose(np.asarray(fn.rms_norm(x, w)),
                                   np.asarray(fn._rmsnorm_ref(x, w, 1e-6)),
                                   rtol=1e-5, atol=1e-5)

    def test_flash_candidates_respect_divisibility(self, tuned_cache):
        """The splash candidate grid only offers blocks that divide the
        sequence; with the flag on but nothing measurable (CPU), the
        heuristic default survives and the kernel still runs."""
        from paddle_tpu.ops.pallas import flash_attention as pf

        set_flags({"FLAGS_use_autotune": True})
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
        out = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        assert out.shape == q.shape
        assert not os.path.exists(tuned_cache)  # nothing was measured

    def test_flash_reads_cached_geometry(self, tuned_cache):
        from paddle_tpu.ops.pallas import flash_attention as pf

        set_flags({"FLAGS_use_autotune": True})
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
        key = (f"q{tuple(q.shape)} kv{tuple(q.shape)} {q.dtype} "
               "causal=True win=None")
        autotune.get_cache().put("splash_mha", autotune.full_key(key),
                                 (128, 128), 1.0)
        out = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        # parity against the non-tuned geometry (the 256-block default)
        set_flags({"FLAGS_use_autotune": False})
        out2 = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   rtol=1e-5, atol=1e-5)
