"""Kernel block-geometry autotune (ops/pallas/autotune.py — the analog of
paddle/phi/kernels/autotune/cache.h + switch_autotune.cc): flag-gated
measurement, persisted cross-process cache, heuristic fallback."""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu  # noqa: F401  (flag registry init)
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.utils.flags import get_flags, set_flags


@pytest.fixture
def tuned_cache(tmp_path, monkeypatch):
    """Point the autotune cache at a throwaway file and restore the flag."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", path)
    prev = get_flags("FLAGS_use_autotune")["FLAGS_use_autotune"]
    yield path
    set_flags({"FLAGS_use_autotune": prev})


def _runner_factory(timings, calls):
    """Candidate runner whose fake work duration comes from ``timings``."""
    import time

    def runner(cfg):
        def run():
            calls.append(cfg)
            time.sleep(timings[cfg])
            return np.zeros(())
        return run
    return runner


class TestPick:
    def test_flag_off_returns_default_and_never_measures(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": False})
        calls = []
        got = autotune.pick("k", "key", (256,), [(128,), (64,)],
                            _runner_factory({}, calls), can_measure=True)
        assert got == (256,)
        assert calls == []
        assert not os.path.exists(tuned_cache)

    def test_measures_picks_fastest_and_persists(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        calls = []
        timings = {(128,): 0.03, (64,): 0.001, (32,): 0.02}
        got = autotune.pick("k", "rows128 d256", (128,), list(timings),
                            _runner_factory(timings, calls),
                            can_measure=True, log=False)
        assert got == (64,)
        assert set(calls) == set(timings)
        # persisted: a FRESH cache object (new process analog) sees it
        data = json.load(open(tuned_cache))
        assert data["k"][autotune.full_key("rows128 d256")]["choice"] == [64]
        fresh = autotune.AutotuneCache(tuned_cache)
        assert fresh.get("k", autotune.full_key("rows128 d256")) == [64]

    def test_cache_hit_skips_measurement(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        autotune.get_cache().put("k", autotune.full_key("sig"), (32,), 1.0)
        calls = []
        got = autotune.pick("k", "sig", (128,), [(128,), (32,)],
                            _runner_factory({}, calls), can_measure=True)
        assert got == (32,) and calls == []

    def test_no_measure_context_returns_default(self, tuned_cache):
        """Traced / off-TPU callers pass can_measure=False: cache miss must
        fall back to the heuristic default, not try to time tracers."""
        set_flags({"FLAGS_use_autotune": True})
        got = autotune.pick("k", "other", (128,), [(64,)],
                            _runner_factory({}, []), can_measure=False)
        assert got == (128,)

    def test_failing_candidates_lose(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})

        def runner(cfg):
            def run():
                if cfg == (512,):
                    raise RuntimeError("VMEM OOM")  # oversized block
                return np.zeros(())
            return run

        got = autotune.pick("k", "oom", (128,), [(512,), (64,)], runner,
                            can_measure=True, log=False)
        assert got == (64,)


class TestSearch:
    """The staged search: cost-table recording, failure pruning,
    roofline ranking, deferred flush (PR 7)."""

    def test_cost_table_records_all_candidates_and_roundtrips(
            self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        calls = []
        timings = {(128,): 0.02, (64,): 0.001, (32,): 0.01}
        params = {"rows": 128, "d": 64, "dtype": "float32"}

        def cost_model(cfg):
            return {"bytes": 1000, "flops": 2000, "vmem_bytes": 10,
                    "grid": 128 // cfg[0]}

        got = autotune.search("k", "sig", (128,), list(timings),
                              _runner_factory(timings, calls),
                              can_measure=True, params=params,
                              cost_model=cost_model, log=False)
        assert got == (64,)
        assert set(calls) == set(timings)
        # a FRESH cache object (new-process analog) reads the full table
        fresh = autotune.AutotuneCache(tuned_cache)
        ent = fresh.entry("k", autotune.full_key("sig"))
        assert ent["choice"] == [64]
        assert ent["params"] == params
        assert ent["est"]["bytes"] == 1000 and ent["est"]["flops"] == 2000
        table = ent["table"]
        assert set(table) == {"128", "64", "32"}
        assert all(r["status"] == "ok" and r["ms"] >= 0
                   for r in table.values())

    def test_failures_recorded_and_never_retried(self, tuned_cache):
        """An OOM-ing geometry is measured at most ONCE per device: the
        failure lands in the cost table (kind + message) and later
        searches prune it instead of launching it again."""
        set_flags({"FLAGS_use_autotune": True})
        calls = []

        def runner(cfg):
            def run():
                calls.append(cfg)
                raise RuntimeError("VMEM OOM")
            return run

        got = autotune.search("k", "oom", (128,), [(512,), (256,)],
                              runner, can_measure=True, log=False)
        assert got == (128,)          # no winner: heuristic default
        assert len(calls) == 2
        fresh = autotune.AutotuneCache(tuned_cache)
        ent = fresh.entry("k", autotune.full_key("oom"))
        assert ent["table"]["512"]["status"] == "fail"
        assert "VMEM OOM" in ent["table"]["512"]["error"]
        assert fresh.failures("k", autotune.full_key("oom")) == {
            (512,), (256,)}
        # second search: both candidates pruned, nothing launched
        calls.clear()
        got = autotune.search("k", "oom", (128,), [(512,), (256,)],
                              runner, can_measure=True, log=False)
        assert got == (128,) and calls == []

    def test_roofline_pruning_drops_infeasible_and_ranks(
            self, tuned_cache):
        """A VMEM-infeasible candidate is recorded without launching;
        max_measure keeps only the best-ranked survivors."""
        set_flags({"FLAGS_use_autotune": True})
        calls = []
        timings = {(64,): 0.002, (32,): 0.002, (16,): 0.002}

        def cost_model(cfg):
            (b,) = cfg
            return {"bytes": 1000, "flops": 1000,
                    "vmem_bytes": 10 ** 9 if b == 16 else 10,
                    "grid": 128 // b}  # fewer grid steps rank better

        got = autotune.search("k", "pruned", (128,),
                              [(64,), (32,), (16,)],
                              _runner_factory(timings, calls),
                              can_measure=True, cost_model=cost_model,
                              max_measure=1, log=False)
        assert set(calls) == {(64,)}  # only the best-ranked survivor
        assert got == (64,)
        fresh = autotune.AutotuneCache(tuned_cache)
        tab = fresh.entry("k", autotune.full_key("pruned"))["table"]
        assert tab["16"]["status"] == "infeasible"
        assert "vmem" in tab["16"]["reason"]

    def test_sweep_records_flightrecorder_event(self, tuned_cache):
        from paddle_tpu.observability import flightrecorder as frec

        set_flags({"FLAGS_use_autotune": True})
        rec = frec.get_recorder()
        rec.clear()
        rec.enabled = True  # not enable(): skip the compile-events hook
        try:
            autotune.pick("k", "audited", (64,), [(64,), (32,)],
                          _runner_factory({(64,): 0.001, (32,): 0.002},
                                          []),
                          can_measure=True, log=False)
            evs = rec.events(kind="autotune.sweep")
            assert evs and evs[0]["kernel"] == "k"
            assert evs[0]["choice"] == [64]
            assert evs[0]["measured"] == 2
        finally:
            rec.enabled = False
            rec.clear()

    def test_deferred_flush(self, tuned_cache):
        """put() batches in memory; the file appears on flush (sweep
        end / atexit / incident), not per entry."""
        set_flags({"FLAGS_use_autotune": True})
        cache = autotune.get_cache()
        cache.put("k", "sig", (8,), 1.0)
        assert not os.path.exists(tuned_cache)
        cache.flush()
        assert json.load(open(tuned_cache))["k"]["sig"]["choice"] == [8]
        assert autotune._ATEXIT_REGISTERED  # atexit flush armed

    def test_incident_flush_path(self, tuned_cache):
        """The cache is tracked by the observability flush set: the
        incident reporter's flush_all_writers persists a mid-search
        table."""
        from paddle_tpu.observability.snapshot import flush_all_writers

        set_flags({"FLAGS_use_autotune": True})
        autotune.get_cache().put("k", "mid-search", (4,), 2.0)
        assert not os.path.exists(tuned_cache)
        flush_all_writers()
        assert json.load(open(tuned_cache))["k"]["mid-search"][
            "choice"] == [4]

    def test_corrupt_cache_file_starts_empty(self, tuned_cache):
        with open(tuned_cache, "w") as f:
            f.write("{ not json")
        fresh = autotune.AutotuneCache(tuned_cache)
        assert fresh.get("k", "sig") is None  # logged, not raised


class TestStaleness:
    """The guard at the cache-hit stage: a persisted winner whose
    geometry no longer fits the current candidate space must fall back
    (satellite: it had no test)."""

    def test_stale_winner_falls_back_to_default(self, tuned_cache):
        set_flags({"FLAGS_use_autotune": True})
        autotune.get_cache().put("k", autotune.full_key("shape"),
                                 (48,), 1.0)
        got = autotune.pick("k", "shape", (128,), [(64,), (32,)],
                            _runner_factory({}, []), can_measure=False)
        assert got == (128,)  # (48,) not in the space: heuristic wins

    def test_stale_winner_kernel_integration(self, tuned_cache):
        """A persisted rms_norm block that no longer divides the row
        count is ignored by the kernel wrapper — numerics unchanged."""
        from paddle_tpu.ops.pallas import fused_norm as fn

        set_flags({"FLAGS_use_autotune": True})
        rows, d = 64, 256
        autotune.get_cache().put(
            "rms_norm", autotune.full_key(f"rows{rows} d{d} float32"),
            (48,), 1.0)  # 64 % 48 != 0: not in the candidate space
        block = fn._tuned_block_rows("rms_norm", rows, d, jnp.float32,
                                     None)
        assert block == fn._pick_block_rows(rows, d)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 16, d),
                        jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).randn(d), jnp.float32)
        np.testing.assert_allclose(np.asarray(fn.rms_norm(x, w)),
                                   np.asarray(fn._rmsnorm_ref(x, w, 1e-6)),
                                   rtol=1e-5, atol=1e-5)


class TestKernelIntegration:
    def test_rms_norm_uses_cached_block_and_stays_correct(self, tuned_cache):
        """A cached (non-default) geometry is honored by the kernel wrapper
        and does not change numerics."""
        from paddle_tpu.ops.pallas import fused_norm as fn

        set_flags({"FLAGS_use_autotune": True})
        rows, d = 64, 256
        autotune.get_cache().put("rms_norm",
                                 autotune.full_key(f"rows{rows} d{d} float32"),
                                 (8,), 1.0)
        block = fn._tuned_block_rows("rms_norm", rows, d, jnp.float32, None)
        assert block == 8 and block != fn._pick_block_rows(rows, d)
        x = jnp.asarray(np.random.RandomState(0).randn(4, 16, d), jnp.float32)
        w = jnp.asarray(np.random.RandomState(1).randn(d), jnp.float32)
        np.testing.assert_allclose(np.asarray(fn.rms_norm(x, w)),
                                   np.asarray(fn._rmsnorm_ref(x, w, 1e-6)),
                                   rtol=1e-5, atol=1e-5)

    def test_flash_candidates_respect_divisibility(self, tuned_cache):
        """The splash candidate grid only offers blocks that divide the
        sequence; with the flag on but nothing measurable (CPU), the
        heuristic default survives and the kernel still runs."""
        from paddle_tpu.ops.pallas import flash_attention as pf

        set_flags({"FLAGS_use_autotune": True})
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
        out = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        assert out.shape == q.shape
        assert not os.path.exists(tuned_cache)  # nothing was measured

    def test_flash_reads_cached_geometry(self, tuned_cache):
        from paddle_tpu.ops.pallas import flash_attention as pf

        set_flags({"FLAGS_use_autotune": True})
        rng = np.random.RandomState(3)
        q = jnp.asarray(rng.randn(1, 256, 2, 128), jnp.float32)
        key = (f"q{tuple(q.shape)} kv{tuple(q.shape)} {q.dtype} "
               "causal=True win=None")
        autotune.get_cache().put("splash_mha", autotune.full_key(key),
                                 (128, 128), 1.0)
        out = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        # parity against the non-tuned geometry (the 256-block default)
        set_flags({"FLAGS_use_autotune": False})
        out2 = pf.flash_attention_bshd(q, q, q, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   rtol=1e-5, atol=1e-5)
