"""Round-3 nn tail: numeric references for the new F.*/nn.* surface
(rnnt_loss DP, hierarchical sigmoid, pooling masks, adaptive softmax,
flashmask attention, beam decode)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def _rnnt_ref(logits, labels, blank=0):
    """Direct O(T*U) log-space DP in numpy (per sample)."""
    T, U1, V = logits.shape
    U = U1 - 1
    lp = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
    alpha = np.full((T, U1), -np.inf)
    alpha[0, 0] = 0.0
    for t_idx in range(T):
        for u in range(U1):
            acc = []
            if t_idx > 0:
                acc.append(alpha[t_idx - 1, u] + lp[t_idx - 1, u, blank])
            if u > 0:
                acc.append(alpha[t_idx, u - 1] + lp[t_idx, u - 1, labels[u - 1]])
            if acc:
                m = max(acc)
                alpha[t_idx, u] = m + np.log(sum(np.exp(a - m) for a in acc))
    return -(alpha[T - 1, U] + lp[T - 1, U, blank])


def test_rnnt_loss_matches_dp_reference():
    rng = np.random.RandomState(0)
    B, T, U, V = 2, 5, 3, 6
    logits = rng.randn(B, T, U + 1, V).astype("float32")
    labels = rng.randint(1, V, (B, U))
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(np.array([T, T])),
                      paddle.to_tensor(np.array([U, U])),
                      reduction="none")
    want = np.array([_rnnt_ref(logits[b], labels[b]) for b in range(B)])
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-4, atol=1e-4)


def test_rnnt_loss_respects_lengths():
    rng = np.random.RandomState(1)
    B, T, U, V = 2, 6, 3, 5
    logits = rng.randn(B, T, U + 1, V).astype("float32")
    labels = rng.randint(1, V, (B, U))
    # sample 1 uses only T-2 frames / U-1 labels
    got = F.rnnt_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                      paddle.to_tensor(np.array([T, T - 2])),
                      paddle.to_tensor(np.array([U, U - 1])),
                      reduction="none").numpy()
    want = _rnnt_ref(logits[1][: T - 2, : U, :], labels[1][: U - 1])
    np.testing.assert_allclose(got[1], want, rtol=1e-4, atol=1e-4)


def test_max_pool_return_mask_and_unpool_roundtrip():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 6, 8).astype("float32")
    t = paddle.to_tensor(x)
    mx, idx = F.max_pool2d(t, 2, 2, return_mask=True)
    # values match plain pooling; indices point at the max elements
    np.testing.assert_allclose(mx.numpy(), F.max_pool2d(t, 2, 2).numpy())
    flat = x.reshape(2, 3, -1)
    np.testing.assert_allclose(
        np.take_along_axis(flat, idx.numpy().reshape(2, 3, -1), -1),
        mx.numpy().reshape(2, 3, -1))
    # unpool scatters each max back to its recorded position
    un = F.max_unpool2d(mx, idx, 2, 2).numpy()
    assert un.shape == x.shape
    np.testing.assert_allclose(np.sort(un[un != 0]),
                               np.sort(mx.numpy().reshape(-1)))


def test_fractional_pool_partitions_input():
    x = paddle.to_tensor(np.arange(64, dtype="float32").reshape(1, 1, 8, 8))
    out = F.fractional_max_pool2d(x, 4)
    assert out.shape == [1, 1, 4, 4]
    # global max must survive any pooling partition
    assert float(out.numpy().max()) == 63.0
    out3 = F.fractional_max_pool3d(
        paddle.to_tensor(np.arange(216, dtype="float32").reshape(1, 1, 6, 6, 6)), 2)
    assert out3.shape == [1, 1, 2, 2, 2]
    assert float(out3.numpy().max()) == 215.0


def test_hsigmoid_loss_binary_tree():
    """num_classes=2: the tree has one internal node → plain logistic loss."""
    rng = np.random.RandomState(3)
    x = rng.randn(4, 5).astype("float32")
    w = rng.randn(1, 5).astype("float32")
    lab = np.array([0, 1, 0, 1])
    got = F.hsigmoid_loss(paddle.to_tensor(x), paddle.to_tensor(lab), 2,
                          paddle.to_tensor(w)).numpy()
    logit = x @ w.T
    # leaf l ↔ node 2+l; bit for class 0 is 0, class 1 is 1
    z = (1 - 2 * lab)[:, None] * logit
    want = np.log1p(np.exp(-np.abs(z))) + np.maximum(z, 0)
    np.testing.assert_allclose(got, want.sum(-1).mean(), rtol=1e-5)


def test_hsigmoid_loss_grad_flows():
    w = paddle.create_parameter([9, 8], "float32")
    x = paddle.to_tensor(np.random.rand(4, 8).astype("float32"),
                         stop_gradient=False)
    loss = F.hsigmoid_loss(x, paddle.to_tensor(np.array([1, 4, 7, 9])), 10, w)
    loss.backward()
    assert x.grad is not None and np.isfinite(x.grad.numpy()).all()


def test_adaptive_log_softmax_normalizes():
    als = nn.AdaptiveLogSoftmaxWithLoss(8, 12, [4, 8])
    lp = als.log_prob(paddle.to_tensor(np.random.rand(3, 8).astype("float32")))
    np.testing.assert_allclose(np.exp(lp.numpy()).sum(-1), 1.0, rtol=1e-4)
    out, loss = als(paddle.to_tensor(np.random.rand(5, 8).astype("float32")),
                    paddle.to_tensor(np.array([0, 3, 6, 9, 11])))
    # per-sample outputs are the label log-probs; loss is their negative mean
    np.testing.assert_allclose(-out.numpy().mean(), loss.numpy(), rtol=1e-5)


def test_gather_tree_traces_parents():
    # T=3, batch=1, beam=2
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]])
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]])
    out = F.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(parents)).numpy()
    # beam 0 at t=2 came from parent 0 at t=2 → which came from parent 1 at t=1
    assert out[2, 0, 0] == 5
    assert out[1, 0, 0] == 3  # parent chain: t2 beam0 -> t1 beam0? parents[2,0,0]=0 -> t1 beam0 id 3
    assert out[0, 0, 0] == 2  # parents[1,0,0]=1 -> t0 beam1 id 2


def test_flashmask_attention_matches_dense_mask():
    rng = np.random.RandomState(5)
    B, S, H, D = 1, 6, 2, 8
    q = rng.randn(B, S, H, D).astype("float32")
    # startend_row_indices [B, 1, S, 1]: causal masking starts at row s[i]
    start = np.array([6, 6, 4, 4, 6, 6])  # keys 2,3 masked for rows >= 4
    se = start.reshape(1, 1, S, 1)
    got = F.flashmask_attention(paddle.to_tensor(q), paddle.to_tensor(q),
                                paddle.to_tensor(q), paddle.to_tensor(se),
                                causal=True).numpy()
    # dense reference
    qh = np.moveaxis(q, 2, 1)
    scores = qh @ np.swapaxes(qh, -1, -2) / np.sqrt(D)
    rows = np.arange(S)[:, None]
    cols = np.arange(S)[None, :]
    allow = (rows >= cols) & ~(rows >= start[None, :])
    scores = np.where(allow, scores, -1e9)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.moveaxis(p @ qh, 1, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_sparse_attention_csr_mask():
    rng = np.random.RandomState(6)
    B, H, S, D = 1, 1, 4, 8
    q = rng.randn(B, H, S, D).astype("float32")
    # CSR pattern: row i attends to columns {0, i}
    offs = np.array([[[0, 2, 4, 6, 8]]])
    cols = np.array([[[0, 0, 0, 1, 0, 2, 0, 3]]])
    out = F.sparse_attention(paddle.to_tensor(q), paddle.to_tensor(q),
                             paddle.to_tensor(q), paddle.to_tensor(offs),
                             paddle.to_tensor(cols)).numpy()
    scores = q[0, 0] @ q[0, 0].T / np.sqrt(D)
    mask = np.zeros((S, S), bool)
    for i in range(S):
        mask[i, 0] = True
        mask[i, i] = True
    scores = np.where(mask, scores, -1e9)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    np.testing.assert_allclose(out[0, 0], p @ q[0, 0], rtol=1e-4, atol=1e-5)


def test_rnn_birnn_and_decode():
    paddle.seed(0)
    cell = nn.GRUCell(4, 6)
    out, state = nn.RNN(cell)(paddle.randn([2, 5, 4]))
    assert out.shape == [2, 5, 6]
    # reverse RNN sees the sequence backwards
    out_r, _ = nn.RNN(cell, is_reverse=True)(paddle.randn([2, 5, 4]))
    assert out_r.shape == [2, 5, 6]
    bi = nn.BiRNN(nn.GRUCell(4, 6), nn.GRUCell(4, 6))
    out_b, _ = bi(paddle.randn([2, 5, 4]))
    assert out_b.shape == [2, 5, 12]
    dec = nn.BeamSearchDecoder(
        nn.GRUCell(3, 6), start_token=paddle.zeros([2], "int64"),
        end_token=7, beam_size=2, embedding_fn=nn.Embedding(8, 3),
        output_fn=nn.Linear(6, 8))
    ids, lp = nn.dynamic_decode(dec, max_step_num=4)
    assert ids.shape[0] == 2 and ids.shape[2] == 2 and lp.shape == [2, 2]
    # beam log-probs sorted descending
    assert (np.diff(lp.numpy(), axis=-1) <= 1e-6).all()


def test_parameter_dict_registers():
    pd = nn.ParameterDict({"w": paddle.create_parameter([2, 2], "float32")})
    pd["b"] = paddle.create_parameter([3], "float32")
    assert set(pd.keys()) == {"w", "b"}
    names = dict(pd.named_parameters()).keys()
    assert len(names) == 2
    assert "w" in pd and len(pd) == 2


def test_inplace_activations_and_losses():
    x = paddle.to_tensor(np.array([-1.0, 2.0], dtype="float32"))
    F.elu_(x)
    np.testing.assert_allclose(x.numpy()[1], 2.0)
    y = paddle.to_tensor(np.array([-3.0, 3.0], dtype="float32"))
    F.hardtanh_(y)
    np.testing.assert_allclose(y.numpy(), [-1.0, 1.0])
    # dice loss on a perfect prediction is ~0
    lbl = np.array([[[0], [1]]])
    pred = np.zeros((1, 2, 2), "float32")
    pred[0, 0, 0] = 1
    pred[0, 1, 1] = 1
    assert float(F.dice_loss(paddle.to_tensor(pred),
                             paddle.to_tensor(lbl)).numpy()) < 1e-3


def test_class_center_sample_contains_positives():
    lab = paddle.to_tensor(np.array([2, 2, 8, 5]))
    remapped, centers = F.class_center_sample(lab, 10, 6)
    c = centers.numpy()
    assert {2, 5, 8}.issubset(set(c.tolist())) and c.size == 6
    # remapped labels index into the sampled centers
    np.testing.assert_array_equal(c[remapped.numpy()], lab.numpy())


def test_functional_tail_wrappers():
    """Direct coverage for the remaining F round-3 entries: bilinear,
    zeropad2d, pairwise_distance, poisson/gaussian NLL, lp_pool1d,
    feature_alpha_dropout, flash_attn_qkvpacked."""
    rng = np.random.RandomState(7)
    x1 = rng.randn(3, 5).astype("float32")
    x2 = rng.randn(3, 4).astype("float32")
    w = rng.randn(6, 5, 4).astype("float32")
    b = rng.randn(6).astype("float32")
    got = F.bilinear(paddle.to_tensor(x1), paddle.to_tensor(x2),
                     paddle.to_tensor(w), paddle.to_tensor(b)).numpy()
    want = np.einsum("bi,oij,bj->bo", x1, w, x2) + b
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    z = F.zeropad2d(paddle.ones([1, 1, 2, 2]), [1, 2, 3, 4]).numpy()
    assert z.shape == (1, 1, 9, 5) and z.sum() == 4.0

    a = rng.randn(4, 8).astype("float32")
    c = rng.randn(4, 8).astype("float32")
    np.testing.assert_allclose(
        F.pairwise_distance(paddle.to_tensor(a), paddle.to_tensor(c)).numpy(),
        np.linalg.norm(a - c + 1e-6, axis=-1), rtol=1e-5)

    inp = rng.rand(6).astype("float32") + 0.5
    lbl = rng.poisson(2.0, 6).astype("float32")
    np.testing.assert_allclose(
        F.poisson_nll_loss(paddle.to_tensor(inp), paddle.to_tensor(lbl)).numpy(),
        (np.exp(inp) - lbl * inp).mean(), rtol=1e-5)
    var = rng.rand(6).astype("float32") + 0.1
    np.testing.assert_allclose(
        F.gaussian_nll_loss(paddle.to_tensor(inp), paddle.to_tensor(lbl),
                            paddle.to_tensor(var)).numpy(),
        (0.5 * (np.log(var) + (lbl - inp) ** 2 / var)).mean(), rtol=1e-4)

    lp1 = F.lp_pool1d(paddle.to_tensor(rng.randn(1, 2, 8).astype("float32")),
                      2.0, 2)
    assert lp1.shape == [1, 2, 4]

    paddle.seed(1)
    fad = F.feature_alpha_dropout(paddle.ones([2, 8, 4]), 0.5)
    # whole channels share their fate
    per_channel = fad.numpy().std(axis=-1)
    np.testing.assert_allclose(per_channel, 0.0, atol=1e-6)

    qkv = paddle.to_tensor(rng.randn(1, 4, 3, 2, 8).astype("float32"))
    out = F.flash_attn_qkvpacked(qkv, causal=True)
    assert out.shape == [1, 4, 2, 8]


def test_rnn_sequence_length_masks():
    """RNN/BiRNN honor sequence_length: outputs past each sample's length
    are zero and the final state freezes at that step (review fix)."""
    paddle.seed(0)
    cell = nn.GRUCell(3, 4)
    rnn = nn.RNN(cell)
    inp = paddle.to_tensor(np.random.rand(2, 5, 3).astype("float32"))
    out, st = rnn(inp, sequence_length=paddle.to_tensor(np.array([5, 2])))
    assert np.allclose(out.numpy()[1, 2:], 0)
    out2, st2 = rnn(paddle.to_tensor(inp.numpy()[:, :2]),
                    sequence_length=paddle.to_tensor(np.array([2, 2])))
    np.testing.assert_allclose(st.numpy()[1], st2.numpy()[1], atol=1e-6)


def test_model_average_and_lookahead():
    """incubate.ModelAverage: apply() installs the true running mean and
    restore() puts the live weights back (review fix: no zero-biased EMA)."""
    import paddle_tpu.incubate as inc
    import paddle_tpu.optimizer as opt

    w = paddle.Parameter(np.array([0.0], dtype="float32"))
    ma = inc.ModelAverage(parameters=[w])
    for v in [1.0, 2.0, 3.0]:
        w.set_value(np.array([v], dtype="float32"))
        ma.step()
    with ma:
        assert abs(float(w.numpy()[0]) - 2.0) < 1e-6  # mean(1,2,3)
    assert float(w.numpy()[0]) == 3.0  # restored
    # LookAhead pulls slow weights toward fast
    wp = paddle.Parameter(np.array([4.0], dtype="float32"))
    la = inc.LookAhead(opt.SGD(0.1, parameters=[wp]), alpha=0.5, k=2)
    for _ in range(4):
        loss = (wp ** 2).sum()
        loss.backward()
        la.step()
        la.clear_grad()
    assert 0 < float(wp.numpy()[0]) < 4.0


def test_flash_attn_unpadded_matches_sdpa():
    """flash_attn_unpadded (varlen, separate q/k/v) == per-segment causal
    SDPA (review: was a NotImplementedError stub)."""
    from paddle_tpu.nn.functional.attention import flash_attn_unpadded

    paddle.seed(0)
    tot = paddle.randn([10, 2, 8])
    cu = paddle.to_tensor(np.array([0, 4, 10]))
    out = flash_attn_unpadded(tot, tot, tot, cu, cu, 6, 6, causal=True)
    assert out.shape == [10, 2, 8]
    q = tot.numpy()
    seg = []
    for lo, hi in [(0, 4), (4, 10)]:
        qs = np.moveaxis(q[lo:hi][None], 2, 1)
        s = qs @ np.swapaxes(qs, -1, -2) / np.sqrt(8)
        S = hi - lo
        s = np.where(np.tril(np.ones((S, S))), s, -1e9)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        seg.append(np.moveaxis(p @ qs, 1, 2)[0])
    np.testing.assert_allclose(out.numpy(), np.concatenate(seg, 0),
                               atol=2e-3)


def test_class_center_sample_negatives_use_seed_stream():
    """Negative sampling draws from the framework key stream (ADVICE r3):
    fresh negatives per call, reproducible under paddle.seed — not a
    deterministic function of the label batch."""
    paddle.seed(0)
    lab = paddle.to_tensor(np.array([2, 2, 8, 5]))
    _, c1 = F.class_center_sample(lab, 50, 10)
    _, c2 = F.class_center_sample(lab, 50, 10)
    assert not np.array_equal(c1.numpy(), c2.numpy())  # fresh per call
    paddle.seed(0)
    _, c1b = F.class_center_sample(lab, 50, 10)
    _, c2b = F.class_center_sample(lab, 50, 10)
    np.testing.assert_array_equal(c1.numpy(), c1b.numpy())  # reproducible
    np.testing.assert_array_equal(c2.numpy(), c2b.numpy())


def test_lookahead_first_sync_anchors_initial_weights():
    """LookAhead's slow weights are the INITIAL params (ADVICE r3): with
    k=1, alpha=0.5, w0=4, lr=0.1 on loss=w^2 the first sync lands at
    4 + 0.5*((4 - 0.1*8) - 4) = 3.6 — not 3.2 (slow captured post-step)."""
    import paddle_tpu.incubate as inc
    import paddle_tpu.optimizer as opt

    wp = paddle.Parameter(np.array([4.0], dtype="float32"))
    la = inc.LookAhead(opt.SGD(0.1, parameters=[wp]), alpha=0.5, k=1)
    loss = (wp ** 2).sum()
    loss.backward()
    la.step()
    la.clear_grad()
    assert abs(float(wp.numpy()[0]) - 3.6) < 1e-5
