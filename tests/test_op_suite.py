"""Registry-wide op sweep: numpy-reference forward + numeric grad checks.

Parity model: /root/reference/test/legacy_test/op_test.py (OpTest :418,
check_grad :3081) — every spec below is (public fn, independent numpy/scipy
reference, dtypes, grad-checked inputs). test_registry_swept asserts every
op registered in ops.registry.OPS is either covered here or whitelisted
with a reason (the role of test/white_list/op_accuracy_white_list.py).
"""
import numpy as np
import pytest
import scipy.special as sp

import paddle_tpu as paddle
from op_harness import OpSpec, run_spec

R = np.random.RandomState(1234)


def _arr(shape=(3, 4), lo=-2.0, hi=2.0):
    return R.uniform(lo, hi, shape)


def _pos(shape=(3, 4), lo=0.1, hi=3.0):
    return R.uniform(lo, hi, shape)


def _ints(shape=(3, 4), lo=0, hi=8):
    return R.randint(lo, hi, shape).astype("int64")


def _spd(n=4):
    a = R.uniform(-1, 1, (n, n))
    return a @ a.T + n * np.eye(n)


def U(name, ref, x=None, grad=True, covers=(), **kw):
    """Unary elementwise spec."""
    x = _arr() if x is None else x
    return OpSpec(name=name, inputs={"x": x}, ref=lambda x: ref(x),
                  grad=("x",) if grad else (), covers=covers, **kw)


def B(name, ref, x=None, y=None, grad=("x", "y"), covers=(), **kw):
    """Binary (broadcasting) spec."""
    x = _arr() if x is None else x
    y = _arr((4,)) if y is None else y
    return OpSpec(name=name, inputs={"x": x, "y": y},
                  ref=lambda x, y: ref(x, y), grad=tuple(grad),
                  covers=covers, **kw)


def RED(name, ref, x=None, grad=True, **kw):
    """Reduction spec: checks full, per-axis, and keepdim forms."""
    x = _arr((3, 4, 2)) if x is None else x
    specs = []
    for attrs in ({}, {"axis": 1}, {"axis": -1, "keepdim": True}):
        def mkref(attrs=attrs):
            def f(x, **_):
                ax = attrs.get("axis")
                return ref(x, axis=ax, keepdims=attrs.get("keepdim", False))
            return f
        specs.append(OpSpec(name=name, inputs={"x": x}, ref=mkref(),
                            attrs=dict(attrs),
                            grad=("x",) if grad else (), **kw))
    return specs


_softplus = lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)
_sigmoid = lambda x: 1 / (1 + np.exp(-x))

SPECS = [
    # ---- unary math ----------------------------------------------------------
    U("abs", np.abs, x=_arr() + 0.3),  # keep away from the |x| kink
    U("acos", np.arccos, x=_arr(lo=-0.9, hi=0.9)),
    U("acosh", np.arccosh, x=_pos(lo=1.2, hi=4.0)),
    U("asin", np.arcsin, x=_arr(lo=-0.9, hi=0.9)),
    U("asinh", np.arcsinh),
    U("atan", np.arctan),
    U("atanh", np.arctanh, x=_arr(lo=-0.8, hi=0.8)),
    U("ceil", np.ceil, grad=False),
    U("cos", np.cos),
    U("cosh", np.cosh),
    U("deg2rad", np.deg2rad),
    U("digamma", sp.digamma, x=_pos(lo=0.5)),
    U("erf", sp.erf),
    U("erfinv", sp.erfinv, x=_arr(lo=-0.9, hi=0.9), rtol=1e-4, atol=1e-5),
    U("exp", np.exp),
    U("expm1", np.expm1),
    U("floor", np.floor, grad=False),
    U("frac", lambda x: x - np.trunc(x), grad=False),
    U("i0", sp.i0, rtol=1e-4, atol=1e-5),
    U("i1", sp.i1, rtol=1e-4, atol=1e-5),
    U("lgamma", sp.gammaln, x=_pos(lo=0.5), rtol=1e-4, atol=1e-5),
    U("log", np.log, x=_pos()),
    U("log10", np.log10, x=_pos()),
    U("log1p", np.log1p, x=_pos(lo=-0.5)),
    U("log2", np.log2, x=_pos()),
    U("neg", np.negative),
    U("rad2deg", np.rad2deg, rtol=1e-4, atol=1e-4),
    U("reciprocal", np.reciprocal, x=_pos(lo=0.4)),
    U("round", np.round, grad=False),
    U("rsqrt", lambda x: 1 / np.sqrt(x), x=_pos(lo=0.3)),
    U("sign", np.sign, x=_arr() + 0.2, grad=False),
    U("sin", np.sin),
    U("sinh", np.sinh),
    U("sqrt", np.sqrt, x=_pos(lo=0.2)),
    U("square", np.square),
    U("tan", np.tan, x=_arr(lo=-1.2, hi=1.2)),
    U("tanh", np.tanh),
    U("trunc", np.trunc, grad=False),
    U("angle", np.angle, x=_arr() + 0.3, grad=False),
    U("conj", np.conj),
    U("real", np.real),
    U("imag", np.imag, grad=False),  # imag(real tensor) == 0, grad is 0-fn
    OpSpec(name="logit", inputs={"x": _arr(lo=0.1, hi=0.9)},
           ref=lambda x: np.log(x / (1 - x)), grad=("x",)),
    OpSpec(name="polygamma", inputs={"x": _pos(lo=0.6)}, attrs={"n": 1},
           ref=lambda x, n: sp.polygamma(n, x), rtol=1e-4, atol=1e-4,
           grad=("x",)),
    OpSpec(name="nan_to_num",
           inputs={"x": np.array([1.0, np.nan, np.inf, -np.inf, 2.0])},
           ref=lambda x: np.nan_to_num(x, posinf=np.finfo(np.float32).max,
                                       neginf=np.finfo(np.float32).min),
           grad=()),
    OpSpec(name="cast", inputs={"x": _arr()}, attrs={"dtype": "int32"},
           ref=lambda x, dtype: x.astype(dtype), grad=(), out_cast=False),
    OpSpec(name="scale", inputs={"x": _arr()},
           attrs={"scale": 2.5, "bias": 0.5},
           ref=lambda x, scale, bias: x * scale + bias, grad=("x",)),
    OpSpec(name="clip", inputs={"x": _arr()}, attrs={"min": -0.5, "max": 1.0},
           ref=lambda x, min, max: np.clip(x, min, max), grad=("x",)),
    OpSpec(name="stanh", inputs={"x": _arr()},
           attrs={"scale_a": 0.67, "scale_b": 1.7159},
           ref=lambda x, scale_a, scale_b: scale_b * np.tanh(scale_a * x),
           grad=("x",)),

    # ---- activations ---------------------------------------------------------
    U("nn.functional.relu", lambda x: np.maximum(x, 0), x=_arr() + 0.15),
    U("nn.functional.relu6", lambda x: np.clip(x, 0, 6), x=_arr() + 0.15),
    U("sigmoid", _sigmoid),
    U("nn.functional.log_sigmoid", lambda x: -_softplus(-x)),
    U("nn.functional.silu", lambda x: x * _sigmoid(x)),
    U("nn.functional.mish", lambda x: x * np.tanh(_softplus(x))),
    U("nn.functional.softsign", lambda x: x / (1 + np.abs(x))),
    U("nn.functional.tanhshrink", lambda x: x - np.tanh(x)),
    U("nn.functional.selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), x=_arr() + 0.15),
    U("nn.functional.hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6,
      x=_arr(lo=-5, hi=5) + 0.1),

    # ---- binary math ---------------------------------------------------------
    B("add", np.add),
    B("subtract", np.subtract),
    B("multiply", np.multiply),
    B("divide", np.divide, y=_pos((4,), lo=0.4)),
    B("divide_no_nan",
      lambda x, y: np.where(y == 0, 0.0, x / np.where(y == 0, 1.0, y)),
      y=np.array([0.5, 0.0, 2.0, 0.0]), grad=()),
    B("floor_divide", np.floor_divide, y=_pos((4,), lo=0.4), grad=()),
    B("remainder", lambda x, y: np.mod(x, y), y=_pos((4,), lo=0.5), grad=()),
    B("pow", np.power, x=_pos(lo=0.3), y=_pos((4,), lo=0.5, hi=2.0)),
    B("maximum", np.maximum, grad=()),
    B("minimum", np.minimum, grad=()),
    B("fmax", np.fmax, grad=()),
    B("fmin", np.fmin, grad=()),
    B("atan2", np.arctan2, x=_pos(), y=_pos((4,))),
    B("copysign", np.copysign, x=_arr() + 0.3, y=_arr((4,)) + 0.2, grad=("x",)),
    B("hypot", np.hypot, x=_pos(lo=0.3), y=_pos((4,), lo=0.3)),
    B("logaddexp", np.logaddexp),
    B("nextafter", lambda x, y: np.nextafter(
          x.astype("float32"), y.astype("float32")), grad=(), rtol=0, atol=0),
    B("heaviside", np.heaviside, x=_arr() + 0.2, y=_arr((4,)), grad=()),
    OpSpec(name="ldexp", inputs={"x": _arr(), "y": _ints((4,), 0, 4)},
           ref=lambda x, y: np.ldexp(x, y), grad=()),
    OpSpec(name="lerp", inputs={"x": _arr(), "y": _arr(), "weight": _pos(lo=0.1, hi=0.9)},
           ref=lambda x, y, weight: x + weight * (y - x),
           grad=("x", "y", "weight")),
    OpSpec(name="gcd", inputs={"x": _ints(lo=1, hi=30), "y": _ints(lo=1, hi=30)},
           ref=lambda x, y: np.gcd(x, y), grad=()),
    OpSpec(name="lcm", inputs={"x": _ints(lo=1, hi=12), "y": _ints(lo=1, hi=12)},
           ref=lambda x, y: np.lcm(x, y), grad=()),

    # ---- bitwise / logical / compare ----------------------------------------
    OpSpec(name="bitwise_and", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_and(x, y)),
    OpSpec(name="bitwise_or", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_or(x, y)),
    OpSpec(name="bitwise_xor", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_xor(x, y)),
    OpSpec(name="bitwise_not", inputs={"x": _ints()}, ref=lambda x: np.bitwise_not(x)),
    OpSpec(name="bitwise_left_shift", inputs={"x": _ints(), "y": _ints(lo=0, hi=4)},
           ref=lambda x, y: np.left_shift(x, y)),
    OpSpec(name="bitwise_right_shift", inputs={"x": _ints(hi=64), "y": _ints(lo=0, hi=4)},
           ref=lambda x, y: np.right_shift(x, y)),
    OpSpec(name="logical_and", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_and(x, y)),
    OpSpec(name="logical_or", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_or(x, y)),
    OpSpec(name="logical_xor", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_xor(x, y)),
    OpSpec(name="logical_not", inputs={"x": _ints(hi=2).astype(bool)},
           ref=lambda x: np.logical_not(x)),
    B("equal", np.equal, y=_arr((4,)), grad=()),
    B("not_equal", np.not_equal, grad=()),
    B("greater_equal", np.greater_equal, grad=()),
    B("greater_than", np.greater, grad=()),
    B("less_equal", np.less_equal, grad=()),
    B("less_than", np.less, grad=()),
    B("equal_all", lambda x, y: np.array(np.array_equal(x, y)), grad=()),
    B("allclose", lambda x, y: np.array(np.allclose(x, y)), grad=()),
    B("isclose", np.isclose, grad=()),
    U("isfinite", np.isfinite, grad=False),
    U("isinf", np.isinf, grad=False),
    U("isnan", np.isnan, grad=False),
    U("isneginf", np.isneginf, grad=False),
    U("isposinf", np.isposinf, grad=False),
    U("isreal", np.isreal, grad=False),

    # ---- reductions ----------------------------------------------------------
    *RED("sum", np.sum),
    *RED("mean", np.mean),
    *RED("prod", np.prod, x=_arr((3, 4, 2), lo=0.5, hi=1.5)),
    *RED("max", np.max, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("min", np.min, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("amax", np.max, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("amin", np.min, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("all", lambda x, axis=None, keepdims=False: np.all(x, axis=axis, keepdims=keepdims),
         x=_ints((3, 4, 2), hi=2).astype(bool), grad=False),
    *RED("any", lambda x, axis=None, keepdims=False: np.any(x, axis=axis, keepdims=keepdims),
         x=_ints((3, 4, 2), hi=2).astype(bool), grad=False),
    *RED("nansum", np.nansum, grad=False),
    *RED("nanmean", np.nanmean, grad=False),
    *RED("logsumexp", lambda x, axis=None, keepdims=False: sp.logsumexp(x, axis=axis, keepdims=keepdims)),
    *RED("median", lambda x, axis=None, keepdims=False: np.median(x, axis=axis, keepdims=keepdims),
         x=_arr((3, 5)), grad=False),
    *RED("nanmedian", lambda x, axis=None, keepdims=False: np.nanmedian(x, axis=axis, keepdims=keepdims),
         x=_arr((3, 5)), grad=False),
    *RED("count_nonzero", lambda x, axis=None, keepdims=False:
         np.count_nonzero(x, axis=axis, keepdims=keepdims), grad=False),
    OpSpec(name="std", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.std(x, ddof=1), grad=("x",)),
    OpSpec(name="std", inputs={"x": _arr((3, 5))}, attrs={"axis": 1},
           ref=lambda x, axis: np.std(x, axis=axis, ddof=1), grad=("x",)),
    OpSpec(name="var", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.var(x, ddof=1), grad=("x",)),
    OpSpec(name="var", inputs={"x": _arr((3, 5))},
           attrs={"axis": 0, "unbiased": False},
           ref=lambda x, axis, unbiased: np.var(x, axis=axis, ddof=0),
           grad=("x",)),
    OpSpec(name="argmax", inputs={"x": _arr((3, 5)) * 9}, attrs={"axis": 1},
           ref=lambda x, axis: np.argmax(x, axis=axis), out_cast=False, grad=()),
    OpSpec(name="argmin", inputs={"x": _arr((3, 5)) * 9}, attrs={"axis": 0},
           ref=lambda x, axis: np.argmin(x, axis=axis), out_cast=False, grad=()),

    # ---- cumulative ----------------------------------------------------------
    OpSpec(name="cumsum", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.cumsum(x, axis=axis), grad=("x",)),
    OpSpec(name="cumprod", inputs={"x": _arr((3, 4), lo=0.4, hi=1.6)},
           attrs={"dim": 1},
           ref=lambda x, dim: np.cumprod(x, axis=dim), grad=("x",)),
    OpSpec(name="logcumsumexp", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.log(np.cumsum(np.exp(x), axis=axis)),
           grad=("x",)),
    OpSpec(name="cummax", inputs={"x": _arr((3, 4)) * 5}, attrs={"axis": 1},
           ref=lambda x, axis: (np.maximum.accumulate(x, axis=axis),
                                _cum_idx(x, axis, np.greater_equal)),
           out_cast=False, grad=()),
    OpSpec(name="cummin", inputs={"x": _arr((3, 4)) * 5}, attrs={"axis": 1},
           ref=lambda x, axis: (np.minimum.accumulate(x, axis=axis),
                                _cum_idx(x, axis, np.less_equal)),
           out_cast=False, grad=()),

    # ---- linalg --------------------------------------------------------------
    OpSpec(name="matmul", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="bmm", inputs={"x": _arr((2, 3, 4)), "y": _arr((2, 4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="mm", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="mv", inputs={"x": _arr((3, 4)), "vec": _arr((4,))},
           ref=lambda x, vec: x @ vec, grad=("x", "vec")),
    OpSpec(name="dot", inputs={"x": _arr((5,)), "y": _arr((5,))},
           ref=lambda x, y: np.array(np.dot(x, y)), grad=("x", "y")),
    B("inner", np.inner, x=_arr((3, 4)), y=_arr((2, 4))),
    B("outer", np.outer, x=_arr((3,)), y=_arr((4,))),
    B("kron", np.kron, x=_arr((2, 3)), y=_arr((3, 2))),
    B("cross", lambda x, y: np.cross(x, y), x=_arr((4, 3)), y=_arr((4, 3))),
    OpSpec(name="trace", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.array(np.trace(x)), grad=("x",)),
    OpSpec(name="diagonal", inputs={"x": _arr((3, 4))},
           ref=lambda x: np.diagonal(x), grad=("x",)),
    OpSpec(name="linalg.diag_embed", inputs={"x": _arr((3, 4))},
           ref=lambda x: _diag_embed_ref(x), grad=()),
    OpSpec(name="linalg.det", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.det(x)), grad=("x",),
           grad_rtol=3e-2),
    OpSpec(name="linalg.inverse", inputs={"x": _spd()},
           ref=lambda x: np.linalg.inv(x), grad=("x",), grad_rtol=3e-2),
    # grad via symmetrized ref: numpy reads only the lower triangle, while
    # the jax VJP distributes the cotangent across both triangles
    OpSpec(name="linalg.cholesky", inputs={"x": _spd()},
           ref=lambda x: np.linalg.cholesky((x + x.T) / 2),
           grad=("x",), grad_rtol=3e-2),
    OpSpec(name="linalg.solve", inputs={"x": _spd(), "y": _arr((4, 2))},
           ref=lambda x, y: np.linalg.solve(x, y), grad=("x", "y"), grad_rtol=3e-2),
    OpSpec(name="linalg.cholesky_solve", inputs={"x": _arr((4, 2)),
                                          "y": np.linalg.cholesky(_spd())},
           attrs={"upper": False},
           ref=lambda x, y, upper: np.linalg.solve(y @ y.T, x), grad=(),
           rtol=1e-4, atol=1e-5),
    OpSpec(name="linalg.triangular_solve",
           inputs={"x": np.tril(_arr((4, 4))) + 3 * np.eye(4), "y": _arr((4, 2))},
           attrs={"upper": False},
           ref=lambda x, y, upper: np.linalg.solve(x, y), grad=(),
           rtol=1e-4, atol=1e-5),
    OpSpec(name="linalg.matrix_power", inputs={"x": _spd()}, attrs={"n": 3},
           ref=lambda x, n: np.linalg.matrix_power(x, n), rtol=1e-4, atol=1e-4, grad=("x",),
           grad_rtol=5e-2, grad_atol=1e-2),
    OpSpec(name="linalg.matrix_rank", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.matrix_rank(x)), out_cast=False,
           grad=()),
    OpSpec(name="linalg.pinv", inputs={"x": _arr((4, 3))},
           ref=lambda x: np.linalg.pinv(x), rtol=1e-4, atol=1e-5, grad=()),
    OpSpec(name="linalg.cond", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.cond(x)), rtol=1e-4, atol=1e-4,
           grad=()),
    OpSpec(name="linalg.multi_dot", inputs={"xs": [_arr((3, 4)), _arr((4, 5)), _arr((5, 2))]},
           ref=lambda xs: np.linalg.multi_dot(xs), grad=()),
    OpSpec(name="addmm", inputs={"input": _arr((3, 5)), "x": _arr((3, 4)),
                                 "y": _arr((4, 5))},
           attrs={"beta": 0.7, "alpha": 1.3},
           ref=lambda input, x, y, beta, alpha: beta * input + alpha * (x @ y),
           grad=("input", "x", "y")),
    OpSpec(name="linalg.cov", inputs={"x": _arr((3, 6))},
           ref=lambda x: np.cov(x), grad=("x",)),
    OpSpec(name="linalg.corrcoef", inputs={"x": _arr((3, 6))},
           ref=lambda x: np.corrcoef(x), grad=()),
    OpSpec(name="dist", inputs={"x": _arr((3, 4)), "y": _arr((3, 4))},
           attrs={"p": 2},
           ref=lambda x, y, p: np.array(np.linalg.norm((x - y).ravel(), p)),
           grad=("x", "y")),
    OpSpec(name="linalg.householder_product",
           inputs={"x": np.tril(_arr((4, 3)), -1) + np.eye(4, 3),
                   "tau": _pos((3,), 0.1, 0.9)},
           ref=lambda x, tau: _householder_ref(x, tau),
           rtol=1e-4, atol=1e-5, grad=()),

    # ---- manipulation --------------------------------------------------------
    OpSpec(name="concat", inputs={"x": [_arr((2, 3)), _arr((2, 3))]},
           attrs={"axis": 1},
           ref=lambda x, axis: np.concatenate(x, axis=axis), grad=()),
    OpSpec(name="stack", inputs={"x": [_arr((2, 3)), _arr((2, 3))]},
           attrs={"axis": 0}, ref=lambda x, axis: np.stack(x, axis), grad=()),
    OpSpec(name="reshape", inputs={"x": _arr((3, 4))}, attrs={"shape": [2, 6]},
           ref=lambda x, shape: np.reshape(x, shape), grad=("x",)),
    OpSpec(name="transpose", inputs={"x": _arr((2, 3, 4))},
           attrs={"perm": [2, 0, 1]},
           ref=lambda x, perm: np.transpose(x, perm), grad=("x",)),
    OpSpec(name="t", inputs={"x": _arr((3, 4))},
           ref=lambda x: x.T, grad=("x",)),
    OpSpec(name="moveaxis", inputs={"x": _arr((2, 3, 4))},
           attrs={"source": 0, "destination": 2},
           ref=lambda x, source, destination: np.moveaxis(x, source, destination),
           grad=("x",)),
    OpSpec(name="swapaxes", inputs={"x": _arr((2, 3, 4))},
           attrs={"axis0": 0, "axis1": 2},
           ref=lambda x, axis0, axis1: np.swapaxes(x, axis0, axis1),
           grad=("x",)),
    OpSpec(name="flatten", inputs={"x": _arr((2, 3, 4))},
           attrs={"start_axis": 1, "stop_axis": 2},
           ref=lambda x, start_axis, stop_axis: x.reshape(2, 12), grad=("x",)),
    OpSpec(name="squeeze", inputs={"x": _arr((3, 1, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.squeeze(x, axis), grad=("x",)),
    OpSpec(name="unsqueeze", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.expand_dims(x, axis), grad=("x",)),
    OpSpec(name="tile", inputs={"x": _arr((2, 3))},
           attrs={"repeat_times": [2, 2]},
           ref=lambda x, repeat_times: np.tile(x, repeat_times), grad=("x",)),
    OpSpec(name="expand", inputs={"x": _arr((1, 3))}, attrs={"shape": [4, 3]},
           ref=lambda x, shape: np.broadcast_to(x, shape), grad=("x",)),
    OpSpec(name="broadcast_to", inputs={"x": _arr((1, 3))},
           attrs={"shape": [4, 3]},
           ref=lambda x, shape: np.broadcast_to(x, shape), grad=("x",)),
    OpSpec(name="expand_as", inputs={"x": _arr((1, 3)), "y": _arr((4, 3))},
           ref=lambda x, y: np.broadcast_to(x, y.shape), grad=()),
    OpSpec(name="flip", inputs={"x": _arr((3, 4))}, attrs={"axis": [0]},
           ref=lambda x, axis: np.flip(x, axis), grad=("x",)),
    OpSpec(name="rot90", inputs={"x": _arr((3, 4))}, attrs={"k": 1},
           ref=lambda x, k: np.rot90(x, k), grad=("x",)),
    OpSpec(name="roll", inputs={"x": _arr((3, 4))},
           attrs={"shifts": 2, "axis": 1},
           ref=lambda x, shifts, axis: np.roll(x, shifts, axis), grad=("x",)),
    OpSpec(name="tril", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.tril(x), grad=("x",)),
    OpSpec(name="triu", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.triu(x), grad=("x",)),
    OpSpec(name="diag", inputs={"x": _arr((4,))},
           ref=lambda x: np.diag(x), grad=("x",)),
    OpSpec(name="diagflat", inputs={"x": _arr((2, 3))},
           ref=lambda x: np.diagflat(x), grad=()),
    OpSpec(name="gather", inputs={"x": _arr((5, 3)),
                                  "index": np.array([0, 2, 4])},
           ref=lambda x, index: x[index], grad=("x",)),
    OpSpec(name="gather_nd", inputs={"x": _arr((3, 4)),
                                     "index": np.array([[0, 1], [2, 3]])},
           ref=lambda x, index: x[tuple(index.T)], grad=("x",)),
    OpSpec(name="index_select", inputs={"x": _arr((5, 3)),
                                        "index": np.array([1, 1, 3])},
           attrs={"axis": 0},
           ref=lambda x, index, axis: np.take(x, index, axis), grad=("x",)),
    OpSpec(name="index_sample", inputs={"x": _arr((3, 5)),
                                        "index": _ints((3, 2), 0, 5)},
           ref=lambda x, index: np.take_along_axis(x, index, 1), grad=("x",)),
    OpSpec(name="take", inputs={"x": _arr((3, 4)),
                                "index": np.array([0, 5, 11])},
           ref=lambda x, index: x.ravel()[index], grad=()),
    OpSpec(name="take_along_axis", inputs={"x": _arr((3, 5)),
                                           "indices": _ints((3, 2), 0, 5)},
           attrs={"axis": 1},
           ref=lambda x, indices, axis: np.take_along_axis(x, indices, axis),
           grad=()),
    OpSpec(name="masked_select",
           inputs={"x": np.array([1.0, 2.0, 3.0, 4.0]),
                   "mask": np.array([True, False, True, False])},
           ref=lambda x, mask: x[mask], grad=()),
    OpSpec(name="masked_fill",
           inputs={"x": _arr((3, 4)),
                   "mask": _ints((3, 4), 0, 2).astype(bool)},
           attrs={"value": -1.5},
           ref=lambda x, mask, value: np.where(mask, value, x), grad=("x",)),
    OpSpec(name="where", inputs={"condition": _ints((3, 4), 0, 2).astype(bool),
                                 "x": _arr((3, 4)), "y": _arr((3, 4))},
           ref=lambda condition, x, y: np.where(condition, x, y),
           grad=("x", "y")),
    OpSpec(name="multiplex", inputs={"inputs": [_arr((4, 3)), _arr((4, 3))],
                                     "index": np.array([[0], [1], [0], [1]])},
           ref=lambda inputs, index: np.stack(
               [inputs[int(i)][r] for r, i in enumerate(index[:, 0])]),
           grad=()),
    OpSpec(name="pad", inputs={"x": _arr((3, 4))},
           attrs={"pad": [1, 1, 0, 2], "value": 0.5},
           ref=lambda x, pad, value: np.pad(
               x, [(pad[0], pad[1]), (pad[2], pad[3])],
               constant_values=value),
           grad=("x",)),
    OpSpec(name="slice", inputs={"x": _arr((4, 5))},
           attrs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]},
           ref=lambda x, axes, starts, ends: x[1:3, 0:4], grad=("x",)),
    OpSpec(name="strided_slice", inputs={"x": _arr((6, 5))},
           attrs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
           ref=lambda x, axes, starts, ends, strides: x[::2], grad=("x",)),
    OpSpec(name="crop", inputs={"x": _arr((4, 5))},
           attrs={"shape": [2, 3], "offsets": [1, 1]},
           ref=lambda x, shape, offsets: x[1:3, 1:4], grad=()),
    OpSpec(name="repeat_interleave", inputs={"x": _arr((3, 2))},
           attrs={"repeats": 2, "axis": 0},
           ref=lambda x, repeats, axis: np.repeat(x, repeats, axis),
           grad=("x",)),
    OpSpec(name="unbind", inputs={"x": _arr((3, 4))}, attrs={"axis": 0},
           ref=lambda x, axis: [x[i] for i in range(3)], grad=()),
    OpSpec(name="unstack", inputs={"x": _arr((3, 4))}, attrs={"axis": 0},
           ref=lambda x, axis: [x[i] for i in range(3)], grad=()),
    OpSpec(name="split", inputs={"x": _arr((4, 6))},
           attrs={"num_or_sections": 2, "axis": 1},
           ref=lambda x, num_or_sections, axis: np.split(x, 2, axis), grad=()),
    OpSpec(name="chunk", inputs={"x": _arr((4, 6))},
           attrs={"chunks": 3, "axis": 1},
           ref=lambda x, chunks, axis: np.split(x, 3, axis), grad=()),
    OpSpec(name="as_complex", inputs={"x": np.stack([_arr((3, 4)), _arr((3, 4))], -1)},
           ref=lambda x: x[..., 0] + 1j * x[..., 1], grad=(), out_cast=False,
           rtol=1e-6, atol=1e-6),
    OpSpec(name="as_real", inputs={"x": (_arr((3, 4)) + 1j * _arr((3, 4))).astype("complex64")},
           ref=lambda x: np.stack([x.real, x.imag], -1), grad=(),
           rtol=1e-6, atol=1e-6),

    # ---- sorting / search ----------------------------------------------------
    OpSpec(name="sort", inputs={"x": _arr((3, 5)) * 9},
           ref=lambda x: np.sort(x, axis=-1), grad=("x",)),
    OpSpec(name="argsort", inputs={"x": _arr((3, 5)) * 9},
           ref=lambda x: np.argsort(x, axis=-1, kind="stable"),
           out_cast=False, grad=()),
    OpSpec(name="topk", inputs={"x": _arr((3, 6)) * 9}, attrs={"k": 2},
           ref=lambda x, k: (np.sort(x, -1)[:, ::-1][:, :k],
                             np.argsort(-x, -1, kind="stable")[:, :k]),
           out_cast=False, grad=()),
    OpSpec(name="kthvalue", inputs={"x": _arr((3, 6)) * 9}, attrs={"k": 2},
           ref=lambda x, k: (np.sort(x, -1)[:, k - 1],
                             np.argsort(x, -1, kind="stable")[:, k - 1]),
           out_cast=False, grad=()),
    OpSpec(name="mode", inputs={"x": _ints((3, 5), 0, 3).astype("float64")},
           ref=lambda x: _mode_ref(x), out_cast=False, grad=()),
    OpSpec(name="searchsorted",
           inputs={"sorted_sequence": np.array([1.0, 3.0, 5.0, 7.0]),
                   "values": np.array([0.0, 4.0, 8.0])},
           ref=lambda sorted_sequence, values: np.searchsorted(
               sorted_sequence, values), out_cast=False, grad=()),
    OpSpec(name="bucketize",
           inputs={"x": np.array([0.0, 2.0, 4.0, 6.0]),
                   "sorted_sequence": np.array([1.0, 3.0, 5.0])},
           ref=lambda x, sorted_sequence: np.searchsorted(sorted_sequence, x),
           out_cast=False, grad=()),
    OpSpec(name="nonzero", inputs={"x": np.array([[1.0, 0.0], [0.0, 2.0]])},
           ref=lambda x: np.stack(np.nonzero(x), -1), out_cast=False, grad=()),
    OpSpec(name="unique", inputs={"x": np.array([3.0, 1.0, 3.0, 2.0])},
           ref=lambda x: np.unique(x), grad=()),
    OpSpec(name="unique_consecutive",
           inputs={"x": np.array([1.0, 1.0, 2.0, 2.0, 3.0, 1.0])},
           ref=lambda x: np.array([1.0, 2.0, 3.0, 1.0]), grad=()),
    OpSpec(name="histogram", inputs={"x": _pos((20,), 0.0, 1.0)},
           attrs={"bins": 4, "min": 0.0, "max": 1.0},
           ref=lambda x, bins, min, max: np.histogram(
               x, bins=bins, range=(min, max))[0],
           out_cast=False, grad=()),
    OpSpec(name="bincount", inputs={"x": _ints((12,), 0, 5)},
           ref=lambda x: np.bincount(x), out_cast=False, grad=()),

    # ---- misc ----------------------------------------------------------------
    OpSpec(name="trapezoid", inputs={"y": _arr((3, 5))}, attrs={"dx": 0.5},
           ref=lambda y, dx: np.trapz(y, dx=dx, axis=-1), grad=("y",)),
    OpSpec(name="diff", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.diff(x, axis=-1), grad=("x",)),
    OpSpec(name="norm", inputs={"x": _arr((3, 4))},
           ref=lambda x: np.array(np.linalg.norm(x)), grad=("x",)),
    OpSpec(name="norm", inputs={"x": _arr((3, 4))}, attrs={"p": 1, "axis": 1},
           ref=lambda x, p, axis: np.linalg.norm(x, p, axis), grad=()),
    OpSpec(name="tensordot", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           attrs={"axes": 1},
           ref=lambda x, y, axes: np.tensordot(x, y, axes), grad=()),
    OpSpec(name="dot", inputs={"x": _arr((2, 5)), "y": _arr((2, 5))},
           ref=lambda x, y: np.sum(x * y, -1), grad=("x", "y")),
]


def _cum_idx(x, axis, cmp):
    """Running-extreme indices, latest occurrence winning ties (torch/paddle
    cummax/cummin convention)."""
    running = np.take(x, [0], axis=axis)
    run_idx = np.zeros(running.shape, "int64")
    parts = []
    for i in range(x.shape[axis]):
        cur = np.take(x, [i], axis=axis)
        better = cmp(cur, running)
        running = np.where(better, cur, running)
        run_idx = np.where(better, i, run_idx)
        parts.append(run_idx.copy())
    return np.concatenate(parts, axis=axis)


def _diag_embed_ref(x):
    out = np.zeros(x.shape + (x.shape[-1],), x.dtype)
    for i in range(x.shape[0]):
        out[i] = np.diag(x[i])
    return out


def _householder_ref(x, tau):
    m, n = x.shape
    q = np.eye(m)
    for j in range(n):
        v = x[:, j].copy()
        v[:j] = 0
        v[j] = 1
        q = q @ (np.eye(m) - tau[j] * np.outer(v, v))
    return q[:, :n]


def _mode_ref(x):
    """Smallest most-frequent value, last-occurrence index (torch/paddle
    mode tie convention)."""
    vals = np.zeros(x.shape[0])
    idxs = np.zeros(x.shape[0], "int64")
    for r in range(x.shape[0]):
        uniq, counts = np.unique(x[r], return_counts=True)
        best = uniq[counts == counts.max()].min()
        vals[r] = best
        idxs[r] = np.where(x[r] == best)[0][-1]
    return vals, idxs


_IDS = [f"{i}_{s.name.replace('.', '_')}" for i, s in enumerate(SPECS)]


@pytest.mark.parametrize("spec", SPECS, ids=_IDS)
def test_op(spec):
    run_spec(spec)


def test_einsum_and_atleast():
    """Positional-vararg signatures the OpSpec harness can't express."""
    a, b = _arr((3, 4)), _arr((4, 5))
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a.astype("float32")),
                        paddle.to_tensor(b.astype("float32")))
    np.testing.assert_allclose(out.numpy(), (a @ b).astype("float32"),
                               rtol=1e-5, atol=1e-6)
    v = _arr((4,)).astype("float32")
    np.testing.assert_allclose(
        paddle.atleast_2d(paddle.to_tensor(v)).numpy(), np.atleast_2d(v))
    assert paddle.atleast_1d(paddle.to_tensor(v)).shape == [4]
    assert paddle.atleast_3d(paddle.to_tensor(v)).numpy().ndim == 3


# ---- registry completeness ---------------------------------------------------

# Ops that cannot be checked by this harness, each with the reason —
# the role of the reference's test/white_list/ files.
WHITELIST = {
    # positional-vararg signature; dedicated test_einsum_and_atleast
    "einsum": "vararg signature; test_einsum_and_atleast",
}


def test_registry_swept():
    """Every registered op is covered by a spec (by name or `covers`) or
    whitelisted with a reason."""
    from paddle_tpu.ops.registry import OPS

    covered = set()
    for s in SPECS:
        covered.add(s.name.split(".")[-1])
        covered.update(s.covers)
    missing = [n for n in sorted(OPS)
               if n not in covered and n not in WHITELIST
               and not n.rstrip("_") in covered]
    assert not missing, (
        f"{len(missing)} registered ops lack an OpSpec or whitelist entry: "
        f"{missing}")
