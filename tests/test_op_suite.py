"""Registry-wide op sweep: numpy-reference forward + numeric grad checks.

Parity model: /root/reference/test/legacy_test/op_test.py (OpTest :418,
check_grad :3081) — every spec below is (public fn, independent numpy/scipy
reference, dtypes, grad-checked inputs). test_registry_swept asserts every
op registered in ops.registry.OPS is either covered here or whitelisted
with a reason (the role of test/white_list/op_accuracy_white_list.py).
"""
import numpy as np
import pytest
import scipy.special as sp

import paddle_tpu as paddle
from op_harness import OpSpec, run_spec

R = np.random.RandomState(1234)


def _arr(shape=(3, 4), lo=-2.0, hi=2.0):
    return R.uniform(lo, hi, shape)


def _pos(shape=(3, 4), lo=0.1, hi=3.0):
    return R.uniform(lo, hi, shape)


def _ints(shape=(3, 4), lo=0, hi=8):
    return R.randint(lo, hi, shape).astype("int64")


def _spd(n=4):
    a = R.uniform(-1, 1, (n, n))
    return a @ a.T + n * np.eye(n)


def _ormqr_inputs():
    import scipy.linalg as sl

    a = R.uniform(-1, 1, (5, 3))
    (qr_f, tau), _ = sl.qr(a, mode="raw")
    return {"x": np.asarray(qr_f), "tau": np.asarray(tau),
            "other": R.uniform(-1, 1, (5, 4))}


def U(name, ref, x=None, grad=True, covers=(), **kw):
    """Unary elementwise spec."""
    x = _arr() if x is None else x
    return OpSpec(name=name, inputs={"x": x}, ref=lambda x: ref(x),
                  grad=("x",) if grad else (), covers=covers, **kw)


def B(name, ref, x=None, y=None, grad=("x", "y"), covers=(), **kw):
    """Binary (broadcasting) spec."""
    x = _arr() if x is None else x
    y = _arr((4,)) if y is None else y
    return OpSpec(name=name, inputs={"x": x, "y": y},
                  ref=lambda x, y: ref(x, y), grad=tuple(grad),
                  covers=covers, **kw)


def RED(name, ref, x=None, grad=True, **kw):
    """Reduction spec: checks full, per-axis, and keepdim forms."""
    x = _arr((3, 4, 2)) if x is None else x
    specs = []
    for attrs in ({}, {"axis": 1}, {"axis": -1, "keepdim": True}):
        def mkref(attrs=attrs):
            def f(x, **_):
                ax = attrs.get("axis")
                return ref(x, axis=ax, keepdims=attrs.get("keepdim", False))
            return f
        specs.append(OpSpec(name=name, inputs={"x": x}, ref=mkref(),
                            attrs=dict(attrs),
                            grad=("x",) if grad else (), **kw))
    return specs


_softplus = lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)
_sigmoid = lambda x: 1 / (1 + np.exp(-x))

SPECS = [
    # ---- unary math ----------------------------------------------------------
    U("abs", np.abs, x=_arr() + 0.3),  # keep away from the |x| kink
    U("acos", np.arccos, x=_arr(lo=-0.9, hi=0.9)),
    U("acosh", np.arccosh, x=_pos(lo=1.2, hi=4.0)),
    U("asin", np.arcsin, x=_arr(lo=-0.9, hi=0.9)),
    U("asinh", np.arcsinh),
    U("atan", np.arctan),
    U("atanh", np.arctanh, x=_arr(lo=-0.8, hi=0.8)),
    U("ceil", np.ceil, grad=False),
    U("cos", np.cos),
    U("cosh", np.cosh),
    U("deg2rad", np.deg2rad),
    U("digamma", sp.digamma, x=_pos(lo=0.5)),
    U("erf", sp.erf),
    U("erfinv", sp.erfinv, x=_arr(lo=-0.9, hi=0.9), rtol=1e-4, atol=1e-5),
    U("exp", np.exp),
    U("expm1", np.expm1),
    U("floor", np.floor, grad=False),
    U("frac", lambda x: x - np.trunc(x), grad=False),
    U("i0", sp.i0, rtol=1e-4, atol=1e-5),
    U("i1", sp.i1, rtol=1e-4, atol=1e-5),
    U("lgamma", sp.gammaln, x=_pos(lo=0.5), rtol=1e-4, atol=1e-5),
    U("log", np.log, x=_pos()),
    U("log10", np.log10, x=_pos()),
    U("log1p", np.log1p, x=_pos(lo=-0.5)),
    U("log2", np.log2, x=_pos()),
    U("neg", np.negative),
    U("rad2deg", np.rad2deg, rtol=1e-4, atol=1e-4),
    U("reciprocal", np.reciprocal, x=_pos(lo=0.4)),
    U("round", np.round, grad=False),
    U("rsqrt", lambda x: 1 / np.sqrt(x), x=_pos(lo=0.3)),
    U("sign", np.sign, x=_arr() + 0.2, grad=False),
    U("sin", np.sin),
    U("sinh", np.sinh),
    U("sqrt", np.sqrt, x=_pos(lo=0.2)),
    U("square", np.square),
    U("tan", np.tan, x=_arr(lo=-1.2, hi=1.2)),
    U("tanh", np.tanh),
    U("trunc", np.trunc, grad=False),
    U("angle", np.angle, x=_arr() + 0.3, grad=False),
    U("conj", np.conj),
    U("real", np.real),
    U("imag", np.imag, grad=False),  # imag(real tensor) == 0, grad is 0-fn
    OpSpec(name="logit", inputs={"x": _arr(lo=0.1, hi=0.9)},
           ref=lambda x: np.log(x / (1 - x)), grad=("x",)),
    OpSpec(name="polygamma", inputs={"x": _pos(lo=0.6)}, attrs={"n": 1},
           ref=lambda x, n: sp.polygamma(n, x), rtol=1e-4, atol=1e-4,
           grad=("x",)),
    OpSpec(name="nan_to_num",
           inputs={"x": np.array([1.0, np.nan, np.inf, -np.inf, 2.0])},
           ref=lambda x: np.nan_to_num(x, posinf=np.finfo(np.float32).max,
                                       neginf=np.finfo(np.float32).min),
           grad=()),
    OpSpec(name="cast", inputs={"x": _arr()}, attrs={"dtype": "int32"},
           ref=lambda x, dtype: x.astype(dtype), grad=(), out_cast=False),
    OpSpec(name="scale", inputs={"x": _arr()},
           attrs={"scale": 2.5, "bias": 0.5},
           ref=lambda x, scale, bias: x * scale + bias, grad=("x",)),
    OpSpec(name="clip", inputs={"x": _arr()}, attrs={"min": -0.5, "max": 1.0},
           ref=lambda x, min, max: np.clip(x, min, max), grad=("x",)),
    OpSpec(name="stanh", inputs={"x": _arr()},
           attrs={"scale_a": 0.67, "scale_b": 1.7159},
           ref=lambda x, scale_a, scale_b: scale_b * np.tanh(scale_a * x),
           grad=("x",)),

    # ---- activations ---------------------------------------------------------
    U("nn.functional.relu", lambda x: np.maximum(x, 0), x=_arr() + 0.15),
    U("nn.functional.relu6", lambda x: np.clip(x, 0, 6), x=_arr() + 0.15),
    U("sigmoid", _sigmoid),
    U("nn.functional.log_sigmoid", lambda x: -_softplus(-x)),
    U("nn.functional.silu", lambda x: x * _sigmoid(x)),
    U("nn.functional.mish", lambda x: x * np.tanh(_softplus(x))),
    U("nn.functional.softsign", lambda x: x / (1 + np.abs(x))),
    U("nn.functional.tanhshrink", lambda x: x - np.tanh(x)),
    U("nn.functional.selu", lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), x=_arr() + 0.15),
    U("nn.functional.hardswish", lambda x: x * np.clip(x + 3, 0, 6) / 6,
      x=_arr(lo=-5, hi=5) + 0.1),

    # ---- binary math ---------------------------------------------------------
    B("add", np.add),
    B("subtract", np.subtract),
    B("multiply", np.multiply),
    B("divide", np.divide, y=_pos((4,), lo=0.4)),
    B("divide_no_nan",
      lambda x, y: np.where(y == 0, 0.0, x / np.where(y == 0, 1.0, y)),
      y=np.array([0.5, 0.0, 2.0, 0.0]), grad=()),
    B("floor_divide", np.floor_divide, y=_pos((4,), lo=0.4), grad=()),
    B("remainder", lambda x, y: np.mod(x, y), y=_pos((4,), lo=0.5), grad=()),
    B("pow", np.power, x=_pos(lo=0.3), y=_pos((4,), lo=0.5, hi=2.0)),
    B("maximum", np.maximum, grad=()),
    B("minimum", np.minimum, grad=()),
    B("fmax", np.fmax, grad=()),
    B("fmin", np.fmin, grad=()),
    B("atan2", np.arctan2, x=_pos(), y=_pos((4,))),
    B("copysign", np.copysign, x=_arr() + 0.3, y=_arr((4,)) + 0.2, grad=("x",)),
    B("hypot", np.hypot, x=_pos(lo=0.3), y=_pos((4,), lo=0.3)),
    B("logaddexp", np.logaddexp),
    B("nextafter", lambda x, y: np.nextafter(
          x.astype("float32"), y.astype("float32")), grad=(), rtol=0, atol=0),
    B("heaviside", np.heaviside, x=_arr() + 0.2, y=_arr((4,)), grad=()),
    OpSpec(name="ldexp", inputs={"x": _arr(), "y": _ints((4,), 0, 4)},
           ref=lambda x, y: np.ldexp(x, y), grad=()),
    OpSpec(name="lerp", inputs={"x": _arr(), "y": _arr(), "weight": _pos(lo=0.1, hi=0.9)},
           ref=lambda x, y, weight: x + weight * (y - x),
           grad=("x", "y", "weight")),
    OpSpec(name="gcd", inputs={"x": _ints(lo=1, hi=30), "y": _ints(lo=1, hi=30)},
           ref=lambda x, y: np.gcd(x, y), grad=()),
    OpSpec(name="lcm", inputs={"x": _ints(lo=1, hi=12), "y": _ints(lo=1, hi=12)},
           ref=lambda x, y: np.lcm(x, y), grad=()),

    # ---- bitwise / logical / compare ----------------------------------------
    OpSpec(name="bitwise_and", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_and(x, y)),
    OpSpec(name="bitwise_or", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_or(x, y)),
    OpSpec(name="bitwise_xor", inputs={"x": _ints(), "y": _ints()}, ref=lambda x, y: np.bitwise_xor(x, y)),
    OpSpec(name="bitwise_not", inputs={"x": _ints()}, ref=lambda x: np.bitwise_not(x)),
    OpSpec(name="bitwise_left_shift", inputs={"x": _ints(), "y": _ints(lo=0, hi=4)},
           ref=lambda x, y: np.left_shift(x, y)),
    OpSpec(name="bitwise_right_shift", inputs={"x": _ints(hi=64), "y": _ints(lo=0, hi=4)},
           ref=lambda x, y: np.right_shift(x, y)),
    OpSpec(name="logical_and", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_and(x, y)),
    OpSpec(name="logical_or", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_or(x, y)),
    OpSpec(name="logical_xor", inputs={"x": _ints(hi=2).astype(bool), "y": _ints(hi=2).astype(bool)},
           ref=lambda x, y: np.logical_xor(x, y)),
    OpSpec(name="logical_not", inputs={"x": _ints(hi=2).astype(bool)},
           ref=lambda x: np.logical_not(x)),
    B("equal", np.equal, y=_arr((4,)), grad=()),
    B("not_equal", np.not_equal, grad=()),
    B("greater_equal", np.greater_equal, grad=()),
    B("greater_than", np.greater, grad=()),
    B("less_equal", np.less_equal, grad=()),
    B("less_than", np.less, grad=()),
    B("equal_all", lambda x, y: np.array(np.array_equal(x, y)), grad=()),
    B("allclose", lambda x, y: np.array(np.allclose(x, y)), grad=()),
    B("isclose", np.isclose, grad=()),
    U("isfinite", np.isfinite, grad=False),
    U("isinf", np.isinf, grad=False),
    U("isnan", np.isnan, grad=False),
    U("isneginf", np.isneginf, grad=False),
    U("isposinf", np.isposinf, grad=False),
    U("isreal", np.isreal, grad=False),

    # ---- reductions ----------------------------------------------------------
    *RED("sum", np.sum),
    *RED("mean", np.mean),
    *RED("prod", np.prod, x=_arr((3, 4, 2), lo=0.5, hi=1.5)),
    *RED("max", np.max, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("min", np.min, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("amax", np.max, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("amin", np.min, x=_arr((3, 4, 2)) * 7, grad=False),
    *RED("all", lambda x, axis=None, keepdims=False: np.all(x, axis=axis, keepdims=keepdims),
         x=_ints((3, 4, 2), hi=2).astype(bool), grad=False),
    *RED("any", lambda x, axis=None, keepdims=False: np.any(x, axis=axis, keepdims=keepdims),
         x=_ints((3, 4, 2), hi=2).astype(bool), grad=False),
    *RED("nansum", np.nansum, grad=False),
    *RED("nanmean", np.nanmean, grad=False),
    *RED("logsumexp", lambda x, axis=None, keepdims=False: sp.logsumexp(x, axis=axis, keepdims=keepdims)),
    *RED("median", lambda x, axis=None, keepdims=False: np.median(x, axis=axis, keepdims=keepdims),
         x=_arr((3, 5)), grad=False),
    *RED("nanmedian", lambda x, axis=None, keepdims=False: np.nanmedian(x, axis=axis, keepdims=keepdims),
         x=_arr((3, 5)), grad=False),
    *RED("count_nonzero", lambda x, axis=None, keepdims=False:
         np.count_nonzero(x, axis=axis, keepdims=keepdims), grad=False),
    OpSpec(name="std", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.std(x, ddof=1), grad=("x",)),
    OpSpec(name="std", inputs={"x": _arr((3, 5))}, attrs={"axis": 1},
           ref=lambda x, axis: np.std(x, axis=axis, ddof=1), grad=("x",)),
    OpSpec(name="var", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.var(x, ddof=1), grad=("x",)),
    OpSpec(name="var", inputs={"x": _arr((3, 5))},
           attrs={"axis": 0, "unbiased": False},
           ref=lambda x, axis, unbiased: np.var(x, axis=axis, ddof=0),
           grad=("x",)),
    OpSpec(name="argmax", inputs={"x": _arr((3, 5)) * 9}, attrs={"axis": 1},
           ref=lambda x, axis: np.argmax(x, axis=axis), out_cast=False, grad=()),
    OpSpec(name="argmin", inputs={"x": _arr((3, 5)) * 9}, attrs={"axis": 0},
           ref=lambda x, axis: np.argmin(x, axis=axis), out_cast=False, grad=()),

    # ---- cumulative ----------------------------------------------------------
    OpSpec(name="cumsum", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.cumsum(x, axis=axis), grad=("x",)),
    OpSpec(name="cumprod", inputs={"x": _arr((3, 4), lo=0.4, hi=1.6)},
           attrs={"dim": 1},
           ref=lambda x, dim: np.cumprod(x, axis=dim), grad=("x",)),
    OpSpec(name="logcumsumexp", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.log(np.cumsum(np.exp(x), axis=axis)),
           grad=("x",)),
    OpSpec(name="cummax", inputs={"x": _arr((3, 4)) * 5}, attrs={"axis": 1},
           ref=lambda x, axis: (np.maximum.accumulate(x, axis=axis),
                                _cum_idx(x, axis, np.greater_equal)),
           out_cast=False, grad=()),
    OpSpec(name="cummin", inputs={"x": _arr((3, 4)) * 5}, attrs={"axis": 1},
           ref=lambda x, axis: (np.minimum.accumulate(x, axis=axis),
                                _cum_idx(x, axis, np.less_equal)),
           out_cast=False, grad=()),

    # ---- linalg --------------------------------------------------------------
    OpSpec(name="matmul", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="bmm", inputs={"x": _arr((2, 3, 4)), "y": _arr((2, 4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="mm", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           ref=lambda x, y: x @ y, grad=("x", "y")),
    OpSpec(name="mv", inputs={"x": _arr((3, 4)), "vec": _arr((4,))},
           ref=lambda x, vec: x @ vec, grad=("x", "vec")),
    OpSpec(name="dot", inputs={"x": _arr((5,)), "y": _arr((5,))},
           ref=lambda x, y: np.array(np.dot(x, y)), grad=("x", "y")),
    B("inner", np.inner, x=_arr((3, 4)), y=_arr((2, 4))),
    B("outer", np.outer, x=_arr((3,)), y=_arr((4,))),
    B("kron", np.kron, x=_arr((2, 3)), y=_arr((3, 2))),
    B("cross", lambda x, y: np.cross(x, y), x=_arr((4, 3)), y=_arr((4, 3))),
    OpSpec(name="trace", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.array(np.trace(x)), grad=("x",)),
    OpSpec(name="diagonal", inputs={"x": _arr((3, 4))},
           ref=lambda x: np.diagonal(x), grad=("x",)),
    OpSpec(name="linalg.diag_embed", inputs={"x": _arr((3, 4))},
           ref=lambda x: _diag_embed_ref(x), grad=()),
    OpSpec(name="linalg.det", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.det(x)), grad=("x",),
           grad_rtol=3e-2),
    OpSpec(name="linalg.inverse", inputs={"x": _spd()},
           ref=lambda x: np.linalg.inv(x), grad=("x",), grad_rtol=3e-2),
    # grad via symmetrized ref: numpy reads only the lower triangle, while
    # the jax VJP distributes the cotangent across both triangles
    OpSpec(name="linalg.cholesky", inputs={"x": _spd()},
           ref=lambda x: np.linalg.cholesky((x + x.T) / 2),
           grad=("x",), grad_rtol=3e-2),
    OpSpec(name="linalg.solve", inputs={"x": _spd(), "y": _arr((4, 2))},
           ref=lambda x, y: np.linalg.solve(x, y), grad=("x", "y"), grad_rtol=3e-2),
    OpSpec(name="linalg.cholesky_solve", inputs={"x": _arr((4, 2)),
                                          "y": np.linalg.cholesky(_spd())},
           attrs={"upper": False},
           ref=lambda x, y, upper: np.linalg.solve(y @ y.T, x), grad=(),
           rtol=1e-4, atol=1e-5),
    OpSpec(name="linalg.triangular_solve",
           inputs={"x": np.tril(_arr((4, 4))) + 3 * np.eye(4), "y": _arr((4, 2))},
           attrs={"upper": False},
           ref=lambda x, y, upper: np.linalg.solve(x, y), grad=(),
           rtol=1e-4, atol=1e-5),
    OpSpec(name="linalg.matrix_power", inputs={"x": _spd()}, attrs={"n": 3},
           ref=lambda x, n: np.linalg.matrix_power(x, n), rtol=1e-4, atol=1e-4, grad=("x",),
           grad_rtol=5e-2, grad_atol=1e-2),
    OpSpec(name="linalg.matrix_rank", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.matrix_rank(x)), out_cast=False,
           grad=()),
    OpSpec(name="linalg.pinv", inputs={"x": _arr((4, 3))},
           ref=lambda x: np.linalg.pinv(x), rtol=1e-4, atol=1e-5, grad=()),
    OpSpec(name="linalg.cond", inputs={"x": _spd()},
           ref=lambda x: np.array(np.linalg.cond(x)), rtol=1e-4, atol=1e-4,
           grad=()),
    OpSpec(name="linalg.multi_dot", inputs={"xs": [_arr((3, 4)), _arr((4, 5)), _arr((5, 2))]},
           ref=lambda xs: np.linalg.multi_dot(xs), grad=()),
    OpSpec(name="addmm", inputs={"input": _arr((3, 5)), "x": _arr((3, 4)),
                                 "y": _arr((4, 5))},
           attrs={"beta": 0.7, "alpha": 1.3},
           ref=lambda input, x, y, beta, alpha: beta * input + alpha * (x @ y),
           grad=("input", "x", "y")),
    OpSpec(name="linalg.cov", inputs={"x": _arr((3, 6))},
           ref=lambda x: np.cov(x), grad=("x",)),
    OpSpec(name="linalg.corrcoef", inputs={"x": _arr((3, 6))},
           ref=lambda x: np.corrcoef(x), grad=()),
    OpSpec(name="dist", inputs={"x": _arr((3, 4)), "y": _arr((3, 4))},
           attrs={"p": 2},
           ref=lambda x, y, p: np.array(np.linalg.norm((x - y).ravel(), p)),
           grad=("x", "y")),
    OpSpec(name="linalg.householder_product",
           inputs={"x": np.tril(_arr((4, 3)), -1) + np.eye(4, 3),
                   "tau": _pos((3,), 0.1, 0.9)},
           ref=lambda x, tau: _householder_ref(x, tau),
           rtol=1e-4, atol=1e-5, grad=()),

    # ---- manipulation --------------------------------------------------------
    OpSpec(name="concat", inputs={"x": [_arr((2, 3)), _arr((2, 3))]},
           attrs={"axis": 1},
           ref=lambda x, axis: np.concatenate(x, axis=axis), grad=()),
    OpSpec(name="stack", inputs={"x": [_arr((2, 3)), _arr((2, 3))]},
           attrs={"axis": 0}, ref=lambda x, axis: np.stack(x, axis), grad=()),
    OpSpec(name="reshape", inputs={"x": _arr((3, 4))}, attrs={"shape": [2, 6]},
           ref=lambda x, shape: np.reshape(x, shape), grad=("x",)),
    OpSpec(name="transpose", inputs={"x": _arr((2, 3, 4))},
           attrs={"perm": [2, 0, 1]},
           ref=lambda x, perm: np.transpose(x, perm), grad=("x",)),
    OpSpec(name="t", inputs={"x": _arr((3, 4))},
           ref=lambda x: x.T, grad=("x",)),
    OpSpec(name="moveaxis", inputs={"x": _arr((2, 3, 4))},
           attrs={"source": 0, "destination": 2},
           ref=lambda x, source, destination: np.moveaxis(x, source, destination),
           grad=("x",)),
    OpSpec(name="swapaxes", inputs={"x": _arr((2, 3, 4))},
           attrs={"axis0": 0, "axis1": 2},
           ref=lambda x, axis0, axis1: np.swapaxes(x, axis0, axis1),
           grad=("x",)),
    OpSpec(name="flatten", inputs={"x": _arr((2, 3, 4))},
           attrs={"start_axis": 1, "stop_axis": 2},
           ref=lambda x, start_axis, stop_axis: x.reshape(2, 12), grad=("x",)),
    OpSpec(name="squeeze", inputs={"x": _arr((3, 1, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.squeeze(x, axis), grad=("x",)),
    OpSpec(name="unsqueeze", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.expand_dims(x, axis), grad=("x",)),
    OpSpec(name="tile", inputs={"x": _arr((2, 3))},
           attrs={"repeat_times": [2, 2]},
           ref=lambda x, repeat_times: np.tile(x, repeat_times), grad=("x",)),
    OpSpec(name="expand", inputs={"x": _arr((1, 3))}, attrs={"shape": [4, 3]},
           ref=lambda x, shape: np.broadcast_to(x, shape), grad=("x",)),
    OpSpec(name="broadcast_to", inputs={"x": _arr((1, 3))},
           attrs={"shape": [4, 3]},
           ref=lambda x, shape: np.broadcast_to(x, shape), grad=("x",)),
    OpSpec(name="expand_as", inputs={"x": _arr((1, 3)), "y": _arr((4, 3))},
           ref=lambda x, y: np.broadcast_to(x, y.shape), grad=()),
    OpSpec(name="flip", inputs={"x": _arr((3, 4))}, attrs={"axis": [0]},
           ref=lambda x, axis: np.flip(x, axis), grad=("x",)),
    OpSpec(name="rot90", inputs={"x": _arr((3, 4))}, attrs={"k": 1},
           ref=lambda x, k: np.rot90(x, k), grad=("x",)),
    OpSpec(name="roll", inputs={"x": _arr((3, 4))},
           attrs={"shifts": 2, "axis": 1},
           ref=lambda x, shifts, axis: np.roll(x, shifts, axis), grad=("x",)),
    OpSpec(name="tril", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.tril(x), grad=("x",)),
    OpSpec(name="triu", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.triu(x), grad=("x",)),
    OpSpec(name="diag", inputs={"x": _arr((4,))},
           ref=lambda x: np.diag(x), grad=("x",)),
    OpSpec(name="diagflat", inputs={"x": _arr((2, 3))},
           ref=lambda x: np.diagflat(x), grad=()),
    OpSpec(name="gather", inputs={"x": _arr((5, 3)),
                                  "index": np.array([0, 2, 4])},
           ref=lambda x, index: x[index], grad=("x",)),
    OpSpec(name="gather_nd", inputs={"x": _arr((3, 4)),
                                     "index": np.array([[0, 1], [2, 3]])},
           ref=lambda x, index: x[tuple(index.T)], grad=("x",)),
    OpSpec(name="index_select", inputs={"x": _arr((5, 3)),
                                        "index": np.array([1, 1, 3])},
           attrs={"axis": 0},
           ref=lambda x, index, axis: np.take(x, index, axis), grad=("x",)),
    OpSpec(name="index_sample", inputs={"x": _arr((3, 5)),
                                        "index": _ints((3, 2), 0, 5)},
           ref=lambda x, index: np.take_along_axis(x, index, 1), grad=("x",)),
    OpSpec(name="take", inputs={"x": _arr((3, 4)),
                                "index": np.array([0, 5, 11])},
           ref=lambda x, index: x.ravel()[index], grad=()),
    OpSpec(name="take_along_axis", inputs={"x": _arr((3, 5)),
                                           "indices": _ints((3, 2), 0, 5)},
           attrs={"axis": 1},
           ref=lambda x, indices, axis: np.take_along_axis(x, indices, axis),
           grad=()),
    OpSpec(name="masked_select",
           inputs={"x": np.array([1.0, 2.0, 3.0, 4.0]),
                   "mask": np.array([True, False, True, False])},
           ref=lambda x, mask: x[mask], grad=()),
    OpSpec(name="masked_fill",
           inputs={"x": _arr((3, 4)),
                   "mask": _ints((3, 4), 0, 2).astype(bool)},
           attrs={"value": -1.5},
           ref=lambda x, mask, value: np.where(mask, value, x), grad=("x",)),
    OpSpec(name="where", inputs={"condition": _ints((3, 4), 0, 2).astype(bool),
                                 "x": _arr((3, 4)), "y": _arr((3, 4))},
           ref=lambda condition, x, y: np.where(condition, x, y),
           grad=("x", "y")),
    OpSpec(name="multiplex", inputs={"inputs": [_arr((4, 3)), _arr((4, 3))],
                                     "index": np.array([[0], [1], [0], [1]])},
           ref=lambda inputs, index: np.stack(
               [inputs[int(i)][r] for r, i in enumerate(index[:, 0])]),
           grad=()),
    OpSpec(name="pad", inputs={"x": _arr((3, 4))},
           attrs={"pad": [1, 1, 0, 2], "value": 0.5},
           ref=lambda x, pad, value: np.pad(
               x, [(pad[0], pad[1]), (pad[2], pad[3])],
               constant_values=value),
           grad=("x",)),
    OpSpec(name="slice", inputs={"x": _arr((4, 5))},
           attrs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]},
           ref=lambda x, axes, starts, ends: x[1:3, 0:4], grad=("x",)),
    OpSpec(name="strided_slice", inputs={"x": _arr((6, 5))},
           attrs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
           ref=lambda x, axes, starts, ends, strides: x[::2], grad=("x",)),
    OpSpec(name="crop", inputs={"x": _arr((4, 5))},
           attrs={"shape": [2, 3], "offsets": [1, 1]},
           ref=lambda x, shape, offsets: x[1:3, 1:4], grad=()),
    OpSpec(name="repeat_interleave", inputs={"x": _arr((3, 2))},
           attrs={"repeats": 2, "axis": 0},
           ref=lambda x, repeats, axis: np.repeat(x, repeats, axis),
           grad=("x",)),
    OpSpec(name="unbind", inputs={"x": _arr((3, 4))}, attrs={"axis": 0},
           ref=lambda x, axis: [x[i] for i in range(3)], grad=()),
    OpSpec(name="unstack", inputs={"x": _arr((3, 4))}, attrs={"axis": 0},
           ref=lambda x, axis: [x[i] for i in range(3)], grad=()),
    OpSpec(name="split", inputs={"x": _arr((4, 6))},
           attrs={"num_or_sections": 2, "axis": 1},
           ref=lambda x, num_or_sections, axis: np.split(x, 2, axis), grad=()),
    OpSpec(name="chunk", inputs={"x": _arr((4, 6))},
           attrs={"chunks": 3, "axis": 1},
           ref=lambda x, chunks, axis: np.split(x, 3, axis), grad=()),
    OpSpec(name="as_complex", inputs={"x": np.stack([_arr((3, 4)), _arr((3, 4))], -1)},
           ref=lambda x: x[..., 0] + 1j * x[..., 1], grad=(), out_cast=False,
           rtol=1e-6, atol=1e-6),
    OpSpec(name="as_real", inputs={"x": (_arr((3, 4)) + 1j * _arr((3, 4))).astype("complex64")},
           ref=lambda x: np.stack([x.real, x.imag], -1), grad=(),
           rtol=1e-6, atol=1e-6),

    # ---- sorting / search ----------------------------------------------------
    OpSpec(name="sort", inputs={"x": _arr((3, 5)) * 9},
           ref=lambda x: np.sort(x, axis=-1), grad=("x",)),
    OpSpec(name="argsort", inputs={"x": _arr((3, 5)) * 9},
           ref=lambda x: np.argsort(x, axis=-1, kind="stable"),
           out_cast=False, grad=()),
    OpSpec(name="topk", inputs={"x": _arr((3, 6)) * 9}, attrs={"k": 2},
           ref=lambda x, k: (np.sort(x, -1)[:, ::-1][:, :k],
                             np.argsort(-x, -1, kind="stable")[:, :k]),
           out_cast=False, grad=()),
    OpSpec(name="kthvalue", inputs={"x": _arr((3, 6)) * 9}, attrs={"k": 2},
           ref=lambda x, k: (np.sort(x, -1)[:, k - 1],
                             np.argsort(x, -1, kind="stable")[:, k - 1]),
           out_cast=False, grad=()),
    OpSpec(name="mode", inputs={"x": _ints((3, 5), 0, 3).astype("float64")},
           ref=lambda x: _mode_ref(x), out_cast=False, grad=()),
    OpSpec(name="searchsorted",
           inputs={"sorted_sequence": np.array([1.0, 3.0, 5.0, 7.0]),
                   "values": np.array([0.0, 4.0, 8.0])},
           ref=lambda sorted_sequence, values: np.searchsorted(
               sorted_sequence, values), out_cast=False, grad=()),
    OpSpec(name="bucketize",
           inputs={"x": np.array([0.0, 2.0, 4.0, 6.0]),
                   "sorted_sequence": np.array([1.0, 3.0, 5.0])},
           ref=lambda x, sorted_sequence: np.searchsorted(sorted_sequence, x),
           out_cast=False, grad=()),
    OpSpec(name="nonzero", inputs={"x": np.array([[1.0, 0.0], [0.0, 2.0]])},
           ref=lambda x: np.stack(np.nonzero(x), -1), out_cast=False, grad=()),
    OpSpec(name="unique", inputs={"x": np.array([3.0, 1.0, 3.0, 2.0])},
           ref=lambda x: np.unique(x), grad=()),
    OpSpec(name="unique_consecutive",
           inputs={"x": np.array([1.0, 1.0, 2.0, 2.0, 3.0, 1.0])},
           ref=lambda x: np.array([1.0, 2.0, 3.0, 1.0]), grad=()),
    OpSpec(name="histogram", inputs={"x": _pos((20,), 0.0, 1.0)},
           attrs={"bins": 4, "min": 0.0, "max": 1.0},
           ref=lambda x, bins, min, max: np.histogram(
               x, bins=bins, range=(min, max))[0],
           out_cast=False, grad=()),
    OpSpec(name="bincount", inputs={"x": _ints((12,), 0, 5)},
           ref=lambda x: np.bincount(x), out_cast=False, grad=()),

    # ---- misc ----------------------------------------------------------------
    OpSpec(name="trapezoid", inputs={"y": _arr((3, 5))}, attrs={"dx": 0.5},
           ref=lambda y, dx: np.trapz(y, dx=dx, axis=-1), grad=("y",)),
    OpSpec(name="diff", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.diff(x, axis=-1), grad=("x",)),
    OpSpec(name="norm", inputs={"x": _arr((3, 4))},
           ref=lambda x: np.array(np.linalg.norm(x)), grad=("x",)),
    OpSpec(name="norm", inputs={"x": _arr((3, 4))}, attrs={"p": 1, "axis": 1},
           ref=lambda x, p, axis: np.linalg.norm(x, p, axis), grad=()),
    OpSpec(name="tensordot", inputs={"x": _arr((3, 4)), "y": _arr((4, 5))},
           attrs={"axes": 1},
           ref=lambda x, y, axes: np.tensordot(x, y, axes), grad=()),
    OpSpec(name="dot", inputs={"x": _arr((2, 5)), "y": _arr((2, 5))},
           ref=lambda x, y: np.sum(x * y, -1), grad=("x", "y")),

    # ---- round-3 tensor-surface tail ----------------------------------------
    U("sinc", np.sinc),
    OpSpec(name="multigammaln", inputs={"x": _pos(lo=2.0, hi=5.0)},
           attrs={"p": 2},
           ref=lambda x, p: __import__("scipy.special", fromlist=["x"])
           .multigammaln(x, p), grad=("x",)),
    OpSpec(name="isin", inputs={"x": _ints((3, 4), 0, 6),
                                "test_x": _ints((4,), 0, 4)},
           ref=lambda x, test_x: np.isin(x, test_x), out_cast=False, grad=()),
    U("sgn", np.sign, grad=False),
    OpSpec(name="frexp", inputs={"x": _arr(lo=0.3, hi=4.0)},
           ref=lambda x: tuple(np.frexp(x)), grad=(), out_cast=True),
    U("signbit", np.signbit, grad=False, out_cast=False),
    OpSpec(name="cumulative_trapezoid", inputs={"y": _arr((3, 5))},
           attrs={"dx": 0.5},
           ref=lambda y, dx: __import__(
               "scipy.integrate", fromlist=["x"]).cumulative_trapezoid(
                   y, dx=dx, axis=-1), grad=("y",)),
    OpSpec(name="reduce_as", inputs={"x": _arr((3, 4)), "target": _arr((1, 4))},
           ref=lambda x, target: np.sum(x, 0, keepdims=True), grad=("x",)),
    OpSpec(name="add_n", inputs={"inputs": [_arr(), _arr(), _arr()]},
           ref=lambda inputs: inputs[0] + inputs[1] + inputs[2], grad=()),
    OpSpec(name="histogram_bin_edges", inputs={"x": _arr()},
           attrs={"bins": 5, "min": -1.0, "max": 1.0},
           ref=lambda x, bins, min, max: np.histogram_bin_edges(
               x, bins=bins, range=(min, max)), grad=()),
    OpSpec(name="block_diag", inputs={"inputs": [_arr((2, 3)), _arr((3, 2))]},
           ref=lambda inputs: __import__(
               "scipy.linalg", fromlist=["x"]).block_diag(*inputs), grad=()),
    OpSpec(name="cdist", inputs={"x": _arr((4, 3)), "y": _arr((5, 3))},
           ref=lambda x, y: __import__(
               "scipy.spatial.distance", fromlist=["x"]).cdist(x, y),
           grad=("x", "y"), grad_atol=5e-3),
    OpSpec(name="unflatten", inputs={"x": _arr((3, 4))},
           attrs={"axis": 1, "shape": [2, 2]},
           ref=lambda x, axis, shape: x.reshape(3, 2, 2), grad=("x",)),
    OpSpec(name="slice_scatter",
           inputs={"x": _arr((4, 5)), "value": _arr((4, 2))},
           attrs={"axes": [1], "starts": [1], "ends": [3], "strides": [1]},
           ref=lambda x, value, axes, starts, ends, strides: _np_slice_scatter(
               x, value), grad=("x", "value")),
    OpSpec(name="select_scatter",
           inputs={"x": _arr((4, 5)), "value": _arr((5,))},
           attrs={"axis": 0, "index": 2},
           ref=lambda x, value, axis, index: _np_select_scatter(x, value),
           grad=("x", "value")),
    OpSpec(name="diagonal_scatter",
           inputs={"x": _arr((4, 4)), "y": _arr((4,))},
           ref=lambda x, y: _np_diagonal_scatter(x, y), grad=("x", "y")),
    OpSpec(name="masked_scatter",
           inputs={"x": _arr((3, 4)),
                   "mask": R.uniform(0, 1, (3, 4)) > 0.5,
                   "value": _arr((12,))},
           ref=lambda x, mask, value: _np_masked_scatter(x, mask, value),
           grad=()),
    OpSpec(name="cholesky_inverse",
           inputs={"x": np.linalg.cholesky(_spd(4))},
           ref=lambda x: np.linalg.inv(x @ x.T), grad=(),
           rtol=1e-4, atol=1e-4),
    OpSpec(name="pdist", inputs={"x": _arr((5, 3))},
           ref=lambda x: __import__(
               "scipy.spatial.distance", fromlist=["x"]).pdist(x),
           grad=("x",), grad_atol=5e-3),
    U("positive", lambda x: +x),
    OpSpec(name="hstack", inputs={"x": [_arr((2, 3)), _arr((2, 2))]},
           ref=lambda x: np.hstack(x), grad=()),
    OpSpec(name="vstack", inputs={"x": [_arr((2, 3)), _arr((1, 3))]},
           ref=lambda x: np.vstack(x), grad=(), covers=("row_stack",)),
    OpSpec(name="dstack", inputs={"x": [_arr((2, 3)), _arr((2, 3))]},
           ref=lambda x: np.dstack(x), grad=()),
    OpSpec(name="column_stack", inputs={"x": [_arr((3,)), _arr((3, 2))]},
           ref=lambda x: np.column_stack(x), grad=()),
    OpSpec(name="cartesian_prod",
           inputs={"x": [_arr((2,)), _arr((3,))]},
           ref=lambda x: np.stack(
               [g.reshape(-1) for g in np.meshgrid(*x, indexing="ij")], -1),
           grad=()),
    OpSpec(name="combinations", inputs={"x": _arr((4,))},
           ref=lambda x: np.asarray(
               list(__import__("itertools").combinations(x, 2))), grad=()),
    OpSpec(name="linalg.ormqr",
           inputs=_ormqr_inputs(),
           ref=lambda x, tau, other: _np_ormqr(x, tau, other), grad=(),
           rtol=1e-4, atol=1e-5),
]


def _np_slice_scatter(x, value):
    out = x.copy()
    out[:, 1:3] = value
    return out


def _np_select_scatter(x, value):
    out = x.copy()
    out[2] = value
    return out


def _np_diagonal_scatter(x, y):
    out = x.copy()
    np.fill_diagonal(out, y)
    return out


def _np_masked_scatter(x, mask, value):
    out = x.copy()
    out[mask] = value[: mask.sum()]
    return out


def _np_ormqr(x, tau, other):
    import scipy.linalg as sl

    # apply the full implicit Q via LAPACK ormqr itself
    res = sl.lapack.dormqr("L", "N", x, tau, other.copy(),
                           max(1, 64 * other.shape[1]))
    return res[0]


def _cum_idx(x, axis, cmp):
    """Running-extreme indices, latest occurrence winning ties (torch/paddle
    cummax/cummin convention)."""
    running = np.take(x, [0], axis=axis)
    run_idx = np.zeros(running.shape, "int64")
    parts = []
    for i in range(x.shape[axis]):
        cur = np.take(x, [i], axis=axis)
        better = cmp(cur, running)
        running = np.where(better, cur, running)
        run_idx = np.where(better, i, run_idx)
        parts.append(run_idx.copy())
    return np.concatenate(parts, axis=axis)


def _diag_embed_ref(x):
    out = np.zeros(x.shape + (x.shape[-1],), x.dtype)
    for i in range(x.shape[0]):
        out[i] = np.diag(x[i])
    return out


def _householder_ref(x, tau):
    m, n = x.shape
    q = np.eye(m)
    for j in range(n):
        v = x[:, j].copy()
        v[:j] = 0
        v[j] = 1
        q = q @ (np.eye(m) - tau[j] * np.outer(v, v))
    return q[:, :n]


def _mode_ref(x):
    """Smallest most-frequent value, last-occurrence index (torch/paddle
    mode tie convention)."""
    vals = np.zeros(x.shape[0])
    idxs = np.zeros(x.shape[0], "int64")
    for r in range(x.shape[0]):
        uniq, counts = np.unique(x[r], return_counts=True)
        best = uniq[counts == counts.max()].min()
        vals[r] = best
        idxs[r] = np.where(x[r] == best)[0][-1]
    return vals, idxs




# ---- helper refs for the schema-tail specs ----------------------------------
import scipy.special as _sp  # noqa: E402


def _erf(x):
    return _sp.erf(x)


def _with_nan(x):
    x = x.copy()
    x[0, 0] = np.nan
    return x


def _pos(shape=(3, 4), lo=0.1, hi=2.0):
    return R.uniform(lo, hi, shape)


def _cos_sim(a, b, axis=1):
    num = (a * b).sum(axis)
    den = np.sqrt((a * a).sum(axis)) * np.sqrt((b * b).sum(axis))
    return num / np.maximum(den, 1e-12)


def _softmax_ce_ref(logits, label):
    m = logits.max(-1, keepdims=True)
    logp = logits - m - np.log(np.exp(logits - m).sum(-1, keepdims=True))
    return np.mean(-np.take_along_axis(logp, label[:, None], -1)[:, 0])


def _multi_margin_ref(logit, label):
    n, c = logit.shape
    correct = np.take_along_axis(logit, label[:, None], 1)
    m = np.maximum(0.0, 1.0 - correct + logit)
    mask = np.eye(c)[label]
    return np.mean((m * (1 - mask)).sum(1) / c)


def _npair_ref(anchor, positive, labels):
    reg = 0.002 * ((anchor ** 2).sum(-1).mean()
                   + (positive ** 2).sum(-1).mean()) * 0.25
    sim = anchor @ positive.T
    eq = (labels[:, None] == labels[None, :]).astype("float64")
    tgt = eq / eq.sum(-1, keepdims=True)
    m = sim.max(-1, keepdims=True)
    logp = sim - m - np.log(np.exp(sim - m).sum(-1, keepdims=True))
    return -(tgt * logp).sum(-1).mean() + reg


def _temporal_shift_ref(x, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    y = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    out = np.zeros_like(y)
    out[:, :-1, :fold] = y[:, 1:, :fold]            # shift left
    out[:, 1:, fold:2 * fold] = y[:, :-1, fold:2 * fold]  # shift right
    out[:, :, 2 * fold:] = y[:, :, 2 * fold:]
    return out.reshape(nt, c, h, w)


def _fold_ref(x, output_sizes, kernel_sizes, strides):
    n, ckk, L = x.shape
    kh, kw = kernel_sizes
    c = ckk // (kh * kw)
    oh, ow = output_sizes
    lh = (oh - kh) // strides + 1
    lw = (ow - kw) // strides + 1
    cols = x.reshape(n, c, kh, kw, lh, lw)
    out = np.zeros((n, c, oh, ow))
    for i in range(kh):
        for j in range(kw):
            out[:, :, i:i + strides * lh:strides,
                j:j + strides * lw:strides] += cols[:, :, i, j]
    return out


def _unfold_ref(x, k, s):
    n, c, h, w = x.shape
    lh = (h - k) // s + 1
    lw = (w - k) // s + 1
    cols = np.zeros((n, c, k, k, lh, lw))
    for i in range(k):
        for j in range(k):
            cols[:, :, i, j] = x[:, :, i:i + s * lh:s, j:j + s * lw:s]
    return cols.reshape(n, c * k * k, lh * lw)


def _lp_pool_ref(x, p, k):
    n, c, h, w = x.shape
    out = np.zeros((n, c, h // k, w // k))
    for i in range(h // k):
        for j in range(w // k):
            win = x[:, :, i * k:(i + 1) * k, j * k:(j + 1) * k]
            out[:, :, i, j] = ((np.abs(win) ** p).sum((-2, -1))) ** (1.0 / p)
    return out


def _affine_grid_ref(theta, out_shape):
    n, _c, h, w = out_shape
    ys = np.linspace(-1, 1, h)
    xs = np.linspace(-1, 1, w)
    gx, gy = np.meshgrid(xs, ys)
    base = np.stack([gx, gy, np.ones_like(gx)], -1)
    return np.einsum("hwk,nok->nhwo", base, theta)


def _identity_grid(n, h, w):
    ys = np.linspace(-1, 1, h)
    xs = np.linspace(-1, 1, w)
    gx, gy = np.meshgrid(xs, ys)
    g = np.stack([gx, gy], -1)[None]
    return np.repeat(g, n, 0)


def _max_unpool_ref(x, indices, out_hw):
    n, c = x.shape[:2]
    out = np.zeros((n, c, out_hw[0] * out_hw[1]))
    for b in range(n):
        for ch in range(c):
            out[b, ch, indices[b, ch].ravel()] = x[b, ch].ravel()
    return out.reshape(n, c, *out_hw)


def _overlap_add_ref(x, hop):
    frame_len, n_frames = x.shape
    out = np.zeros(hop * (n_frames - 1) + frame_len)
    for i in range(n_frames):
        out[i * hop:i * hop + frame_len] += x[:, i]
    return out


def _index_fill_ref(x, index, axis, value):
    out = x.copy()
    if axis == 0:
        out[index] = value
    else:
        out[:, index] = value
    return out


def _index_add_ref(x, index, value):
    out = x.copy()
    for i, idx in enumerate(index):
        out[idx] += value[i]
    return out


def _index_put_ref(x, indices, value):
    out = x.copy()
    out[indices] = value
    return out


def _put_along_ref(arr, indices, values, axis):
    out = arr.copy()
    np.put_along_axis(out, indices, values, axis)
    return out


def _scatter_ref(x, index, updates):
    out = x.copy()
    out[index] = updates
    return out


def _scatter_nd_ref(index, updates, shape):
    out = np.zeros(shape)
    for i, idx in enumerate(index[:, 0]):
        out[idx] += updates[i]
    return out


def _fill_diag_ref(x, value):
    out = x.copy()
    np.fill_diagonal(out, value)
    return out


def _flatten_specs(items):
    flat = []
    for it in items:
        if isinstance(it, list):
            flat.extend(it)
        else:
            flat.append(it)
    return flat


# ---- schema tail: activations (VERDICT r3: registry >=300, all swept) -------

_SCHEMA_SPECS = [
    OpSpec(name="nn.functional.celu", inputs={"x": _arr()}, attrs={"alpha": 2.0},
           ref=lambda x, alpha: np.maximum(0, x) + np.minimum(0, alpha * np.expm1(x / alpha)),
           grad=("x",), covers=("celu",)),
    OpSpec(name="nn.functional.elu", inputs={"x": _arr()}, attrs={"alpha": 1.5},
           ref=lambda x, alpha: np.where(x > 0, x, alpha * np.expm1(x)),
           grad=("x",), covers=("elu",)),
    OpSpec(name="nn.functional.gelu", inputs={"x": _arr()},
           ref=lambda x: x * 0.5 * (1 + _erf(x / np.sqrt(2.0))),
           grad=("x",), covers=("gelu",)),
    OpSpec(name="nn.functional.glu", inputs={"x": _arr((3, 6))},
           ref=lambda x: x[:, :3] * _sigmoid(x[:, 3:]), grad=("x",),
           covers=("glu",)),
    OpSpec(name="nn.functional.hardshrink", inputs={"x": _arr()},
           ref=lambda x: np.where(np.abs(x) > 0.5, x, 0.0), grad=("x",),
           covers=("hardshrink",)),
    OpSpec(name="nn.functional.hardsigmoid", inputs={"x": _arr()},
           ref=lambda x: np.clip(x * 0.1666667 + 0.5, 0, 1), grad=("x",),
           covers=("hardsigmoid",)),
    OpSpec(name="nn.functional.hardtanh", inputs={"x": _arr() * 3},
           ref=lambda x: np.clip(x, -1, 1), grad=("x",), covers=("hardtanh",)),
    OpSpec(name="nn.functional.leaky_relu", inputs={"x": _arr()},
           ref=lambda x: np.where(x >= 0, x, 0.01 * x), grad=("x",),
           covers=("leaky_relu",)),
    OpSpec(name="nn.functional.log_softmax", inputs={"x": _arr((3, 5))},
           ref=lambda x: x - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True)) - x.max(-1, keepdims=True),
           grad=("x",), covers=("log_softmax",)),
    OpSpec(name="nn.functional.softmax", inputs={"x": _arr((3, 5))},
           ref=lambda x: np.exp(x - x.max(-1, keepdims=True)) / np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
           grad=("x",), covers=("softmax", "softmax_")),
    OpSpec(name="nn.functional.maxout", inputs={"x": _arr((2, 6, 3, 3))},
           attrs={"groups": 2},
           ref=lambda x, groups: x.reshape(2, 3, groups, 3, 3).max(2),
           grad=("x",), covers=("maxout",)),
    OpSpec(name="nn.functional.prelu",
           inputs={"x": _arr((2, 3, 4)), "weight": np.array([0.25, 0.2, 0.1])},
           ref=lambda x, weight: np.where(x >= 0, x, x * weight[None, :, None]),
           grad=("x",), covers=("prelu",)),
    OpSpec(name="nn.functional.softplus", inputs={"x": _arr()},
           ref=lambda x: _softplus(x), grad=("x",), covers=("softplus",)),
    OpSpec(name="nn.functional.softshrink", inputs={"x": _arr() * 2},
           ref=lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0)),
           grad=("x",), covers=("softshrink",)),
    OpSpec(name="nn.functional.swish", inputs={"x": _arr()},
           ref=lambda x: x * _sigmoid(x), grad=("x",), covers=("swish",)),
    OpSpec(name="nn.functional.thresholded_relu", inputs={"x": _arr() * 2},
           ref=lambda x: np.where(x > 1.0, x, 0.0), grad=("x",),
           covers=("thresholded_relu",)),
    # ---- losses -------------------------------------------------------------
    OpSpec(name="nn.functional.binary_cross_entropy",
           inputs={"input": _arr(lo=0.05, hi=0.95), "label": _arr(lo=0.0, hi=1.0)},
           ref=lambda input, label: np.mean(-(label * np.log(input) + (1 - label) * np.log(1 - input))),
           grad=("input",), covers=("binary_cross_entropy",)),
    OpSpec(name="nn.functional.binary_cross_entropy_with_logits",
           inputs={"logit": _arr(), "label": _arr(lo=0.0, hi=1.0)},
           ref=lambda logit, label: np.mean(_softplus(logit) - label * logit),
           grad=("logit",), covers=("binary_cross_entropy_with_logits",)),
    OpSpec(name="nn.functional.mse_loss",
           inputs={"input": _arr(), "label": _arr()},
           ref=lambda input, label: np.mean((input - label) ** 2),
           grad=("input",), covers=("mse_loss",)),
    OpSpec(name="nn.functional.l1_loss",
           inputs={"input": _arr(), "label": _arr() + 0.3},
           ref=lambda input, label: np.mean(np.abs(input - label)),
           grad=("input",), covers=("l1_loss",)),
    OpSpec(name="nn.functional.smooth_l1_loss",
           inputs={"input": _arr() * 3, "label": _arr()},
           ref=lambda input, label: np.mean(np.where(np.abs(input - label) < 1.0,
                                                     0.5 * (input - label) ** 2,
                                                     np.abs(input - label) - 0.5)),
           grad=("input",), covers=("smooth_l1_loss",)),
    OpSpec(name="nn.functional.huber_loss",
           inputs={"input": _arr() * 3, "label": _arr()},
           ref=lambda input, label: np.mean(np.where(np.abs(input - label) <= 1.0,
                                                     0.5 * (input - label) ** 2,
                                                     np.abs(input - label) - 0.5)),
           grad=("input",), covers=("huber_loss",)),
    OpSpec(name="nn.functional.kl_div",
           inputs={"input": np.log(_arr(lo=0.1, hi=0.9)), "label": _arr(lo=0.1, hi=0.9)},
           ref=lambda input, label: np.mean(label * (np.log(label) - input)),
           grad=("input",), covers=("kl_div",)),
    OpSpec(name="nn.functional.margin_ranking_loss",
           inputs={"input": _arr(), "other": _arr(),
                   "label": np.sign(_arr()) + (np.sign(_arr()) == 0)},
           ref=lambda input, other, label: np.mean(np.maximum(0, -label * (input - other))),
           grad=("input",), covers=("margin_ranking_loss",)),
    OpSpec(name="nn.functional.hinge_embedding_loss",
           inputs={"input": _arr() * 2,
                   "label": np.where(_arr() > 0, 1.0, -1.0)},
           ref=lambda input, label: np.mean(np.where(label == 1.0, input,
                                                     np.maximum(0, 1.0 - input))),
           grad=("input",), covers=("hinge_embedding_loss",)),
    OpSpec(name="nn.functional.cosine_embedding_loss",
           inputs={"input1": _arr((4, 8)), "input2": _arr((4, 8)),
                   "label": np.array([1.0, -1.0, 1.0, -1.0])},
           ref=lambda input1, input2, label: np.mean(np.where(
               label == 1,
               1 - _cos_sim(input1, input2),
               np.maximum(0, _cos_sim(input1, input2)))),
           grad=(), covers=("cosine_embedding_loss",)),
    OpSpec(name="nn.functional.cosine_similarity",
           inputs={"x1": _arr((4, 8)), "x2": _arr((4, 8))},
           ref=lambda x1, x2: _cos_sim(x1, x2), grad=("x1", "x2"),
           covers=("cosine_similarity",)),
    OpSpec(name="nn.functional.triplet_margin_loss",
           inputs={"input": _arr((4, 8)), "positive": _arr((4, 8)),
                   "negative": _arr((4, 8))},
           ref=lambda input, positive, negative: np.mean(np.maximum(
               0, np.sqrt(((input - positive) ** 2).sum(-1) + 1e-6)
               - np.sqrt(((input - negative) ** 2).sum(-1) + 1e-6) + 1.0)),
           rtol=1e-4, atol=1e-5,
           grad=(), covers=("triplet_margin_loss",)),
    OpSpec(name="nn.functional.log_loss",
           inputs={"input": _arr(lo=0.1, hi=0.9), "label": _arr(lo=0.0, hi=1.0)},
           ref=lambda input, label: -label * np.log(input + 1e-4)
           - (1 - label) * np.log(1 - input + 1e-4),
           grad=("input",), covers=("log_loss",)),
    OpSpec(name="nn.functional.square_error_cost",
           inputs={"input": _arr(), "label": _arr()},
           ref=lambda input, label: (input - label) ** 2,
           grad=("input",), covers=("square_error_cost",)),
    OpSpec(name="nn.functional.sigmoid_focal_loss",
           inputs={"logit": _arr((4, 3)), "label": (_arr((4, 3)) > 0).astype("float64")},
           ref=lambda logit, label: np.sum(
               -(label * 0.25 + (1 - label) * 0.75)
               * ((1 - np.where(label > 0, _sigmoid(logit), 1 - _sigmoid(logit))) ** 2.0)
               * np.where(label > 0, np.log(_sigmoid(logit)), np.log(1 - _sigmoid(logit)))),
           rtol=1e-4, atol=1e-4, grad=(), covers=("sigmoid_focal_loss",)),
    OpSpec(name="nn.functional.softmax_with_cross_entropy",
           inputs={"logits": _arr((4, 5)), "label": np.array([[0], [2], [4], [1]])},
           ref=lambda logits, label: -np.take_along_axis(
               logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1, keepdims=True)) - logits.max(-1, keepdims=True),
               label, axis=-1),
           grad=("logits",), covers=("softmax_with_cross_entropy",)),
    OpSpec(name="nn.functional.soft_margin_loss",
           inputs={"logit": _arr() * 40, "label": np.where(_arr() > 0, 1.0, -1.0)},
           ref=lambda logit, label: np.mean(_softplus(-label * logit)),
           grad=("logit",), covers=("soft_margin_loss",)),
    OpSpec(name="nn.functional.multi_margin_loss",
           inputs={"logit": _arr((4, 5)), "label": np.array([0, 2, 4, 1])},
           ref=lambda logit, label: _multi_margin_ref(logit, label),
           grad=(), covers=("multi_margin_loss",)),
    OpSpec(name="nn.functional.multi_label_soft_margin_loss",
           inputs={"logit": _arr((4, 5)),
                   "label": (_arr((4, 5)) > 0).astype("float64")},
           ref=lambda logit, label: np.mean(np.mean(
               -(label * np.log(_sigmoid(logit))
                 + (1 - label) * np.log(_sigmoid(-logit))), -1)),
           grad=("logit",), covers=("multi_label_soft_margin_loss",)),
    OpSpec(name="nn.functional.npair_loss",
           inputs={"anchor": _arr((4, 8)), "positive": _arr((4, 8)),
                   "labels": np.array([0.0, 1.0, 0.0, 2.0])},
           ref=lambda anchor, positive, labels: _npair_ref(anchor, positive, labels),
           rtol=1e-4, atol=1e-5, grad=(), covers=("npair_loss",)),
    OpSpec(name="nn.functional.margin_cross_entropy",
           inputs={"logits": _arr((4, 5), lo=-0.9, hi=0.9),
                   "label": np.array([0, 2, 4, 1])},
           attrs={"margin1": 1.0, "margin2": 0.0, "margin3": 0.0, "scale": 2.0},
           ref=lambda logits, label, margin1, margin2, margin3, scale:
           _softmax_ce_ref(logits * scale, label),
           rtol=1e-4, atol=1e-4, grad=(), covers=("margin_cross_entropy",)),
    OpSpec(name="nn.functional.normalize", inputs={"x": _arr((3, 4))},
           ref=lambda x: x / np.maximum(np.sqrt((x * x).sum(1, keepdims=True)), 1e-12),
           grad=("x",), covers=("normalize",)),
    OpSpec(name="nn.functional.label_smooth",
           inputs={"label": (_arr((4, 5)) > 0).astype("float64")},
           ref=lambda label: 0.9 * label + 0.1 / 5,
           grad=(), covers=("label_smooth",)),
    OpSpec(name="nn.functional.one_hot", inputs={"x": np.array([0, 2, 1])},
           attrs={"num_classes": 4},
           ref=lambda x, num_classes: np.eye(num_classes)[x],
           grad=(), covers=("one_hot",)),
    OpSpec(name="nn.functional.sequence_mask",
           inputs={"lengths": np.array([1, 3, 2])}, attrs={"maxlen": 4},
           ref=lambda lengths, maxlen: (np.arange(maxlen)[None, :]
                                        < lengths[:, None]).astype("int64"),
           out_cast=False, grad=(), covers=("sequence_mask",)),
    OpSpec(name="nn.functional.temporal_shift",
           inputs={"x": _arr((4, 4, 2, 2))},
           attrs={"seg_num": 2, "shift_ratio": 0.25},
           ref=lambda x, seg_num, shift_ratio: _temporal_shift_ref(x, seg_num, shift_ratio),
           grad=("x",), covers=("temporal_shift",)),
    # ---- nn spatial tail ----------------------------------------------------
    OpSpec(name="nn.functional.channel_shuffle",
           inputs={"x": _arr((2, 6, 3, 3))}, attrs={"groups": 3},
           ref=lambda x, groups: x.reshape(2, groups, 2, 3, 3)
           .transpose(0, 2, 1, 3, 4).reshape(2, 6, 3, 3),
           grad=("x",), covers=("channel_shuffle",)),
    OpSpec(name="nn.functional.fold",
           inputs={"x": _arr((2, 4 * 4, 4))},
           attrs={"output_sizes": (4, 4), "kernel_sizes": (2, 2),
                  "strides": 2},
           ref=lambda x, output_sizes, kernel_sizes, strides:
           _fold_ref(x, output_sizes, kernel_sizes, strides),
           grad=("x",), covers=("fold",)),
    OpSpec(name="nn.functional.lp_pool2d",
           inputs={"x": _arr((2, 3, 4, 4), lo=0.1, hi=1.0)},
           attrs={"norm_type": 2, "kernel_size": 2},
           ref=lambda x, norm_type, kernel_size: _lp_pool_ref(x, norm_type, kernel_size),
           rtol=1e-4, atol=1e-5, grad=("x",), covers=("lp_pool2d",)),
    OpSpec(name="nn.functional.affine_grid",
           inputs={"theta": _arr((2, 2, 3))},
           attrs={"out_shape": (2, 1, 3, 4)},
           ref=lambda theta, out_shape: _affine_grid_ref(theta, out_shape),
           rtol=1e-4, atol=1e-5, grad=("theta",), covers=("affine_grid",)),
    # grid_sample checked against its own identity-warp property + ref
    OpSpec(name="nn.functional.grid_sample",
           inputs={"x": _arr((1, 2, 4, 4)),
                   "grid": _identity_grid(1, 4, 4)},
           ref=lambda x, grid: x,  # identity grid returns the input
           rtol=1e-4, atol=1e-5, grad=("x",), covers=("grid_sample",)),
    OpSpec(name="nn.functional.max_unpool2d",
           inputs={"x": _arr((1, 1, 2, 2)),
                   "indices": np.array([[[[0, 3], [8, 11]]]])},
           attrs={"kernel_size": 2},
           ref=lambda x, indices, kernel_size: _max_unpool_ref(x, indices, (4, 4)),
           grad=(), covers=("max_unpool2d",)),
    OpSpec(name="nn.functional.unfold",
           inputs={"x": _arr((2, 3, 4, 4))},
           attrs={"kernel_sizes": 2, "strides": 2},
           ref=lambda x, kernel_sizes, strides: _unfold_ref(x, 2, 2),
           grad=("x",), covers=("unfold",)),
    # ---- schema tensor tail -------------------------------------------------
    OpSpec(name="histogramdd", inputs={"x": _arr((20, 2))},
           attrs={"bins": 4},
           ref=lambda x, bins: (lambda h_e: [h_e[0]] + list(h_e[1]))(
               np.histogramdd(x, bins=bins)),
           grad=(), covers=("histogramdd",)),
    OpSpec(name="renorm", inputs={"x": _arr((3, 4))},
           attrs={"p": 2.0, "axis": 0, "max_norm": 1.0},
           ref=lambda x, p, axis, max_norm: x * np.minimum(
               1.0, max_norm / np.maximum(
                   np.sqrt((x * x).sum(1)), 1e-12))[:, None],
           grad=("x",), covers=("renorm",)),
    OpSpec(name="reverse", inputs={"x": _arr((3, 4))}, attrs={"axis": 1},
           ref=lambda x, axis: np.flip(x, axis), grad=("x",),
           covers=("reverse",)),
    OpSpec(name="increment", inputs={"x": _arr((1,))},
           ref=lambda x: x + 1.0, grad=("x",), covers=("increment",)),
    OpSpec(name="as_strided", inputs={"x": _arr((12,))},
           attrs={"shape": (3, 2), "stride": (4, 1), "offset": 1},
           ref=lambda x, shape, stride, offset: np.lib.stride_tricks.as_strided(
               x[offset:], shape, (x.strides[0] * stride[0],
                                   x.strides[0] * stride[1])).copy(),
           grad=("x",), covers=("as_strided",)),
    OpSpec(name="view_as", inputs={"x": _arr((2, 6)), "other": _arr((3, 4))},
           ref=lambda x, other: x.reshape(3, 4), grad=("x",),
           covers=("view_as",)),
    OpSpec(name="vander", inputs={"x": _arr((4,))}, attrs={"n": 3},
           ref=lambda x, n: np.vander(x, n), grad=("x",), covers=("vander",)),
    OpSpec(name="quantile", inputs={"x": _arr((3, 8))},
           attrs={"q": 0.25, "axis": 1},
           ref=lambda x, q, axis: np.quantile(x, q, axis=axis),
           grad=("x",), covers=("quantile",)),
    OpSpec(name="nanquantile", inputs={"x": _with_nan(_arr((3, 8)))},
           attrs={"q": 0.5, "axis": 1},
           ref=lambda x, q, axis: np.nanquantile(x, q, axis=axis),
           grad=(), covers=("nanquantile",)),
    OpSpec(name="index_fill",
           inputs={"x": _arr((3, 4)), "index": np.array([0, 2])},
           attrs={"axis": 0, "fill_value": 9.0},
           ref=lambda x, index, axis, fill_value: _index_fill_ref(x, index, axis, fill_value),
           grad=("x",), covers=("index_fill",)),
    OpSpec(name="fill_diagonal", inputs={"x": _arr((4, 4))},
           attrs={"value": 7.0},
           ref=lambda x, value: _fill_diag_ref(x, value),
           grad=(), covers=("fill_diagonal",)),
    # ---- special functions --------------------------------------------------
    U("gammaln", lambda x: _sp.gammaln(x), x=_pos(lo=0.5)),
    B("gammainc", lambda x, y: _sp.gammainc(x, y), x=_pos(lo=0.5),
      y=_pos((4,), lo=0.2), grad=()),
    B("gammaincc", lambda x, y: _sp.gammaincc(x, y), x=_pos(lo=0.5),
      y=_pos((4,), lo=0.2), grad=()),
    U("i0e", lambda x: _sp.i0e(x)),
    U("i1e", lambda x: _sp.i1e(x)),
    # ---- fft family (linear ops; value parity vs numpy) ---------------------
    OpSpec(name="fft.fft", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.fft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("fft",)),
    OpSpec(name="fft.ifft", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.ifft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("ifft",)),
    OpSpec(name="fft.rfft", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.rfft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("rfft",)),
    OpSpec(name="fft.irfft", inputs={"x": _arr((5,))},
           ref=lambda x: np.fft.irfft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("irfft",)),
    OpSpec(name="fft.hfft", inputs={"x": _arr((5,))},
           ref=lambda x: np.fft.hfft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("hfft",)),
    OpSpec(name="fft.ihfft", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.ihfft(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("ihfft",)),
    OpSpec(name="fft.fft2", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.fft.fft2(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("fft2",)),
    OpSpec(name="fft.ifft2", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.fft.ifft2(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("ifft2",)),
    OpSpec(name="fft.fftn", inputs={"x": _arr((2, 4, 4))},
           ref=lambda x: np.fft.fftn(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("fftn",)),
    OpSpec(name="fft.ifftn", inputs={"x": _arr((2, 4, 4))},
           ref=lambda x: np.fft.ifftn(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("ifftn",)),
    OpSpec(name="fft.rfft2", inputs={"x": _arr((4, 4))},
           ref=lambda x: np.fft.rfft2(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("rfft2",)),
    OpSpec(name="fft.irfft2", inputs={"x": _arr((4, 3))},
           ref=lambda x: np.fft.irfft2(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("irfft2",)),
    OpSpec(name="fft.rfftn", inputs={"x": _arr((2, 4, 4))},
           ref=lambda x: np.fft.rfftn(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("rfftn",)),
    OpSpec(name="fft.irfftn", inputs={"x": _arr((2, 4, 3))},
           ref=lambda x: np.fft.irfftn(x), rtol=1e-4, atol=1e-4, grad=(),
           covers=("irfftn",)),
    OpSpec(name="fft.fftshift", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.fftshift(x), grad=("x",), covers=("fftshift",)),
    OpSpec(name="fft.ifftshift", inputs={"x": _arr((8,))},
           ref=lambda x: np.fft.ifftshift(x), grad=("x",),
           covers=("ifftshift",)),
    OpSpec(name="fft.fftfreq", inputs={}, attrs={"n": 8, "d": 0.5},
           ref=lambda n, d: np.fft.fftfreq(n, d), grad=(),
           covers=("fftfreq",)),
    OpSpec(name="fft.rfftfreq", inputs={}, attrs={"n": 8, "d": 0.5},
           ref=lambda n, d: np.fft.rfftfreq(n, d), grad=(),
           covers=("rfftfreq",)),
    # ---- signal -------------------------------------------------------------
    OpSpec(name="signal.frame", inputs={"x": _arr((16,))},
           attrs={"frame_length": 4, "hop_length": 2},
           ref=lambda x, frame_length, hop_length: np.stack(
               [x[i * 2:i * 2 + 4] for i in range(7)], -1),
           grad=("x",), covers=("frame",)),
    OpSpec(name="signal.overlap_add",
           inputs={"x": _arr((4, 7))}, attrs={"hop_length": 2},
           ref=lambda x, hop_length: _overlap_add_ref(x, hop_length),
           grad=("x",), covers=("overlap_add",)),
    # ---- creation -----------------------------------------------------------
    OpSpec(name="arange", inputs={}, attrs={"start": 1.0, "end": 5.0, "step": 0.5},
           ref=lambda start, end, step: np.arange(start, end, step), grad=(),
           covers=("arange",)),
    OpSpec(name="linspace", inputs={}, attrs={"start": 0.0, "stop": 1.0, "num": 7},
           ref=lambda start, stop, num: np.linspace(start, stop, num), grad=(),
           covers=("linspace",)),
    OpSpec(name="logspace", inputs={}, attrs={"start": 0.0, "stop": 2.0, "num": 5},
           ref=lambda start, stop, num: np.logspace(start, stop, num), grad=(),
           rtol=1e-4, atol=1e-4, covers=("logspace",)),
    OpSpec(name="eye", inputs={}, attrs={"num_rows": 3, "num_columns": 4},
           ref=lambda num_rows, num_columns: np.eye(num_rows, num_columns),
           grad=(), covers=("eye",)),
    OpSpec(name="ones", inputs={}, attrs={"shape": (2, 3)},
           ref=lambda shape: np.ones(shape), grad=(), covers=("ones",)),
    OpSpec(name="zeros", inputs={}, attrs={"shape": (2, 3)},
           ref=lambda shape: np.zeros(shape), grad=(), covers=("zeros",)),
    OpSpec(name="full", inputs={}, attrs={"shape": (2, 3), "fill_value": 2.5},
           ref=lambda shape, fill_value: np.full(shape, fill_value), grad=(),
           covers=("full",)),
    OpSpec(name="ones_like", inputs={"x": _arr((2, 3))},
           ref=lambda x: np.ones_like(x), grad=(), covers=("ones_like",)),
    OpSpec(name="zeros_like", inputs={"x": _arr((2, 3))},
           ref=lambda x: np.zeros_like(x), grad=(), covers=("zeros_like",)),
    OpSpec(name="full_like", inputs={"x": _arr((2, 3))},
           attrs={"fill_value": 3.5},
           ref=lambda x, fill_value: np.full_like(x, fill_value), grad=(),
           covers=("full_like",)),
    OpSpec(name="empty", inputs={}, attrs={"shape": (2, 3)},
           ref=lambda shape: np.zeros(shape), grad=(), covers=("empty",)),
    OpSpec(name="empty_like", inputs={"x": _arr((2, 3))},
           ref=lambda x: np.zeros_like(x), grad=(), covers=("empty_like",)),
    OpSpec(name="tril_indices", inputs={}, attrs={"row": 4, "col": 4},
           ref=lambda row, col: np.stack(np.tril_indices(row, 0, col)),
           out_cast=False, grad=(), covers=("tril_indices",)),
    OpSpec(name="triu_indices", inputs={}, attrs={"row": 4, "col": 4},
           ref=lambda row, col: np.stack(np.triu_indices(row, 0, col)),
           out_cast=False, grad=(), covers=("triu_indices",)),
    OpSpec(name="complex", inputs={"real": _arr((3,)), "imag": _arr((3,))},
           ref=lambda real, imag: real + 1j * imag, grad=(),
           covers=("complex",)),
    OpSpec(name="polar", inputs={"abs": _pos((3,)), "angle": _arr((3,))},
           ref=lambda abs, angle: abs * np.cos(angle) + 1j * abs * np.sin(angle),
           rtol=1e-4, atol=1e-5, grad=(), covers=("polar",)),
    OpSpec(name="assign", inputs={"x": _arr((3,))}, ref=lambda x: x,
           grad=(), covers=("assign",)),
    OpSpec(name="numel", inputs={"x": _arr((3, 4))},
           ref=lambda x: np.array(12), out_cast=False, grad=(),
           covers=("numel",)),
    OpSpec(name="broadcast_tensors",
           inputs={"inputs": [_arr((1, 4)), _arr((3, 1))]},
           ref=lambda inputs: list(np.broadcast_arrays(*inputs)), grad=(),
           covers=("broadcast_tensors",)),
    # ---- indexing tail ------------------------------------------------------
    OpSpec(name="index_add",
           inputs={"x": _arr((4, 3)), "index": np.array([0, 2]),
                   "value": _arr((2, 3))},
           attrs={"axis": 0},
           ref=lambda x, index, value, axis: _index_add_ref(x, index, value),
           grad=("x",), covers=("index_add",)),
    OpSpec(name="index_put",
           inputs={"x": _arr((4, 3)),
                   "indices": (np.array([0, 2]), np.array([1, 2])),
                   "value": _arr((2,))},
           ref=lambda x, indices, value: _index_put_ref(x, indices, value),
           grad=("x",), covers=("index_put",)),
    OpSpec(name="put_along_axis",
           inputs={"x": _arr((3, 4)), "indices": np.array([[0], [1], [2]]),
                   "values": _arr((3, 1))},
           attrs={"axis": 1},
           ref=lambda x, indices, values, axis: _put_along_ref(x, indices, values, axis),
           grad=(), covers=("put_along_axis",)),
    OpSpec(name="scatter",
           inputs={"x": _arr((4, 3)), "index": np.array([1, 3]),
                   "updates": _arr((2, 3))},
           ref=lambda x, index, updates: _scatter_ref(x, index, updates),
           grad=("x",), covers=("scatter",)),
    OpSpec(name="scatter_nd",
           inputs={"index": np.array([[1], [3]]), "updates": _arr((2, 3))},
           attrs={"shape": (5, 3)},
           ref=lambda index, updates, shape: _scatter_nd_ref(index, updates, shape),
           grad=(), covers=("scatter_nd",)),
    OpSpec(name="shard_index", inputs={"input": np.array([[1], [6], [11]])},
           attrs={"index_num": 20, "nshards": 2, "shard_id": 0},
           ref=lambda input, index_num, nshards, shard_id: np.where(
               (input // (index_num // nshards)) == shard_id,
               input % (index_num // nshards), -1),
           out_cast=False, grad=(), covers=("shard_index",)),
]

SPECS.extend(_flatten_specs(_SCHEMA_SPECS))


_IDS = [f"{i}_{s.name.replace('.', '_')}" for i, s in enumerate(SPECS)]


@pytest.mark.parametrize("spec", SPECS, ids=_IDS)
def test_op(spec):
    run_spec(spec)


def test_tensor_unfold_direct():
    x = _arr((8,)).astype("float32")
    out = paddle.to_tensor(x).unfold(0, 4, 2).numpy()
    np.testing.assert_allclose(out, np.stack([x[0:4], x[2:6], x[4:8]]))


def test_meshgrid_direct():
    a = _arr((3,)).astype("float32")
    b = _arr((4,)).astype("float32")
    ga, gb = paddle.meshgrid(paddle.to_tensor(a), paddle.to_tensor(b))
    ra, rb = np.meshgrid(a, b, indexing="ij")
    np.testing.assert_allclose(ga.numpy(), ra)
    np.testing.assert_allclose(gb.numpy(), rb)


def test_einsum_and_atleast():
    """Positional-vararg signatures the OpSpec harness can't express."""
    a, b = _arr((3, 4)), _arr((4, 5))
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a.astype("float32")),
                        paddle.to_tensor(b.astype("float32")))
    np.testing.assert_allclose(out.numpy(), (a @ b).astype("float32"),
                               rtol=1e-5, atol=1e-6)
    v = _arr((4,)).astype("float32")
    np.testing.assert_allclose(
        paddle.atleast_2d(paddle.to_tensor(v)).numpy(), np.atleast_2d(v))
    assert paddle.atleast_1d(paddle.to_tensor(v)).shape == [4]
    assert paddle.atleast_3d(paddle.to_tensor(v)).numpy().ndim == 3


# ---- registry completeness ---------------------------------------------------

# Ops that cannot be checked by this harness, each with the reason —
# the role of the reference's test/white_list/ files.
WHITELIST = {
    # positional-vararg signature; dedicated test_einsum_and_atleast
    "einsum": "vararg signature; test_einsum_and_atleast",
    "unfold_window": "Tensor.unfold method surface; test_tensor_unfold_direct",
    "meshgrid": "vararg signature; test_meshgrid_direct",
    # SVD sign ambiguity / sampling randomness; dedicated tests below
    "svd_lowrank": "sign-ambiguous factors; test_lowrank_factorizations",
    "pca_lowrank": "sign-ambiguous factors; test_lowrank_factorizations",
    "top_p_sampling": "stochastic output; test_top_p_sampling_direct",
}


def test_lowrank_factorizations():
    """svd_lowrank/pca_lowrank: reconstruction + orthonormality (factor
    signs are implementation-defined, so compare subspaces not entries)."""
    import paddle_tpu.linalg as L

    a = paddle.to_tensor(R.uniform(-1, 1, (6, 4)).astype("float32"))
    u, s, v = L.svd_lowrank(a, q=4)
    recon = u.numpy() @ np.diag(s.numpy()) @ v.numpy().T
    np.testing.assert_allclose(recon, a.numpy(), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(u.numpy().T @ u.numpy(), np.eye(4),
                               atol=1e-4)
    u2, s2, v2 = L.pca_lowrank(a, q=3)
    centered = a.numpy() - a.numpy().mean(0, keepdims=True)
    ref_s = np.linalg.svd(centered, compute_uv=False)[:3]
    np.testing.assert_allclose(s2.numpy(), ref_s, rtol=1e-4, atol=1e-4)


def test_top_p_sampling_direct():
    """top_p_sampling: sampled ids always fall inside the nucleus set."""
    paddle.seed(0)
    logits = paddle.to_tensor(
        np.array([[4.0, 3.9, -10.0, -10.0], [5.0, -9.0, -9.0, -9.0]],
                 dtype="float32"))
    ps = paddle.to_tensor(np.array([0.9, 0.5], dtype="float32"))
    for _ in range(5):
        val, idx = paddle.top_p_sampling(logits, ps)
        assert idx.numpy()[0, 0] in (0, 1)
        assert idx.numpy()[1, 0] == 0
        assert val.shape == [2, 1]


def _tested_by_exists(ref: str) -> bool:
    """Verify a schema declaration's tested_by pointer ("tests/x.py::fn")
    names a real test function — a declaration cannot point at nothing."""
    import os

    path, _, fn = ref.partition("::")
    full = os.path.join(os.path.dirname(os.path.dirname(__file__)), path)
    if not (fn and os.path.exists(full)):
        return False
    with open(full) as f:
        return f"def {fn}(" in f.read()


def test_registry_swept():
    """Every registered op is covered by a spec (by name or `covers`),
    whitelisted with a reason, or schema-declared with a VERIFIED
    tested_by pointer (ops/schema.py Retrofit.tested_by)."""
    from paddle_tpu.ops.registry import OPS
    from paddle_tpu.ops.schema import validate_retrofits

    validate_retrofits()  # every declaration's public path must resolve

    covered = set()
    for s in SPECS:
        covered.add(s.name.split(".")[-1])
        covered.update(s.covers)
    missing, bad_refs = [], []
    for n in sorted(OPS):
        if n in covered or n in WHITELIST or n.rstrip("_") in covered:
            continue
        decl = OPS[n].decl
        ref = getattr(decl, "tested_by", "") if decl is not None else ""
        if ref:
            if _tested_by_exists(ref):
                continue
            bad_refs.append(f"{n} -> {ref}")
            continue
        missing.append(n)
    assert not bad_refs, (
        f"schema tested_by references point at nonexistent tests: {bad_refs}")
    assert not missing, (
        f"{len(missing)} registered ops lack an OpSpec, whitelist entry, or "
        f"schema tested_by: {missing}")


def test_infer_meta_abstract_shapes():
    """InferMeta parity: output shapes/dtypes without execution
    (jax.eval_shape over the registered impls — schema.infer_meta)."""
    from paddle_tpu.ops import schema

    out = schema.infer_meta("cdist", ((4, 3), "float32"),
                            ((5, 3), "float32"))
    assert out.shape == (4, 5) and str(out.dtype) == "float32"
    outs = schema.infer_meta("frexp", ((3, 4), "float32"))
    assert outs[0].shape == (3, 4) and "int" in str(outs[1].dtype)
    # static positional attrs stay concrete (impls branch on them)
    assert schema.infer_meta("renorm", ((2, 6), "float32"),
                             2.0, 0, 1.0).shape == (2, 6)
    # lazy retrofit ops resolve through the same path
    assert str(schema.infer_meta("gelu", ((8, 16), "bfloat16")).dtype) \
        == "bfloat16"
    with pytest.raises(KeyError):
        schema.infer_meta("not_an_op", ((1,), "float32"))


def test_ops_yaml_inventory_reconciled():
    """VERDICT r4 item 7: the completeness gate consumes the REFERENCE op
    inventory (paddle/phi/ops/yaml/ops.yaml, 472 entries) — every entry is
    implemented (registry/public surface), renamed with a VALIDATED target
    path, or excluded with a reason tied to the entry; and no bookkeeping
    entry refers to an op the yaml no longer declares."""
    import os
    from paddle_tpu.ops.yaml_reconciliation import (
        OPS_YAML, reconcile, yaml_ops)

    if not os.path.exists(OPS_YAML):
        import pytest
        pytest.skip("reference checkout not available")
    assert len(yaml_ops()) >= 470  # the pinned snapshot's inventory size
    problems = reconcile()
    assert problems["unaccounted"] == [], (
        f"{len(problems['unaccounted'])} reference ops have neither an "
        f"implementation nor a reasoned exclusion: {problems['unaccounted']}")
    assert problems["bad_renames"] == [], problems["bad_renames"]
    assert problems["stale_entries"] == [], problems["stale_entries"]


def test_ftrl_optimizer_converges():
    """Ftrl (ops.yaml `ftrl`): proximal update drives a convex problem
    down; l1 pressure zeroes small weights."""
    from paddle_tpu.optimizer import Ftrl

    paddle.seed(0)
    w = paddle.to_tensor(np.zeros((4,), np.float32), stop_gradient=False)
    x = paddle.to_tensor(np.array([[1., 0, 0, 0], [0, 1, 0, 0],
                                   [0, 0, 1, 0]], np.float32))
    target = paddle.to_tensor(np.array([2., -3., 0., 0.], np.float32))
    opt = Ftrl(learning_rate=0.5, l1=0.01, parameters=[w])
    first = None
    for _ in range(60):
        diff = (x @ (w - target).reshape((4, 1))).flatten()
        loss = (diff * diff).mean()
        if first is None:
            first = float(loss.numpy())
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss.numpy()) < first * 0.01
    # the never-observed coordinate (col 3) stays exactly 0 under l1
    assert w.numpy()[3] == 0.0

    # single-step hand check incl. the reference kernel's 2*l2 denominator
    # (ftrl_kernel_impl.h): g=1, n0=z0=w0=0, lr=.5, l2=1 ->
    # sigma=2, z=1, denom=2*1+1/.5=4, w=-1/4
    w2 = paddle.to_tensor(np.zeros((1,), np.float32), stop_gradient=False)
    opt2 = Ftrl(learning_rate=0.5, l2=1.0, parameters=[w2])
    (w2 * paddle.to_tensor(np.ones((1,), np.float32))).sum().backward()
    opt2.step()
    np.testing.assert_allclose(w2.numpy(), [-0.25], rtol=1e-6)


def test_distributed_reduce_and_gather():
    import paddle_tpu.distributed as dist

    import jax

    world = jax.device_count()  # default group = the whole test mesh
    t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    out = dist.reduce(t, dst=0)
    # a replicated value reduced over the world axis sums `world` copies
    # (all_reduce semantics; reduce's dst additionally observes it)
    np.testing.assert_allclose(out.numpy(), np.array([1.0, 2.0]) * world)
    lst = []
    dist.gather(paddle.to_tensor(np.array([1.0, 2.0], np.float32)), lst,
                dst=0)
    assert len(lst) >= 1


def test_nn_lazy_submodules():
    """paddle.nn.<submodule> attribute access must import lazily without
    recursion (nn.utils previously recursed in __getattr__)."""
    import paddle_tpu.nn as nn

    assert hasattr(nn.utils, "spectral_norm")
    assert hasattr(nn.quant, "WeightOnlyLinear")
    import pytest
    with pytest.raises(AttributeError):
        nn.definitely_not_a_module
