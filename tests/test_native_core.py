"""Native C++ core: TCPStore rendezvous + shm ring queue + DataLoader shm
transport.

Parity model: the reference's TCPStore gtests (test/cpp .../store) and
multi-process dataloader tests — real processes, real sockets/shm.
"""
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle


def test_native_builds():
    from paddle_tpu.core import load_native

    lib = load_native()
    assert lib is not None


# ---- TCPStore ----------------------------------------------------------------

def test_tcp_store_set_get_add():
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=10)
    port = master.port
    client = TCPStore("127.0.0.1", port, is_master=False, world_size=1,
                      timeout=10)
    master.set("alpha", b"hello")
    assert client.get("alpha") == b"hello"
    assert client.add("ctr", 5) == 5
    assert master.add("ctr", 2) == 7
    client.set("beta", "world")
    assert master.get("beta") == b"world"
    master.delete_key("alpha")
    with pytest.raises(TimeoutError):
        client.get("alpha", timeout=0.3)
    client.wait(["beta"], timeout=1.0)


def _store_rank(port, rank, world, results):
    from paddle_tpu.distributed.store import TCPStore

    store = TCPStore("127.0.0.1", port, is_master=False, world_size=world,
                     timeout=15)
    store.set(f"rank_{rank}", str(rank).encode())
    store.barrier("init")
    # after the barrier every rank's key must be visible
    vals = [int(store.get(f"rank_{r}", timeout=5)) for r in range(world)]
    results.put((rank, vals))


def test_tcp_store_multiprocess_barrier():
    from paddle_tpu.distributed.store import TCPStore

    world = 4
    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=world,
                      timeout=15)
    ctx = mp.get_context("fork")
    results = ctx.Queue()
    procs = [ctx.Process(target=_store_rank,
                         args=(master.port, r, world, results))
             for r in range(world)]
    for p in procs:
        p.start()
    seen = {}
    for _ in range(world):
        rank, vals = results.get(timeout=30)
        seen[rank] = vals
    for p in procs:
        p.join(timeout=10)
        assert p.exitcode == 0
    assert len(seen) == world
    for vals in seen.values():
        assert vals == list(range(world))


# ---- shm queue ---------------------------------------------------------------

def test_shm_channel_roundtrip():
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(capacity_mb=4)
    payload = {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
               "meta": ("label", 7), "l": [np.ones(2, np.int64)]}
    chan.put((0, payload))
    seq, got = chan.get(timeout=5)
    assert seq == 0
    np.testing.assert_array_equal(got["x"], payload["x"])
    assert got["meta"] == ("label", 7)
    np.testing.assert_array_equal(got["l"][0], payload["l"][0])
    chan.close()


def test_shm_channel_wraps_and_backpressure():
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(capacity_mb=1)
    big = np.zeros(200 * 1024, np.uint8)  # ~200KB per message
    for i in range(12):  # forces multiple ring wraps
        chan.put(np.full_like(big, i))
        got = chan.get(timeout=5)
        assert got[0] == i and got.shape == big.shape
    # overfull message errors cleanly
    with pytest.raises(RuntimeError):
        chan.put(np.zeros(2 * 1024 * 1024, np.uint8), timeout=0.2)
    chan.close()


def _shm_producer(name, n):
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(name, create=False)
    for i in range(n):
        chan.put((i, np.full((64, 64), i, np.float32)))
    chan.close()


def test_shm_channel_cross_process():
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(capacity_mb=8)
    ctx = mp.get_context("fork")
    n = 20
    p = ctx.Process(target=_shm_producer, args=(chan.name, n))
    p.start()
    got = set()
    for _ in range(n):
        i, arr = chan.get(timeout=20)
        assert arr[0, 0] == i
        got.add(i)
    p.join(timeout=10)
    assert p.exitcode == 0
    assert got == set(range(n))
    chan.close()


def test_shm_channel_timeout_is_named():
    """Timeouts raise ShmChannelTimeout (a TimeoutError subclass — the
    DataLoader's except clauses keep working) carrying the channel name
    and queue depth, distinguishing a dead producer from a stuck
    consumer."""
    from paddle_tpu.io.shm_channel import ShmChannel, ShmChannelTimeout

    chan = ShmChannel(capacity_mb=1)
    # empty ring: get() times out with qsize 0
    with pytest.raises(ShmChannelTimeout) as ei:
        chan.get(timeout=0.1)
    assert isinstance(ei.value, TimeoutError)
    assert ei.value.channel == chan.name
    assert ei.value.qsize == 0 and ei.value.op == "get"
    assert chan.name in str(ei.value)
    # full ring: put() times out with the depth at the moment of failure
    big = np.zeros(400 * 1024, np.uint8)
    with pytest.raises(ShmChannelTimeout) as ei:
        for _ in range(8):
            chan.put(big, timeout=0.1)
    assert ei.value.qsize >= 1 and ei.value.op == "put"
    assert ei.value.channel == chan.name
    chan.close()


def test_shm_channel_close_idempotent():
    """Double close (and close racing __del__ at teardown) is a no-op,
    not a double-free; post-close ops raise BrokenPipeError instead of
    segfaulting on a dead native handle."""
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(capacity_mb=1)
    chan.put(np.arange(4))
    chan.close()
    chan.close()      # second close: no-op
    chan.__del__()    # teardown path on a closed channel: no-op
    for op in (lambda: chan.put(1), lambda: chan.get(timeout=0.1),
               chan.qsize, chan.close_writers):
        with pytest.raises(BrokenPipeError):
            op()
    # a failed constructor leaves a partial object __del__ must survive
    with pytest.raises(RuntimeError):
        ShmChannel("/pdtpu_does_not_exist", create=False)


def test_tcp_store_timeout_not_hang():
    """Ops against a dead daemon must error within the timeout, not hang
    (round-1 VERDICT Weak #1: native layer ignored the Python timeout)."""
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore("127.0.0.1", 0, is_master=True, world_size=1, timeout=2)
    port = master.port
    client = TCPStore("127.0.0.1", port, is_master=False, world_size=1,
                      timeout=2)
    client.set("k", b"v")
    # kill the daemon; subsequent client ops must fail fast
    master._lib.pd_store_server_stop(master._server)
    master._server = None
    t0 = time.time()
    with pytest.raises((RuntimeError, TimeoutError)):
        client.set("k2", b"v2")
        client.get("k2", timeout=1.0)
    assert time.time() - t0 < 10.0


def _wrap_producer(name, sizes):
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(name, create=False)
    for i, sz in enumerate(sizes):
        arr = np.full(sz, i % 251, np.uint8)
        chan.put((i, arr), timeout=30.0)
    chan.close()


def test_shm_channel_variable_size_backpressure():
    """Regression for the round-1 ring-wrap overwrite (ADVICE high,
    shm_queue.cpp): variable-size messages pushed through a small near-full
    ring with a slow consumer must come out intact and in order."""
    from paddle_tpu.io.shm_channel import ShmChannel

    chan = ShmChannel(capacity_mb=1)
    rng = np.random.RandomState(7)
    # sizes tuned to leave awkward tail gaps (the overwrite precondition)
    sizes = [int(rng.randint(1, 300 * 1024)) for _ in range(60)]
    ctx = mp.get_context("fork")
    p = ctx.Process(target=_wrap_producer, args=(chan.name, sizes))
    p.start()
    for i, sz in enumerate(sizes):
        seq, arr = chan.get(timeout=30)
        assert seq == i
        assert arr.shape == (sz,)
        assert (arr == i % 251).all(), f"corrupt message {i}"
        if i % 5 == 0:
            time.sleep(0.01)  # backpressure: let the ring fill
    p.join(timeout=10)
    assert p.exitcode == 0
    chan.close()


# ---- DataLoader over shm -----------------------------------------------------

class _SquareDataset:
    def __getitem__(self, i):
        return np.full((8,), i, np.float32), np.int64(i * i)

    def __len__(self):
        return 16


def test_dataloader_shm_transport():
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    class DS(Dataset):
        def __getitem__(self, i):
            return np.full((8,), i, np.float32), np.int64(i * i)

        def __len__(self):
            return 16

    loader = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False,
                        use_shared_memory=True)
    batches = list(loader)
    assert len(batches) == 4
    for b_idx, (x, y) in enumerate(batches):
        expect = np.arange(b_idx * 4, b_idx * 4 + 4)
        np.testing.assert_array_equal(x.numpy()[:, 0], expect)
        np.testing.assert_array_equal(y.numpy(), expect ** 2)


RPC_WORKER = r'''
import os
import sys
sys.path.insert(0, os.environ["REPO_ROOT"])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu.distributed.rpc as rpc


def add_mul(a, b):
    return {"sum": a + b, "prod": (np.asarray(a) * b).tolist()}


def whoami():
    return rpc.get_current_worker_info().name


def _boom():
    raise ValueError("boom")


def main():
    rank = int(sys.argv[1])
    info = rpc.init_rpc(f"worker{rank}", rank, 2, sys.argv[2])
    assert info.rank == rank
    peer = f"worker{1 - rank}"
    out = rpc.rpc_sync(peer, add_mul, args=(3, 4))
    assert out["sum"] == 7 and out["prod"] == 12, out
    fut = rpc.rpc_async(peer, whoami)
    assert fut.wait() == peer
    assert [w.rank for w in rpc.get_all_worker_infos()] == [0, 1]
    try:
        rpc.rpc_sync(peer, _boom)
        raise SystemExit("remote exception not propagated")
    except ValueError as e:
        assert "boom" in str(e)
    rpc.shutdown()
    print(f"RANK{rank} OK")


if __name__ == "__main__":
    main()
'''


def test_rpc_two_process_roundtrip(tmp_path):
    """distributed.rpc: 2 real processes rendezvous through the native
    TCPStore, call functions on each other (sync + async), propagate
    remote exceptions, and shut down gracefully."""
    import socket
    import subprocess
    import sys

    script = tmp_path / "rpc_worker.py"
    script.write_text(RPC_WORKER)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, REPO_ROOT=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), f"127.0.0.1:{port}"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for r in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=120)
        outs.append(out)
        assert p.returncode == 0, out
    assert "RANK0 OK" in outs[0] and "RANK1 OK" in outs[1]
