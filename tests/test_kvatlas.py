"""KV & memory atlas (observability/kvatlas.py): the live page-pool
ledger, prefix-reuse telemetry, capacity forecasting, and cluster
memory federation (docs/SERVING.md "KV & memory atlas").

THE correctness gate pinned here is the exactness invariant: at every
engine step of a chunked / speculative / preempted / migrated run, the
atlas's incrementally-maintained totals equal ``kvatlas.recompute`` —
pool pages and bytes recomputed from engine config + live slot lengths
— while the runs themselves stay token-identical to solo decodes. Plus
the < 1% enabled-overhead gate, the disabled-by-default contract, the
``GET /kvstate`` / ``GET /kvstate/cluster`` surfaces, the TSDB
time-to-full forecast on a fake clock, and the incident-bundle
``kvstate`` section with its read_incident rendering.
"""
import http.client
import json
import os
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import catalog as cat
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.observability import kvatlas
from paddle_tpu.serving import ContinuousBatchEngine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=2))


def _solo(model, prompt, new):
    return model.generate(paddle.to_tensor(np.asarray(prompt)[None]),
                          max_new_tokens=new).numpy()[0]


def _assert_exact(eng):
    """The invariant: ledger totals == ground truth recomputed from
    engine config + slot lengths, byte for byte."""
    gt = kvatlas.recompute(eng)
    at = eng.kvatlas
    with at._lock:
        pages, chunk_pages = at._pages, at._chunk_pages
    assert pages == gt["pages"], (
        f"ledger {pages} pages != recomputed {gt['pages']}")
    assert pages * at.bytes_per_page == gt["bytes"]
    assert 0 <= chunk_pages <= pages


def _run_exact(eng, max_steps=600):
    """Step to completion, checking exactness after EVERY step."""
    done = {}
    for _ in range(max_steps):
        done.update(eng.step())
        _assert_exact(eng)
        if eng.num_active == 0 and not eng._queue \
                and not eng._chunking:
            break
    return done


# ---- exactness legs ---------------------------------------------------------

def test_exactness_chunked_prefill_with_prefix_reuse(tiny_model):
    """Chunked prefill + prefix-cache hit: the ledger tracks the chunk
    frontier's parked pages exactly at every step, adopts the reuse
    depth into the slot entry, and the run stays token-identical."""
    m = tiny_model
    rng = np.random.RandomState(11)
    base = rng.randint(0, m.config.vocab_size, (24,))
    p_a = np.concatenate([base, rng.randint(0, m.config.vocab_size, (9,))])
    p_b = np.concatenate([base, rng.randint(0, m.config.vocab_size, (17,))])
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8,
                                prefill_chunk_tokens=16,
                                enable_prefix_cache=True)
    at = eng.kvatlas.enable()
    r_a = eng.add_request(p_a, max_new_tokens=8)
    saw_chunk = False
    for _ in range(4):
        eng.step()
        _assert_exact(eng)
        saw_chunk = saw_chunk or at._chunk_pages > 0
    assert saw_chunk, "chunk frontier never parked pages in the ledger"
    r_b = eng.add_request(p_b, max_new_tokens=8)
    done = _run_exact(eng)
    np.testing.assert_array_equal(done[r_a], _solo(m, p_a, 8))
    np.testing.assert_array_equal(done[r_b], _solo(m, p_b, 8))
    # the reuse landed in the prefix index and the hit ratio moved
    assert at._prefix_hits >= 1
    pay = at.payload()
    assert pay["prefix"]["hit_ratio"] > 0
    assert pay["prefix"]["index"], "reused prefix never indexed"
    assert pay["prefix"]["index"][0]["pages"] >= 1
    # drained: every page and parked byte released
    assert pay["pages_in_use"] == 0 and pay["host_parked_bytes"] == 0
    assert pay["chunk_parked_pages"] == 0
    assert pay["pages_peak"] > 0


def test_exactness_speculative(tiny_model):
    """Speculative decode: the ledger frontier advances by DELIVERED
    tokens only (rejected-draft KV above it is garbage the next scatter
    overwrites), so recompute from ids+tokens matches every step."""
    m = tiny_model
    p = np.tile(np.asarray([3, 5, 7, 9]), 8)
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=128, page_size=8,
                                speculative_k=4)
    eng.kvatlas.enable()
    rid = eng.add_request(p, max_new_tokens=16)
    done = _run_exact(eng)
    np.testing.assert_array_equal(done[rid], _solo(m, p, 16))
    assert eng.stats()["spec_dispatches"] > 0


def test_exactness_preempt_restore(tiny_model):
    """Preempt→restore: eviction frees the slot's device pages and
    parks the bundle bytes host-side; restore consumes the parked bytes
    and republishes the slot — exact at every step, token-identical."""
    m = tiny_model
    rng = np.random.RandomState(4)
    long_p = rng.randint(0, m.config.vocab_size, (41,))
    short_p = rng.randint(0, m.config.vocab_size, (5,))
    eng = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8,
                                enable_preemption=True)
    at = eng.kvatlas.enable()
    n0 = cat.SERVING_BUNDLE_BYTES.count(engine="decoder", kind="preempt")
    victim = eng.add_request(short_p, max_new_tokens=12, priority=2)
    for _ in range(3):
        eng.step()
        _assert_exact(eng)
    hi = eng.add_request(long_p, max_new_tokens=6, priority=0)
    saw_parked = False
    done = {}
    for _ in range(600):
        done.update(eng.step())
        _assert_exact(eng)
        saw_parked = saw_parked or at._parked_bytes > 0
        if eng.num_active == 0 and not eng._queue:
            break
    np.testing.assert_array_equal(done[hi], _solo(m, long_p, 6))
    np.testing.assert_array_equal(done[victim], _solo(m, short_p, 12))
    assert saw_parked, "preempted bundle never parked host bytes"
    assert at._parked_bytes == 0 and not at._parked     # restore unparked
    assert cat.SERVING_BUNDLE_BYTES.count(engine="decoder",
                                          kind="preempt") == n0 + 1


def test_exactness_migration(tiny_model):
    """export_slot frees the source ledger; admit_migrated parks the
    bundle host-side on the destination until the restore scatters it
    back — both ledgers exact throughout, stream token-identical."""
    m = tiny_model
    p = np.random.RandomState(11).randint(1, m.config.vocab_size, (9,))
    n_tok = 10
    solo = _solo(m, p, n_tok)
    n0 = cat.SERVING_BUNDLE_BYTES.count(engine="decoder", kind="migrate")
    src = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    src.kvatlas.enable()
    rid = src.add_request(p, max_new_tokens=n_tok)
    for _ in range(4):
        src.step()
        _assert_exact(src)
    bundle = src.export_slot(rid)
    _assert_exact(src)
    with src.kvatlas._lock:
        assert src.kvatlas._pages == 0      # migrated out: pool empty
    assert cat.SERVING_BUNDLE_BYTES.count(engine="decoder",
                                          kind="migrate") == n0 + 1
    # a single-slot destination with the slot held: the bundle PARKS
    # host-side until the holder retires and the restore scatters it
    dst = ContinuousBatchEngine(m, max_batch=1, max_len=64, page_size=8)
    at = dst.kvatlas.enable()
    holder = dst.add_request(np.arange(1, 6), max_new_tokens=3)
    dst.step()
    _assert_exact(dst)
    rid2 = dst.admit_migrated(bundle)
    assert at._parked_bytes > 0             # parked until the restore
    _assert_exact(dst)
    done = _run_exact(dst)
    assert holder in done
    np.testing.assert_array_equal(done[rid2], solo)
    assert at._parked_bytes == 0 and not at._parked


def test_latent_engine_has_no_paged_pool():
    """MLA engines carry no paged KV pool: the atlas reports paged=False
    and zero pages while headroom/occupancy still track."""
    from paddle_tpu.models.deepseek import (DeepseekV2Config,
                                            DeepseekV2ForCausalLM)

    paddle.seed(3)
    m = DeepseekV2ForCausalLM(DeepseekV2Config.tiny_mla(num_hidden_layers=2))
    eng = ContinuousBatchEngine(m, max_batch=2, max_len=64, page_size=8)
    assert eng._latent_mode
    at = eng.kvatlas.enable()
    assert at.paged is False
    rid = eng.add_request(np.arange(1, 8), max_new_tokens=4)
    eng.step()
    fed = at.federated()
    assert fed["kv_pages_in_use"] == 0.0
    assert fed["kv_headroom_slots"] == 1.0          # one of two slots
    done = eng.run_until_done()
    assert rid in done
    assert at.federated()["kv_headroom_slots"] == 2.0
    # per-token coefficient uses the latent layout (c_kv + k_pe rows)
    cfg = m.config
    item = kvatlas._dtype_bytes(getattr(cfg, "dtype", "bfloat16"))
    expect = cfg.num_hidden_layers * (
        cfg.kv_lora_rank + cfg.qk_rope_head_dim) * item
    assert at.bytes_per_token == expect > 0


def test_kv_bytes_per_token_paged_layout(tiny_model):
    from paddle_tpu.models.llama import head_dim_of

    cfg = tiny_model.config
    item = kvatlas._dtype_bytes(getattr(cfg, "dtype", "bfloat16"))
    hk = cfg.num_key_value_heads or cfg.num_attention_heads
    expect = 2 * cfg.num_hidden_layers * hk * head_dim_of(cfg) * item
    assert kvatlas.kv_bytes_per_token(cfg) == expect > 0
    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    at = eng.kvatlas
    assert at.bytes_per_token == expect
    assert at.bytes_per_page == expect * 8
    # capacity arithmetic follows the engine geometry
    assert at.pages_per_slot == 64 // 8
    pay = at.payload()
    assert pay["capacity_pages"] == 2 * (64 // 8)
    assert pay["capacity_bytes"] == pay["capacity_pages"] * at.bytes_per_page


# ---- disabled-by-default & the overhead gate --------------------------------

def test_atlas_disabled_by_default_mutates_nothing(tiny_model):
    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    at = eng.kvatlas
    assert at.enabled is False
    rid = eng.add_request(np.arange(1, 8), max_new_tokens=4)
    assert rid in eng.run_until_done()
    assert at._mutations == 0 and not at._slots
    # slot_info stays truthful through the computed fallback
    info = at.slot_info(0, kv_tokens=17)
    assert info["kv_pages"] == 3            # ceil(17 / 8)
    assert info["kv_bytes"] == 3 * at.bytes_per_page
    # stats() still carries the federated keys (zeros + full headroom),
    # so the router's collector never KeyErrors on an atlas-off worker
    st = eng.stats()
    assert st["kv_pages_in_use"] == 0.0
    assert st["kv_headroom_slots"] == 2.0
    assert st["prefix_hit_ratio"] == 0.0


def test_atlas_overhead_under_one_percent(tiny_model):
    """The enabled per-step instrumentation (one advance per active
    slot, gauge batch included) must cost < 1% of a real decode step."""
    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    eng.profiler.enable()
    rng = np.random.RandomState(0)
    for i in range(4):
        eng.add_request(rng.randint(1, tiny_model.config.vocab_size,
                                    (5 + i,)), 12)
    eng.run_until_done()
    step_p50_ms = eng.profiler.payload()["step_ms"]["p50"]
    assert step_p50_ms > 0

    at = kvatlas.KvAtlas("overhead_gate", max_batch=2, page_size=8,
                         pages_per_slot=8, bytes_per_token=1024,
                         paged=True)
    at.enable()
    at.set_slot(0, 5)
    at.set_slot(1, 7)
    for _ in range(200):                    # warm the gauge-batch path
        at.advance(0)
        at.advance(1)
    # min over rounds: a single scheduler preemption inflates a mean
    # but not the best round, so the gate holds under full-suite load
    rounds, per = 10, 200
    over_ms = float("inf")
    for _ in range(rounds):
        at.set_slot(0, 5)
        at.set_slot(1, 7)
        t0 = time.perf_counter()
        for _ in range(per):
            at.advance(0)                   # the two-active-slot step
            at.advance(1)
        over_ms = min(over_ms, (time.perf_counter() - t0) * 1e3 / per)
    assert over_ms < 0.01 * step_p50_ms, (
        f"atlas overhead {over_ms * 1e3:.2f}us is "
        f">= 1% of a {step_p50_ms:.3f}ms decode step")


# ---- prefix-reuse index -----------------------------------------------------

def test_prefix_key_is_page_aligned():
    at = kvatlas.KvAtlas("prefix_unit", max_batch=2, page_size=4,
                         pages_per_slot=4, bytes_per_token=10, paged=True)
    a = np.asarray([1, 2, 3, 4, 5, 6, 7, 8, 9])
    b = np.asarray([1, 2, 3, 4, 5, 6, 7, 8, 42])       # differs past 2 pages
    c = np.asarray([1, 2, 3, 4, 9, 9, 9, 9])           # differs inside
    assert at.prefix_key(a, 2) == at.prefix_key(b, 2)
    assert at.prefix_key(a, 2) != at.prefix_key(c, 2)
    assert at.prefix_key(a, 1) != at.prefix_key(a, 2)  # depth matters


def test_prefix_index_is_lru_bounded():
    at = kvatlas.KvAtlas("prefix_lru", max_batch=2, page_size=2,
                         pages_per_slot=4, bytes_per_token=10, paged=True)
    at.enable()
    rng = np.random.RandomState(0)
    n = kvatlas.PREFIX_INDEX_CAP + 40
    for i in range(n):
        at.note_prefix_hit(0, rng.randint(0, 1000, (4,)), 2)
    assert len(at._index) == kvatlas.PREFIX_INDEX_CAP
    assert at._prefix_evicted >= 40
    assert at._prefix_hits == n
    summary = at.prefix_summary(top=5)
    assert len(summary) == 5
    assert all(set(e) == {"hash", "pages", "hits"} for e in summary)
    # a repeat hit refreshes the entry and bumps its count to the top
    ids = np.asarray([7, 7, 7, 7])
    for _ in range(3):
        at.note_prefix_hit(1, ids, 2)
    assert at.prefix_summary(top=1)[0]["hash"] == at.prefix_key(ids, 2)
    assert at.prefix_summary(top=1)[0]["hits"] == 3
    cs = at.cluster_summary(top=3)
    assert cs["prefix_hit_ratio"] == 1.0 and len(cs["prefixes"]) == 3


# ---- capacity forecast ------------------------------------------------------

def test_forecast_time_to_full_on_fake_clock():
    """Admissions outpacing finishes by 1 slot/s with 6 free slots →
    eta_s ≈ 6 s; a draining pool (net ≤ 0) forecasts no fill time."""
    from paddle_tpu.observability import timeseries as tsm

    clk = {"t": 1000.0}
    store = tsm.TimeSeriesStore(interval_s=1.0,
                                clock=lambda: clk["t"]).enable()
    at = kvatlas.KvAtlas("fc_engine", max_batch=6, page_size=8,
                         pages_per_slot=8, bytes_per_token=64, paged=True)
    at.enable()
    cat.SERVING_REQUESTS.labels(engine="fc_engine", event="admitted")
    cat.SERVING_REQUESTS.labels(engine="fc_engine", event="finished")
    store.sample_once()
    for _ in range(12):
        clk["t"] += 1.0
        cat.SERVING_REQUESTS.inc(2.0, engine="fc_engine", event="admitted")
        cat.SERVING_REQUESTS.inc(1.0, engine="fc_engine", event="finished")
        store.sample_once()
    fc = at.forecast(store=store, now=clk["t"], window_s=10.0)
    assert fc["headroom_slots"] == 6
    assert fc["admit_rate"] == pytest.approx(2.0, rel=0.15)
    assert fc["finish_rate"] == pytest.approx(1.0, rel=0.15)
    assert fc["net_slots_per_s"] == pytest.approx(1.0, rel=0.3)
    assert fc["eta_s"] == pytest.approx(6.0, rel=0.3)
    # draining: finishes now outpace admissions → no fill forecast
    for _ in range(12):
        clk["t"] += 1.0
        cat.SERVING_REQUESTS.inc(2.0, engine="fc_engine", event="finished")
        store.sample_once()
    fc = at.forecast(store=store, now=clk["t"], window_s=10.0)
    assert fc["net_slots_per_s"] is not None
    assert fc["net_slots_per_s"] < 0 and fc["eta_s"] is None


# ---- alert objective --------------------------------------------------------

def test_kv_pressure_objective_registered():
    from paddle_tpu.observability import alerts as al

    obj = al.DEFAULT_OBJECTIVES["kv_pressure_high"]
    assert obj.metric == "serving_kv_headroom_frac"
    assert obj.op == "<" and obj.threshold == pytest.approx(0.10)
    assert obj.window_s == 60.0 and obj.for_s == 60.0
    assert obj.labels == {"engine": "decoder"}
    # the federation list carries the cluster kv series
    assert {"cluster_kv_pages_in_use", "cluster_kv_bytes",
            "cluster_kv_headroom_slots",
            "cluster_prefix_hit_ratio"} <= set(al.FEDERATED_SERIES)


# ---- debug_state columns ----------------------------------------------------

def test_debug_state_carries_kv_columns(tiny_model):
    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8, enable_prefix_cache=True)
    eng.kvatlas.enable()
    rng = np.random.RandomState(2)
    base = rng.randint(0, tiny_model.config.vocab_size, (16,))
    eng.add_request(np.concatenate([base, [5, 6, 7]]), max_new_tokens=16)
    eng.step()
    eng.add_request(np.concatenate([base, [9, 8]]), max_new_tokens=4)
    eng.step()
    rows = [r for r in eng.debug_state()["slots"] if r is not None]
    assert rows
    for row in rows:
        assert row["kv_pages"] > 0
        assert row["kv_bytes"] == row["kv_pages"] * eng.kvatlas.bytes_per_page
        assert "prefix_pages" in row
    assert any(r["prefix_pages"] > 0 for r in rows), \
        "prefix reuse never surfaced in debug_state"
    eng.run_until_done()


# ---- HTTP surfaces ----------------------------------------------------------

@pytest.fixture(scope="module")
def served(tiny_model):
    from paddle_tpu.serving_http import CompletionServer

    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    with CompletionServer(eng, model_name="tiny-kvatlas") as srv:
        yield srv


def _post(srv, path, body):
    host, port = srv.address
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out


def _get(srv, path):
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


def test_kvstate_endpoint(served):
    code, _ = _post(served, "/v1/completions",
                    {"prompt_token_ids": [3, 5, 7], "max_tokens": 6})
    assert code == 200
    doc = _get(served, "/kvstate")
    assert doc["schema_version"] == 1
    eng = doc["engines"]["decoder"]
    assert eng["enabled"] is True            # the server enabled it
    assert eng["paged"] is True
    assert eng["page_size"] == 8 and eng["pages_per_slot"] == 8
    assert eng["bytes_per_page"] > 0
    assert eng["capacity_pages"] == 16
    assert eng["pages_peak"] >= 1            # traffic left a peak behind
    assert eng["pages_in_use"] == 0          # drained
    assert eng["headroom_slots"] == 2
    assert eng["chunk_parked_pages"] == 0
    assert eng["host_parked_bytes"] == 0
    assert set(eng["prefix"]) >= {"hits", "misses", "hit_ratio", "index"}
    assert "kv_cache_bytes" in eng["preflight"]
    assert set(eng["forecast"]) >= {"eta_s", "headroom_slots"}
    # stats()/health carries the federated scalars
    st = _get(served, "/health")["stats"]
    for key in ("kv_pages_in_use", "kv_bytes", "kv_headroom_slots",
                "kv_headroom_frac", "prefix_hit_ratio"):
        assert key in st
    assert st["kv_headroom_slots"] == 2.0
    # the occupancy gauges published
    assert cat.SERVING_KV_HEADROOM_SLOTS.value(engine="decoder") == 2.0


def test_bundle_carries_kvstate_section(served):
    b = frec.get_reporter().bundle("manual", context="kvatlas-unit")
    frec.validate_bundle(b)
    assert b["kvstate"]["schema_version"] == 1
    assert "decoder" in b["kvstate"]["engines"]
    # additive-optional: a bundle written before this PR still validates
    legacy = {k: v for k, v in b.items() if k != "kvstate"}
    frec.validate_bundle(legacy)


def test_read_incident_prints_kv_memory_section(tiny_model, tmp_path,
                                                capsys):
    """scripts/read_incident.py renders the kvstate section — pool
    line, per-slot rows, host-parked residency."""
    import importlib.util

    eng = ContinuousBatchEngine(tiny_model, max_batch=2, max_len=64,
                                page_size=8)
    eng.kvatlas.enable()
    rep = frec.IncidentReporter(str(tmp_path))
    rep.register_engine("decoder", eng)
    eng.add_request(np.arange(1, 8), max_new_tokens=12)
    for _ in range(3):
        eng.step()                       # slots active at dump time
    path = rep.activate().dump("manual", context="kvatlas-test")
    eng.run_until_done()
    spec = importlib.util.spec_from_file_location(
        "_read_incident_kv",
        os.path.join(_REPO, "scripts", "read_incident.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([path]) == 0
    out = capsys.readouterr().out
    assert "KV/MEMORY" in out
    assert "slot 0" in out and "headroom" in out


# ---- cluster federation -----------------------------------------------------

def test_cluster_kvstate_federation(tmp_path, monkeypatch):
    """Router-side ``GET /kvstate/cluster`` federates ≥ 2 workers keyed
    by replica id with their pool-metadata prefix summaries, and the
    federated TSDB carries the per-replica kv gauges under their
    declared series names — live, never a 5xx."""
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    from paddle_tpu.observability import alerts as al
    from paddle_tpu.observability import timeseries as tsm
    from paddle_tpu.serving_cluster import launch_cluster

    cluster = launch_cluster({
        "cluster": {"host": "127.0.0.1", "port": 0, "ttl": 2.0,
                    "platform": "cpu", "model_name": "tiny-kv-cluster",
                    "ts_interval_s": 0.25},
        "model": {"kind": "tiny_llama", "num_hidden_layers": 2,
                  "seed": 0},
        "engine": {"max_batch": 4, "max_len": 64, "page_size": 8},
        "workers": [{"role": "unified", "count": 2}],
    }, supervise=False)
    try:
        host, port = cluster.address
        url = f"http://{host}:{port}"
        for i in range(4):                   # traffic lands on both
            code, body = _post_url(host, port, "/v1/completions",
                                   {"prompt_token_ids": [2 + i, 5, 9],
                                    "max_tokens": 4})
            assert code == 200
            assert body["usage"]["completion_tokens"] == 4
        with urllib.request.urlopen(url + "/kvstate/cluster",
                                    timeout=30) as r:
            doc = json.loads(r.read())
        assert doc["schema_version"] == 1
        assert set(doc["replicas"]) == {"0", "1"}, doc.get("errors")
        for rid, sub in doc["replicas"].items():
            dec = sub["engines"]["decoder"]
            assert dec["enabled"] is True, rid
            assert dec["pages_peak"] >= 0
            assert dec["capacity_pages"] == 4 * (64 // 8)
        # workers published their kv summary through pool metadata
        cluster.pool.refresh()
        with urllib.request.urlopen(url + "/kvstate/cluster",
                                    timeout=30) as r:
            doc = json.loads(r.read())
        assert set(doc["pool"]) == {"0", "1"}
        for summ in doc["pool"].values():
            assert {"kv_pages_in_use", "headroom_slots",
                    "prefix_hit_ratio", "prefixes"} <= set(summ)
        # the per-replica kv gauges reach the federated store under
        # their FEDERATED_SERIES names
        tsm.get_store().sample_once()
        with urllib.request.urlopen(url + "/timeseries",
                                    timeout=30) as r:
            ts = json.loads(r.read())
        kv_series = {s["name"] for s in ts["series"]
                     if s["name"].startswith(("cluster_kv_",
                                              "cluster_prefix_"))}
        assert kv_series == {"cluster_kv_pages_in_use",
                             "cluster_kv_bytes",
                             "cluster_kv_headroom_slots",
                             "cluster_prefix_hit_ratio"}
        assert kv_series <= set(al.FEDERATED_SERIES)
        reps = {s["labels"].get("replica") for s in ts["series"]
                if s["name"] == "cluster_kv_headroom_slots"}
        assert {"0", "1"} <= reps
        # the router's own (engineless) /kvstate answers 200, empty
        with urllib.request.urlopen(url + "/kvstate", timeout=30) as r:
            local = json.loads(r.read())
        assert local["schema_version"] == 1
    finally:
        cluster.close()


def _post_url(host, port, path, body):
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out
