"""pdlint: the framework-native static analyzer (paddle_tpu/analysis).

Three layers, mirroring how the metric/span catalog lints are wired:

1. **Fixture tests per rule** — known-bad snippets that FAIL without the
   rule and known-good twins that stay clean (the acceptance criterion:
   every rule id is pinned by at least one bad fixture).
2. **Framework tests** — pragma suppression, baseline round-trip, JSON
   reporter schema stability.
3. **The tier-1 gate** — ``scripts/pdlint.py --json --baseline
   .pdlint_baseline.json`` over the whole package must exit 0 (zero
   non-baselined findings), invoked through the script exactly like
   check_metrics_catalog.py / check_span_catalog.py are.

Plus regression tests for the sites this PR fixed (the chrome-export
silent swallow now logs through the rank-aware logger).
"""
import importlib.util
import json
import os
import sys

import pytest

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, _REPO)

from paddle_tpu import analysis
from paddle_tpu.analysis import baseline as bl
from paddle_tpu.analysis import report
from paddle_tpu.analysis.core import Finding


def lint(src, filename="m.py", rule=None):
    found = analysis.analyze_source(src, filename)
    return [f for f in found if rule is None or f.rule == rule]


# ---------------------------------------------------------------------------
# trace-purity
# ---------------------------------------------------------------------------

def test_trace_purity_flags_impure_jit_fn():
    bad = (
        "import time\n"
        "import numpy as np\n"
        "import jax\n"
        "def f(x):\n"
        "    t = time.time()\n"
        "    print('tracing', t)\n"
        "    return x * np.random.rand()\n"
        "g = jax.jit(f, donate_argnums=(0,))\n"
    )
    rules = {f.message.split("impure call ")[1].split("(")[0]
             for f in lint(bad, rule="trace-purity")}
    assert rules == {"time.time", "print", "numpy.random.rand"}


def test_trace_purity_decorator_and_global_mutation():
    bad = (
        "import jax\n"
        "_N = 0\n"
        "@jax.jit\n"
        "def g(x):\n"
        "    global _N\n"
        "    _N += 1\n"
        "    return x\n"
    )
    msgs = [f.message for f in lint(bad, rule="trace-purity")]
    assert any("mutates nonlocal/global '_N'" in m for m in msgs)


def test_trace_purity_pallas_kernel_via_partial():
    bad = (
        "import functools\n"
        "import jax.experimental.pallas as pl\n"
        "def kern(x_ref, o_ref):\n"
        "    print('side effect')\n"
        "    o_ref[...] = x_ref[...]\n"
        "k = functools.partial(kern)\n"
        "out = pl.pallas_call(k, out_shape=None)\n"
    )
    assert lint(bad, rule="trace-purity")


def test_trace_purity_clean_traced_fn_and_untraced_impurity():
    good = (
        "import time\n"
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def pure(x):\n"
        "    return jnp.tanh(x) * 2\n"
        "j = jax.jit(pure)\n"
        "def host_loop():\n"
        "    return time.time()\n"   # impure but NOT traced: legal
        "from paddle_tpu.framework import random as _random\n"
        "def pure2(x, key):\n"
        "    return x\n"
        "j2 = jax.jit(pure2)\n"
    )
    assert lint(good, rule="trace-purity") == []


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

def test_host_sync_flags_item_and_tainted_conversions():
    bad = (
        "import numpy as np\n"
        "class E:\n"
        "    def step(self):\n"
        "        nxt, aux = self._fn(self._state)\n"
        "        toks = np.asarray(nxt)\n"
        "        y = float(aux)\n"
        "        z = aux.item()\n"
        "        return toks, y, z\n"
    )
    found = lint(bad, filename="serving.py", rule="host-sync")
    assert len(found) == 3
    assert {f.line for f in found} == {5, 6, 7}


def test_host_sync_taint_clears_after_fetch_and_ignores_host_data():
    good = (
        "import numpy as np\n"
        "class E:\n"
        "    def step(self):\n"
        "        nxt = self._fn()\n"
        "        toks = np.asarray(nxt)  # pdlint: disable=host-sync\n"
        "        n = int(toks[0])\n"            # host already: legal
        "        flags = np.array([s is None for s in self._slots])\n"
        "        m = int(len(self._slots))\n"
        "        return n, flags, m\n"
    )
    assert lint(good, filename="serving.py", rule="host-sync") == []


def test_host_sync_only_hot_modules_and_functions():
    src = (
        "import numpy as np\n"
        "def step(self):\n"
        "    v = self._fn()\n"
        "    return v.item()\n"
    )
    # same code: hot in serving.py, ignored in an arbitrary module,
    # ignored in a non-hot function name
    assert lint(src, filename="serving.py", rule="host-sync")
    assert lint(src, filename="models/llama.py", rule="host-sync") == []
    cold = src.replace("def step", "def bookkeeping")
    assert lint(cold, filename="serving.py", rule="host-sync") == []


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------

_LOCKED = (
    "import threading\n"
    "class Reg:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self.count = 0\n"
    "    def add(self):\n"
    "        with self._lock:\n"
    "            self.count += 1\n"
)


def test_lock_discipline_flags_mixed_write():
    bad = _LOCKED + (
        "    def sneaky(self):\n"
        "        self.count -= 1\n"
    )
    found = lint(bad, rule="lock-discipline")
    assert len(found) == 1
    assert "self.count" in found[0].message
    assert "sneaky" in found[0].message


def test_lock_discipline_subscript_store_counts_as_write():
    bad = (
        "import threading\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "        self._children = {}\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._children[k] = v\n"
        "    def wipe(self, k):\n"
        "        self._children[k] = None\n"
    )
    found = lint(bad, rule="lock-discipline")
    assert len(found) == 1 and "_children" in found[0].message


def test_lock_discipline_clean_patterns():
    # all-locked writes, __init__ writes, single-writer lock-free flags,
    # and lock-less classes are all legal
    good = _LOCKED + (
        "    def also_locked(self):\n"
        "        with self._lock:\n"
        "            self.count = 0\n"
        "class Flag:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.enabled = False\n"
        "    def enable(self):\n"
        "        self.enabled = True\n"   # never written under lock: ok
        "class NoLock:\n"
        "    def set(self, v):\n"
        "        self.v = v\n"
    )
    assert lint(good, rule="lock-discipline") == []


def test_lock_discipline_observability_is_clean():
    """Satellite sweep: the lock-owning observability/serving-front-end
    classes carry no mixed-discipline writes (rule verified against the
    live files, so a future off-lock write becomes a tier-1 failure)."""
    for rel in ("paddle_tpu/observability/metrics.py",
                "paddle_tpu/observability/tracing.py",
                "paddle_tpu/serving_http.py"):
        found = analysis.analyze_file(os.path.join(_REPO, rel), _REPO)
        assert [f for f in found if f.rule == "lock-discipline"] == [], rel


# ---------------------------------------------------------------------------
# silent-exception
# ---------------------------------------------------------------------------

def test_silent_exception_flags_broad_pass():
    bad = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    assert len(lint(bad, rule="silent-exception")) == 1


def test_silent_exception_bare_and_tuple_forms():
    bad = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except (ValueError, Exception):\n"
        "        x = 1\n"
    )
    assert len(lint(bad, rule="silent-exception")) == 2


def test_silent_exception_clean_forms():
    good = (
        "import logging\n"
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except ValueError:\n"       # narrow: legal
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"        # logged: legal
        "        logging.warning('boom')\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"        # re-raised: legal
        "        raise RuntimeError('ctx')\n"
    )
    assert lint(good, rule="silent-exception") == []


# ---------------------------------------------------------------------------
# op-schema (validation core on fixture records)
# ---------------------------------------------------------------------------

class _Decl:
    def __init__(self, name, category="math", dtypes=("float32",),
                 differentiable=True, vjp="jax.vjp of impl", n_outputs=1):
        self.name, self.category, self.dtypes = name, category, dtypes
        self.differentiable, self.vjp = differentiable, vjp
        self.n_outputs = n_outputs


class _Retro:
    def __init__(self, name, category="nn", tested_by=""):
        self.name, self.category, self.tested_by = name, category, tested_by


def test_op_schema_core_flags_bad_records():
    from paddle_tpu.analysis.rules.op_schema import check_records

    decls = [
        _Decl("dup"), _Decl("dup"),                      # duplicate
        _Decl("badcat", category="kernels"),             # unknown category
        _Decl("baddt", dtypes=("float99",)),             # unknown dtype
        _Decl("nograd", vjp=""),                         # diff, no strategy
        _Decl("noout", n_outputs=0),                     # outputs < 1
        _Decl("unswept"),                                # not enumerated
    ]
    retros = [
        _Retro("dup"),                                   # shadows a decl
        _Retro("untested"),                              # no sweep, no ref
        _Retro("badref", tested_by="tests/nope.py::test_x"),
    ]
    enumerated = {"dup", "badcat", "baddt", "nograd", "noout"}
    problems = check_records(decls, retros, enumerated, lambda ref: False)
    joined = "\n".join(m for _, m in problems)
    for frag in ("duplicate OpDecl", "unknown category", "unknown dtypes",
                 "no grad strategy", "n_outputs", "not enumerated",
                 "shadows another declaration", "does not point at"):
        assert frag in joined, frag


def test_op_schema_sweep_enumeration_parses_real_suite():
    from paddle_tpu.analysis.rules.op_schema import (
        make_tested_by_checker, sweep_enumeration)

    names = sweep_enumeration(os.path.join(_REPO, "tests",
                                           "test_op_suite.py"))
    # spec names, covers entries, and whitelist keys all collected
    assert "matmul" in names
    assert "gelu" in names        # a covers= entry
    assert "einsum" in names      # a WHITELIST key
    ok = make_tested_by_checker(_REPO)
    assert ok("tests/test_nn.py::test_pools")
    assert not ok("tests/test_nn.py::test_no_such_test")
    assert not ok("garbage")


def test_op_schema_project_rule_clean():
    (rule,) = analysis.project_rules(["op-schema"])
    assert list(rule.check_project(_REPO)) == []


# ---------------------------------------------------------------------------
# catalog rules (re-homed metric/span lints)
# ---------------------------------------------------------------------------

def test_catalog_comparison_cores_flag_drift():
    from paddle_tpu.analysis.rules.catalogs import (
        compare_metric_catalogs, compare_span_catalogs)

    docs = {"a_total": ("counter", frozenset({"x"})),
            "gone": ("gauge", frozenset())}
    reg = {"a_total": ("counter", frozenset({"x", "y"})),
           "fresh": ("gauge", frozenset())}
    msgs = compare_metric_catalogs(docs, reg)
    assert any("registered but not in docs" in m for m in msgs)
    assert any("documented but not registered" in m for m in msgs)
    assert any("schema drift for a_total" in m for m in msgs)

    msgs = compare_span_catalogs(
        docs={"a.b"}, registered={"a.b", "c.d"},
        emitted_ok={"a.b": True, "c.d": False})
    assert any("c.d" in m and "not in docs" in m for m in msgs)
    assert any("never emitted" in m for m in msgs)


def test_catalog_project_rules_clean():
    for rid in ("metrics-catalog", "span-catalog"):
        (rule,) = analysis.project_rules([rid])
        assert list(rule.check_project(_REPO)) == [], rid


# ---------------------------------------------------------------------------
# framework: pragmas, baseline, reporters
# ---------------------------------------------------------------------------

def test_pragma_suppression_inline_and_all():
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # pdlint: disable=silent-exception -- why\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # pdlint: disable=all\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:  # pdlint: disable=host-sync\n"  # wrong id
        "        pass\n"
    )
    found = lint(src, rule="silent-exception")
    assert [f.line for f in found] == [12]


def test_baseline_round_trip(tmp_path):
    src = (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
    )
    findings = lint(src)
    assert findings
    path = str(tmp_path / "base.json")
    n = bl.save(path, findings)
    assert n == len(bl.to_entries(findings))
    known = bl.load(path)
    assert bl.filter_new(findings, known) == []
    # a NEW finding (different symbol) still fails
    fresh = Finding(file="m.py", line=9, rule="silent-exception",
                    message=findings[0].message, symbol="other.fn")
    assert bl.filter_new([fresh], known) == [fresh]


def test_stale_baseline_entries_detected(tmp_path):
    """Satellite: entries whose (file, symbol) no longer resolves are
    stale — the file is gone, unparsable, or no longer defines the
    symbol. Graph pseudo-files (``<graph:...>``) are never stale."""
    (tmp_path / "live.py").write_text(
        "class C:\n    def step(self):\n        pass\n")
    entries = [
        {"file": "live.py", "rule": "r", "symbol": "C.step", "message": "m"},
        {"file": "live.py", "rule": "r", "symbol": "", "message": "m"},
        {"file": "live.py", "rule": "r", "symbol": "C.gone", "message": "m"},
        {"file": "deleted.py", "rule": "r", "symbol": "f", "message": "m"},
        {"file": "<graph:llama>", "rule": "graph-dtype-promotion",
         "symbol": "mul@3", "message": "m"},
    ]
    stale = bl.stale_entries(entries, str(tmp_path))
    assert [(e["file"], e["symbol"]) for e in stale] == [
        ("live.py", "C.gone"), ("deleted.py", "f")]


def test_write_baseline_prunes_stale_entries(tmp_path, capsys):
    """``--write-baseline`` reports and drops entries that no longer
    resolve instead of letting them linger forever."""
    import importlib.util

    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f():\n    try:\n        g()\n"
        "    except Exception:\n        pass\n")
    base = tmp_path / "base.json"
    base.write_text(json.dumps({"version": 1, "findings": [
        # resolves (and is re-found): kept
        {"file": "paddle_tpu/mod.py", "rule": "silent-exception",
         "symbol": "f", "message": "broad `except Exception:` swallows "
         "errors with no logging and no re-raise"},
        # (file, symbol) gone: pruned as stale
        {"file": "paddle_tpu/removed.py", "rule": "silent-exception",
         "symbol": "old_fn", "message": "whatever"},
    ]}))
    path = os.path.join(_REPO, "scripts", "pdlint.py")
    spec = importlib.util.spec_from_file_location("pdlint_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._REPO = str(tmp_path)
    rc = mod.main(["--write-baseline", "--baseline", str(base),
                   "--no-project-rules", str(pkg)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "pruned stale entry paddle_tpu/removed.py" in out
    doc = json.loads(base.read_text())
    files = [e["file"] for e in doc["findings"]]
    assert "paddle_tpu/removed.py" not in files
    assert "paddle_tpu/mod.py" in files


def test_baseline_keys_survive_line_drift():
    src1 = ("def f():\n    try:\n        g()\n"
            "    except Exception:\n        pass\n")
    src2 = "\n\n# moved down by edits above\n" + src1
    k1 = [f.key() for f in lint(src1)]
    k2 = [f.key() for f in lint(src2)]
    assert k1 == k2


def test_json_reporter_schema_stable():
    f = Finding(file="a.py", line=3, rule="host-sync", message="m",
                symbol="C.step")
    doc = json.loads(report.render_json([f, f], baselined=2,
                                        rule_ids=["host-sync"]))
    assert doc["schema_version"] == 1
    assert doc["tool"] == "pdlint"
    assert doc["total"] == 2
    assert doc["baselined"] == 2
    assert doc["counts"] == {"host-sync": 2}
    assert doc["rules"] == ["host-sync"]
    assert set(doc["findings"][0]) == {"file", "line", "rule", "symbol",
                                       "message"}
    text = report.render_text([f], baselined=1)
    assert "a.py:3 host-sync m [C.step]" in text


def test_json_reporter_emits_finding_data_when_present():
    """A rule-attached payload (the shard-solver's rejected-plan
    ledger) rides --json as an additive per-finding ``data`` key;
    findings without one keep the pinned 5-key shape, and the key never
    leaks into baselines."""
    from paddle_tpu.analysis import baseline as _bl

    plain = Finding(file="a.py", line=1, rule="r", message="m")
    rich = Finding(file="<graph:llama>", line=1, rule="graph-shard-solver",
                   message="m", symbol="solver",
                   data={"ledger": [{"status": "costlier"}]})
    doc = json.loads(report.render_json([plain, rich]))
    assert set(doc["findings"][0]) == {"file", "line", "rule", "symbol",
                                       "message"}
    assert doc["findings"][1]["data"] == {"ledger": [{"status":
                                                      "costlier"}]}
    assert set(_bl.to_entries([rich])[0]) == {"file", "rule", "symbol",
                                              "message"}


def test_rule_catalog_has_required_rules():
    analysis.ast_rules()  # force registration
    assert {"trace-purity", "host-sync", "lock-discipline",
            "silent-exception", "op-schema", "metrics-catalog",
            "span-catalog"} <= set(analysis.RULES)
    for rule in analysis.RULES.values():
        assert rule.rationale  # every rule documents why it exists


# ---------------------------------------------------------------------------
# the tier-1 gate: zero non-baselined findings over paddle_tpu/
# ---------------------------------------------------------------------------

def _load_script(name):
    path = os.path.join(_REPO, "scripts", name)
    spec = importlib.util.spec_from_file_location(name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_pdlint_gate_zero_new_findings(capsys):
    """THE gate: ``scripts/pdlint.py --json --baseline
    .pdlint_baseline.json`` exits 0 — any new finding in paddle_tpu/
    fails tier-1 (same invocation style as the catalog lint scripts)."""
    mod = _load_script("pdlint.py")
    rc = mod.main(["--json", "--baseline",
                   os.path.join(_REPO, ".pdlint_baseline.json")])
    out = capsys.readouterr().out
    doc = json.loads(out)
    assert rc == 0, f"pdlint found new findings:\n{out}"
    assert doc["total"] == 0
    # the grandfathered set is fully burned down: the gate passes with
    # ZERO baselined suppressions (see test_baseline_retired_empty)
    assert doc["baselined"] == 0


def test_baseline_retired_empty():
    """The 39-site silent-exception grandfather set is gone: the
    checked-in baseline is pinned EMPTY (new findings must be fixed or
    pragma'd, never re-baselined), and the package lints clean with no
    baseline at all."""
    with open(os.path.join(_REPO, ".pdlint_baseline.json")) as fh:
        doc = json.load(fh)
    assert doc["findings"] == []
    mod = _load_script("pdlint.py")
    assert mod.main(["--json"]) == 0   # no --baseline: still zero


def test_burned_down_sites_lint_clean():
    """Regression for the last four baselined silent-exception sites
    (rpc._handle, deepseek empty_cache_layer, llama._rope memoization,
    batch_norm's trace probe): each now narrows, logs, routes through
    jit.is_tracing, or carries a reasoned pragma — zero findings with
    no baseline behind them."""
    for rel in ("paddle_tpu/distributed/rpc.py",
                "paddle_tpu/models/deepseek.py",
                "paddle_tpu/models/llama.py",
                "paddle_tpu/nn/functional/common.py"):
        found = analysis.analyze_file(os.path.join(_REPO, rel), _REPO)
        bad = [f for f in found if f.rule == "silent-exception"]
        assert bad == [], f"{rel}: {[f.render() for f in bad]}"


def test_pdlint_cli_list_rules(capsys):
    mod = _load_script("pdlint.py")
    assert mod.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "trace-purity" in out and "op-schema" in out


# ---------------------------------------------------------------------------
# regressions for sites this PR fixed
# ---------------------------------------------------------------------------

def test_chrome_export_logs_profiler_failure(monkeypatch):
    """tracing.export_chrome used to ``except Exception: pass`` around
    the profiler merge — a broken profiler silently produced a thinner
    timeline. Now it logs through the rank-aware logger and still
    exports the spans."""
    import logging

    from paddle_tpu.observability import tracing

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    lg = tracing._logger()
    handler = _Capture(level=logging.WARNING)
    lg.addHandler(handler)
    tracer = tracing.Tracer()
    tracer.enable()
    try:
        with tracer.span("t"):
            pass
        import paddle_tpu.profiler.profiler as prof

        monkeypatch.setattr(prof, "_recorder", None)  # .events() -> raise
        trace = tracer.export_chrome()
        assert len(trace["traceEvents"]) == 1      # spans still export
        assert any("profiler host events skipped" in r.getMessage()
                   for r in records)
    finally:
        tracer.disable()
        lg.removeHandler(handler)


def test_timer_pragmas_keep_silent_fallbacks_clean():
    """The two deliberately-silent StepTimer fallbacks carry justified
    pragmas (satellite: baseline only deliberate sites, with a reason) —
    so the file lints clean WITHOUT baseline entries."""
    found = analysis.analyze_file(
        os.path.join(_REPO, "paddle_tpu/observability/timer.py"), _REPO)
    assert [f for f in found if f.rule == "silent-exception"] == []
    src = open(os.path.join(
        _REPO, "paddle_tpu/observability/timer.py")).read()
    assert src.count("pdlint: disable=silent-exception --") == 2
