"""Step-anatomy profiler: per-phase attribution whose buckets sum to
the step wall time by construction, the < 1% enabled-overhead gate,
roofline/MFU accounting against the autotune cost model, the ``usage``
block on completion responses, and the ``GET /profile`` /
``GET /profile/cluster`` / incident-bundle surfaces (docs/SERVING.md
"Step anatomy & roofline accounting")."""
import http.client
import json
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.observability import catalog as cat
from paddle_tpu.observability import flightrecorder as frec
from paddle_tpu.observability import perf
from paddle_tpu.serving import ContinuousBatchEngine, Seq2SeqBatchEngine


def _tiny_model(layers=2):
    paddle.seed(0)
    return LlamaForCausalLM(LlamaConfig.tiny(num_hidden_layers=layers))


def _run_engine(model, n_req=3, new=8, slots=2, profiler=True):
    eng = ContinuousBatchEngine(model, max_batch=slots, max_len=64,
                                page_size=8)
    if profiler:
        eng.profiler.enable()
    rng = np.random.RandomState(0)
    for i in range(n_req):
        eng.add_request(rng.randint(1, model.config.vocab_size, (5 + i,)),
                        new)
    eng.run_until_done()
    return eng


# ---- PhaseClock -------------------------------------------------------------

def test_phase_clock_sums_exactly():
    clk = perf.PhaseClock()
    clk.begin()
    for phase in ("admit", "dispatch", "sync", "retire", "admit"):
        time.sleep(0.001)
        clk.lap(phase)
    # repeated laps accumulate (trailing admission re-laps "admit") and
    # the bucket total equals the wall total EXACTLY — same timestamps,
    # no sampling
    assert set(clk.phases) == {"admit", "dispatch", "sync", "retire"}
    assert sum(clk.phases.values()) == pytest.approx(clk.total(),
                                                     abs=1e-12)
    assert clk.phases["admit"] > 0


# ---- engine wiring ----------------------------------------------------------

def test_engine_steps_satisfy_phase_sum_invariant():
    eng = _run_engine(_tiny_model())
    prof = eng.profiler
    assert prof.steps > 0
    pay = prof.payload()
    assert pay["engine"] == "decoder" and pay["enabled"]
    for rec in prof.recent:
        assert sum(rec["phases"].values()) == pytest.approx(rec["ms"],
                                                            rel=1e-9)
    # the decode path exercises every non-speculative phase
    assert {"admit", "dispatch", "sync", "retire"} <= set(pay["phases"])
    shares = sum(p["share"] for p in pay["phases"].values())
    assert shares == pytest.approx(1.0, abs=1e-6)
    # phase histograms landed in the shared registry
    assert cat.SERVING_STEP_PHASE.count(engine="decoder",
                                        phase="dispatch") > 0


def test_disabled_profiler_commits_nothing():
    eng = _run_engine(_tiny_model(), profiler=False)
    assert eng.profiler.steps == 0
    assert not eng.profiler.recent
    # stats() still carries the federated keys (zeros), so the router's
    # collector never KeyErrors on a profiler-off worker
    st = eng.stats()
    assert st["profile_step_ms"] == 0.0
    assert st["profile_roofline_ratio"] == 0.0


def test_seq2seq_engine_drives_the_profiler():
    from paddle_tpu.models.whisper import (WhisperConfig,
                                           WhisperForConditionalGeneration)

    paddle.seed(0)
    m = WhisperForConditionalGeneration(WhisperConfig.tiny())
    eng = Seq2SeqBatchEngine(m, max_batch=2, max_decode_len=16,
                             max_encoder_len=16)
    eng.profiler.enable()
    feats = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    eng.add_request(feats, max_new_tokens=6)
    eng.run_until_done()
    prof = eng.profiler
    assert prof.engine == "seq2seq" and prof.steps > 0
    for rec in prof.recent:
        assert sum(rec["phases"].values()) == pytest.approx(rec["ms"],
                                                            rel=1e-9)
    # encoder+seed prefill is this engine's admission
    assert {"admit", "dispatch", "sync"} <= set(prof.payload()["phases"])


def test_usage_recorded_per_request():
    eng = _run_engine(_tiny_model(), n_req=2, new=6)
    for rid in list(eng._finished_usage):
        u = eng.request_usage(rid)
        assert u["completion_tokens"] == 6
        assert u["prompt_tokens"] >= 5
        assert u["dispatches"] == 6          # one token per decode step
        assert u["queue_ms"] >= 0 and u["compute_ms"] > 0
        assert u["accepted_tokens_per_dispatch"] == pytest.approx(1.0)


# ---- the < 1% overhead gate -------------------------------------------------

def test_profiler_overhead_under_one_percent(monkeypatch, tmp_path):
    """The enabled instrumentation (begin + six laps + commit with the
    roofline join) must cost < 1% of a real decode step."""
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    model = _tiny_model()
    _run_engine(model)                       # warm-up: compiles
    eng = _run_engine(model, n_req=4, new=12)
    step_p50_ms = eng.profiler.payload()["step_ms"]["p50"]
    assert step_p50_ms > 0

    prof = perf.StepProfiler("overhead_gate")
    prof.set_cost_params(perf.decode_step_params(model.config, 2))
    prof.enable()
    clk = prof.clock
    n = 2000
    for _ in range(200):                     # warm the commit path
        clk.begin()
        for ph in perf.PHASES:
            clk.lap(ph)
        prof.commit(active=2, kv_len=32)
    # min over rounds: a single scheduler preemption inflates a mean
    # but not the best round, so the gate holds under full-suite load
    rounds, per = 10, n // 10
    over_ms = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(per):
            clk.begin()
            for ph in perf.PHASES:
                clk.lap(ph)
            prof.commit(active=2, kv_len=32)
        over_ms = min(over_ms,
                      (time.perf_counter() - t0) * 1e3 / per)
    assert over_ms < 0.01 * step_p50_ms, (
        f"profiler overhead {over_ms * 1e3:.2f}us is "
        f">= 1% of a {step_p50_ms:.3f}ms decode step")


# ---- roofline accounting ----------------------------------------------------

def test_roofline_ratio_sanity(monkeypatch, tmp_path):
    """Enough active commits publish a roofline block whose ratio is a
    sane fraction of the cap (never > 1: measured time can't beat the
    analytical floor) and whose observation persists into the autotune
    cost table under the engine's shape signature."""
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    from paddle_tpu.ops.pallas import autotune

    model = _tiny_model()
    prof = perf.StepProfiler("roofline_gate")
    prof.set_cost_params(perf.decode_step_params(model.config, 2))
    prof.enable()
    clk = prof.clock
    for _ in range(256):
        clk.begin()
        time.sleep(0.0002)
        clk.lap("dispatch")
        clk.lap("sync")
        prof.commit(active=2, kv_len=40)
    roof = prof.last_roofline
    assert roof is not None
    assert 0.0 < roof["ratio"] <= 1.0
    assert roof["predicted_ms"] > 0 and roof["measured_ms"] > 0
    assert roof["achieved_hbm_gbps"] > 0 and roof["achieved_gflops"] > 0
    assert 0.0 <= roof["mfu"] <= 1.0
    assert roof["choice"] == [2, 64] or tuple(roof["choice"]) == (2, 64)
    # the gauges carry the same numbers
    assert cat.SERVING_ROOFLINE_RATIO.value(
        engine="roofline_gate") == pytest.approx(roof["ratio"])
    # a (signature, measured, predicted) observation reached the table
    key = autotune.full_key(prof._sig)
    row = autotune.get_cache().entry("serving_decode_step", key)
    assert row, "no serving_decode_step observation persisted"
    assert row["est"]["roofline_ms"] > 0 and row["ms"] > 0
    # the persisted est replays against the registered model — the
    # graph-cost-table lint's exact contract
    cost = autotune.analytical_cost("serving_decode_step", row["params"],
                                    tuple(row["choice"]))
    assert cost["bytes"] == int(row["est"]["bytes"])
    assert cost["flops"] == int(row["est"]["flops"])


def test_decode_step_params_from_config():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    p = perf.decode_step_params(cfg, 4)
    assert p["batch"] == 4 and p["layers"] == 2
    cost = perf._decode_step_cost(p, (2, 64))
    assert cost["bytes"] > 0 and cost["flops"] > 0
    # weights are read once per dispatch: doubling batch must not
    # double bytes, while flops scale ~linearly
    c2 = perf._decode_step_cost(p, (4, 64))
    assert c2["bytes"] < 2 * cost["bytes"]
    assert c2["flops"] == pytest.approx(2 * cost["flops"], rel=0.1)
    assert perf.decode_step_params(object(), 2) is None


# ---- HTTP surfaces ----------------------------------------------------------

@pytest.fixture(scope="module")
def served():
    from paddle_tpu.serving_http import CompletionServer

    model = _tiny_model()
    eng = ContinuousBatchEngine(model, max_batch=2, max_len=64,
                                page_size=8)
    with CompletionServer(eng, model_name="tiny-perf") as srv:
        yield srv


def _post(srv, path, body):
    host, port = srv.address
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out


def _get(srv, path):
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=30) as r:
        return json.loads(r.read())


def test_usage_block_on_completion_response(served):
    code, body = _post(served, "/v1/completions",
                       {"prompt_token_ids": [3, 5, 7], "max_tokens": 6})
    assert code == 200
    u = body["usage"]
    assert u["prompt_tokens"] == 3 and u["completion_tokens"] == 6
    assert u["total_tokens"] == 9
    assert u["queue_ms"] >= 0 and u["compute_ms"] > 0
    assert u["dispatches"] >= 1
    assert u["accepted_tokens_per_dispatch"] == pytest.approx(1.0)


def test_usage_rides_final_sse_chunk_before_done(served):
    host, port = served.address
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt_token_ids": [2, 4, 6],
                             "max_tokens": 5, "stream": True}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    pieces, clean = [], False
    while True:
        line = resp.readline()
        if not line:
            break
        if not line.startswith(b"data: "):
            continue
        payload = line[len(b"data: "):].strip()
        if payload == b"[DONE]":
            clean = True
            break
        pieces.append(json.loads(payload))
    conn.close()
    assert clean and len(pieces) == 5
    # every chunk stays choices[0]-parseable; ONLY the final one
    # carries usage (attached, not an extra event)
    assert all(p["choices"][0]["token_ids"] for p in pieces)
    assert all("usage" not in p for p in pieces[:-1])
    u = pieces[-1]["usage"]
    assert u["prompt_tokens"] == 3 and u["completion_tokens"] == 5
    assert u["total_tokens"] == 8 and u["dispatches"] >= 1


def test_profile_endpoint(served):
    doc = _get(served, "/profile?top=3")
    assert doc["schema_version"] == 1
    eng = doc["engines"]["decoder"]
    assert eng["enabled"] is True            # the server enabled it
    assert eng["steps"] > 0 and eng["window"] > 0
    assert eng["step_ms"]["p50"] > 0
    assert eng["step_ms"]["p99"] >= eng["step_ms"]["p50"]
    for info in eng["phases"].values():
        assert info["p99_ms"] >= info["p50_ms"] >= 0
        assert 0.0 <= info["share"] <= 1.0
    assert len(eng["top_slowest"]) <= 3
    for row in eng["top_slowest"]:
        assert row["ms"] > 0 and "fr_seq" in row and "active" in row
    # stats()/health carries the federated scalars
    st = _get(served, "/health")["stats"]
    assert st["profile_step_ms"] > 0


def test_bundle_carries_profile_section(served):
    b = frec.get_reporter().bundle("manual", context="perf-unit")
    frec.validate_bundle(b)
    assert b["profile"]["schema_version"] == 1
    assert "decoder" in b["profile"]["engines"]
    # additive-optional: a bundle written before this PR still validates
    legacy = {k: v for k, v in b.items() if k != "profile"}
    frec.validate_bundle(legacy)


def test_step_anatomy_script_renders(served):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_step_anatomy_t", os.path.join(os.path.dirname(__file__), "..",
                                        "scripts", "step_anatomy.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    host, port = served.address
    doc = mod.load(f"http://{host}:{port}", top=2)
    text = mod.render(doc)
    assert "ENGINE decoder" in text and "dispatch" in text
    # bundle-file mode reads the PROFILE section
    b = frec.get_reporter().bundle("manual", context="perf-unit")
    assert "ENGINE decoder" in mod.render(b["profile"])


# ---- cluster federation -----------------------------------------------------

def test_cluster_profile_federation(tmp_path, monkeypatch):
    """Router-side ``GET /profile/cluster`` federates ≥ 2 workers keyed
    by replica id, and the federated TSDB carries the per-replica perf
    gauges under their declared series names."""
    monkeypatch.setenv("PD_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    from paddle_tpu.observability import alerts as al
    from paddle_tpu.observability import timeseries as tsm
    from paddle_tpu.serving_cluster import launch_cluster

    cluster = launch_cluster({
        "cluster": {"host": "127.0.0.1", "port": 0, "ttl": 2.0,
                    "platform": "cpu", "model_name": "tiny-perf-cluster",
                    "ts_interval_s": 0.25},
        "model": {"kind": "tiny_llama", "num_hidden_layers": 2,
                  "seed": 0},
        "engine": {"max_batch": 4, "max_len": 64, "page_size": 8},
        "workers": [{"role": "unified", "count": 2}],
    }, supervise=False)
    try:
        host, port = cluster.address
        url = f"http://{host}:{port}"
        for i in range(4):                   # traffic lands on both
            code, body = _post_url(host, port, "/v1/completions",
                                   {"prompt_token_ids": [2 + i, 5, 9],
                                    "max_tokens": 4})
            assert code == 200
            assert body["usage"]["completion_tokens"] == 4
        with urllib.request.urlopen(url + "/profile/cluster",
                                    timeout=30) as r:
            doc = json.loads(r.read())
        assert doc["schema_version"] == 1
        assert set(doc["replicas"]) == {"0", "1"}, doc.get("errors")
        for rid, sub in doc["replicas"].items():
            dec = sub["engines"]["decoder"]
            assert dec["enabled"] is True, rid
        served_steps = [sub["engines"]["decoder"]["steps"]
                        for sub in doc["replicas"].values()]
        assert sum(served_steps) > 0
        # the per-replica perf gauges reach the federated store under
        # their FEDERATED_SERIES names
        cluster.pool.refresh()
        tsm.get_store().sample_once()
        with urllib.request.urlopen(url + "/timeseries",
                                    timeout=30) as r:
            ts = json.loads(r.read())
        perf_series = {s["name"] for s in ts["series"]
                       if s["name"].startswith("cluster_profile_")}
        assert perf_series == {"cluster_profile_step_ms",
                               "cluster_profile_roofline_ratio"}
        assert perf_series <= set(al.FEDERATED_SERIES)
        reps = {s["labels"].get("replica") for s in ts["series"]
                if s["name"] == "cluster_profile_step_ms"}
        assert {"0", "1"} <= reps
    finally:
        cluster.close()


def _post_url(host, port, path, body):
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    out = resp.status, json.loads(resp.read())
    conn.close()
    return out
