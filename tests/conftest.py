"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §7: test sharding on
host-platform devices; the driver separately dry-runs the multi-chip path).
Env vars must be set before jax initialises.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np
import pytest

import jax

# Force the CPU backend at the *config* level: the environment's TPU-tunnel
# plugin (sitecustomize) overrides jax_platforms after import, so the env var
# alone is not enough — without this, "CPU" tests silently run through the
# remote TPU tunnel (and hang when it is down).
jax.config.update("jax_platforms", "cpu")

# numeric-parity tests compare against float64-ish numpy references
jax.config.update("jax_default_matmul_precision", "highest")

# persistent XLA compilation cache: the suite is compile-dominated (every
# jit in every test), and the HLO-keyed disk cache makes repeat runs reuse
# executables across processes and sessions
_cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR",
                            "/tmp/paddle_tpu_jax_cache")
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:  # older jax without the knobs — run uncached
    pass


@pytest.fixture(autouse=True)
def _seed_rngs():
    import paddle_tpu

    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
    # drop any tape left by a test that didn't call backward
    from paddle_tpu.autograd import tape

    tape.reset_tape()
    tape.set_grad_enabled(True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / e2e tests (several seconds each)")
